"""BertClassifier (models/bert.py): the encoder fine-tuning workflow —
pretrained MLM weights graft under a fresh pooler/classifier head, a
converted HF classifier logit-matches transformers, and the classifier
trains on a separable synthetic task through the standard machinery."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tfde_tpu.models.bert import (
    Bert,
    BertClassifier,
    bert_tiny_test,
    classifier_params_from_mlm,
)


def _tiny_classifier(**kw):
    return BertClassifier(
        num_labels=3, vocab_size=97, hidden_size=32, depth=2, num_heads=4,
        mlp_dim=64, max_position=64, dtype=jnp.float32, dropout_rate=0.0,
        **kw,
    )


def test_classifier_shapes_and_mask(rng):
    m = _tiny_classifier()
    ids = jnp.asarray(rng.integers(0, 97, (4, 10)), jnp.int32)
    params = m.init(jax.random.key(0), ids)["params"]
    logits = m.apply({"params": params}, ids, train=False)
    assert logits.shape == (4, 3) and logits.dtype == jnp.float32
    # padding mask changes the result (it reaches attention)
    am = jnp.ones((4, 10), jnp.int32).at[:, 5:].set(0)
    masked = m.apply({"params": params}, ids, attention_mask=am,
                     train=False)
    assert not np.allclose(np.asarray(logits), np.asarray(masked))


def test_mlm_weights_graft(rng):
    """classifier_params_from_mlm: embeddings/encoder come from the MLM
    tree bit-for-bit; pooler/classifier stay freshly initialized."""
    mlm = bert_tiny_test()
    ids = jnp.asarray(rng.integers(0, 97, (2, 8)), jnp.int32)
    mlm_params = mlm.init(jax.random.key(1), ids)["params"]
    clf = _tiny_classifier()
    params = classifier_params_from_mlm(clf, mlm_params, jax.random.key(2),
                                        ids)
    np.testing.assert_array_equal(
        np.asarray(params["encoder"]["block_0"]["attn"]["query"]["kernel"]),
        np.asarray(mlm_params["encoder"]["block_0"]["attn"]["query"]["kernel"]),
    )
    assert "pooler" in params and "classifier" in params
    logits = clf.apply({"params": params}, ids, train=False)
    assert logits.shape == (2, 3)
    with pytest.raises(ValueError, match="embeddings"):
        classifier_params_from_mlm(clf, {"encoder": {}}, jax.random.key(0),
                                   ids)


@pytest.mark.slow
def test_hf_classifier_logits_match(rng):
    transformers = pytest.importorskip("transformers")
    torch = pytest.importorskip("torch")

    from tfde_tpu.models.convert import bert_classifier_from_hf

    cfg = transformers.BertConfig(
        vocab_size=97, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0, num_labels=3,
    )
    torch.manual_seed(6)
    hf = transformers.BertForSequenceClassification(cfg)
    hf.eval()
    model, params = bert_classifier_from_hf(hf, dtype=jnp.float32)
    ids = rng.integers(0, 97, (2, 12)).astype(np.int32)
    with torch.no_grad():
        ref = hf(torch.tensor(ids.astype(np.int64))).logits.numpy()
    ours = np.asarray(model.apply({"params": params}, jnp.asarray(ids)))
    # exact-gelu (HF) vs tanh-gelu (ours) in the encoder MLPs: ~1e-3
    np.testing.assert_allclose(ours, ref, rtol=5e-3, atol=5e-3)


@pytest.mark.slow
def test_classifier_finetunes(rng):
    """A separable task: class = first-token bucket. The grafted classifier
    fine-tunes to high accuracy in a few steps (the GLUE-recipe smoke)."""
    import optax

    from tfde_tpu.parallel.strategies import MultiWorkerMirroredStrategy
    from tfde_tpu.training.step import init_state, make_custom_train_step
    from tfde_tpu.ops import losses

    clf = _tiny_classifier()
    strategy = MultiWorkerMirroredStrategy()

    def loss_fn(state, params, batch, rng_):
        ids, labels = batch
        logits = state.apply_fn({"params": params}, ids, train=True,
                                rngs={"dropout": rng_})
        loss = losses.sparse_categorical_crossentropy(logits, labels)
        acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
        return loss, {"accuracy": acc}

    state, _ = init_state(clf, optax.adamw(3e-3), strategy,
                          np.zeros((16, 12), np.int32))
    step = make_custom_train_step(strategy, state, loss_fn, donate=False)
    key = jax.random.key(0)
    for i in range(60):
        ids = rng.integers(0, 97, (16, 12)).astype(np.int32)
        labels = (ids[:, 0] % 3).astype(np.int32)
        state, m = step(state, (jnp.asarray(ids), jnp.asarray(labels)), key)
    assert float(m["accuracy"]) > 0.7, float(m["accuracy"])
