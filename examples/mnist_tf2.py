"""TF2-style Estimator MNIST — the TPU-native equivalent of the reference's
`tf2_mnist_distributed.py` (SURVEY.md §3.3).

Reference -> here:
- constants BATCH_SIZE=128, BUFFER_SIZE=10000, LEARNING_RATE=1e-4
  (tf2_mnist:33-35);
- `ParameterServerStrategy()` + RunConfig(train_distribute=strategy)
  (tf2_mnist:189,205) -> ZeRO-1 sync DP (SURVEY.md §7);
- BN-CNN via create_model with default model_dir '/tmp/mode'
  (tf2_mnist:208-211) — kept as the default but exposed as --model-dir,
  fixing the hardcode quirk (SURVEY.md §2a);
- TrainSpec/EvalSpec + FinalExporter + train_and_evaluate
  (tf2_mnist:214-241).

The reference also carries a dead hand-written `model_fn`
(tf2_mnist:65-91) showing the custom-training-loop shape — per-example CE
summed x 1/BATCH_SIZE into optimizer.minimize. That path is alive here as
`custom_train_loop()` (--custom-loop): the same plain CNN trained by a raw
jit-compiled step, which is exactly what Estimator.train compiles anyway.
"""

from __future__ import annotations

import argparse
import logging

import jax
import jax.numpy as jnp
import optax

import os as _os
import sys as _sys

# runnable as `python examples/<name>.py` without an install: put the repo
# root (the directory holding tfde_tpu/) ahead of the script dir
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

from tfde_tpu import bootstrap
from tfde_tpu.data import Dataset, datasets, device_prefetch
from tfde_tpu.export.serving import FinalExporter
from tfde_tpu.models.cnn import BatchNormCNN, PlainCNN
from tfde_tpu.parallel.strategies import ParameterServerStrategy
from tfde_tpu.training import Estimator, EvalSpec, RunConfig, TrainSpec, train_and_evaluate
from tfde_tpu.training.step import init_state, make_train_step
from tfde_tpu.utils import model_summary

BATCH_SIZE = 128       # tf2_mnist:33
BUFFER_SIZE = 10000    # tf2_mnist:34
LEARNING_RATE = 1e-4   # tf2_mnist:35


def input_fn(features, labels, batch_size, mode):
    """tf2_mnist_distributed.py:38-63 (same pipeline as mnist_keras)."""
    ds = Dataset.from_tensor_slices((features, labels))
    if mode == "train":
        ds = ds.shuffle(len(features), seed=0).repeat().batch(
            batch_size, drop_remainder=True
        ).prefetch(4)
    else:
        ds = ds.batch(batch_size)
    return ds


def custom_train_loop(steps: int = 100):
    """The reference's dead model_fn path (tf2_mnist:65-91), alive: raw
    per-step loop with the canonical sum x 1/BATCH_SIZE loss scaling
    (tf2_mnist:81-83) — which is what ops/losses.py implements."""
    strategy = ParameterServerStrategy()
    (tx, ty), _ = datasets.mnist(flatten=False)
    ds = (
        Dataset.from_tensor_slices((tx, ty))
        .shuffle(len(tx), seed=0)
        .repeat()
        .batch(BATCH_SIZE, drop_remainder=True)
    )
    state, _ = init_state(
        PlainCNN(), optax.sgd(LEARNING_RATE), strategy,
        jnp.zeros((BATCH_SIZE, 28, 28, 1)),
    )
    step_fn = make_train_step(strategy, state)
    rng = jax.random.key(0)
    it = iter(ds)
    feed = device_prefetch((next(it) for _ in range(steps)), strategy.mesh)
    m = None
    for batch in feed:
        state, m = step_fn(state, batch, rng)
    logging.info(
        "custom loop done: step=%d loss=%.4f",
        int(jax.device_get(state.step)), float(jax.device_get(m["loss"])),
    )
    return state


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--model-dir", type=str, default="/tmp/mode")  # tf2_mnist:209
    parser.add_argument("--max-steps", type=int, default=None)
    parser.add_argument("--custom-loop", action="store_true")
    args, _ = parser.parse_known_args(argv)

    logging.getLogger().setLevel(logging.INFO)  # tf2_mnist:187
    bootstrap()

    if args.custom_loop:
        return custom_train_loop(100 if args.max_steps is None else args.max_steps)

    strategy = ParameterServerStrategy()  # tf2_mnist:189
    (train_images, train_labels), (test_images, test_labels) = datasets.mnist(
        flatten=True
    )  # tf2_mnist:191-200
    train_steps = (  # tf2_mnist:203
        len(train_images) // BATCH_SIZE if args.max_steps is None else args.max_steps
    )

    model = BatchNormCNN()
    # the reference prints model.summary() before training (tf2_mnist:143)
    print(model_summary(model, jnp.zeros((BATCH_SIZE, 28 * 28))))
    est = Estimator(
        model,
        optax.sgd(LEARNING_RATE),
        strategy=strategy,
        config=RunConfig(model_dir=args.model_dir),  # tf2_mnist:205-211
    )
    state, metrics = train_and_evaluate(  # tf2_mnist:214-241
        est,
        TrainSpec(
            lambda: input_fn(train_images, train_labels, BATCH_SIZE, "train"),
            max_steps=train_steps,
        ),
        EvalSpec(
            lambda: input_fn(test_images, test_labels, BATCH_SIZE, "eval"),
            steps=None,
            name="mnist-eval",
            exporters=[FinalExporter("exporter", (None, 28 * 28))],
            start_delay_secs=10,
            throttle_secs=10,
        ),
    )
    est.close()
    return state, metrics


if __name__ == "__main__":
    main()
