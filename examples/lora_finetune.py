"""LoRA fine-tuning — adapt a pretrained decoder with rank-r adapters.

The parameter-efficient fine-tuning entrypoint (training/lora.py): the
base model stays frozen (its params are constants of the compiled step),
only the rank-r `a`/`b` adapter pairs — and their AdamW slots — train.
The analog of the reference's train loop (its optimizer updates every
variable, /root/reference/tf2_mnist_distributed.py:85-90) restricted to
the adapter subspace, which is the standard recipe at converted-LLM size.

Two modes:

- `--hf-dir DIR`: fine-tune a converted checkpoint (models/convert.py
  artifact — GPT-2/LLaMA/Mistral), the real workflow.
- default: pretrain a tiny decoder on the synthetic structured stream
  for a few steps, then LoRA-adapt it — a hermetic demo of the same
  path (CPU smoke: `python examples/lora_finetune.py --fake-devices 8
  --tiny --max-steps 20`).

After training the adapters are merged (`merge_lora`) into a plain
base-shaped checkpoint: `--generate N` samples from the merged model
through the standard decode path, proving the export contract.
"""

from __future__ import annotations

import argparse
import logging
import time

import jax
import numpy as np
import optax

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

from tfde_tpu import bootstrap
from tfde_tpu.data import datasets
from tfde_tpu.models.gpt import GPT, gpt_tiny_test, next_token_loss
from tfde_tpu.parallel.strategies import MultiWorkerMirroredStrategy
from tfde_tpu.training.lora import (
    LoraConfig,
    init_lora_state,
    lora_param_count,
    make_lora_loss,
    merge_lora,
)
from tfde_tpu.training.step import init_state, make_custom_train_step

log = logging.getLogger(__name__)


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--hf-dir", type=str, default=None,
                        help="converted checkpoint dir (models/convert.py); "
                             "default: pretrain a tiny base inline")
    parser.add_argument("--rank", type=int, default=8)
    parser.add_argument("--alpha", type=float, default=16.0)
    parser.add_argument("--target", type=str,
                        default=r"attn/(query|value)/kernel$",
                        help="regex over param paths (the HF-standard "
                             "q/v-projection default); use 'kernel$' to "
                             "adapt every projection")
    parser.add_argument("--batch-size", type=int, default=16)
    parser.add_argument("--seq-len", type=int, default=64)
    parser.add_argument("--max-steps", type=int, default=200)
    parser.add_argument("--learning-rate", type=float, default=1e-3)
    parser.add_argument("--pretrain-steps", type=int, default=60,
                        help="inline base pretraining steps (no --hf-dir)")
    parser.add_argument("--generate", type=int, default=0, metavar="N",
                        help="sample N tokens from the MERGED model after "
                             "fine-tuning (the export contract)")
    parser.add_argument("--tiny", action="store_true")
    parser.add_argument("--fake-devices", type=int, default=None)
    args, _ = parser.parse_known_args(argv)

    if args.fake_devices:
        jax.config.update("jax_platforms", "cpu")
        from tfde_tpu.utils.devices import request_cpu_devices
        request_cpu_devices(args.fake_devices)

    # force: the axon site shim's early jax import already attached handlers
    logging.basicConfig(level=logging.INFO, format="%(message)s", force=True)
    bootstrap()
    strategy = MultiWorkerMirroredStrategy()
    rng = np.random.default_rng(0)
    key = jax.random.key(0)

    # -- the frozen base --------------------------------------------------
    if args.hf_dir:
        from tfde_tpu.models.convert import load_converted

        model, base_params = load_converted(args.hf_dir)
        vocab = model.vocab_size
        base_params = jax.device_put(
            base_params, strategy.params_sharding(base_params)
        )
    else:
        vocab = 97
        model = (gpt_tiny_test() if args.tiny else
                 GPT(vocab_size=vocab, hidden_size=64, depth=4, num_heads=4,
                     mlp_dim=128, max_position=args.seq_len,
                     dtype=jax.numpy.float32))
        vocab = model.vocab_size
        state, _ = init_state(model, optax.adamw(3e-3), strategy,
                              np.zeros((args.batch_size, args.seq_len),
                                       np.int32))
        pre_step = make_custom_train_step(strategy, state, next_token_loss,
                                          donate=False)
        toks = datasets.synthetic_tokens(2048, args.seq_len, vocab=vocab - 1)
        m = None
        for i in range(args.pretrain_steps):
            idx = rng.integers(0, len(toks), args.batch_size)
            state, m = pre_step(state, (jax.numpy.asarray(toks[idx]),), key)
        if m is not None:
            log.info("base pretrained %d steps, loss %.4f",
                     args.pretrain_steps, float(m["loss"]))
        base_params = state.params

    # -- adapters ---------------------------------------------------------
    cfg = LoraConfig(rank=args.rank, alpha=args.alpha, target=args.target)
    lstate, _ = init_lora_state(
        model, optax.adamw(args.learning_rate), strategy, base_params, cfg
    )
    n_base = sum(x.size for x in jax.tree_util.tree_leaves(base_params))
    n_lora = lora_param_count(lstate.params)
    log.info("LoRA rank %d on %r: %d trainable params (%.2f%% of %d)",
             args.rank, args.target, n_lora, 100.0 * n_lora / n_base, n_base)

    step = make_custom_train_step(
        strategy, lstate, make_lora_loss(base_params, next_token_loss, cfg),
        donate=False,
    )
    # a genuinely SHIFTED domain: relabel every token t -> (t + 11) mod V.
    # The stream's Markov successor relation changes (the pretrained
    # "t predicts 31t+7" rule no longer holds on the relabeled ids), so
    # the adapters must learn the new transition structure, not just
    # continue pretraining on identically-distributed data
    ft = (datasets.synthetic_tokens(2048, args.seq_len, vocab=vocab - 1)
          + 11) % (vocab - 1)
    t0 = time.time()
    first = None
    m = None
    for i in range(args.max_steps):
        idx = rng.integers(0, len(ft), args.batch_size)
        lstate, m = step(lstate, (jax.numpy.asarray(ft[idx]),), key)
        if first is None:
            first = float(m["loss"])
        if (i + 1) % 50 == 0:
            log.info("step %d loss %.4f", i + 1, float(m["loss"]))
    if m is not None:
        log.info("fine-tune: loss %.4f -> %.4f in %.1fs",
                 first, float(m["loss"]), time.time() - t0)

    # -- merge + the export contract --------------------------------------
    merged = merge_lora(base_params, lstate.params, cfg)
    if args.generate:
        from tfde_tpu.inference.decode import generate

        prompt = jax.numpy.asarray(ft[:1, : args.seq_len // 2])
        out, _ = generate(model, merged, prompt,
                          max_new_tokens=args.generate)
        log.info("merged-model sample: %s",
                 np.asarray(out[0, -args.generate:]).tolist())
    return base_params, merged


if __name__ == "__main__":
    main()
