"""Multi-worker synchronous data-parallel MNIST — the TPU-native equivalent of
the reference's `distributed_with_keras.py`.

Reference shape (cited per line):
- module constants: per-worker BATCH_SIZE=64, GLOBAL_BATCH_SIZE=64*NUM_WORKERS
  (distributed_with_keras.py:12-15) — here num_workers comes from the actual
  cluster instead of a hardcoded 2;
- `MultiWorkerMirroredStrategy()` built before training (dwk:16) — here the
  strategy is just sharding rules over the mesh, so construction order cannot
  deadlock; the collective all-reduce is XLA `psum` over ICI/DCN, not
  RING-over-gRPC;
- dataset scaled to [0,1], cached, shuffled with BUFFER_SIZE=10000
  (dwk:18-30), batched at the *global* batch size with autoshard OFF
  (dwk:54-57) — reproduced literally, including the OFF semantics (every host
  iterates the identical stream and takes its slice of each global batch);
- plain CNN compiled with SGD lr=0.001 (dwk:32-44);
- fit(epochs=3, steps_per_epoch=5) demo schedule (dwk:63).

Run single-host: python examples/mnist_multiworker.py
Multi-host: set CLUSTER_SPEC/TASK_INDEX/JOB_NAME (or TFDE_* vars) per host.
"""

from __future__ import annotations

import argparse
import logging

import jax
import optax

import os as _os
import sys as _sys

# runnable as `python examples/<name>.py` without an install: put the repo
# root (the directory holding tfde_tpu/) ahead of the script dir
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

from tfde_tpu import bootstrap
from tfde_tpu.data import Dataset, datasets
from tfde_tpu.data.pipeline import AutoShardPolicy
from tfde_tpu.models.cnn import PlainCNN
from tfde_tpu.parallel.strategies import MultiWorkerMirroredStrategy
from tfde_tpu.training import Estimator, RunConfig

BUFFER_SIZE = 10000  # dwk:12
BATCH_SIZE = 64      # per-worker, dwk:13


def make_datasets_unbatched():
    """tfds.load('mnist') -> scale -> cache -> shuffle (dwk:18-30)."""
    (train_x, train_y), _ = datasets.mnist(flatten=False)

    def scale(image, label):  # dwk:20-23 (data already in [0,1] when synthetic)
        return image.astype("float32"), label

    return (
        Dataset.from_tensor_slices((train_x, train_y))
        .map(scale)
        .cache()
        .shuffle(BUFFER_SIZE, seed=0)
    )


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=3)            # dwk:63
    parser.add_argument("--steps-per-epoch", type=int, default=5)   # dwk:63
    parser.add_argument("--learning-rate", type=float, default=0.001)  # dwk:42
    parser.add_argument("--model-dir", type=str, default=None)
    args, _ = parser.parse_known_args(argv)

    info = bootstrap()
    global_batch = BATCH_SIZE * max(info.num_processes, 1)  # dwk:15

    strategy = MultiWorkerMirroredStrategy()
    train_ds = make_datasets_unbatched().repeat().batch(
        global_batch, drop_remainder=True
    )

    est = Estimator(
        PlainCNN(),
        optax.sgd(args.learning_rate),
        strategy=strategy,
        config=RunConfig(model_dir=args.model_dir),
    )
    state = est.train(
        lambda: train_ds,
        max_steps=args.epochs * args.steps_per_epoch,
        shard_policy=AutoShardPolicy.OFF,  # dwk:55-57
    )
    est.close()
    logging.info("done at step %d", int(jax.device_get(state.step)))
    return state


if __name__ == "__main__":
    # force=True: jax/absl already installed a root handler at WARNING
    logging.basicConfig(level=logging.INFO, force=True)
    main()
