"""ImageNet ViT-B/16 with FSDP over the ICI mesh — BASELINE.json configs[3].

The fourth scale config: the reference's sync-DP recipe
(distributed_with_keras.py) taken past the replicated-weights regime. Params
and AdamW state shard over the 'fsdp' mesh axis (parallel/strategies.
FSDPStrategy); the batch splits over data x fsdp so the per-step weight
all-gather amortizes over the whole local batch; XLA overlaps the gathers
with the forward matmuls.

Run single-host: python examples/imagenet_vit.py --max-steps 100
CPU smoke:       python examples/imagenet_vit.py --fake-devices 8 --data 2 \
                     --image-size 32 --tiny --max-steps 2 --batch-size 16
"""

from __future__ import annotations

import argparse
import logging

import jax
import numpy as np
import optax

import os as _os
import sys as _sys

# runnable as `python examples/<name>.py` without an install: put the repo
# root (the directory holding tfde_tpu/) ahead of the script dir
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

from tfde_tpu import bootstrap, native
from tfde_tpu.data import Dataset, datasets
from tfde_tpu.data.pipeline import AutoShardPolicy
from tfde_tpu.models.vit import ViT_B16, vit_tiny_test
from tfde_tpu.parallel.strategies import FSDPStrategy
from tfde_tpu.training import Estimator, RunConfig
from tfde_tpu.training.optimizers import adamw as masked_adamw


def make_train_dataset(
    global_batch: int, image_size: int, n: int, num_classes: int, seed: int = 0,
    use_native: bool | None = None,
):
    """Shuffle/repeat/batch over the (synthetic-or-real) ImageNet arrays.

    At ViT input sizes (224x224x3 = 588 KB/row) the batch gather is pure
    memory bandwidth — the C++ NativeBatchLoader's GIL-free multi-thread
    memcpy ring (tfde_tpu/native) is the intended hot path; the python
    Dataset chain is the no-toolchain fallback. copy=True because the
    yielded views alias the slot ring and the device transfer downstream
    is asynchronous.
    """
    (train_x, train_y), _ = datasets.imagenet(
        n_train=n, n_test=1, side=image_size, num_classes=num_classes
    )
    if use_native is None:
        use_native = native.available()
    if use_native:
        return native.NativeBatchLoader(
            [train_x, train_y], batch_size=global_batch, seed=seed,
            drop_remainder=True, num_threads=4, depth=4, copy=True,
        )
    return (
        Dataset.from_tensor_slices((train_x, train_y))
        .shuffle(len(train_x), seed=seed)
        .repeat()
        .batch(global_batch, drop_remainder=True)
    )


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch-size", type=int, default=256, help="per worker")
    parser.add_argument("--max-steps", type=int, default=1000)
    parser.add_argument("--learning-rate", type=float, default=1e-3)
    parser.add_argument("--weight-decay", type=float, default=0.05)
    parser.add_argument("--warmup-steps", type=int, default=100)
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--train-examples", type=int, default=4096,
                        help="synthetic-set size; real imagenet.npz overrides")
    parser.add_argument("--model-dir", type=str, default=None)
    parser.add_argument("--data", type=int, default=1,
                        help="size of the 'data' mesh axis; 'fsdp' fills the rest")
    parser.add_argument("--tiny", action="store_true", help="CI-sized model")
    parser.add_argument("--remat", nargs="?", const="full", default=False,
                        choices=["full", "dots"],
                        help="checkpoint each block: bare --remat recomputes "
                             "everything; '--remat dots' saves MXU outputs "
                             "and recomputes only elementwise ops")
    parser.add_argument("--fake-devices", type=int, default=None)
    args, _ = parser.parse_known_args(argv)

    if args.fake_devices:
        jax.config.update("jax_platforms", "cpu")
        from tfde_tpu.utils.devices import request_cpu_devices
        request_cpu_devices(args.fake_devices)

    info = bootstrap()
    global_batch = args.batch_size * max(info.num_processes, 1)

    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=args.learning_rate,
        warmup_steps=min(args.warmup_steps, max(args.max_steps - 1, 1)),
        decay_steps=args.max_steps,
    )
    tx = masked_adamw(schedule, weight_decay=args.weight_decay)

    num_classes = 10 if args.tiny else 1000
    if args.tiny:
        model = vit_tiny_test(num_classes=num_classes, remat=args.remat)
    else:
        model = ViT_B16(
            num_classes=num_classes, dropout_rate=0.1, remat=args.remat
        )

    strategy = FSDPStrategy(data=args.data)
    est = Estimator(
        model, tx, strategy=strategy, config=RunConfig(model_dir=args.model_dir)
    )
    state = est.train(
        lambda: make_train_dataset(
            global_batch, args.image_size, args.train_examples, num_classes
        ),
        max_steps=args.max_steps,
        shard_policy=AutoShardPolicy.OFF,
    )
    est.close()
    logging.info("done at step %d", int(jax.device_get(state.step)))
    return state


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO, force=True)
    main()
