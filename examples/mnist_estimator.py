"""Estimator-style distributed MNIST with the BN-CNN — the TPU-native
equivalent of the reference's `mnist_keras_distributed.py` (its richest path:
cluster bootstrap, PS training, throttled eval, checkpoints, TensorBoard,
final serving export — SURVEY.md §3.1).

Reference -> here:
- CLI flags --working-dir/--num-epochs/--batch-size/--learning-rate/
  --verbosity with parse_known_args (mnist_keras:33-65): identical surface;
- CLUSTER_SPEC/TASK_INDEX/JOB_NAME -> TF_CONFIG bootstrap
  (mnist_keras:221-233): `tfde_tpu.bootstrap()` honors the same env contract,
  mapping roles to SPMD ranks (ps tasks fold into ZeRO sharding);
- DistributeConfig(ParameterServerStrategy train, MirroredStrategy eval)
  (mnist_keras:240-243): `ParameterServerStrategy` here = sync DP with ZeRO-1
  sharded optimizer state (same capability, documented semantic change —
  SURVEY.md §7); eval runs on the same mesh;
- per-role gRPC device filters (mnist_keras:165-189): obsolete by design —
  SPMD has no worker<->worker RPC topology to restrict;
- BN-CNN + SGD (mnist_keras:67-120), summaries/log/ckpt cadences 100/100/500
  (mnist_keras:246-248), eval delay/throttle 10s/10s named 'mnist-eval'
  (mnist_keras:264-275), FinalExporter on [None,784] (mnist_keras:151-162),
  worker-0 TensorBoard on $TB_PORT (mnist_keras:192-197,277-280): all below.
"""

from __future__ import annotations

import argparse
import logging

import optax

import os as _os
import sys as _sys

# runnable as `python examples/<name>.py` without an install: put the repo
# root (the directory holding tfde_tpu/) ahead of the script dir
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import jax.numpy as jnp

from tfde_tpu import bootstrap
from tfde_tpu.data import Dataset, datasets
from tfde_tpu.export.serving import FinalExporter
from tfde_tpu.models.cnn import BatchNormCNN
from tfde_tpu.observability.tb_server import start_tensorboard
from tfde_tpu.parallel.strategies import ParameterServerStrategy
from tfde_tpu.training import Estimator, EvalSpec, RunConfig, TrainSpec, train_and_evaluate
from tfde_tpu.utils import model_summary


def get_args(argv=None):
    """Flag surface of mnist_keras_distributed.py:33-65."""
    parser = argparse.ArgumentParser()
    parser.add_argument(
        "--working-dir", type=str, required=True,
        help="location to write checkpoints and export models (GCS-capable)")
    parser.add_argument(
        "--num-epochs", type=float, default=5,
        help="number of times to go through the data, default=5")
    parser.add_argument(
        "--batch-size", default=128, type=int,
        help="number of records to read during each training step, default=128")
    parser.add_argument(
        "--learning-rate", default=0.01, type=float,
        help="learning rate for gradient descent, default=.01")
    parser.add_argument(
        "--verbosity", choices=["DEBUG", "ERROR", "FATAL", "INFO", "WARN"],
        default="INFO")
    parser.add_argument(
        "--no-tensorboard", action="store_true",
        help="skip the in-process TensorBoard server (CI/tests)")
    args, _ = parser.parse_known_args(argv)  # tolerate extra flags (mnist_keras:64)
    return args


def input_fn(features, labels, batch_size, mode):
    """Pipeline semantics of mnist_keras_distributed.py:123-148.

    TRAIN: shuffle -> repeat -> batch -> prefetch. The reference's
    shuffle(1000) window is widened to the full dataset: same contract,
    better mixing, and it unlocks the vectorized batching fast path.
    """
    ds = Dataset.from_tensor_slices((features, labels))
    if mode == "train":
        ds = ds.shuffle(len(features), seed=0).repeat().batch(
            batch_size, drop_remainder=True
        ).prefetch(4)
    else:
        ds = ds.batch(batch_size)
    return ds


def train_and_evaluate_main(args):
    """mnist_keras_distributed.py:200-283 equivalent."""
    (train_images, train_labels), (test_images, test_labels) = datasets.mnist(
        flatten=True
    )  # load + /255 + int column labels (mnist_keras:207-216)

    # one epoch of steps; int() fixes the reference's float train_steps
    # (mnist_keras:219, SURVEY.md §2a quirks)
    train_steps = int(args.num_epochs * len(train_images) // args.batch_size)

    info = bootstrap()  # CLUSTER_SPEC/TASK_INDEX/JOB_NAME contract (:221-233)

    run_config = RunConfig(  # mnist_keras:240-248
        model_dir=args.working_dir,
        save_summary_steps=100,
        log_step_count_steps=100,
        save_checkpoints_steps=500,
    )
    model = BatchNormCNN()
    # the reference prints model.summary() before training (mnist_keras:117)
    print(model_summary(model, jnp.zeros((args.batch_size, 28 * 28))))
    est = Estimator(
        model,
        optax.sgd(args.learning_rate),
        strategy=ParameterServerStrategy(),
        config=run_config,
    )
    train_spec = TrainSpec(  # mnist_keras:255-262
        lambda: input_fn(train_images, train_labels, args.batch_size, "train"),
        max_steps=train_steps,
    )
    eval_spec = EvalSpec(  # mnist_keras:264-275
        lambda: input_fn(test_images, test_labels, args.batch_size, "eval"),
        steps=None,
        name="mnist-eval",
        exporters=[FinalExporter("exporter", (None, 28 * 28))],
        start_delay_secs=10,
        throttle_secs=10,
    )

    if info.is_chief and not args.no_tensorboard:  # worker-0 TB (mnist_keras:277-280)
        start_tensorboard(args.working_dir)

    state, metrics = train_and_evaluate(est, train_spec, eval_spec)
    est.close()
    return state, metrics


def main(argv=None):
    args = get_args(argv)
    logging.getLogger().setLevel(args.verbosity if args.verbosity != "WARN" else "WARNING")
    return train_and_evaluate_main(args)


if __name__ == "__main__":
    main()
