"""CIFAR-10 ResNet-50, multi-worker sync data-parallel — the scale-up of the
reference's `distributed_with_keras.py` recipe (BASELINE.json configs[2]:
"CIFAR-10 ResNet-50 (distributed_with_keras.py scaled to v4-32)").

Same shape as examples/mnist_multiworker.py — strategy over the full mesh,
global batch = per-worker batch x processes (distributed_with_keras.py:13-15),
autoshard OFF semantics (dwk:54-57) — with the scale-config training recipe:
SGD momentum 0.9, cosine LR decay with linear warmup, standard random-crop +
horizontal-flip augmentation done on host.

Run single-host: python examples/cifar10_resnet.py --max-steps 200
CPU smoke:       python examples/cifar10_resnet.py --fake-devices 8 --max-steps 2 --batch-size 8
"""

from __future__ import annotations

import argparse
import logging
from typing import Optional

import jax
import numpy as np
import optax

import os as _os
import sys as _sys

# runnable as `python examples/<name>.py` without an install: put the repo
# root (the directory holding tfde_tpu/) ahead of the script dir
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

from tfde_tpu import bootstrap, native
from tfde_tpu.data import Dataset, datasets
from tfde_tpu.data.pipeline import AutoShardPolicy
from tfde_tpu.models.resnet import resnet50_cifar
from tfde_tpu.parallel.strategies import MultiWorkerMirroredStrategy
from tfde_tpu.training import Estimator, RunConfig


def augment(rng: np.random.Generator, images: np.ndarray) -> np.ndarray:
    """Pad-4 random crop + horizontal flip (host-side, vectorized per batch)."""
    n, h, w, _ = images.shape
    padded = np.pad(images, ((0, 0), (4, 4), (4, 4), (0, 0)), mode="reflect")
    ys = rng.integers(0, 9, n)[:, None, None]
    xs = rng.integers(0, 9, n)[:, None, None]
    rows = ys + np.arange(h)[None, :, None]
    cols = xs + np.arange(w)[None, None, :]
    out = padded[np.arange(n)[:, None, None], rows, cols]
    flip = rng.random(n) < 0.5
    return np.where(flip[:, None, None, None], out[:, :, ::-1], out)


def make_train_dataset(global_batch: int, seed: int = 0,
                       use_native: Optional[bool] = None):
    """Shuffle/repeat/batch + per-batch augmentation.

    Hot path: the C++ NativeBatchLoader (GIL-free shuffle+gather+prefetch
    ring, tfde_tpu/native) when the toolchain built it — the tf.data C++
    engine capability at the batch sizes where it decisively beats the numpy
    path (SURVEY.md §2b row 3). Same deterministic per-seed stream on every
    host, as AutoShardPolicy.OFF requires. Python Dataset is the fallback.
    """
    (train_x, train_y), _ = datasets.cifar10()
    rng = np.random.default_rng(seed)
    if use_native is None:
        use_native = native.available()
    if use_native:
        def gen():
            loader = native.NativeBatchLoader(
                [train_x, train_y], batch_size=global_batch, seed=seed,
                drop_remainder=True, num_threads=4, depth=4,
            )
            for images, labels in loader:
                # augment() materializes fresh arrays; labels still alias
                # the slot ring, so copy before handing downstream
                yield augment(rng, images), labels.copy()
        return gen()

    def aug(images, labels):
        return augment(rng, images), labels

    return (
        Dataset.from_tensor_slices((train_x, train_y))
        .shuffle(len(train_x), seed=seed)
        .repeat()
        .batch(global_batch, drop_remainder=True)
        .map(aug)
    )


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch-size", type=int, default=128, help="per worker")
    parser.add_argument("--max-steps", type=int, default=1000)
    parser.add_argument("--learning-rate", type=float, default=0.4,
                        help="peak LR at global batch 1024; scaled linearly")
    parser.add_argument("--warmup-steps", type=int, default=100)
    parser.add_argument("--model-dir", type=str, default=None)
    parser.add_argument("--fake-devices", type=int, default=None)
    args, _ = parser.parse_known_args(argv)

    if args.fake_devices:
        jax.config.update("jax_platforms", "cpu")
        from tfde_tpu.utils.devices import request_cpu_devices
        request_cpu_devices(args.fake_devices)

    info = bootstrap()
    global_batch = args.batch_size * max(info.num_processes, 1)

    peak_lr = args.learning_rate * global_batch / 1024.0
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=peak_lr,
        warmup_steps=min(args.warmup_steps, max(args.max_steps - 1, 1)),
        decay_steps=args.max_steps,
    )
    tx = optax.sgd(schedule, momentum=0.9, nesterov=True)

    strategy = MultiWorkerMirroredStrategy()
    est = Estimator(
        resnet50_cifar(),
        tx,
        strategy=strategy,
        config=RunConfig(model_dir=args.model_dir),
    )
    state = est.train(
        lambda: make_train_dataset(global_batch),
        max_steps=args.max_steps,
        shard_policy=AutoShardPolicy.OFF,
    )
    est.close()
    logging.info("done at step %d", int(jax.device_get(state.step)))
    return state


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO, force=True)
    main()
