"""T5 sequence-to-sequence fine-tuning + generation, data-parallel.

The encoder-decoder recipe beside the causal-LM one (examples/gpt_lm.py):
a custom seq2seq objective (models/t5.t5_seq2seq_loss — teacher-forced CE
over shifted labels) through `make_custom_train_step`, the TPU-native
analog of the reference's hand-written `model_fn` path
(tf2_mnist_distributed.py:65-91), then KV-cache generation (`t5_generate`:
encoder once, cross-attention K/V cached, one compiled decode program).

Data: a hermetic synthetic task — REVERSE the input token sequence — that
a tiny T5 learns in a few hundred steps and that makes generation quality
visible by eye in the logs. `--hf-dir` swaps in a converted
T5ForConditionalGeneration artifact (models/convert.py CLI) instead.

Run single-host: python examples/t5_seq2seq.py --max-steps 300 --generate 4
CPU smoke:       python examples/t5_seq2seq.py --fake-devices 8 --tiny \
                     --seq-len 8 --max-steps 5 --batch-size 16
"""

from __future__ import annotations

import argparse
import logging
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

from tfde_tpu import bootstrap
from tfde_tpu.models.t5 import (
    T5Small,
    t5_generate,
    t5_seq2seq_loss,
    t5_tiny_test,
)
from tfde_tpu.parallel.strategies import MultiWorkerMirroredStrategy
from tfde_tpu.training.step import init_state, make_custom_train_step

log = logging.getLogger(__name__)


def reverse_batches(vocab: int, batch: int, seq: int, seed: int = 0):
    """(input_ids, labels) streams for the reverse-copy task; ids in
    [2, vocab) keep 0 (pad/start) and 1 (</s>) out of the payload."""
    rng = np.random.default_rng(seed)
    while True:
        x = rng.integers(2, vocab, (batch, seq)).astype(np.int32)
        yield x, x[:, ::-1].copy()


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch-size", type=int, default=64, help="per worker")
    parser.add_argument("--seq-len", type=int, default=16)
    parser.add_argument("--max-steps", type=int, default=300)
    parser.add_argument("--learning-rate", type=float, default=3e-3)
    parser.add_argument("--generate", type=int, default=0,
                        help="after training, greedy-decode this many "
                             "held-out inputs and log input vs output")
    parser.add_argument("--hf-dir", type=str, default=None,
                        help="conversion artifact dir (models/convert.py) "
                             "to fine-tune instead of the fresh tiny model")
    parser.add_argument("--tiny", action="store_true", help="CI-sized model")
    parser.add_argument("--fake-devices", type=int, default=None)
    args, _ = parser.parse_known_args(argv)

    if args.fake_devices:
        jax.config.update("jax_platforms", "cpu")
        from tfde_tpu.utils.devices import request_cpu_devices
        request_cpu_devices(args.fake_devices)

    info = bootstrap()
    global_batch = args.batch_size * max(info.num_processes, 1)

    params0 = None
    if args.hf_dir:
        from tfde_tpu.models.convert import load_converted

        model, params0 = load_converted(args.hf_dir)
    elif args.tiny:
        model = t5_tiny_test()
    else:
        model = T5Small(
            vocab_size=128, dropout_rate=0.0, dtype=jnp.float32,
        )

    strategy = MultiWorkerMirroredStrategy()
    sample = (np.zeros((global_batch, args.seq_len), np.int32),
              np.zeros((global_batch, args.seq_len), np.int32))
    tx = optax.adamw(args.learning_rate)
    state, _ = init_state(model, tx, strategy, sample, seed=0)
    if params0 is not None:
        # place the converted params per the strategy (the
        # examples/lora_finetune.py pattern)
        state = state.replace(params=jax.device_put(
            params0, strategy.params_sharding(params0)
        ))

    step_fn = make_custom_train_step(strategy, state, t5_seq2seq_loss)
    rng = jax.random.key(1)
    stream = reverse_batches(model.vocab_size, global_batch, args.seq_len)
    t0 = time.time()
    metrics = {}
    for step in range(args.max_steps):
        state, metrics = step_fn(state, next(stream), rng)
        if (step + 1) % 100 == 0:
            vals = {k: float(jax.device_get(v)) for k, v in metrics.items()}
            sps = 100 / (time.time() - t0)
            t0 = time.time()
            log.info("step %d: %s (%.2f steps/s)", step + 1, vals, sps)

    if args.generate > 0:
        params = jax.device_get(state.params)
        x, want = next(reverse_batches(model.vocab_size, args.generate,
                                       args.seq_len, seed=99))
        toks, _ = t5_generate(model, params, jnp.asarray(x),
                              max_new_tokens=args.seq_len, eos_id=None)
        out = np.asarray(toks)[:, 1:]  # drop the start token
        for i in range(args.generate):
            hit = (out[i] == want[i]).mean()
            log.info("input %s -> generated %s (target match %.0f%%)",
                     x[i].tolist(), out[i].tolist(), 100 * hit)
    return state, metrics


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO, force=True)
    main()
