"""GPT-style causal-LM pretraining — decoder-only, data-parallel with
optional sequence parallelism for long context and optional pipeline
parallelism for deep stacks.

The long-context entrypoint: `--seq-parallel N` shards the sequence over an
N-way 'seq' mesh axis and attention auto-dispatches to ring attention
(ops/ring_attention.py) — max context scales linearly with N. On a single
chip, long sequences use the Pallas flash kernel when TFDE_FLASH=1.

`--pipeline S` switches to the stage-stacked PipelinedLM
(models/pipelined.py) on a {'data': D, 'pipe': S} mesh: each pipe rank holds
depth/S transformer blocks, microbatches (--microbatches) flow through the
GPipe schedule via ppermute (parallel/pipeline.py), and the loss rides the
last-stage reduction (scalars cross the ring, not full logits). Add
`--tensor T` for 3D dp x pp x tp: stage weights also shard Megatron-style
over a 'tensor' axis, with the pipe in partial-manual shard_map mode so the
automatic partitioner handles the tensor collectives inside the ring.

`--moe E` swaps every 2nd block's MLP for an E-expert routed MoE
(models/moe.py, GShard per-group capacity) and shards the expert weights
over an 'expert' mesh axis (ExpertParallelStrategy).

Run single-host: python examples/gpt_lm.py --max-steps 200
CPU smoke:       python examples/gpt_lm.py --fake-devices 8 --tiny \
                     --seq-len 32 --max-steps 2 --batch-size 16 --seq-parallel 2
Pipeline smoke:  python examples/gpt_lm.py --fake-devices 8 --tiny \
                     --seq-len 32 --max-steps 2 --batch-size 16 --pipeline 2
"""

from __future__ import annotations

import argparse
import logging
import time

import jax
import numpy as np
import optax

import os as _os
import sys as _sys

# runnable as `python examples/<name>.py` without an install: put the repo
# root (the directory holding tfde_tpu/) ahead of the script dir
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

from tfde_tpu import bootstrap
from tfde_tpu.data import datasets
from tfde_tpu.models.gpt import GPT2Small, gpt_tiny_test, next_token_loss
from tfde_tpu.parallel.strategies import (
    MultiWorkerMirroredStrategy,
    SequenceParallelStrategy,
)
from tfde_tpu.training.step import init_state, make_custom_train_step
from tfde_tpu.training.optimizers import adamw as masked_adamw

log = logging.getLogger(__name__)


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch-size", type=int, default=32, help="per worker")
    parser.add_argument("--seq-len", type=int, default=256)
    parser.add_argument("--max-steps", type=int, default=1000)
    parser.add_argument("--learning-rate", type=float, default=3e-4)
    parser.add_argument("--warmup-steps", type=int, default=100)
    parser.add_argument("--train-examples", type=int, default=8192)
    parser.add_argument("--grad-accum", type=int, default=1,
                        help="sequential microbatches per optimizer update")
    parser.add_argument("--seq-parallel", type=int, default=0,
                        help="size of the 'seq' mesh axis (ring attention)")
    parser.add_argument("--pipeline", type=int, default=0,
                        help="size of the 'pipe' mesh axis (GPipe stages)")
    parser.add_argument("--microbatches", type=int, default=4,
                        help="GPipe microbatches (with --pipeline)")
    parser.add_argument("--schedule", default="gpipe",
                        choices=["gpipe", "1f1b"],
                        help="with --pipeline: backward schedule — gpipe "
                             "(AD, O(M+S) activation memory) or 1f1b "
                             "(explicit interleave, O(S) memory)")
    parser.add_argument("--tensor", type=int, default=1,
                        help="with --pipeline: Megatron tensor-parallel "
                             "size inside each stage (dp x pp x tp, 3D)")
    parser.add_argument("--packed", action="store_true",
                        help="sequence packing (data/packing.py): pack "
                             "variable-length synthetic documents into "
                             "fixed rows with block-diagonal attention "
                             "and boundary-masked loss")
    parser.add_argument("--moe", type=int, default=0,
                        help="experts per MoE block; shards them over an "
                             "'expert' mesh axis (expert parallelism)")
    parser.add_argument("--generate", type=int, default=0, metavar="N",
                        help="after training, sample N continuation tokens "
                             "from a training prompt (inference/decode.py; "
                             "not with --pipeline: PipelinedLM is a "
                             "training-schedule model, export weights to "
                             "GPT for serving)")
    parser.add_argument("--beams", type=int, default=0, metavar="K",
                        help="with --generate: beam-search decode with K "
                             "beams (inference/beam.py) instead of sampling")
    parser.add_argument("--export-generate", type=str, default=None,
                        metavar="DIR",
                        help="with --generate: also export the whole decode "
                             "loop as a StableHLO serving artifact "
                             "(export/generative.py) under DIR")
    parser.add_argument("--kv-heads", type=int, default=0,
                        help="grouped-query attention: KV heads per layer "
                             "(0 = classic MHA); the KV cache shrinks by "
                             "heads/kv-heads")
    parser.add_argument("--rope", action="store_true",
                        help="rotary position embeddings instead of the "
                             "learned GPT-2 table (ops/rotary.py)")
    parser.add_argument("--sliding-window", type=int, default=0,
                        help="Mistral-style sliding-window attention: each "
                             "position attends the last N only (composes "
                             "with --kv-heads, --rope, --seq-parallel)")
    parser.add_argument("--tiny", action="store_true")
    parser.add_argument("--remat", nargs="?", const="full", default=False,
                        choices=["full", "dots"])
    parser.add_argument("--fake-devices", type=int, default=None)
    args, _ = parser.parse_known_args(argv)

    if args.fake_devices:
        jax.config.update("jax_platforms", "cpu")
        from tfde_tpu.utils.devices import request_cpu_devices
        request_cpu_devices(args.fake_devices)

    info = bootstrap()
    global_batch = args.batch_size * max(info.num_processes, 1)

    if args.rope and args.pipeline > 1:
        raise ValueError(
            "--rope applies to the GPT decoder; PipelinedLM keeps its "
            "learned positions (drop --pipeline to use rotary)"
        )
    if args.sliding_window > 0 and args.pipeline > 1:
        raise ValueError(
            "--sliding-window applies to the GPT decoder; the banded ring "
            "does not ride the pipeline yet (drop --pipeline)"
        )
    if args.kv_heads > 0 and args.pipeline > 1:
        raise ValueError(
            "--kv-heads applies to the GPT decoder; PipelinedLM keeps "
            "classic MHA (drop --pipeline to use GQA)"
        )
    if args.pipeline > 1 and args.seq_parallel > 1 and args.tensor > 1:
        raise ValueError(
            "--pipeline + --seq-parallel + --tensor don't compose: pp x sp "
            "needs the fully-manual pipe, tp the partial-manual one — drop "
            "--tensor or --seq-parallel"
        )
    if args.schedule == "1f1b" and args.pipeline <= 1:
        raise ValueError("--schedule 1f1b applies to --pipeline runs")
    if args.schedule == "1f1b" and (args.tensor > 1 or args.seq_parallel > 1):
        raise ValueError(
            "--schedule 1f1b runs in the plain dp x pp ring (no "
            "--tensor/--seq-parallel); use the default gpipe schedule there"
        )
    if args.moe > 1 and (args.pipeline > 1 or args.seq_parallel > 1):
        # loud, not silent: PipelinedLM has no MoE blocks, and the seq/pipe
        # strategies would drop the expert-axis sharding --moe promises
        raise ValueError("--moe doesn't compose with --pipeline/--seq-parallel yet")
    if args.tensor > 1 and args.pipeline <= 1:
        raise ValueError(
            "--tensor requires --pipeline (3D dp x pp x tp); for TP without "
            "pipelining use TensorParallelStrategy via a custom entrypoint"
        )
    if args.beams > 0 and args.generate <= 0:
        raise ValueError(
            "--beams selects the decode mode for --generate; pass "
            "--generate N to produce output"
        )
    if args.export_generate and args.generate <= 0:
        raise ValueError(
            "--export-generate sizes the artifact from --generate; pass "
            "--generate N to export"
        )
    if args.export_generate and args.beams > 0:
        raise ValueError(
            "--export-generate exports the sampling decode loop; exporting "
            "beam search is not supported yet — drop --beams to export, or "
            "drop --export-generate to beam-decode in process"
        )
    if args.generate > 0 and args.pipeline > 1:
        # fail before training, not after: the post-training generate call
        # would otherwise discard the whole run
        raise ValueError(
            "--generate doesn't apply to --pipeline runs: PipelinedLM is a "
            "training-schedule model without a KV-cache decode path — serve "
            "the weights through GPT instead"
        )
    if args.pipeline > 1:
        from tfde_tpu.models.pipelined import PipelinedLM, pipelined_tiny_test

        if args.tiny:
            model = pipelined_tiny_test(
                num_stages=args.pipeline, microbatches=args.microbatches,
                remat=args.remat, schedule=args.schedule,
            )
        else:
            # GPT-2 small dims, depth 12 split across the stages
            if 12 % args.pipeline:
                raise ValueError("--pipeline must divide depth 12")
            model = PipelinedLM(
                num_stages=args.pipeline,
                layers_per_stage=12 // args.pipeline,
                microbatches=args.microbatches,
                remat=args.remat, schedule=args.schedule,
            )
    else:
        model_kw = {"num_experts": args.moe} if args.moe > 1 else {}
        if args.rope:
            model_kw["position"] = "rope"
        if args.kv_heads > 0:
            model_kw["num_kv_heads"] = args.kv_heads
        if args.sliding_window > 0:
            model_kw["sliding_window"] = args.sliding_window
        model = (
            gpt_tiny_test(remat=args.remat, **model_kw) if args.tiny
            else GPT2Small(remat=args.remat, **model_kw)
        )
    if args.seq_len % max(args.seq_parallel, 1) != 0:
        raise ValueError("--seq-len must divide evenly by --seq-parallel")

    seg = None
    # one corpus-construction site for both branches
    tokens = datasets.synthetic_tokens(
        args.train_examples, args.seq_len, vocab=model.vocab_size
    )
    if args.packed:
        if args.pipeline > 1 or args.seq_parallel > 1:
            raise ValueError(
                "--packed doesn't compose with --pipeline/--seq-parallel "
                "(the segment mask needs the plain dp/tp attention path)"
            )
        if args.sliding_window > 0:
            raise ValueError("--packed doesn't compose with --sliding-window")
        from tfde_tpu.data.packing import pack_documents

        # trim the [N, S] stream to per-document lengths: every row is an
        # independent Markov sequence (a fixed per-doc seed would make
        # equal-length documents bit-identical and the corpus degenerate)
        nrng0 = np.random.default_rng(7)
        lengths = nrng0.integers(args.seq_len // 4, args.seq_len,
                                 args.train_examples)
        docs = [tokens[i, : int(n)] for i, n in enumerate(lengths)]
        tokens, seg = pack_documents(docs, args.seq_len)
        log.info("packed %d docs into %d rows (fill %.0f%%)",
                 len(docs), len(tokens), 100 * (seg > 0).mean())

    schedule = optax.warmup_cosine_decay_schedule(
        0.0, args.learning_rate,
        warmup_steps=min(args.warmup_steps, max(args.max_steps - 1, 1)),
        decay_steps=args.max_steps,
    )
    tx = masked_adamw(schedule, weight_decay=0.1)

    if args.pipeline > 1:
        from tfde_tpu.parallel.strategies import PipelineParallelStrategy

        n = jax.device_count()
        inner = args.pipeline * args.tensor * max(args.seq_parallel, 1)
        if n % inner:
            raise ValueError(
                f"--pipeline {args.pipeline} x --tensor {args.tensor} x "
                f"--seq-parallel {max(args.seq_parallel, 1)} must divide "
                f"the device count {n}"
            )
        strategy = PipelineParallelStrategy(
            data=n // inner,
            pipe=args.pipeline,
            tensor=args.tensor,
            seq=max(args.seq_parallel, 1),
        )
    elif args.seq_parallel > 1:
        n = jax.device_count()
        if n % args.seq_parallel:
            raise ValueError(
                f"--seq-parallel {args.seq_parallel} must divide the device "
                f"count {n}"
            )
        strategy = SequenceParallelStrategy(data=n // args.seq_parallel)
    elif args.moe > 1:
        from tfde_tpu.parallel.strategies import ExpertParallelStrategy

        n = jax.device_count()
        expert = min(args.moe, n)
        while n % expert or args.moe % expert:
            expert -= 1  # largest expert-axis size dividing devices & experts
        strategy = ExpertParallelStrategy(data=n // expert)
    else:
        strategy = MultiWorkerMirroredStrategy()

    state, _ = init_state(
        model, tx, strategy, np.zeros((global_batch, args.seq_len), np.int32)
    )
    if args.pipeline > 1:
        # last-stage-reduction loss: only {loss, correct, count} scalars
        # cross the pipe ring instead of the full-logit broadcast
        from tfde_tpu.models.pipelined import pipelined_next_token_loss

        loss_fn = pipelined_next_token_loss
    elif args.packed:
        from tfde_tpu.data.packing import packed_next_token_loss

        loss_fn = packed_next_token_loss
    else:
        loss_fn = next_token_loss
    step_fn = make_custom_train_step(strategy, state, loss_fn,
                                     grad_accum=args.grad_accum)
    rng = jax.random.key(1)
    nrng = np.random.default_rng(0)
    t0 = time.time()
    metrics = {}
    for step in range(args.max_steps):
        idx = nrng.integers(0, len(tokens), global_batch)
        batch = (tokens[idx], seg[idx]) if seg is not None else (tokens[idx],)
        state, metrics = step_fn(state, batch, rng)
        if (step + 1) % 100 == 0:
            vals = {k: float(jax.device_get(v)) for k, v in metrics.items()}
            sps = 100 / (time.time() - t0)
            t0 = time.time()
            log.info("step %d: %s (%.2f steps/s)", step + 1, vals, sps)

    if args.generate > 0:
        if seg is not None:
            # packed rows hold several documents: prompting across a
            # boundary would condition on context training explicitly
            # masked. Prompt from each row's FIRST document only, at a
            # common length.
            keep = min(
                int((seg[0] == 1).sum()), int((seg[1] == 1).sum()),
                16, args.seq_len,
            )
            prompt = tokens[:2, :keep]
        else:
            prompt = tokens[:2, : min(16, args.seq_len)]
        # one sampling config for the in-process decode AND the export, so
        # the artifact reproduces exactly what was just logged
        sampling = dict(temperature=0.8, top_k=40)
        if args.beams > 0:
            from tfde_tpu.inference.beam import beam_search

            out, scores, lengths = beam_search(
                model, state.params, prompt,
                max_new_tokens=args.generate, num_beams=args.beams,
            )
            for row, score, n in zip(
                np.asarray(out[:, 0]), np.asarray(scores[:, 0]),
                np.asarray(lengths[:, 0]),
            ):
                log.info("beam best (%.3f): %s", score, row[: int(n)].tolist())
        else:
            from tfde_tpu.inference.decode import generate

            out, lengths = generate(
                model, state.params, prompt,
                max_new_tokens=args.generate,
                rng=jax.random.key(2), **sampling,
            )
            for row, n in zip(np.asarray(out), np.asarray(lengths)):
                log.info("generated: %s", row[: int(n)].tolist())
        if args.export_generate:
            from tfde_tpu.export.generative import export_generate

            d = export_generate(
                model, state.params, args.export_generate,
                prompt_len=prompt.shape[1], max_new_tokens=args.generate,
                batch_size=prompt.shape[0], **sampling,
            )
            log.info("generative serving artifact: %s", d)
    return state, metrics


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO, force=True)
    main()
