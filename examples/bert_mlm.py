"""BERT-base masked-LM pretraining, sequence-batch data-parallel —
BASELINE.json configs[4].

The custom-training-loop recipe: where the classification entrypoints ride
the Estimator lifecycle, this one owns its loss via
`training.step.make_custom_train_step` — the TPU-native analog of the
reference's hand-written `model_fn` EstimatorSpec path
(tf2_mnist_distributed.py:65-91): user-defined objective, framework-provided
differentiation/sharding/optimizer plumbing.

Data: Markov-structured synthetic token streams (data/datasets.
synthetic_tokens) masked host-side per the standard 80/10/10 recipe
(data/mlm.py). Plain DP over sequences — each chip sees global_batch/N
sequences; the gradient psum rides the ICI mesh.

Run single-host: python examples/bert_mlm.py --max-steps 100
CPU smoke:       python examples/bert_mlm.py --fake-devices 8 --tiny \
                     --seq-len 32 --max-steps 2 --batch-size 16
"""

from __future__ import annotations

import argparse
import logging
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import os as _os
import sys as _sys

# runnable as `python examples/<name>.py` without an install: put the repo
# root (the directory holding tfde_tpu/) ahead of the script dir
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

from tfde_tpu import bootstrap
from tfde_tpu.checkpoint.manager import CheckpointManager
from tfde_tpu.data import datasets
from tfde_tpu.data import mlm
from tfde_tpu.data.mlm import MlmConfig, mask_tokens
from tfde_tpu.models.bert import BertBase, bert_tiny_test
from tfde_tpu.observability.tensorboard import SummaryWriter
from tfde_tpu.ops import losses
from tfde_tpu.parallel.strategies import MultiWorkerMirroredStrategy
from tfde_tpu.training.step import init_state, make_custom_train_step
from tfde_tpu.training.optimizers import adamw as masked_adamw

log = logging.getLogger(__name__)


def mlm_loss_fn(state, params, batch, rng):
    """(loss, metrics) for make_custom_train_step. `grad_weight` carries the
    masked-position count: the MLM loss normalizes by it, so gradient
    accumulation must weight each microbatch by its own count to reproduce
    the full-batch update (training/step.py grad_accum)."""
    input_ids, labels = batch
    logits = state.apply_fn(
        {"params": params}, input_ids, train=True, rngs={"dropout": rng}
    )
    loss, acc = losses.masked_lm_loss(logits, labels)
    n_targets = jnp.sum((labels != mlm.IGNORE_ID).astype(jnp.float32))
    return loss, {"mlm_accuracy": acc, "grad_weight": n_targets}


def batch_stream(tokens: np.ndarray, cfg: MlmConfig, global_batch: int, seed: int):
    rng = np.random.default_rng(seed)
    n = len(tokens)
    while True:
        idx = rng.integers(0, n, global_batch)
        yield mask_tokens(tokens[idx], cfg, rng)


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch-size", type=int, default=64, help="per worker")
    parser.add_argument("--seq-len", type=int, default=128)
    parser.add_argument("--max-steps", type=int, default=1000)
    parser.add_argument("--learning-rate", type=float, default=1e-4)
    parser.add_argument("--warmup-steps", type=int, default=100)
    parser.add_argument("--train-examples", type=int, default=8192)
    parser.add_argument("--grad-accum", type=int, default=1,
                        help="sequential microbatches per optimizer update "
                             "(training/step.py grad_accum)")
    parser.add_argument("--model-dir", type=str, default=None)
    parser.add_argument("--tiny", action="store_true", help="CI-sized model")
    parser.add_argument("--remat", nargs="?", const="full", default=False,
                        choices=["full", "dots"])
    parser.add_argument("--fake-devices", type=int, default=None)
    args, _ = parser.parse_known_args(argv)

    if args.fake_devices:
        jax.config.update("jax_platforms", "cpu")
        from tfde_tpu.utils.devices import request_cpu_devices
        request_cpu_devices(args.fake_devices)

    info = bootstrap()
    global_batch = args.batch_size * max(info.num_processes, 1)

    model = bert_tiny_test(remat=args.remat) if args.tiny else BertBase(
        remat=args.remat
    )
    vocab = model.vocab_size
    # reserve the last id as [MASK] so synthetic streams never collide with it
    cfg = MlmConfig(vocab_size=vocab - 1, mask_id=vocab - 1)

    tokens = datasets.synthetic_tokens(
        args.train_examples, args.seq_len, vocab=vocab - 1
    )

    schedule = optax.warmup_cosine_decay_schedule(
        0.0, args.learning_rate,
        warmup_steps=min(args.warmup_steps, max(args.max_steps - 1, 1)),
        decay_steps=args.max_steps,
    )
    tx = masked_adamw(schedule, weight_decay=0.01)

    strategy = MultiWorkerMirroredStrategy()
    sample = np.zeros((global_batch, args.seq_len), np.int32)
    state, _ = init_state(model, tx, strategy, sample, seed=0)

    mngr = None
    if args.model_dir:
        mngr = CheckpointManager(f"{args.model_dir}/checkpoints")
        restored = mngr.restore_latest(state)
        if restored is not None:
            state = restored
    writer = (
        SummaryWriter(args.model_dir)
        if args.model_dir and jax.process_index() == 0
        else None
    )

    step_fn = make_custom_train_step(strategy, state, mlm_loss_fn,
                                     grad_accum=args.grad_accum)
    rng = jax.random.key(1)
    stream = batch_stream(tokens, cfg, global_batch, seed=0)
    start = int(jax.device_get(state.step))
    t0 = time.time()
    metrics = {}
    for step in range(start, args.max_steps):
        state, metrics = step_fn(state, next(stream), rng)
        if (step + 1) % 100 == 0:
            vals = {k: float(jax.device_get(v)) for k, v in metrics.items()}
            sps = 100 / (time.time() - t0)
            t0 = time.time()
            log.info("step %d: %s (%.2f steps/s)", step + 1, vals, sps)
            if writer is not None:
                writer.scalars(step + 1, {**vals, "global_step/sec": sps})
        if mngr is not None and (step + 1) % 500 == 0:
            mngr.save(state)

    if mngr is not None:
        mngr.save(state, force=True)
        mngr.wait()
        mngr.close()
    if writer is not None:
        writer.flush()
        writer.close()
    return state, metrics


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO, force=True)
    main()
