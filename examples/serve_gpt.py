"""Continuous-batching GPT serving demo — the framework's serving loop.

The reference's serving story ends at a SavedModel export of one forward
pass (`/root/reference/mnist_keras_distributed.py:151-162`); for the
causal-LM families this framework adds, serving means a decode loop. This
entrypoint drives `inference.ContinuousBatcher`: a fixed decode batch
where finished rows are re-used for queued requests mid-flight, every
request's greedy output identical to a solo `generate` run.

Usage (CPU demo):

    python examples/serve_gpt.py --tiny --fake-devices 1 \
        --requests 12 --batch-size 4 --max-new-tokens 24

Load real weights instead with --hf-dir (models/convert.py layout).
"""

from __future__ import annotations

import argparse
import logging
import time

import numpy as np

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import jax  # noqa: E402

from tfde_tpu.inference.server import ContinuousBatcher  # noqa: E402
from tfde_tpu.models.gpt import GPT2Small, gpt_tiny_test  # noqa: E402

log = logging.getLogger("serve_gpt")


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch-size", type=int, default=4,
                        help="resident decode rows")
    parser.add_argument("--max-len", type=int, default=128,
                        help="per-row cache budget (prompt + generated)")
    parser.add_argument("--max-new-tokens", type=int, default=24)
    parser.add_argument("--requests", type=int, default=12,
                        help="synthetic requests to serve")
    parser.add_argument("--temperature", type=float, default=0.0)
    parser.add_argument("--top-k", type=int, default=None)
    parser.add_argument("--top-p", type=float, default=None)
    parser.add_argument("--min-p", type=float, default=None)
    parser.add_argument("--repetition-penalty", type=float, default=1.0,
                        help="CTRL rule over each row's prompt+output "
                             "(1.0 = off); acts under greedy decoding too")
    parser.add_argument("--eos-id", type=int, default=None)
    parser.add_argument("--scan-depth", type=int, default=4, metavar="K",
                        help="fused decode ticks per host round-trip: the "
                             "batcher runs K model steps + sampling as ONE "
                             "jitted scan, so host scheduling cost drops to "
                             "O(1/K) per token (K adapts down near row "
                             "completions; 1 = a host sync every token)")
    parser.add_argument("--num-draft", type=int, default=0, metavar="K",
                        help="serve through SpeculativeContinuousBatcher "
                             "with K draft proposals per round (greedy "
                             "only; demo uses a tiny random draft — point "
                             "real deployments at a distilled draft)")
    parser.add_argument("--prefix-cache", type=str, default=None,
                        metavar="SPEC",
                        help="prefix-KV cache: 'on', 'off', or a byte "
                             "budget (default: the TFDE_PREFIX_CACHE env "
                             "knob). Requests sharing a cached prompt "
                             "prefix prefill only the uncached suffix "
                             "(inference/prefix_cache.py); greedy outputs "
                             "are bit-identical either way")
    parser.add_argument("--serve", type=int, default=None, metavar="PORT",
                        help="instead of the synthetic one-shot batch: "
                             "expose this batcher as an HTTP/SSE replica "
                             "on PORT (POST /generate, GET /healthz; "
                             "front several with inference.router.Router, "
                             "which adds the /v1/generate front door — "
                             "WORKFLOWS.md §13)")
    parser.add_argument("--hf-dir", type=str, default=None,
                        help="load GPT-2 weights converted by "
                             "`python -m tfde_tpu.models.convert`")
    parser.add_argument("--tokenizer", type=str, default=None,
                        metavar="DIR",
                        help="local save_pretrained() tokenizer directory "
                             "(offline, transformers.AutoTokenizer): serve "
                             "--prompt TEXT requests and print decoded "
                             "text instead of token ids")
    parser.add_argument("--prompt", action="append", default=None,
                        metavar="TEXT",
                        help="with --tokenizer: a text prompt to serve "
                             "(repeatable); replaces the synthetic "
                             "random-token requests")
    parser.add_argument("--tiny", action="store_true")
    parser.add_argument("--fake-devices", type=int, default=None)
    args, _ = parser.parse_known_args(argv)

    if args.fake_devices:
        jax.config.update("jax_platforms", "cpu")
        from tfde_tpu.utils.devices import request_cpu_devices
        request_cpu_devices(args.fake_devices)

    if args.hf_dir:
        from tfde_tpu.models.convert import load_converted

        model, params = load_converted(args.hf_dir)
    elif args.tiny:
        model = gpt_tiny_test()
        params = model.init(
            jax.random.key(0), np.zeros((1, 8), np.int32)
        )["params"]
    else:
        model = GPT2Small()
        params = model.init(
            jax.random.key(0), np.zeros((1, 8), np.int32)
        )["params"]
        log.warning("serving RANDOM weights; pass --hf-dir for a real model")

    sampling_flags = (args.temperature != 0.0 or args.top_k is not None
                      or args.top_p is not None or args.min_p is not None)
    if args.temperature == 0.0 and (args.top_k is not None
                                    or args.top_p is not None
                                    or args.min_p is not None):
        raise SystemExit(
            "--top-k/--top-p/--min-p only act when sampling — set "
            "--temperature > 0 (at 0.0 decoding is greedy argmax and the "
            "filters would be silent no-ops)"
        )
    prefix_spec = args.prefix_cache
    if prefix_spec is not None and prefix_spec.lstrip("-").isdigit():
        prefix_spec = int(prefix_spec)
    if args.num_draft > 0:
        if prefix_spec is not None:
            raise SystemExit(
                "--prefix-cache serves the plain batcher; the speculative "
                "batcher recomputes draft K/V per round and does not take "
                "a prefix cache yet"
            )
        if sampling_flags or args.repetition_penalty != 1.0:
            raise ValueError(
                "--num-draft serves the plain greedy verifier; drop "
                "--temperature/--top-k/--top-p/--min-p/"
                "--repetition-penalty (speculative SAMPLING lives in "
                "generate_speculative, not the batcher yet)"
            )
        from tfde_tpu.inference.server import SpeculativeContinuousBatcher
        from tfde_tpu.models.gpt import GPT

        draft = GPT(
            vocab_size=model.vocab_size,
            hidden_size=max(model.hidden_size // 4, 8),
            depth=max(model.depth // 4, 1),
            num_heads=max(model.num_heads // 4, 1),
            mlp_dim=max(model.mlp_dim // 4, 16),
            max_position=model.max_position,
            dtype=model.dtype,
        )
        draft_params = draft.init(
            jax.random.key(7), np.zeros((1, 8), np.int32)
        )["params"]
        srv = SpeculativeContinuousBatcher(
            model, draft, params, draft_params,
            batch_size=args.batch_size, max_len=args.max_len,
            num_draft=args.num_draft, eos_id=args.eos_id,
        )
    else:
        srv = ContinuousBatcher(
            model, params, batch_size=args.batch_size, max_len=args.max_len,
            temperature=args.temperature, top_k=args.top_k,
            top_p=args.top_p, min_p=args.min_p,
            repetition_penalty=args.repetition_penalty,
            eos_id=args.eos_id, scan_depth=args.scan_depth,
            prefix_cache=prefix_spec,
        )
    if args.serve is not None:
        from tfde_tpu.inference.router import ReplicaServer

        rs = ReplicaServer(srv, port=args.serve).start()
        log.info("replica serving on %s (POST /generate, GET /healthz); "
                 "Ctrl-C to stop", rs.url)
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            rs.close()
        return []
    tok = None
    if args.tokenizer:
        # offline by construction, like the conversion CLI: a local
        # save_pretrained() directory, nothing downloaded
        from transformers import AutoTokenizer

        tok = AutoTokenizer.from_pretrained(args.tokenizer,
                                            local_files_only=True)
    if args.prompt and tok is None:
        raise SystemExit("--prompt TEXT needs --tokenizer DIR to encode it")

    rng = np.random.default_rng(0)
    lengths = {}
    prompts = {}
    if args.prompt:
        for text in args.prompt:
            ids = np.asarray(tok(text)["input_ids"], np.int32)
            if not ids.size:
                raise SystemExit(
                    f"prompt {text!r} tokenized to zero ids — nothing to "
                    f"serve"
                )
            if int(ids.max()) >= model.vocab_size:
                # the embedding gather clamps inside jit — garbage output
                # with no error; refuse a mismatched tokenizer loudly
                raise SystemExit(
                    f"tokenizer id {int(ids.max())} >= model vocab "
                    f"{model.vocab_size}: this tokenizer does not belong "
                    f"to the served model"
                )
            rid = srv.submit(ids, args.max_new_tokens)
            lengths[rid] = len(ids)
            prompts[rid] = text
    else:
        for _ in range(args.requests):
            plen = int(rng.integers(2, 9))
            rid = srv.submit(
                rng.integers(0, model.vocab_size, plen), args.max_new_tokens
            )
            lengths[rid] = plen

    t0 = time.time()
    done = srv.run()
    dt = time.time() - t0
    total = sum(len(toks) for _, toks in done)
    for rid, toks in done:
        if tok is not None and rid in prompts:
            log.info("req %d (%d prompt tokens): %r -> %r", rid,
                     lengths[rid], prompts[rid],
                     tok.decode(np.asarray(toks).tolist()))
        else:
            log.info("req %d: prompt %d -> %d tokens", rid, lengths[rid],
                     len(toks))
    log.info("served %d requests / %d tokens in %.2fs (%.1f tok/s, "
             "batch %d)", len(done), total, dt, total / max(dt, 1e-9),
             args.batch_size)
    if hasattr(srv, "stats"):
        # host-overhead accounting: dispatches/syncs per token fall as
        # O(1/scan_depth) in steady state (the fused-scan payoff)
        log.info("serving stats: %s", srv.stats())
    if getattr(srv, "prefix_cache", None) is not None:
        log.info("prefix cache: %s", srv.prefix_cache.stats())
    return done


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    main()
