"""AST project lint: lock discipline, greedy-path rng ban, knob audit.

Three rules over the source tree (no execution, no jax import needed for
the first two):

1. **Lock discipline** — classes whose methods run on more than one
   thread (HTTP handler threads vs the step/rollup loop) are declared in
   LOCKED_CLASSES with the lock attribute that guards their shared
   state. Inside their methods, attribute writes must happen under a
   ``with self.<lock>`` block:

   - any augmented assignment to an attribute (``rep.outstanding += n``,
     ``self._evictions += 1``) — the read-modify-write the GIL does NOT
     make atomic across the read and the write, the exact bug class the
     PR 8 review fixed by hand in the Router;
   - plain assignment to a ``self.*`` attribute or a ``self.*[...]``
     subscript (``self._inflight[tid] = idx``) — the publish of shared
     state.

   Plain assignment to a *local* object's attribute stays legal
   (constructing a new object before publishing it is the standard
   pattern). ``__init__`` and per-class allow-listed methods/attributes
   are exempt; classes whose instances are serialized by an EXTERNAL
   lock (``_BatcherBase`` runs entirely under ``ReplicaServer.lock``)
   are declared with ``external=...`` and skipped with that reason in
   the audit output, so the exemption is a reviewable line here, not
   silence.

   A spec may additionally name ``guarded_attrs``: attributes where ANY
   access — reads included — must happen under the lock, because the
   object behind the attribute is only single-threaded by virtue of
   that lock (``ReplicaServer.batcher``: the step loop mutates the
   batcher's queue under ``self.lock``, so even ``len(b._queue)`` from
   a handler thread is a race — the exact bug the PR 14 review fixed in
   the Router's /load path).

2. **Greedy-path `jax.random.split` ban** — in `tfde_tpu/inference/`,
   every ``jax.random.split`` call must be lexically inside an ``if``
   whose condition mentions ``temperature`` or ``greedy``: splitting on
   the greedy path burns a key derivation per token for a sampler that
   never consumes it, and (worse) makes greedy outputs depend on the rng
   plumbing, breaking the bit-identity pins.

3. **Knob audit** — every string literal matching ``TFDE_[A-Z0-9_]+``
   in `tfde_tpu/` and `tools/` must be registered in
   `tfde_tpu/knobs.py` (prefix families like ``TFDE_RETRY_`` count);
   an unregistered name is a knob the operator cannot discover and the
   import-time typo check cannot defend.

Run: ``python tools/tfdelint.py [--root DIR]`` — exits 1 and lists
violations. `tools/lintgate.py` embeds the same pass and diffs its
output against the checked-in baseline.
"""

import argparse
import ast
import dataclasses
import os
import re
import sys
from typing import Dict, List, Optional, Set, Tuple

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

_KNOB_RE = re.compile(r"TFDE_[A-Z0-9_]+\Z")


@dataclasses.dataclass(frozen=True)
class LockSpec:
    """One threaded class's lock-discipline declaration."""

    #: attribute name of the guarding lock on self ('_lock', 'lock')
    lock: Optional[str] = "_lock"
    #: methods exempt from the check (beyond __init__): constructors,
    #: single-threaded setup, or methods that acquire the lock via a
    #: helper the AST pass can't see through
    exempt_methods: Tuple[str, ...] = ()
    #: self-attributes writable without the lock (documented reasons)
    exempt_attrs: Tuple[str, ...] = ()
    #: self-attributes where ANY access (reads included) must hold the
    #: lock: the attribute is a handle to an object that is only
    #: single-threaded under this lock
    guarded_attrs: Tuple[str, ...] = ()
    #: set when the class is serialized by a lock its OWNER holds; the
    #: class is skipped and the reason surfaces in the audit output
    external: Optional[str] = None


#: (repo-relative file, class name) -> LockSpec. Adding a threaded class
#: here is part of adding the class; the audit census in lintgate's
#: baseline pins this table's coverage.
LOCKED_CLASSES: Dict[Tuple[str, str], LockSpec] = {
    ("tfde_tpu/inference/router.py", "Router"): LockSpec(
        lock="_lock",
        # snapshot/exposition methods read shared state without the lock
        # by design (stale reads are fine for status surfaces); writes
        # anywhere must still be locked — the rule below only exempts a
        # method from the check entirely, so keep this list empty and
        # let reads pass (reads are never flagged).
    ),
    ("tfde_tpu/observability/aggregate.py", "ClusterAggregator"): LockSpec(
        lock="_lock",
    ),
    ("tfde_tpu/observability/metrics.py", "Registry"): LockSpec(
        lock="_lock",
    ),
    ("tfde_tpu/inference/server.py", "_BatcherBase"): LockSpec(
        external="ReplicaServer.lock — the HTTP server holds its RLock "
                 "around every submit/step/take_progress/cancel call; the "
                 "batcher itself is single-threaded by contract",
    ),
    ("tfde_tpu/inference/router.py", "ReplicaServer"): LockSpec(
        lock="lock",
        # the batcher is the object _BatcherBase's external-lock entry
        # above points at: it is only single-threaded while this
        # server's lock is held, so even READING through self.batcher
        # from a handler thread races the step loop
        guarded_attrs=("batcher",),
    ),
    # KV-capacity observability (PR 15): written from the batcher's step
    # loop under ReplicaServer.lock but READ from handler threads
    # (/load's kv block) and test threads, so each carries its own lock
    ("tfde_tpu/observability/capacity.py", "CapacityLedger"): LockSpec(
        lock="_lock",
    ),
    ("tfde_tpu/observability/capacity.py", "UsageMeter"): LockSpec(
        lock="_lock",
    ),
    ("tfde_tpu/observability/capacity.py", "UsageLog"): LockSpec(
        lock="_lock",
        # called only from write() with the lock already held (the
        # _locked suffix is the contract; the AST pass can't see a
        # caller-held lock)
        exempt_methods=("_compact_locked",),
    ),
    # boot & readiness (PR 17): phase edges arrive from the owner's
    # boot thread while /load handler threads snapshot() and the
    # module-level serving-path marks fan in from the batcher step loop
    ("tfde_tpu/observability/boot.py", "BootLedger"): LockSpec(
        lock="_lock",
        # called only from begin()/end()/ready()/new_epoch() with the
        # lock already held (the _locked suffix is the contract)
        exempt_methods=("_close_open_locked",),
    ),
    # paged KV (PR 18): allocations/frees arrive from the batcher step
    # loop under ReplicaServer.lock, but stats() is read from /load
    # handler threads and the paged capacity ledger, so the free-list
    # and refcounts carry their own lock
    ("tfde_tpu/inference/paged.py", "BlockPool"): LockSpec(
        lock="_lock",
    ),
    ("tfde_tpu/observability/capacity.py", "PagedCapacityLedger"): LockSpec(
        lock="_lock",
    ),
}

#: files whose jax.random.split calls must be temperature-guarded
GREEDY_BAN_DIRS = ("tfde_tpu/inference",)

#: files exempt from the knob audit: the registry itself (it declares
#: every name) and this linter (it documents the pattern)
KNOB_AUDIT_EXEMPT = ("tfde_tpu/knobs.py", "tools/tfdelint.py")


def _iter_py(root: str, subdirs=("tfde_tpu", "tools")) -> List[str]:
    out = []
    for sub in subdirs:
        base = os.path.join(root, sub)
        for dirpath, _dirnames, filenames in os.walk(base):
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return sorted(out)


def _rel(root: str, path: str) -> str:
    return os.path.relpath(path, root).replace(os.sep, "/")


# -- rule 1: lock discipline --------------------------------------------------
def _with_holds_lock(node: ast.With, lock: str) -> bool:
    for item in node.items:
        e = item.context_expr
        if (isinstance(e, ast.Attribute) and e.attr == lock
                and isinstance(e.value, ast.Name) and e.value.id == "self"):
            return True
    return False


class _LockVisitor(ast.NodeVisitor):
    def __init__(self, spec: LockSpec, filename: str, cls: str):
        self.spec = spec
        self.filename = filename
        self.cls = cls
        self.violations: List[str] = []
        self._lock_depth = 0
        self._method = None

    def _flag(self, node, what: str) -> None:
        self.violations.append(
            f"{self.filename}:{node.lineno}: {self.cls}.{self._method}: "
            f"{what} outside `with self.{self.spec.lock}` — shared state "
            f"mutated from handler threads must hold the class lock "
            f"(tools/tfdelint.py lock-discipline rule)")

    def check_method(self, fn: ast.FunctionDef) -> None:
        self._method = fn.name
        self._lock_depth = 0
        for stmt in fn.body:
            self.visit(stmt)

    def visit_With(self, node: ast.With) -> None:
        held = _with_holds_lock(node, self.spec.lock)
        if held:
            self._lock_depth += 1
        for stmt in node.body:
            self.visit(stmt)
        if held:
            self._lock_depth -= 1

    def visit_FunctionDef(self, node) -> None:
        # a nested function (thread target, callback) runs on its own
        # schedule: its body is checked with the lock NOT held, whatever
        # the enclosing context (the closure outlives the with block)
        saved = self._lock_depth
        self._lock_depth = 0
        for stmt in node.body:
            self.visit(stmt)
        self._lock_depth = saved

    visit_AsyncFunctionDef = visit_FunctionDef

    def _is_self_attr_target(self, t) -> bool:
        return (isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name) and t.value.id == "self")

    def _is_self_subscript_target(self, t) -> bool:
        return (isinstance(t, ast.Subscript)
                and self._is_self_attr_target(t.value))

    def _attr_name(self, t) -> str:
        if isinstance(t, ast.Subscript):
            t = t.value
        return t.attr if isinstance(t, ast.Attribute) else "?"

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        # ANY attribute aug-assign (self.* or local-object.*) is a
        # read-modify-write on possibly-shared state
        t = node.target
        if isinstance(t, (ast.Attribute, ast.Subscript)) \
                and self._lock_depth == 0:
            name = self._attr_name(t)
            if name not in self.spec.exempt_attrs:
                self._flag(node, f"augmented write to .{name}")
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        if self._lock_depth == 0:
            for t in node.targets:
                if (self._is_self_attr_target(t)
                        or self._is_self_subscript_target(t)):
                    name = self._attr_name(t)
                    if name not in self.spec.exempt_attrs:
                        self._flag(node, f"write to self.{name}")
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        # guarded attrs: reads count too — the attribute is a handle to
        # an object whose thread-safety IS this lock
        if (self._lock_depth == 0
                and node.attr in self.spec.guarded_attrs
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            self._flag(node, f"access to self.{node.attr}")
        self.generic_visit(node)


def lint_locks(root: str, table=None) -> Tuple[List[str], Dict[str, str]]:
    """Returns (violations, audit) where audit maps 'file::Class' to its
    status ('checked' or the external-lock reason)."""
    table = LOCKED_CLASSES if table is None else table
    violations: List[str] = []
    audit: Dict[str, str] = {}
    for (relpath, clsname), spec in sorted(table.items()):
        path = os.path.join(root, relpath)
        key = f"{relpath}::{clsname}"
        if spec.external is not None:
            audit[key] = f"external lock: {spec.external}"
            continue
        try:
            tree = ast.parse(open(path).read(), filename=relpath)
        except (OSError, SyntaxError) as e:
            violations.append(f"{relpath}: could not parse for lock "
                              f"discipline: {e}")
            continue
        cls = next((n for n in ast.walk(tree)
                    if isinstance(n, ast.ClassDef) and n.name == clsname),
                   None)
        if cls is None:
            violations.append(
                f"{relpath}: class {clsname} not found — LOCKED_CLASSES "
                f"is stale; update tools/tfdelint.py")
            continue
        audit[key] = "checked"
        for item in cls.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if item.name == "__init__" or item.name in spec.exempt_methods:
                continue
            v = _LockVisitor(spec, relpath, clsname)
            v.check_method(item)
            violations.extend(v.violations)
    return violations, audit


# -- rule 2: greedy-path jax.random.split ban ---------------------------------
def _is_random_split(call: ast.Call) -> bool:
    f = call.func
    return (isinstance(f, ast.Attribute) and f.attr == "split"
            and isinstance(f.value, ast.Attribute) and f.value.attr == "random")


class _SplitVisitor(ast.NodeVisitor):
    """Tracks whether any enclosing `if` condition mentions temperature/
    greedy/sampled; flags unguarded jax.random.split calls. BOTH branches
    of a guarded `if` count as guarded — the author has branched on the
    greedy/sampling distinction, and the split belongs to whichever side
    they put it on. A function whose NAME marks it as the sampling-only
    program (`*_sampled`) is guarded throughout: it is a distinct jit
    entry point the greedy path never calls (speculative.py's
    `_spec_round_sampled` vs `_spec_round`)."""

    GUARD_WORDS = ("temperature", "greedy", "sampled")

    def __init__(self, filename: str):
        self.filename = filename
        self.violations: List[str] = []
        self._guard = 0

    def _guarded_test(self, test) -> bool:
        src = ast.dump(test)
        return any(w in src for w in self.GUARD_WORDS)

    def visit_FunctionDef(self, node) -> None:
        guarded = "sampled" in node.name
        if guarded:
            self._guard += 1
        self.generic_visit(node)
        if guarded:
            self._guard -= 1

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_If(self, node: ast.If) -> None:
        guarded = self._guarded_test(node.test)
        if guarded:
            self._guard += 1
        for stmt in node.body:
            self.visit(stmt)
        for stmt in node.orelse:
            self.visit(stmt)
        if guarded:
            self._guard -= 1

    def visit_IfExp(self, node: ast.IfExp) -> None:
        guarded = self._guarded_test(node.test)
        if guarded:
            self._guard += 1
        self.visit(node.body)
        self.visit(node.orelse)
        if guarded:
            self._guard -= 1
        self.visit(node.test)

    def visit_Call(self, node: ast.Call) -> None:
        if _is_random_split(node) and self._guard == 0:
            self.violations.append(
                f"{self.filename}:{node.lineno}: jax.random.split on an "
                f"unguarded path — in inference code every split must sit "
                f"under an `if` mentioning temperature/greedy, or greedy "
                f"decoding pays for (and depends on) sampling rng "
                f"(tools/tfdelint.py greedy-split rule)")
        self.generic_visit(node)


def lint_greedy_split(root: str, dirs=GREEDY_BAN_DIRS) -> List[str]:
    violations: List[str] = []
    for path in _iter_py(root, dirs):
        rel = _rel(root, path)
        try:
            tree = ast.parse(open(path).read(), filename=rel)
        except (OSError, SyntaxError) as e:
            violations.append(f"{rel}: could not parse: {e}")
            continue
        v = _SplitVisitor(rel)
        v.visit(tree)
        violations.extend(v.violations)
    return violations


# -- rule 3: knob audit -------------------------------------------------------
def collect_knob_literals(root: str, subdirs=("tfde_tpu", "tools")):
    """All (file, line, name) TFDE_* string literals in the tree."""
    hits = []
    for path in _iter_py(root, subdirs):
        rel = _rel(root, path)
        if rel in KNOB_AUDIT_EXEMPT:
            continue
        try:
            tree = ast.parse(open(path).read(), filename=rel)
        except (OSError, SyntaxError):
            continue
        for node in ast.walk(tree):
            if (isinstance(node, ast.Constant) and isinstance(node.value, str)
                    and _KNOB_RE.match(node.value)):
                hits.append((rel, node.lineno, node.value))
    return hits


def lint_knobs(root: str) -> Tuple[List[str], List[str]]:
    """Returns (violations, sorted unique knob names seen)."""
    from tfde_tpu import knobs

    violations = []
    seen: Set[str] = set()
    for rel, lineno, name in collect_knob_literals(root):
        seen.add(name)
        if not knobs.is_registered(name):
            violations.append(
                f"{rel}:{lineno}: env knob {name!r} is not registered in "
                f"tfde_tpu/knobs.py — add a Knob entry (name, kind, "
                f"default, doc) so the typo check and the README table "
                f"cover it (tools/tfdelint.py knob-audit rule)")
    return violations, sorted(seen)


# -- entry points -------------------------------------------------------------
def lint_repo(root: str = ROOT) -> dict:
    """Run all three rules; returns {violations: [...], audit: {...},
    knobs_seen: [...]} — the structure lintgate baselines."""
    lock_v, audit = lint_locks(root)
    split_v = lint_greedy_split(root)
    knob_v, seen = lint_knobs(root)
    return {
        "violations": lock_v + split_v + knob_v,
        "lock_audit": audit,
        "knobs_seen": seen,
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=ROOT)
    args = ap.parse_args()
    result = lint_repo(args.root)
    for key in ("lock_audit",):
        for cls, status in sorted(result[key].items()):
            print(f"  {cls}: {status}")
    print(f"  knob audit: {len(result['knobs_seen'])} TFDE_* names seen")
    if result["violations"]:
        print("tfdelint: FAIL")
        for v in result["violations"]:
            print(f"  - {v}")
        return 1
    print("tfdelint: pass")
    return 0


if __name__ == "__main__":
    sys.exit(main())
