"""Per-op roofline suite for the attention hot path.

Extends the tools/flash_ab.py lowered-HLO/microbench pattern from a single
A/B into a roofline report: each op variant is timed on the live chip and
its ACHIEVED flops — credited by the analytic model in ops/roofline.py,
which counts causal/windowed attention at its true in-band work — are
reported against chip peak (bench.py's PEAK_FLOPS table). A full-causal
MFU figure computed against full-S^2 flops looks artificially healthy;
this is the per-op view that shows where the gpt_long gap actually lives.

Two modes:

  python tools/roofline.py               # hardware microbench (run on TPU;
                                         # runs on CPU via interpret mode
                                         # for plumbing checks, slowly)
  python tools/roofline.py --smoke       # tiny shapes, any backend
  python tools/roofline.py --check-tiles # tile-visit gate only: pins the
                                         # flash kernels' executed tile
                                         # schedule against the analytic
                                         # band (CPU-fast, no hardware) and
                                         # exits 1 on regression — wired
                                         # into tools/tier1.sh

Per-op JSON fields (one line per op, cumulative like bench.py):
  <op>_ms            timed fwd+bwd step
  <op>_credited_tflops   achieved, counting in-band work only
  <op>_frac_of_peak  credited achieved / chip peak (the roofline height)
  <op>_band_frac     credited / executed-tile flops — how much of what the
                     kernel computes is useful work (tile-quantization
                     overhead of the band; 1.0 for bidirectional)
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from bench import _Clock, chip_peak_flops
from tfde_tpu.ops import roofline as rl
from tfde_tpu.ops.flash_attention import flash_attention, bwd_tile_plan

# (name, seq, causal, window, logit_cap): the bench attention variants —
# plain causal (gpt_long), windowed and windowed+softcap (gpt_long_win /
# the Gemma-2 family), bidirectional (bert)
OPS = [
    ("attn_causal", 4096, True, None, None),
    ("attn_win1024", 4096, True, 1024, None),
    ("attn_win1024_cap50", 4096, True, 1024, 50.0),
    ("attn_bidir", 4096, False, None, None),
]
TRAIN_MULT = 3.0  # fwd+bwd credited at 3x forward (backward ~2x)


def measure(clock, name, b, s, h, d, causal, window, logit_cap, peak,
            interpret, smoke):
    rng = np.random.default_rng(0)
    dtype = jnp.float32 if interpret else jnp.bfloat16
    q, k, v = (
        jnp.asarray(rng.standard_normal((b, s, h, d)), dtype)
        for _ in range(3)
    )

    def loss(q, k, v):
        return flash_attention(
            q, k, v, causal, None, None, interpret, window, None, logit_cap
        ).astype(jnp.float32).sum()

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    clock.fetch_scalar(g(q, k, v)[0][0, 0, 0, 0].astype(jnp.float32))

    def run(reps):
        dq = None
        for _ in range(reps):
            dq, _, _ = g(q, k, v)
        return dq

    reps, window_s, _, _ = clock.timed(
        run, lambda dq: dq[0, 0, 0, 0].astype(jnp.float32),
        0.05 if smoke else 1.0, start_reps=1 if smoke else 5,
        max_reps=5_000,
    )
    step_s = window_s / reps

    credited = TRAIN_MULT * b * s * rl.attention_flops_per_token(
        h * d, s, causal, window
    )
    plan = rl.tile_visits(s, None, None, causal, window)
    # executed-tile flops: every visited tile runs a full bq x bk block
    executed = credited * (
        plan["fwd"] * plan["block_q"] * plan["block_k"]
        / (s * rl.mean_attended_keys(s, causal, window))
    )
    achieved = credited / step_s
    return {
        f"{name}_ms": round(step_s * 1e3, 3),
        f"{name}_credited_tflops": round(achieved / 1e12, 2),
        f"{name}_frac_of_peak": round(achieved / peak, 4),
        f"{name}_band_frac": round(credited / executed, 4),
        f"{name}_tile_visits": plan["fwd"],
        f"{name}_tile_grid": plan["grid"],
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--check-tiles", action="store_true",
                    help="tile-visit gate only (tier-1; exits 1 on drift)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes for plumbing checks on any backend")
    args = ap.parse_args()

    if args.check_tiles:
        failures = rl.check_tile_visits(verbose=True)
        for f in failures:
            print(f"TILE REGRESSION: {f}", file=sys.stderr)
        print(json.dumps({"roofline_tile_gate": "fail" if failures
                          else "pass", "failures": failures}))
        sys.exit(1 if failures else 0)

    dev = jax.devices()[0]
    interpret = dev.platform == "cpu"
    peak, peak_known = chip_peak_flops(getattr(dev, "device_kind", ""))
    clock = _Clock()
    out = {
        "platform": dev.platform,
        "chip_peak_tflops": round(peak / 1e12, 1),
        "chip_peak_known": peak_known,
    }
    for name, seq, causal, window, cap in OPS:
        b, s, h, d = (1, 512, 2, 64) if args.smoke else (1, seq, 12, 64)
        if args.smoke and window is not None:
            window = 128
        try:
            out.update(measure(clock, name, b, s, h, d, causal, window,
                               cap, peak, interpret, args.smoke))
        except Exception as e:
            out[f"{name}_error"] = f"{type(e).__name__}: {e}"[:200]
        print(json.dumps(out), flush=True)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
