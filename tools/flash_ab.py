"""Hardware A/B of the flash-attention backward implementations.

Times causal fwd+bwd at the bench shapes for three implementations:
XLA reference einsum (autodiff), Pallas forward + Pallas dKV/dQ backward
(TFDE_FLASH_BWD=pallas), Pallas forward + blockwise-JAX backward
(TFDE_FLASH_BWD=jax). Prints one JSON line. Run on the live chip to pick
the default backward (BENCH_builder_r04.json showed the round-3 Pallas
pair at 0.55-0.69x of XLA — slower than the blockwise backward it
replaced).
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from bench import _Clock
from tfde_tpu.ops.attention import reference_attention
from tfde_tpu.ops.flash_attention import flash_attention


def make_qkv(b, s, h, d):
    rng = np.random.default_rng(0)
    return tuple(
        jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)
        for _ in range(3)
    )


def main():
    causal = "--non-causal" not in sys.argv
    clock = _Clock()
    out = {"platform": jax.devices()[0].platform, "causal": causal}

    def ref_loss(q, k, v):
        return reference_attention(q, k, v, causal=causal).astype(jnp.float32).sum()

    ref_g = jax.jit(jax.grad(ref_loss, argnums=(0, 1, 2)))

    def make_flash_grad(bwd):
        # separate closures per bwd mode: the env var is read at trace time
        def loss(q, k, v):
            os.environ["TFDE_FLASH_BWD"] = bwd
            return flash_attention(q, k, v, causal=causal).astype(jnp.float32).sum()

        return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

    impls = {"ref": ref_g, "pallas": make_flash_grad("pallas"),
             "jax": make_flash_grad("jax")}

    def time_impl(g, q, k, v):
        def run(reps):
            dq = None
            for _ in range(reps):
                dq, _, _ = g(q, k, v)
            return dq

        reps, window, _, _ = clock.timed(
            run, lambda dq: dq[0, 0, 0, 0].astype(jnp.float32), 1.0,
            start_reps=5, max_reps=5_000,
        )
        return window / reps

    for b, s in ((4, 2048), (2, 4096), (1, 8192)):
        q, k, v = make_qkv(b, s, 12, 64)
        times = {}
        for name, g in impls.items():
            os.environ["TFDE_FLASH_BWD"] = (
                "jax" if name == "jax" else "pallas"
            )
            clock.fetch_scalar(g(q, k, v)[0][0, 0, 0, 0].astype(jnp.float32))
            times[name] = time_impl(g, q, k, v)
        for name, t in times.items():
            out[f"{name}_ms_s{s}"] = round(t * 1e3, 3)
        out[f"pallas_speedup_s{s}"] = round(times["ref"] / times["pallas"], 3)
        out[f"jax_speedup_s{s}"] = round(times["ref"] / times["jax"], 3)
        print(json.dumps(out), flush=True)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
