"""Memory & compile regression gate: pin the compile counts and peak-HBM
of a deterministic workload against a checked-in baseline.

The memwatch ledger (tfde_tpu/observability/memwatch.py) measures what
every compiled program costs, and the recompile sentinel (recompile.py)
counts every jit-cache miss per site. This tool turns both into a tier-1
gate: it drives ONE fixed CPU workload — a short instrumented train run
(tiny CNN through the Estimator loop) plus a serving drain (tiny GPT
through ContinuousBatcher's pad-ladder admission and fused decode scan) —
then compares the observed per-site miss counts and per-program peak
bytes against tools/memgate_baseline.json:

- a site compiling MORE than its baselined miss count fails the gate (a
  new bucket, a donation bug, a per-token recompile — the regression
  class the sentinel exists for);
- a program whose peak bytes exceed its baselined ceiling by more than
  PEAK_SLACK fails the gate (an activation or cache blow-up);
- a site or program MISSING from the baseline fails loudly: the workload
  is deterministic, so new names mean the wiring changed and the
  baseline must be regenerated deliberately.

Modes:

  python tools/memgate.py --check    # compare vs baseline; exit 1 on
                                     # regression (wired into tier1.sh)
  python tools/memgate.py --update   # run the workload and REWRITE the
                                     # baseline (commit the diff)
  python tools/memgate.py --print    # run and dump the observation only

Injection self-test (used by tests/test_recompile.py): with
TFDE_MEMGATE_INJECT=1 the serving phase mutates the decode scan's static
sampling temperature every step — a genuine per-token-recompile
regression through the real batcher — and --check must fail.

Re-baseline after a deliberate compile-count or memory change::

  JAX_PLATFORMS=cpu python tools/memgate.py --update
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TFDE_MEMWATCH", "on")

BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "memgate_baseline.json")
#: peak-bytes ceiling slack: estimate-mode arg/out bytes are exact for a
#: fixed workload, but leave headroom for dtype/layout drift that is not
#: a regression (10%)
PEAK_SLACK = 1.10
ENV_INJECT = "TFDE_MEMGATE_INJECT"


def _train_phase() -> None:
    """A short instrumented Estimator run: registers the train_step site
    and mem/train_step program, exercises the goodput compile bucket."""
    import tempfile

    import numpy as np
    import optax

    from tfde_tpu.models.cnn import PlainCNN
    from tfde_tpu.training.lifecycle import Estimator, RunConfig

    n, b = 128, 32
    rng = np.random.default_rng(0)
    images = rng.random((n, 784), np.float32)
    labels = rng.integers(0, 10, (n, 1)).astype(np.int32)

    def input_fn():
        def gen():
            i = 0
            while True:
                s = (i * b) % n
                yield (images[s:s + b], labels[s:s + b])
                i += 1

        return gen()

    est = Estimator(
        model=PlainCNN(),
        optimizer=optax.sgd(0.1),
        config=RunConfig(
            model_dir=tempfile.mkdtemp(prefix="tfde-memgate-"),
            save_summary_steps=4,
            log_step_count_steps=8,
            save_checkpoints_steps=None,
        ),
    )
    est.train(input_fn, 6)
    est.close()


def _serve_phase(inject: bool) -> None:
    """A deterministic serving drain through the real batcher: two prompt
    buckets, staggered budgets, the full pad ladder + decode-depth
    ladder. With `inject`, every step perturbs the decode scan's static
    temperature — the per-token-recompile regression the gate must
    catch."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tfde_tpu.inference.server import ContinuousBatcher
    from tfde_tpu.models.gpt import GPT

    model = GPT(vocab_size=256, hidden_size=32, depth=2, num_heads=2,
                mlp_dim=64, max_position=64, dtype=jnp.float32)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    srv = ContinuousBatcher(model, params, batch_size=4, max_len=48,
                            scan_depth=4)
    rng = np.random.default_rng(0)
    for i, (plen, n_new) in enumerate(
            [(3, 8), (6, 5), (4, 12), (7, 6), (3, 9), (5, 4)]):
        srv.submit(rng.integers(0, model.vocab_size, plen), n_new)
    step = 0
    while not srv.idle:
        if inject and step > 0:
            # a DISTINCT static temperature every step recompiles the
            # decode scan on an already-seen fingerprint — the genuine
            # cache-thrash pathology, driven through the real entry point
            srv._sampling["temperature"] = 0.5 + 1e-4 * step
        srv.step()
        step += 1
        if step > 200:
            raise RuntimeError("serve phase failed to drain")

    # the same drain through the paged block pool: registers the paged
    # prefill/decode sites and the pool's program footprint so the gate
    # pins both layouts (the paged prefill is chunk-shaped, so mixed
    # prompt lengths must NOT widen its compile count — the one-program
    # claim the paged-KV PR makes)
    paged = ContinuousBatcher(model, params, batch_size=4, max_len=48,
                              scan_depth=4, paged=True)
    for plen, n_new in [(3, 8), (6, 5), (4, 12), (7, 6), (3, 9), (5, 4)]:
        paged.submit(rng.integers(0, model.vocab_size, plen), n_new)
    step = 0
    while not paged.idle:
        paged.step()
        step += 1
        if step > 200:
            raise RuntimeError("paged serve phase failed to drain")


def observe() -> dict:
    """Run the workload; return {sites: {name: misses}, programs:
    {name: peak_bytes}} from the sentinel + ledger."""
    from tfde_tpu.observability import memwatch, recompile

    recompile.install()
    _train_phase()
    _serve_phase(inject=os.environ.get(ENV_INJECT, "") not in ("", "0"))
    return {
        "sites": {name: {"misses": s["misses"]}
                  for name, s in sorted(recompile.sites().items())},
        "programs": {name: {"peak_bytes": int(p.peak_bytes)}
                     for name, p in sorted(memwatch.programs().items())},
    }


def check(obs: dict, base: dict) -> list:
    """Compare an observation against the baseline; returns the list of
    failure strings (empty = gate passes)."""
    fails = []
    for name, s in obs["sites"].items():
        b = base.get("sites", {}).get(name)
        if b is None:
            fails.append(
                f"site {name} not in baseline — new watched entry point; "
                f"re-baseline with: python tools/memgate.py --update"
            )
            continue
        if s["misses"] > b["misses"]:
            fails.append(
                f"site {name}: {s['misses']} compiles > baseline "
                f"{b['misses']} — a jit program is recompiling beyond "
                f"its pinned budget (see WORKFLOWS.md §15)"
            )
    for name, p in obs["programs"].items():
        b = base.get("programs", {}).get(name)
        if b is None:
            fails.append(
                f"program {name} not in baseline — re-baseline with: "
                f"python tools/memgate.py --update"
            )
            continue
        ceiling = int(b["peak_bytes"] * PEAK_SLACK)
        if p["peak_bytes"] > ceiling:
            fails.append(
                f"program {name}: peak {p['peak_bytes']} bytes > ceiling "
                f"{ceiling} (baseline {b['peak_bytes']} x {PEAK_SLACK})"
            )
    return fails


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    mode = ap.add_mutually_exclusive_group(required=True)
    mode.add_argument("--check", action="store_true",
                      help="compare vs baseline; exit 1 on regression")
    mode.add_argument("--update", action="store_true",
                      help="run the workload and rewrite the baseline")
    mode.add_argument("--print", dest="show", action="store_true",
                      help="run and dump the observation JSON only")
    ap.add_argument("--baseline", default=BASELINE,
                    help=f"baseline path (default {BASELINE})")
    args = ap.parse_args()

    obs = observe()
    if args.show:
        print(json.dumps(obs, indent=2, sort_keys=True))
        return 0
    if args.update:
        obs["_note"] = ("generated by: JAX_PLATFORMS=cpu python "
                        "tools/memgate.py --update — regenerate after any "
                        "deliberate compile-count or memory change")
        with open(args.baseline, "w") as f:
            json.dump(obs, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"memgate: baseline written to {args.baseline}")
        return 0

    try:
        with open(args.baseline) as f:
            base = json.load(f)
    except OSError as e:
        print(f"memgate: FAIL — no baseline ({e}); generate one with "
              f"python tools/memgate.py --update")
        return 1
    fails = check(obs, base)
    if fails:
        print("memgate: FAIL")
        for f in fails:
            print(f"  - {f}")
        return 1
    print(f"memgate: pass ({len(obs['sites'])} sites, "
          f"{len(obs['programs'])} programs within baseline)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
