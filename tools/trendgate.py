"""Perf trendline gate: pin the committed BENCH history against a
direction/threshold policy so a hardware capture that regresses a
headline metric fails tier-1 the same way a compile-count or peak-bytes
regression does (the hardware sibling of tools/memgate.py — ROADMAP
item 6).

The committed ``BENCH_*.json`` files are heterogeneous: driver wrapper
records (``{"n", "cmd", "rc", "tail", "parsed"}`` — ``parsed`` may be
null and ``tail`` may hold a truncated payload), flat builder artifacts,
failed rounds carrying an ``"error"``, and one capture with plausible-
looking numbers but no calibration anchor. This gate reads them ALL, in
round order, and sorts each into:

- **comparable**: parses, has no error, ``platform == "tpu"`` and a
  calibration anchor at >= 0.8 of chip peak (the BASELINE.md trust rule
  — a capture that cannot vouch for its own clock cannot vouch for a
  trend either);
- **skipped-with-reason**: everything else, listed in TREND.md so a
  burned round is visible instead of silently absent.

``--check`` compares the latest comparable capture against the previous
one, metric by metric, under tools/trendgate_policy.json: higher-is-
better for mfu/throughput, lower-is-better for step/latency/compile
metrics, per-metric slack, and ``gate: false`` for informational rows
(e.g. ``flash_speedup``, whose reference implementation legitimately got
faster between rounds). A gated metric moving past its slack in the
wrong direction — or disappearing from the latest capture — fails
loudly.

Modes:

  python tools/trendgate.py --check    # gate the committed history;
                                       # exit 1 on regression (tier1.sh)
  python tools/trendgate.py --update   # rewrite TREND.md (commit it)
  python tools/trendgate.py --print    # dump the trend table as JSON

Injection self-test: with TFDE_TRENDGATE_INJECT=1 a synthetic latest
round is appended with every gated metric pushed past twice its slack in
the regressing direction — --check must fail (tools/tier1.sh runs this
to prove the gate bites, like the memgate/lintgate drills).

A deliberate perf change re-baselines by committing the new BENCH
capture and regenerating the report::

  python tools/trendgate.py --update

(adjust the metric's slack in tools/trendgate_policy.json when the new
level is intended).
"""

import argparse
import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
POLICY = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "trendgate_policy.json")
REPORT = os.path.join(REPO, "TREND.md")
ENV_INJECT = "TFDE_TRENDGATE_INJECT"

_ROUND = re.compile(r"BENCH_(builder_)?r(\d+)\.json$")
#: trend columns rendered in TREND.md (older comparable rounds elide)
MAX_COLUMNS = 6


# -- capture parsing ----------------------------------------------------------
def _salvage_tail(tail: str):
    """Last line of a wrapper's captured tail that parses as a JSON
    object — the driver emits one cumulative line per config, so a
    timed-out attempt's tail may still hold a full payload. A HEAD-
    truncated tail (BENCH_r05) fails here and the round skips."""
    for ln in reversed((tail or "").strip().splitlines()):
        try:
            obj = json.loads(ln)
        except json.JSONDecodeError:
            continue
        if isinstance(obj, dict):
            return obj
    return None


def parse_capture(path: str, trust: dict) -> dict:
    """One BENCH file -> {"file", "round", "source", "metrics"|None,
    "skip"|None, "meta", "raw"}. Never raises: a malformed committed
    capture is a skip reason, not a gate crash."""
    name = os.path.basename(path)
    m = _ROUND.search(name)
    cap = {
        "file": name,
        "round": int(m.group(2)) if m else 0,
        "source": "builder" if (m and m.group(1)) or "builder" in name
        else "driver",
        "metrics": None,
        "skip": None,
        "meta": None,
    }

    def skip(reason):
        cap["skip"] = reason
        return cap

    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return skip(f"unparseable file: {e}")
    if not isinstance(doc, dict):
        return skip("not a JSON object")

    if "parsed" in doc and "cmd" in doc:  # driver wrapper record
        payload = doc.get("parsed")
        if payload is None:
            payload = _salvage_tail(doc.get("tail", ""))
        if payload is None:
            return skip(f"no parseable payload (driver rc={doc.get('rc')}; "
                        f"tail holds no complete JSON line)")
    else:
        payload = doc
    if not isinstance(payload, dict):
        return skip("payload is not a JSON object")
    cap["meta"] = payload.get("bench_meta")

    if payload.get("error"):
        return skip(f"failed capture: {payload['error']}")
    want_platform = trust.get("platform", "tpu")
    if payload.get("platform") != want_platform:
        return skip(f"platform {payload.get('platform')!r} != "
                    f"{want_platform!r}")
    calib = payload.get("calib_frac_of_peak")
    if calib is None:
        return skip("no calibration anchor (calib_frac_of_peak absent) — "
                    "untrusted clock")
    floor = float(trust.get("min_calib_frac_of_peak", 0.8))
    try:
        calib = float(calib)
    except (TypeError, ValueError):
        return skip(f"calibration anchor not a number: {calib!r}")
    if calib < floor:
        return skip(f"calib_frac_of_peak {calib} below trust floor {floor}")
    if not float(payload.get("value", 0.0) or 0.0) > 0.0:
        return skip("headline value is zero/absent")

    cap["metrics"] = {
        k: float(v) for k, v in payload.items()
        if isinstance(v, (int, float)) and not isinstance(v, bool)
    }
    return cap


def load_history(repo: str, trust: dict) -> list:
    """Every BENCH_*.json parsed, in round order (builder captures sort
    before the driver record of the same round — the driver line may be
    a replay OF the builder artifact)."""
    caps = [parse_capture(p, trust)
            for p in glob.glob(os.path.join(repo, "BENCH_*.json"))]
    caps.sort(key=lambda c: (c["round"],
                             0 if c["source"] == "builder" else 1,
                             c["file"]))
    return caps


def inject_capture(last: dict, policy: dict) -> dict:
    """Synthetic regressed round for the TFDE_TRENDGATE_INJECT drill:
    every gated metric present in the latest comparable capture is
    pushed past TWICE its slack in the regressing direction."""
    metrics = dict(last["metrics"])
    default_slack = float(policy.get("default_slack", 0.10))
    for name, mp in policy.get("metrics", {}).items():
        if not mp.get("gate", True) or name not in metrics:
            continue
        slack = float(mp.get("slack", default_slack))
        if mp.get("direction", "higher") == "higher":
            metrics[name] *= (1.0 - 2.0 * slack)
        else:
            metrics[name] *= (1.0 + 2.0 * slack)
    return {
        "file": "INJECTED(seeded regression)",
        "round": last["round"] + 1,
        "source": "inject",
        "metrics": metrics,
        "skip": None,
        "meta": {"note": "synthetic TFDE_TRENDGATE_INJECT round"},
    }


# -- trend + gate -------------------------------------------------------------
def comparable(caps: list) -> list:
    return [c for c in caps if c["skip"] is None]


def build_trend(caps: list, policy: dict) -> dict:
    """{"rows": [per-policy-metric], "skipped": [...], "pair": (prev,
    last) filenames or None} — the --print payload and the TREND.md
    source."""
    comp = comparable(caps)
    default_slack = float(policy.get("default_slack", 0.10))
    rows = []
    prev = comp[-2] if len(comp) >= 2 else None
    last = comp[-1] if comp else None
    for name in sorted(policy.get("metrics", {})):
        mp = policy["metrics"][name]
        direction = mp.get("direction", "higher")
        slack = float(mp.get("slack", default_slack))
        gate = bool(mp.get("gate", True))
        row = {
            "metric": name, "direction": direction, "slack": slack,
            "gate": gate,
            "values": [(c["file"], c["metrics"].get(name)) for c in comp],
            "delta_pct": None, "status": "no data",
        }
        a = prev["metrics"].get(name) if prev else None
        b = last["metrics"].get(name) if last else None
        if b is not None and a is None:
            row["status"] = "new"
        elif b is None and a is not None:
            row["status"] = "missing from latest"
        elif a is not None and b is not None:
            row["delta_pct"] = 100.0 * (b - a) / a if a else None
            worse = (b < a * (1.0 - slack) if direction == "higher"
                     else b > a * (1.0 + slack))
            better = b > a if direction == "higher" else b < a
            row["status"] = ("REGRESSED" if worse
                             else "improved" if better else "ok")
            if worse and not gate:
                row["status"] = "regressed (informational)"
        rows.append(row)
    return {
        "rows": rows,
        "skipped": [{"file": c["file"], "reason": c["skip"]}
                    for c in caps if c["skip"] is not None],
        "pair": (prev["file"], last["file"]) if prev else None,
        "comparable": [c["file"] for c in comp],
    }


def check(caps: list, policy: dict) -> list:
    """Gate the latest comparable capture against the previous one;
    returns failure strings (empty = pass)."""
    comp = comparable(caps)
    if len(comp) < 2:
        # a single trusted capture is a baseline, not a trend
        return []
    prev, last = comp[-2], comp[-1]
    default_slack = float(policy.get("default_slack", 0.10))
    fails = []
    for name in sorted(policy.get("metrics", {})):
        mp = policy["metrics"][name]
        if not mp.get("gate", True):
            continue
        direction = mp.get("direction", "higher")
        slack = float(mp.get("slack", default_slack))
        a, b = prev["metrics"].get(name), last["metrics"].get(name)
        if a is None:
            continue  # metric is new (or older than the window) — no trend
        if b is None:
            fails.append(
                f"{name}: present in {prev['file']} but ABSENT from "
                f"{last['file']} — a gated metric disappeared; fix the "
                f"capture or mark it gate:false in tools/"
                f"trendgate_policy.json"
            )
            continue
        worse = (b < a * (1.0 - slack) if direction == "higher"
                 else b > a * (1.0 + slack))
        if worse:
            arrow = "dropped" if direction == "higher" else "rose"
            fails.append(
                f"{name} ({direction}-is-better): {arrow} "
                f"{a:g} -> {b:g} ({100.0 * (b - a) / a:+.1f}%, slack "
                f"{slack:.0%}) between {prev['file']} and {last['file']} "
                f"— a deliberate change commits the new capture and "
                f"re-renders with: python tools/trendgate.py --update"
            )
    return fails


# -- report -------------------------------------------------------------------
def _fmt(v) -> str:
    if v is None:
        return "—"
    if abs(v) >= 1000:
        return f"{v:,.1f}"
    return f"{v:g}"


def render_report(caps: list, policy: dict, fails: list) -> str:
    trend = build_trend(caps, policy)
    comp = comparable(caps)
    cols = comp[-MAX_COLUMNS:]
    lines = [
        "# BENCH trendline",
        "",
        "Generated by `python tools/trendgate.py --update` — do not edit "
        "by hand. Gate policy: `tools/trendgate_policy.json`; gate "
        "command: `python tools/trendgate.py --check` (wired into "
        "`tools/tier1.sh` as `TRENDGATE`).",
        "",
        "## Captures",
        "",
        "| capture | round | status |",
        "| --- | --- | --- |",
    ]
    for c in caps:
        status = "comparable" if c["skip"] is None else f"skipped: {c['skip']}"
        sha = (c["meta"] or {}).get("git_sha")
        if sha and c["skip"] is None:
            status += f" (sha {sha})"
        lines.append(f"| `{c['file']}` | r{c['round']:02d} | {status} |")
    lines += ["", "## Trend", ""]
    if trend["pair"]:
        lines.append(f"Gate compares `{trend['pair'][1]}` (latest "
                     f"comparable) against `{trend['pair'][0]}`.")
    else:
        lines.append("Fewer than two comparable captures — no trend to "
                     "gate yet.")
    header = "| metric | dir | gated | slack | " + " | ".join(
        f"`{c['file'].replace('BENCH_', '').replace('.json', '')}`"
        for c in cols) + " | Δ% | status |"
    sep = "| --- | --- | --- | --- |" + " --- |" * (len(cols) + 2)
    lines += ["", header, sep]
    for row in trend["rows"]:
        vals = dict(row["values"])
        cells = " | ".join(_fmt(vals.get(c["file"])) for c in cols)
        delta = ("—" if row["delta_pct"] is None
                 else f"{row['delta_pct']:+.1f}%")
        lines.append(
            f"| `{row['metric']}` | {row['direction']} "
            f"| {'yes' if row['gate'] else 'no'} | {row['slack']:.0%} "
            f"| {cells} | {delta} | {row['status']} |"
        )
    lines += ["", "## Gate result", ""]
    if fails:
        lines.append("**FAIL**")
        lines += [f"- {f}" for f in fails]
    else:
        lines.append(f"pass ({len(comp)} comparable capture(s), "
                     f"{len(trend['skipped'])} skipped)")
    lines += ["", ""]
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    mode = ap.add_mutually_exclusive_group(required=True)
    mode.add_argument("--check", action="store_true",
                      help="gate the committed history; exit 1 on "
                           "regression")
    mode.add_argument("--update", action="store_true",
                      help="rewrite TREND.md from the committed history")
    mode.add_argument("--print", dest="show", action="store_true",
                      help="dump the trend table as JSON")
    ap.add_argument("--repo", default=REPO,
                    help=f"repo root holding BENCH_*.json (default {REPO})")
    ap.add_argument("--policy", default=POLICY,
                    help=f"policy path (default {POLICY})")
    args = ap.parse_args()

    try:
        with open(args.policy) as f:
            policy = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"trendgate: FAIL — unreadable policy ({e})")
        return 1
    caps = load_history(args.repo, policy.get("trust", {}))
    if os.environ.get(ENV_INJECT, "") not in ("", "0"):
        comp = comparable(caps)
        if comp:
            caps.append(inject_capture(comp[-1], policy))
    fails = check(caps, policy)

    if args.show:
        print(json.dumps(build_trend(caps, policy), indent=2))
        return 0
    if args.update:
        report = render_report(caps, policy, fails)
        with open(os.path.join(args.repo, "TREND.md"), "w") as f:
            f.write(report)
        print(f"trendgate: report written to "
              f"{os.path.join(args.repo, 'TREND.md')}")
        return 0
    if fails:
        print("trendgate: FAIL")
        for f in fails:
            print(f"  - {f}")
        return 1
    comp = comparable(caps)
    skipped = [c for c in caps if c["skip"] is not None]
    print(f"trendgate: pass ({len(comp)} comparable capture(s), "
          f"{len(skipped)} skipped with reasons; latest "
          f"{comp[-1]['file'] if comp else 'n/a'})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
