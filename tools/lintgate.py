"""Static-analysis regression gate: lint the hot programs + the source
tree against a checked-in baseline.

Two passes over ONE deterministic CPU workload, the static-analysis
sibling of tools/memgate.py:

- **Program lint** (`tfde_tpu/analysis/hlolint.py`): the train step
  under all four `grad_transport` x `opt_sharding` combos (built the
  way tests/test_comms.py builds them), plus every serving program the
  real batcher compiles while draining a fixed request mix — decode
  scan depths and cold/warm/primed prefill waves, captured through the
  armed registration seam (`TFDE_HLOLINT`). For each program: the
  collective census (counts AND payload bytes), donation survival,
  host-callback count, dtype policy, large constants.
- **Project lint** (`tools/tfdelint.py`): lock discipline for threaded
  classes, the greedy-path `jax.random.split` ban, and the TFDE_* knob
  audit against `tfde_tpu/knobs.py`.

The observation is diffed EXACTLY against tools/lintgate_baseline.json:
the workload is deterministic, so any census drift — one extra
all-reduce, one fewer aliased output, a new bf16->f32 convert — is a
program change that must be re-baselined deliberately. Unknown program
names (either direction) and any lint violation fail loudly.

Modes:

  python tools/lintgate.py --check    # compare vs baseline; exit 1 on
                                      # drift/violation (tier1.sh)
  python tools/lintgate.py --update   # rewrite the baseline (commit it)
  python tools/lintgate.py --print    # dump the observation JSON

Injection self-test: with TFDE_LINTGATE_INJECT=1 the workload also
lints two deliberately-broken programs through the real linter — one
carrying a `jax.pure_callback` (stray host callback) and one whose
declared donation cannot alias any output (dropped donation) — and
--check must fail. tools/tier1.sh runs this after the clean check,
mirroring the memgate inject drill.

Re-baseline after a deliberate program or rule change::

  JAX_PLATFORMS=cpu python tools/lintgate.py --update
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# the train matrix needs a multi-device DP mesh; must be set before the
# first jax import (same flag the test suite pins)
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
# arm the hlolint registration seam before any tfde import
os.environ.setdefault("TFDE_HLOLINT", "1")

BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "lintgate_baseline.json")
ENV_INJECT = "TFDE_LINTGATE_INJECT"

#: the transport x opt-sharding matrix, same combos tier1.sh sweeps
TRAIN_COMBOS = (
    ("fp32", "replicated"),
    ("fp32", "shard"),
    ("int8", "replicated"),
    ("int8", "shard"),
)


def _train_matrix(reports: dict) -> None:
    """Lint the train step under all four transport x sharding combos
    (the tests/test_comms.py construction: PlainCNN on a 4-way DP mesh,
    fixed batch)."""
    import jax
    import numpy as np
    import optax

    from tfde_tpu.analysis import hlolint
    from tfde_tpu.models.cnn import PlainCNN
    from tfde_tpu.parallel.strategies import MirroredStrategy
    from tfde_tpu.runtime.mesh import make_mesh
    from tfde_tpu.training.step import init_state, make_train_step

    mesh = make_mesh({"data": -1}, jax.devices()[:4])
    rng = np.random.default_rng(0)
    images = rng.random((16, 784), np.float32)
    labels = rng.integers(0, 10, (16, 1)).astype(np.int32)
    for transport, sharding in TRAIN_COMBOS:
        strategy = MirroredStrategy(mesh=mesh, grad_transport=transport,
                                    opt_sharding=sharding)
        state, _ = init_state(PlainCNN(), optax.sgd(0.1), strategy, images)
        step = make_train_step(strategy, state, donate=True)
        # plain fp32/replicated returns a bare jax.jit; the custom-step
        # combos wrap it and expose .jitted
        jitted = getattr(step, "jitted", step)
        name = f"train_step/{transport}+{sharding}"
        reports[name] = hlolint.lint(
            name, jitted, (state, (images, labels), jax.random.key(0)),
            donated=state)


def _serve_phase() -> None:
    """Drive the real batcher through every admission kind so the armed
    seam captures decode + cold/warm/primed prefill programs:

    - a cold drain over two prompt buckets (memgate's mix);
    - a prefix-cache warm re-admission (same >=1-chunk prompt twice);
    - a disaggregated prefill-role prime() handed to a decode-role
      batcher via submit_primed().
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tfde_tpu.inference.prefix_cache import PrefixCache
    from tfde_tpu.inference.server import ContinuousBatcher
    from tfde_tpu.models.gpt import GPT

    model = GPT(vocab_size=256, hidden_size=32, depth=2, num_heads=2,
                mlp_dim=64, max_position=64, dtype=jnp.float32)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    rng = np.random.default_rng(0)

    def drain(srv):
        step = 0
        while not srv.idle:
            srv.step()
            step += 1
            if step > 200:
                raise RuntimeError("serve phase failed to drain")

    # cold + decode ladder
    srv = ContinuousBatcher(model, params, batch_size=4, max_len=48,
                            scan_depth=4)
    for plen, n_new in [(3, 8), (6, 5), (4, 12), (7, 6)]:
        srv.submit(rng.integers(0, model.vocab_size, plen), n_new)
    drain(srv)

    # warm: one full-chunk prompt cached, then re-admitted with a suffix
    warm = ContinuousBatcher(model, params, batch_size=4, max_len=64,
                             scan_depth=4, prefix_cache=PrefixCache())
    prompt = rng.integers(0, model.vocab_size, 20)
    warm.submit(prompt, 4)
    drain(warm)
    warm.submit(np.concatenate([prompt, [5, 7]]), 4)
    drain(warm)

    # primed: prefill-role prime -> decode-role scatter + stream
    pre = ContinuousBatcher(model, params, batch_size=1, max_len=64,
                            role="prefill")
    dec = ContinuousBatcher(model, params, batch_size=2, max_len=64,
                            role="decode")
    primed = [pre.prime(rng.integers(0, model.vocab_size, k), 4)
              for k in (3, 5)]
    for pr in primed:
        dec.submit_primed(pr)
    drain(dec)


def _inject(reports: dict) -> None:
    """Seed two genuinely-broken programs through the real linter: the
    self-test that proves the gate bites (tier1.sh, test_recompile's
    memgate sibling in tests/test_hlolint.py)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tfde_tpu.analysis import hlolint

    # stray host callback inside a jitted program
    def poll(x):
        flag = jax.pure_callback(
            lambda v: np.asarray(float(v) > 0, np.float32),
            jax.ShapeDtypeStruct((), jnp.float32), jnp.sum(x))
        return x * flag

    cb = jax.jit(poll)
    reports["inject/callback"] = hlolint.lint(
        "inject/callback", cb, (jnp.ones((4, 4), jnp.float32),))

    # declared donation that cannot alias: the donated input's shape
    # matches no output, so lowering drops the alias
    def shrink(x):
        return jnp.sum(x, axis=0)

    dn = jax.jit(shrink, donate_argnums=(0,))
    x = jnp.ones((8, 8), jnp.float32)
    reports["inject/dropped_donation"] = hlolint.lint(
        "inject/dropped_donation", dn, (x,), donated=x)


def observe() -> dict:
    """Run both passes; returns the baseline-diffable observation."""
    from tfde_tpu.analysis import hlolint

    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "tfdelint", os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "tfdelint.py"))
    tfdelint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tfdelint)

    hlolint.arm(True)
    reports: dict = {}
    _train_matrix(reports)
    _serve_phase()
    reports.update(hlolint.collect())
    if os.environ.get(ENV_INJECT, "") not in ("", "0"):
        _inject(reports)

    project = tfdelint.lint_repo()
    return {
        "programs": {name: rep.as_dict() for name, rep in sorted(
            reports.items())},
        "project": project,
    }


#: census fields diffed exactly per program
_CENSUS_FIELDS = ("all_reduce", "reduce_scatter", "all_gather",
                  "collective_permute", "callbacks", "aliased_outputs",
                  "f64_tensors", "bf16_to_f32_converts")
_REBASE = "re-baseline deliberately with: python tools/lintgate.py --update"


def check(obs: dict, base: dict) -> list:
    """Compare an observation against the baseline; returns failure
    strings (empty = gate passes)."""
    fails = []
    for name, prog in obs["programs"].items():
        for v in prog["violations"]:
            fails.append(f"violation: {v}")
        b = base.get("programs", {}).get(name)
        if b is None:
            fails.append(f"program {name} not in baseline — new hot "
                         f"program; {_REBASE}")
            continue
        for field in _CENSUS_FIELDS:
            got = prog["census"].get(field, 0)
            want = b["census"].get(field, 0)
            if got != want:
                fails.append(
                    f"program {name}: {field} {got} != baseline {want} — "
                    f"the lowered program changed (an extra collective, a "
                    f"lost donation alias, a new upcast); if deliberate, "
                    f"{_REBASE}")
        got_b = prog["census"].get("collective_bytes", {})
        want_b = b["census"].get("collective_bytes", {})
        if got_b != want_b:
            fails.append(
                f"program {name}: collective payload bytes {got_b} != "
                f"baseline {want_b} — same op count but different tensor "
                f"sizes on the wire; if deliberate, {_REBASE}")
        if prog["census"]["large_constants"] != b["census"].get(
                "large_constants", []):
            fails.append(
                f"program {name}: large embedded constants changed "
                f"({prog['census']['large_constants']} vs baseline "
                f"{b['census'].get('large_constants', [])}); {_REBASE}")
    for name in base.get("programs", {}):
        if name not in obs["programs"]:
            fails.append(f"program {name} in baseline but not observed — "
                         f"the workload lost a hot program; {_REBASE}")
    for v in obs["project"]["violations"]:
        fails.append(f"violation: {v}")
    if obs["project"]["lock_audit"] != base.get("project", {}).get(
            "lock_audit", {}):
        fails.append(f"lock-discipline audit coverage changed "
                     f"(threaded-class table drift); {_REBASE}")
    if obs["project"]["knobs_seen"] != base.get("project", {}).get(
            "knobs_seen", []):
        fails.append(f"TFDE_* knob census changed (knob added or removed); "
                     f"{_REBASE}")
    return fails


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    mode = ap.add_mutually_exclusive_group(required=True)
    mode.add_argument("--check", action="store_true",
                      help="compare vs baseline; exit 1 on drift")
    mode.add_argument("--update", action="store_true",
                      help="run the workload and rewrite the baseline")
    mode.add_argument("--print", dest="show", action="store_true",
                      help="run and dump the observation JSON only")
    ap.add_argument("--baseline", default=BASELINE,
                    help=f"baseline path (default {BASELINE})")
    args = ap.parse_args()

    obs = observe()
    if args.show:
        print(json.dumps(obs, indent=2, sort_keys=True))
        return 0
    if args.update:
        obs["_note"] = ("generated by: JAX_PLATFORMS=cpu python "
                        "tools/lintgate.py --update — regenerate after any "
                        "deliberate change to a hot program's collectives/"
                        "donation/dtypes or to the lint rules")
        with open(args.baseline, "w") as f:
            json.dump(obs, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"lintgate: baseline written to {args.baseline}")
        return 0

    try:
        with open(args.baseline) as f:
            base = json.load(f)
    except OSError as e:
        print(f"lintgate: FAIL — no baseline ({e}); generate one with "
              f"python tools/lintgate.py --update")
        return 1
    fails = check(obs, base)
    if fails:
        print("lintgate: FAIL")
        for f in fails:
            print(f"  - {f}")
        return 1
    print(f"lintgate: pass ({len(obs['programs'])} programs clean, "
          f"{len(obs['project']['knobs_seen'])} knobs audited, "
          f"{len(obs['project']['lock_audit'])} threaded classes)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
