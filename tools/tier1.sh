#!/usr/bin/env bash
# Tier-1 verify: the exact gate from ROADMAP.md. CPU-only, excludes
# @pytest.mark.slow, survives collection errors, and prints DOTS_PASSED
# (count of '.' in pytest progress lines) so a harness can diff pass
# counts across revisions even when the exit code is nonzero.
#
# Usage: tools/tier1.sh            (from the repo root)
#        TFDE_GRAD_TRANSPORT=int8 tools/tier1.sh
#                                  (re-run the whole suite with the
#                                   quantized gradient exchange as the
#                                   default transport — parallel/comms.py;
#                                   non-DP meshes warn-fallback to fp32)
#        TFDE_OPT_SHARDING=shard tools/tier1.sh
#                                  (re-run with ZeRO weight-update
#                                   sharding as the default —
#                                   parallel/zero.py; ineligible meshes/
#                                   optimizers warn-fallback to
#                                   replicated, and parity-pinning tests
#                                   request 'replicated' explicitly)
#        TFDE_PREFIX_CACHE=on tools/tier1.sh
#                                  (re-run with the serving prefix-KV
#                                   cache enabled by default on every
#                                   ContinuousBatcher —
#                                   inference/prefix_cache.py; greedy
#                                   outputs are pinned bit-identical, so
#                                   the whole suite doubles as the
#                                   cache-on parity sweep. Also accepts
#                                   an integer byte budget.)
#        TFDE_TRACE=on tools/tier1.sh
#                                  (re-run with per-request distributed
#                                   tracing recording into every
#                                   process's ring —
#                                   observability/trace.py; greedy
#                                   outputs are unaffected by design, so
#                                   the whole suite doubles as the
#                                   tracing-on parity sweep. Also
#                                   accepts an integer ring capacity.)
#        TFDE_MEMWATCH=full tools/tier1.sh
#                                  (re-run with the memory ledger in
#                                   AOT-measured mode — every registered
#                                   program is lowered+compiled for XLA's
#                                   memory_analysis instead of the free
#                                   eval_shape estimate —
#                                   observability/memwatch.py; 'off'
#                                   disables the ledger entirely)
#        TFDE_ELASTIC=on tools/tier1.sh
#                                  (re-run with elastic topology-change
#                                   handling enabled by default in every
#                                   Supervisor — resilience/elastic.py;
#                                   the dedicated drills in
#                                   tests/test_elastic.py and
#                                   tests/test_multiprocess.py enable it
#                                   explicitly either way)
#        TFDE_ADMIT_MAX_QUEUE=8 tools/tier1.sh
#                                  (re-run the whole suite with serving
#                                   admission caps armed by default —
#                                   inference/admission.py; 0 = off.
#                                   TFDE_ADMIT_MAX_QUEUED_TOKENS and
#                                   TFDE_ADMIT_TTFT_DEADLINE_MS forward
#                                   the same way; the overload drills in
#                                   tests/test_server.py and
#                                   tests/test_multiprocess.py arm them
#                                   explicitly either way)
#        TFDE_BROWNOUT_BURN=2 tools/tier1.sh
#                                  (router brownout burn-rate thresholds
#                                   — inference/router.py; _BATCH is the
#                                   level-2 threshold that also sheds
#                                   the batch class)
#        TFDE_ADMIT_KV_HEADROOM=2 tools/tier1.sh
#                                  (re-run with the KV-headroom admission
#                                   gate armed by default — reject with
#                                   429 + a kv payload once the capacity
#                                   model says fewer than N free rows
#                                   remain; observability/capacity.py +
#                                   inference/admission.py; 0 = off. The
#                                   dedicated drills in
#                                   tests/test_server.py arm it
#                                   explicitly either way.)
#        TFDE_USAGE_LOG=on tools/tier1.sh
#                                  (re-run with per-request usage
#                                   metering journaled to
#                                   model_dir/metrics/usage_<host>.jsonl
#                                   on every router replica —
#                                   observability/capacity.py; counters
#                                   publish either way, only the JSONL
#                                   is gated. TFDE_CAPACITY_BUDGET_BYTES
#                                   forwards the same way and pins the
#                                   headroom model's memory budget.)
#        TFDE_PAGED_KV=on tools/tier1.sh
#                                  (re-run with the block-granular paged
#                                   KV pool enabled by default on every
#                                   ContinuousBatcher — inference/paged.py;
#                                   greedy outputs are pinned
#                                   bit-identical to the dense slab, so
#                                   the whole suite doubles as the
#                                   paged-on parity sweep.
#                                   TFDE_KV_BLOCK forwards the same way
#                                   and must match the prefix trie's
#                                   chunk size.)
#        TFDE_KV_QUANT=int8 tools/tier1.sh
#                                  (re-run with the int8 quantized KV
#                                   cache enabled by default on every
#                                   ContinuousBatcher — ops/quant.py +
#                                   inference/decode.py; blockwise int8
#                                   payload + fp32 scale sidecars,
#                                   dequantized inside the fused
#                                   attention tick. Greedy parity is
#                                   statistical (>=0.98), not
#                                   bit-exact, so the parity-pinning
#                                   tests request 'fp' explicitly.
#                                   TFDE_KV_DEFRAG_THRESHOLD forwards
#                                   the same way: pool fragmentation
#                                   fraction above which an admission
#                                   stall triggers a compaction pass
#                                   (default 0.5; 0 = off).)
#        TFDE_BOOT_READY_REQUIRE=off tools/tier1.sh
#                                  (re-run with the router's readiness
#                                   gate disabled — traffic places on
#                                   any live replica regardless of its
#                                   boot state, the pre-PR-17 behaviour;
#                                   observability/boot.py +
#                                   inference/router.py.
#                                   TFDE_BOOT_READY_GRACE_S forwards
#                                   the same way: seconds a never-ready
#                                   booting replica is shielded from
#                                   the staleness down-marker.)
#
# Also prints DOTS_DELTA (this run's DOTS_PASSED minus the previous
# run's, from /tmp/_t1.passed) so a regression is visible at a glance
# without diffing logs by hand.
set -o pipefail
cd "$(dirname "$0")/.." || exit 1

rm -f /tmp/_t1.log
# 30 min: the suite has grown a subsystem per PR — PR 10's memwatch
# default-on registrations pushed a loaded box past the old 1140s
# budget (a fully-green run was killed at 93%), and the boot/readiness
# drills (a third cold-booting replica child in the kill drill) pushed
# a loaded box past 1440s (killed at ~70%)
timeout -k 10 1800 env JAX_PLATFORMS=cpu \
    TFDE_GRAD_TRANSPORT="${TFDE_GRAD_TRANSPORT:-fp32}" \
    TFDE_OPT_SHARDING="${TFDE_OPT_SHARDING:-replicated}" \
    TFDE_PREFIX_CACHE="${TFDE_PREFIX_CACHE:-off}" \
    TFDE_TRACE="${TFDE_TRACE:-off}" \
    TFDE_MEMWATCH="${TFDE_MEMWATCH:-on}" \
    TFDE_ELASTIC="${TFDE_ELASTIC:-off}" \
    TFDE_ADMIT_MAX_QUEUE="${TFDE_ADMIT_MAX_QUEUE:-0}" \
    TFDE_ADMIT_MAX_QUEUED_TOKENS="${TFDE_ADMIT_MAX_QUEUED_TOKENS:-0}" \
    TFDE_ADMIT_TTFT_DEADLINE_MS="${TFDE_ADMIT_TTFT_DEADLINE_MS:-0}" \
    TFDE_BROWNOUT_BURN="${TFDE_BROWNOUT_BURN:-8}" \
    TFDE_BROWNOUT_BURN_BATCH="${TFDE_BROWNOUT_BURN_BATCH:-16}" \
    TFDE_ADMIT_KV_HEADROOM="${TFDE_ADMIT_KV_HEADROOM:-0}" \
    TFDE_USAGE_LOG="${TFDE_USAGE_LOG:-off}" \
    TFDE_CAPACITY_BUDGET_BYTES="${TFDE_CAPACITY_BUDGET_BYTES:-0}" \
    TFDE_PAGED_KV="${TFDE_PAGED_KV:-off}" \
    TFDE_KV_QUANT="${TFDE_KV_QUANT:-fp}" \
    TFDE_KV_DEFRAG_THRESHOLD="${TFDE_KV_DEFRAG_THRESHOLD:-0.5}" \
    TFDE_BOOT_READY_REQUIRE="${TFDE_BOOT_READY_REQUIRE:-on}" \
    TFDE_BOOT_READY_GRACE_S="${TFDE_BOOT_READY_GRACE_S:-120}" \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors \
    --durations=10 \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
passed=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
echo DOTS_PASSED=$passed

# Roofline tile-visit gate: pins the flash kernels' executed tile schedule
# (forward pl.when predication + backward in-band pair scan) against the
# analytic band, so an attention tile-count regression fails tier-1 the
# same way a collective-count regression does (tools/roofline.py).
if ! timeout -k 10 300 env JAX_PLATFORMS=cpu \
    python tools/roofline.py --check-tiles; then
    echo "ROOFLINE_TILE_GATE=fail"
    [ $rc -eq 0 ] && rc=1
else
    echo "ROOFLINE_TILE_GATE=pass"
fi
# Memory & compile gate: one deterministic train+serve workload, per-site
# jit-cache-miss counts and per-program peak bytes pinned against the
# checked-in baseline (tools/memgate_baseline.json). A pad-ladder compile
# regression or an HBM blow-up fails tier-1 here; re-baseline a
# deliberate change with: python tools/memgate.py --update
if ! timeout -k 10 420 env JAX_PLATFORMS=cpu \
    TFDE_MEMWATCH="${TFDE_MEMWATCH:-on}" \
    python tools/memgate.py --check; then
    echo "MEMGATE=fail"
    [ $rc -eq 0 ] && rc=1
else
    echo "MEMGATE=pass"
fi
# Static-analysis gate: hlolint census of every hot program (train-step
# transport x sharding matrix, decode scan, cold/warm/primed prefill)
# diffed exactly against tools/lintgate_baseline.json, plus the project
# lint (lock discipline, greedy-split ban, TFDE_* knob audit). An extra
# collective, a dropped donation alias, a stray host callback, an
# unlocked threaded write or an unregistered knob fails tier-1 here;
# re-baseline a deliberate change with: python tools/lintgate.py --update
if ! timeout -k 10 420 env JAX_PLATFORMS=cpu \
    python tools/lintgate.py --check; then
    echo "LINTGATE=fail"
    [ $rc -eq 0 ] && rc=1
else
    echo "LINTGATE=pass"
fi
# Injection self-test: seed a host-callback program and a dropped
# donation through the real linter — the gate must FAIL, proving it bites
# (the memgate TFDE_MEMGATE_INJECT drill's static-analysis sibling).
if timeout -k 10 420 env JAX_PLATFORMS=cpu TFDE_LINTGATE_INJECT=1 \
    python tools/lintgate.py --check >/dev/null 2>&1; then
    echo "LINTGATE_INJECT=fail (seeded violations did not fail the gate)"
    [ $rc -eq 0 ] && rc=1
else
    echo "LINTGATE_INJECT=pass"
fi
# Perf trendline gate: every committed BENCH_*.json parsed in round order
# and the latest comparable capture diffed per-metric against the
# direction/slack policy (tools/trendgate_policy.json). A hardware capture
# that regressed a gated metric past its slack fails tier-1 here;
# re-render the report after a deliberate change with:
# python tools/trendgate.py --update
if ! timeout -k 10 120 env JAX_PLATFORMS=cpu \
    python tools/trendgate.py --check; then
    echo "TRENDGATE=fail"
    [ $rc -eq 0 ] && rc=1
else
    echo "TRENDGATE=pass"
fi
# Injection self-test: synthesize a latest capture with every gated metric
# regressed past 2x slack — the gate must FAIL, proving it bites.
if timeout -k 10 120 env JAX_PLATFORMS=cpu TFDE_TRENDGATE_INJECT=1 \
    python tools/trendgate.py --check >/dev/null 2>&1; then
    echo "TRENDGATE_INJECT=fail (seeded regression did not fail the gate)"
    [ $rc -eq 0 ] && rc=1
else
    echo "TRENDGATE_INJECT=pass"
fi
if [ -f /tmp/_t1.passed ]; then
    prev=$(cat /tmp/_t1.passed)
    echo DOTS_DELTA=$((passed - prev))
fi
echo "$passed" > /tmp/_t1.passed
exit $rc
