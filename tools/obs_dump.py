#!/usr/bin/env python
"""obs_dump: pretty-print the observability artifacts of a run.

The post-mortem companion to WORKFLOWS.md's debugging runbook. Three
surfaces, composable in one invocation:

- ``python tools/obs_dump.py <model_dir>`` — summarize every flight
  recorder ring dump (``<model_dir>/debug/flight_*.jsonl``): event
  histogram, the latest sentry trip / stall / straggler / preemption
  breadcrumbs, and the tail of the ring; plus the last metrics snapshot
  from ``<model_dir>/metrics/*.jsonl`` (steps/sec, goodput, cluster and
  resilience gauges).
- ``python tools/obs_dump.py --url http://chief:9090`` — scrape a LIVE
  chief ``/metrics`` and print the per-host table (up/stale, snapshot
  age, steps/sec, push counts) plus the cluster rollups (min/median/max
  step time, straggler) the aggregator exported.
- ``python tools/obs_dump.py --router http://router:8000`` — hit a LIVE
  serving Router's ``/replicas`` and print the routing table: per
  replica up/drained, outstanding tokens (the placement signal), served
  sessions, and metric-push age (the serving-cluster runbook surface,
  WORKFLOWS.md §13), plus the SLO block (TTFT/TPOT attainment and
  burn rates, WORKFLOWS.md §14).
- ``python tools/obs_dump.py --trace <id> --router http://router:8000``
  (or with a model_dir holding ``debug/trace_*.jsonl`` dumps) — print
  one request's stitched cross-process waterfall; add ``--chrome
  out.json`` to also write Chrome trace-event JSON for Perfetto /
  chrome://tracing.
- ``python tools/obs_dump.py --mem <model_dir>`` — the memory & compile
  view (WORKFLOWS.md §15): per-program peak/arg/out bytes from the
  memwatch ledger, per-site jit-cache hit/miss counters from the
  recompile sentinel, the live-device-buffer trend across snapshots,
  and the top-K largest buffers from ``debug/memwatch.json``.
- ``python tools/obs_dump.py --capacity [--router URL | <model_dir>]``
  — the KV-capacity view (WORKFLOWS.md §20): per-replica slab
  occupancy, pad-ladder waste, and headroom from the capacity ledger,
  the block-pool split (free / active / trie blocks with the
  evictable-on-demand callout) when a replica runs paged KV
  (``TFDE_PAGED_KV``, WORKFLOWS.md §22), the KV dtype census
  (quantized-vs-fp byte split — int8 payload, fp32 scale sidecars,
  fp32-equivalent — with the headroom callout priced in the active
  dtype; ``TFDE_KV_QUANT``, WORKFLOWS.md §23), the top-waste-bucket
  callout (the cells paged-KV reclaims), and per-host
  ``metrics/usage_*.jsonl`` summaries.
- ``python tools/obs_dump.py --boot [--router URL | <model_dir>]`` —
  the cold-start view (WORKFLOWS.md §21): per-replica boot waterfall
  (phase durations process-birth → first token, restore bandwidth,
  compile share of time-to-ready) from a live router's ``/replicas``
  boot block or from dumped metrics snapshots + ``boot_phase`` /
  ``boot_ready`` flight breadcrumbs, with a slowest-phase callout
  naming the fix.
- ``--tail N`` — how many trailing flight events to print (default 10).

Reads only; stdlib only — safe to run against a production model_dir
(the sole exception: ``--trace`` imports tfde_tpu's stitcher, still
pure stdlib underneath).
"""

from __future__ import annotations

import argparse
import collections
import glob
import json
import os
import re
import sys
import urllib.request

#: flight-event kinds worth surfacing on their own line, newest occurrence
_HEADLINE_KINDS = (
    "sentry_trip", "stall", "straggler", "stale_host", "supervisor_abort",
    "supervisor_failure", "supervisor_restart", "preempted",
)

#: metric-name prefixes worth printing from the last JSONL snapshot
_SNAPSHOT_PREFIXES = ("train/", "goodput/", "cluster/", "resilience/",
                      "sentry/", "checkpoint/", "serving/", "slo/",
                      "router/", "mem/", "compile/", "opt/", "kv/",
                      "usage/")

_LABELLED = re.compile(r'^(\w+)\{host="(\d+)"\}\s+(\S+)$')


def _load_jsonl(path: str) -> list:
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                pass  # truncated tail of a crash-time dump
    return out


def _fmt_event(e: dict) -> str:
    extra = {k: v for k, v in e.items() if k not in ("ts", "kind")}
    fields = " ".join(f"{k}={v}" for k, v in sorted(extra.items()))
    return f"  {e.get('ts', 0):.3f}  {e.get('kind', '?'):<18} {fields}"


def dump_flight(path: str, tail: int) -> None:
    events = _load_jsonl(path)
    print(f"\n== flight: {path} ({len(events)} events)")
    if not events:
        return
    hist = collections.Counter(e.get("kind", "?") for e in events)
    print("  kinds: " + ", ".join(f"{k}x{n}" for k, n in sorted(hist.items())))
    for kind in _HEADLINE_KINDS:
        latest = next((e for e in reversed(events) if e.get("kind") == kind),
                      None)
        if latest is not None:
            print("  latest " + kind + ":")
            print("  " + _fmt_event(latest))
    print(f"  last {min(tail, len(events))} events:")
    for e in events[-tail:]:
        print(_fmt_event(e))


def dump_metrics_log(path: str) -> None:
    rows = _load_jsonl(path)
    print(f"\n== metrics log: {path} ({len(rows)} snapshots)")
    if not rows:
        return
    last = rows[-1]
    print(f"  last snapshot: step {last.get('step')} ts {last.get('ts', 0):.1f}")
    flat = last.get("metrics", {})
    for name in sorted(flat):
        if name.startswith(_SNAPSHOT_PREFIXES):
            print(f"    {name:<40} {flat[name]}")
    ex = last.get("exemplars", {})
    if ex:
        print("  slowest-request exemplars (value, trace id):")
        for metric in sorted(ex):
            rows_ = ", ".join(f"{r['value']:.1f}:{r['trace']}"
                              for r in ex[metric][:3])
            print(f"    {metric:<40} {rows_}")


def dump_live(url: str) -> None:
    target = url.rstrip("/")
    if not target.endswith("/metrics"):
        target += "/metrics"
    body = urllib.request.urlopen(target, timeout=5).read().decode()
    hosts: dict = collections.defaultdict(dict)
    rollups = {}
    for line in body.splitlines():
        m = _LABELLED.match(line)
        if m:
            name, host, val = m.groups()
            hosts[int(host)][name] = float(val)
            continue
        if line.startswith("tfde_cluster_") and " " in line:
            name, _, val = line.rpartition(" ")
            try:
                rollups[name] = float(val)
            except ValueError:
                pass
    print(f"== live scrape: {target}")
    if rollups:
        print("  cluster rollups:")
        for name in sorted(rollups):
            print(f"    {name:<36} {rollups[name]}")
    if hosts:
        print(f"  {'host':>4} {'up':>3} {'age_s':>8} {'steps/sec':>10} "
              f"{'pushes':>7}")
        for hid in sorted(hosts):
            h = hosts[hid]
            print(f"  {hid:>4} "
                  f"{int(h.get('tfde_cluster_host_up', 1)):>3} "
                  f"{h.get('tfde_cluster_host_age_seconds', 0.0):>8.1f} "
                  f"{h.get('tfde_train_steps_per_sec', float('nan')):>10.2f} "
                  f"{int(h.get('tfde_cluster_pushes_total', 0)):>7}")
    else:
        print("  (no host-labelled series — single process, or no "
              "aggregator on this endpoint)")


def dump_router(url: str) -> None:
    target = url.rstrip("/")
    if not target.endswith("/replicas"):
        target += "/replicas"
    body = json.loads(urllib.request.urlopen(target, timeout=5).read())
    rows = body.get("replicas", [])
    print(f"== router: {target} ({len(rows)} replicas)")
    print(f"  {'replica':>7} {'up':>3} {'drained':>7} {'outstanding':>11} "
          f"{'served':>7} {'push_age_s':>10}  url")
    for r in rows:
        age = r.get("push_age_s")
        print(f"  {r.get('replica', '?'):>7} "
              f"{int(bool(r.get('up'))):>3} "
              f"{int(bool(r.get('drained'))):>7} "
              f"{r.get('outstanding_tokens', 0):>11} "
              f"{r.get('served', 0):>7} "
              f"{(f'{age:.1f}' if age is not None else '-'):>10}  "
              f"{r.get('url', '?')}")
    mem = body.get("mem")
    if mem:
        print(f"  {'host':>7} {'live_mb':>9} {'buffers':>8} "
              f"{'peak_mb':>9} {'misses':>7} {'compile_s':>9}  "
              f"peak program")
        for hid in sorted(mem):
            m = mem[hid]

            def _mb(v):
                return f"{v / 1e6:.1f}" if v is not None else "-"

            print(f"  {hid:>7} {_mb(m.get('live_bytes')):>9} "
                  f"{int(m.get('live_buffers') or 0):>8} "
                  f"{_mb(m.get('peak_bytes')):>9} "
                  f"{int(m.get('compile_misses') or 0):>7} "
                  f"{(m.get('compile_seconds') or 0.0):>9.2f}  "
                  f"{m.get('peak_program') or '-'}")
    slo = body.get("slo")
    if slo:
        print(f"  slo: objective {slo.get('objective')} | "
              f"ttft target {slo.get('ttft_target_ms')}ms | "
              f"tpot target {slo.get('tpot_target_ms')}ms")
        for metric in ("ttft", "tpot"):
            att = slo.get(f"{metric}_attainment")
            att_s = f"{att:.4f}" if att is not None else "-"
            burns = slo.get(f"{metric}_burn_rate", {})
            burn_s = " ".join(
                f"{w}={v:.2f}" if v is not None else f"{w}=-"
                for w, v in sorted(burns.items())
            )
            print(f"    {metric}: attainment {att_s} "
                  f"({slo.get(f'{metric}_requests', 0)} reqs) "
                  f"burn[{burn_s}]")


def dump_mem(model_dir: str) -> int:
    """``--mem``: the memory & compile post-mortem view of one run —
    per-program peak/arg/out bytes and per-site compile counters from the
    last metrics snapshot, the live-device-buffer trend across snapshots
    (is it a leak or a plateau?), and the top-K largest live buffers from
    the armed ``debug/memwatch.json`` side-file."""
    logs = sorted(glob.glob(os.path.join(model_dir, "metrics", "*.jsonl")))
    rows = []
    for p in logs:
        rows.extend(_load_jsonl(p))
    side_path = os.path.join(model_dir, "debug", "memwatch.json")
    side = None
    if os.path.exists(side_path):
        try:
            with open(side_path) as f:
                side = json.load(f)
        except ValueError:
            pass
    if not rows and side is None:
        print(f"no metrics/*.jsonl snapshots or debug/memwatch.json "
              f"under {model_dir} — was the run instrumented "
              f"(TFDE_MEMWATCH) with a model_dir?")
        return 1

    flat = rows[-1].get("metrics", {}) if rows else {}
    programs: dict = collections.defaultdict(dict)
    for name, val in flat.items():
        if not name.startswith("mem/") or name.startswith("mem/live/"):
            continue
        prog, _, field = name[len("mem/"):].rpartition("/")
        programs[prog][field] = val
    if side:  # the side-file also has programs when no snapshot log exists
        for prog, pm in side.get("programs", {}).items():
            programs[prog] = {**pm, **programs[prog]}
    print(f"== mem ledger: {model_dir} ({len(programs)} programs)")
    if programs:
        print(f"  {'program':<32} {'peak_mb':>9} {'args_mb':>9} "
              f"{'out_mb':>9} {'temp_mb':>9} {'meas':>5}")
        for prog in sorted(programs,
                           key=lambda p: -programs[p].get("peak_bytes", 0)):
            pm = programs[prog]
            print(f"  {prog:<32} "
                  f"{pm.get('peak_bytes', 0) / 1e6:>9.2f} "
                  f"{pm.get('argument_bytes', 0) / 1e6:>9.2f} "
                  f"{pm.get('output_bytes', 0) / 1e6:>9.2f} "
                  f"{pm.get('temp_bytes', 0) / 1e6:>9.2f} "
                  f"{int(pm.get('measured', 0)):>5}")

    sites: dict = collections.defaultdict(dict)
    for name, val in flat.items():
        if not name.startswith("compile/") or name.count("/") < 2:
            continue
        site, _, field = name[len("compile/"):].rpartition("/")
        sites[site][field] = val
    if sites:
        print(f"\n  {'compile site':<32} {'hits':>7} {'misses':>7} "
              f"{'sigs':>5} {'seconds':>8} {'unexpected':>10}")
        for site in sorted(sites):
            s = sites[site]
            print(f"  {site:<32} {int(s.get('cache_hits', 0)):>7} "
                  f"{int(s.get('misses', 0)):>7} "
                  f"{int(s.get('signatures', 0)):>5} "
                  f"{s.get('seconds_total', 0.0):>8.2f} "
                  f"{int(s.get('unexpected', 0)):>10}")

    trend = [(r.get("step"), r["metrics"]["mem/live/bytes"],
              r["metrics"].get("mem/live/buffers", 0))
             for r in rows if "mem/live/bytes" in r.get("metrics", {})]
    if trend:
        print(f"\n  live device buffers across {len(trend)} snapshots "
              f"(leak check — bytes should plateau):")
        show = trend if len(trend) <= 8 else (
            trend[:3] + [None] + trend[-4:])
        for t in show:
            if t is None:
                print("    ...")
                continue
            step, b, n = t
            print(f"    step {str(step):>8}  {b / 1e6:>10.2f} MB  "
                  f"{int(n):>6} buffers")
        first, last = trend[0][1], trend[-1][1]
        if first > 0 and last > 1.5 * first:
            print(f"    WARNING: live bytes grew {last / first:.2f}x over "
                  f"the run — possible buffer leak (WORKFLOWS.md §15)")

    if side and side.get("live", {}).get("top"):
        live = side["live"]
        print(f"\n  top live buffers at last dump "
              f"({live.get('bytes', 0) / 1e6:.2f} MB total, "
              f"{live.get('buffers', 0)} buffers):")
        for b in live["top"]:
            shape = "x".join(str(d) for d in b.get("shape", []))
            print(f"    {b['bytes'] / 1e6:>10.3f} MB  "
                  f"[{shape or 'scalar'}] {b.get('dtype', '?')}")
    return 0


def _capacity_row(hid, kv: dict) -> str:
    def _mb(v):
        return f"{v / 1e6:.1f}" if v is not None else "-"

    def _i(v):
        return str(int(v)) if v is not None else "-"

    wf = kv.get("waste_frac")
    return (f"  {str(hid):>7} {_mb(kv.get('allocated_bytes')):>9} "
            f"{_mb(kv.get('used_bytes')):>9} "
            f"{(f'{wf:.3f}' if wf is not None else '-'):>7} "
            f"{_i(kv.get('rows_active')):>6} {_i(kv.get('rows_free')):>5} "
            f"{_i(kv.get('headroom_rows')):>9} "
            f"{_i(kv.get('headroom_tokens')):>10} "
            f"{_mb(kv.get('trie_bytes')):>8}")


_CAPACITY_HEADER = (f"  {'host':>7} {'alloc_mb':>9} {'used_mb':>9} "
                    f"{'waste':>7} {'active':>6} {'free':>5} "
                    f"{'hd_rows':>9} {'hd_tokens':>10} {'trie_mb':>8}")


def _capacity_callout(per_bucket: dict) -> None:
    """Name the worst pad-ladder cell: the bucket whose cumulative pad
    waste is largest — the dense cells a paged-KV slab would reclaim."""
    if not per_bucket:
        return
    top = max(per_bucket, key=per_bucket.get)
    total = sum(per_bucket.values())
    if total <= 0:
        return
    print(f"  top waste bucket: {top} ({per_bucket[top]:.0f} of "
          f"{total:.0f} pad-waste tokens, "
          f"{per_bucket[top] / total:.0%}) — the pad-ladder cells a "
          f"paged-KV slab reclaims (ROADMAP item 1)")


_POOL_HEADER = (f"  {'host':>7} {'blocks':>7} {'free':>6} {'active':>7} "
                f"{'trie':>6} {'pool_occ':>8} {'block_waste':>11}")


def _pool_row(hid, kv: dict) -> str:
    def _i(v):
        return str(int(v)) if v is not None else "-"

    total = kv.get("pool_blocks_total") or 0
    free = kv.get("pool_blocks_free") or 0
    act = kv.get("pool_blocks_active") or 0
    trie = kv.get("pool_blocks_trie") or 0
    occ = (act + trie) / total if total else 0.0
    wf = kv.get("waste_frac")
    return (f"  {str(hid):>7} {_i(total):>7} {_i(free):>6} {_i(act):>7} "
            f"{_i(trie):>6} {occ:>8.3f} "
            f"{(f'{wf:.3f}' if wf is not None else '-'):>11}")


def _pool_section(per_host: dict) -> None:
    """Block-pool view (paged KV, inference/paged.py): per replica the
    pool split free / active-row / trie blocks, plus a fleet callout —
    trie blocks are reclaimable on demand (the pool's evictor drains the
    trie LRU before refusing an allocation), so real pressure is
    active/total, not held/total. block_waste is the intra-block slack
    fraction (committed tokens not filling their last block) — the only
    waste mode a paged pool has left."""
    rows = {h: kv for h, kv in per_host.items()
            if kv.get("pool_blocks_total")}
    if not rows:
        return
    print("  -- block pool (paged KV) --")
    print(_POOL_HEADER)
    tot = free = act = trie = 0
    for hid in sorted(rows):
        print(_pool_row(hid, rows[hid]))
        tot += int(rows[hid].get("pool_blocks_total") or 0)
        free += int(rows[hid].get("pool_blocks_free") or 0)
        act += int(rows[hid].get("pool_blocks_active") or 0)
        trie += int(rows[hid].get("pool_blocks_trie") or 0)
    held = act + trie
    if held:
        print(f"  pool: {held}/{tot} blocks held, {free} free; "
              f"{trie} ({trie / held:.0%} of held) are trie blocks — "
              f"evictable on demand, so effective headroom is "
              f"{free + trie} blocks")


_DTYPE_HEADER = (f"  {'host':>7} {'dtype':>7} {'bits':>5} "
                 f"{'payload_mb':>11} {'scale_mb':>9} {'fp32_mb':>8} "
                 f"{'saving':>7}")


def _dtype_section(per_host: dict) -> None:
    """KV dtype census (ops/quant.py int8 path): per replica the
    quantized-vs-fp byte split — int8 payload cells vs their fp32
    scale sidecars — against what the same cells cost at fp32.
    headroom_rows in the table above is already priced in the active
    dtype (the capacity models charge payload+scale per cell), so
    `saving` is the admission-headroom multiplier TFDE_KV_QUANT=int8
    buys at a fixed byte budget."""
    rows = {h: kv for h, kv in per_host.items()
            if kv.get("kv_payload_bytes")}
    if not rows:
        return
    print("  -- kv dtype census --")
    print(_DTYPE_HEADER)
    quantized = []
    for hid in sorted(rows):
        kv = rows[hid]
        bits = int(kv.get("kv_quant_bits") or 0)
        dtype = kv.get("kv_dtype") or (
            "int8" if bits == 8 else (f"fp{bits}" if bits else "?"))
        pay = float(kv.get("kv_payload_bytes") or 0)
        sc = float(kv.get("kv_scale_bytes") or 0)
        fp = float(kv.get("kv_fp32_equiv_bytes") or 0)
        saving = fp / (pay + sc) if (pay + sc) else 0.0
        print(f"  {str(hid):>7} {dtype:>7} {bits:>5} {pay / 1e6:>11.1f} "
              f"{sc / 1e6:>9.1f} {fp / 1e6:>8.1f} "
              f"{f'{saving:.2f}x':>7}")
        if bits and bits < 32 and saving > 1.0:
            quantized.append((hid, dtype, saving, kv.get("headroom_rows")))
    for hid, dtype, saving, hd in quantized:
        if hd is None:
            continue
        print(f"  {hid}: headroom is priced at {dtype} cells + fp32 "
              f"scales ({int(hd)} rows); the same byte budget at fp32 "
              f"holds ~{int(int(hd) / saving)} rows "
              f"({saving:.2f}x from TFDE_KV_QUANT)")


def dump_capacity(model_dir=None, router_url=None) -> int:
    """``--capacity``: the KV occupancy / pad-waste / headroom view —
    per replica from a LIVE router's /replicas kv table, or from the
    last metrics snapshot(s) under a model_dir (WORKFLOWS.md §20)."""
    if router_url:
        target = router_url.rstrip("/")
        if not target.endswith("/replicas"):
            target += "/replicas"
        body = json.loads(urllib.request.urlopen(target, timeout=5).read())
        kv = body.get("kv") or {}
        print(f"== capacity: {target} ({len(kv)} replicas reporting)")
        if not kv:
            print("  (no kv/* metrics pushed yet — are the replicas "
                  "constructed with push_url and past their first step?)")
            return 1
        print(_CAPACITY_HEADER)
        for hid in sorted(kv):
            print(_capacity_row(hid, kv[hid]))
        _pool_section(kv)
        _dtype_section(kv)
        per_bucket = {
            str(h["top_waste_bucket"]): h.get("top_waste_bucket_tokens", 0)
            for h in kv.values() if h.get("top_waste_bucket") is not None
        }
        _capacity_callout(per_bucket)
        return 0

    logs = sorted(glob.glob(os.path.join(model_dir, "metrics", "*.jsonl")))
    logs = [p for p in logs
            if not os.path.basename(p).startswith("usage_")]
    shown = 0
    print(f"== capacity: {model_dir}")
    print(_CAPACITY_HEADER)
    per_bucket: dict = collections.Counter()
    pool_hosts: dict = {}
    census_hosts: dict = {}
    for p in logs:
        rows = _load_jsonl(p)
        if not rows:
            continue
        flat = rows[-1].get("metrics", {})
        if "kv/allocated_bytes" not in flat:
            continue
        shown += 1
        host = os.path.basename(p).rsplit(".", 1)[0]
        if host.startswith("metrics-"):
            host = host[len("metrics-"):]
        print(_capacity_row(host, {
            "allocated_bytes": flat.get("kv/allocated_bytes"),
            "used_bytes": flat.get("kv/used_bytes"),
            "waste_frac": flat.get("kv/waste_frac"),
            "rows_active": flat.get("kv/rows_active"),
            "rows_free": flat.get("kv/rows_free"),
            "headroom_rows": flat.get("kv/headroom_rows"),
            "headroom_tokens": flat.get("kv/headroom_tokens"),
            "trie_bytes": flat.get("kv/trie_bytes"),
        }))
        if flat.get("kv/pool_blocks_total"):
            pool_hosts[host] = {
                "pool_blocks_total": flat.get("kv/pool_blocks_total"),
                "pool_blocks_free": flat.get("kv/pool_blocks_free"),
                "pool_blocks_active": flat.get("kv/pool_blocks_active"),
                "pool_blocks_trie": flat.get("kv/pool_blocks_trie"),
                "waste_frac": flat.get("kv/waste_frac"),
            }
        if flat.get("kv/payload_bytes"):
            census_hosts[host] = {
                "kv_quant_bits": flat.get("kv/quant_bits"),
                "kv_payload_bytes": flat.get("kv/payload_bytes"),
                "kv_scale_bytes": flat.get("kv/scale_bytes"),
                "kv_fp32_equiv_bytes": flat.get("kv/fp32_equiv_bytes"),
                "headroom_rows": flat.get("kv/headroom_rows"),
            }
        pre = "kv/pad_waste_tokens/bucket_"
        for name, v in flat.items():
            if name.startswith(pre):
                per_bucket[name[len(pre):]] += v
    if not shown:
        print(f"  (no kv/* metrics in any snapshot under "
              f"{model_dir}/metrics — serving run without the ledger?)")
    else:
        _pool_section(pool_hosts)
        _dtype_section(census_hosts)
        _capacity_callout(dict(per_bucket))

    usage = sorted(glob.glob(
        os.path.join(model_dir, "metrics", "usage_*.jsonl")))
    for p in usage:
        recs = _load_jsonl(p)
        prompt = sum(r.get("prompt_tokens", 0) for r in recs)
        gen = sum(r.get("generated_tokens", 0) for r in recs)
        res = sum(r.get("kv_token_seconds", 0.0) for r in recs)
        print(f"  usage {os.path.basename(p)}: {len(recs)} requests, "
              f"{prompt} prompt + {gen} generated tokens, "
              f"{res:.1f} KV token-seconds")
    return 0 if (shown or usage) else 1


#: boot phases in ledger order (mirrors observability/boot.py PHASES —
#: kept literal so this tool stays import-free for --boot)
_BOOT_PHASES = ("init", "bootstrap", "restore", "compile", "warmup")

#: fat-phase → fix, the WORKFLOWS.md §21 runbook in one line each
_BOOT_FIXES = {
    "init": "trim process init: lazy imports, defer device/backend setup",
    "bootstrap": "check coordinator reachability and barrier stragglers",
    "restore": "streamed / sharded restore — raise restore bandwidth",
    "compile": "AOT-warm the pad ladder or persist the jit cache",
    "warmup": "cap trie pre-warm work or pre-warm from a snapshot",
}


def _boot_row(hid, b: dict) -> str:
    phases = b.get("phases") or {}

    def _s(name):
        v = phases.get(name)
        return f"{v:.2f}" if v is not None else "-"

    ttr = b.get("time_to_ready_s")
    ttft = b.get("ttft_from_birth_ms")
    bw = (b.get("restore") or {}).get("bandwidth_bps")
    comp = (b.get("compile") or {}).get("boot_seconds")
    share = (f"{comp / ttr:.0%}" if comp is not None and ttr else "-")
    return (f"  {str(hid):>7} {str(b.get('state') or '-'):>9} "
            + " ".join(f"{_s(p):>7}" for p in _BOOT_PHASES)
            + f" {(f'{ttr:.2f}' if ttr is not None else '-'):>8}"
            + f" {(f'{ttft:.0f}' if ttft is not None else '-'):>8}"
            + f" {(f'{bw / 1e6:.1f}' if bw else '-'):>8}"
            + f" {share:>7}")


_BOOT_HEADER = (f"  {'replica':>7} {'state':>9} "
                + " ".join(f"{p[:7]:>7}" for p in _BOOT_PHASES)
                + f" {'ready_s':>8} {'ttft_ms':>8} {'rst_mbs':>8} "
                f"{'cmp%':>7}")


def _boot_callout(tables: dict) -> None:
    """Name the fattest boot phase across replicas — where the next
    second of time-to-ready comes from — and its runbook fix."""
    totals: dict = collections.Counter()
    for b in tables.values():
        for p, v in (b.get("phases") or {}).items():
            if p in _BOOT_FIXES and v:
                totals[p] += v
    if not totals:
        return
    top = max(totals, key=totals.get)
    whole = sum(totals.values())
    print(f"  slowest phase: {top} ({totals[top]:.2f}s of {whole:.2f}s "
          f"summed boot, {totals[top] / whole:.0%}) — "
          f"{_BOOT_FIXES[top]} (WORKFLOWS.md §21)")


def dump_boot(model_dir=None, router_url=None, tail: int = 10) -> int:
    """``--boot``: the per-replica cold-start waterfall — phase seconds
    from process birth to first token, restore bandwidth, and compile's
    share of time-to-ready — live from a Router's /replicas boot block,
    or offline from metrics snapshots + boot_* flight breadcrumbs."""
    if router_url:
        target = router_url.rstrip("/")
        if not target.endswith("/replicas"):
            target += "/replicas"
        body = json.loads(urllib.request.urlopen(target, timeout=5).read())
        boot = body.get("boot") or {}
        print(f"== boot: {target} ({len(boot)} replicas reporting)")
        if not boot:
            print("  (no boot ledgers yet — replicas on a pre-ledger "
                  "build, or none snapshotted/pushed so far)")
            return 1
        print(_BOOT_HEADER)
        for hid in sorted(boot):
            print(_boot_row(hid, boot[hid]))
        _boot_callout(boot)
        return 0

    # offline: last per-host boot/* gauges out of the metrics snapshots,
    # then the boot breadcrumbs out of the flight dumps
    logs = sorted(glob.glob(os.path.join(model_dir, "metrics", "*.jsonl")))
    logs = [p for p in logs
            if not os.path.basename(p).startswith("usage_")]
    tables: dict = {}
    for p in logs:
        rows = _load_jsonl(p)
        if not rows:
            continue
        flat = rows[-1].get("metrics", {})
        if not any(k.startswith("boot/") for k in flat):
            continue
        host = os.path.basename(p).rsplit(".", 1)[0]
        if host.startswith("metrics-"):
            host = host[len("metrics-"):]
        phases = {
            name: flat[g] for name, g in (
                ("init", "boot/init_seconds"),
                ("bootstrap", "boot/bootstrap_seconds"),
                ("restore", "boot/restore_seconds"),
                ("compile", "boot/compile_wall_seconds"),
                ("warmup", "boot/warmup_seconds"),
            ) if g in flat
        }
        tables[host] = {
            "state": None,   # gauges carry numbers, not the FSM
            "phases": phases,
            "time_to_ready_s": flat.get("boot/time_to_ready_seconds"),
            "ttft_from_birth_ms": flat.get("boot/ttft_from_birth_ms"),
            "restore": {"bandwidth_bps":
                        flat.get("boot/restore_bandwidth_bps")},
            "compile": {"boot_count": flat.get("boot/compile_count"),
                        "boot_seconds": flat.get("boot/compile_seconds")},
        }
    print(f"== boot: {model_dir} ({len(tables)} hosts with boot/* "
          f"gauges)")
    if tables:
        print(_BOOT_HEADER)
        for hid in sorted(tables):
            print(_boot_row(hid, tables[hid]))
        _boot_callout(tables)

    shown_crumbs = 0
    for p in sorted(glob.glob(
            os.path.join(model_dir, "debug", "flight_*.jsonl"))):
        events = [e for e in _load_jsonl(p)
                  if e.get("kind") in ("boot_phase", "boot_ready",
                                       "boot_epoch")]
        if not events:
            continue
        shown_crumbs += len(events)
        print(f"\n  boot breadcrumbs: {p} "
              f"(last {min(tail, len(events))} of {len(events)})")
        for e in events[-tail:]:
            print(_fmt_event(e))
    if not tables and not shown_crumbs:
        print(f"  (no boot/* gauges or boot_* flight events under "
              f"{model_dir} — pre-ledger run, or replicas never pushed)")
        return 1
    return 0


def _fmt_trace_event(e: dict, t0: float) -> str:
    extra = {k: v for k, v in e.items()
             if k not in ("ts", "dur", "name", "proc", "pid", "trace",
                          "traces")}
    fields = " ".join(f"{k}={v}" for k, v in sorted(extra.items()))
    dur = f"{e['dur'] * 1e3:8.2f}ms" if "dur" in e else " " * 10
    return (f"  +{(e.get('ts', t0) - t0) * 1e3:9.2f}ms {dur} "
            f"{str(e.get('proc', '?')):<10} {e.get('name', '?'):<22} "
            f"{fields}")


def dump_trace(trace_id: str, router_url=None, model_dir=None,
               chrome_out=None) -> int:
    """Print one request's stitched waterfall — from a live router's
    /trace/<id> endpoint, or from dumped debug/trace_*.jsonl files —
    and optionally write Chrome trace-event JSON."""
    # lazy: only --trace pays the package import (and the path shim for
    # running as `python tools/obs_dump.py`); every other mode stays
    # import-free
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from tfde_tpu.observability import trace as reqtrace

    if router_url:
        target = router_url.rstrip("/") + f"/trace/{trace_id}"
        body = json.loads(urllib.request.urlopen(target, timeout=5).read())
        events = body.get("events", [])
        src = target
    else:
        paths = sorted(glob.glob(
            os.path.join(model_dir, "debug", "trace_*.jsonl")))
        if not paths:
            print(f"no debug/trace_*.jsonl dumps under {model_dir}")
            return 1
        per_proc = [reqtrace.load(p) for p in paths]
        events = reqtrace.stitch([
            [e for e in evs
             if e.get("trace") == trace_id
             or trace_id in e.get("traces", ())]
            for evs in per_proc
        ])
        src = f"{len(paths)} dump file(s) under {model_dir}/debug"
    print(f"== trace {trace_id} ({src}): {len(events)} events, "
          f"procs {sorted({str(e.get('proc')) for e in events})}")
    if not events:
        return 1
    t0 = min(e.get("ts", 0.0) for e in events)
    for e in events:
        print(_fmt_trace_event(e, t0))
    if chrome_out:
        with open(chrome_out, "w") as f:
            json.dump(reqtrace.to_chrome(events), f)
        print(f"  chrome trace-event JSON -> {chrome_out} "
              f"(load in Perfetto / chrome://tracing)")
    return 0


def dump_profiles(model_dir: str) -> int:
    """The capture index: every debug/profiles/*.json record — what
    triggered it, the step/round window it covered, and the request trace
    ids that were in flight (feed those back to --trace)."""
    # same lazy package import + path shim as --trace
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from tfde_tpu.observability import profiler

    recs = profiler.list_artifacts(model_dir)
    if not recs:
        print(f"no profile captures under {model_dir}/debug/profiles "
              f"(nothing triggered, or retention pruned them)")
        return 1
    print(f"== profile captures ({len(recs)}) under {model_dir}")
    for r in recs:
        window = f"[{r.get('start')}, {r.get('stop')}]"
        traces = r.get("traces") or []
        shown = ",".join(traces[:4]) + ("…" if len(traces) > 4 else "")
        print(f"  {r.get('_file')}: reason={r.get('reason')} "
              f"kind={r.get('kind')} {window} host={r.get('host')}"
              + (f" traces={shown}" if traces else ""))
        if r.get("logdir"):
            print(f"    xprof -> {r['logdir']}/plugins/profile/ "
                  f"(TensorBoard profile plugin)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("model_dir", nargs="?",
                    help="run directory holding debug/ and metrics/")
    ap.add_argument("--url", help="live chief to scrape, e.g. "
                                  "http://chief:9090")
    ap.add_argument("--router", help="live serving Router to query, e.g. "
                                     "http://router:8000")
    ap.add_argument("--tail", type=int, default=10,
                    help="trailing flight events to print (default 10)")
    ap.add_argument("--trace", metavar="ID",
                    help="print one request's stitched waterfall (needs "
                         "--router for live stitching, or a model_dir "
                         "with debug/trace_*.jsonl dumps)")
    ap.add_argument("--chrome", metavar="PATH",
                    help="with --trace: also write Chrome trace-event "
                         "JSON (Perfetto-loadable) to PATH")
    ap.add_argument("--mem", action="store_true",
                    help="memory & compile view of a model_dir: per-"
                         "program peak bytes, per-site compile counters, "
                         "live-buffer trend, top-K largest buffers")
    ap.add_argument("--profiles", action="store_true",
                    help="list the triggered-capture index under "
                         "<model_dir>/debug/profiles: trigger reason, "
                         "step/round window, in-flight trace ids")
    ap.add_argument("--capacity", action="store_true",
                    help="KV occupancy/waste/headroom table per replica "
                         "(live via --router, or from a model_dir's last "
                         "metrics snapshots) + top-waste-bucket callout "
                         "and usage-log summaries")
    ap.add_argument("--boot", action="store_true",
                    help="per-replica boot waterfall (phase seconds "
                         "birth → first token, restore bandwidth, "
                         "compile share) live via --router or from a "
                         "model_dir's snapshots + flight breadcrumbs, "
                         "with a slowest-phase callout")
    args = ap.parse_args(argv)
    if not args.model_dir and not args.url and not args.router:
        ap.error("give a model_dir, --url, --router, or a combination")
    if args.trace and not (args.router or args.model_dir):
        ap.error("--trace needs --router (live) or a model_dir (dumps)")
    if args.mem and not args.model_dir:
        ap.error("--mem needs a model_dir")
    if args.profiles and not args.model_dir:
        ap.error("--profiles needs a model_dir")
    if args.capacity and not (args.router or args.model_dir):
        ap.error("--capacity needs --router (live) or a model_dir "
                 "(snapshots)")
    if args.boot and not (args.router or args.model_dir):
        ap.error("--boot needs --router (live) or a model_dir "
                 "(snapshots/flight dumps)")

    if args.boot:
        return dump_boot(model_dir=args.model_dir,
                         router_url=args.router, tail=args.tail)
    if args.capacity:
        return dump_capacity(model_dir=args.model_dir,
                             router_url=args.router)
    if args.profiles:
        return dump_profiles(args.model_dir)
    if args.mem:
        return dump_mem(args.model_dir)
    if args.trace:
        return dump_trace(args.trace, router_url=args.router,
                          model_dir=args.model_dir,
                          chrome_out=args.chrome)
    if args.url:
        dump_live(args.url)
    if args.router:
        dump_router(args.router)
    if args.model_dir:
        flights = sorted(glob.glob(
            os.path.join(args.model_dir, "debug", "flight_*.jsonl")))
        logs = sorted(glob.glob(
            os.path.join(args.model_dir, "metrics", "*.jsonl")))
        if not flights and not logs:
            print(f"no flight or metrics files under {args.model_dir} "
                  f"(expected debug/flight_*.jsonl, metrics/*.jsonl)")
            return 1
        for p in flights:
            dump_flight(p, args.tail)
        for p in logs:
            dump_metrics_log(p)
    return 0


if __name__ == "__main__":
    sys.exit(main())
