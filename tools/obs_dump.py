#!/usr/bin/env python
"""obs_dump: pretty-print the observability artifacts of a run.

The post-mortem companion to WORKFLOWS.md's debugging runbook. Three
surfaces, composable in one invocation:

- ``python tools/obs_dump.py <model_dir>`` — summarize every flight
  recorder ring dump (``<model_dir>/debug/flight_*.jsonl``): event
  histogram, the latest sentry trip / stall / straggler / preemption
  breadcrumbs, and the tail of the ring; plus the last metrics snapshot
  from ``<model_dir>/metrics/*.jsonl`` (steps/sec, goodput, cluster and
  resilience gauges).
- ``python tools/obs_dump.py --url http://chief:9090`` — scrape a LIVE
  chief ``/metrics`` and print the per-host table (up/stale, snapshot
  age, steps/sec, push counts) plus the cluster rollups (min/median/max
  step time, straggler) the aggregator exported.
- ``python tools/obs_dump.py --router http://router:8000`` — hit a LIVE
  serving Router's ``/replicas`` and print the routing table: per
  replica up/drained, outstanding tokens (the placement signal), served
  sessions, and metric-push age (the serving-cluster runbook surface,
  WORKFLOWS.md §13).
- ``--tail N`` — how many trailing flight events to print (default 10).

Reads only; stdlib only — safe to run against a production model_dir.
"""

from __future__ import annotations

import argparse
import collections
import glob
import json
import os
import re
import sys
import urllib.request

#: flight-event kinds worth surfacing on their own line, newest occurrence
_HEADLINE_KINDS = (
    "sentry_trip", "stall", "straggler", "stale_host", "supervisor_abort",
    "supervisor_failure", "supervisor_restart", "preempted",
)

#: metric-name prefixes worth printing from the last JSONL snapshot
_SNAPSHOT_PREFIXES = ("train/", "goodput/", "cluster/", "resilience/",
                      "sentry/", "checkpoint/")

_LABELLED = re.compile(r'^(\w+)\{host="(\d+)"\}\s+(\S+)$')


def _load_jsonl(path: str) -> list:
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                pass  # truncated tail of a crash-time dump
    return out


def _fmt_event(e: dict) -> str:
    extra = {k: v for k, v in e.items() if k not in ("ts", "kind")}
    fields = " ".join(f"{k}={v}" for k, v in sorted(extra.items()))
    return f"  {e.get('ts', 0):.3f}  {e.get('kind', '?'):<18} {fields}"


def dump_flight(path: str, tail: int) -> None:
    events = _load_jsonl(path)
    print(f"\n== flight: {path} ({len(events)} events)")
    if not events:
        return
    hist = collections.Counter(e.get("kind", "?") for e in events)
    print("  kinds: " + ", ".join(f"{k}x{n}" for k, n in sorted(hist.items())))
    for kind in _HEADLINE_KINDS:
        latest = next((e for e in reversed(events) if e.get("kind") == kind),
                      None)
        if latest is not None:
            print("  latest " + kind + ":")
            print("  " + _fmt_event(latest))
    print(f"  last {min(tail, len(events))} events:")
    for e in events[-tail:]:
        print(_fmt_event(e))


def dump_metrics_log(path: str) -> None:
    rows = _load_jsonl(path)
    print(f"\n== metrics log: {path} ({len(rows)} snapshots)")
    if not rows:
        return
    last = rows[-1]
    print(f"  last snapshot: step {last.get('step')} ts {last.get('ts', 0):.1f}")
    flat = last.get("metrics", {})
    for name in sorted(flat):
        if name.startswith(_SNAPSHOT_PREFIXES):
            print(f"    {name:<40} {flat[name]}")


def dump_live(url: str) -> None:
    target = url.rstrip("/")
    if not target.endswith("/metrics"):
        target += "/metrics"
    body = urllib.request.urlopen(target, timeout=5).read().decode()
    hosts: dict = collections.defaultdict(dict)
    rollups = {}
    for line in body.splitlines():
        m = _LABELLED.match(line)
        if m:
            name, host, val = m.groups()
            hosts[int(host)][name] = float(val)
            continue
        if line.startswith("tfde_cluster_") and " " in line:
            name, _, val = line.rpartition(" ")
            try:
                rollups[name] = float(val)
            except ValueError:
                pass
    print(f"== live scrape: {target}")
    if rollups:
        print("  cluster rollups:")
        for name in sorted(rollups):
            print(f"    {name:<36} {rollups[name]}")
    if hosts:
        print(f"  {'host':>4} {'up':>3} {'age_s':>8} {'steps/sec':>10} "
              f"{'pushes':>7}")
        for hid in sorted(hosts):
            h = hosts[hid]
            print(f"  {hid:>4} "
                  f"{int(h.get('tfde_cluster_host_up', 1)):>3} "
                  f"{h.get('tfde_cluster_host_age_seconds', 0.0):>8.1f} "
                  f"{h.get('tfde_train_steps_per_sec', float('nan')):>10.2f} "
                  f"{int(h.get('tfde_cluster_pushes_total', 0)):>7}")
    else:
        print("  (no host-labelled series — single process, or no "
              "aggregator on this endpoint)")


def dump_router(url: str) -> None:
    target = url.rstrip("/")
    if not target.endswith("/replicas"):
        target += "/replicas"
    body = json.loads(urllib.request.urlopen(target, timeout=5).read())
    rows = body.get("replicas", [])
    print(f"== router: {target} ({len(rows)} replicas)")
    print(f"  {'replica':>7} {'up':>3} {'drained':>7} {'outstanding':>11} "
          f"{'served':>7} {'push_age_s':>10}  url")
    for r in rows:
        age = r.get("push_age_s")
        print(f"  {r.get('replica', '?'):>7} "
              f"{int(bool(r.get('up'))):>3} "
              f"{int(bool(r.get('drained'))):>7} "
              f"{r.get('outstanding_tokens', 0):>11} "
              f"{r.get('served', 0):>7} "
              f"{(f'{age:.1f}' if age is not None else '-'):>10}  "
              f"{r.get('url', '?')}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("model_dir", nargs="?",
                    help="run directory holding debug/ and metrics/")
    ap.add_argument("--url", help="live chief to scrape, e.g. "
                                  "http://chief:9090")
    ap.add_argument("--router", help="live serving Router to query, e.g. "
                                     "http://router:8000")
    ap.add_argument("--tail", type=int, default=10,
                    help="trailing flight events to print (default 10)")
    args = ap.parse_args(argv)
    if not args.model_dir and not args.url and not args.router:
        ap.error("give a model_dir, --url, --router, or a combination")

    if args.url:
        dump_live(args.url)
    if args.router:
        dump_router(args.router)
    if args.model_dir:
        flights = sorted(glob.glob(
            os.path.join(args.model_dir, "debug", "flight_*.jsonl")))
        logs = sorted(glob.glob(
            os.path.join(args.model_dir, "metrics", "*.jsonl")))
        if not flights and not logs:
            print(f"no flight or metrics files under {args.model_dir} "
                  f"(expected debug/flight_*.jsonl, metrics/*.jsonl)")
            return 1
        for p in flights:
            dump_flight(p, args.tail)
        for p in logs:
            dump_metrics_log(p)
    return 0


if __name__ == "__main__":
    sys.exit(main())
