"""Filesystem abstraction: local paths and remote URLs behind one API.

The reference documents `--working-dir` as a GCS location
(/root/reference/mnist_keras_distributed.py:41-44) and relies on TF's GFile
machinery so that event files, checkpoints, and exports all land there. The
TPU-native stack gets checkpoints for free (Orbax/tensorstore speak gs://),
but the hand-rolled side-effect IO — SummaryWriter
(observability/tensorboard.py) and the serving exporter (export/serving.py)
— was local-only in round 1 (VERDICT "What's missing" #1). This module closes
that: plain paths use the standard library; anything with a URL scheme
(gs://, s3://, memory://, ...) routes through fsspec, which is baked into
the image (gcsfs included).

`memory://` is the hermetic test double — fsspec's in-memory filesystem lets
the whole Estimator side-effect surface run against a "remote" working dir
in CI (tests/test_fs.py).

Append semantics: object stores have none (a GCS object is immutable), so
callers that need append-like behavior (the event writer) buffer and rewrite
the whole object via `write_bytes` — event files are scalar-only and tiny,
and the rewrite gives real flush durability, which a streamed gcsfs upload
(visible only at close) would not.
"""

from __future__ import annotations

import os
import posixpath
import re
from typing import IO, List

_SCHEME_RE = re.compile(r"^[a-zA-Z][a-zA-Z0-9+.-]*://")

# Remote operations retry under the operator's TFDE_RETRY_* policy
# (resilience/policy.py): object-store blips are transient by nature, local
# filesystem errors are not — so only the remote branches go through
# _remote_call. Imported lazily to keep this module import-light (the
# observability event writer imports fs; fs must not import it back at
# module scope).
_RETRY = None


def _remote_call(fn, *args, what: str = "", **kwargs):
    global _RETRY
    from tfde_tpu.resilience.policy import policy_from_env, retry_call

    if _RETRY is None:
        _RETRY = policy_from_env()
    return retry_call(
        fn, *args, policy=_RETRY, what=what,
        counter="resilience/fs_retries", **kwargs,
    )


def is_remote(path: str) -> bool:
    """True for scheme-prefixed URLs (gs://...), False for local paths."""
    return bool(_SCHEME_RE.match(path)) and not path.startswith("file://")


def _fs(path: str):
    import fsspec

    fs, _ = fsspec.core.url_to_fs(path)
    return fs


def _strip(path: str) -> str:
    """fsspec filesystems want scheme-less paths for most operations."""
    import fsspec

    _, p = fsspec.core.url_to_fs(path)
    return p


def join(path: str, *parts: str) -> str:
    """URL-aware path join (posix rules for remote, os rules locally)."""
    if is_remote(path):
        return posixpath.join(path, *parts)
    return os.path.join(path, *parts)


def makedirs(path: str, exist_ok: bool = True) -> None:
    if is_remote(path):
        _remote_call(_fs(path).makedirs, _strip(path), exist_ok=exist_ok,
                     what=f"makedirs({path})")
        return
    os.makedirs(path, exist_ok=exist_ok)


def fs_open(path: str, mode: str = "rb") -> IO:
    if is_remote(path):
        return _remote_call(_fs(path).open, _strip(path), mode,
                            what=f"open({path})")
    return open(path, mode)


def write_bytes(path: str, data: bytes) -> None:
    """Atomically-ish replace the object/file at `path` with `data`."""
    if is_remote(path):
        _remote_call(_fs(path).pipe_file, _strip(path), data,
                     what=f"write_bytes({path})")
        return
    with open(path, "wb") as f:
        f.write(data)


def exists(path: str) -> bool:
    if is_remote(path):
        return _remote_call(_fs(path).exists, _strip(path),
                            what=f"exists({path})")
    return os.path.exists(path)


def isdir(path: str) -> bool:
    if is_remote(path):
        return _remote_call(_fs(path).isdir, _strip(path),
                            what=f"isdir({path})")
    return os.path.isdir(path)


def listdir(path: str) -> List[str]:
    """Base names of entries in `path` (not full paths), like os.listdir."""
    if is_remote(path):
        fs = _fs(path)
        out = []
        for entry in _remote_call(fs.ls, _strip(path), detail=False,
                                  what=f"listdir({path})"):
            name = entry.rstrip("/").rsplit("/", 1)[-1]
            if name:
                out.append(name)
        return out
    return os.listdir(path)
