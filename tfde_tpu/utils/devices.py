"""Virtual CPU device setup, portable across JAX versions.

Newer JAX exposes `jax_num_cpu_devices` as a config option; older releases
only honor the `--xla_force_host_platform_device_count` XLA flag, and pass
unknown *config* names straight to AttributeError. Call
`request_cpu_devices(n)` before the first computation (before the CPU
backend is instantiated) and it picks whichever mechanism this JAX has.
"""

from __future__ import annotations

import os

_FLAG = "--xla_force_host_platform_device_count"


def request_cpu_devices(n: int) -> None:
    """Ask for `n` virtual CPU devices on the host platform.

    Must run before the JAX backend initializes (i.e. before the first
    device/computation touch; importing jax is fine). No-op if the backend
    is already up — JAX itself raises in that case for the config path,
    and the env var is simply never re-read.
    """
    import jax

    try:
        jax.config.update("jax_num_cpu_devices", int(n))
        return
    except AttributeError:
        pass  # older jax: config option absent -> use the XLA flag
    # The caller asked for exactly n: override any inherited flag value (a
    # parent test process's XLA_FLAGS leaks into subprocesses).
    cur = os.environ.get("XLA_FLAGS", "")
    kept = [t for t in cur.split() if not t.startswith(f"{_FLAG}=")]
    os.environ["XLA_FLAGS"] = " ".join(kept + [f"{_FLAG}={int(n)}"])
