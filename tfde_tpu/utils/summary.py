"""Model architecture/parameter summary — `model.summary()` parity.

Both reference Estimator scripts print a Keras layer/param summary before
training (`/root/reference/mnist_keras_distributed.py:117`,
`tf2_mnist_distributed.py:143`); this is the framework-native equivalent
for any model the step factories accept (flax modules and duck-typed
models like PipelinedLM alike — anything with `init(rng, sample)`).

Counting happens on abstract shapes (`jax.eval_shape`), so summarizing a
70B-param config costs nothing: no parameter materializes, no device
memory is touched.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np


def _count(tree) -> tuple:
    """(param count, bytes) over a pytree of ShapeDtypeStructs/arrays."""
    n = b = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        size = int(np.prod(leaf.shape, dtype=np.int64)) if leaf.shape else 1
        n += size
        b += size * np.dtype(leaf.dtype).itemsize
    return n, b


def _fmt_count(n: int) -> str:
    return f"{n:,}"


def _fmt_bytes(b: int) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if b < 1024 or unit == "TB":
            return f"{b:.1f} {unit}" if unit != "B" else f"{b} B"
        b /= 1024
    return f"{b:.1f} TB"


def model_summary(
    model: Any,
    sample_input: Any,
    depth: int = 2,
    variables: Optional[dict] = None,
) -> str:
    """Parameter summary table for `model`, grouped to `depth` path levels.

    model: anything with `init(rng, sample) -> variables` (flax module or
    duck-typed). sample_input: one batch-shaped input (only shapes/dtypes
    are read). variables: pass an existing tree to skip abstract init.
    Returns the table as a string — print it, the reference's
    `model.summary()` behavior.
    """
    if variables is None:
        variables = jax.eval_shape(
            lambda s: model.init(jax.random.key(0), s), sample_input
        )
    params = variables.get("params", variables)
    groups: dict = {}
    for path, leaf in jax.tree_util.tree_leaves_with_path(params):
        keys = [str(getattr(p, "key", getattr(p, "idx", p))) for p in path]
        group = "/".join(keys[:depth]) or "(root)"
        n, b = _count([leaf])
        cn, cb = groups.get(group, (0, 0))
        groups[group] = (cn + n, cb + b)

    name = type(model).__name__
    rows = [(g, *groups[g]) for g in groups]
    w = max([len(r[0]) for r in rows] + [len("module")]) + 2
    cw = max([len(_fmt_count(r[1])) for r in rows] + [len("params")]) + 2
    lines = [
        f'Model: "{name}"',
        "=" * (w + cw + 10),
        f"{'module':<{w}}{'params':>{cw}}  {'bytes':>8}",
        "-" * (w + cw + 10),
    ]
    for g, n, b in rows:
        lines.append(f"{g:<{w}}{_fmt_count(n):>{cw}}  {_fmt_bytes(b):>8}")
    total_n, total_b = _count(params)
    lines.append("=" * (w + cw + 10))
    lines.append(
        f"Total params: {_fmt_count(total_n)} ({_fmt_bytes(total_b)})"
    )
    extras = [k for k in variables if k not in ("params",)] \
        if isinstance(variables, dict) else []
    for col in extras:
        n, b = _count(variables[col])
        if n:
            lines.append(
                f"{col}: {_fmt_count(n)} ({_fmt_bytes(b)}) — non-trainable"
            )
    return "\n".join(lines)
