"""Shared utilities: filesystem abstraction for remote working dirs; model
parameter summaries (`model.summary()` parity)."""

from tfde_tpu.utils.fs import (  # noqa: F401
    exists,
    fs_open,
    is_remote,
    isdir,
    join,
    listdir,
    makedirs,
    write_bytes,
)
from tfde_tpu.utils.summary import model_summary  # noqa: F401
