"""Shared utilities: filesystem abstraction for remote working dirs."""

from tfde_tpu.utils.fs import (  # noqa: F401
    exists,
    fs_open,
    is_remote,
    isdir,
    join,
    listdir,
    makedirs,
    write_bytes,
)
