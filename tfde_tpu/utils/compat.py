"""JAX version compatibility shims.

The codebase targets current JAX spellings; older releases in the support
window spell a few of them differently. Each shim takes the NEW surface and
translates down when needed, so call sites stay modern.

- `shard_map`: new JAX exposes `jax.shard_map(..., check_vma=, axis_names=)`;
  older releases have `jax.experimental.shard_map.shard_map(..., check_rep=,
  auto=)`. `axis_names` (the axes the body is manual over) is the complement
  of old `auto` (the axes left automatic).
- `pcast` / `vma_of`: new JAX types device-variance into avals
  (`jax.typeof(x).vma`) and converts with `jax.lax.pcast`; old JAX has no
  vma typing and `check_rep`'s rewrite rules insert `pbroadcast`s
  automatically, so the shims degrade to frozenset() / identity.
"""

from __future__ import annotations

from typing import Optional

import jax


def shard_map(
    f,
    mesh,
    in_specs,
    out_specs,
    check_vma: Optional[bool] = None,
    axis_names=None,
):
    if hasattr(jax, "shard_map"):
        kwargs = {}
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
        if axis_names is not None:
            kwargs["axis_names"] = set(axis_names)
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )

    from jax.experimental.shard_map import shard_map as _shard_map

    # Old check_rep's rewrite rules predate vma typing and reject valid
    # programs (cond branches, scan carries) that the explicit pcast calls
    # handle on new jax — and those calls shim to the identity here, so
    # replication checking defaults OFF on the old path.
    kwargs = {"check_rep": bool(check_vma)}
    if axis_names is not None:
        kwargs["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )


def pcast(x, axis_names, to: str = "varying"):
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axis_names, to=to)
    # Old jax has no vma typing: check_rep's rewrite rules insert
    # pbroadcasts automatically, and an explicit one on an already-varying
    # value is an error — the correct translation is the identity.
    return x


def get_abstract_mesh():
    """New `jax.sharding.get_abstract_mesh`, old internal equivalent."""
    if hasattr(jax.sharding, "get_abstract_mesh"):
        return jax.sharding.get_abstract_mesh()
    from jax._src import mesh as _mesh

    return _mesh.get_abstract_mesh()


def supports_partial_manual() -> bool:
    """Whether shard_map's partial-manual ("auto") mode works on this jax.
    Pre-vma releases lower axis_index inside a partial-manual region to a
    PartitionId op the SPMD partitioner rejects; the capability tracks the
    jax.shard_map surface."""
    return hasattr(jax, "shard_map")


def abstract_mesh(axis_sizes, axis_names):
    """`jax.sharding.AbstractMesh` across the signature change: new jax
    takes `(sizes, names)`, old jax a single `((name, size), ...)` tuple."""
    try:
        return jax.sharding.AbstractMesh(tuple(axis_sizes), tuple(axis_names))
    except TypeError:
        return jax.sharding.AbstractMesh(
            tuple(zip(axis_names, (int(s) for s in axis_sizes)))
        )


def vma_of(x) -> frozenset:
    """The device-variance axes of `x` (frozenset() on jax without vma
    typing, where variance is not part of the aval)."""
    if hasattr(jax, "typeof"):
        return frozenset(getattr(jax.typeof(x), "vma", frozenset()))
    return frozenset()
