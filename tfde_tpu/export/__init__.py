"""Serving export — the SavedModel/FinalExporter capability (SURVEY.md §3.4)."""

from tfde_tpu.export.generative import (  # noqa: F401
    export_generate,
    load_generate,
)
from tfde_tpu.export.serving import (  # noqa: F401
    BestExporter,
    FinalExporter,
    export_serving,
    load_serving,
)
