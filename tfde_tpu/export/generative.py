"""Generative serving export: freeze a causal LM's ENTIRE decode loop.

Where export/serving.py freezes a single forward pass (the reference's
SavedModel signature, mnist_keras:151-162), a token model's servable unit is
the generation program — prefill, KV-cache decode scan, and sampling
(inference/decode.generate). Because that whole loop is one jitted XLA
program, it exports exactly like a forward pass: one StableHLO artifact,
loadable and callable with no model code, deterministic given (prompt, seed).

Artifact layout mirrors the classifier export:

    <dir>/<timestamp>/
      signature.json    prompt/output spec + the burned-in sampling config
      params.npz        final params, host-gathered
      model.stablehlo   jax.export serialization of generate(), cpu+tpu

The sampling configuration (temperature/top_k/top_p/min_p/eos) is part of the
compiled program — a deployment picks it at export time, the way it picks
the signature shape. The `seed` argument stays runtime: one artifact serves
any number of sampled continuations.
"""

from __future__ import annotations

import logging
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import export as jax_export

from tfde_tpu.export.serving import _load_artifact, _write_artifact
from tfde_tpu.inference.decode import generate, validate_budget

log = logging.getLogger(__name__)


def export_generate(
    model,
    params,
    directory: str,
    prompt_len: int,
    max_new_tokens: int,
    batch_size: int = 1,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    min_p: Optional[float] = None,
    eos_id: Optional[int] = None,
    pad_id: int = 0,
    platforms: Tuple[str, ...] = ("cpu", "tpu"),
) -> str:
    """Write a generative serving artifact; returns the timestamped dir.

    The exported entry point is `(prompt [B, P] int32, seed [] int32) ->
    (tokens [B, P + N] int32, lengths [B] int32)` with B/P/N fixed at
    export (XLA static shapes; export one artifact per serving bucket)."""
    validate_budget(model, prompt_len, max_new_tokens)
    host_params = jax.tree_util.tree_map(
        lambda x: np.asarray(jax.device_get(x)), params
    )

    def serve(prompt, seed):
        return generate(
            model, host_params, prompt, max_new_tokens,
            rng=jax.random.key(seed), temperature=temperature, top_k=top_k,
            top_p=top_p, min_p=min_p, eos_id=eos_id, pad_id=pad_id,
        )

    prompt_arg = jax.ShapeDtypeStruct((batch_size, prompt_len), jnp.int32)
    seed_arg = jax.ShapeDtypeStruct((), jnp.int32)
    exported = jax_export.export(jax.jit(serve), platforms=platforms)(
        prompt_arg, seed_arg
    )
    out_dir = _write_artifact(
        directory, exported, host_params,
        {
            "kind": "generate",
            "inputs": {
                "prompt": {"shape": [batch_size, prompt_len],
                           "dtype": "int32"},
                "seed": {"shape": [], "dtype": "int32"},
            },
            # the entry point returns a (tokens, lengths) TUPLE — schema
            # consumers must expect both arrays
            "outputs": {
                "tokens": {"shape": [batch_size,
                                     prompt_len + max_new_tokens],
                           "dtype": "int32"},
                "lengths": {"shape": [batch_size], "dtype": "int32"},
            },
            "max_new_tokens": max_new_tokens,
            "sampling": {
                "temperature": temperature,
                "top_k": top_k,
                "top_p": top_p,
                "min_p": min_p,
                "eos_id": eos_id,
                "pad_id": pad_id,
            },
            "platforms": list(platforms),
            "framework": "tfde_tpu",
        },
    )
    log.info("generative artifact exported -> %s", out_dir)
    return out_dir


class GenerativeModel:
    """Loaded artifact; `generate(prompt, seed)` -> (tokens, lengths)."""

    def __init__(self, exported, signature: dict, params: dict):
        self._exported = exported
        self.signature = signature
        self.params = params

    def generate(self, prompt: np.ndarray, seed: int = 0):
        toks, lengths = self._exported.call(
            np.asarray(prompt, np.int32), np.int32(seed)
        )
        return np.asarray(toks), np.asarray(lengths)


def load_generate(export_dir: str) -> GenerativeModel:
    """Load a generative artifact (timestamped dir, or the parent resolving
    the newest). Local paths and remote URLs both work (utils/fs)."""
    exported, signature, params = _load_artifact(export_dir)
    if signature.get("kind") != "generate":
        raise ValueError(
            f"{export_dir} is not a generative artifact "
            f"(kind={signature.get('kind')!r}); use export.serving."
            f"load_serving for forward-pass artifacts"
        )
    return GenerativeModel(exported, signature, params)
