"""TensorFlow SavedModel export — ecosystem interop for TF-Serving shops.

The reference's `FinalExporter` writes a SavedModel
(`/root/reference/mnist_keras_distributed.py:151-162,264`) that TF Serving
loads directly. The framework's native artifact (export/serving.py:
StableHLO + params.npz + signature.json) is capability-equivalent and
self-contained, but a TF-Serving deployment cannot consume it — this
module closes that gap with an OPT-IN exporter that wraps the same jitted
serve function via `jax.experimental.jax2tf` and writes a genuine
SavedModel with a `serving_default` signature and a symbolic batch dim.

Opt-in and lazily imported: TensorFlow is an interop dependency only (the
compute path never touches it); without TF installed this module raises a
clear error and everything else works. `FinalExporter(...,
savedmodel=True)` (export/serving.py) writes both artifacts side by side.
"""

from __future__ import annotations

import datetime
import logging
from typing import Callable, Optional, Sequence

import jax
import numpy as np

from tfde_tpu.utils import fs

log = logging.getLogger(__name__)


def export_savedmodel(
    apply_fn: Callable,
    variables: dict,
    input_shape: Sequence[Optional[int]],
    directory: str,
    input_dtype=np.float32,
    apply_softmax: bool = True,
) -> str:
    """Write `<directory>/<timestamp>/` as a TF SavedModel; returns the
    timestamped dir. Same contract as export_serving: `apply_fn(variables,
    x) -> logits`, `input_shape` with None for the batch dim.
    """
    try:
        import tensorflow as tf
        from jax.experimental import jax2tf
    except ImportError as e:
        raise RuntimeError(
            "export_savedmodel needs tensorflow (an interop-only "
            "dependency): pip install tensorflow, or use the native "
            "artifact (export_serving) which has no TF dependency"
        ) from e

    host_vars = jax.tree_util.tree_map(
        lambda x: np.asarray(jax.device_get(x)), variables
    )

    def serve(x):
        logits = apply_fn(host_vars, x)
        return jax.nn.softmax(logits, axis=-1) if apply_softmax else logits

    # symbolic batch dim ("b") so one artifact serves any batch size —
    # the [None, 784] placeholder contract (mnist_keras:159)
    poly = ",".join("b" if d is None else str(d) for d in input_shape)
    tf_fn = tf.function(
        jax2tf.convert(
            serve, with_gradient=False, polymorphic_shapes=[f"({poly})"]
        ),
        input_signature=[
            tf.TensorSpec(list(input_shape), tf.as_dtype(np.dtype(input_dtype)))
        ],
        autograph=False,
    )
    module = tf.Module()
    module.serve = tf_fn  # keep the concrete function referenced
    stamp = datetime.datetime.now().strftime("%Y%m%d%H%M%S")
    out_dir = fs.join(directory, stamp)
    if fs.is_remote(out_dir):
        # tf.saved_model.save writes through TF's own filesystem layer,
        # which handles gs:// natively; memory:// etc. do not exist there
        raise ValueError(
            f"SavedModel export supports local and gs:// paths (TF's "
            f"filesystem), got {out_dir}; use export_serving for "
            f"arbitrary fsspec URLs"
        )
    fs.makedirs(directory)
    tf.saved_model.save(
        module, out_dir, signatures={"serving_default": tf_fn}
    )
    log.info("SavedModel exported -> %s", out_dir)
    return out_dir
