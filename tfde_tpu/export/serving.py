"""Serving export: freeze trained params behind a serving signature.

The reference's export path (SURVEY.md §3.4): at end of training,
`FinalExporter('exporter', serving_input_fn)` rebuilds an inference graph on a
`[None, 784]` float placeholder and writes a SavedModel under
`<working_dir>/export/exporter/<timestamp>/` (mnist_keras:151-162,264).

TPU-native artifact (one directory per export):

    <dir>/<timestamp>/
      signature.json   input/output spec + framework version
      params.npz       final params (+ batch_stats), host-gathered
      model.stablehlo  jax.export serialization of the jitted apply fn,
                       symbolic batch dim, lowered for cpu+tpu

The StableHLO file is the SavedModel analog — a self-contained compiled
artifact loadable with no model code. `params.npz` + `signature.json` make the
artifact inspectable and let a loader with model code rebuild natively.

The serving function applies softmax, preserving the reference's observable
signature ([N,784] float32 -> [N,10] probabilities) even though our models
return logits (see models/cnn.py docstring).
"""

from __future__ import annotations

import datetime
import io
import json
import logging
import os
from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import export as jax_export

from tfde_tpu.utils import fs

log = logging.getLogger(__name__)

_FLAT_SEP = "/"


def _flatten_params(tree) -> dict:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = _FLAT_SEP.join(
            str(getattr(p, "key", getattr(p, "idx", p))) for p in path
        )
        flat[key] = np.asarray(jax.device_get(leaf))
    return flat


def _unflatten_params(flat: dict) -> dict:
    tree: dict = {}
    for key, value in flat.items():
        node = tree
        parts = key.split(_FLAT_SEP)
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value
    return tree


def write_params_npz(path: str, tree) -> None:
    """One definition of the params.npz convention (flat '/'-joined keys,
    buffer-then-write for remote fs) — serving/generative artifacts and the
    conversion CLI all write through here."""
    buf = io.BytesIO()
    np.savez(buf, **_flatten_params(tree))
    with fs.fs_open(path, "wb") as f:
        f.write(buf.getvalue())


def _write_artifact(directory: str, exported, host_vars, signature: dict) -> str:
    """Shared artifact writer: timestamped dir + model.stablehlo +
    params.npz + signature.json (export_serving and export_generate)."""
    stamp = datetime.datetime.now().strftime("%Y%m%d%H%M%S")
    out_dir = fs.join(directory, stamp)
    # two exports in one wall-clock second (per-eval BestExporter cadence)
    # must not overwrite each other in place — bump to the next free
    # stamp; numeric ordering keeps "newest resolves last" intact
    bump = 0
    while fs.exists(out_dir):
        bump += 1
        out_dir = fs.join(directory, str(int(stamp) + bump))
    fs.makedirs(out_dir, exist_ok=True)
    with fs.fs_open(fs.join(out_dir, "model.stablehlo"), "wb") as f:
        f.write(exported.serialize())
    write_params_npz(fs.join(out_dir, "params.npz"), host_vars)
    with fs.fs_open(fs.join(out_dir, "signature.json"), "w") as f:
        json.dump(signature, f, indent=2)
    return out_dir


def _load_artifact(export_dir: str):
    """Shared loader: resolve the newest timestamped subdir, read
    (exported, signature, params)."""
    entries = sorted(
        d for d in fs.listdir(export_dir)
        if fs.isdir(fs.join(export_dir, d)) and d.isdigit()
    )
    if entries and not fs.exists(fs.join(export_dir, "signature.json")):
        export_dir = fs.join(export_dir, entries[-1])
    with fs.fs_open(fs.join(export_dir, "signature.json"), "r") as f:
        signature = json.load(f)
    with fs.fs_open(fs.join(export_dir, "model.stablehlo"), "rb") as f:
        exported = jax_export.deserialize(f.read())
    with fs.fs_open(fs.join(export_dir, "params.npz"), "rb") as f:
        z = np.load(io.BytesIO(f.read()))
    params = _unflatten_params({k: z[k] for k in z.files})
    return exported, signature, params


def export_serving(
    apply_fn: Callable,
    variables: dict,
    input_shape: Sequence[Optional[int]],
    directory: str,
    input_dtype=jnp.float32,
    apply_softmax: bool = True,
    platforms: Tuple[str, ...] = ("cpu", "tpu"),
) -> str:
    """Write a serving artifact; returns the timestamped export dir.

    `apply_fn(variables, x)` -> logits; `input_shape` uses None for the
    symbolic batch dim, e.g. (None, 784) — the reference's serving
    placeholder shape (mnist_keras:159).
    """
    host_vars = jax.tree_util.tree_map(lambda x: np.asarray(jax.device_get(x)), variables)

    def serve(x):
        logits = apply_fn(host_vars, x)
        return jax.nn.softmax(logits, axis=-1) if apply_softmax else logits

    # symbolic batch dim so any batch size serves from one artifact
    dims = []
    sym = jax_export.symbolic_shape("b")[0]
    for d in input_shape:
        dims.append(sym if d is None else d)
    arg = jax.ShapeDtypeStruct(tuple(dims), input_dtype)

    exported = jax_export.export(jax.jit(serve), platforms=platforms)(arg)
    out_shape = jax.eval_shape(serve, arg)
    out_dir = _write_artifact(
        directory, exported, host_vars,
        {
            "input": {"shape": list(input_shape), "dtype": str(np.dtype(input_dtype))},
            "output": {
                "shape": [int(d) if isinstance(d, int) else None for d in out_shape.shape],
                "dtype": str(out_shape.dtype),
            },
            "apply_softmax": apply_softmax,
            "platforms": list(platforms),
            "framework": "tfde_tpu",
        },
    )
    log.info("serving artifact exported -> %s", out_dir)
    return out_dir


class ServingModel:
    """Loaded artifact; `predict(x)` mirrors the SavedModel signature."""

    def __init__(self, exported, signature: dict, params: dict):
        self._exported = exported
        self.signature = signature
        self.params = params

    def predict(self, x: np.ndarray) -> np.ndarray:
        return np.asarray(self._exported.call(np.asarray(x)))


def load_serving(export_dir: str) -> ServingModel:
    """Load a serving artifact from its timestamped directory (or the parent,
    resolving the newest timestamp — FinalExporter keeps history). Works on
    local paths and remote URLs (gs://, memory://)."""
    exported, signature, params = _load_artifact(export_dir)
    if signature.get("kind") == "generate":
        raise ValueError(
            f"{export_dir} is a generative artifact (2-argument "
            f"(prompt, seed) entry point); use export.generative."
            f"load_generate"
        )
    return ServingModel(exported, signature, params)


class FinalExporter:
    """End-of-training exporter (mnist_keras:264 analog): writes under
    `<model_dir>/export/<name>/<timestamp>/`."""

    def __init__(
        self,
        name: str,
        input_shape: Sequence[Optional[int]],
        input_dtype=jnp.float32,
        apply_softmax: bool = True,
        savedmodel: bool = False,
    ):
        """savedmodel=True additionally writes a genuine TF SavedModel
        next to the native artifact (under `<...>/<name>_savedmodel/`) for
        TF-Serving deployments — opt-in, needs tensorflow installed
        (export/savedmodel.py)."""
        self.name = name
        self.input_shape = tuple(input_shape)
        self.input_dtype = input_dtype
        self.apply_softmax = apply_softmax
        self.savedmodel = savedmodel

    def export(self, model_dir: str, apply_fn: Callable, variables: dict) -> str:
        out = export_serving(
            apply_fn,
            variables,
            self.input_shape,
            fs.join(model_dir, "export", self.name),
            input_dtype=self.input_dtype,
            apply_softmax=self.apply_softmax,
        )
        if self.savedmodel:
            from tfde_tpu.export.savedmodel import export_savedmodel

            export_savedmodel(
                apply_fn,
                variables,
                self.input_shape,
                fs.join(model_dir, "export", f"{self.name}_savedmodel"),
                input_dtype=np.dtype(jnp.dtype(self.input_dtype).name),
                apply_softmax=self.apply_softmax,
            )
        return out


class BestExporter(FinalExporter):
    """Metric-gated exporter — the `tf.estimator.BestExporter` analog:
    exports only when the monitored eval metric improves on the best seen
    so far. The bar persists in `<export dir>/best_metric.json`, so a
    resumed run keeps comparing against its own history. Runs after every
    throttled eval in `train_and_evaluate` (inline mode), after every
    evaluated checkpoint in `continuous_eval` / eval_mode='from_checkpoint',
    and once more at the final eval; the timestamped layout matches
    FinalExporter, newest == best."""

    def __init__(
        self,
        name: str,
        input_shape,
        metric: str = "loss",
        higher_is_better: bool = False,
        **kw,
    ):
        super().__init__(name, input_shape, **kw)
        self.metric = metric
        self.higher_is_better = higher_is_better

    def maybe_export(self, model_dir: str, apply_fn: Callable,
                     variables: dict, metrics: dict):
        """Export iff metrics[self.metric] beats the persisted best;
        returns the artifact dir or None."""
        if self.metric not in metrics:
            raise ValueError(
                f"BestExporter({self.name!r}) monitors {self.metric!r} but "
                f"the eval produced {sorted(metrics)} — set metric= to one "
                f"of those"
            )
        val = float(metrics[self.metric])
        if not np.isfinite(val):
            # a NaN written as the bar would compare False against every
            # future value, silently disabling the exporter for the run's
            # lifetime — a diverged eval is never "best"
            return None
        bar_path = fs.join(model_dir, "export", self.name,
                           "best_metric.json")
        best = None
        if fs.exists(bar_path):
            with fs.fs_open(bar_path, "r") as f:
                best = json.load(f)["value"]
        improved = best is None or not np.isfinite(best) or (
            val > best if self.higher_is_better else val < best
        )
        if not improved:
            return None
        out = self.export(model_dir, apply_fn, variables)
        with fs.fs_open(bar_path, "w") as f:
            json.dump({"metric": self.metric, "value": val,
                       "artifact": out}, f)
        return out
