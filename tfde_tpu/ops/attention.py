"""Attention ops — the hot kernel of the transformer scale-up configs.

The reference has no attention anywhere (its models are MNIST CNNs, SURVEY.md
§5 "long-context: entirely absent"); this exists for the driver's scale
configs (BASELINE.json: ViT-B/16 FSDP, BERT-base MLM) and the long-context
story (ring attention over a 'seq' mesh axis).

Three implementations behind one dispatcher:

- ``reference``: einsum + fp32 softmax. The numerics oracle; also what XLA
  fuses perfectly well at short sequence lengths.
- ``flash``: Pallas TPU kernel (ops/flash_attention.py) — blockwise online
  softmax, O(S) memory, MXU-shaped tiles. Hardware-qualified on TPU v5e
  (bench.py flash config, 2026-07: numerics match the reference within bf16
  tolerance; fwd+bwd speedup 1.02x at S=2048, 1.39x at S=4096, 6.65x at
  S=8192) — auto-dispatch uses it on TPU from S>=4096, where XLA's fused
  attention falls off. ``TFDE_FLASH=0`` disables; ``TFDE_FLASH=1`` lowers
  the threshold to S>=1024.
- ``ring``: sequence-parallel blockwise attention over the mesh's 'seq' axis
  (ops/ring_attention.py) — KV blocks rotate around the ring via ppermute
  while compute overlaps, so sequence length scales with the number of chips.

Shapes follow the Flax convention: q/k/v are [batch, length, heads, head_dim].
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from tfde_tpu.parallel import axes as axes_lib


def reference_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array] = None,
    causal: bool = False,
) -> jax.Array:
    """Plain softmax(QK^T/sqrt(d))V with fp32 accumulation.

    mask: broadcastable to [B, H, Sq, Sk]; True/1 = attend. Additive -inf
    masking in fp32 keeps bf16 inputs numerically safe.
    """
    *_, sq, _, d = q.shape
    sk = k.shape[1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    # [B,Sq,H,D] x [B,Sk,H,D] -> [B,H,Sq,Sk]; accumulate in fp32.
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        cm = jnp.tril(jnp.ones((sq, sk), jnp.bool_), k=sk - sq)
        mask = cm if mask is None else jnp.logical_and(mask, cm)
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    weights = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", weights.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)


def _seq_parallel_active() -> bool:
    mesh = axes_lib.current_mesh()
    return mesh is not None and "seq" in mesh.axis_names and mesh.shape["seq"] > 1


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _have(module: str) -> bool:
    import importlib.util

    return importlib.util.find_spec(f"tfde_tpu.ops.{module}") is not None


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array] = None,
    causal: bool = False,
    impl: str = "auto",
) -> jax.Array:
    """Dispatching attention: [B,S,H,D] -> [B,S,H,D].

    impl: 'auto' | 'reference' | 'flash' | 'ring'. 'auto' picks ring when the
    active mesh shards 'seq'; on TPU it picks flash for self-attention at
    S >= 4096 (no mask) — the regime where the hardware qualification showed
    the O(S^2) reference einsum falling off (1.4x at 4096, 6.7x at 8192;
    bench.py flash config on v5e) — and the reference einsum otherwise (XLA
    fuses it optimally at short S). ``TFDE_FLASH=0`` disables the flash
    auto-pick; ``TFDE_FLASH=1`` lowers its threshold to S >= 1024.
    """
    if impl == "auto":
        import os

        flash_env = os.environ.get("TFDE_FLASH", "auto")
        flash_min_seq = {"0": None, "false": None, "False": None,
                         "": 4096, "auto": 4096}.get(flash_env, 1024)
        if _seq_parallel_active() and _have("ring_attention"):
            impl = "ring"
        elif (
            _on_tpu()
            and flash_min_seq is not None
            and q.shape[1] >= flash_min_seq
            and q.shape == k.shape
            and q.shape[1] % 128 == 0
            and mask is None
            and _have("flash_attention")
        ):
            impl = "flash"
        else:
            impl = "reference"
    if impl == "reference":
        return reference_attention(q, k, v, mask=mask, causal=causal)
    if impl == "flash":
        if mask is not None:
            raise NotImplementedError(
                "flash attention does not take an explicit mask; use "
                "impl='reference' (or 'auto', which refuses flash when a "
                "mask is present)"
            )
        from tfde_tpu.ops import flash_attention

        return flash_attention.flash_attention(q, k, v, causal=causal)
    if impl == "ring":
        from tfde_tpu.ops import ring_attention

        return ring_attention.ring_attention(
            q, k, v, mask=mask, causal=causal, mesh=axes_lib.current_mesh()
        )
    raise ValueError(f"unknown attention impl {impl!r}")


def padding_mask(valid: jax.Array) -> jax.Array:
    """[B, S] 1/True-for-real-token -> [B, 1, 1, S] attention mask."""
    return valid.astype(jnp.bool_)[:, None, None, :]
