"""Attention ops — the hot kernel of the transformer scale-up configs.

The reference has no attention anywhere (its models are MNIST CNNs, SURVEY.md
§5 "long-context: entirely absent"); this exists for the driver's scale
configs (BASELINE.json: ViT-B/16 FSDP, BERT-base MLM) and the long-context
story (ring attention over a 'seq' mesh axis).

Three implementations behind one dispatcher:

- ``reference``: einsum + fp32 softmax. The numerics oracle; also what XLA
  fuses perfectly well at short sequence lengths.
- ``flash``: Pallas TPU forward + blockwise backward (ops/flash_attention.py)
  — online softmax, O(S) memory, MXU-shaped tiles. Hardware-qualified on
  TPU v5e (r04 A/B, tools/flash_ab.py: causal fwd+bwd 1.15x/1.28x/1.30x
  over the reference einsum at S=2048/4096/8192) — auto-dispatch uses it
  on TPU from S>=2048 causal / S>=4096 non-causal (where its O(S) memory,
  not speed, is the win). ``TFDE_FLASH=0`` disables; ``TFDE_FLASH=1``
  lowers both thresholds to S>=1024. Takes GQA shapes (k/v with fewer
  heads) directly — the kernel folds each q head onto its serving KV head
  (r04 hardware A/B vs the grouped einsum, h=16 kv=4 S=2048/4096: 1.14x/
  0.99x causal, 1.13x with window=1024, grads <1% Frobenius error).
- ``ring``: sequence-parallel blockwise attention over the mesh's 'seq' axis
  (ops/ring_attention.py) — KV blocks rotate around the ring via ppermute
  while compute overlaps, so sequence length scales with the number of chips.
  Takes GQA shapes too: the rotating KV shards stay kv_heads-sized, so the
  per-hop ICI transfer shrinks by the group factor.

Shapes follow the Flax convention: q/k/v are [batch, length, heads, head_dim].
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from tfde_tpu.utils.compat import shard_map as _compat_shard_map

from tfde_tpu.parallel import axes as axes_lib


def reference_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array] = None,
    causal: bool = False,
    window: Optional[int] = None,
    bias: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    logit_cap: Optional[float] = None,
) -> jax.Array:
    """Plain softmax(QK^T/sqrt(d))V with fp32 accumulation.

    mask: broadcastable to [B, H, Sq, Sk]; True/1 = attend. Additive -inf
    masking in fp32 keeps bf16 inputs numerically safe. window: sliding-
    window (Mistral-style) band — position i attends [i-window+1, i];
    requires causal=True. bias: additive pre-softmax score bias (see
    grouped_attention). scale/logit_cap: see grouped_attention.

    The numerics oracle every other kernel is tested against. Internally
    the degenerate (groups == 1) case of `grouped_attention` — ONE
    scale/mask/fp32-softmax implementation, so the oracle and the GQA
    decode path cannot drift.
    """
    return grouped_attention(q, k, v, mask=mask, causal=causal,
                             window=window, bias=bias, scale=scale,
                             logit_cap=logit_cap)


def grouped_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array] = None,
    causal: bool = False,
    window: Optional[int] = None,
    bias: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    logit_cap: Optional[float] = None,
) -> jax.Array:
    """Grouped-query attention: q [B,Sq,H,D] against k/v [B,Sk,Kv,D] with
    H = Kv * groups — each KV head serves a contiguous group of query heads.

    The einsums index the KV head directly (`bqkgd,bskd->bkgqs`), so the
    [B,Sk,H,D] expansion a repeat-then-attend formulation would write/read
    through HBM never exists — the point of GQA is exactly that bandwidth
    saving, largest on the decode path where K/V is the whole cache.

    mask: broadcastable to [B, H, Sq, Sk] (or with a size-1 head dim);
    True = attend, matching reference_attention.

    bias: additive pre-softmax score bias broadcastable to [B, H, Sq, Sk]
    (T5's relative position bias, models/t5.py) — added in fp32 AFTER the
    score scaling and BEFORE masking, matching the transformers ordering.

    scale: score multiplier; None = the standard 1/sqrt(d). T5 runs
    UNSCALED attention (the scale is folded into its init) — its module
    passes scale=1.0; Gemma-2 passes query_pre_attn_scalar^-0.5 — one
    einsum path for every convention.

    logit_cap: attention logit softcapping (Gemma-2):
    cap * tanh(score / cap) applied after scaling and bias, before the
    mask — bounds score magnitudes without the hard clip's dead
    gradient.
    """
    b, sq, h, d = q.shape
    kv = k.shape[2]
    if h % kv:
        raise ValueError(f"query heads {h} must be a multiple of kv heads {kv}")
    if window is not None and (not causal or window < 1):
        raise ValueError(
            f"window={window} requires causal=True and window >= 1 — the "
            f"sliding window is a band below the causal diagonal"
        )
    if logit_cap is not None and logit_cap <= 0:
        raise ValueError(
            f"logit_cap={logit_cap} must be > 0 (cap * tanh(score / cap) "
            f"divides by the cap) — same check as the flash kernel"
        )
    g = h // kv
    sk = k.shape[1]
    if scale is None:
        scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    qg = q.reshape(b, sq, kv, g, d)
    logits = jnp.einsum(
        "bqkgd,bskd->bkgqs", qg, k, preferred_element_type=jnp.float32
    ) * scale
    if bias is not None:
        if bias.ndim == 3:  # [H, Sq, Sk]
            bias = bias[None]
        if bias.ndim != 4:
            raise ValueError(
                f"bias must be broadcastable to [B,H,Sq,Sk] (ndim 3/4), "
                f"got ndim={bias.ndim}"
            )
        if bias.shape[1] == h:
            bias = bias.reshape(bias.shape[0], kv, g, *bias.shape[2:])
        else:  # size-1 head dim broadcasts over [kv, g]
            bias = bias[:, :, None]
        logits = logits + bias.astype(jnp.float32)
    if logit_cap is not None:
        logits = logit_cap * jnp.tanh(logits / logit_cap)
    if causal:
        cm = jnp.tril(jnp.ones((sq, sk), jnp.bool_), k=sk - sq)
        if window is not None:
            # rows are the LAST sq absolute positions (offset sk - sq, the
            # same alignment the causal tril uses): row i sees cols in
            # (i - window, i]
            rows = (sk - sq) + jnp.arange(sq)[:, None]
            cols = jnp.arange(sk)[None, :]
            cm = jnp.logical_and(cm, rows - cols < window)
        mask = cm if mask is None else jnp.logical_and(mask, cm)
    if mask is not None:
        if mask.ndim == 2:  # [Sq, Sk]
            mask = mask[None, None, None]
        elif mask.ndim == 3:  # [B|1, Sq, Sk]
            mask = mask[:, None, None]
        elif mask.ndim == 4:  # [B|1, H|1, Sq, Sk]
            if mask.shape[1] == h:
                mask = mask.reshape(mask.shape[0], kv, g, *mask.shape[2:])
            else:
                mask = mask[:, :, None]  # size-1 head dim broadcasts
        else:
            raise ValueError(
                f"mask must be broadcastable to [B,H,Sq,Sk] "
                f"(ndim 2/3/4), got ndim={mask.ndim}"
            )
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    weights = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum(
        "bkgqs,bskd->bqkgd", weights.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(b, sq, h, d).astype(q.dtype)


def _seq_parallel_active() -> bool:
    if axes_lib.manual_seq_info() is not None:
        return True  # pp x sp: seq is a manual axis, no mesh to consult
    mesh = axes_lib.current_mesh()
    return mesh is not None and "seq" in mesh.axis_names and mesh.shape["seq"] > 1


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _have(module: str) -> bool:
    import importlib.util

    return importlib.util.find_spec(f"tfde_tpu.ops.{module}") is not None


# impls whose kernels take scale/logit_cap natively. All three current
# impls do (flash applies the cap inside the fused forward AND backward);
# the capability check below is the warn-fallback safety net for any impl
# that loses (or ships without) cap support — the model keeps training on
# the grouped einsum instead of hard-refusing.
_KNOWN_IMPLS = ("reference", "flash", "ring")
_CAP_IMPLS = frozenset(_KNOWN_IMPLS)


def _flash_min_seq(causal: bool) -> Optional[int]:
    """Parse ``TFDE_FLASH`` into a minimum auto-dispatch sequence length.

    '0'/'false' disable the flash auto-pick (None); '1'/'true' lower both
    thresholds to 1024; ''/'auto' keep the r04-measured defaults (2048
    causal / 4096 non-causal). Any OTHER value used to fall through a
    ``.get(env, 1024)`` — a typo like ``TFDE_FLASH=ture`` silently
    LOWERED the threshold to 1024 instead of doing nothing; now it warns
    once per call site and falls back to auto."""
    import os

    env = os.environ.get("TFDE_FLASH", "auto")
    default_min = 2048 if causal else 4096
    table = {
        "0": None, "false": None, "False": None,
        "": default_min, "auto": default_min,
        "1": 1024, "true": 1024, "True": 1024,
    }
    if env in table:
        return table[env]
    import warnings

    warnings.warn(
        f"TFDE_FLASH={env!r} is not a recognized value (expected 0/false, "
        f"1/true, or auto); ignoring it — flash auto-dispatch keeps the "
        f"measured default (S >= {default_min})",
        stacklevel=3,
    )
    return default_min


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array] = None,
    causal: bool = False,
    impl: str = "auto",
    window: Optional[int] = None,
    scale: Optional[float] = None,
    logit_cap: Optional[float] = None,
) -> jax.Array:
    """Dispatching attention: [B,S,H,D] -> [B,S,H,D].

    window: sliding-window band (Mistral convention — position i attends
    the last `window` positions inclusive, requires causal). Composes with
    every impl: 'reference' masks, 'flash' skips out-of-band tiles in the
    forward AND the backward (compute and DMA O(S * window) fwd+bwd — the
    backward scans only the statically in-band tile pairs), and 'ring'
    masks on global positions — the band is exact across shard boundaries,
    so sliding-window models train under sequence parallelism and pp x sp.

    scale: logit multiplier (None = 1/sqrt(d)); logit_cap: Gemma-2 tanh
    softcapping, cap * tanh(score / cap) before masking. Both compose with
    every impl — flash applies them inside the fused kernels, ring inside
    its online-softmax chunk step. If a selected impl ever lacks cap
    support (`_CAP_IMPLS`), dispatch warns and falls back to the grouped
    reference einsum instead of refusing.

    impl: 'auto' | 'reference' | 'flash' | 'ring'. 'auto' picks ring when the
    active mesh shards 'seq'; on TPU it picks flash for CAUSAL
    self-attention at S >= 2048 (no mask) — the r04 hardware A/B
    (tools/flash_ab.py, v5e: causal fwd+bwd 1.15x at 2048, 1.28x at 4096,
    1.30x at 8192 with the blockwise backward; the causal whole-tile skip
    is where the kernel wins) — and for non-causal at S >= 4096, where the
    same A/B measured 0.87-0.97x (slightly slower) but the O(S) memory
    replaces the reference's O(S^2) score tensor, the binding constraint at
    long S. Below those, the reference einsum (XLA fuses it optimally).
    ``TFDE_FLASH=0`` disables the flash auto-pick; ``TFDE_FLASH=1`` lowers
    both thresholds to S >= 1024.

    Inside a fully-manual region whose 'seq' axis is manual (the pp x sp
    pipeline, parallel/axes.manual_seq), dispatch goes straight to the
    per-shard ring body — there is no mesh to consult in there, and local
    attention over a seq shard would silently be the wrong math.
    """
    manual = axes_lib.manual_seq_info()
    if manual is not None:
        if impl not in ("auto", "ring"):
            # q/k/v here are per-shard sequence slices: any non-ring impl
            # would silently attend within the shard only — wrong math
            raise NotImplementedError(
                f"attn_impl={impl!r} inside a seq-manual region would "
                f"compute shard-local attention; use 'auto' or 'ring' "
                f"(the per-shard ring body) under pp x sp"
            )
        ring_size, vary_axes = manual
        if mask is not None:
            raise NotImplementedError(
                "ring attention inside a manual region supports causal "
                "masking only (key-padding masks would need a sharded "
                "validity plane threaded through the pipe)"
            )
        from tfde_tpu.ops import ring_attention as ra

        return ra.ring_attention_manual(
            q, k, v, causal=causal, ring_size=ring_size,
            vary_axes=vary_axes, window=window, scale=scale,
            logit_cap=logit_cap,
        )
    if impl == "auto":
        flash_min_seq = _flash_min_seq(causal)
        if _seq_parallel_active() and _have("ring_attention"):
            impl = "ring"
        elif (
            _on_tpu()
            and flash_min_seq is not None
            and q.shape[1] >= flash_min_seq
            # self-attention, MHA or GQA (k/v may carry fewer heads)
            and q.shape[:2] == k.shape[:2]
            and q.shape[3] == k.shape[3]
            and q.shape[2] % k.shape[2] == 0
            and q.shape[1] % 128 == 0
            and mask is None
            and _have("flash_attention")
            # inside a partial-manual pipeline region (AbstractMesh) the
            # kernel's custom-VJP variance doesn't compose with a nested
            # shard_map; the reference einsum partitions fine there
            and not isinstance(
                axes_lib.current_mesh(), jax.sharding.AbstractMesh
            )
        ):
            impl = "flash"
        else:
            impl = "reference"
    if ((scale is not None or logit_cap is not None)
            and impl in _KNOWN_IMPLS and impl not in _CAP_IMPLS):
        import warnings

        warnings.warn(
            f"attention impl {impl!r} does not support scale/logit_cap; "
            f"falling back to the grouped reference einsum",
            stacklevel=2,
        )
        impl = "reference"
    if impl == "reference":
        return reference_attention(q, k, v, mask=mask, causal=causal,
                                   window=window, scale=scale,
                                   logit_cap=logit_cap)
    if impl == "flash":
        if mask is not None:
            raise NotImplementedError(
                "flash attention does not take an explicit mask; use "
                "impl='reference' (or 'auto', which refuses flash when a "
                "mask is present)"
            )
        return _flash_sharded(q, k, v, causal, window, scale, logit_cap)
    if impl == "ring":
        from tfde_tpu.ops import ring_attention

        return ring_attention.ring_attention(
            q, k, v, mask=mask, causal=causal, mesh=axes_lib.current_mesh(),
            window=window, scale=scale, logit_cap=logit_cap,
        )
    raise ValueError(f"unknown attention impl {impl!r}")


def _flash_sharded(q: jax.Array, k: jax.Array, v: jax.Array,
                   causal: bool, window=None, scale=None,
                   logit_cap=None) -> jax.Array:
    """Call the Pallas flash kernel batch-parallel over the active mesh.

    A pallas_call under plain jit with sharded operands is NOT partitioned
    automatically — XLA gathers the inputs and replicates the whole kernel
    (measured: sharded-in, replicated-out), silently destroying data
    parallelism. Attention is embarrassingly parallel over batch (and over
    heads under TP), so when a concrete mesh is active we shard_map the
    kernel over those axes; each device runs flash on its own shard with
    zero communication. Falls back to the direct (replicating) call when no
    mesh is active, inside a fully-manual region (current_mesh is None
    there), or when the shapes don't divide. Inside a *partial-manual*
    region (AbstractMesh — the 3D pipe) flash is refused outright: the
    kernel's custom-VJP loses the pipe-variance annotations through a
    nested shard_map, so auto-dispatch picks the reference einsum there
    and an explicit impl='flash' errors with guidance."""
    from tfde_tpu.ops import flash_attention as fa

    # interpret on CPU only, for the fake-device test methodology; any
    # other non-TPU backend should fail loudly at Mosaic lowering rather
    # than silently run the orders-of-magnitude-slower interpreter
    interpret = jax.default_backend() == "cpu"
    mesh = axes_lib.current_mesh()
    if isinstance(mesh, jax.sharding.AbstractMesh):
        raise NotImplementedError(
            "flash attention inside a partial-manual pipeline region is not "
            "supported (the kernel's custom-VJP variance does not compose "
            "with a nested shard_map); use attn_impl='reference' (or 'auto', "
            "which picks it automatically) for pipelined models"
        )
    if not isinstance(mesh, jax.sharding.Mesh):
        return fa.flash_attention(q, k, v, causal=causal, window=window,
                                  interpret=interpret, scale=scale,
                                  logit_cap=logit_cap)
    from jax.sharding import PartitionSpec as P

    from tfde_tpu.parallel.sharding import data_axes as _data_axes

    batch_axes = _data_axes(mesh)
    d = 1
    for a in batch_axes:
        d *= mesh.shape[a]
    heads = None
    if "tensor" in mesh.axis_names and mesh.shape["tensor"] > 1 \
            and q.shape[2] % mesh.shape["tensor"] == 0 \
            and k.shape[2] % mesh.shape["tensor"] == 0:
        # GQA: k/v heads must also divide (each shard keeps whole groups)
        heads = "tensor"
    if q.shape[0] % max(d, 1):
        batch_axes, d = (), 1
    if d <= 1 and heads is None:
        return fa.flash_attention(q, k, v, causal=causal, window=window,
                                  interpret=interpret, scale=scale,
                                  logit_cap=logit_cap)
    spec = P(batch_axes if batch_axes else None, None, heads, None)
    fn = _compat_shard_map(
        lambda q, k, v: fa.flash_attention(
            q, k, v, causal=causal, window=window, interpret=interpret,
            scale=scale, logit_cap=logit_cap
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        # pallas_call's out_shape carries no vma annotations; the kernel is
        # pure per-shard compute (no collectives), so the check adds nothing
        check_vma=False,
    )
    return fn(q, k, v)


def padding_mask(valid: jax.Array) -> jax.Array:
    """[B, S] 1/True-for-real-token -> [B, 1, 1, S] attention mask."""
    return valid.astype(jnp.bool_)[:, None, None, :]
