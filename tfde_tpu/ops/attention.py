"""Attention ops — the hot kernel of the transformer scale-up configs.

The reference has no attention anywhere (its models are MNIST CNNs, SURVEY.md
§5 "long-context: entirely absent"); this exists for the driver's scale
configs (BASELINE.json: ViT-B/16 FSDP, BERT-base MLM) and the long-context
story (ring attention over a 'seq' mesh axis).

Three implementations behind one dispatcher:

- ``reference``: einsum + fp32 softmax. The numerics oracle; also what XLA
  fuses perfectly well at short sequence lengths.
- ``flash``: Pallas TPU kernel (ops/flash_attention.py) — blockwise online
  softmax, O(S) memory, MXU-shaped tiles. Opt-in on TPU for long sequences
  (``TFDE_FLASH`` env var, or ``impl='flash'``) until hardware-qualified.
- ``ring``: sequence-parallel blockwise attention over the mesh's 'seq' axis
  (ops/ring_attention.py) — KV blocks rotate around the ring via ppermute
  while compute overlaps, so sequence length scales with the number of chips.

Shapes follow the Flax convention: q/k/v are [batch, length, heads, head_dim].
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from tfde_tpu.parallel import axes as axes_lib


def reference_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array] = None,
    causal: bool = False,
) -> jax.Array:
    """Plain softmax(QK^T/sqrt(d))V with fp32 accumulation.

    mask: broadcastable to [B, H, Sq, Sk]; True/1 = attend. Additive -inf
    masking in fp32 keeps bf16 inputs numerically safe.
    """
    *_, sq, _, d = q.shape
    sk = k.shape[1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    # [B,Sq,H,D] x [B,Sk,H,D] -> [B,H,Sq,Sk]; accumulate in fp32.
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        cm = jnp.tril(jnp.ones((sq, sk), jnp.bool_), k=sk - sq)
        mask = cm if mask is None else jnp.logical_and(mask, cm)
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    weights = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", weights.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)


def _seq_parallel_active() -> bool:
    mesh = axes_lib.current_mesh()
    return mesh is not None and "seq" in mesh.axis_names and mesh.shape["seq"] > 1


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _have(module: str) -> bool:
    import importlib.util

    return importlib.util.find_spec(f"tfde_tpu.ops.{module}") is not None


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array] = None,
    causal: bool = False,
    impl: str = "auto",
) -> jax.Array:
    """Dispatching attention: [B,S,H,D] -> [B,S,H,D].

    impl: 'auto' | 'reference' | 'flash' | 'ring'. 'auto' picks ring when the
    active mesh shards 'seq'; on TPU with ``TFDE_FLASH`` set it picks flash
    for sequences long enough that the O(S^2) score tensor hurts (S >= 1024,
    no mask); otherwise the reference einsum (XLA already fuses it optimally
    at short S). Flash stays opt-in until hardware-qualified — long-sequence
    users should set TFDE_FLASH=1 or pass impl='flash' explicitly.
    """
    if impl == "auto":
        import os

        if _seq_parallel_active() and _have("ring_attention"):
            impl = "ring"
        elif (
            _on_tpu()
            and q.shape[1] >= 1024
            and q.shape[1] % 128 == 0 and k.shape[1] % 128 == 0
            and mask is None
            and _have("flash_attention")
            and os.environ.get("TFDE_FLASH", "0") not in ("", "0", "false", "False")
        ):
            # opt-in until hardware-qualified: the kernel passes interpret-
            # mode numerics/grad tests, but auto-selecting an unproven Mosaic
            # compile in every long-sequence model is not worth the risk;
            # set TFDE_FLASH=1 (or impl='flash') to enable.
            impl = "flash"
        else:
            impl = "reference"
    if impl == "reference":
        return reference_attention(q, k, v, mask=mask, causal=causal)
    if impl == "flash":
        if mask is not None:
            raise NotImplementedError(
                "flash attention does not take an explicit mask; use "
                "impl='reference' (or 'auto', which refuses flash when a "
                "mask is present)"
            )
        from tfde_tpu.ops import flash_attention

        return flash_attention.flash_attention(q, k, v, causal=causal)
    if impl == "ring":
        from tfde_tpu.ops import ring_attention

        return ring_attention.ring_attention(
            q, k, v, mask=mask, causal=causal, mesh=axes_lib.current_mesh()
        )
    raise ValueError(f"unknown attention impl {impl!r}")


def padding_mask(valid: jax.Array) -> jax.Array:
    """[B, S] 1/True-for-real-token -> [B, 1, 1, S] attention mask."""
    return valid.astype(jnp.bool_)[:, None, None, :]
