"""Per-op roofline accounting for the attention hot path.

Two halves, one source of truth:

- **Analytic flop model** — `mean_attended_keys` credits causal and
  sliding-window attention with the flops the kernels actually have to do
  (exactly (S+1)/2 in-band keys per query for plain causal; the
  triangle-plus-band mean for windowed), fixing the MFU accounting caveat
  bench.py's `gpt_train_flops_per_token` used to carry ("half-counting is
  ~1/(2n) conservative") and making windowed configs (`gpt_long_win`)
  report MFU against their true useful work instead of the full-causal
  figure.

- **Tile-visit counter** — the flash kernels decide which (Q-tile, K-tile)
  pairs to execute from `flash_attention._tile_in_band`; the counter
  replays the same predicate statically (`tile_visits`) and records the
  schedule the kernels trace (`measured_tile_visits`, via
  `flash_attention.record_tile_visits` in interpret mode — the causal
  backward additionally bumps a runtime counter from inside its scan
  body). `check_tile_visits` pins the two against the analytic band bound,
  so an attention tile-count regression (e.g. a backward that quietly goes
  back to scanning all tiles) gates in tier-1 the same way collective
  counts already do (tools/tier1.sh runs it; tests/test_roofline.py
  asserts the pins).

The flop model is plain arithmetic on Python ints — importable with no
device and usable from bench.py's flop accounting without tracing anything.
"""

from __future__ import annotations

from typing import Optional


def mean_attended_keys(seq: int, causal: bool = True,
                       window: Optional[int] = None) -> float:
    """Mean number of attended (in-band) keys per query position.

    - bidirectional: every query sees all S keys.
    - causal: query i sees i+1 keys -> mean (S+1)/2, the EXACT triangle
      count (not the S/2 approximation).
    - causal + window w: the first w queries are still filling the band
      (i+1 keys), the rest see exactly w -> (w(w+1)/2 + (S-w)w) / S.
    """
    if not causal:
        return float(seq)
    if window is None or window >= seq:
        return (seq + 1) / 2.0
    if window < 1:
        raise ValueError(f"window={window} must be >= 1")
    w = window
    return (w * (w + 1) / 2.0 + (seq - w) * w) / seq


def attention_flops_per_token(attn_width: int, seq: int,
                              causal: bool = True,
                              window: Optional[int] = None) -> float:
    """FORWARD attention-matmul flops per token for one layer.

    Per (query, in-band key) pair each head does 2*head_dim flops in the
    score matmul and 2*head_dim in the value matmul -> 4 * heads *
    head_dim * mean_keys = 4 * attn_width * mean_keys per token
    (attn_width = heads * head_dim, == hidden for every bench config).
    Training credit is conventionally 3x this (backward ~2x forward);
    callers apply their own multiplier so fwd-only benches can use it too.
    """
    return 4.0 * attn_width * mean_attended_keys(seq, causal, window)


def stacked_attention_flops_per_token(
    attn_width: int, seq: int, depth: int, causal: bool = True,
    window: Optional[int] = None, window_pattern: str = "all",
) -> float:
    """Forward attention-matmul flops per token summed over `depth` layers.

    window_pattern follows models/transformer.Encoder: 'all' gives every
    layer the band; 'alternate' (Gemma-2) windows the EVEN layers and
    leaves the odd layers full causal."""
    if window_pattern not in ("all", "alternate"):
        raise ValueError(f"unknown window_pattern {window_pattern!r}")
    full = attention_flops_per_token(attn_width, seq, causal, None)
    if window is None:
        return depth * full
    banded = attention_flops_per_token(attn_width, seq, causal, window)
    if window_pattern == "alternate":
        n_banded = (depth + 1) // 2  # even layer indices: 0, 2, ...
        return n_banded * banded + (depth - n_banded) * full
    return depth * banded


def tile_visits(seq: int, block_q: Optional[int] = None,
                block_k: Optional[int] = None, causal: bool = True,
                window: Optional[int] = None) -> dict:
    """Static tile-visit counts for one head-slice of flash attention.

    Derived from the SAME `_tile_in_band` predicate the kernels branch on
    (via `flash_attention.bwd_tile_plan`), so these are the tiles the
    compiled forward executes (`pl.when`) and the causal backward scans
    (the in-band pair list IS its scan schedule). The forward, dq and
    dk/dv passes share one band, hence one count."""
    from tfde_tpu.ops import flash_attention as fa

    plan = fa.bwd_tile_plan(seq, block_q, block_k, causal, window)
    return {
        "block_q": plan["block_q"],
        "block_k": plan["block_k"],
        "grid": plan["grid"],
        "fwd": plan["visits"],
        "bwd_dq": plan["visits"],
        "bwd_dkv": plan["visits"],
        "max_visits_per_q_tile": plan["max_visits_per_q_tile"],
        "max_visits_per_k_tile": plan["max_visits_per_k_tile"],
    }


def max_band_tiles_per_q_tile(block_q: int, block_k: int,
                              window: Optional[int]) -> int:
    """Analytic ceiling on in-band K tiles per Q tile for a windowed band:
    the band behind a Q tile spans block_q + window - 1 rows' worth of
    columns, which straddles at most that many K tiles plus one partial —
    the O(S * window / block^2) bound of the acceptance criterion, per
    Q tile. Full causal has no such cap (the diagonal grows with qi)."""
    if window is None:
        raise ValueError("the per-Q-tile band bound needs a window")
    return (block_q + window - 2) // block_k + 2


def measured_tile_visits(
    seq: int = 512, block_q: int = 64, block_k: int = 64,
    causal: bool = True, window: Optional[int] = None,
    logit_cap: Optional[float] = None, batch: int = 1, heads: int = 2,
    head_dim: int = 8, kv_heads: Optional[int] = None,
) -> dict:
    """Run flash fwd+bwd in interpret mode under the kernel tile-visit
    recorder and return what the kernels actually scheduled: the traced
    forward/backward visit counts plus `bwd_steps_executed` — a runtime
    counter bumped from inside the causal backward's scan body, i.e. the
    number of tile computations that genuinely ran."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from tfde_tpu.ops import flash_attention as fa

    rng = np.random.default_rng(0)
    kv = kv_heads or heads
    q = jnp.asarray(rng.standard_normal((batch, seq, heads, head_dim)),
                    jnp.float32)
    k = jnp.asarray(rng.standard_normal((batch, seq, kv, head_dim)),
                    jnp.float32)
    v = jnp.asarray(rng.standard_normal((batch, seq, kv, head_dim)),
                    jnp.float32)

    def loss(q, k, v):
        return fa.flash_attention(
            q, k, v, causal, block_q, block_k, True, window, None, logit_cap
        ).astype(jnp.float32).sum()

    with fa.record_tile_visits() as counts:
        grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        jax.block_until_ready(grads)
        try:
            jax.effects_barrier()  # flush the debug-callback counter
        except Exception:
            pass
        return dict(counts)


def check_tile_visits(verbose: bool = False) -> list:
    """Pin the flash tile schedule against the analytic band. Returns a
    list of failure strings (empty = pass) so both the tier-1 smoke
    (tools/roofline.py --check-tiles) and the unit tests share one gate.

    What must hold, per case:
    - the traced forward/backward visit counts equal the static plan
      (same predicate, so a mismatch means the kernels' schedule drifted);
    - the causal backward's runtime-executed scan steps equal the plan
      (the backward provably does NOT visit out-of-band tiles);
    - causal visits are the exact triangle count (~half the grid);
    - windowed visits respect the O(S * window / block^2) ceiling per
      Q tile.
    """
    failures = []
    cases = [
        # (seq, block, window, kv_heads) — multi-tile, MHA and GQA
        (512, 64, None, None),
        (512, 64, 128, None),
        (768, 128, 256, 1),
    ]
    for seq, block, window, kv_heads in cases:
        name = f"s{seq}b{block}w{window}kv{kv_heads}"
        static = tile_visits(seq, block, block, True, window)
        measured = measured_tile_visits(
            seq=seq, block_q=block, block_k=block, window=window,
            kv_heads=kv_heads,
        )
        n = seq // block
        if window is None:
            expect = n * (n + 1) // 2  # exact causal triangle
            if static["fwd"] != expect:
                failures.append(
                    f"{name}: causal band is {static['fwd']} tiles, "
                    f"expected the exact triangle {expect}"
                )
        else:
            ceiling = max_band_tiles_per_q_tile(block, block, window)
            if static["max_visits_per_q_tile"] > ceiling:
                failures.append(
                    f"{name}: {static['max_visits_per_q_tile']} K tiles "
                    f"per Q tile exceeds the band ceiling {ceiling}"
                )
            if static["fwd"] > n * ceiling:
                failures.append(
                    f"{name}: total visits {static['fwd']} exceed "
                    f"n_q * ceiling = {n * ceiling}"
                )
        for key in ("fwd", "bwd_dq", "bwd_dkv"):
            got = measured.get(f"{key}_visits")
            if got != static[key]:
                failures.append(
                    f"{name}: traced {key} visits {got} != static plan "
                    f"{static[key]}"
                )
        executed = measured.get("bwd_steps_executed")
        if executed != static["bwd_dq"]:
            failures.append(
                f"{name}: backward executed {executed} scan steps, "
                f"plan says {static['bwd_dq']} — the backward is visiting "
                f"tiles outside the band (or skipping in-band ones)"
            )
        if verbose:
            print(f"{name}: grid={static['grid']} visits={static['fwd']} "
                  f"executed={executed}")
    return failures
