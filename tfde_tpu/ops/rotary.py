"""Rotary position embeddings (RoFormer/RoPE) — the positional scheme of
the modern decoder families (LLaMA/GPT-NeoX lineage).

Instead of adding learned absolute positions to the embedding stream
(models/gpt.py `wpe`), RoPE rotates each (even, odd) feature pair of the
query/key heads by an angle proportional to the token's absolute position;
the q.k dot product then depends only on RELATIVE position — better length
extrapolation, no learned position table, and a natural fit for the KV
cache (a cached key's rotation never changes, so decode steps rotate only
the new token; models/transformer.py passes the cache offset as
`positions`).

TPU shape notes: operates on [B, S, H, D] with D even, as two half-feature
blocks (the GPT-NeoX/LLaMA "rotate_half" convention — contiguous halves
vectorize on the VPU; the interleaved original is a permutation of the
same math). Everything is elementwise over S, so XLA partitions it
transparently under any mesh, including the 'seq' ring."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def scale_frequencies(freqs: jax.Array, scaling,
                      theta: float = 10_000.0) -> jax.Array:
    """RoPE frequency rescaling for long-context fine-tunes.

    `scaling` is a tuple (hashable — it lives on flax module configs):
      ('linear', factor) — position-interpolation (Llama-2-long style):
          every frequency divided by factor.
      ('llama3', factor, low_freq_factor, high_freq_factor,
       original_max_position) — the Llama-3.1 rule (HF
       `_compute_llama3_parameters` math): wavelengths shorter than
       original_max/high_freq_factor keep their frequency, longer than
       original_max/low_freq_factor divide by factor, and the band
       between interpolates smoothly.
      ('yarn', factor, beta_fast, beta_slow, original_max,
       attention_factor, truncate) — NTK-by-parts (HF
       `_compute_yarn_parameters` math): dimensions rotating faster than
       beta_fast turns over the original context keep their frequency
       (extrapolation), slower than beta_slow divide by factor
       (interpolation), with a linear ramp between; attention_factor
       additionally scales cos/sin (applied in rotary_angles).
       `theta` must be the same base the frequencies were built with —
       the correction range is computed in its log space.
    """
    import math

    kind = scaling[0]
    if kind == "linear":
        return freqs / float(scaling[1])
    if kind == "yarn":
        _, factor, beta_fast, beta_slow, orig_max, _att, truncate = scaling
        factor = float(factor)
        dim = freqs.shape[0] * 2

        def corr_dim(num_rot: float) -> float:
            return (dim * math.log(float(orig_max)
                                   / (num_rot * 2 * math.pi))
                    ) / (2 * math.log(theta))

        low = corr_dim(float(beta_fast))
        high = corr_dim(float(beta_slow))
        if truncate:
            low, high = math.floor(low), math.ceil(high)
        low, high = max(low, 0), min(high, dim - 1)
        if low == high:
            high += 0.001  # prevent singularity (the HF guard)
        ramp = jnp.clip(
            (jnp.arange(dim // 2, dtype=jnp.float32) - low) / (high - low),
            0.0, 1.0,
        )
        extrapolation_factor = 1.0 - ramp
        return (freqs / factor) * (1.0 - extrapolation_factor) \
            + freqs * extrapolation_factor
    if kind == "llama3":
        _, factor, low_f, high_f, orig_max = scaling
        factor, low_f, high_f = float(factor), float(low_f), float(high_f)
        orig_max = float(orig_max)
        wavelen = 2.0 * math.pi / freqs
        low_wl = orig_max / low_f
        high_wl = orig_max / high_f
        smooth = (orig_max / wavelen - low_f) / (high_f - low_f)
        interpolated = (1.0 - smooth) * freqs / factor + smooth * freqs
        return jnp.where(
            wavelen < high_wl, freqs,
            jnp.where(wavelen > low_wl, freqs / factor, interpolated),
        )
    raise ValueError(
        f"rope scaling kind must be 'linear', 'llama3' or 'yarn', "
        f"got {kind!r}"
    )


def rotary_angles(positions: jax.Array, dim: int,
                  theta: float = 10_000.0, scaling=None) -> tuple:
    """(cos, sin) [..., dim/2] for integer `positions` [...]."""
    if dim % 2:
        raise ValueError(f"rotary head_dim must be even, got {dim}")
    freqs = theta ** (
        -jnp.arange(0, dim, 2, dtype=jnp.float32) / dim
    )  # [dim/2]
    if scaling is not None:
        freqs = scale_frequencies(freqs, scaling, theta)
    ang = positions.astype(jnp.float32)[..., None] * freqs  # [..., dim/2]
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    if scaling is not None and scaling[0] == "yarn":
        # yarn's attention temperature: cos/sin scale by the attention
        # factor (HF multiplies the cached cos/sin the same way)
        att = float(scaling[5])
        cos, sin = cos * att, sin * att
    return cos, sin


def apply_rotary(x: jax.Array, positions: jax.Array,
                 theta: float = 10_000.0,
                 rotary_dim=None, scaling=None) -> jax.Array:
    """Rotate [B, S, H, D] by per-token angles; `positions` is [S] or
    [B, S] absolute token positions. fp32 trig, result in x.dtype.

    rotary_dim: PARTIAL rotary (the Phi/GPT-NeoX partial_rotary_factor
    convention) — only the first `rotary_dim` features rotate, the rest
    pass through untouched. None/D = full rotation.

    scaling: RoPE frequency rescaling tuple (see scale_frequencies) —
    the Llama-3.1 long-context convention."""
    d = x.shape[-1]
    if rotary_dim is not None and rotary_dim != d:
        if not 0 < rotary_dim < d:
            raise ValueError(
                f"rotary_dim {rotary_dim} must be in (0, head_dim={d}]"
            )
        rot, rest = x[..., :rotary_dim], x[..., rotary_dim:]
        return jnp.concatenate(
            [apply_rotary(rot, positions, theta, scaling=scaling), rest],
            axis=-1,
        )
    cos, sin = rotary_angles(positions, d, theta, scaling)  # [..., S, d/2]
    # broadcast to [B, S, 1, d/2] over heads
    if cos.ndim == 2:  # [S, d/2] -> [1, S, 1, d/2]
        cos, sin = cos[None, :, None], sin[None, :, None]
    else:  # [B, S, d/2] -> [B, S, 1, d/2]
        cos, sin = cos[:, :, None], sin[:, :, None]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return out.astype(x.dtype)
