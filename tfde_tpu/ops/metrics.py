"""Metrics — the `metrics=['accuracy']` capability of the reference models
(mnist_keras_distributed.py:115, distributed_with_keras.py:43,
tf2_mnist_distributed.py:141). Full-dataset eval aggregates masked sums
on-device (training/step.py eval_step) — there is no host-side accumulator."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def accuracy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Fraction of correct argmax predictions. labels: int, any trailing 1-dims."""
    labels = labels.reshape(labels.shape[: logits.ndim - 1])
    return jnp.mean((jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32))
