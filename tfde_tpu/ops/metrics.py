"""Metrics — the `metrics=['accuracy']` capability of the reference models
(mnist_keras_distributed.py:115, distributed_with_keras.py:43,
tf2_mnist_distributed.py:141), plus streaming accumulation for full-dataset
eval (EvalSpec steps=None, mnist_keras:271)."""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


def accuracy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Fraction of correct argmax predictions. labels: int, any trailing 1-dims."""
    labels = labels.reshape(labels.shape[: logits.ndim - 1])
    return jnp.mean((jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32))


@dataclasses.dataclass
class MeanAccumulator:
    """Host-side streaming weighted mean, for multi-batch eval aggregation."""

    total: float = 0.0
    weight: float = 0.0

    def update(self, value, weight: float = 1.0) -> None:
        self.total += float(value) * weight
        self.weight += weight

    def result(self) -> float:
        return self.total / self.weight if self.weight else float("nan")
