"""Loss functions with the reference's distributed loss-scaling convention.

The reference's canonical pattern (tf2_mnist_distributed.py:81-83) is

    loss = tf.reduce_sum(per_example_ce) * (1. / BATCH_SIZE)

i.e. *sum over examples divided by the global batch size* — so that when the
batch is split across replicas and gradients are summed (all-reduce), the
result equals the single-replica gradient of the global-batch mean. Under
`jit` over a mesh the batch is one logical array, so `jnp.mean` over the batch
axis is exactly this convention; XLA inserts the `psum` when the batch axis is
sharded.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax


def softmax_cross_entropy_with_integer_labels(
    logits: jax.Array, labels: jax.Array
) -> jax.Array:
    """Per-example CE from logits; accepts the reference's [N,1] int column
    labels (mnist_keras:215-216). Delegates to optax for the numerics."""
    labels = labels.reshape(labels.shape[: logits.ndim - 1])
    return optax.losses.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), labels.astype(jnp.int32)
    )


def sparse_categorical_crossentropy(
    logits: jax.Array,
    labels: jax.Array,
    from_logits: bool = True,
    global_batch_size: int | None = None,
) -> jax.Array:
    """Scalar loss = sum(per-example CE) / global_batch.

    Matches Keras `sparse_categorical_crossentropy` (mnist_keras:114,
    dwk:41) combined with the reference's 1/BATCH_SIZE scaling
    (tf2_mnist:81-83). `from_logits=False` accepts probabilities (the
    reference BN-CNN ends in softmax, mnist_keras:108); we clip like Keras.
    """
    if not from_logits:
        probs = jnp.clip(logits.astype(jnp.float32), 1e-7, 1.0 - 1e-7)
        logits = jnp.log(probs)
    per_example = softmax_cross_entropy_with_integer_labels(logits, labels)
    denom = global_batch_size if global_batch_size is not None else per_example.size
    return jnp.sum(per_example) / denom


def masked_lm_loss(
    logits: jax.Array, labels: jax.Array, ignore_id: int = -100
) -> tuple[jax.Array, jax.Array]:
    """(mean CE over target positions, target-position accuracy).

    logits [B,S,V], labels [B,S] with `ignore_id` marking non-targets
    (data/mlm.mask_tokens). The mean normalizes by the *global* target count
    — under a sharded batch both sums psum across devices, so the gradient
    matches the single-device run exactly (same convention as the
    reference's sum x 1/BATCH_SIZE, tf2_mnist_distributed.py:81-83).
    """
    weights = (labels != ignore_id).astype(jnp.float32)
    safe = jnp.where(labels == ignore_id, 0, labels)
    per_tok = optax.losses.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), safe
    )
    denom = jnp.maximum(jnp.sum(weights), 1.0)
    loss = jnp.sum(per_tok * weights) / denom
    correct = (jnp.argmax(logits, axis=-1) == safe).astype(jnp.float32)
    acc = jnp.sum(correct * weights) / denom
    return loss, acc
