"""Ring attention — sequence/context parallelism over the 'seq' mesh axis.

Long-context capability (absent from the reference, SURVEY.md §5; mandated
by the framework goals): the sequence dimension is sharded across chips, so
max context scales linearly with the ring size. Each device keeps its Q
shard resident and computes blockwise attention against the KV shard it
currently holds, while `jax.lax.ppermute` rotates the KV shards one hop
around the ring per step — compute overlaps the ICI transfer (XLA schedules
the collective-permute concurrently with the matmuls; on TPU the permute
rides neighbor ICI links, the topology ring attention was designed for).

Math: the standard online-softmax accumulation (same recurrence the flash
kernel uses) in fp32 —

    m' = max(m, rowmax(S));  o' = o*e^(m-m') + e^(S-m') V;  l' = l*e^(m-m') + rowsum(e^(S-m'))

which yields exactly softmax(QK^T)V after the last ring step, so numerics
match ops/attention.reference_attention to float tolerance regardless of
ring size (tests/test_ring_attention.py asserts this).

Masks: `causal` and key-padding masks ([B,1,1,S], ops/attention.padding_mask)
are supported — the padding row rotates with its KV shard; arbitrary dense
[B,H,Sq,Sk] masks are not (they would have to be sharded along two axes at
once).

GQA: k/v may carry fewer heads than q (H = Kv * groups) — the grouped ring
body rotates kv_heads-sized KV shards, shrinking the per-hop ICI transfer
by the group factor; numerics match ops/attention.grouped_attention
(tests/test_ring_attention.py). Long-context Mistral/LLaMA-class training
composes with the 'seq' axis out of the box.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tfde_tpu.utils.compat import shard_map as _compat_shard_map

_NEG = -1e30  # finite -inf stand-in: keeps exp() NaN-free on fully-masked blocks


def _chunk_attention(carry, q, k, v, kv_valid, q_pos, k_pos, causal,
                     window=None, scale=None, logit_cap=None):
    """One online-softmax accumulation step against one KV chunk.

    GQA: k/v may carry fewer heads [B, Sk, Kv, D] than q (H = Kv * groups)
    — the score/value einsums index the KV head directly, mirroring
    ops/attention.grouped_attention, so the KV shards that rotate around
    the ring stay kv_heads-sized (the ICI transfer shrinks by the group
    factor, on top of the HBM saving). Accumulators stay per-QUERY-head,
    so the carries and every ring/block caller are unchanged.

    scale (None = 1/sqrt(d)) and logit_cap (Gemma-2 tanh softcapping,
    cap * tanh(s / cap) BEFORE masking — same ordering as
    grouped_attention) apply inside the chunk step, so capped models keep
    exact numerics across shard boundaries; the backward is plain AD
    through the recurrence."""
    o, m, l = carry
    b, sq, h, d = q.shape
    kv_heads = k.shape[2]
    sk = k.shape[1]
    if scale is None:
        scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    if kv_heads != h:
        g = h // kv_heads  # head index = c * g + group member (h-major)
        qg = q.reshape(b, sq, kv_heads, g, d)
        s = jnp.einsum(
            "bqcgd,bkcd->bcgqk", qg, k, preferred_element_type=jnp.float32
        ).reshape(b, h, sq, sk)
    else:
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                       preferred_element_type=jnp.float32)
    s = s * scale
    if logit_cap is not None:
        s = logit_cap * jnp.tanh(s / logit_cap)
    if kv_valid is not None:
        s = jnp.where(kv_valid[:, None, None, :], s, _NEG)
    if causal:
        allowed = q_pos[:, None] >= k_pos[None, :]  # [sq, sk] global positions
        if window is not None:
            # sliding band on GLOBAL positions: row i sees (i - window, i] —
            # exact across shard boundaries because q_pos/k_pos are global
            allowed = jnp.logical_and(
                allowed, q_pos[:, None] - k_pos[None, :] < window
            )
        s = jnp.where(allowed[None, None], s, _NEG)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))            # [b,h,sq]
    p = jnp.exp(s - m_new[..., None])                      # [b,h,sq,sk]
    corr = jnp.exp(m - m_new)                              # [b,h,sq]
    l_new = l * corr + jnp.sum(p, axis=-1)
    if kv_heads != h:
        pv = jnp.einsum(
            "bcgqk,bkcd->bqcgd",
            p.reshape(b, kv_heads, h // kv_heads, sq, sk).astype(v.dtype), v,
            preferred_element_type=jnp.float32,
        ).reshape(b, sq, h, d)
    else:
        pv = jnp.einsum(
            "bhqk,bkhd->bqhd", p.astype(v.dtype), v,
            preferred_element_type=jnp.float32,
        )
    o_new = o * corr.transpose(0, 2, 1)[..., None] + pv    # [b,sq,h,d]
    return o_new, m_new, l_new


def _block_attention(carry, q, k, v, kv_valid, q_pos, k_pos, causal,
                     block_k: int = 1024, window=None, scale=None,
                     logit_cap=None):
    """Online-softmax accumulation against the current KV shard, blockwise:
    the shard is scanned in `block_k` chunks so per-device score memory is
    O(sq * block_k), never O(sq * sk_shard) — the 'blockwise' half of ring
    attention's memory story (the ring shards the sequence across chips;
    this keeps each chip's local block from re-materializing a quadratic
    score tensor at large per-chip shards). Shards at or below `block_k`
    take the single-chunk path unchanged."""
    sk = k.shape[1]
    if sk <= block_k or sk % block_k:
        return _chunk_attention(carry, q, k, v, kv_valid, q_pos, k_pos,
                                causal, window=window, scale=scale,
                                logit_cap=logit_cap)

    def chunk(carry, i):
        start = i * block_k
        kc = jax.lax.dynamic_slice_in_dim(k, start, block_k, axis=1)
        vc = jax.lax.dynamic_slice_in_dim(v, start, block_k, axis=1)
        kvc = (
            None if kv_valid is None
            else jax.lax.dynamic_slice_in_dim(kv_valid, start, block_k, axis=1)
        )
        kpc = jax.lax.dynamic_slice_in_dim(k_pos, start, block_k, axis=0)
        return (
            _chunk_attention(carry, q, kc, vc, kvc, q_pos, kpc, causal,
                             window=window, scale=scale,
                             logit_cap=logit_cap),
            None,
        )

    carry, _ = jax.lax.scan(chunk, carry, jnp.arange(sk // block_k))
    return carry


def ring_attention_manual(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_valid: Optional[jax.Array] = None,
    causal: bool = False,
    axis: str = "seq",
    ring_size: int = 1,
    block_k: int = 1024,
    vary_axes: tuple = (),
    window=None,
    scale=None,
    logit_cap=None,
) -> jax.Array:
    """The per-shard ring body, for callers ALREADY inside a manual region
    where `axis` is a manual mesh axis — e.g. a stage of the fully-manual
    pipeline (models/pipelined.py), which is how pp x sp composes: one
    flat manual region, pipe hops and seq rotations side by side, AD
    straight through (the round-3 refusal was about NESTED manual
    regions; a flat one lowers fine — tests/test_pipelined_lm.py pp x sp
    suite).

    q/k/v are this shard's [B, S_local, H, D]; `ring_size` the number of
    seq shards; `vary_axes` the manual axes accumulators must be typed
    varying over (normally every manual axis in play). Returns the
    local shard of softmax(QK^T)V over the GLOBAL sequence.
    """
    if window is not None and (not causal or window < 1):
        # the funnel both the public ring and the pp x sp manual dispatch
        # flow through — siblings (grouped/flash) validate identically, and
        # a silently ignored band would be wrong math, not an error
        raise ValueError(
            f"window={window} requires causal=True and window >= 1"
        )
    idx = jax.lax.axis_index(axis)
    sq = q.shape[1]
    out_dtype = q.dtype
    q_pos = idx * sq + jnp.arange(sq)
    b, _, h, d = q.shape
    from tfde_tpu.parallel.axes import vary_over

    o, m, l = (
        vary_over(jnp.zeros((b, sq, h, d), jnp.float32), vary_axes),
        vary_over(jnp.full((b, h, sq), _NEG, jnp.float32), vary_axes),
        vary_over(jnp.zeros((b, h, sq), jnp.float32), vary_axes),
    )
    n = ring_size
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(t, carry):
        o_m_l, k, v, kv_valid = carry
        src = (idx - t) % n  # whose KV shard we hold at step t
        k_pos = src * sq + jnp.arange(sq)

        def accumulate(c):
            return _block_attention(
                c, q, k, v, kv_valid, q_pos, k_pos, causal,
                block_k=block_k, window=window, scale=scale,
                logit_cap=logit_cap,
            )

        if causal:
            # hop skip: a source shard entirely in this shard's future
            # (or, with a window, entirely older than the band) is fully
            # masked — its accumulation is an exact no-op, so skip the
            # compute and let only the rotation run. The long-context
            # windowed case is the payoff: with S >> window each query
            # shard overlaps O(window / S_local + 1) of the n hops, the
            # ring analog of the flash forward's O(S * window) tile skip.
            in_band = idx * sq + sq - 1 >= src * sq  # q_hi >= k_lo
            if window is not None:
                in_band = jnp.logical_and(
                    in_band,
                    idx * sq - (src * sq + sq - 1) < window,  # q_lo-k_hi
                )
            o_m_l = jax.lax.cond(in_band, accumulate, lambda c: c, o_m_l)
        else:
            o_m_l = accumulate(o_m_l)

        # rotate KV one hop; skipped after the last accumulation
        def rotate(args):
            k, v, kv_valid = args
            k = jax.lax.ppermute(k, axis, perm)
            v = jax.lax.ppermute(v, axis, perm)
            if kv_valid is not None:
                kv_valid = jax.lax.ppermute(kv_valid, axis, perm)
            return k, v, kv_valid

        k, v, kv_valid = jax.lax.cond(
            t < n - 1, rotate, lambda args: args, (k, v, kv_valid)
        )
        return o_m_l, k, v, kv_valid

    (o, m, l), _, _, _ = jax.lax.fori_loop(
        0, n, body, ((o, m, l), k, v, kv_valid)
    )
    l = jnp.maximum(l, 1e-20)  # fully-masked rows (padding) stay finite
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(out_dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array] = None,
    causal: bool = False,
    mesh: Optional[Mesh] = None,
    axis: str = "seq",
    block_k: int = 1024,
    window: Optional[int] = None,
    scale: Optional[float] = None,
    logit_cap: Optional[float] = None,
) -> jax.Array:
    """[B, S, H, D] attention with S sharded over `axis` of `mesh`.

    Global arrays in, global arrays out — call it like any attention; the
    shard_map inside binds the mesh axes. Degrades to a single local block
    (i.e. plain blockwise attention) when the mesh has no 'seq' axis.
    `block_k` caps the per-chip score-tensor chunk (see _block_attention).
    """
    if mesh is None or axis not in mesh.axis_names:
        raise ValueError(
            f"ring_attention needs a mesh with a {axis!r} axis; use "
            "ops.attention.attention(impl='reference') otherwise"
        )
    if window is not None and (not causal or window < 1):
        raise ValueError(
            f"window={window} requires causal=True and window >= 1"
        )
    kv_valid = None
    if mask is not None:
        if mask.ndim != 4 or mask.shape[1] != 1 or mask.shape[2] != 1:
            raise NotImplementedError(
                "ring attention supports key-padding masks [B,1,1,S] only"
            )
        kv_valid = mask[:, 0, 0, :].astype(jnp.bool_)

    # Note on pp x sp: NESTING this shard_map inside a partial-manual pipe
    # region does not lower (Shardy, jax 0.9, backward residuals) — the
    # composition instead runs the extracted `ring_attention_manual` body
    # directly inside the pipe's FULLY-manual region (models/pipelined.py
    # via parallel/axes.manual_seq), one flat region, AD straight through.
    if q.shape[2] % k.shape[2]:
        raise ValueError(
            f"query heads {q.shape[2]} must be a multiple of kv heads "
            f"{k.shape[2]} (GQA)"
        )
    batch = tuple(a for a in ("data", "fsdp") if a in mesh.axis_names)
    batch = batch if batch else None
    heads = "tensor" if "tensor" in mesh.axis_names else None
    if heads is not None and (q.shape[2] % mesh.shape["tensor"]
                              or k.shape[2] % mesh.shape["tensor"]):
        # GQA under TP: both head counts must divide so each shard keeps
        # whole query groups beside their serving KV heads
        raise NotImplementedError(
            f"ring attention with a 'tensor' axis of {mesh.shape['tensor']} "
            f"needs both q heads ({q.shape[2]}) and kv heads "
            f"({k.shape[2]}) divisible by it"
        )
    qkv_spec = P(batch, axis, heads, None)
    valid_spec = P(batch, axis)

    n = mesh.shape[axis]

    def local(q, k, v, kv_valid):
        # accumulators typed varying over every mesh axis: the incoming
        # q/k/v end up varying over all of them, and the fori_loop carry
        # type check requires input/output variance to match
        return ring_attention_manual(
            q, k, v, kv_valid, causal=causal, axis=axis, ring_size=n,
            block_k=block_k, vary_axes=tuple(mesh.axis_names),
            window=window, scale=scale, logit_cap=logit_cap,
        )

    if kv_valid is None:
        # thread a dummy validity plane so the shard_map signature is static
        def local2(q, k, v):
            return local(q, k, v, None)

        fn = _compat_shard_map(
            local2, mesh=mesh,
            in_specs=(qkv_spec, qkv_spec, qkv_spec),
            out_specs=qkv_spec,
        )
        return fn(q, k, v)

    fn = _compat_shard_map(
        local, mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, valid_spec),
        out_specs=qkv_spec,
    )
    return fn(q, k, v, kv_valid)
