"""Numerical ops: losses and metrics (attention and Pallas kernels join as
the transformer model families land — SURVEY.md §7 layer order)."""

from tfde_tpu.ops.losses import (  # noqa: F401
    sparse_categorical_crossentropy,
    softmax_cross_entropy_with_integer_labels,
)
from tfde_tpu.ops.metrics import accuracy  # noqa: F401
