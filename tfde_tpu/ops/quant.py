"""Int8 quantized inference — the serving-side compression the TPU rewards.

The reference's serving story is a float SavedModel export
(`/root/reference/mnist_keras_distributed.py:151-162`); for the generative
families this framework adds, decode throughput is bound by weight HBM
traffic (every step reads every parameter once to produce one token per
row). Int8 quantization attacks exactly that bound, TPU-first:

- **W8A8 dynamic**: weights are symmetric per-output-channel int8
  (absmax), activations are quantized per row (per token) on the fly, and
  the matmul runs `lax.dot_general(int8, int8) -> int32` — the v5e MXU's
  int8 path has 2x the bf16 peak, and the weight read from HBM is half the
  bytes. Scales multiply back in fp32 after the dot (one fused elementwise
  pass).
- **Static shapes, one compile**: quantize-dequantize is pure elementwise
  + matmul; the decode scan (inference/decode.py) compiles once, same as
  the fp path.
- **No training**: gradients through `round` are zero; quantized modules
  are serving-only twins. Train in bf16/fp32, `quantize_model` the result.

Usage:
    qmodel, qparams = quantize_model(model, params)       # one call
    tokens, lengths = generate(qmodel, qparams, prompt, ...)

The quantized parameter tree mirrors the fp tree: each projection's
`kernel` becomes `kernel_q` (int8) + `kernel_scale` (fp32, per output
channel); the tied embedding becomes `embedding_q` + per-row `scale`;
biases and norms ride through unchanged in fp32.
"""

from __future__ import annotations

from typing import Sequence, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp


def stochastic_round(x: jax.Array, rng: jax.Array) -> jax.Array:
    """Randomized round-to-integer, unbiased in expectation:
    ``floor(x + u)`` with ``u ~ U[0, 1)``, so ``E[result] == x`` exactly
    (the fractional part rounds up with probability equal to itself).
    Deterministic under a fixed key. Used by the gradient transport
    (parallel/comms.py) — nearest rounding is biased toward the grid,
    and that bias accumulates over an all-reduce where stochastic noise
    averages out across devices and steps."""
    u = jax.random.uniform(rng, x.shape, dtype=jnp.float32)
    return jnp.floor(x.astype(jnp.float32) + u)


def absmax_quantize(
    w: jax.Array, contract_ndim: int, *, rng: jax.Array | None = None
) -> Tuple[jax.Array, jax.Array]:
    """Symmetric int8 quantization of a kernel whose LEADING `contract_ndim`
    axes are contracted (the nn.DenseGeneral layout): returns
    (q int8 [same shape], scale fp32 [w.shape[contract_ndim:]]) with
    `w ~= q * scale` broadcast over the leading axes — one scale per output
    channel, the grain that keeps per-channel dynamic range.

    `rng` switches nearest rounding to `stochastic_round` — the gradient
    quantizer's mode (unbiased in expectation; serving-side weight
    quantization keeps the default nearest mode, which minimizes
    per-tensor error)."""
    w = w.astype(jnp.float32)
    axes = tuple(range(contract_ndim))
    amax = jnp.max(jnp.abs(w), axis=axes)
    scale = jnp.maximum(amax, 1e-12) / 127.0
    rounded = jnp.round(w / scale) if rng is None else stochastic_round(
        w / scale, rng
    )
    q = jnp.clip(rounded, -127, 127).astype(jnp.int8)
    return q, scale


def quantize_activations(x: jax.Array, contract_ndim: int) -> Tuple[jax.Array, jax.Array]:
    """Dynamic per-row (per-token) int8 quantization: absmax over the
    trailing `contract_ndim` axes. Returns (q int8, scale fp32 with the
    contracted axes squeezed out)."""
    xf = x.astype(jnp.float32)
    axes = tuple(range(x.ndim - contract_ndim, x.ndim))
    amax = jnp.max(jnp.abs(xf), axis=axes, keepdims=True)
    scale = jnp.maximum(amax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    return q, jnp.squeeze(scale, axis=axes)


def int8_dot_general(
    x: jax.Array,
    kernel_q: jax.Array,
    kernel_scale: jax.Array,
    contract_ndim: int,
    dtype: jnp.dtype = jnp.bfloat16,
) -> jax.Array:
    """`x @ kernel` with both sides int8 and fp32 rescale after the dot.

    x's trailing `contract_ndim` axes contract against kernel_q's leading
    `contract_ndim` axes (the nn.DenseGeneral convention); kernel_scale is
    per output channel, shape `kernel_q.shape[contract_ndim:]`. The int32
    accumulator is exact (127*127*K fits easily), so the only error is the
    two quantization roundings."""
    xq, x_scale = quantize_activations(x, contract_ndim)
    dims = (
        (tuple(range(x.ndim - contract_ndim, x.ndim)),
         tuple(range(contract_ndim))),
        ((), ()),
    )
    y = jax.lax.dot_general(xq, kernel_q, dims,
                            preferred_element_type=jnp.int32)
    out_ndim = kernel_q.ndim - contract_ndim
    sx = x_scale.reshape(x_scale.shape + (1,) * out_ndim)
    return (y.astype(jnp.float32) * sx * kernel_scale).astype(dtype)


def kv_quantize(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Symmetric int8 KV-cache quantization: one fp32 scale per
    (position, kv-head) — absmax over the trailing head_dim vector, the
    finest grain that still writes one scale cell per cached token (a
    coarser per-block scale would put a read-modify-rescale of the whole
    block on the single-token decode hot path). Returns
    (q int8 [x.shape], scale fp32 [x.shape[:-1]]).

    Non-finite inputs are zeroed before the absmax: junk positions (a
    rider row pad-fed past its committed count) can carry NaN/inf
    activations, and one inf in a head vector would blow that vector's
    scale while a NaN would poison the masked-attention output through
    0 * NaN. Infinities map to 0 rather than nan_to_num's default
    float32-max — max/127 rounds UP, and 127x the rounded-up scale
    overflows straight back to inf on dequant. Zeroing is identity on
    every finite (legit) value, so real tokens quantize bit-identically
    with or without it."""
    xf = jnp.nan_to_num(x.astype(jnp.float32), posinf=0.0, neginf=0.0)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127).astype(jnp.int8)
    return q, scale


def kv_dequantize(q: jax.Array, scale: jax.Array,
                  dtype: jnp.dtype) -> jax.Array:
    """Inverse of `kv_quantize`: `q * scale` broadcast over the trailing
    head_dim axis, cast to the attention compute dtype. Elementwise, so
    XLA fuses it into the attention einsum's operand read — the int8
    wire format never leaves the device program."""
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


class QuantDenseGeneral(nn.Module):
    """Serving twin of `nn.DenseGeneral` over int8 weights.

    Supports exactly the layouts the transformer uses: contraction over the
    trailing input axes (axis=-1 or (-2, -1)), tuple or int `features`.
    Parameters: `kernel_q` int8 [in..., out...], `kernel_scale` fp32
    [out...], optional `bias` fp32 [out...] (same name/shape as the fp
    layer's, so conversion carries it through untouched)."""

    features: Union[int, Sequence[int]]
    axis: Union[int, Sequence[int]] = -1
    use_bias: bool = True
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        feats = (tuple(self.features) if isinstance(self.features, Sequence)
                 else (self.features,))
        axes = (tuple(self.axis) if isinstance(self.axis, Sequence)
                else (self.axis,))
        norm_axes = tuple(sorted(a % x.ndim for a in axes))
        contract_ndim = len(norm_axes)
        if norm_axes != tuple(range(x.ndim - contract_ndim, x.ndim)):
            raise NotImplementedError(
                f"QuantDenseGeneral contracts trailing axes only; got "
                f"axis={self.axis} on a rank-{x.ndim} input"
            )
        in_shape = x.shape[-contract_ndim:]
        kernel_q = self.param("kernel_q", nn.initializers.zeros,
                              in_shape + feats, jnp.int8)
        kernel_scale = self.param("kernel_scale", nn.initializers.ones,
                                  feats, jnp.float32)
        y = int8_dot_general(x, kernel_q, kernel_scale, contract_ndim,
                             dtype=self.dtype)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros, feats,
                              jnp.float32)
            y = y + bias.astype(self.dtype)
        return y


class QuantEmbed(nn.Module):
    """Serving twin of `nn.Embed` with int8 rows and per-row scales.

    The tied LM head (`wte.attend`, models/gpt.py) is the single largest
    matmul weight in a GPT-2-class decode step ([vocab, embed]); `attend`
    runs it as an int8 x int8 dot without materializing a transpose (the
    dot's dimension numbers contract the embed axis in place). The gather
    path dequantizes only the looked-up rows."""

    num_embeddings: int
    features: int
    dtype: jnp.dtype = jnp.bfloat16

    def setup(self):
        self.embedding_q = self.param(
            "embedding_q", nn.initializers.zeros,
            (self.num_embeddings, self.features), jnp.int8,
        )
        self.scale = self.param("scale", nn.initializers.ones,
                                (self.num_embeddings,), jnp.float32)

    def __call__(self, ids: jax.Array) -> jax.Array:
        rows = jnp.take(self.embedding_q, ids, axis=0).astype(jnp.float32)
        return (rows * self.scale[ids][..., None]).astype(self.dtype)

    def attend(self, x: jax.Array) -> jax.Array:
        # [..., E] -> [..., V]: contract x's last axis with embedding axis 1
        xq, x_scale = quantize_activations(x, 1)
        dims = (((x.ndim - 1,), (1,)), ((), ()))
        y = jax.lax.dot_general(xq, self.embedding_q, dims,
                                preferred_element_type=jnp.int32)
        return (y.astype(jnp.float32) * x_scale[..., None] * self.scale
                ).astype(self.dtype)


def quantize_params(qmodel, params):
    """fp params -> the quantized tree `qmodel` (a `.clone(quant='int8')`
    twin) expects. Driven by the quantized model's own abstract param
    structure (`jax.eval_shape` on its init), so every `kernel_q`/
    `kernel_scale`/`embedding_q` slot is filled by quantizing the fp leaf
    at the same path and everything else (biases, norms, wpe, MoE experts)
    is carried through verbatim — no name list to drift out of sync with
    the model code."""
    sample = jnp.zeros((1, 2), jnp.int32)
    expected = jax.eval_shape(
        lambda: qmodel.init(jax.random.key(0), sample)
    )["params"]
    src = params.get("params", params) if isinstance(params, dict) else params
    src = jax.tree_util.tree_map(lambda x: x, src)  # shallow copy / unfreeze

    def build(exp, fp, path):
        if not isinstance(exp, dict):
            if fp is None:
                raise ValueError(f"missing fp parameter at {'/'.join(path)}")
            return jnp.asarray(fp)
        out = {}
        for name, sub in exp.items():
            p = path + (name,)
            if name == "kernel_q":
                w = fp.get("kernel")
                if w is None:
                    raise ValueError(f"no fp kernel to quantize at {'/'.join(path)}")
                contract_ndim = w.ndim - len(exp["kernel_scale"].shape)
                q, s = absmax_quantize(jnp.asarray(w), contract_ndim)
                out["kernel_q"], out["kernel_scale"] = q, s
            elif name == "kernel_scale":
                continue  # produced with kernel_q
            elif name == "embedding_q":
                w = jnp.asarray(fp["embedding"]).astype(jnp.float32)
                amax = jnp.max(jnp.abs(w), axis=1)  # per-row (per-token-id)
                s = jnp.maximum(amax, 1e-12) / 127.0
                out["embedding_q"] = jnp.clip(
                    jnp.round(w / s[:, None]), -127, 127
                ).astype(jnp.int8)
                out["scale"] = s
            elif name == "scale" and "embedding_q" in exp:
                continue  # produced with embedding_q
            else:
                out[name] = build(sub, fp.get(name) if isinstance(fp, dict)
                                  else None, p)
        return out

    return {"params": build(expected, src, ())}


def quantize_model(model, params):
    """One-call quantization: returns (qmodel, qparams) ready for
    inference/decode.generate and friends. `model` must expose a `quant`
    field (the GPT family); `params` is the fp tree ({'params': ...} or
    bare)."""
    if not hasattr(model, "quant"):
        raise ValueError(
            f"{type(model).__name__} has no quant mode — int8 serving is a "
            f"causal-LM capability (models/gpt.GPT)"
        )
    qmodel = model.clone(quant="int8")
    return qmodel, quantize_params(qmodel, params)
