"""Flash attention — Pallas TPU kernel for the long-sequence regime.

Hot-op kernel scope (the reference delegates all kernels to TF's C++ library,
SURVEY.md §2b "Dense/conv/BN kernel library"; here the transformer configs'
attention gets a hand kernel where XLA's default fusion stops helping).

Forward is a Pallas kernel (per /opt/skills/guides/pallas_guide.md):
- grid (batch, heads, Sq/block_q, Sk/block_k) with K minor: one Q tile and
  one K/V tile are VMEM-resident per step (VMEM stays O(block) at any S);
  the online-softmax state persists in VMEM scratch across the K-tile steps
  that revisit the same output block — the [Sq, Sk] score matrix never
  materializes (O(S) memory instead of O(S^2)).
- score matmuls hit the MXU with fp32 accumulation (preferred_element_type),
  tiles default to the largest MXU multiple of 512/256/128 dividing S
  (`_auto_block`: the r04 hardware sweep measured 512-edge tiles
  1.25-1.45x over 128 at every shape tried).
- causal masking predicates whole future K-tiles off (pl.when), halving the
  work for causal models rather than masking it.
- `scale` and `logit_cap` (Gemma-2 tanh softcapping) apply inside the
  kernel, so capped/scaled models stay on the fused path.

Backward DEFAULTS to the blockwise-JAX recurrence (`_bwd_blockwise`):
recompute P tile-by-tile from the saved logsumexp, O(S) memory,
XLA-scheduled matmuls. For causal (and windowed) attention the recurrence
is a `lax.scan` over the STATICALLY enumerated in-band (Q-tile, K-tile)
pairs (`_band_tile_pairs`) — strictly-future tiles and tiles outside the
sliding band are never visited, so compute and DMA drop to ~half for plain
causal and to O(S * window) for windowed, in both the dq and dk/dv
accumulations (they share the pair scan). The non-causal backward keeps
the r04-measured full K-tile scan (tools/flash_ab.py on v5e: 1.15x/1.28x/
1.30x of the XLA reference einsum at S=2048/4096/8192 causal fwd+bwd),
while the round-3 Pallas dK/dV + dQ kernel pair (`TFDE_FLASH_BWD=pallas`,
FlashAttention-2 arrangement, retained below with 128-lane lse/delta
layout and band-aware prefetch index maps) lands at 0.6-0.73x — XLA's own
scheduling of the same recurrence beats the hand pipeline on this chip
generation, so the kernel pair is opt-in until it wins a measurement.

The band membership predicate (`_tile_in_band`) is shared by the forward
kernel, both backward paths, the DMA-eliding index maps, and the roofline
tile-visit counter (ops/roofline.py) — one source of truth, so a counter
regression in tier-1 means the kernels' schedule actually changed.

Ring attention (ops/ring_attention.py) composes with this by construction:
its per-device block computation is the same recurrence, so the flash kernel
can serve as its local step on TPU.
"""

from __future__ import annotations

import contextlib
import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG = -1e30

# Trace-time tile-visit recorder (see `record_tile_visits`). None when
# disabled; a dict while a recording context is open.
_TILE_COUNTS = None


@contextlib.contextmanager
def record_tile_visits():
    """Record the tile schedule of flash calls traced inside the context.

    Yields a dict that the forward/backward builders populate at TRACE
    time with the statically-known schedule: number of grid steps, number
    of in-band (executed) tile visits per pass, and the resolved tile
    sizes. Because `pl.when` predication and the backward pair-scan length
    are decided by the same `_tile_in_band` predicate recorded here, these
    numbers are exactly the tiles the compiled kernels execute. The
    causal/windowed backward additionally bumps `bwd_steps_executed` from
    inside the scan body via `jax.debug.callback`, giving a runtime-
    executed corroboration of the static plan.

    Recording happens when the call is traced — call the kernels directly
    (or with fresh shapes) inside the context rather than through an
    already-warm jit cache."""
    global _TILE_COUNTS
    prev = _TILE_COUNTS
    _TILE_COUNTS = {}
    try:
        yield _TILE_COUNTS
    finally:
        _TILE_COUNTS = prev


def _auto_block(s: int) -> int:
    """Default tile edge: the largest MXU-multiple that divides S.

    The r04 hardware sweep (v5e, causal fwd+bwd, h=12 d=64) measured
    512x512 tiles 1.25-1.45x faster than the original 128x128 at every
    shape tried (b1-b4, S=2048-8192, windowed, GQA) — fewer grid steps
    amortize the per-tile online-softmax state updates, and a 512-row
    MXU operand keeps the systolic array busier. Explicit block_q/block_k
    still override (tests use small tiles to exercise multi-block paths
    at small S).

    A sliding window does NOT cap the edge: a tile wider than the band
    runs more in-band columns per Q row (~block + window), but the
    hardware A/B at the worst case (window=128, S=4096) still put 512
    tiles ahead — 2.32 vs 3.13 ms forward-only, 6.69 vs 7.26 ms fwd+bwd
    — per-tile efficiency outweighs the extra span on this chip."""
    for bl in (512, 256, 128):
        if s % bl == 0:
            return bl
    return min(s, 128)


def _resolve_block(block, s: int) -> int:
    """The one resolution rule for every kernel entry point: None -> the
    measured auto default; explicit -> clamped to S. Keeping this single
    prevents forward/backward tile defaults from silently diverging."""
    return _auto_block(s) if block is None else min(block, s)


def _tile_in_band(qi, kb, block_q: int, block_k: int, causal, window):
    """Whether tile (qi, kb) holds any unmasked (row, col) pair.

    THE band predicate: the forward kernel's `pl.when`, both backward
    paths, the DMA-eliding index maps, and the roofline counter all derive
    from this one function. Works on Python ints (static planning) and on
    traced scalars (inside kernels) alike. A K tile is live iff its first
    column is not strictly past the Q tile's last row, and — with a
    sliding window — its last column is not entirely older than the
    oldest position the Q tile's first row can see."""
    if not causal:
        return True
    live = kb * block_k <= (qi + 1) * block_q - 1
    if window is not None:
        live = (kb * block_k + block_k - 1 >= qi * block_q - (window - 1)) & live
    return live


def _band_tile_pairs(s: int, block_q: int, block_k: int, causal: bool,
                     window) -> list:
    """Statically enumerate the in-band (qi, kb) tile pairs for an S x S
    attention. Plain-causal yields ~half the grid; a sliding window yields
    O(window / block_k) + O(1) pairs per Q tile. The causal backward scans
    exactly this list, so its length IS the executed tile-visit count."""
    n_q, n_k = s // block_q, s // block_k
    return [
        (qi, kb)
        for qi in range(n_q)
        for kb in range(n_k)
        if bool(_tile_in_band(qi, kb, block_q, block_k, causal, window))
    ]


def bwd_tile_plan(s: int, block_q=None, block_k=None, causal: bool = True,
                  window=None) -> dict:
    """Public schedule introspection for tools/tests (roofline counter).

    Returns the resolved tile sizes, the full grid size per pass, and the
    in-band pairs the causal backward will actually scan — computed from
    the same `_tile_in_band` predicate the kernels branch on."""
    bq = _resolve_block(block_q, s)
    bk = _resolve_block(block_k, s)
    pairs = _band_tile_pairs(s, bq, bk, causal, window)
    n_q, n_k = s // bq, s // bk
    per_q = [0] * n_q
    per_k = [0] * n_k
    for qi, kb in pairs:
        per_q[qi] += 1
        per_k[kb] += 1
    return {
        "block_q": bq,
        "block_k": bk,
        "grid": n_q * n_k,
        "visits": len(pairs),
        "pairs": pairs,
        "max_visits_per_q_tile": max(per_q) if per_q else 0,
        "max_visits_per_k_tile": max(per_k) if per_k else 0,
    }


def _apply_cap(z, logit_cap):
    """tanh softcapping (Gemma-2): c = cap * tanh(z / cap). Returns the
    capped logits and tanh(z/cap) (needed by the backward chain rule:
    dc/dz = 1 - tanh^2)."""
    t = jnp.tanh(z / logit_cap)
    return logit_cap * t, t


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
    *, causal, scale, window, logit_cap,
):
    # BHSD layout, grid (B, H, Sq/bq, Sk/bk) with the K dimension minor:
    # q_ref [1, 1, bq, D]; k_ref/v_ref [1, 1, bk, D] — only one K/V tile is
    # VMEM-resident at a time, so VMEM stays O(block) at any S. The online-
    # softmax state (acc/m/l) lives in VMEM scratch, which persists across
    # the kb grid steps that revisit the same (b, h, qi) output block.
    qi = pl.program_id(2)
    kb = pl.program_id(3)
    num_kb = pl.num_programs(3)
    bq = q_ref.shape[2]
    bk = k_ref.shape[2]

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG)
        l_ref[:] = jnp.zeros_like(l_ref)

    def _step():
        q = q_ref[0, 0]  # [bq, D]
        k_blk = k_ref[0, 0]
        v_blk = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [bq, bk]
        if logit_cap is not None:
            s, _ = _apply_cap(s, logit_cap)
        if causal:
            rows = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = kb * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            keep = rows >= cols
            if window is not None:
                # sliding band: row i sees cols in (i - window, i]
                keep = jnp.logical_and(keep, rows - cols < window)
            s = jnp.where(keep, s, _NEG)
        m_prev = m_ref[:, 0:1]  # [bq, 1]
        l_prev = l_ref[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_ref[:] = jnp.broadcast_to(
            l_prev * corr + jnp.sum(p, axis=-1, keepdims=True), l_ref.shape
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        # K-tiles strictly past this Q-tile's last row contribute nothing;
        # with a sliding window, neither do tiles entirely older than the
        # oldest position the tile's first row can see
        # An interior/diagonal split (mask only the straddling tiles) was
        # measured 3-4% SLOWER at 512 tiles on v5e — the duplicated step
        # body costs more than the iota/select it saves; keep one body.
        pl.when(_tile_in_band(qi, kb, bq, bk, True, window))(_step)
    else:
        _step()

    @pl.when(kb == num_kb - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, 0:1], 1e-20)
        o_ref[0, 0] = (acc_ref[:] / l).astype(o_ref.dtype)
        lse_ref[0, 0] = m_ref[:, 0:1] + jnp.log(l)


def _flash_forward(
    q: jax.Array, k: jax.Array, v: jax.Array,
    causal: bool, block_q: int, block_k: int, interpret: bool,
    window=None, scale=None, logit_cap=None,
) -> Tuple[jax.Array, jax.Array]:
    b, s, h, d = q.shape
    if k.shape != v.shape:
        raise ValueError(f"k {k.shape} and v {v.shape} must match")
    kv = k.shape[2]
    if k.shape[0] != b or k.shape[1] != s or k.shape[3] != d:
        # All tiling below derives from q.shape; a cross-attention call with
        # longer K/V would silently attend over the wrong range (ADVICE r1).
        raise ValueError(
            f"flash_attention requires self-attention shapes: q {q.shape}, "
            f"k {k.shape}, v {v.shape}; use impl='reference' for "
            f"cross-attention (Sk != Sq)"
        )
    if h % kv:
        raise ValueError(
            f"query heads {h} must be a multiple of kv heads {kv} (GQA)"
        )
    # GQA: the grid stays per-QUERY-head; each q head's K/V index map folds
    # onto its serving KV head (hi // group). The kernel body never sees the
    # grouping, and the [B,S,H,D] K/V expansion of a repeat-then-attend
    # formulation never exists in HBM — the bandwidth saving GQA is for.
    group = h // kv
    auto_blocks = block_q is None and block_k is None
    block_q = _resolve_block(block_q, s)
    block_k = _resolve_block(block_k, s)
    if s % block_q or s % block_k:
        raise ValueError(
            f"sequence length {s} is not divisible by the kernel tile "
            f"sizes ({block_q}, {block_k})"
            + (" chosen automatically — flash attention needs S to be a "
               "multiple of 128; pad the sequence or use impl='reference'"
               if auto_blocks else " — pass block_q/block_k that divide S")
        )
    if window is not None and (not causal or window < 1):
        raise ValueError(
            f"window={window} requires causal=True and window >= 1"
        )
    if logit_cap is not None and logit_cap <= 0:
        raise ValueError(f"logit_cap={logit_cap} must be positive")
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if _TILE_COUNTS is not None:
        n_q, n_k = s // block_q, s // block_k
        _TILE_COUNTS["fwd_grid"] = n_q * n_k
        _TILE_COUNTS["fwd_visits"] = len(
            _band_tile_pairs(s, block_q, block_k, causal, window)
        )
        _TILE_COUNTS["block_q"] = block_q
        _TILE_COUNTS["block_k"] = block_k
    kernel = functools.partial(_fwd_kernel, causal=causal, scale=scale,
                               window=window, logit_cap=logit_cap)
    # BSHD -> BHSD so the S/D dims are the TPU-tiled trailing pair
    qt, kt, vt = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
    from jax.experimental.pallas import tpu as pltpu

    if causal:
        # skipped K-tiles (strictly past the Q-tile's last row, or — with a
        # sliding window — entirely older than the band) must not spend
        # DMA: point their index map at an in-band tile the pipeline will
        # need anyway; repeat fetches are elided, so masked-off steps cost
        # ~nothing instead of a dead K/V copy
        def kv_idx(bi, hi, qi, kb):
            run = kb * block_k <= (qi + 1) * block_q - 1
            if window is None:
                first = 0
            else:
                pre_band = (
                    kb * block_k + block_k - 1 < qi * block_q - (window - 1)
                )
                first = jnp.maximum(
                    (qi * block_q - (window - 1)) // block_k, 0
                )
                # post-diagonal skipped steps park on the just-used diagonal
                # tile (fetch elided), NOT on first(qi) — that tile already
                # passed, so pointing back at it would issue one dead
                # block_k x d DMA per Q-row; pre-band skipped steps park on
                # first(qi), the tile the first in-band step needs anyway
                diag = ((qi + 1) * block_q - 1) // block_k
                return (
                    bi, hi // group,
                    jnp.where(run, jnp.where(pre_band, first, kb), diag),
                    0,
                )
            return (bi, hi // group, jax.lax.select(run, kb, first), 0)
    else:
        def kv_idx(bi, hi, qi, kb):
            return (bi, hi // group, kb, 0)

    grid = (b, h, s // block_q, s // block_k)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi, kb: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d), kv_idx),
            pl.BlockSpec((1, 1, block_k, d), kv_idx),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi, kb: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda bi, hi, qi, kb: (bi, hi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, s, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),    # acc
            pltpu.VMEM((block_q, 128), jnp.float32),  # m (col 0 used)
            pltpu.VMEM((block_q, 128), jnp.float32),  # l (col 0 used)
        ],
        interpret=interpret,
    )(qt, kt, vt)
    return jnp.swapaxes(out, 1, 2), lse[..., 0]


def _bwd_pair_scan(res, g, *, block_q: int, block_k: int, window=None,
                   scale=None, logit_cap=None):
    """Causal/windowed backward: lax.scan over the statically enumerated
    in-band (Q-tile, K-tile) pairs, skipping strictly-future and
    out-of-band tiles entirely — compute AND the q/k/v/dO tile loads drop
    to ~half for plain causal and to O(S * window) for windowed, in both
    the dq and dk/dv accumulations (one scan serves both).

    Handles MHA and GQA uniformly: q is viewed [B,S,Kv,Grp,D] (Grp = 1 for
    MHA); dK/dV sum over each KV head's query group inside the contraction
    so the [B,S,H,D] K/V expansion never materializes. The carry holds the
    full fp32 dq/dk/dv; each step read-modify-writes one tile via
    dynamic_slice / dynamic_update_slice."""
    q, k, v, out, lse = res
    b, s, h, d = q.shape
    kv = k.shape[2]
    grp = h // kv
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    pairs = _band_tile_pairs(s, block_q, block_k, True, window)

    qf = q.astype(jnp.float32).reshape(b, s, kv, grp, d)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    gf = g.astype(jnp.float32).reshape(b, s, kv, grp, d)
    # delta[b,c,g,i] = rowsum(dO * O); lse arrives [b,h,s] -> [b,c,g,s]
    delta = jnp.einsum(
        "bscgd,bscgd->bcgs", gf,
        out.astype(jnp.float32).reshape(b, s, kv, grp, d),
    )
    lse4 = lse.reshape(b, kv, grp, s)

    counts = _TILE_COUNTS
    if counts is not None:
        counts["bwd_grid"] = (s // block_q) * (s // block_k)
        counts["bwd_dq_visits"] = len(pairs)
        counts["bwd_dkv_visits"] = len(pairs)
        counts["bwd_pairs"] = len(pairs)

        def _bump():
            counts["bwd_steps_executed"] = (
                counts.get("bwd_steps_executed", 0) + 1
            )

    def step(carry, pair):
        dq, dk, dv = carry
        if counts is not None:
            jax.debug.callback(_bump)
        qs = pair[0] * block_q
        ks = pair[1] * block_k
        qt = jax.lax.dynamic_slice_in_dim(qf, qs, block_q, axis=1)
        gt = jax.lax.dynamic_slice_in_dim(gf, qs, block_q, axis=1)
        lt = jax.lax.dynamic_slice_in_dim(lse4, qs, block_q, axis=3)
        dt = jax.lax.dynamic_slice_in_dim(delta, qs, block_q, axis=3)
        kt = jax.lax.dynamic_slice_in_dim(kf, ks, block_k, axis=1)
        vt = jax.lax.dynamic_slice_in_dim(vf, ks, block_k, axis=1)
        z = jnp.einsum("bqcgd,bkcd->bcgqk", qt, kt,
                       preferred_element_type=jnp.float32) * scale
        if logit_cap is not None:
            logits, t = _apply_cap(z, logit_cap)
        else:
            logits = z
        rows = qs + jnp.arange(block_q)
        cols = ks + jnp.arange(block_k)
        keep = rows[:, None] >= cols[None, :]
        if window is not None:
            keep = jnp.logical_and(
                keep, rows[:, None] - cols[None, :] < window
            )
        logits = jnp.where(keep, logits, _NEG)
        p = jnp.exp(logits - lt[..., None])  # [b,c,g,bq,bk]
        dv_t = jnp.einsum("bcgqk,bqcgd->bkcd", p, gt)
        dp = jnp.einsum("bqcgd,bkcd->bcgqk", gt, vt)
        ds = p * (dp - dt[..., None])
        if logit_cap is not None:
            # chain rule through c = cap * tanh(z / cap): dc/dz = 1 - t^2
            # (masked entries have p = 0, hence ds = 0, regardless of t)
            ds = ds * (1.0 - t * t)
        dq_t = jnp.einsum("bcgqk,bkcd->bqcgd", ds, kt) * scale
        dk_t = jnp.einsum("bcgqk,bqcgd->bkcd", ds, qt) * scale
        dq = jax.lax.dynamic_update_slice_in_dim(
            dq, jax.lax.dynamic_slice_in_dim(dq, qs, block_q, axis=1) + dq_t,
            qs, axis=1,
        )
        dk = jax.lax.dynamic_update_slice_in_dim(
            dk, jax.lax.dynamic_slice_in_dim(dk, ks, block_k, axis=1) + dk_t,
            ks, axis=1,
        )
        dv = jax.lax.dynamic_update_slice_in_dim(
            dv, jax.lax.dynamic_slice_in_dim(dv, ks, block_k, axis=1) + dv_t,
            ks, axis=1,
        )
        return (dq, dk, dv), None

    carry0 = (
        jnp.zeros((b, s, kv, grp, d), jnp.float32),
        jnp.zeros((b, s, kv, d), jnp.float32),
        jnp.zeros((b, s, kv, d), jnp.float32),
    )
    (dq, dk, dv), _ = jax.lax.scan(
        step, carry0, jnp.asarray(pairs, dtype=jnp.int32)
    )
    return (
        dq.reshape(b, s, h, d).astype(q.dtype),
        dk.astype(k.dtype),
        dv.astype(v.dtype),
    )


def _bwd_blockwise(res, g, *, causal: bool, block_q=None, block_k=None,
                   window=None, scale=None, logit_cap=None):
    """Blockwise JAX backward: recompute P tile-by-tile from the saved
    logsumexp (standard flash-attention backward), O(S) memory. Causal
    (and windowed) routes to the in-band pair scan, which never visits
    out-of-band tiles; non-causal keeps the measured full K-tile scan."""
    q, k, v, out, lse = res
    b, s, h, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    block_k = _resolve_block(block_k, s)
    if causal:
        return _bwd_pair_scan(
            res, g, block_q=_resolve_block(block_q, s), block_k=block_k,
            window=window, scale=scale, logit_cap=logit_cap,
        )
    if k.shape[2] != h:
        return _bwd_blockwise_grouped(res, g, block_k=block_k, scale=scale,
                                      logit_cap=logit_cap)

    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    # delta[b,h,i] = rowsum(dO * O)
    delta = jnp.einsum("bshd,bshd->bhs", gf, out.astype(jnp.float32))

    def step(carry, kb):
        dq = carry
        sl = jax.lax.dynamic_slice_in_dim(kf, kb * block_k, block_k, axis=1)
        vl = jax.lax.dynamic_slice_in_dim(vf, kb * block_k, block_k, axis=1)
        z = jnp.einsum("bqhd,bkhd->bhqk", qf, sl,
                       preferred_element_type=jnp.float32) * scale
        if logit_cap is not None:
            logits, t = _apply_cap(z, logit_cap)
        else:
            logits = z
        p = jnp.exp(logits - lse[..., None])  # [b,h,Sq,bk]
        dv = jnp.einsum("bhqk,bqhd->bkhd", p, gf)
        dp = jnp.einsum("bqhd,bkhd->bhqk", gf, vl)
        ds = p * (dp - delta[..., None])  # [b,h,Sq,bk]
        if logit_cap is not None:
            ds = ds * (1.0 - t * t)
        dq = dq + jnp.einsum("bhqk,bkhd->bqhd", ds, sl) * scale
        dk = jnp.einsum("bhqk,bqhd->bkhd", ds, qf) * scale
        return dq, (dk, dv)

    n_kb = s // block_k
    dq0 = jnp.zeros_like(qf)
    dq, (dks, dvs) = jax.lax.scan(step, dq0, jnp.arange(n_kb))
    dk = dks.transpose(1, 0, 2, 3, 4).reshape(b, s, h, d)
    dv = dvs.transpose(1, 0, 2, 3, 4).reshape(b, s, h, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _bwd_blockwise_grouped(res, g, *, block_k: int, scale=None,
                           logit_cap=None):
    """GQA twin of the non-causal `_bwd_blockwise` scan: q [B,S,H,D]
    against k/v [B,S,Kv,D] with H = Kv * groups. Query heads carry an
    explicit group axis through the einsums (`c` = kv head, `g` = group
    member), so dK/dV sum over each KV head's query group inside the
    contraction and the [B,S,H,D] K/V expansion never materializes —
    mirroring grouped_attention (ops/attention.py). Causal/windowed GQA
    goes through `_bwd_pair_scan` instead (same grouped einsums, in-band
    tiles only)."""
    q, k, v, out, lse = res
    b, s, h, d = q.shape
    kv = k.shape[2]
    grp = h // kv
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    # block_k arrives already resolved by _bwd_blockwise (the only caller)

    qf = q.astype(jnp.float32).reshape(b, s, kv, grp, d)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    gf = g.astype(jnp.float32).reshape(b, s, kv, grp, d)
    # delta[b,c,g,i] = rowsum(dO * O); lse arrives [b,h,s] -> [b,c,g,s]
    delta = jnp.einsum(
        "bscgd,bscgd->bcgs", gf,
        out.astype(jnp.float32).reshape(b, s, kv, grp, d),
    )
    lse5 = lse.reshape(b, kv, grp, s)

    def step(carry, kb):
        dq = carry
        sl = jax.lax.dynamic_slice_in_dim(kf, kb * block_k, block_k, axis=1)
        vl = jax.lax.dynamic_slice_in_dim(vf, kb * block_k, block_k, axis=1)
        z = jnp.einsum("bqcgd,bkcd->bcgqk", qf, sl,
                       preferred_element_type=jnp.float32) * scale
        if logit_cap is not None:
            logits, t = _apply_cap(z, logit_cap)
        else:
            logits = z
        p = jnp.exp(logits - lse5[..., None])  # [b,c,g,Sq,bk]
        dv = jnp.einsum("bcgqk,bqcgd->bkcd", p, gf)
        dp = jnp.einsum("bqcgd,bkcd->bcgqk", gf, vl)
        ds = p * (dp - delta[..., None])
        if logit_cap is not None:
            ds = ds * (1.0 - t * t)
        dq = dq + jnp.einsum("bcgqk,bkcd->bqcgd", ds, sl) * scale
        dk = jnp.einsum("bcgqk,bqcgd->bkcd", ds, qf) * scale
        return dq, (dk, dv)

    n_kb = s // block_k
    dq0 = jnp.zeros_like(qf)
    dq, (dks, dvs) = jax.lax.scan(step, dq0, jnp.arange(n_kb))
    dk = dks.transpose(1, 0, 2, 3, 4).reshape(b, s, kv, d)
    dv = dvs.transpose(1, 0, 2, 3, 4).reshape(b, s, kv, d)
    return (
        dq.reshape(b, s, h, d).astype(q.dtype),
        dk.astype(k.dtype),
        dv.astype(v.dtype),
    )


def _dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_acc, dv_acc, *, causal, scale, window, logit_cap,
):
    # grid (B, H, Sk/bk, Sq/bq) with the Q dimension minor: one K/V tile's
    # gradient accumulators live in VMEM scratch while every Q tile streams
    # past; refs are BHSD tiles [1, 1, bq|bk, D], lse/delta [1, 1, bq, 1].
    kb = pl.program_id(2)
    qi = pl.program_id(3)
    num_qi = pl.num_programs(3)
    bq = q_ref.shape[2]
    bk = k_ref.shape[2]

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _step():
        q = q_ref[0, 0]          # [bq, D]
        k_blk = k_ref[0, 0]      # [bk, D]
        v_blk = v_ref[0, 0]
        do = do_ref[0, 0]        # [bq, D]
        # lse/delta arrive broadcast to 128 lanes (layout, not data — a
        # [bq, 1]-minor tile would force Mosaic's degenerate-lane path);
        # col 0 carries the value
        lse = lse_ref[0, 0, :, 0:1]      # [bq, 1]
        delta = delta_ref[0, 0, :, 0:1]  # [bq, 1]
        z = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [bq, bk]
        if logit_cap is not None:
            s, t = _apply_cap(z, logit_cap)
        else:
            s = z
        if causal:
            rows = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = kb * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            keep = rows >= cols
            if window is not None:
                keep = jnp.logical_and(keep, rows - cols < window)
            s = jnp.where(keep, s, _NEG)
        p = jnp.exp(s - lse)  # [bq, bk]
        # dV += P^T dO
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # dP = dO V^T ; dS = P * (dP - delta) [* (1 - tanh^2) under cap]
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta)
        if logit_cap is not None:
            ds = ds * (1.0 - t * t)
        ds = ds * scale  # [bq, bk]
        # dK += dS^T Q
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        # Q tiles strictly above this K tile's first column see none of it;
        # with a window, neither do Q tiles entirely past the band
        pl.when(_tile_in_band(qi, kb, bq, bk, True, window))(_step)
    else:
        _step()

    @pl.when(qi == num_qi - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc,
    *, causal, scale, window, logit_cap,
):
    # grid (B, H, Sq/bq, Sk/bk) with K minor: one Q tile's dQ accumulates in
    # VMEM scratch while K/V tiles stream past (same traversal as forward).
    qi = pl.program_id(2)
    kb = pl.program_id(3)
    num_kb = pl.num_programs(3)
    bq = q_ref.shape[2]
    bk = k_ref.shape[2]

    @pl.when(kb == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def _step():
        q = q_ref[0, 0]
        k_blk = k_ref[0, 0]
        v_blk = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0, :, 0:1]      # 128-lane broadcast, col 0 (see
        delta = delta_ref[0, 0, :, 0:1]  # _dkv_kernel)
        z = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if logit_cap is not None:
            s, t = _apply_cap(z, logit_cap)
        else:
            s = z
        if causal:
            rows = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = kb * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            keep = rows >= cols
            if window is not None:
                keep = jnp.logical_and(keep, rows - cols < window)
            s = jnp.where(keep, s, _NEG)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta)
        if logit_cap is not None:
            ds = ds * (1.0 - t * t)
        ds = ds * scale  # [bq, bk]
        dq_acc[:] = dq_acc[:] + jax.lax.dot_general(
            ds.astype(k_blk.dtype), k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        pl.when(_tile_in_band(qi, kb, bq, bk, True, window))(_step)
    else:
        _step()

    @pl.when(kb == num_kb - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_pallas(res, g, *, causal: bool, block_q: int, block_k: int,
                interpret: bool, window=None, scale=None, logit_cap=None):
    """FlashAttention-2 backward: dK/dV kernel + dQ kernel, O(S) memory."""
    q, k, v, out, lse = res
    b, s, h, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    block_q = _resolve_block(block_q, s)
    block_k = _resolve_block(block_k, s)
    from jax.experimental.pallas import tpu as pltpu

    if _TILE_COUNTS is not None:
        n_q, n_k = s // block_q, s // block_k
        visits = len(_band_tile_pairs(s, block_q, block_k, causal, window))
        _TILE_COUNTS["bwd_grid"] = n_q * n_k
        _TILE_COUNTS["bwd_dq_visits"] = visits
        _TILE_COUNTS["bwd_dkv_visits"] = visits

    # delta[b,h,s] = rowsum(dO * O), fp32 — cheap elementwise, stays in JAX
    delta = jnp.einsum(
        "bshd,bshd->bhs", g.astype(jnp.float32), out.astype(jnp.float32)
    )
    # BSHD -> BHSD tiles; lse/delta broadcast to 128 lanes (the official
    # TPU-kernel convention, MIN_BLOCK_SIZE lanes): a [*, 1]-minor block
    # would put every per-step load on Mosaic's degenerate-lane layout
    lanes = 128
    qt, kt, vt, gt = (jnp.swapaxes(t, 1, 2) for t in (q, k, v, g))
    lse4 = jnp.broadcast_to(lse[..., None], (b, h, s, lanes))
    delta4 = jnp.broadcast_to(delta[..., None], (b, h, s, lanes))

    def tile(n, idx):
        return pl.BlockSpec((1, 1, n, d), idx)

    def col(n, idx):
        return pl.BlockSpec((1, 1, n, lanes), idx)

    num_qi = s // block_q
    if causal:
        # Q tiles strictly above the K tile's first column are masked off —
        # prefetch the first contributing Q tile instead of a dead copy;
        # with a window, Q tiles entirely past the band park on the
        # just-used last in-band tile (fetch elided) the same way the
        # forward parks post-diagonal K tiles
        def kq_q(bi, hi, kb, qi):
            run = (qi + 1) * block_q - 1 >= kb * block_k
            first = (kb * block_k) // block_q
            if window is None:
                return (bi, hi, jax.lax.select(run, qi, first), 0)
            post = qi * block_q > kb * block_k + block_k - 1 + (window - 1)
            run = jnp.logical_and(run, jnp.logical_not(post))
            last = jnp.minimum(
                (kb * block_k + block_k - 1 + (window - 1)) // block_q,
                num_qi - 1,
            )
            return (
                bi, hi,
                jnp.where(run, qi, jnp.where(post, last, first)),
                0,
            )
    else:
        def kq_q(bi, hi, kb, qi):
            return (bi, hi, qi, 0)

    kq_k = lambda bi, hi, kb, qi: (bi, hi, kb, 0)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, causal=causal, scale=scale,
                          window=window, logit_cap=logit_cap),
        grid=(b, h, s // block_k, s // block_q),
        in_specs=[
            tile(block_q, kq_q),   # q
            tile(block_k, kq_k),   # k
            tile(block_k, kq_k),   # v
            tile(block_q, kq_q),   # dO
            col(block_q, kq_q),    # lse
            col(block_q, kq_q),    # delta
        ],
        out_specs=[tile(block_k, kq_k), tile(block_k, kq_k)],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, s, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt, gt, lse4, delta4)

    qk_q = lambda bi, hi, qi, kb: (bi, hi, qi, 0)
    if causal:
        # K tiles strictly past the Q tile's last row: prefetch the next
        # needed tile instead of a dead copy — mirrors the forward's
        # parking (plain causal: tile 0, the next Q tile's first step;
        # windowed: pre-band parks on first(qi), post-diagonal parks on
        # the just-used diagonal tile)
        def qk_k(bi, hi, qi, kb):
            run = kb * block_k <= (qi + 1) * block_q - 1
            if window is None:
                return (bi, hi, jax.lax.select(run, kb, 0), 0)
            pre_band = (
                kb * block_k + block_k - 1 < qi * block_q - (window - 1)
            )
            run = jnp.logical_and(run, jnp.logical_not(pre_band))
            first = jnp.maximum((qi * block_q - (window - 1)) // block_k, 0)
            diag = ((qi + 1) * block_q - 1) // block_k
            return (
                bi, hi,
                jnp.where(run, kb, jnp.where(pre_band, first, diag)),
                0,
            )
    else:
        qk_k = lambda bi, hi, qi, kb: (bi, hi, kb, 0)
    (dq,) = pl.pallas_call(
        functools.partial(_dq_kernel, causal=causal, scale=scale,
                          window=window, logit_cap=logit_cap),
        grid=(b, h, s // block_q, s // block_k),
        in_specs=[
            tile(block_q, qk_q),
            tile(block_k, qk_k),
            tile(block_k, qk_k),
            tile(block_q, qk_q),
            col(block_q, qk_q),
            col(block_q, qk_q),
        ],
        out_specs=[tile(block_q, qk_q)],
        out_shape=[jax.ShapeDtypeStruct((b, h, s, d), q.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, gt, lse4, delta4)

    return (
        jnp.swapaxes(dq, 1, 2),
        jnp.swapaxes(dk, 1, 2),
        jnp.swapaxes(dv, 1, 2),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    block_q=None,
    block_k=None,
    interpret: bool = False,
    window=None,
    scale=None,
    logit_cap=None,
) -> jax.Array:
    """softmax(cap(QK^T * scale))V over [B, S, H, D], O(S) memory.

    GQA: k/v may carry fewer heads [B, S, Kv, D] with H a multiple of Kv —
    the grid stays per-query-head and each q head's K/V DMA folds onto its
    serving KV head, so the repeat-expanded K/V never exists in HBM.

    window: sliding-window band (requires causal) — position i attends the
    last `window` positions inclusive; out-of-band K tiles are skipped
    entirely (compute AND DMA) in BOTH the forward and the backward, so
    fwd+bwd cost drops to O(S * window).

    scale: logit multiplier, default 1/sqrt(D).
    logit_cap: Gemma-2 tanh softcapping — logits become
    cap * tanh(logits / cap) inside the kernels (forward and backward),
    before masking."""
    out, _ = _flash_forward(q, k, v, causal, block_q, block_k, interpret,
                            window, scale, logit_cap)
    return out


def _fwd(q, k, v, causal, block_q, block_k, interpret, window, scale,
         logit_cap):
    out, lse = _flash_forward(q, k, v, causal, block_q, block_k, interpret,
                              window, scale, logit_cap)
    return out, (q, k, v, out, lse)


def _bwd(causal, block_q, block_k, interpret, window, scale, logit_cap,
         res, g):
    from tfde_tpu import knobs

    # default 'jax' (blockwise): the r04 hardware A/B (tools/flash_ab.py,
    # v5e) times it at 1.15-1.30x of the XLA reference einsum while the
    # Pallas dKV/dQ pair — even with 128-lane lse/delta layout and causal
    # prefetch maps — lands at 0.6-0.73x. Same O(S) memory either way;
    # TFDE_FLASH_BWD=pallas keeps the kernel pair selectable.
    q, k = res[0], res[1]
    if (knobs.env_choice("TFDE_FLASH_BWD") == "pallas"
            and k.shape[2] == q.shape[2]):
        # the kernel pair is MHA-only (its dK/dV out specs are per-q-head;
        # GQA would need a cross-head reduction) — GQA always takes the
        # blockwise recurrence, which is also the measured-faster default
        return _bwd_pallas(res, g, causal=causal, block_q=block_q,
                           block_k=block_k, interpret=interpret,
                           window=window, scale=scale, logit_cap=logit_cap)
    return _bwd_blockwise(res, g, causal=causal, block_q=block_q,
                          block_k=block_k, window=window, scale=scale,
                          logit_cap=logit_cap)


flash_attention.defvjp(_fwd, _bwd)
