"""Flash attention — Pallas TPU kernel for the long-sequence regime.

Hot-op kernel scope (the reference delegates all kernels to TF's C++ library,
SURVEY.md §2b "Dense/conv/BN kernel library"; here the transformer configs'
attention gets a hand kernel where XLA's default fusion stops helping).

Forward is a Pallas kernel (per /opt/skills/guides/pallas_guide.md):
- grid (batch, heads, Sq/block_q); the Q tile stays VMEM-resident while an
  inner fori_loop walks K/V tiles with the online-softmax recurrence — the
  [Sq, Sk] score matrix never materializes (O(S) memory instead of O(S^2)).
- score matmuls hit the MXU with fp32 accumulation (preferred_element_type),
  tiles default 128x128 — the MXU's native shape.
- causal masking skips whole future K-blocks (the loop bound shrinks per
  Q-block), halving the work for causal models rather than masking it.

Backward is blockwise JAX (custom_vjp): recompute P per K-tile from the
saved logsumexp under lax.scan — also O(S) memory, XLA-fused matmuls. A
Pallas backward is a later optimization; the contract (numerics + memory
scaling) is already met.

Ring attention (ops/ring_attention.py) composes with this by construction:
its per-device block computation is the same recurrence, so the flash kernel
can serve as its local step on TPU.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k, causal, scale):
    # BHSD layout: q_ref [1, 1, bq, D]; k_ref/v_ref [1, 1, S, D];
    # o_ref [1, 1, bq, D]; lse_ref [1, 1, bq, 1] — the trailing singleton
    # keeps the block's last-two dims TPU-tileable (bq % 8 == 0, 1 == dim).
    qi = pl.program_id(2)
    bq = q_ref.shape[2]
    sk = k_ref.shape[2]
    d = q_ref.shape[-1]
    q = q_ref[0, 0]  # [bq, D]

    acc = jnp.zeros((bq, d), jnp.float32)
    m = jnp.full((bq,), _NEG, jnp.float32)
    l = jnp.zeros((bq,), jnp.float32)

    if causal:
        # K-blocks strictly past this Q-tile's last row contribute nothing
        num_kb = pl.cdiv((qi + 1) * bq, block_k)
    else:
        num_kb = sk // block_k

    def body(kb, carry):
        acc, m, l = carry
        k_blk = k_ref[0, 0, pl.ds(kb * block_k, block_k), :]  # [bk, D]
        v_blk = v_ref[0, 0, pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [bq, bk]
        if causal:
            rows = qi * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0
            )
            cols = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1
            )
            s = jnp.where(rows >= cols, s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr[:, None] + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc_new, m_new, l_new

    acc, m, l = jax.lax.fori_loop(0, num_kb, body, (acc, m, l))
    l = jnp.maximum(l, 1e-20)
    o_ref[0, 0] = (acc / l[:, None]).astype(o_ref.dtype)
    lse_ref[0, 0] = (m + jnp.log(l))[:, None]


def _flash_forward(
    q: jax.Array, k: jax.Array, v: jax.Array,
    causal: bool, block_q: int, block_k: int, interpret: bool,
) -> Tuple[jax.Array, jax.Array]:
    b, s, h, d = q.shape
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if s % block_q or s % block_k:
        raise ValueError(
            f"sequence length {s} must be divisible by block sizes "
            f"({block_q}, {block_k})"
        )
    scale = 1.0 / (d ** 0.5)
    grid = (b, h, s // block_q)
    kernel = functools.partial(
        _fwd_kernel, block_k=block_k, causal=causal, scale=scale
    )
    # BSHD -> BHSD so the S/D dims are the TPU-tiled trailing pair
    qt, kt, vt = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, s, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, s, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, s, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    return jnp.swapaxes(out, 1, 2), lse[..., 0]


def _bwd_blockwise(res, g, *, causal: bool, block_k: int):
    """Blockwise JAX backward: recompute P tile-by-tile from the saved
    logsumexp (standard flash-attention backward), O(S) memory."""
    q, k, v, out, lse = res
    b, s, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    block_k = min(block_k, s)

    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    # delta[b,h,i] = rowsum(dO * O)
    delta = jnp.einsum("bshd,bshd->bhs", gf, out.astype(jnp.float32))
    q_pos = jnp.arange(s)

    def step(carry, kb):
        dq = carry
        sl = jax.lax.dynamic_slice_in_dim(kf, kb * block_k, block_k, axis=1)
        vl = jax.lax.dynamic_slice_in_dim(vf, kb * block_k, block_k, axis=1)
        logits = jnp.einsum("bqhd,bkhd->bhqk", qf, sl,
                            preferred_element_type=jnp.float32) * scale
        if causal:
            cols = kb * block_k + jnp.arange(block_k)
            logits = jnp.where(q_pos[:, None] >= cols[None, :], logits, _NEG)
        p = jnp.exp(logits - lse[..., None])  # [b,h,Sq,bk]
        dv = jnp.einsum("bhqk,bqhd->bkhd", p, gf)
        dp = jnp.einsum("bqhd,bkhd->bhqk", gf, vl)
        ds = p * (dp - delta[..., None])  # [b,h,Sq,bk]
        dq = dq + jnp.einsum("bhqk,bkhd->bqhd", ds, sl) * scale
        dk = jnp.einsum("bhqk,bqhd->bkhd", ds, qf) * scale
        return dq, (dk, dv)

    n_kb = s // block_k
    dq0 = jnp.zeros_like(qf)
    dq, (dks, dvs) = jax.lax.scan(step, dq0, jnp.arange(n_kb))
    dk = dks.transpose(1, 0, 2, 3, 4).reshape(b, s, h, d)
    dv = dvs.transpose(1, 0, 2, 3, 4).reshape(b, s, h, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """softmax(QK^T/sqrt(d))V over [B, S, H, D], O(S) memory."""
    out, _ = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    return out


def _fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _bwd(causal, block_q, block_k, interpret, res, g):
    return _bwd_blockwise(res, g, causal=causal, block_k=block_k)


flash_attention.defvjp(_fwd, _bwd)
