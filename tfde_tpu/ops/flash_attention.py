"""Flash attention — Pallas TPU kernel for the long-sequence regime.

Hot-op kernel scope (the reference delegates all kernels to TF's C++ library,
SURVEY.md §2b "Dense/conv/BN kernel library"; here the transformer configs'
attention gets a hand kernel where XLA's default fusion stops helping).

Forward is a Pallas kernel (per /opt/skills/guides/pallas_guide.md):
- grid (batch, heads, Sq/block_q, Sk/block_k) with K minor: one Q tile and
  one K/V tile are VMEM-resident per step (VMEM stays O(block) at any S);
  the online-softmax state persists in VMEM scratch across the K-tile steps
  that revisit the same output block — the [Sq, Sk] score matrix never
  materializes (O(S) memory instead of O(S^2)).
- score matmuls hit the MXU with fp32 accumulation (preferred_element_type),
  tiles default to the largest MXU multiple of 512/256/128 dividing S
  (`_auto_block`: the r04 hardware sweep measured 512-edge tiles
  1.25-1.45x over 128 at every shape tried).
- causal masking predicates whole future K-tiles off (pl.when), halving the
  work for causal models rather than masking it.

Backward DEFAULTS to the blockwise-JAX recurrence (`_bwd_blockwise`):
recompute P tile-by-tile from the saved logsumexp under a `lax.scan`, O(S)
memory, XLA-scheduled matmuls. The r04 hardware A/B (tools/flash_ab.py on
v5e) measured it at 1.15x/1.28x/1.30x of the XLA reference einsum at
S=2048/4096/8192 (causal fwd+bwd), while the round-3 Pallas dK/dV + dQ
kernel pair (`TFDE_FLASH_BWD=pallas`, FlashAttention-2 arrangement,
retained below with 128-lane lse/delta layout and causal prefetch index
maps) lands at 0.6-0.73x — XLA's own scheduling of the same recurrence
beats the hand pipeline on this chip generation, so the kernel pair is
opt-in until it wins a measurement.

Ring attention (ops/ring_attention.py) composes with this by construction:
its per-device block computation is the same recurrence, so the flash kernel
can serve as its local step on TPU.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG = -1e30


def _auto_block(s: int) -> int:
    """Default tile edge: the largest MXU-multiple that divides S.

    The r04 hardware sweep (v5e, causal fwd+bwd, h=12 d=64) measured
    512x512 tiles 1.25-1.45x faster than the original 128x128 at every
    shape tried (b1-b4, S=2048-8192, windowed, GQA) — fewer grid steps
    amortize the per-tile online-softmax state updates, and a 512-row
    MXU operand keeps the systolic array busier. Explicit block_q/block_k
    still override (tests use small tiles to exercise multi-block paths
    at small S).

    A sliding window does NOT cap the edge: a tile wider than the band
    runs more in-band columns per Q row (~block + window), but the
    hardware A/B at the worst case (window=128, S=4096) still put 512
    tiles ahead — 2.32 vs 3.13 ms forward-only, 6.69 vs 7.26 ms fwd+bwd
    — per-tile efficiency outweighs the extra span on this chip."""
    for bl in (512, 256, 128):
        if s % bl == 0:
            return bl
    return min(s, 128)


def _resolve_block(block, s: int) -> int:
    """The one resolution rule for every kernel entry point: None -> the
    measured auto default; explicit -> clamped to S. Keeping this single
    prevents forward/backward tile defaults from silently diverging."""
    return _auto_block(s) if block is None else min(block, s)


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
    *, causal, scale, window,
):
    # BHSD layout, grid (B, H, Sq/bq, Sk/bk) with the K dimension minor:
    # q_ref [1, 1, bq, D]; k_ref/v_ref [1, 1, bk, D] — only one K/V tile is
    # VMEM-resident at a time, so VMEM stays O(block) at any S. The online-
    # softmax state (acc/m/l) lives in VMEM scratch, which persists across
    # the kb grid steps that revisit the same (b, h, qi) output block.
    qi = pl.program_id(2)
    kb = pl.program_id(3)
    num_kb = pl.num_programs(3)
    bq = q_ref.shape[2]
    bk = k_ref.shape[2]

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG)
        l_ref[:] = jnp.zeros_like(l_ref)

    def _step():
        q = q_ref[0, 0]  # [bq, D]
        k_blk = k_ref[0, 0]
        v_blk = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [bq, bk]
        if causal:
            rows = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = kb * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            keep = rows >= cols
            if window is not None:
                # sliding band: row i sees cols in (i - window, i]
                keep = jnp.logical_and(keep, rows - cols < window)
            s = jnp.where(keep, s, _NEG)
        m_prev = m_ref[:, 0:1]  # [bq, 1]
        l_prev = l_ref[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_ref[:] = jnp.broadcast_to(
            l_prev * corr + jnp.sum(p, axis=-1, keepdims=True), l_ref.shape
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        # K-tiles strictly past this Q-tile's last row contribute nothing;
        # with a sliding window, neither do tiles entirely older than the
        # oldest position the tile's first row can see
        # An interior/diagonal split (mask only the straddling tiles) was
        # measured 3-4% SLOWER at 512 tiles on v5e — the duplicated step
        # body costs more than the iota/select it saves; keep one body.
        run = kb * bk <= (qi + 1) * bq - 1
        if window is not None:
            run = jnp.logical_and(run,
                                  kb * bk + bk - 1 >= qi * bq - (window - 1))
        pl.when(run)(_step)
    else:
        _step()

    @pl.when(kb == num_kb - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, 0:1], 1e-20)
        o_ref[0, 0] = (acc_ref[:] / l).astype(o_ref.dtype)
        lse_ref[0, 0] = m_ref[:, 0:1] + jnp.log(l)


def _flash_forward(
    q: jax.Array, k: jax.Array, v: jax.Array,
    causal: bool, block_q: int, block_k: int, interpret: bool,
    window=None,
) -> Tuple[jax.Array, jax.Array]:
    b, s, h, d = q.shape
    if k.shape != v.shape:
        raise ValueError(f"k {k.shape} and v {v.shape} must match")
    kv = k.shape[2]
    if k.shape[0] != b or k.shape[1] != s or k.shape[3] != d:
        # All tiling below derives from q.shape; a cross-attention call with
        # longer K/V would silently attend over the wrong range (ADVICE r1).
        raise ValueError(
            f"flash_attention requires self-attention shapes: q {q.shape}, "
            f"k {k.shape}, v {v.shape}; use impl='reference' for "
            f"cross-attention (Sk != Sq)"
        )
    if h % kv:
        raise ValueError(
            f"query heads {h} must be a multiple of kv heads {kv} (GQA)"
        )
    # GQA: the grid stays per-QUERY-head; each q head's K/V index map folds
    # onto its serving KV head (hi // group). The kernel body never sees the
    # grouping, and the [B,S,H,D] K/V expansion of a repeat-then-attend
    # formulation never exists in HBM — the bandwidth saving GQA is for.
    group = h // kv
    auto_blocks = block_q is None and block_k is None
    block_q = _resolve_block(block_q, s)
    block_k = _resolve_block(block_k, s)
    if s % block_q or s % block_k:
        raise ValueError(
            f"sequence length {s} is not divisible by the kernel tile "
            f"sizes ({block_q}, {block_k})"
            + (" chosen automatically — flash attention needs S to be a "
               "multiple of 128; pad the sequence or use impl='reference'"
               if auto_blocks else " — pass block_q/block_k that divide S")
        )
    if window is not None and (not causal or window < 1):
        raise ValueError(
            f"window={window} requires causal=True and window >= 1"
        )
    scale = 1.0 / (d ** 0.5)
    kernel = functools.partial(_fwd_kernel, causal=causal, scale=scale,
                               window=window)
    # BSHD -> BHSD so the S/D dims are the TPU-tiled trailing pair
    qt, kt, vt = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
    from jax.experimental.pallas import tpu as pltpu

    if causal:
        # skipped K-tiles (strictly past the Q-tile's last row, or — with a
        # sliding window — entirely older than the band) must not spend
        # DMA: point their index map at an in-band tile the pipeline will
        # need anyway; repeat fetches are elided, so masked-off steps cost
        # ~nothing instead of a dead K/V copy
        def kv_idx(bi, hi, qi, kb):
            run = kb * block_k <= (qi + 1) * block_q - 1
            if window is None:
                first = 0
            else:
                pre_band = (
                    kb * block_k + block_k - 1 < qi * block_q - (window - 1)
                )
                first = jnp.maximum(
                    (qi * block_q - (window - 1)) // block_k, 0
                )
                # post-diagonal skipped steps park on the just-used diagonal
                # tile (fetch elided), NOT on first(qi) — that tile already
                # passed, so pointing back at it would issue one dead
                # block_k x d DMA per Q-row; pre-band skipped steps park on
                # first(qi), the tile the first in-band step needs anyway
                diag = ((qi + 1) * block_q - 1) // block_k
                return (
                    bi, hi // group,
                    jnp.where(run, jnp.where(pre_band, first, kb), diag),
                    0,
                )
            return (bi, hi // group, jax.lax.select(run, kb, first), 0)
    else:
        def kv_idx(bi, hi, qi, kb):
            return (bi, hi // group, kb, 0)

    grid = (b, h, s // block_q, s // block_k)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi, kb: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d), kv_idx),
            pl.BlockSpec((1, 1, block_k, d), kv_idx),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi, kb: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda bi, hi, qi, kb: (bi, hi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, s, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),    # acc
            pltpu.VMEM((block_q, 128), jnp.float32),  # m (col 0 used)
            pltpu.VMEM((block_q, 128), jnp.float32),  # l (col 0 used)
        ],
        interpret=interpret,
    )(qt, kt, vt)
    return jnp.swapaxes(out, 1, 2), lse[..., 0]


def _bwd_blockwise(res, g, *, causal: bool, block_k: int, window=None):
    """Blockwise JAX backward: recompute P tile-by-tile from the saved
    logsumexp (standard flash-attention backward), O(S) memory."""
    q, k, v, out, lse = res
    b, s, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    block_k = _resolve_block(block_k, s)
    if k.shape[2] != h:
        return _bwd_blockwise_grouped(res, g, causal=causal,
                                      block_k=block_k, window=window)

    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    # delta[b,h,i] = rowsum(dO * O)
    delta = jnp.einsum("bshd,bshd->bhs", gf, out.astype(jnp.float32))
    q_pos = jnp.arange(s)

    def step(carry, kb):
        dq = carry
        sl = jax.lax.dynamic_slice_in_dim(kf, kb * block_k, block_k, axis=1)
        vl = jax.lax.dynamic_slice_in_dim(vf, kb * block_k, block_k, axis=1)
        logits = jnp.einsum("bqhd,bkhd->bhqk", qf, sl,
                            preferred_element_type=jnp.float32) * scale
        if causal:
            cols = kb * block_k + jnp.arange(block_k)
            keep = q_pos[:, None] >= cols[None, :]
            if window is not None:
                keep = jnp.logical_and(
                    keep, q_pos[:, None] - cols[None, :] < window
                )
            logits = jnp.where(keep, logits, _NEG)
        p = jnp.exp(logits - lse[..., None])  # [b,h,Sq,bk]
        dv = jnp.einsum("bhqk,bqhd->bkhd", p, gf)
        dp = jnp.einsum("bqhd,bkhd->bhqk", gf, vl)
        ds = p * (dp - delta[..., None])  # [b,h,Sq,bk]
        dq = dq + jnp.einsum("bhqk,bkhd->bqhd", ds, sl) * scale
        dk = jnp.einsum("bhqk,bqhd->bkhd", ds, qf) * scale
        return dq, (dk, dv)

    n_kb = s // block_k
    dq0 = jnp.zeros_like(qf)
    dq, (dks, dvs) = jax.lax.scan(step, dq0, jnp.arange(n_kb))
    dk = dks.transpose(1, 0, 2, 3, 4).reshape(b, s, h, d)
    dv = dvs.transpose(1, 0, 2, 3, 4).reshape(b, s, h, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _bwd_blockwise_grouped(res, g, *, causal: bool, block_k: int,
                           window=None):
    """GQA twin of `_bwd_blockwise`: q [B,S,H,D] against k/v [B,S,Kv,D]
    with H = Kv * groups. Query heads carry an explicit group axis through
    the einsums (`c` = kv head, `g` = group member), so dK/dV sum over
    each KV head's query group inside the contraction and the [B,S,H,D]
    K/V expansion never materializes — mirroring grouped_attention
    (ops/attention.py). Kept separate from the MHA recurrence so the
    hardware-qualified path stays byte-identical."""
    q, k, v, out, lse = res
    b, s, h, d = q.shape
    kv = k.shape[2]
    grp = h // kv
    scale = 1.0 / (d ** 0.5)
    # block_k arrives already resolved by _bwd_blockwise (the only caller)

    qf = q.astype(jnp.float32).reshape(b, s, kv, grp, d)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    gf = g.astype(jnp.float32).reshape(b, s, kv, grp, d)
    # delta[b,c,g,i] = rowsum(dO * O); lse arrives [b,h,s] -> [b,c,g,s]
    delta = jnp.einsum(
        "bscgd,bscgd->bcgs", gf,
        out.astype(jnp.float32).reshape(b, s, kv, grp, d),
    )
    lse5 = lse.reshape(b, kv, grp, s)
    q_pos = jnp.arange(s)

    def step(carry, kb):
        dq = carry
        sl = jax.lax.dynamic_slice_in_dim(kf, kb * block_k, block_k, axis=1)
        vl = jax.lax.dynamic_slice_in_dim(vf, kb * block_k, block_k, axis=1)
        logits = jnp.einsum("bqcgd,bkcd->bcgqk", qf, sl,
                            preferred_element_type=jnp.float32) * scale
        if causal:
            cols = kb * block_k + jnp.arange(block_k)
            keep = q_pos[:, None] >= cols[None, :]
            if window is not None:
                keep = jnp.logical_and(
                    keep, q_pos[:, None] - cols[None, :] < window
                )
            logits = jnp.where(keep, logits, _NEG)
        p = jnp.exp(logits - lse5[..., None])  # [b,c,g,Sq,bk]
        dv = jnp.einsum("bcgqk,bqcgd->bkcd", p, gf)
        dp = jnp.einsum("bqcgd,bkcd->bcgqk", gf, vl)
        ds = p * (dp - delta[..., None])
        dq = dq + jnp.einsum("bcgqk,bkcd->bqcgd", ds, sl) * scale
        dk = jnp.einsum("bcgqk,bqcgd->bkcd", ds, qf) * scale
        return dq, (dk, dv)

    n_kb = s // block_k
    dq0 = jnp.zeros_like(qf)
    dq, (dks, dvs) = jax.lax.scan(step, dq0, jnp.arange(n_kb))
    dk = dks.transpose(1, 0, 2, 3, 4).reshape(b, s, kv, d)
    dv = dvs.transpose(1, 0, 2, 3, 4).reshape(b, s, kv, d)
    return (
        dq.reshape(b, s, h, d).astype(q.dtype),
        dk.astype(k.dtype),
        dv.astype(v.dtype),
    )


def _dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_acc, dv_acc, *, causal, scale, window,
):
    # grid (B, H, Sk/bk, Sq/bq) with the Q dimension minor: one K/V tile's
    # gradient accumulators live in VMEM scratch while every Q tile streams
    # past; refs are BHSD tiles [1, 1, bq|bk, D], lse/delta [1, 1, bq, 1].
    kb = pl.program_id(2)
    qi = pl.program_id(3)
    num_qi = pl.num_programs(3)
    bq = q_ref.shape[2]
    bk = k_ref.shape[2]

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _step():
        q = q_ref[0, 0]          # [bq, D]
        k_blk = k_ref[0, 0]      # [bk, D]
        v_blk = v_ref[0, 0]
        do = do_ref[0, 0]        # [bq, D]
        # lse/delta arrive broadcast to 128 lanes (layout, not data — a
        # [bq, 1]-minor tile would force Mosaic's degenerate-lane path);
        # col 0 carries the value
        lse = lse_ref[0, 0, :, 0:1]      # [bq, 1]
        delta = delta_ref[0, 0, :, 0:1]  # [bq, 1]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [bq, bk]
        if causal:
            rows = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = kb * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            keep = rows >= cols
            if window is not None:
                keep = jnp.logical_and(keep, rows - cols < window)
            s = jnp.where(keep, s, _NEG)
        p = jnp.exp(s - lse)  # [bq, bk]
        # dV += P^T dO
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # dP = dO V^T ; dS = P * (dP - delta) * scale
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * scale  # [bq, bk]
        # dK += dS^T Q
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        # Q tiles strictly above this K tile's first column see none of it;
        # with a window, neither do Q tiles entirely past the band
        run = (qi + 1) * bq - 1 >= kb * bk
        if window is not None:
            run = jnp.logical_and(
                run, qi * bq <= kb * bk + bk - 1 + (window - 1)
            )
        pl.when(run)(_step)
    else:
        _step()

    @pl.when(qi == num_qi - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc,
    *, causal, scale, window,
):
    # grid (B, H, Sq/bq, Sk/bk) with K minor: one Q tile's dQ accumulates in
    # VMEM scratch while K/V tiles stream past (same traversal as forward).
    qi = pl.program_id(2)
    kb = pl.program_id(3)
    num_kb = pl.num_programs(3)
    bq = q_ref.shape[2]
    bk = k_ref.shape[2]

    @pl.when(kb == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def _step():
        q = q_ref[0, 0]
        k_blk = k_ref[0, 0]
        v_blk = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0, :, 0:1]      # 128-lane broadcast, col 0 (see
        delta = delta_ref[0, 0, :, 0:1]  # _dkv_kernel)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            rows = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = kb * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            keep = rows >= cols
            if window is not None:
                keep = jnp.logical_and(keep, rows - cols < window)
            s = jnp.where(keep, s, _NEG)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * scale  # [bq, bk]
        dq_acc[:] = dq_acc[:] + jax.lax.dot_general(
            ds.astype(k_blk.dtype), k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        run = kb * bk <= (qi + 1) * bq - 1
        if window is not None:
            run = jnp.logical_and(run,
                                  kb * bk + bk - 1 >= qi * bq - (window - 1))
        pl.when(run)(_step)
    else:
        _step()

    @pl.when(kb == num_kb - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_pallas(res, g, *, causal: bool, block_q: int, block_k: int,
                interpret: bool, window=None):
    """FlashAttention-2 backward: dK/dV kernel + dQ kernel, O(S) memory."""
    q, k, v, out, lse = res
    b, s, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    block_q = _resolve_block(block_q, s)
    block_k = _resolve_block(block_k, s)
    from jax.experimental.pallas import tpu as pltpu

    # delta[b,h,s] = rowsum(dO * O), fp32 — cheap elementwise, stays in JAX
    delta = jnp.einsum(
        "bshd,bshd->bhs", g.astype(jnp.float32), out.astype(jnp.float32)
    )
    # BSHD -> BHSD tiles; lse/delta broadcast to 128 lanes (the official
    # TPU-kernel convention, MIN_BLOCK_SIZE lanes): a [*, 1]-minor block
    # would put every per-step load on Mosaic's degenerate-lane layout
    lanes = 128
    qt, kt, vt, gt = (jnp.swapaxes(t, 1, 2) for t in (q, k, v, g))
    lse4 = jnp.broadcast_to(lse[..., None], (b, h, s, lanes))
    delta4 = jnp.broadcast_to(delta[..., None], (b, h, s, lanes))

    def tile(n, idx):
        return pl.BlockSpec((1, 1, n, d), idx)

    def col(n, idx):
        return pl.BlockSpec((1, 1, n, lanes), idx)

    if causal:
        # Q tiles strictly above the K tile's first row are masked off —
        # prefetch the first contributing Q tile instead of a dead copy
        def kq_q(bi, hi, kb, qi):
            first = (kb * block_k) // block_q
            return (bi, hi,
                    jax.lax.select((qi + 1) * block_q - 1 >= kb * block_k,
                                   qi, first), 0)
    else:
        def kq_q(bi, hi, kb, qi):
            return (bi, hi, qi, 0)

    kq_k = lambda bi, hi, kb, qi: (bi, hi, kb, 0)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, causal=causal, scale=scale,
                          window=window),
        grid=(b, h, s // block_k, s // block_q),
        in_specs=[
            tile(block_q, kq_q),   # q
            tile(block_k, kq_k),   # k
            tile(block_k, kq_k),   # v
            tile(block_q, kq_q),   # dO
            col(block_q, kq_q),    # lse
            col(block_q, kq_q),    # delta
        ],
        out_specs=[tile(block_k, kq_k), tile(block_k, kq_k)],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, s, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt, gt, lse4, delta4)

    qk_q = lambda bi, hi, qi, kb: (bi, hi, qi, 0)
    if causal:
        # K tiles strictly past the Q tile's last row: prefetch tile 0 (the
        # next Q tile's first step) instead of a dead copy — mirrors forward
        def qk_k(bi, hi, qi, kb):
            return (bi, hi,
                    jax.lax.select(kb * block_k <= (qi + 1) * block_q - 1,
                                   kb, 0), 0)
    else:
        qk_k = lambda bi, hi, qi, kb: (bi, hi, kb, 0)
    (dq,) = pl.pallas_call(
        functools.partial(_dq_kernel, causal=causal, scale=scale,
                          window=window),
        grid=(b, h, s // block_q, s // block_k),
        in_specs=[
            tile(block_q, qk_q),
            tile(block_k, qk_k),
            tile(block_k, qk_k),
            tile(block_q, qk_q),
            col(block_q, qk_q),
            col(block_q, qk_q),
        ],
        out_specs=[tile(block_q, qk_q)],
        out_shape=[jax.ShapeDtypeStruct((b, h, s, d), q.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, gt, lse4, delta4)

    return (
        jnp.swapaxes(dq, 1, 2),
        jnp.swapaxes(dk, 1, 2),
        jnp.swapaxes(dv, 1, 2),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    block_q=None,
    block_k=None,
    interpret: bool = False,
    window=None,
) -> jax.Array:
    """softmax(QK^T/sqrt(d))V over [B, S, H, D], O(S) memory.

    GQA: k/v may carry fewer heads [B, S, Kv, D] with H a multiple of Kv —
    the grid stays per-query-head and each q head's K/V DMA folds onto its
    serving KV head, so the repeat-expanded K/V never exists in HBM.

    window: sliding-window band (requires causal) — position i attends the
    last `window` positions inclusive; out-of-band K tiles are skipped
    entirely (compute AND DMA), so cost drops to O(S * window)."""
    out, _ = _flash_forward(q, k, v, causal, block_q, block_k, interpret,
                            window)
    return out


def _fwd(q, k, v, causal, block_q, block_k, interpret, window):
    out, lse = _flash_forward(q, k, v, causal, block_q, block_k, interpret,
                              window)
    return out, (q, k, v, out, lse)


def _bwd(causal, block_q, block_k, interpret, window, res, g):
    import os

    # default 'jax' (blockwise): the r04 hardware A/B (tools/flash_ab.py,
    # v5e) times it at 1.15-1.30x of the XLA reference einsum while the
    # Pallas dKV/dQ pair — even with 128-lane lse/delta layout and causal
    # prefetch maps — lands at 0.6-0.73x. Same O(S) memory either way;
    # TFDE_FLASH_BWD=pallas keeps the kernel pair selectable.
    q, k = res[0], res[1]
    if (os.environ.get("TFDE_FLASH_BWD", "jax") == "pallas"
            and k.shape[2] == q.shape[2]):
        # the kernel pair is MHA-only (its dK/dV out specs are per-q-head;
        # GQA would need a cross-head reduction) — GQA always takes the
        # blockwise recurrence, which is also the measured-faster default
        return _bwd_pallas(res, g, causal=causal, block_q=block_q,
                           block_k=block_k, interpret=interpret,
                           window=window)
    return _bwd_blockwise(res, g, causal=causal, block_k=block_k,
                          window=window)


flash_attention.defvjp(_fwd, _bwd)
