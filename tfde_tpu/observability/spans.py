"""Lightweight span timers feeding the metric registry.

    with span("train/data_wait"):
        batch = next(host_iter)

Each span observes its wall duration into `registry.histogram(name)` —
that is the step-time-breakdown substrate: the train loop wraps its
phases (data wait, step dispatch, device sync, summary write, checkpoint
save, eval) and `goodput.py` reads the histogram sums back out to
classify the run's wall-clock.

When a profiler trace is active (profiler.py's `profile_trace` or
`StepWindowProfiler` window), every span additionally opens a
`jax.profiler.TraceAnnotation` region, so the SAME names appear on the
XProf timeline — one vocabulary across metrics and traces. The
TraceAnnotation is only constructed while tracing (the
`set_trace_active` flag, flipped by profiler.py at start/stop), keeping
the steady-state span cost to a clock read and a locked histogram add.

When the request-trace ring (trace.py) is active, every span ALSO lands
as a duration event on that timeline, tagged with the thread's bound
trace id (`trace.bind`) — so training-phase spans and serving request
waterfalls share one vocabulary and one viewer.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional

from tfde_tpu.observability import metrics
from tfde_tpu.observability import trace as reqtrace

_trace_active = False
# jax is resolved ONCE when profiler tracing first activates — span()
# used to re-run the import machinery on every traced span
_jax = None


def set_trace_active(active: bool) -> None:
    """Flipped by profiler.py when a jax.profiler trace starts/stops; spans
    emit TraceAnnotations only while True."""
    global _trace_active, _jax
    _trace_active = bool(active)
    if _trace_active and _jax is None:
        import jax

        _jax = jax


def trace_active() -> bool:
    return _trace_active


@contextlib.contextmanager
def span(name: str,
         registry: Optional[metrics.Registry] = None) -> Iterator[None]:
    """Time the enclosed block into `histogram(name)` (seconds); mirror it
    as a TraceAnnotation when a profiler trace is running. Duration is
    recorded even when the block raises — a failing phase still spent the
    wall-clock."""
    reg = registry or metrics.default_registry()
    ann = None
    if _trace_active:
        ann = _jax.profiler.TraceAnnotation(name)
        ann.__enter__()
    wall = time.time() if reqtrace.active() else None
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        if ann is not None:
            ann.__exit__(None, None, None)
        reg.histogram(name).observe(dt)
        if wall is not None:
            # same name, same timeline: picks up the thread's bound
            # request id (trace.bind) automatically via current()
            reqtrace.event(name, ts=wall, dur=dt)


def record(name: str, seconds: float,
           registry: Optional[metrics.Registry] = None) -> None:
    """Observe an externally measured duration under a span name — for
    call sites that already hold a timer (the prefetch generator times its
    own blocking pulls) and can't wrap a `with` block around the wait."""
    (registry or metrics.default_registry()).histogram(name).observe(seconds)
