"""Boot & readiness observability: the cold-start ledger for fast-boot
replicas.

ROADMAP item 2 (fast-boot replicas, autoscaled fleet) is blocked on an
unmeasured interval: a joining replica pays a full checkpoint restore
plus a compile storm before serving token one, and nothing decomposed
that interval or told the router when the joiner was safe to place
traffic on. This module is that instrument, three legs:

- **BootLedger.** Decomposes a replica's life from process birth to
  first served token into tiled phases — ``init`` (process start to
  first instrumented edge), ``bootstrap`` (distributed init), ``restore``
  (checkpoint read, with per-top-level-leaf bytes + seconds feeding
  ``boot/restore_bandwidth_bps``), ``compile`` (the pad-ladder
  enumeration), ``warmup`` (prefix-trie / cache priming) — and marks the
  first admitted request and first served token. Phases are published
  eagerly as ``boot/{phase}_seconds`` gauges (the compile phase's wall
  is ``boot/compile_wall_seconds``; ``boot/compile_{count,seconds}`` are
  the backend-compile attribution from the recompile sentinel, split
  boot vs steady-state at the ready edge), plus
  ``boot/time_to_ready_seconds`` and ``boot/ttft_from_birth_ms``, with a
  flight-recorder breadcrumb per phase edge. ``new_epoch()`` re-arms the
  ledger for the elastic path: a supervisor re-bootstrap measures its
  rejoin with the identical instrument, cross-checkable against
  goodput's init/compile buckets.

- **Readiness states.** The ledger owns a tiny state machine
  (``starting -> restoring -> compiling -> warming -> ready ->
  draining``) derived from the open phase. `ReplicaServer` surfaces it
  in ``/healthz`` and ``/load``; the Router places traffic only on
  ``ready`` replicas (``TFDE_BOOT_READY_REQUIRE``) and gives a booting
  replica ``TFDE_BOOT_READY_GRACE_S`` before push staleness may declare
  it lost — `Router._mark_down` accounts a never-ready death to
  ``router/replicas_never_ready``, not ``router/replicas_lost``.

- **Fleet rollup.** Replicas push their ``boot/*`` gauges like any
  other metric; `aggregate.py` rolls up ``cluster/boot_{p50,max}_seconds``
  — the control signals the autoscaler will consume — and
  ``tools/obs_dump.py --boot`` renders the per-replica waterfall.

Deployment contract: one replica per process (the cluster shape), so
the per-process gauges and the process-global ``current()`` ledger are
unambiguous. In-process multi-replica tests construct per-instance
ledgers; their gauges share the registry and last-writer-wins, which is
fine for the readiness machine (per-instance state) and irrelevant for
the fleet rollup (gauges are host-labelled by the push path).
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from typing import Callable, Dict, Optional

from tfde_tpu import knobs
from tfde_tpu.observability import flightrec, metrics

#: boot phases in canonical order; `begin()` tiles them (each phase
#: starts where the previous ended; the first is backdated to birth)
PHASES = ("init", "bootstrap", "restore", "compile", "warmup")

#: readiness states in lifecycle order
STATES = ("starting", "restoring", "compiling", "warming", "ready",
          "draining")

#: which state an OPEN phase maps to (init/bootstrap are both pre-restore
#: process bring-up; the split matters for the waterfall, not the router)
_PHASE_STATE = {"init": "starting", "bootstrap": "starting",
                "restore": "restoring", "compile": "compiling",
                "warmup": "warming"}

#: states the router may place traffic on ("unknown" is a replica the
#: router has not snapshotted yet — fail open for legacy robustness)
PLACEABLE_STATES = ("ready", "unknown")

#: fallback birth anchor: this module's import time
_IMPORT_MONOTONIC = time.monotonic()

#: every live ledger, so the serving path's module-level first-admit /
#: first-token marks reach whichever ledger(s) this process is driving
_LEDGERS: "weakref.WeakSet[BootLedger]" = weakref.WeakSet()

_CURRENT: Optional["BootLedger"] = None
_CURRENT_LOCK = threading.Lock()


def ready_require() -> bool:
    """Router-side gate: place traffic only on `ready` replicas."""
    return knobs.env_flag("TFDE_BOOT_READY_REQUIRE", True)


def ready_grace_s() -> float:
    """Seconds a never-ready replica may stay silent/not-ready before
    push staleness is allowed to declare it down."""
    return knobs.env_float("TFDE_BOOT_READY_GRACE_S", 120.0)


def process_birth_monotonic() -> float:
    """This process's birth on the `time.monotonic` clock, from
    /proc/self/stat start time vs /proc/uptime (Linux). Falls back to
    this module's import time — late, but strictly after-birth, so the
    ledger's time-to-ready underestimates rather than invents."""
    try:
        with open("/proc/self/stat") as f:
            stat = f.read()
        # field 22 (1-based) is starttime in clock ticks; fields after
        # the parenthesised comm (which may contain spaces) are stable
        after = stat.rsplit(")", 1)[1].split()
        start_ticks = float(after[19])
        hertz = float(os.sysconf("SC_CLK_TCK"))
        with open("/proc/uptime") as f:
            uptime = float(f.read().split()[0])
        age = uptime - start_ticks / hertz
        if age < 0:
            raise ValueError("negative process age")
        return time.monotonic() - age
    except Exception:
        return _IMPORT_MONOTONIC


def _default_compile_probe():
    """(count, seconds) of backend compiles this process has paid, from
    the recompile sentinel's jax.monitoring listener. (0, 0.0) when the
    sentinel is not installed — attribution then degrades to zeros
    instead of lying."""
    from tfde_tpu.observability import recompile

    return recompile.process_compiles(), recompile.seconds_total()


class BootLedger:
    """One boot epoch's phase ledger + readiness state (module
    docstring). Thread-safe: HTTP handler threads read `snapshot()`
    while the boot driver advances phases."""

    def __init__(self, birth: Optional[float] = None,
                 registry: Optional[metrics.Registry] = None,
                 clock: Callable[[], float] = time.monotonic,
                 compile_probe: Optional[Callable] = None):
        self._lock = threading.Lock()
        self._clock = clock
        self._reg = registry or metrics.default_registry()
        self._probe = compile_probe or _default_compile_probe
        with self._lock:
            self._birth = (float(birth) if birth is not None
                           else process_birth_monotonic())
            self._epoch = 0
            self._phases: Dict[str, float] = {}
            self._open: Optional[tuple] = None   # (name, start)
            self._state = "starting"
            self._ready_at: Optional[float] = None
            self._first_admit_at: Optional[float] = None
            self._first_token_at: Optional[float] = None
            self._restore_leaves: Dict[str, dict] = {}
            self._compile_base = self._probe()
            self._compile_at_ready: Optional[tuple] = None
        _LEDGERS.add(self)

    # -- phase edges ---------------------------------------------------------
    def begin(self, phase: str) -> None:
        """Open `phase`, closing any open phase at the same instant so
        phases tile. The epoch's first phase is backdated to birth: the
        un-instrumented interval before the driver's first edge IS
        process init."""
        if phase not in PHASES:
            raise ValueError(f"unknown boot phase {phase!r} "
                             f"(one of {PHASES})")
        now = self._clock()
        with self._lock:
            self._close_open_locked(now)
            start = now if self._phases or self._open else self._birth
            self._open = (phase, start)
            if self._state != "ready":   # a ready replica priming more
                self._state = _PHASE_STATE[phase]
        flightrec.record("boot_phase", phase=phase, edge="begin",
                         epoch=self._epoch)

    def end(self) -> None:
        """Close the open phase (no-op when none is open)."""
        now = self._clock()
        with self._lock:
            closed = self._close_open_locked(now)
        if closed is not None:
            name, secs = closed
            self._publish_phase(name, self._phases[name])
            flightrec.record("boot_phase", phase=name, edge="end",
                             seconds=round(secs, 4), epoch=self._epoch)

    def phase(self, name: str):
        """Context manager: ``with ledger.phase("restore"): ...``"""
        ledger = self

        class _Phase:
            def __enter__(self):
                ledger.begin(name)
                return ledger

            def __exit__(self, *exc):
                ledger.end()
                return False

        return _Phase()

    def note_phase(self, phase: str, seconds: float) -> None:
        """Credit an externally timed interval to `phase` (the
        checkpoint manager times its own restore call; the supervisor
        times the elastic re-bootstrap)."""
        if phase not in PHASES:
            raise ValueError(f"unknown boot phase {phase!r}")
        secs = max(0.0, float(seconds))
        with self._lock:
            self._phases[phase] = self._phases.get(phase, 0.0) + secs
            total = self._phases[phase]
        self._publish_phase(phase, total)
        flightrec.record("boot_phase", phase=phase, edge="note",
                         seconds=round(secs, 4), epoch=self._epoch)

    def _close_open_locked(self, now: float):
        if self._open is None:
            return None
        name, start = self._open
        secs = max(0.0, now - start)
        self._phases[name] = self._phases.get(name, 0.0) + secs
        self._open = None
        return name, secs

    def _publish_phase(self, name: str, total: float) -> None:
        # the compile PHASE is wall-clock around the ladder enumeration;
        # boot/compile_seconds is reserved for the backend-compile
        # attribution published at the ready edge
        gname = ("boot/compile_wall_seconds" if name == "compile"
                 else f"boot/{name}_seconds")
        self._reg.gauge(gname).set(total)

    # -- restore accounting --------------------------------------------------
    def note_restore_leaf(self, name: str, nbytes: int,
                          seconds: float) -> None:
        """Record one top-level checkpoint leaf's restore cost. Seconds
        may be the shared call's wall attributed proportionally by the
        caller; the bandwidth gauge divides summed bytes by summed
        seconds either way."""
        with self._lock:
            self._restore_leaves[str(name)] = {
                "bytes": int(nbytes), "seconds": max(0.0, float(seconds)),
            }
            tot_b = sum(e["bytes"] for e in self._restore_leaves.values())
            tot_s = sum(e["seconds"] for e in self._restore_leaves.values())
        if tot_s > 0:
            self._reg.gauge("boot/restore_bandwidth_bps").set(tot_b / tot_s)

    # -- serving edges -------------------------------------------------------
    def note_first_admit(self) -> None:
        """First request admitted this epoch (idempotent)."""
        now = self._clock()
        with self._lock:
            if self._first_admit_at is not None:
                return
            self._first_admit_at = now
        self._reg.gauge("boot/first_admit_seconds").set(now - self._birth)
        flightrec.record("boot_phase", phase="first_admit", edge="mark",
                         epoch=self._epoch)

    def note_first_token(self) -> None:
        """First served token this epoch (idempotent):
        ``boot/ttft_from_birth_ms`` — the whole cold-start answer."""
        now = self._clock()
        with self._lock:
            if self._first_token_at is not None:
                return
            self._first_token_at = now
            ms = (now - self._birth) * 1e3
        self._reg.gauge("boot/ttft_from_birth_ms").set(ms)
        flightrec.record("boot_phase", phase="first_token", edge="mark",
                         ttft_from_birth_ms=round(ms, 2), epoch=self._epoch)

    # -- lifecycle -----------------------------------------------------------
    def ready(self) -> None:
        """Boot is over: close any open phase, snapshot the compile
        probe (the boot-vs-steady attribution split point), publish the
        epoch's gauges, flip the state (idempotent)."""
        now = self._clock()
        with self._lock:
            if self._state == "ready":
                return
            self._close_open_locked(now)   # folds into _phases below
            self._state = "ready"
            self._ready_at = now
            self._compile_at_ready = self._probe()
            ttr = now - self._birth
            boot_count = self._compile_at_ready[0] - self._compile_base[0]
            boot_secs = self._compile_at_ready[1] - self._compile_base[1]
            phases = dict(self._phases)
        for name, total in phases.items():
            self._publish_phase(name, total)
        g = self._reg.gauge
        g("boot/time_to_ready_seconds").set(ttr)
        g("boot/compile_count").set(max(0, boot_count))
        g("boot/compile_seconds").set(max(0.0, boot_secs))
        g("boot/epoch").set(self._epoch)
        flightrec.record("boot_ready", epoch=self._epoch,
                         time_to_ready_s=round(ttr, 3),
                         compile_count=max(0, boot_count),
                         compile_seconds=round(max(0.0, boot_secs), 3),
                         phases={k: round(v, 3) for k, v in phases.items()})

    def draining(self) -> None:
        with self._lock:
            self._state = "draining"
        flightrec.record("boot_phase", phase="draining", edge="mark",
                         epoch=self._epoch)

    def new_epoch(self, cause: str = "") -> int:
        """Re-arm for a fresh boot (elastic rejoin): phases, marks and
        the compile base reset; birth becomes now. Returns the epoch."""
        now = self._clock()
        with self._lock:
            self._epoch += 1
            epoch = self._epoch
            self._birth = now
            self._phases = {}
            self._open = None
            self._state = "starting"
            self._ready_at = None
            self._first_admit_at = None
            self._first_token_at = None
            self._restore_leaves = {}
            self._compile_base = self._probe()
            self._compile_at_ready = None
        self._reg.counter("boot/epochs").incr()
        self._reg.gauge("boot/epoch").set(epoch)
        flightrec.record("boot_epoch", epoch=epoch, cause=str(cause))
        return epoch

    # -- reads ---------------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    @property
    def birth(self) -> float:
        with self._lock:
            return self._birth

    def phase_seconds(self) -> Dict[str, float]:
        """Closed phase durations, the open phase counted up to now."""
        now = self._clock()
        with self._lock:
            out = dict(self._phases)
            if self._open is not None:
                name, start = self._open
                out[name] = out.get(name, 0.0) + max(0.0, now - start)
        return out

    def time_to_ready(self) -> Optional[float]:
        with self._lock:
            if self._ready_at is None:
                return None
            return self._ready_at - self._birth

    def compile_attribution(self) -> dict:
        """Backend-compile split at the ready edge: compiles paid before
        ready are boot cost (the pad-ladder enumeration the fast-boot
        work must cache away); after, steady-state recompiles."""
        now_c, now_s = self._probe()
        with self._lock:
            base_c, base_s = self._compile_base
            at_ready = self._compile_at_ready
        if at_ready is None:   # still booting: everything so far is boot
            return {"boot": {"count": max(0, now_c - base_c),
                             "seconds": max(0.0, now_s - base_s)},
                    "steady": {"count": 0, "seconds": 0.0}}
        return {"boot": {"count": max(0, at_ready[0] - base_c),
                         "seconds": max(0.0, at_ready[1] - base_s)},
                "steady": {"count": max(0, now_c - at_ready[0]),
                           "seconds": max(0.0, now_s - at_ready[1])}}

    def snapshot(self) -> dict:
        """JSON-able ledger view (the /load and /replicas `boot` block)."""
        now = self._clock()
        phases = self.phase_seconds()
        attr = self.compile_attribution()
        with self._lock:
            birth = self._birth
            ready_at = self._ready_at
            first_admit = self._first_admit_at
            first_token = self._first_token_at
            leaves = {k: dict(v) for k, v in self._restore_leaves.items()}
            state, epoch = self._state, self._epoch
        tot_b = sum(e["bytes"] for e in leaves.values())
        tot_s = sum(e["seconds"] for e in leaves.values())
        return {
            "state": state,
            "epoch": epoch,
            "age_s": round(now - birth, 3),
            "phases": {k: round(v, 4) for k, v in phases.items()},
            "time_to_ready_s": (round(ready_at - birth, 3)
                                if ready_at is not None else None),
            "first_admit_s": (round(first_admit - birth, 3)
                              if first_admit is not None else None),
            "ttft_from_birth_ms": (round((first_token - birth) * 1e3, 2)
                                   if first_token is not None else None),
            "restore": {
                "bytes": tot_b,
                "seconds": round(tot_s, 4),
                "bandwidth_bps": (tot_b / tot_s if tot_s > 0 else None),
                "leaves": leaves,
            },
            "compile": {
                "boot_count": attr["boot"]["count"],
                "boot_seconds": round(attr["boot"]["seconds"], 4),
                "steady_count": attr["steady"]["count"],
                "steady_seconds": round(attr["steady"]["seconds"], 4),
            },
        }


# -- process-global ledger + serving-path marks ------------------------------
def current() -> BootLedger:
    """The process-global ledger (training path, serve children). Lazily
    created; its birth is the real process birth when /proc allows."""
    global _CURRENT
    with _CURRENT_LOCK:
        if _CURRENT is None:
            _CURRENT = BootLedger()
        return _CURRENT


def note_first_admit() -> None:
    """Serving-path hook (`server.py` enqueue): mark every READY
    ledger's first admitted request — cheap after the first call per
    ledger. Gated on readiness so a replica's own warm-up submits
    (compile/warmup phases drive the same batcher path) never pass for
    client traffic; the mark lands on the first post-ready request."""
    for led in list(_LEDGERS):
        if led.state == "ready":
            led.note_first_admit()


def note_first_token() -> None:
    """Serving-path hook (`server.py` TTFT observation): mark every
    READY ledger's first served token (same warm-up gate as
    `note_first_admit`) — `boot/ttft_from_birth_ms` means a token a
    CLIENT saw, not a warm-up token the replica fed itself."""
    for led in list(_LEDGERS):
        if led.state == "ready":
            led.note_first_token()


def note_restore(leaves: Dict[str, int], seconds: float) -> None:
    """Checkpoint-manager hook: credit a restore's per-top-level-leaf
    bytes (seconds attributed proportionally by bytes) to every ledger
    still booting — a steady-state restore is not boot cost."""
    total = sum(max(0, int(b)) for b in leaves.values())
    secs = max(0.0, float(seconds))
    targets = [led for led in list(_LEDGERS) if led.state != "ready"]
    for led in targets:
        for name, nbytes in leaves.items():
            frac = (int(nbytes) / total) if total else 0.0
            led.note_restore_leaf(name, int(nbytes), secs * frac)
        led.note_phase("restore", secs)
