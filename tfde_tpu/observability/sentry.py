"""Device-resident numerics sentry: catch NaN/Inf and gradient blow-ups
without paying a per-step host sync.

The naive guard — `if not np.isfinite(loss): ...` every step — forces a
device->host transfer per step, serializing the async dispatch pipeline the
whole training loop is built around. The sentry instead keeps its state ON
DEVICE and fuses the check into the already-compiled train step
(training/step.py threads it through when a `SentryConfig` is passed):

- ``isfinite(loss)`` and ``isfinite(grad_norm)`` — a NaN/Inf anywhere in
  the update poisons these first;
- a gradient-norm EWMA spike ratio: after `warmup_steps` finite samples,
  ``grad_norm > spike_ratio * ewma`` flags a divergence while the loss
  still looks plausible;
- trips accumulate into a sticky device flag (with the first trip's step),
  so the host can poll **every `poll_every` steps** — one tiny transfer per
  window, zero extra dispatches, and a trip anywhere inside the window is
  still caught with its original step number.

On a host-observed trip, `SentryMonitor.on_trip`:
1. records a flight-recorder event (observability/flightrec.py) — the
   post-mortem exists even if the escalation path itself dies;
2. optionally arms a bounded auto `jax.profiler` capture via
   `StepWindowProfiler.arm()` (profile_span > 0 + action='warn'), so the
   steps right after the trip land on an XProf timeline;
3. escalates: action='raise' raises `NumericsError`, which the supervisor
   classifies as FailureKind.NUMERICS and aborts — restarting from the
   pre-NaN checkpoint would deterministically replay the blow-up, so a
   numerics trip is poison with a better error message.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Optional

import jax.numpy as jnp

from tfde_tpu.observability import flightrec, metrics

log = logging.getLogger(__name__)

#: sticky flag bits
FLAG_NONFINITE = 1      # loss or grad_norm was NaN/Inf
FLAG_SPIKE = 2          # grad_norm exceeded spike_ratio x EWMA post-warmup
FLAG_COMM_OVERFLOW = 4  # int8 gradient transport saw a non-finite quantizer
                        # scale (parallel/comms.py) — saturation never passes
                        # silently


class NumericsError(RuntimeError):
    """A sentry trip escalated by action='raise'. The supervisor maps this
    to FailureKind.NUMERICS (non-restartable: the blow-up replays from the
    checkpoint)."""

    def __init__(self, flag: int, trip_step: int, observed_step: int):
        kinds = []
        if flag & FLAG_NONFINITE:
            kinds.append("non-finite loss/grad_norm")
        if flag & FLAG_SPIKE:
            kinds.append("grad-norm spike")
        if flag & FLAG_COMM_OVERFLOW:
            kinds.append("int8 grad-transport quantizer overflow")
        super().__init__(
            f"numerics sentry tripped at step {trip_step} "
            f"({' + '.join(kinds) or f'flag {flag}'}; "
            f"observed at host poll, step {observed_step})"
        )
        self.flag = flag
        self.trip_step = trip_step
        self.observed_step = observed_step


@dataclasses.dataclass(frozen=True)
class SentryConfig:
    """Knobs for the fused check + the host poll cadence."""

    #: grad_norm > spike_ratio * EWMA(grad_norm) trips FLAG_SPIKE
    spike_ratio: float = 10.0
    #: EWMA decay (per step) for the grad-norm baseline
    ewma_decay: float = 0.99
    #: finite grad-norm samples before the spike check arms (early training
    #: is legitimately spiky)
    warmup_steps: int = 20
    #: host polls the device flag every this many steps (the ONLY added
    #: device->host transfer; a trip is observed at most poll_every-1 steps
    #: after it happened, with the true trip step preserved on device)
    poll_every: int = 25
    #: on trip, arm a StepWindowProfiler capture of this many steps
    #: (0 = off). Only useful with action='warn' — a raise unwinds first.
    profile_span: int = 0
    #: 'raise' escalates NumericsError to the supervisor; 'warn' logs,
    #: records, and keeps training (the flag re-arms so each new window's
    #: first trip is reported once)
    action: str = "raise"

    def __post_init__(self):
        if self.poll_every < 1:
            raise ValueError("poll_every must be >= 1")
        if self.spike_ratio <= 1.0:
            raise ValueError("spike_ratio must be > 1")
        if not 0.0 < self.ewma_decay < 1.0:
            raise ValueError("ewma_decay must be in (0, 1)")
        if self.action not in ("raise", "warn"):
            raise ValueError(f"unknown sentry action {self.action!r}")


def init_state() -> dict:
    """Fresh device-side sentry carry (replicated scalars)."""
    return {
        "flag": jnp.zeros((), jnp.int32),
        "trip_step": jnp.full((), -1, jnp.int32),
        "ewma": jnp.zeros((), jnp.float32),
        "count": jnp.zeros((), jnp.int32),
        # EWMA of the int8 transport's error-feedback residual norm
        # (parallel/comms.py) — 0 under fp32 transport. A residual baseline
        # that drifts up means the quantizer is shedding more signal each
        # step (shrink the block size or raise the threshold).
        "res_ewma": jnp.zeros((), jnp.float32),
    }


def update(cfg: SentryConfig, sstate: dict, step, loss,
           grad_norm=None, residual_norm=None, comm_overflow=None) -> dict:
    """The fused per-step check: pure jnp, traced INSIDE the train step —
    no extra dispatch, no host callback (tests assert the jaxpr stays
    callback-free). Returns the next sentry carry.

    `residual_norm`/`comm_overflow` arrive from the int8 gradient
    transport: the residual norm feeds its own EWMA (telemetry; a
    non-finite value also trips FLAG_NONFINITE), a positive overflow flag
    trips FLAG_COMM_OVERFLOW — a quantizer that saw NaN/Inf absmaxes must
    abort loudly, not saturate silently."""
    step = jnp.asarray(step, jnp.int32)
    loss = jnp.asarray(loss, jnp.float32)
    bits = jnp.where(jnp.isfinite(loss), 0, FLAG_NONFINITE).astype(jnp.int32)
    ewma, count = sstate["ewma"], sstate["count"]
    if grad_norm is not None:
        g = jnp.asarray(grad_norm, jnp.float32)
        finite = jnp.isfinite(g)
        bits = bits | jnp.where(finite, 0, FLAG_NONFINITE)
        spike = (
            (count >= cfg.warmup_steps)
            & finite
            & (g > cfg.spike_ratio * jnp.maximum(ewma, 1e-30))
        )
        bits = bits | jnp.where(spike, FLAG_SPIKE, 0)
        # EWMA over finite samples only — one NaN must not poison the
        # baseline the recovery (action='warn') keeps comparing against
        new_ewma = jnp.where(
            finite,
            jnp.where(count == 0, g,
                      cfg.ewma_decay * ewma + (1.0 - cfg.ewma_decay) * g),
            ewma,
        )
        ewma = new_ewma
        count = count + jnp.where(finite, 1, 0)
    res_ewma = sstate.get("res_ewma", jnp.zeros((), jnp.float32))
    if residual_norm is not None:
        r = jnp.asarray(residual_norm, jnp.float32)
        r_finite = jnp.isfinite(r)
        bits = bits | jnp.where(r_finite, 0, FLAG_NONFINITE)
        # no warm-start branch: the residual starts at exactly zero (the
        # carry is initialized to zeros), so the EWMA ramps from 0 honestly
        res_ewma = jnp.where(
            r_finite,
            cfg.ewma_decay * res_ewma + (1.0 - cfg.ewma_decay) * r,
            res_ewma,
        )
    if comm_overflow is not None:
        tripped = jnp.asarray(comm_overflow, jnp.float32) > 0
        bits = bits | jnp.where(tripped, FLAG_COMM_OVERFLOW, 0)
    first_trip = (bits != 0) & (sstate["flag"] == 0)
    return {
        "flag": sstate["flag"] | bits,
        "trip_step": jnp.where(first_trip, step, sstate["trip_step"]),
        "ewma": ewma,
        "count": count,
        "res_ewma": res_ewma,
    }


class SentryMonitor:
    """Host-side poller. Owns the poll cadence and the trip escalation;
    the device state itself threads through the compiled step."""

    def __init__(self, cfg: SentryConfig, profiler=None,
                 registry: Optional[metrics.Registry] = None):
        self.cfg = cfg
        self.profiler = profiler
        self._reg = registry or metrics.default_registry()
        self.trips = 0

    def maybe_poll(self, sstate: dict, step: int) -> Optional[dict]:
        """Call once per completed step with the post-increment step; polls
        the device flag every cfg.poll_every steps (one scalar device_get —
        the sentry's entire host cost). Returns the trip info dict when a
        trip was observed, else None. Raises NumericsError when
        cfg.action == 'raise'."""
        if step % self.cfg.poll_every:
            return None
        import jax

        flag = int(jax.device_get(sstate["flag"]))
        if not flag:
            return None
        trip_step = int(jax.device_get(sstate["trip_step"]))
        return self.on_trip(flag, trip_step, step)

    def on_trip(self, flag: int, trip_step: int, step: int) -> dict:
        self.trips += 1
        self._reg.counter("sentry/trips").incr()
        self._reg.gauge("sentry/tripped_flag").set(flag)
        self._reg.gauge("sentry/trip_step").set(trip_step)
        info = {"flag": flag, "trip_step": trip_step, "observed_step": step}
        # flight event FIRST: the record must exist even if escalation
        # (or anything above us on the stack) dies before the dump hook
        flightrec.record("sentry_trip", **info)
        log.error(
            "numerics sentry tripped: flag=%d at step %d (observed at "
            "step %d)", flag, trip_step, step,
        )
        if self.cfg.profile_span > 0 and self.profiler is not None:
            # route through the trigger hub (cooldown/dedupe shared with
            # SLO-burn/straggler/recompile triggers); the extra_sink keeps
            # this working when the profiler is not hub-registered
            from tfde_tpu.observability import profiler as _prof

            armed = _prof.trigger(
                "sentry_trip", key=f"sentry_trip:{flag}",
                span=self.cfg.profile_span, step=step, flag=flag,
                extra_sink=self.profiler.trigger_sink,
            )
            if armed:
                flightrec.record("sentry_profile_armed", start=step + 1,
                                 span=self.cfg.profile_span)
        if self.cfg.action == "raise":
            raise NumericsError(flag, trip_step, step)
        return info


def resolve(sentry) -> Optional[SentryConfig]:
    """RunConfig.sentry sugar: None/False -> off, True -> defaults, a
    SentryConfig passes through."""
    if sentry is None or sentry is False:
        return None
    if sentry is True:
        return SentryConfig()
    if isinstance(sentry, SentryConfig):
        return sentry
    raise TypeError(
        f"sentry must be None/bool/SentryConfig, got {type(sentry).__name__}"
    )
