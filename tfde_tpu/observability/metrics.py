"""Process-wide metric registry: counters, gauges, and fixed-bucket
histograms.

Generalizes `counters.py` (which is now a shim over this module) into the
substrate every subsystem shares: the train loop's span timers, the
resilience counters, the inference batcher's throughput stats. One default
registry per process keeps the export surface single — `exposition.py`
renders any `snapshot()` as Prometheus text, JSONL, or TensorBoard scalars.

Design points:
- Thread-safe: the stall watchdog, background prefetch, and retry wrappers
  all write from non-main threads while the train loop (or the /metrics
  HTTP handler) reads. One registry-wide RLock; metric mutations are a few
  adds under it.
- Histograms are FIXED-BUCKET (Prometheus classic style): observation cost
  is O(log buckets), memory is O(buckets), and percentiles come from linear
  interpolation within the covering bucket — accurate to a bucket width,
  which the default exponential seconds-ladder keeps proportional to the
  value. Min/max are tracked exactly and clamp the interpolation.
- `snapshot()` / `reset()` are the test hooks: a snapshot is plain data
  (floats and dicts), safe to serialize or diff.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, Optional, Sequence, Tuple, Union

#: Default histogram buckets: an exponential seconds ladder from 0.5 ms to
#: 5 minutes — wide enough for a data-wait microsecond and a checkpoint
#: restore alike. Upper bounds are inclusive (`le`, Prometheus semantics);
#: values beyond the last bound land in the implicit +Inf bucket.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)


class Counter:
    """Monotonic named counter. Negative increments are rejected — rates
    and totals must only grow; a value that can fall is a Gauge."""

    kind = "counter"

    def __init__(self, name: str, lock: threading.RLock):
        self.name = name
        self._lock = lock
        self._value = 0.0

    def incr(self, amount: float = 1.0) -> float:
        if amount < 0:
            raise ValueError(f"counter {self.name!r}: negative increment {amount}")
        with self._lock:
            self._value += amount
            return self._value

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _reset(self) -> None:
        self._value = 0.0

    def _snapshot(self) -> float:
        return self._value


class Gauge:
    """Last-written value (queue depth, goodput fraction, steps/sec)."""

    kind = "gauge"

    def __init__(self, name: str, lock: threading.RLock):
        self.name = name
        self._lock = lock
        self._value = 0.0

    def set(self, value: float) -> float:
        with self._lock:
            self._value = float(value)
            return self._value

    def add(self, amount: float) -> float:
        with self._lock:
            self._value += amount
            return self._value

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _reset(self) -> None:
        self._value = 0.0

    def _snapshot(self) -> float:
        return self._value


class Histogram:
    """Fixed-bucket histogram with interpolated percentile readout.

    `bounds` are inclusive upper edges (sorted, finite); an implicit +Inf
    bucket catches the overflow. `percentile(q)` walks the cumulative
    counts to the covering bucket and interpolates linearly inside it,
    clamped to the exact observed min/max — so p50/p95/p99 are correct to
    a bucket width even though individual observations are not retained.
    """

    kind = "histogram"

    def __init__(self, name: str, lock: threading.RLock,
                 buckets: Optional[Sequence[float]] = None):
        bounds = tuple(sorted(float(b) for b in (buckets or DEFAULT_BUCKETS)))
        if not bounds or len(set(bounds)) != len(bounds):
            raise ValueError(f"histogram {name!r}: buckets must be distinct")
        self.name = name
        self.bounds = bounds
        self._lock = lock
        self._counts = [0] * (len(bounds) + 1)  # last slot = +Inf bucket
        self._sum = 0.0
        self._count = 0
        self._min = float("inf")
        self._max = float("-inf")

    def observe(self, value: float) -> None:
        value = float(value)
        i = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1
            if value < self._min:
                self._min = value
            if value > self._max:
                self._max = value

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    @property
    def mean(self) -> float:
        with self._lock:
            return self._sum / self._count if self._count else 0.0

    def percentile(self, q: float) -> float:
        """q in [0, 100]. 0.0 when nothing was observed."""
        if not 0 <= q <= 100:
            raise ValueError(f"percentile q must be in [0, 100], got {q}")
        with self._lock:
            return self._percentile_locked(q)

    def _percentile_locked(self, q: float) -> float:
        if self._count == 0:
            return 0.0
        rank = q / 100.0 * self._count  # fractional rank in (0, count]
        cum = 0
        for i, c in enumerate(self._counts):
            if c == 0:
                continue
            lo = self.bounds[i - 1] if i > 0 else self._min
            hi = self.bounds[i] if i < len(self.bounds) else self._max
            if cum + c >= rank:
                frac = (rank - cum) / c
                v = lo + frac * (hi - lo)
                return min(max(v, self._min), self._max)
            cum += c
        return self._max

    def _reset(self) -> None:
        self._counts = [0] * (len(self.bounds) + 1)
        self._sum = 0.0
        self._count = 0
        self._min = float("inf")
        self._max = float("-inf")

    def _snapshot(self) -> dict:
        cum, buckets = 0, []
        for i, b in enumerate(self.bounds):
            cum += self._counts[i]
            buckets.append((b, cum))
        return {
            "count": self._count,
            "sum": self._sum,
            "min": self._min if self._count else 0.0,
            "max": self._max if self._count else 0.0,
            "buckets": buckets,  # (inclusive upper bound, cumulative count)
            "p50": self._percentile_locked(50.0),
            "p95": self._percentile_locked(95.0),
            "p99": self._percentile_locked(99.0),
        }


Metric = Union[Counter, Gauge, Histogram]


class Registry:
    """Named-metric store. `counter()`/`gauge()`/`histogram()` get-or-create
    (a name is permanently one kind — a mismatch raises); `snapshot()` and
    `reset()` are the exporter/test surface."""

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics: Dict[str, Metric] = {}
        self._collectors: list = []

    def add_collector(self, fn) -> None:
        """Register a callable run at the top of every `snapshot()` —
        the "metrics cadence" hook for values that must be *sampled*
        rather than pushed (live device-buffer totals, memwatch.py). The
        collector runs OUTSIDE the registry lock (it is expected to set
        gauges on this registry) and its exceptions are swallowed: a
        broken sampler must not take the scrape path down."""
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def remove_collector(self, fn) -> None:
        with self._lock:
            if fn in self._collectors:
                self._collectors.remove(fn)

    def _get_or_create(self, name: str, cls, **kwargs) -> Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, self._lock, **kwargs)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise ValueError(
                    f"metric {name!r} is a {m.kind}, not a {cls.kind}"
                )
            return m

    def counter(self, name: str) -> Counter:
        return self._get_or_create(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get_or_create(name, Gauge)

    def histogram(self, name: str,
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._get_or_create(name, Histogram, buckets=buckets)

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def snapshot(self) -> Dict[str, dict]:
        """Point-in-time copy: {name: {"type": kind, **data}} where data is
        {"value": float} for counters/gauges and the histogram dict (count/
        sum/min/max/buckets/p50/p95/p99) for histograms. Plain data — safe
        to serialize, diff, or hand to exposition renderers."""
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:  # outside the lock: collectors set gauges
            try:
                fn()
            except Exception:  # noqa: BLE001 — scrape must survive
                pass
        with self._lock:
            out = {}
            for name in sorted(self._metrics):
                m = self._metrics[name]
                data = m._snapshot()
                if m.kind == "histogram":
                    out[name] = {"type": m.kind, **data}
                else:
                    out[name] = {"type": m.kind, "value": data}
            return out

    def scalars(self, prefix: str = "") -> Dict[str, float]:
        """Counters and gauges only, as {name: value} (the counters.py
        snapshot shape), optionally filtered by prefix."""
        with self._lock:
            return {
                name: m._snapshot()
                for name, m in self._metrics.items()
                if m.kind != "histogram" and name.startswith(prefix)
            }

    def reset(self, prefix: str = "") -> None:
        """Drop metrics under `prefix` (or all) — test isolation hook.
        Dropping (not zeroing) keeps live references valid: a holder of a
        removed metric keeps a working but unregistered object."""
        with self._lock:
            for name in [n for n in self._metrics if n.startswith(prefix)]:
                del self._metrics[name]

    def remove(self, name: str) -> None:
        """Drop exactly `name` (no-op when absent) — unlike reset(), never
        touches other metrics that merely share the prefix."""
        with self._lock:
            self._metrics.pop(name, None)


_default = Registry()


def default_registry() -> Registry:
    """The process-wide registry every subsystem shares by default."""
    return _default


def counter(name: str) -> Counter:
    return _default.counter(name)


def gauge(name: str) -> Gauge:
    return _default.gauge(name)


def histogram(name: str, buckets: Optional[Sequence[float]] = None) -> Histogram:
    return _default.histogram(name, buckets=buckets)


def flatten_snapshot(snap: Dict[str, dict]) -> Dict[str, float]:
    """Flatten a `Registry.snapshot()` to {name: float} for scalar sinks
    (TensorBoard, JSONL): counters/gauges pass through, histograms expand
    to name/count, name/sum, name/mean, name/p50, name/p95, name/p99."""
    out: Dict[str, float] = {}
    for name, data in snap.items():
        if data["type"] == "histogram":
            count = data["count"]
            out[f"{name}/count"] = float(count)
            out[f"{name}/sum"] = float(data["sum"])
            out[f"{name}/mean"] = float(data["sum"] / count) if count else 0.0
            for p in ("p50", "p95", "p99"):
                out[f"{name}/{p}"] = float(data[p])
        else:
            out[name] = float(data["value"])
    return out
