"""Observability: the unified metrics-and-tracing layer.

- metrics.py     process-wide registry (counters/gauges/histograms)
- spans.py       phase timers feeding the histograms (+ XProf regions)
- goodput.py     wall-clock classification -> goodput fraction
- exposition.py  Prometheus text, JSONL logs, /metrics HTTP, TB bridge
- tensorboard.py event-file SummaryWriter
- profiler.py    jax.profiler trace windows
- counters.py    legacy counter API (shim over metrics.py)
- trace.py       per-request distributed tracing ring (TFDE_TRACE)
- slo.py         TTFT/TPOT SLO attainment + burn-rate gauges
- flightrec.py   crash-dump flight recorder ring
- aggregate.py   cross-host metric aggregation + trace stitching
- memwatch.py    measured memory ledger (mem/*, TFDE_MEMWATCH)
- recompile.py   jit-cache-miss sentinel (compile/*)
"""

from tfde_tpu.observability.tensorboard import SummaryWriter  # noqa: F401
from tfde_tpu.observability.profiler import profile_trace  # noqa: F401
from tfde_tpu.observability import counters  # noqa: F401
from tfde_tpu.observability import metrics  # noqa: F401
from tfde_tpu.observability import spans  # noqa: F401
from tfde_tpu.observability.spans import span  # noqa: F401
from tfde_tpu.observability.goodput import GoodputLedger  # noqa: F401
from tfde_tpu.observability.exposition import (  # noqa: F401
    JsonlMetricsLog,
    MetricsServer,
    serve_metrics,
    to_prometheus_text,
)
from tfde_tpu.observability import trace  # noqa: F401
from tfde_tpu.observability.slo import SLOTracker  # noqa: F401
from tfde_tpu.observability import memwatch  # noqa: F401
from tfde_tpu.observability import recompile  # noqa: F401
