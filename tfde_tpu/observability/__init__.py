"""Observability: TensorBoard event files, steps/sec logging, profiling,
and process-wide counters (the resilience subsystem's export surface)."""

from tfde_tpu.observability.tensorboard import SummaryWriter  # noqa: F401
from tfde_tpu.observability.profiler import profile_trace  # noqa: F401
from tfde_tpu.observability import counters  # noqa: F401
