"""Observability: TensorBoard event files, steps/sec logging, profiling."""

from tfde_tpu.observability.tensorboard import SummaryWriter  # noqa: F401
from tfde_tpu.observability.profiler import profile_trace  # noqa: F401
