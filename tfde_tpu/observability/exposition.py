"""Exposition: render a metric snapshot for operators.

Four surfaces off the one registry (metrics.py):

- **Prometheus text** (`to_prometheus_text`): the scrape format, classic
  histograms included (`_bucket{le="..."}` / `_sum` / `_count`).
  `parse_prometheus_text` is the inverse — used by tests to prove the
  round-trip and by anyone who wants the numbers back out of a scrape.
- **HTTP /metrics** (`MetricsServer`): a tiny threaded endpoint the chief
  (or the inference server) runs; `/metrics` serves Prometheus text,
  `/metrics.json` the flattened snapshot, `/healthz` liveness.
- **JSONL event log** (`JsonlMetricsLog`): append-structured snapshots
  under `<model_dir>/metrics/` — the post-hoc analysis surface (works on
  remote model_dirs through utils/fs, like the TensorBoard writer).
- **TensorBoard bridge** (`export_to_tensorboard`): the flattened snapshot
  as scalars through the existing SummaryWriter, so ops metrics land next
  to the training curves.

Metric names are slash-namespaced internally ("train/data_wait");
Prometheus names sanitize to ``tfde_train_data_wait``.
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
import time
from typing import Dict, Optional

from tfde_tpu.observability import metrics
from tfde_tpu.utils import fs

log = logging.getLogger(__name__)

PROM_PREFIX = "tfde_"
PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_INVALID = re.compile(r"[^a-zA-Z0-9_:]")


def prom_name(name: str, prefix: str = PROM_PREFIX) -> str:
    """'train/data_wait' -> 'tfde_train_data_wait' (Prometheus charset)."""
    out = _INVALID.sub("_", f"{prefix}{name}")
    if out[0].isdigit():
        out = f"_{out}"
    return out


def _fmt(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    return repr(float(v))


def to_prometheus_text(snapshot: Optional[Dict[str, dict]] = None,
                       registry: Optional[metrics.Registry] = None,
                       prefix: str = PROM_PREFIX) -> str:
    """Render a `Registry.snapshot()` (or the registry's current state) as
    Prometheus text exposition format. Counters get the conventional
    `_total` suffix; histograms render classic cumulative buckets."""
    if snapshot is None:
        snapshot = (registry or metrics.default_registry()).snapshot()
    lines = []
    for name in sorted(snapshot):
        data = snapshot[name]
        kind = data["type"]
        pname = prom_name(name, prefix)
        if kind == "counter":
            pname = f"{pname}_total"
            lines.append(f"# TYPE {pname} counter")
            lines.append(f"{pname} {_fmt(data['value'])}")
        elif kind == "gauge":
            lines.append(f"# TYPE {pname} gauge")
            lines.append(f"{pname} {_fmt(data['value'])}")
        else:  # histogram
            lines.append(f"# TYPE {pname} histogram")
            cum = 0
            for le, cum in data["buckets"]:
                lines.append(f'{pname}_bucket{{le="{_fmt(le)}"}} {cum}')
            lines.append(f'{pname}_bucket{{le="+Inf"}} {data["count"]}')
            lines.append(f"{pname}_sum {_fmt(data['sum'])}")
            lines.append(f"{pname}_count {data['count']}")
    return "\n".join(lines) + "\n"


def parse_prometheus_text(text: str) -> Dict[str, dict]:
    """Inverse of `to_prometheus_text` for the families it emits. Returns
    {prom_name: {"type": ..., "value": float}} for counters (name keeps its
    `_total` suffix) and gauges, and {"type": "histogram", "buckets":
    [(le, cum)], "sum": float, "count": int} for histograms."""
    types: Dict[str, str] = {}
    out: Dict[str, dict] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        name_part, _, val_part = line.rpartition(" ")
        value = float(val_part)
        if "{" in name_part:
            base, _, rest = name_part.partition("{")
            labels = rest.rstrip("}")
            if base.endswith("_bucket"):
                hname = base[: -len("_bucket")]
                h = out.setdefault(
                    hname, {"type": "histogram", "buckets": [],
                            "sum": 0.0, "count": 0})
                m = re.search(r'le="([^"]+)"', labels)
                le = float(m.group(1)) if m.group(1) != "+Inf" else float("inf")
                h["buckets"].append((le, int(value)))
            continue
        if name_part.endswith("_sum") and name_part[: -4] in out:
            out[name_part[: -4]]["sum"] = value
        elif name_part.endswith("_count") and name_part[: -6] in out:
            out[name_part[: -6]]["count"] = int(value)
        else:
            out[name_part] = {"type": types.get(name_part, "untyped"),
                              "value": value}
    # the +Inf bucket duplicates _count; drop it for a clean comparison
    for h in out.values():
        if h.get("type") == "histogram":
            h["buckets"] = [(le, c) for le, c in h["buckets"]
                            if le != float("inf")]
    return out


# -- JSONL event log ---------------------------------------------------------
class JsonlMetricsLog:
    """Append-only JSONL snapshots under `<model_dir>/metrics/`.

    Each `write(step)` appends one line::

        {"ts": <unix>, "step": N, "metrics": {flattened snapshot}}

    Local paths append through a held file handle; remote paths
    (gs://, memory://) buffer and rewrite the object on flush — the same
    trade the TensorBoard writer makes (remote stores have no append)."""

    def __init__(self, model_dir: str,
                 registry: Optional[metrics.Registry] = None):
        self._reg = registry or metrics.default_registry()
        d = fs.join(model_dir, "metrics")
        fs.makedirs(d)
        fname = f"metrics-{int(time.time())}-{os.getpid()}.jsonl"
        self.path = fs.join(d, fname)
        self._remote = fs.is_remote(self.path)
        self._buf: list = []
        self._f = None if self._remote else open(self.path, "a")
        self._lock = threading.Lock()

    def write(self, step: int, extra: Optional[Dict[str, float]] = None) -> None:
        flat = metrics.flatten_snapshot(self._reg.snapshot())
        if extra:
            flat.update(extra)
        rec = {"ts": time.time(), "step": int(step), "metrics": flat}
        # exemplar linking (trace.py): when request tracing is on, each
        # snapshot line carries the trace ids of the slowest latency
        # observations so a post-mortem can jump from a bad percentile
        # straight to the offending waterfalls
        from tfde_tpu.observability import trace as _trace

        ex = _trace.exemplars()
        if ex:
            rec["exemplars"] = ex
        line = json.dumps(rec, sort_keys=True)
        with self._lock:
            if self._f is not None:
                self._f.write(line + "\n")
            else:
                self._buf.append(line)

    def flush(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.flush()
            elif self._buf:
                fs.write_bytes(self.path,
                               ("\n".join(self._buf) + "\n").encode())

    def close(self) -> None:
        self.flush()
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


# -- TensorBoard bridge ------------------------------------------------------
def export_to_tensorboard(writer, step: int,
                          registry: Optional[metrics.Registry] = None,
                          prefix: str = "") -> Dict[str, float]:
    """Write the flattened snapshot (optionally filtered to names under
    `prefix`) as scalars at `step`. `writer` may be None (non-chief) —
    then this is only the snapshot read. Returns what was (or would be)
    written."""
    reg = registry or metrics.default_registry()
    flat = {k: v for k, v in metrics.flatten_snapshot(reg.snapshot()).items()
            if k.startswith(prefix)}
    if writer is not None and flat:
        writer.scalars(step, flat)
    return flat


# -- HTTP /metrics endpoint --------------------------------------------------
class MetricsServer:
    """Chief-only scrape endpoint: `/metrics` (Prometheus text),
    `/metrics.json` (flattened snapshot), `/healthz`. Runs a
    ThreadingHTTPServer in a daemon thread; `port=0` binds an ephemeral
    port (read it back from `.port` — the test/bench pattern).

    With an `aggregator` (observability/aggregate.ClusterAggregator)
    attached, `POST /push` ingests worker snapshots and `/metrics` appends
    the aggregator's host-labelled series + cluster rollups — each scrape
    re-runs the rollup, so a dead host's staleness gauge flips even though
    it will never push again."""

    def __init__(self, port: int = 0, host: str = "0.0.0.0",
                 registry: Optional[metrics.Registry] = None,
                 aggregator=None):
        import http.server

        reg = registry or metrics.default_registry()
        self._reg = reg
        self.aggregator = aggregator
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            server_version = "tfde-metrics"

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 (http.server API)
                try:
                    agg = outer.aggregator
                    if self.path.split("?")[0] == "/metrics":
                        if agg is not None:
                            agg.rollup()  # staleness flips on scrape too
                        body = to_prometheus_text(registry=reg)
                        if agg is not None:
                            body += agg.prometheus_text()
                        self._send(200, body.encode(), PROM_CONTENT_TYPE)
                    elif self.path.split("?")[0] == "/metrics.json":
                        if agg is not None:
                            agg.rollup()
                        flat = metrics.flatten_snapshot(reg.snapshot())
                        body = json.dumps(flat, sort_keys=True).encode()
                        self._send(200, body, "application/json")
                    elif self.path.split("?")[0] == "/healthz":
                        self._send(200, b"ok\n", "text/plain")
                    else:
                        self._send(404, b"not found\n", "text/plain")
                except BrokenPipeError:  # scraper went away mid-response
                    pass

            def do_POST(self):  # noqa: N802 (http.server API)
                try:
                    agg = outer.aggregator
                    if self.path.split("?")[0] != "/push" or agg is None:
                        self._send(404, b"not found\n", "text/plain")
                        return
                    n = int(self.headers.get("Content-Length", 0))
                    try:
                        payload = json.loads(self.rfile.read(n))
                        agg.ingest(payload)
                    except (ValueError, KeyError, TypeError) as e:
                        self._send(400, f"bad push: {e}\n".encode(),
                                   "text/plain")
                        return
                    # the response doubles as the chief->worker command
                    # channel: a pending coordinated-profile broadcast is
                    # delivered (once per host) in the push reply
                    reply: dict = {"ok": True}
                    pending = getattr(agg, "pending_profile", None)
                    if pending is not None:
                        cmd = pending(int(payload.get("host", -1)))
                        if cmd:
                            reply["profile"] = cmd
                    self._send(200, json.dumps(reply).encode(),
                               "application/json")
                except BrokenPipeError:
                    pass

            def log_message(self, fmt, *args):  # scrapes are not log lines
                log.debug("metrics server: " + fmt, *args)

        try:
            self._httpd = http.server.ThreadingHTTPServer((host, port), Handler)
        except OSError as e:
            if port == 0:
                raise
            # A configured port that is already bound (a stale process, a
            # port-sharing collision on one box) must not crash chief
            # startup — fall back to an ephemeral port and say so loudly.
            log.warning(
                "metrics port %d unavailable (%s); falling back to an "
                "ephemeral port — read it back from MetricsServer.port",
                port, e,
            )
            self._httpd = http.server.ThreadingHTTPServer((host, 0), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="tfde-metrics-server",
        )
        self._thread.start()
        log.info("metrics server listening on %s:%d", host, self.port)

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)


def serve_metrics(port: int = 0, host: str = "0.0.0.0",
                  registry: Optional[metrics.Registry] = None,
                  aggregator=None) -> MetricsServer:
    """Convenience: start a MetricsServer over the default registry — the
    one-liner an inference deployment calls next to its batcher."""
    return MetricsServer(port=port, host=host, registry=registry,
                         aggregator=aggregator)
