"""In-process TensorBoard launcher — start_tensorboard capability
(mnist_keras_distributed.py:27-28,192-197,277-280).

The reference launches TensorBoard in-process on worker 0, port from
``$TB_PORT`` (default 6006), pointed at the working dir. Same here, gated on
the chief process; if the tensorboard package is missing or broken the
launcher degrades to logging the equivalent CLI command (the event files are
standard — any TensorBoard can read them, see observability/tensorboard.py).
"""

from __future__ import annotations

import logging
import os
from typing import Optional

log = logging.getLogger(__name__)


def start_tensorboard(logdir: str, port: Optional[int] = None) -> Optional[str]:
    """Launch TensorBoard for logdir; returns its URL or None if unavailable.

    Call on the chief only (the reference's worker-0 gate, mnist_keras:278 —
    which it implements with a buggy `is 0` identity check; we compare
    process_index properly)."""
    import jax

    if jax.process_index() != 0:
        return None
    if port is None:  # explicit argument wins over the env var
        try:
            port = int(os.environ["TB_PORT"])
        except (KeyError, ValueError):
            port = 6006
    try:
        import tensorboard.program as tb_program

        tb = tb_program.TensorBoard()
        tb.configure(logdir=logdir, port=port)
        url = tb.launch()
        log.info("TensorBoard started at %s --logdir=%s", url, logdir)
        return url
    except Exception as e:  # missing/broken tensorboard install
        log.info(
            "in-process TensorBoard unavailable (%s); run externally: "
            "tensorboard --logdir=%s --port=%d",
            e,
            logdir,
            port,
        )
        return None
