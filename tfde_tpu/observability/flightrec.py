"""Crash flight recorder: the last N structured events, dumped on death.

A `kill -9` leaves zero forensic artifacts; a SIGTERM or an unhandled
exception leaves only whatever the logger happened to flush. The flight
recorder closes that gap for everything short of SIGKILL: every subsystem
that already emits spans also appends a structured event (step transitions,
checkpoint save/restore, serving admits, health beats, sentry trips) to a
bounded in-memory ring buffer — O(1) per event, no I/O on the hot path —
and the buffer is written atomically to
``<model_dir>/debug/flight_<host>_<pid>.jsonl`` when the process is about
to die: on SIGTERM, on an unhandled exception, or explicitly from the
supervisor's abort path. Post-mortems then exist even when the process died
mid-step, and `tools/obs_dump.py` pretty-prints them.

Design points:
- One default recorder per process (like the metric registry); `record()`
  is a deque append under a lock, cheap enough for per-step call sites.
- `arm(model_dir)` fixes the dump directory and installs the death hooks
  ONCE: a chaining SIGTERM handler (it dumps, then defers to whatever
  handler was installed before it — the preemption guard's checkpoint
  commit path keeps working, and the process still exits by signal) and a
  chaining `sys.excepthook`. Signal installation is main-thread-only and
  silently skipped elsewhere, mirroring the preemption guard.
- Dumps are atomic (tmp file + `os.replace`) and idempotent: the latest
  dump wins, so a SIGTERM dump followed by the excepthook firing does not
  interleave partial files.
- `load(path)` is the inverse — the replay surface tests and obs_dump use.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import signal as _signal
import sys
import threading
import time
from typing import Dict, List, Optional

log = logging.getLogger(__name__)

DEFAULT_CAPACITY = 512


def _host_id() -> int:
    """This process's rank for the dump filename: jax.process_index() when
    the distributed runtime is already up, else the env contract, else 0.
    Never *initializes* jax — a recorder must be armable before (or
    without) any backend."""
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            return int(jax.process_index())
        except Exception:
            pass
    try:
        return int(os.environ.get("TFDE_PROCESS_ID")
                   or os.environ.get("TASK_INDEX") or 0)
    except ValueError:
        return 0


class FlightRecorder:
    """Bounded ring of event dicts. `record()` anywhere, `dump()` on death."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self._events: collections.deque = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._dump_dir: Optional[str] = None
        self._hooks_installed = False
        self._prev_sigterm = None
        self._prev_excepthook = None
        self.last_dump_path: Optional[str] = None

    @property
    def capacity(self) -> int:
        return self._events.maxlen  # type: ignore[return-value]

    def record(self, kind: str, **fields) -> None:
        """Append one event. `kind` names the event ('step', 'ckpt_save',
        'sentry_trip', ...); extra fields must be JSON-serializable."""
        ev = {"ts": time.time(), "kind": kind}
        ev.update(fields)
        with self._lock:
            self._events.append(ev)

    def events(self) -> List[dict]:
        """Oldest-to-newest copy of the ring."""
        with self._lock:
            return list(self._events)

    # -- arming --------------------------------------------------------------
    def arm(self, model_dir: str, install_handlers: bool = True) -> None:
        """Fix the dump directory to `<model_dir>/debug` and (once) install
        the SIGTERM + excepthook death hooks. Re-arming with a new
        model_dir just moves the dump target."""
        self._dump_dir = os.path.join(model_dir, "debug")
        self.record("armed", model_dir=model_dir, host=_host_id(),
                    pid=os.getpid())
        if install_handlers and not self._hooks_installed:
            self._install_hooks()

    def _install_hooks(self) -> None:
        self._hooks_installed = True
        self._prev_excepthook = sys.excepthook

        def excepthook(etype, value, tb):
            try:
                self.record("unhandled_exception", error=f"{etype.__name__}: {value}")
                self.dump("unhandled_exception")
            except Exception:
                pass
            (self._prev_excepthook or sys.__excepthook__)(etype, value, tb)

        sys.excepthook = excepthook

        if threading.current_thread() is not threading.main_thread():
            return  # signal API is main-thread-only; excepthook still armed

        def on_sigterm(signum, frame):
            try:
                self.record("sigterm", signum=signum)
                self.dump("sigterm")
            except Exception:
                pass
            prev = self._prev_sigterm
            if callable(prev):
                prev(signum, frame)
            elif prev == _signal.SIG_IGN:
                return
            else:  # SIG_DFL (or None): die by the signal's own semantics
                _signal.signal(signum, _signal.SIG_DFL)
                _signal.raise_signal(signum)

        try:
            self._prev_sigterm = _signal.signal(_signal.SIGTERM, on_sigterm)
        except (ValueError, OSError):  # exotic embedding; stay inert
            self._prev_sigterm = None

    # -- dumping -------------------------------------------------------------
    def dump_path(self) -> Optional[str]:
        if self._dump_dir is None:
            return None
        return os.path.join(
            self._dump_dir, f"flight_{_host_id()}_{os.getpid()}.jsonl"
        )

    def dump(self, reason: str = "manual") -> Optional[str]:
        """Atomically write the ring (plus a trailing 'dump' marker event)
        as JSONL. Safe to call repeatedly — the newest dump replaces the
        file whole, never interleaves. Returns the path (None when not
        armed with a dump dir)."""
        path = self.dump_path()
        if path is None:
            log.debug("flight recorder dump(%s): not armed; skipping", reason)
            return None
        self.record("dump", reason=reason)
        events = self.events()
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                for ev in events:
                    f.write(json.dumps(ev, sort_keys=True, default=repr) + "\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError:
            log.exception("flight recorder dump to %s failed", path)
            return None
        self.last_dump_path = path
        return path


def load(path: str) -> List[dict]:
    """Parse a dumped flight file back into its event list (the replay
    inverse of `dump`). Tolerates a truncated final line — the one case a
    dying process can leave behind."""
    events: List[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                log.warning("flight file %s: skipping unparseable line", path)
    return events


_default = FlightRecorder()


def default_recorder() -> FlightRecorder:
    """The process-wide recorder every subsystem appends to by default."""
    return _default


def record(kind: str, **fields) -> None:
    _default.record(kind, **fields)


def arm(model_dir: str, install_handlers: bool = True) -> None:
    _default.arm(model_dir, install_handlers=install_handlers)


def dump(reason: str = "manual") -> Optional[str]:
    return _default.dump(reason)
