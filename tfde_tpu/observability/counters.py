"""Process-wide named counters — the resilience subsystem's export surface.

The reference's runtime surfaced fault-tolerance activity only as log lines;
at pod scale operators need the numbers (how many restarts, how many retried
saves, how many steps were replayed after a preemption) as *metrics* they
can alarm on. This module is the minimal substrate: monotonic named counters
any subsystem can increment, a snapshot for tests/exporters, and a bridge
that writes the snapshot as TensorBoard scalars through the existing
SummaryWriter so the counters land next to the training curves.

Thread-safe by design: the health watchdog and retry wrappers increment from
background threads while the train loop reads.
"""

from __future__ import annotations

import threading
from typing import Dict

_lock = threading.Lock()
_counters: Dict[str, float] = {}


def incr(name: str, amount: float = 1.0) -> float:
    """Add `amount` to counter `name` (creating it at 0); returns the new
    value. Negative amounts are rejected — counters are monotonic; gauges
    belong in the summary writer directly."""
    if amount < 0:
        raise ValueError(f"counter {name!r}: negative increment {amount}")
    with _lock:
        _counters[name] = _counters.get(name, 0.0) + amount
        return _counters[name]


def value(name: str) -> float:
    with _lock:
        return _counters.get(name, 0.0)


def snapshot() -> Dict[str, float]:
    """Point-in-time copy of every counter."""
    with _lock:
        return dict(_counters)


def reset(prefix: str = "") -> None:
    """Zero counters (those under `prefix`, or all) — test isolation hook."""
    with _lock:
        if not prefix:
            _counters.clear()
            return
        for k in [k for k in _counters if k.startswith(prefix)]:
            del _counters[k]


def export_scalars(writer, step: int, prefix: str = "") -> Dict[str, float]:
    """Write the current snapshot (optionally filtered by `prefix`) to a
    SummaryWriter-compatible object at `step`; returns what was written.
    `writer` may be None (non-chief / no model_dir) — then this is only the
    snapshot read."""
    snap = {k: v for k, v in snapshot().items() if k.startswith(prefix)}
    if writer is not None and snap:
        writer.scalars(step, snap)
    return snap
