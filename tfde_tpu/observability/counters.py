"""Process-wide named counters — kept as a thin shim over the metric
registry (observability/metrics.py).

This was the resilience subsystem's original export surface: monotonic
named counters any subsystem can increment, a snapshot for tests/exporters,
and a TensorBoard bridge. The registry generalized it (gauges, histograms,
Prometheus/JSONL exposition), but this module's API is load-bearing across
resilience/, checkpoint/, utils/fs and their tests, so it stays — every
call now lands in `metrics.default_registry()`, which means counters
incremented here show up in `/metrics` and every other exposition path
for free.
"""

from __future__ import annotations

from typing import Dict

from tfde_tpu.observability import metrics


def incr(name: str, amount: float = 1.0) -> float:
    """Add `amount` to counter `name` (creating it at 0); returns the new
    value. Negative amounts are rejected — counters are monotonic; use a
    registry gauge for values that can fall."""
    return metrics.default_registry().counter(name).incr(amount)


def value(name: str) -> float:
    m = metrics.default_registry().get(name)
    return m.value if m is not None and m.kind == "counter" else 0.0


def snapshot() -> Dict[str, float]:
    """Point-in-time copy of every counter (counters only — gauges and
    histograms live in the registry's own snapshot())."""
    reg = metrics.default_registry()
    return {
        name: v for name, v in reg.scalars().items()
        if reg.get(name) is not None and reg.get(name).kind == "counter"
    }


def reset(prefix: str = "") -> None:
    """Drop counters (those under `prefix`, or all) — test isolation hook.
    Only counters: a prefix-less reset here must not clear the registry's
    gauges/histograms out from under their owners."""
    reg = metrics.default_registry()
    for name in list(snapshot()):
        if name.startswith(prefix):
            reg.remove(name)


def export_scalars(writer, step: int, prefix: str = "") -> Dict[str, float]:
    """Write the current snapshot (optionally filtered by `prefix`) to a
    SummaryWriter-compatible object at `step`; returns what was written.
    `writer` may be None (non-chief / no model_dir) — then this is only the
    snapshot read."""
    snap = {k: v for k, v in snapshot().items() if k.startswith(prefix)}
    if writer is not None and snap:
        writer.scalars(step, snap)
    return snap
