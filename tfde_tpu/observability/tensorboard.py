"""TensorBoard event-file writer, dependency-free.

The reference gets scalar summaries (loss, accuracy, steps/sec) written every
`save_summary_steps=100` by the Estimator machinery and serves them via an
in-process TensorBoard (SURVEY.md §5 observability; mnist_keras:192-197,
246-247). This module re-creates the capability natively: it emits standard
`events.out.tfevents.*` files that any TensorBoard install can read, without
importing TensorFlow — the Event/Summary protobuf wire format and the
TFRecord framing (length + masked crc32c) are small enough to encode by hand.

Wire formats implemented:
- protobuf varint/length-delimited encoding for
  Event{wall_time=1(double), step=2(int64), file_version=3(string),
        summary=5(Summary)} and
  Summary{value=1(repeated Value{tag=1(string), simple_value=2(float)})};
- TFRecord: <len u64le><masked-crc32c(len) u32le><data><masked-crc32c(data)>.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Dict, Optional

from tfde_tpu.utils import fs

# -- crc32c (Castagnoli), table-driven ---------------------------------------

_CRC_TABLE = []


def _build_table() -> None:
    poly = 0x82F63B78
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        _CRC_TABLE.append(c)


_build_table()


_native_crc = None  # resolved lazily; False = probed and unavailable


def crc32c(data: bytes) -> int:
    # the native slice-by-8 crc (native/loader.cc) is ~100x this table
    # walk — load-bearing for the streaming TFRecord reader, where the
    # Python loop was the decode bottleneck (tests/test_streaming.py)
    global _native_crc
    if _native_crc is None:
        try:
            from tfde_tpu import native as _native

            _native_crc = _native.crc32c if _native.available() else False
        except Exception:
            _native_crc = False
    if _native_crc:
        return _native_crc(data)
    c = 0xFFFFFFFF
    for b in data:
        c = _CRC_TABLE[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    c = crc32c(data)
    return ((c >> 15 | c << 17) + 0xA282EAD8) & 0xFFFFFFFF


# -- minimal protobuf encoding ----------------------------------------------


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _key(field: int, wire: int) -> bytes:
    return _varint(field << 3 | wire)


def _double(field: int, v: float) -> bytes:
    return _key(field, 1) + struct.pack("<d", v)


def _float(field: int, v: float) -> bytes:
    return _key(field, 5) + struct.pack("<f", v)


def _int64(field: int, v: int) -> bytes:
    return _key(field, 0) + _varint(v & 0xFFFFFFFFFFFFFFFF)


def _bytes_field(field: int, v: bytes) -> bytes:
    return _key(field, 2) + _varint(len(v)) + v


def _summary_value(tag: str, value: float) -> bytes:
    return _bytes_field(1, _bytes_field(1, tag.encode()) + _float(2, float(value)))


def _event(
    wall_time: float,
    step: Optional[int] = None,
    file_version: Optional[str] = None,
    summary_values: Optional[Dict[str, float]] = None,
) -> bytes:
    msg = _double(1, wall_time)
    if step is not None:
        msg += _int64(2, int(step))
    if file_version is not None:
        msg += _bytes_field(3, file_version.encode())
    if summary_values:
        body = b"".join(_summary_value(t, v) for t, v in summary_values.items())
        msg += _bytes_field(5, body)
    return msg


def _tfrecord(data: bytes) -> bytes:
    header = struct.pack("<Q", len(data))
    return (
        header
        + struct.pack("<I", _masked_crc(header))
        + data
        + struct.pack("<I", _masked_crc(data))
    )


# -- public writer -----------------------------------------------------------


class SummaryWriter:
    """Append-only scalar summary writer for one logdir.

    Usage: `w = SummaryWriter(model_dir); w.scalars(step, {"loss": 0.3})`.
    Only the chief process should construct one (host-side side effects are
    chief-only, matching the reference's worker-0 TensorBoard gating,
    mnist_keras:277-280).

    The logdir may be a remote URL (gs://...) — the reference documents the
    working dir as GCS-capable (mnist_keras:41-44). Local dirs get a real
    append stream; remote ones buffer the event stream in memory and rewrite
    the whole object on flush (object stores have no append; event files are
    scalar-only and tiny, so the rewrite is cheap and gives true flush
    durability — see utils/fs.py).
    """

    def __init__(self, logdir: str, filename_suffix: str = ""):
        fs.makedirs(logdir, exist_ok=True)
        fname = "events.out.tfevents.%010d.%s%s" % (
            int(time.time()),
            socket.gethostname(),
            filename_suffix,
        )
        self._path = fs.join(logdir, fname)
        self._lock = threading.Lock()
        self._remote = fs.is_remote(logdir)
        if self._remote:
            self._buf = bytearray()
            self._f = None
        else:
            self._f = open(self._path, "ab")
        self._write(_event(time.time(), file_version="brain.Event:2"))
        self.flush()

    def _write(self, event_bytes: bytes) -> None:
        with self._lock:
            record = _tfrecord(event_bytes)
            if self._remote:
                self._buf.extend(record)
            else:
                self._f.write(record)

    def scalars(self, step: int, values: Dict[str, float]) -> None:
        self._write(
            _event(time.time(), step=step, summary_values={k: float(v) for k, v in values.items()})
        )

    def scalar(self, step: int, tag: str, value: float) -> None:
        self.scalars(step, {tag: value})

    def flush(self) -> None:
        with self._lock:
            if self._remote:
                fs.write_bytes(self._path, bytes(self._buf))
            else:
                self._f.flush()

    def close(self) -> None:
        self.flush()
        if self._f is not None:
            self._f.close()

    @property
    def path(self) -> str:
        return self._path
