"""Per-request distributed tracing for the serving path.

The metrics layer (metrics.py / aggregate.py) is aggregate by design:
histograms can say p99 TTFT regressed, never WHICH request, WHICH hop,
or WHY. This module adds the missing request-scoped timeline: the
Router mints a trace id per ``/v1/generate`` session and propagates it
via the ``X-Tfde-Trace`` HTTP header; every process on the request's
path (router, prefill-tier replica, decode replica) appends structured
span events to a bounded in-memory ring — queue, plan/admit (cold /
warm / primed, with prefix-cache hit + reused-token annotations),
per-scan-round decode, stream-out, and the primed-KV hand-off.

The ring has three exits:

- ``dump()`` writes ``<model_dir>/debug/trace_<host>_<pid>.jsonl``
  (armed like the flight recorder; ReplicaServer/Router dump on close);
- a replica serves its ring per trace id from ``GET /trace/<id>``, and
  the chief-side collector (`aggregate.collect_trace`) stitches the
  per-process rings into one cross-process waterfall;
- ``to_chrome()`` renders any event list as Chrome trace-event JSON
  (Perfetto/chrome://tracing loadable) — ``tools/obs_dump.py --trace``
  is the CLI for both.

Flag discipline (the `spans.set_trace_active` rule): tracing is OFF by
default and every hook begins with a single module-global check
(`active()`), so the steady-state serving cost of this file is one
pointer compare per call site. Enable with ``TFDE_TRACE=on`` (or an
integer ring capacity) in the environment — `tools/tier1.sh` forwards
it so the whole suite doubles as a tracing-on parity sweep — or
programmatically with `enable()`.

Exemplar linking: `note_exemplar(metric, value, trace_id)` keeps the
trace ids of the SLOWEST observations per metric (the batcher feeds
``serving/ttft_ms`` / ``serving/tpot_ms``), so "p99 got worse"
dereferences to concrete request waterfalls instead of a bucket count.
"""

from __future__ import annotations

import collections
import contextlib
import json
import logging
import os
import threading
import time
import uuid
from typing import Dict, Iterable, Iterator, List, Optional

from tfde_tpu.observability.flightrec import _host_id

log = logging.getLogger(__name__)

DEFAULT_CAPACITY = 8192
#: the propagation header: router -> replicas on the request, router ->
#: client on the response
HEADER = "X-Tfde-Trace"
#: slowest observations kept per metric by the exemplar store
EXEMPLAR_KEEP = 8

#: event keys that are structural, not annotations (everything else is
#: carried into the Chrome export's `args`)
_CORE_KEYS = ("ts", "dur", "name", "proc", "pid", "trace", "traces")

_lock = threading.Lock()
#: the ring IS the on/off flag: None means off, and every record path
#: starts with that one read — the near-zero steady-state cost contract
_ring: Optional[collections.deque] = None
_proc: Optional[str] = None
_dump_dir: Optional[str] = None
_tls = threading.local()
_exemplars: Dict[str, List[tuple]] = {}


# -- lifecycle ---------------------------------------------------------------
def _env_capacity() -> Optional[int]:
    """``TFDE_TRACE`` -> ring capacity (None = off). Accepts on/off
    spellings or an integer capacity, the ``TFDE_PREFIX_CACHE`` idiom."""
    spec = os.environ.get("TFDE_TRACE", "off").strip().lower()
    if spec in ("", "0", "off", "false", "no", "none"):
        return None
    if spec in ("1", "on", "true", "yes"):
        return DEFAULT_CAPACITY
    try:
        return max(1, int(spec))
    except ValueError:
        log.warning("TFDE_TRACE=%r not understood; tracing on with the "
                    "default ring capacity", spec)
        return DEFAULT_CAPACITY


def enable(capacity: Optional[int] = None) -> None:
    """Turn recording on with a bounded ring (idempotent; re-enabling
    with a new capacity re-rings, keeping the newest events)."""
    global _ring
    cap = DEFAULT_CAPACITY if capacity is None else max(1, int(capacity))
    with _lock:
        old = list(_ring) if _ring is not None else []
        _ring = collections.deque(old, maxlen=cap)


def disable() -> None:
    """Turn recording off and drop everything (ring + exemplars) — back
    to the zero-cost state."""
    global _ring
    with _lock:
        _ring = None
        _exemplars.clear()


def active() -> bool:
    """THE hot-path guard every instrumentation site checks first."""
    return _ring is not None


def clear() -> None:
    with _lock:
        if _ring is not None:
            _ring.clear()
        _exemplars.clear()


# -- identity ----------------------------------------------------------------
def new_id() -> str:
    """Mint a trace id (the Router does this once per /v1/generate)."""
    return uuid.uuid4().hex[:16]


def set_process(label: str) -> None:
    """Name this process in every subsequent event ('router',
    'replica0', ...); defaults to 'host<process_index>'."""
    global _proc
    _proc = str(label)


def process() -> str:
    return _proc if _proc is not None else f"host{_host_id()}"


def current() -> Optional[str]:
    """The trace id bound to this thread (None outside `bind`)."""
    return getattr(_tls, "trace", None)


@contextlib.contextmanager
def bind(trace_id: Optional[str]) -> Iterator[None]:
    """Bind `trace_id` as this thread's current trace for the block, so
    `span()`/`event()` call sites that don't thread an id explicitly
    (e.g. spans.py's training-phase timers) still attach to it."""
    prev = getattr(_tls, "trace", None)
    _tls.trace = trace_id
    try:
        yield
    finally:
        _tls.trace = prev


# -- recording ---------------------------------------------------------------
def event(name: str, trace: Optional[str] = None,
          traces: Optional[Iterable[str]] = None,
          ts: Optional[float] = None, dur: Optional[float] = None,
          **args) -> None:
    """Append one span event. `trace` ties it to one request; `traces`
    to several (a decode scan serves many rows at once). `ts` is wall
    epoch seconds (defaults to now, minus `dur` when given — i.e. a
    duration recorded at block exit gets its START as the timestamp);
    `dur` is seconds. Extra kwargs are annotations. No-op unless
    `active()`."""
    ring = _ring
    if ring is None:
        return
    if ts is None:
        ts = time.time() - (dur or 0.0)
    ev: dict = {"ts": ts, "name": name, "proc": process(),
                "pid": os.getpid()}
    if trace is None and traces is None:
        trace = current()
    if trace is not None:
        ev["trace"] = trace
    if traces is not None:
        ev["traces"] = [t for t in traces if t is not None]
    if dur is not None:
        ev["dur"] = dur
    if args:
        ev.update(args)
    with _lock:
        ring.append(ev)


@contextlib.contextmanager
def span(name: str, trace: Optional[str] = None, **args) -> Iterator[None]:
    """Record the enclosed block as one duration event (recorded even
    when the block raises). Cheap no-op when tracing is off."""
    if _ring is None:
        yield
        return
    t0 = time.perf_counter()
    wall = time.time()
    try:
        yield
    finally:
        event(name, trace=trace, ts=wall,
              dur=time.perf_counter() - t0, **args)


def events(trace_id: Optional[str] = None) -> List[dict]:
    """Copy of the ring, oldest first; filtered to one trace id when
    given (an event matches via its `trace` field or membership in its
    `traces` list)."""
    with _lock:
        evs = list(_ring) if _ring is not None else []
    if trace_id is None:
        return evs
    return [e for e in evs
            if e.get("trace") == trace_id or trace_id in e.get("traces", ())]


# -- exemplars ---------------------------------------------------------------
def note_exemplar(metric: str, value: float,
                  trace_id: Optional[str]) -> None:
    """Remember `trace_id` as an exemplar for `metric` if `value` ranks
    among the slowest seen — the histogram-to-waterfall link."""
    if _ring is None or trace_id is None:
        return
    with _lock:
        lst = _exemplars.setdefault(metric, [])
        lst.append((float(value), trace_id))
        lst.sort(key=lambda p: -p[0])
        del lst[EXEMPLAR_KEEP:]


def exemplars(metric: Optional[str] = None):
    """Slowest-first [(value, trace id)] rows for one metric, or
    {metric: rows} for all of them."""
    with _lock:
        if metric is not None:
            return [{"value": v, "trace": t}
                    for v, t in _exemplars.get(metric, [])]
        return {m: [{"value": v, "trace": t} for v, t in lst]
                for m, lst in _exemplars.items()}


# -- dump / load (the flightrec file contract) -------------------------------
def arm(model_dir: str) -> None:
    """Fix the dump directory to ``<model_dir>/debug`` (no death hooks:
    the flight recorder owns those; a trace ring is dumped explicitly,
    typically at server close)."""
    global _dump_dir
    _dump_dir = os.path.join(model_dir, "debug")


def dump_path() -> Optional[str]:
    if _dump_dir is None:
        return None
    return os.path.join(_dump_dir,
                        f"trace_{_host_id()}_{os.getpid()}.jsonl")


def dump(reason: str = "manual") -> Optional[str]:
    """Atomically write the ring as JSONL (newest dump replaces the file
    whole). Returns the path; None when not armed or not active."""
    path = dump_path()
    if path is None or _ring is None:
        return None
    evs = events()
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            for ev in evs:
                f.write(json.dumps(ev, sort_keys=True, default=repr) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except OSError:
        log.exception("trace dump to %s (%s) failed", path, reason)
        return None
    return path


def load(path: str) -> List[dict]:
    """Parse a dumped trace file back; tolerates a truncated tail."""
    out: List[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                log.warning("trace file %s: skipping unparseable line",
                            path)
    return out


# -- stitching + Chrome export -----------------------------------------------
def stitch(event_lists: Iterable[List[dict]]) -> List[dict]:
    """Merge per-process event lists into one wall-clock timeline. All
    serving processes of one cluster share a machine (or NTP-close
    hosts), so epoch `ts` IS the common axis. Exact duplicates are
    dropped: when router and replica share a process (in-process tests,
    single-host dev), the collector sees the same ring twice — once
    locally, once over HTTP."""
    merged: List[dict] = []
    seen = set()
    for lst in event_lists:
        for e in lst:
            key = json.dumps(e, sort_keys=True, default=repr)
            if key in seen:
                continue
            seen.add(key)
            merged.append(e)
    merged.sort(key=lambda e: (e.get("ts", 0.0), e.get("name", "")))
    return merged


def to_chrome(evs: List[dict]) -> dict:
    """Render events as Chrome trace-event JSON: duration events become
    complete ('X') slices, instant events 'i' marks; each source process
    gets its own pid row named via 'process_name' metadata — load the
    result straight into Perfetto / chrome://tracing."""
    pids: Dict[str, int] = {}
    out: List[dict] = []
    for e in sorted(evs, key=lambda e: e.get("ts", 0.0)):
        proc = str(e.get("proc", "?"))
        pid = pids.setdefault(proc, len(pids) + 1)
        args = {k: v for k, v in e.items() if k not in _CORE_KEYS}
        if "trace" in e:
            args["trace"] = e["trace"]
        if "traces" in e:
            args["traces"] = e["traces"]
        rec = {
            "name": str(e.get("name", "?")),
            "cat": "serving",
            "ts": float(e.get("ts", 0.0)) * 1e6,   # epoch us
            "pid": pid,
            "tid": pid,
            "args": args,
        }
        if "dur" in e:
            rec["ph"] = "X"
            rec["dur"] = max(float(e["dur"]), 0.0) * 1e6
        else:
            rec["ph"] = "i"
            rec["s"] = "p"
        out.append(rec)
    meta = [{"name": "process_name", "ph": "M", "pid": pid, "tid": pid,
             "args": {"name": proc}} for proc, pid in pids.items()]
    return {"traceEvents": meta + out, "displayTimeUnit": "ms"}


# honor the env knob at import so subprocess replicas (which inherit the
# parent's environment) come up tracing without any wiring
_cap = _env_capacity()
if _cap is not None:
    enable(_cap)
del _cap
