"""Goodput accounting: classify a run's wall-clock and report the fraction
that trained the model.

At pod scale the question "how fast is training" is really "where did the
wall-clock go": JIT compile, host input waits, checkpoint stalls, and the
restart tax (backoff sleeps plus replayed steps after a preemption) all
eat time that steps/sec alone hides. The span instrumentation (spans.py)
already buckets every train-loop phase into registry histograms and the
resilience layer counts restarts/lost steps — this module is the ledger
that turns those into a single breakdown.

Usage::

    ledger = GoodputLedger()          # snapshot baseline, start the clock
    supervisor.run(input_fn, steps)   # or estimator.train(...)
    report = ledger.report()          # {"seconds", "fractions", "goodput", ...}

The ledger diffs the registry against its construction-time baseline, so
ledgers compose in long-lived processes (benchmarks, notebooks) without a
registry reset. Categories are DISJOINT by construction and `other` is the
residual, so fractions sum to 1.0 exactly; the acceptance bar is that
`other` stays small (< 5 % on a summary-synced CPU run) — i.e. the spans
really do cover the loop.

Category definitions (all in seconds of the measured wall):
- ``init``          state build + checkpoint restore before the loop
                    (train/init span; includes init-time compiles)
- ``compile``       first-step JIT compile+execute (train/compile_seconds,
                    measured by the loop's first-step block-until-ready)
                    PLUS mid-run recompiles observed by the recompile
                    sentinel at watched sites (the per-site
                    ``compile/<site>/seconds_total`` counters, minus the
                    first-step portion already inside
                    train/compile_seconds) — a serving-bucket or
                    int8/ZeRO step-swap recompile lands here, not in
                    ``compute``
- ``data_wait``     host-input blocking in the device feed (train/data_wait)
- ``compute``       step time (start-to-start iteration wall minus the
                    categorized chunks, recorded as train/step) plus the
                    summary device_get that drains the async device queue
                    (train/device_sync), minus replayed-step time — the
                    productive part. Measured start-to-start because under
                    async dispatch the device drains *between* host
                    statements; wrapping the dispatch call alone undercounts
- ``checkpoint``    save dispatch + end-of-run wait (checkpoint/save,
                    checkpoint/wait; restores are under init)
- ``summary``       TensorBoard event writing (train/summary_write)
- ``eval``          inline eval passes (train/eval)
- ``restart_loss``  the preemption tax: restart backoff sleeps, elastic
                    re-bootstrap time (resilience/rebootstrap_seconds),
                    plus replayed steps (resilience/lost_steps x mean
                    step time)
- ``profile``       profile-capture overhead: the host-side dispatch cost
                    of opening/closing XProf trace windows
                    (profile/capture, recorded by observability/profiler).
                    Split out so a triggered capture window can't
                    masquerade as a compute regression
- ``other``         residual — loop bookkeeping and anything unspanned

``goodput`` = compute / wall.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from tfde_tpu.observability import metrics

#: span-sum sources: ledger category -> histogram names whose sum deltas
#: feed it directly
_SPAN_SOURCES = {
    "init": ("train/init",),
    "data_wait": ("train/data_wait",),
    "checkpoint": ("checkpoint/save", "checkpoint/wait"),
    "summary": ("train/summary_write",),
    "eval": ("train/eval",),
}

CATEGORIES = ("init", "compile", "data_wait", "compute", "checkpoint",
              "summary", "eval", "restart_loss", "profile", "other")


class GoodputLedger:
    """Wall-clock ledger over a registry. Construct before the run (it
    snapshots a baseline and starts a monotonic clock); `report()` after.
    Pass `wall_seconds` to report() when the caller measured the wall
    itself (e.g. around a supervisor.run call); default is time since
    construction."""

    def __init__(self, registry: Optional[metrics.Registry] = None):
        self._reg = registry or metrics.default_registry()
        self._t0 = time.perf_counter()
        self._base = self._totals()

    def _totals(self) -> Dict[str, float]:
        """Monotonic totals the ledger consumes, from the registry."""
        snap = self._reg.snapshot()
        out: Dict[str, float] = {}
        for name, data in snap.items():
            if data["type"] == "histogram":
                out[f"sum:{name}"] = float(data["sum"])
                out[f"count:{name}"] = float(data["count"])
            elif data["type"] == "counter":
                out[name] = float(data["value"])
        return out

    def _delta(self, now: Dict[str, float], key: str) -> float:
        return max(0.0, now.get(key, 0.0) - self._base.get(key, 0.0))

    def report(self, wall_seconds: Optional[float] = None) -> dict:
        """Classify the wall-clock since construction. Returns::

            {"wall_seconds": float,
             "steps": int,                  # train steps completed
             "mean_step_seconds": float,
             "lost_steps": float,           # replayed after restarts
             "restarts": float,
             "seconds": {category: float},  # disjoint, sums to ~wall
             "fractions": {category: float},# seconds/wall, sums to 1.0
             "goodput": float}              # compute / wall
        """
        now = self._totals()
        wall = (time.perf_counter() - self._t0
                if wall_seconds is None else float(wall_seconds))
        d = lambda k: self._delta(now, k)

        seconds = {cat: sum(d(f"sum:{h}") for h in hists)
                   for cat, hists in _SPAN_SOURCES.items()}
        # compile = the first step's synchronous compile+execute wall plus
        # every later recompile the sentinel attributed to a watched site
        # (recompile.py). The sentinel-measured portion of the first step
        # (train/compile_seconds_measured, recorded by the loop) is
        # subtracted so it is not double-counted; with no sentinel
        # installed both extra terms are zero and this reduces to the old
        # first-step-only definition. Only WATCHED sites feed the bucket —
        # un-watched compiles (eval hooks, checkpoint glue) stay where
        # they fell, keeping the categories disjoint.
        first_measured = d("train/compile_seconds_measured")
        site_compile = sum(
            self._delta(now, k)
            for k in set(now) | set(self._base)
            if k.startswith("compile/") and k.endswith("/seconds_total")
            and k.count("/") >= 2
            and not k.startswith("compile/memwatch")
        )
        midrun = max(0.0, site_compile - first_measured)
        seconds["compile"] = d("train/compile_seconds") + midrun

        # productive time: step iterations + the sync that drains compute
        steps = d("count:train/step")
        step_time = d("sum:train/step") + d("sum:train/device_sync")
        mean_step = step_time / steps if steps else 0.0
        lost = d("resilience/lost_steps")
        # replayed steps burned step-shaped wall-clock that trained nothing
        replay = min(step_time, lost * mean_step)
        # mid-run recompiles of the train step itself burned step-shaped
        # wall too (the first-step compile is already outside step_time)
        in_step = min(
            max(0.0, step_time - replay),
            max(0.0, d("compile/train_step/seconds_total") - first_measured),
        )
        # profile-capture overhead (start/stop-trace dispatch) — its
        # in-step share comes out of compute (a traced window must not
        # read as a compute regression); any remainder (serving-side
        # captures outside the train loop) comes out of the residual
        profile = d("sum:profile/capture")
        seconds["profile"] = profile
        in_step_profile = min(profile,
                              max(0.0, step_time - replay - in_step))
        seconds["compute"] = step_time - replay - in_step - in_step_profile
        seconds["restart_loss"] = (
            replay + d("resilience/restart_backoff_seconds")
            + d("resilience/rebootstrap_seconds")  # elastic topology changes
        )

        accounted = sum(seconds.values())
        if wall <= 0:
            wall = max(accounted, 1e-9)
        seconds["other"] = max(0.0, wall - accounted)
        fractions = {k: v / wall for k, v in seconds.items()}
        return {
            "wall_seconds": wall,
            "steps": int(steps),
            "mean_step_seconds": mean_step,
            "lost_steps": lost,
            "restarts": d("resilience/restarts"),
            "seconds": seconds,
            "fractions": fractions,
            "goodput": seconds["compute"] / wall,
        }

    def export(self, registry: Optional[metrics.Registry] = None,
               wall_seconds: Optional[float] = None) -> dict:
        """report() + publish the result as ``goodput/*`` gauges so the
        breakdown rides every exposition path (/metrics, JSONL, TB)."""
        rep = self.report(wall_seconds)
        reg = registry or self._reg
        reg.gauge("goodput/goodput").set(rep["goodput"])
        reg.gauge("goodput/wall_seconds").set(rep["wall_seconds"])
        reg.gauge("goodput/mean_step_seconds").set(rep["mean_step_seconds"])
        for cat, frac in rep["fractions"].items():
            reg.gauge(f"goodput/{cat}_fraction").set(frac)
        for cat, secs in rep["seconds"].items():
            reg.gauge(f"goodput/{cat}_seconds").set(secs)
        return rep
