"""Cross-host metric aggregation: the distributed half of observability.

The PR-2 layer is strictly per-process — the chief's `/metrics` endpoint
knows nothing about workers, so a straggling or NaN-producing host is
invisible until the supervisor's no-progress abort fires. This module makes
the fleet visible from one scrape:

- **push path** (`MetricsPusher` / `push_once`): non-chief hosts POST a
  periodic JSON snapshot (`metrics.flatten_snapshot`, so serving stats and
  resilience counters ride along for free) to the chief's metrics endpoint
  at ``/push``. Plain stdlib HTTP — no new dependencies, tolerant of a
  chief that is not up yet (failures are counted, not raised).
- **chief side** (`ClusterAggregator`): stores each host's latest snapshot
  with its arrival time, derives a rolling per-host step-time median from
  the pushed ``train/step`` histogram deltas, and on every rollup exports:

  - ``cluster/hosts_reporting`` / ``cluster/hosts_stale`` gauges,
  - cluster step-time rollups ``cluster/step_time_{min,median,max}_ms``
    (min/median/max of the live hosts' rolling medians),
  - the **straggler detector**: any host whose rolling median exceeds the
    cluster median by `straggler_factor` flips ``cluster/straggler_host``
    (host id, -1 when healthy) and ``cluster/straggler_ratio``, and feeds
    `resilience/health.note_straggler` so the resilience layer sees it;
  - a dead host (no push within `stale_after`) is excluded from rollups,
    counted stale, and reported to `resilience/health.note_stale_host`.

- **exposition**: `prometheus_text()` renders every host's scalar snapshot
  as genuinely *labelled* series (``tfde_train_steps_per_sec{host="1"}``)
  plus per-host liveness (``tfde_cluster_host_up{host="1"}``), which
  `MetricsServer` appends to its `/metrics` body — so one chief scrape
  answers "which host is sick".

Rollups are recomputed on every ingest AND every scrape, so staleness flips
without waiting for a (never-arriving) push from the dead host.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional

from tfde_tpu.observability import metrics
from tfde_tpu.observability.exposition import prom_name

log = logging.getLogger(__name__)

#: step-time histogram the per-host medians are derived from
STEP_HIST = "train/step"


def snapshot_payload(registry: Optional[metrics.Registry] = None,
                     host: Optional[int] = None) -> dict:
    """The push body: this process's flattened snapshot plus identity."""
    from tfde_tpu.observability.flightrec import _host_id

    reg = registry or metrics.default_registry()
    return {
        "host": int(_host_id() if host is None else host),
        "pid": os.getpid(),
        "ts": time.time(),
        "metrics": metrics.flatten_snapshot(reg.snapshot()),
    }


def push_once(url: str, registry: Optional[metrics.Registry] = None,
              host: Optional[int] = None, timeout: float = 2.0) -> bool:
    """POST one snapshot to the chief's ``/push``. Returns success; never
    raises — an unreachable chief must not take a worker down with it."""
    import urllib.request

    body = json.dumps(snapshot_payload(registry, host)).encode()
    req = urllib.request.Request(
        url, data=body, headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            ok = 200 <= resp.status < 300
            reply = resp.read()
    except Exception as e:
        metrics.counter("cluster/push_errors").incr()
        log.debug("metrics push to %s failed: %s", url, e)
        return False
    if ok:
        _apply_push_reply(reply)
    return ok


def _apply_push_reply(reply: bytes) -> None:
    """The push channel is bidirectional on the cheap: the chief's /push
    response can carry a pending coordinated-profile command, which we
    deliver to this worker's trigger hub (stamped ``coordinated`` so the
    chief-side broadcast sink never re-broadcasts it — no loops)."""
    try:
        payload = json.loads(reply)
    except (ValueError, TypeError):
        return  # pre-JSON chiefs reply b"ok\n" — nothing to deliver
    cmd = payload.get("profile") if isinstance(payload, dict) else None
    if not isinstance(cmd, dict):
        return
    try:
        from tfde_tpu.observability import profiler

        profiler.trigger(
            str(cmd.get("reason") or "coordinated"),
            key=f"coordinated:{cmd.get('id')}",
            span=cmd.get("span"),
            coordinated=True,
        )
    except Exception:
        log.exception("coordinated profile command failed")


class MetricsPusher:
    """Background thread pushing this host's snapshot every `interval`
    seconds (plus once at stop, so the chief sees the final state)."""

    def __init__(self, url: str, interval: float = 5.0,
                 registry: Optional[metrics.Registry] = None,
                 host: Optional[int] = None, timeout: float = 2.0):
        if interval <= 0:
            raise ValueError("interval must be positive")
        self.url = url
        self.interval = float(interval)
        self._reg = registry
        self._host = host
        self._timeout = timeout
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="tfde-metrics-pusher"
        )
        self._thread.start()
        log.info("metrics pusher -> %s every %.1fs", url, self.interval)

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            if push_once(self.url, self._reg, self._host, self._timeout):
                metrics.counter("cluster/pushes").incr()

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)
        # final push: the chief's last view of this host includes everything
        # up to shutdown (e.g. the final step's serving stats)
        push_once(self.url, self._reg, self._host, self._timeout)


class _Host:
    """Chief-side record of one pushing host."""

    def __init__(self, window: int):
        self.flat: Dict[str, float] = {}
        self.last_push = 0.0
        self.pushes = 0
        self.step_samples: collections.deque = collections.deque(maxlen=window)
        self._prev_sum: Optional[float] = None
        self._prev_count: Optional[float] = None

    def ingest(self, flat: Dict[str, float], now: float) -> None:
        self.flat = flat
        self.last_push = now
        self.pushes += 1
        s = flat.get(f"{STEP_HIST}/sum")
        c = flat.get(f"{STEP_HIST}/count")
        if s is None or c is None:
            return
        if self._prev_sum is not None and c > self._prev_count:
            # mean step time over the push interval: recency-aware, unlike
            # the cumulative p50 the histogram itself would report
            self.step_samples.append(
                (s - self._prev_sum) / (c - self._prev_count)
            )
        elif self._prev_sum is None and c > 0:
            self.step_samples.append(s / c)
        self._prev_sum, self._prev_count = s, c

    def median_step(self) -> Optional[float]:
        if not self.step_samples:
            return None
        vals = sorted(self.step_samples)
        n = len(vals)
        mid = n // 2
        return vals[mid] if n % 2 else 0.5 * (vals[mid - 1] + vals[mid])


def _median(vals: List[float]) -> float:
    vals = sorted(vals)
    n = len(vals)
    mid = n // 2
    return vals[mid] if n % 2 else 0.5 * (vals[mid - 1] + vals[mid])


class ClusterAggregator:
    """Chief-side store + rollup engine for pushed host snapshots.

    `include_local` (a host id, usually 0) folds the chief's OWN registry
    into every rollup as a synthetic push, so cluster medians cover the
    chief without it HTTP-pushing to itself.
    """

    def __init__(self,
                 registry: Optional[metrics.Registry] = None,
                 straggler_factor: float = 2.0,
                 stale_after: float = 15.0,
                 window: int = 32,
                 include_local: Optional[int] = None,
                 on_straggler: Optional[Callable[[int, float], None]] = None,
                 on_stale: Optional[Callable[[int, float], None]] = None,
                 coordinate: bool = False,
                 clock: Callable[[], float] = time.monotonic):
        if straggler_factor <= 1.0:
            raise ValueError("straggler_factor must be > 1")
        self._reg = registry or metrics.default_registry()
        self.straggler_factor = float(straggler_factor)
        self.stale_after = float(stale_after)
        self._window = int(window)
        self._include_local = include_local
        self._clock = clock
        self._lock = threading.Lock()
        self._hosts: Dict[int, _Host] = {}
        if on_straggler is None or on_stale is None:
            from tfde_tpu.resilience import health as _health

            on_straggler = on_straggler or _health.note_straggler
            on_stale = on_stale or _health.note_stale_host
        self._on_straggler = on_straggler
        self._on_stale = on_stale
        self._flagged_straggler: Optional[int] = None
        self._known_stale: set = set()
        # coordinated-capture broadcast: one pending command, delivered at
        # most once per host via the /push response channel
        self._profile_cmd: Optional[dict] = None
        self._profile_delivered: set = set()
        self._profile_seq = 0
        if coordinate:
            from tfde_tpu.observability import profiler

            profiler.hub().register("cluster_broadcast", self._broadcast_sink)

    # -- coordinated capture -------------------------------------------------
    def broadcast_profile(self, reason: str,
                          span: Optional[int] = None) -> dict:
        """Queue a coordinated capture command for every pushing host. The
        next /push from each host picks it up (once per host) through the
        push response, so cross-host windows need no new channel."""
        with self._lock:
            self._profile_seq += 1
            cmd = {"id": self._profile_seq, "reason": str(reason)}
            if span is not None:
                cmd["span"] = int(span)
            self._profile_cmd = cmd
            self._profile_delivered = set()
        metrics.counter("cluster/profile_broadcasts").incr()
        log.warning("cluster: broadcasting coordinated profile capture "
                    "#%d (%s) to pushing hosts", cmd["id"], reason)
        return dict(cmd)

    def pending_profile(self, host: int) -> Optional[dict]:
        """The command `host` has not seen yet, marking it delivered —
        called by the /push handler to build its response."""
        with self._lock:
            cmd = self._profile_cmd
            if cmd is None or host in self._profile_delivered:
                return None
            self._profile_delivered.add(int(host))
            return dict(cmd)

    def _broadcast_sink(self, reason: str, span: int, info: dict) -> bool:
        # a command that ARRIVED via the push channel must not fan back
        # out — only locally-originated triggers broadcast
        if info.get("coordinated"):
            return False
        self.broadcast_profile(reason, span)
        return True

    # -- ingest --------------------------------------------------------------
    def ingest(self, payload: dict) -> None:
        """Accept one pushed snapshot ({"host", "metrics", ...})."""
        host = int(payload["host"])
        flat = payload.get("metrics") or {}
        now = self._clock()
        with self._lock:
            h = self._hosts.setdefault(host, _Host(self._window))
            h.ingest({k: float(v) for k, v in flat.items()}, now)
        metrics.counter("cluster/snapshots_received").incr()
        self.rollup()

    def _ingest_local_locked(self, now: float) -> None:
        if self._include_local is None:
            return
        h = self._hosts.setdefault(self._include_local, _Host(self._window))
        h.ingest(metrics.flatten_snapshot(self._reg.snapshot()), now)

    # -- rollups -------------------------------------------------------------
    def rollup(self) -> dict:
        """Recompute cluster gauges from the current host set; returns the
        rollup as plain data (the test/obs_dump surface)."""
        now = self._clock()
        with self._lock:
            self._ingest_local_locked(now)
            hosts = dict(self._hosts)
        live, stale = {}, {}
        for hid, h in hosts.items():
            if now - h.last_push > self.stale_after:
                stale[hid] = now - h.last_push
            else:
                live[hid] = h
        medians = {hid: m for hid, h in live.items()
                   if (m := h.median_step()) is not None}

        g = self._reg.gauge
        g("cluster/hosts_reporting").set(len(live))
        g("cluster/hosts_stale").set(len(stale))
        out = {"hosts_reporting": len(live), "hosts_stale": len(stale),
               "stale_hosts": sorted(stale), "straggler_host": -1,
               "straggler_ratio": 0.0, "host_medians_ms": {}}

        # Edge-detection state (_known_stale / _flagged_straggler below) is
        # mutated under the lock: rollup() runs concurrently from every
        # /push handler thread, and unlocked read-modify-writes here can
        # double-fire callbacks or lose the re-arm. Callbacks themselves
        # fire AFTER release — a slow or re-entrant on_stale must not hold
        # the aggregator's lock. (tools/tfdelint.py lock-discipline rule.)
        fire_stale = []
        with self._lock:
            for hid, age in stale.items():
                if hid not in self._known_stale:
                    self._known_stale.add(hid)
                    fire_stale.append((hid, age))
            self._known_stale &= set(stale)  # re-arm when a host comes back
        for hid, age in fire_stale:
            log.warning("cluster: host %d stale (last push %.1fs ago)",
                        hid, age)
            try:
                self._on_stale(hid, age)
            except Exception:
                log.exception("on_stale callback failed")

        if medians:
            cluster_med = _median(list(medians.values()))
            g("cluster/step_time_min_ms").set(min(medians.values()) * 1e3)
            g("cluster/step_time_median_ms").set(cluster_med * 1e3)
            g("cluster/step_time_max_ms").set(max(medians.values()) * 1e3)
            out["host_medians_ms"] = {
                hid: m * 1e3 for hid, m in medians.items()
            }
            straggler, ratio = -1, 0.0
            if len(medians) >= 2 and cluster_med > 0:
                worst = max(medians, key=medians.get)
                worst_ratio = medians[worst] / cluster_med
                if worst_ratio > self.straggler_factor:
                    straggler, ratio = worst, worst_ratio
            g("cluster/straggler_host").set(straggler)
            g("cluster/straggler_ratio").set(ratio)
            out["straggler_host"], out["straggler_ratio"] = straggler, ratio
            with self._lock:
                fire = (straggler >= 0
                        and straggler != self._flagged_straggler)
                self._flagged_straggler = (straggler if straggler >= 0
                                           else None)
            if fire:
                log.warning(
                    "cluster: host %d straggling (%.1fx the cluster median "
                    "step time)", straggler, ratio,
                )
                try:
                    self._on_straggler(straggler, ratio)
                except Exception:
                    log.exception("on_straggler callback failed")
                try:
                    # ask the trigger hub for capture evidence — on a chief
                    # built with coordinate=True the broadcast sink turns
                    # this into a cross-host window
                    from tfde_tpu.observability import profiler

                    profiler.trigger(
                        "straggler", key=f"straggler:{straggler}",
                        host=straggler, ratio=round(ratio, 2),
                    )
                except Exception:
                    log.exception("straggler profile trigger failed")

        # fleet KV capacity (observability/capacity.py): waste is the
        # allocation-weighted mean — a big idle replica's waste should
        # dominate a small busy one's — and headroom is the plain sum of
        # rows the fleet could still admit
        alloc = waste_weighted = headroom = 0.0
        seen_kv = False
        for h in live.values():
            a = h.flat.get("kv/allocated_bytes")
            if a is None:
                continue
            seen_kv = True
            alloc += a
            waste_weighted += a * h.flat.get("kv/waste_frac", 0.0)
            headroom += h.flat.get("kv/headroom_rows", 0.0)
        if seen_kv:
            waste = waste_weighted / alloc if alloc else 0.0
            g("cluster/kv_waste_frac").set(waste)
            g("cluster/kv_headroom_rows").set(headroom)
            out["kv_waste_frac"] = waste
            out["kv_headroom_rows"] = headroom

        # fleet boot picture (observability/boot.py): how long a joining
        # replica takes to become placeable — the autoscaler's scale-out
        # lead-time signal — as the live hosts' time-to-ready p50/max
        ttrs = [v for h in live.values()
                if (v := h.flat.get("boot/time_to_ready_seconds"))
                is not None]
        if ttrs:
            p50 = _median(ttrs)
            g("cluster/boot_p50_seconds").set(p50)
            g("cluster/boot_max_seconds").set(max(ttrs))
            out["boot_p50_seconds"] = p50
            out["boot_max_seconds"] = max(ttrs)
        return out

    # -- exposition ----------------------------------------------------------
    def prometheus_text(self, prefix: str = "tfde_") -> str:
        """Per-host labelled series appended to the chief's /metrics body:
        every pushed scalar as ``<name>{host="<id>"}`` plus liveness/age."""
        now = self._clock()
        with self._lock:
            hosts = {hid: (dict(h.flat), h.last_push)
                     for hid, h in self._hosts.items()}
        lines = []
        for hid in sorted(hosts):
            flat, last_push = hosts[hid]
            age = now - last_push
            up = 0 if age > self.stale_after else 1
            lines.append(f'{prefix}cluster_host_up{{host="{hid}"}} {up}')
            lines.append(
                f'{prefix}cluster_host_age_seconds{{host="{hid}"}} {age:.3f}'
            )
            for name in sorted(flat):
                lines.append(
                    f'{prom_name(name, prefix)}{{host="{hid}"}} '
                    f'{float(flat[name])!r}'
                )
        return "\n".join(lines) + ("\n" if lines else "")

    def host_metrics(self, prefixes: tuple) -> Dict[int, Dict[str, float]]:
        """{host: {name: value}} for every pushed scalar whose name starts
        with one of `prefixes` — the filtered read the router's mem/compile
        snapshot block uses (the full flat dict can be thousands of
        series; nobody should json-dump it per poll)."""
        with self._lock:
            return {
                hid: {name: float(v) for name, v in h.flat.items()
                      if name.startswith(prefixes)}
                for hid, h in self._hosts.items()
            }

    def hosts(self) -> Dict[int, dict]:
        """{host: {"age": s, "pushes": n, "median_step_ms": ms|None}} —
        the obs_dump/debugging surface."""
        now = self._clock()
        with self._lock:
            return {
                hid: {
                    "age": now - h.last_push,
                    "pushes": h.pushes,
                    "median_step_ms": (
                        m * 1e3 if (m := h.median_step()) is not None else None
                    ),
                }
                for hid, h in self._hosts.items()
            }


# -- distributed request traces ----------------------------------------------
def fetch_trace(url: str, trace_id: str, timeout: float = 5.0) -> list:
    """GET one replica's /trace/<id> slice; [] on any transport failure
    (a SIGKILL'd replica has no ring left to contribute — the router's
    own events still tell its side of the story)."""
    import urllib.request

    target = f"{url.rstrip('/')}/trace/{trace_id}"
    try:
        with urllib.request.urlopen(target, timeout=timeout) as resp:
            payload = json.loads(resp.read())
        return list(payload.get("events") or [])
    except Exception as e:  # noqa: BLE001 — dead peers are expected here
        log.debug("trace fetch from %s failed: %s", target, e)
        return []


def collect_trace(trace_id: str, urls, local_events=None,
                  timeout: float = 5.0) -> dict:
    """Chief-side stitcher: pull one trace id's events from every replica
    endpoint, merge them with the caller's local ring slice onto the
    shared wall-clock axis (trace.stitch), and report which processes
    contributed — the payload behind the Router's GET /trace/<id>."""
    from tfde_tpu.observability import trace as _trace

    lists = [fetch_trace(u, trace_id, timeout=timeout) for u in urls]
    if local_events is not None:
        lists.append(list(local_events))
    events = _trace.stitch(lists)
    procs = sorted({str(e["proc"]) for e in events if e.get("proc")})
    return {"trace": trace_id, "events": events, "procs": procs}
