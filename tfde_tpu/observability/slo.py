"""Serving SLO tracking: TTFT/TPOT attainment and burn rate.

The serving comparison literature reports latency SLO attainment — the
fraction of requests whose time-to-first-token (TTFT) and
time-per-output-token (TPOT) land under a target — as the headline
serving metric, and SRE practice alerts on BURN RATE rather than raw
attainment: how fast the error budget is being consumed,

    burn = (1 - window_attainment) / (1 - objective)

so burn 1.0 means "exactly on budget", 10 means "budget gone in a tenth
of the window". Two windows (fast + slow) distinguish a blip from a
sustained regression.

`SLOTracker` lives in the Router (the client-observed vantage point:
TTFT includes queueing, placement, re-routes, and the primed hand-off),
publishes ``slo/*`` gauges into the metrics registry — so they ride the
existing ``/metrics`` exposition and the cluster push loop for free —
and its `summary()` is embedded in the Router's ``/replicas`` table.
Targets come from the constructor or the environment
(``TFDE_SLO_TTFT_MS`` / ``TFDE_SLO_TPOT_MS`` / ``TFDE_SLO_OBJECTIVE``).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, Optional, Sequence

from tfde_tpu import knobs
from tfde_tpu.observability import metrics

DEFAULT_TTFT_MS = 500.0
DEFAULT_TPOT_MS = 200.0
DEFAULT_OBJECTIVE = 0.99
#: fast window catches a live incident; slow window catches a grind
DEFAULT_WINDOWS = (300.0, 3600.0)
#: per-metric sample ring bound — at 10k rps nobody wants this unbounded
MAX_SAMPLES = 65536
#: a burn-threshold crossing needs at least this many in-window samples
#: before it can trigger a capture — one slow request at cold start is not
#: an incident
MIN_BURN_SAMPLES = 8


def _env_float(name: str, default: float) -> float:
    # central registry parse: a non-numeric value warns once and falls
    # back, instead of silently running the default (tfde_tpu/knobs.py)
    return float(knobs.env_float(name, default))


class SLOTracker:
    """Sliding-window attainment + burn-rate accounting for one serving
    endpoint. Thread-safe; `record()` is called from request handler
    threads, `summary()` from status endpoints."""

    def __init__(self, ttft_target_ms: Optional[float] = None,
                 tpot_target_ms: Optional[float] = None,
                 objective: Optional[float] = None,
                 windows: Sequence[float] = DEFAULT_WINDOWS,
                 registry: Optional[metrics.Registry] = None,
                 clock=time.monotonic):
        self.ttft_target_ms = float(
            ttft_target_ms if ttft_target_ms is not None
            else _env_float("TFDE_SLO_TTFT_MS", DEFAULT_TTFT_MS))
        self.tpot_target_ms = float(
            tpot_target_ms if tpot_target_ms is not None
            else _env_float("TFDE_SLO_TPOT_MS", DEFAULT_TPOT_MS))
        obj = (objective if objective is not None
               else _env_float("TFDE_SLO_OBJECTIVE", DEFAULT_OBJECTIVE))
        # clamp away the burn-rate pole at objective == 1.0
        self.objective = min(max(float(obj), 0.0), 0.9999)
        self.windows = tuple(float(w) for w in windows)
        self._reg = registry or metrics.default_registry()
        self._clock = clock
        self._lock = threading.Lock()
        # per metric: ring of (t, ok) + cumulative totals
        self._samples: Dict[str, collections.deque] = {
            "ttft": collections.deque(maxlen=MAX_SAMPLES),
            "tpot": collections.deque(maxlen=MAX_SAMPLES),
        }
        self._total = {"ttft": 0, "tpot": 0}
        self._ok = {"ttft": 0, "tpot": 0}
        # burn-rate capture trigger: fire on the upward crossing of the
        # fast-window burn past TFDE_PROFILE_BURN_THRESHOLD (edge-detected
        # per metric so a sustained burn triggers once, not per request)
        self.burn_threshold = _env_float("TFDE_PROFILE_BURN_THRESHOLD", 10.0)
        self._burning = {"ttft": False, "tpot": False}
        self._publish_targets()

    # -- ingest --------------------------------------------------------------
    def record(self, ttft_ms: Optional[float] = None,
               tpot_ms: Optional[float] = None) -> None:
        """Account one finished request (either latency may be absent —
        a 1-token response has no TPOT) and refresh the gauges."""
        now = self._clock()
        with self._lock:
            if ttft_ms is not None:
                self._note("ttft", now, float(ttft_ms) <= self.ttft_target_ms)
            if tpot_ms is not None:
                self._note("tpot", now, float(tpot_ms) <= self.tpot_target_ms)
        self._publish()

    def _note(self, metric: str, now: float, ok: bool) -> None:
        self._samples[metric].append((now, ok))
        self._total[metric] += 1
        self._ok[metric] += 1 if ok else 0

    # -- queries -------------------------------------------------------------
    def attainment(self, metric: str,
                   window: Optional[float] = None) -> Optional[float]:
        """Fraction of requests under target — over a trailing window in
        seconds, or since startup when `window` is None. None before the
        first sample."""
        with self._lock:
            if window is None:
                total, ok = self._total[metric], self._ok[metric]
            else:
                cut = self._clock() - window
                rows = [okf for (t, okf) in self._samples[metric] if t >= cut]
                total, ok = len(rows), sum(rows)
        if total == 0:
            return None
        return ok / total

    def burn_rate(self, metric: str, window: float) -> Optional[float]:
        att = self.attainment(metric, window)
        if att is None:
            return None
        return (1.0 - att) / (1.0 - self.objective)

    def window_stats(self, metric: str, window: float):
        """(in-window sample count, attainment or None) — callers that
        act on a burn rate (the router's brownout) need the count to
        apply the same MIN_BURN_SAMPLES guard the capture trigger uses:
        one slow request at cold start is not an incident."""
        with self._lock:
            cut = self._clock() - window
            rows = [ok for (t, ok) in self._samples[metric] if t >= cut]
        if not rows:
            return 0, None
        return len(rows), sum(rows) / len(rows)

    def summary(self) -> dict:
        """The /replicas embed: targets, lifetime attainment, and burn
        per window for both latency SLOs."""
        out: dict = {
            "objective": self.objective,
            "ttft_target_ms": self.ttft_target_ms,
            "tpot_target_ms": self.tpot_target_ms,
            "windows_s": list(self.windows),
        }
        for metric in ("ttft", "tpot"):
            out[f"{metric}_requests"] = self._total[metric]
            out[f"{metric}_attainment"] = self.attainment(metric)
            out[f"{metric}_burn_rate"] = {
                f"{int(w)}s": self.burn_rate(metric, w) for w in self.windows
            }
        return out

    # -- exposition ----------------------------------------------------------
    def _publish_targets(self) -> None:
        self._reg.gauge("slo/objective").set(self.objective)
        self._reg.gauge("slo/ttft_target_ms").set(self.ttft_target_ms)
        self._reg.gauge("slo/tpot_target_ms").set(self.tpot_target_ms)

    def _publish(self) -> None:
        for metric in ("ttft", "tpot"):
            self._reg.gauge(f"slo/{metric}_requests").set(self._total[metric])
            att = self.attainment(metric)
            if att is not None:
                self._reg.gauge(f"slo/{metric}_attainment").set(att)
            for w in self.windows:
                burn = self.burn_rate(metric, w)
                if burn is not None:
                    self._reg.gauge(
                        f"slo/{metric}_burn_rate_{int(w)}s").set(burn)
            self._maybe_trigger_capture(metric)

    def _maybe_trigger_capture(self, metric: str) -> None:
        """Fast-window burn crossing -> profile trigger hub. Edge-detected:
        fires on the upward crossing only, and the hub's cooldown/dedupe
        bound how often evidence capture can actually arm."""
        if self.burn_threshold <= 0 or not self.windows:
            return
        fast = self.windows[0]
        with self._lock:
            cut = self._clock() - fast
            rows = [ok for (t, ok) in self._samples[metric] if t >= cut]
        if len(rows) < MIN_BURN_SAMPLES:
            return
        att = sum(rows) / len(rows)
        burn = (1.0 - att) / (1.0 - self.objective)
        above = burn >= self.burn_threshold
        fire = above and not self._burning[metric]
        self._burning[metric] = above
        if not fire:
            return
        from tfde_tpu.observability import profiler

        profiler.trigger(
            f"slo_burn_{metric}",
            burn_rate=round(burn, 2),
            window_s=fast,
            threshold=self.burn_threshold,
        )
