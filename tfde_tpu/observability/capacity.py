"""KV-capacity observability: occupancy ledger, headroom model, usage meter.

ROADMAP item 1 claims paged-KV will unlock 4-8x serving concurrency by
eliminating pad-ladder waste — but nothing measured that waste, so the
win could be neither sized in advance nor proven after. This module is
the capacity half of the observability stack, three legs:

- **CapacityLedger** — the occupancy picture, one subclass per cache
  layout. The dense base reports the per-row slab: the batcher feeds
  committed cells (the true per-row index) per decode round and
  pad-ladder allocation per admission wave; the ledger publishes the
  ``kv/{allocated_bytes,used_bytes,waste_frac,rows_active,rows_free}``
  gauges plus per-bucket pad-waste counters and a unit-interval waste
  histogram. ``kv/used_bytes`` is exact against
  `memwatch.device_bytes` over the live cache cells (tests pin 20%),
  because the per-cell cost is derived from the slab's own leaf bytes.
  `PagedCapacityLedger` re-bases the same gauges on the block pool
  (``TFDE_PAGED_KV``): allocated bytes are the blocks actually held,
  so ``kv/waste_frac`` collapses to intra-block slack — the measured
  statement of what paging reclaimed — and the ``kv/pool_blocks_*``
  gauges split the pool into active/trie/free.
- **CapacityModel** — headroom: memory budget (``TFDE_CAPACITY_BUDGET_
  BYTES``, 0 = slab-derived) folded with the measured per-row cost into
  ``kv/headroom_rows`` / ``kv/headroom_tokens``. `ReplicaServer /load`
  and the Router's saturation gate consume these (behind
  ``TFDE_ADMIT_KV_HEADROOM``) so admission can reject on *memory*
  before queue depth collapses.
- **UsageMeter** — per-request prompt tokens, generated tokens, and
  KV-residency (token·seconds of slab occupancy, the capacity-cost unit
  the Gemma-on-TPU serving study sizes fleets by), stamped with the
  priority class, counted under ``usage/*`` and appended to a bounded
  JSONL log (``TFDE_USAGE_LOG``) — the metering seam multi-tenant
  adapters will key by tenant id.

Thread-safety: the ledger and meter are written from the batcher's step
loop under `ReplicaServer.lock` but *read* from HTTP handler threads
(`/load`'s kv block, tests), so each carries its own lock and is listed
in `tools/tfdelint.py` LOCKED_CLASSES — every shared-state access holds
it (the PR 14 guarded-attrs rule).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Optional

from tfde_tpu import knobs
from tfde_tpu.observability import metrics

#: cache-pytree bookkeeping leaves (prefix_cache.INDEX_LEAVES) — never
#: K/V bytes; named here too so observability never imports inference
_INDEX_LEAVES = ("cache_index", "position_index")

#: unit-interval buckets for pad-waste fractions — the default registry
#: ladder is a seconds scale and would collapse every observation into
#: its first bucket
WASTE_BUCKETS = (0.05, 0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9,
                 0.95, 1.0)

DEFAULT_USAGE_LOG_BYTES = 8 * 1024 * 1024


def _is_index_path(path) -> bool:
    return str(getattr(path[-1], "key", path[-1])) in _INDEX_LEAVES


def kv_slab_bytes(cache) -> int:
    """Total K/V bytes of a dense batcher cache (index leaves excluded):
    the ledger's allocated-bytes baseline AND the denominator of its
    per-cell cost model."""
    import jax

    total = 0
    for path, leaf in jax.tree_util.tree_leaves_with_path(cache):
        if _is_index_path(path):
            continue
        total += int(leaf.nbytes)
    return total


def kv_dtype_census(cache) -> dict:
    """Dtype split of a KV cache tree (index leaves and block tables
    excluded): payload vs scale-sidecar bytes, the payload leaf dtype,
    and the fp32-equivalent payload cost — what the same cells would
    occupy unquantized at fp32 (the quantized-vs-fp delta obs_dump and
    the bench report; for a bf16 model halve it mentally). Scale leaves
    are the ``*_scale`` sidecars the int8 KV cache rides
    (models/transformer.py); an fp cache has none, so its split is all
    payload and ``kv_dtype`` names the storage float type."""
    import jax

    payload = scale = payload_elems = 0
    dtype = None
    for path, leaf in jax.tree_util.tree_leaves_with_path(cache):
        if _is_index_path(path):
            continue
        name = str(getattr(path[-1], "key", path[-1]))
        if name == "block_table":
            continue
        if name.endswith("_scale"):
            scale += int(leaf.nbytes)
        else:
            payload += int(leaf.nbytes)
            payload_elems += int(leaf.size)
            dtype = str(leaf.dtype)
            bits = int(leaf.dtype.itemsize) * 8
    return {
        "kv_dtype": dtype or "none",
        "kv_quant_bits": bits if dtype else 0,
        "kv_payload_bytes": payload,
        "kv_scale_bytes": scale,
        "kv_fp32_equiv_bytes": payload_elems * 4,
    }


class CapacityLedger:
    """Dense-slab KV occupancy and pad-ladder waste accounting.

    One ledger per batcher cache. `observe` is fed the host-side
    committed counts every decode round / stats publish;
    `note_admission` is fed every admitted request's (bucket, true
    prompt length) at wave time. Listed in tools/tfdelint.py
    LOCKED_CLASSES: all shared state under `_lock`.
    """

    def __init__(self, batch_size: int, cells_per_row: int,
                 slab_bytes: int,
                 registry: Optional[metrics.Registry] = None,
                 census: Optional[dict] = None):
        if batch_size < 1 or cells_per_row < 1:
            raise ValueError(
                f"need batch_size/cells_per_row >= 1, got "
                f"{batch_size}/{cells_per_row}"
            )
        self._lock = threading.Lock()
        self._b = int(batch_size)
        self._cells = int(cells_per_row)
        self._slab_bytes = int(slab_bytes)
        #: dtype split of the slab (kv_dtype_census) — prices the
        #: quantized-vs-fp delta; empty when the builder predates it
        self._census = dict(census or {})
        #: measured per-cell cost: the slab's own bytes over its cells,
        #: so used_bytes sums exactly to the slab when every row is full
        self._cell_bytes = self._slab_bytes / float(self._b * self._cells)
        self._reg = registry or metrics.default_registry()
        self._used_cells = 0
        self._rows_active = 0
        self._pad_alloc_tokens = 0
        self._pad_waste_tokens = 0
        self._bucket_alloc: Dict[int, int] = {}
        self._bucket_waste: Dict[int, int] = {}

    @classmethod
    def from_cache(cls, cache, batch_size: int, cells_per_row: int,
                   registry: Optional[metrics.Registry] = None
                   ) -> "CapacityLedger":
        """Build a ledger from a freshly-initialized dense slab."""
        return cls(batch_size, cells_per_row, kv_slab_bytes(cache),
                   registry=registry, census=kv_dtype_census(cache))

    # -- read surface --------------------------------------------------------
    @property
    def cell_bytes(self) -> float:
        return self._cell_bytes

    @property
    def row_bytes(self) -> float:
        """Per-row slab cost — the headroom model's admission unit."""
        return self._cell_bytes * self._cells

    @property
    def slab_bytes(self) -> int:
        return self._slab_bytes

    @property
    def cells_per_row(self) -> int:
        return self._cells

    @property
    def census(self) -> dict:
        """The slab/pool dtype split (kv_dtype_census); {} when unknown."""
        return dict(self._census)

    def _publish_census(self) -> dict:
        """Gauge + stats-dict surface of the dtype split: obs_dump's
        --capacity quantized-vs-fp columns read these (the dtype string
        itself rides the /load kv dict; kv/quant_bits is its numeric
        twin for metrics-snapshot readers)."""
        if not self._census:
            return {}
        g = self._reg.gauge
        g("kv/quant_bits").set(self._census.get("kv_quant_bits", 0))
        g("kv/payload_bytes").set(self._census.get("kv_payload_bytes", 0))
        g("kv/scale_bytes").set(self._census.get("kv_scale_bytes", 0))
        g("kv/fp32_equiv_bytes").set(
            self._census.get("kv_fp32_equiv_bytes", 0))
        return dict(self._census)

    # -- the per-round report ------------------------------------------------
    def observe(self, committed, req) -> dict:
        """Fold one host-bookkeeping snapshot (`committed` [B] counts,
        `req` [B] request-id-or-None) into the occupancy gauges; returns
        the stats dict (`/load`'s kv block)."""
        used = 0
        active = 0
        for r in range(self._b):
            if req[r] is not None:
                active += 1
                used += int(committed[r])
        with self._lock:
            self._used_cells = used
            self._rows_active = active
        used_bytes = used * self._cell_bytes
        waste = 1.0 - used / float(self._b * self._cells)
        g = self._reg.gauge
        g("kv/allocated_bytes").set(self._slab_bytes)
        g("kv/used_bytes").set(used_bytes)
        g("kv/waste_frac").set(waste)
        g("kv/rows_active").set(active)
        g("kv/rows_free").set(self._b - active)
        out = {
            "allocated_bytes": self._slab_bytes,
            "used_bytes": used_bytes,
            "used_cells": used,
            "waste_frac": waste,
            "rows_active": active,
            "rows_free": self._b - active,
        }
        out.update(self._publish_census())
        return out

    # -- the per-wave report -------------------------------------------------
    def note_admission(self, kind: str, bucket: int, used_tokens: int
                       ) -> None:
        """One admitted request's pad-ladder cost: `bucket` cells were
        computed/written (the prefill program's shape), `used_tokens` of
        them are real prompt (or suffix) — the rest is the pad waste the
        paged-KV refactor reclaims. Counted per bucket so obs_dump can
        name the worst pad-ladder cell."""
        bucket = int(bucket)
        used = min(int(used_tokens), bucket)
        waste = bucket - used
        with self._lock:
            self._pad_alloc_tokens += bucket
            self._pad_waste_tokens += waste
            self._bucket_alloc[bucket] = (
                self._bucket_alloc.get(bucket, 0) + bucket)
            self._bucket_waste[bucket] = (
                self._bucket_waste.get(bucket, 0) + waste)
        c = self._reg.counter
        c("kv/pad_alloc_tokens").incr(bucket)
        if waste:
            c("kv/pad_waste_tokens").incr(waste)
        c(f"kv/pad_alloc_tokens/bucket_{bucket}").incr(bucket)
        c(f"kv/pad_waste_tokens/bucket_{bucket}").incr(waste)
        self._reg.histogram(
            "kv/pad_waste_frac", buckets=WASTE_BUCKETS
        ).observe(waste / bucket if bucket else 0.0)

    def pad_stats(self) -> dict:
        """Cumulative pad-ladder accounting (tests + obs_dump)."""
        with self._lock:
            return {
                "pad_alloc_tokens": self._pad_alloc_tokens,
                "pad_waste_tokens": self._pad_waste_tokens,
                "per_bucket": {
                    b: {"alloc": self._bucket_alloc[b],
                        "waste": self._bucket_waste.get(b, 0)}
                    for b in sorted(self._bucket_alloc)
                },
            }


class PagedCapacityLedger(CapacityLedger):
    """Block-pool KV occupancy (``TFDE_PAGED_KV``).

    The dense ledger's denominator is the whole pre-carved slab, so
    ``kv/waste_frac`` charges every cell a short request never touches.
    Under paging a row only holds the blocks it was granted, so the
    honest denominator is the blocks ACTUALLY HELD (active rows + trie)
    and the remaining waste is intra-block slack plus not-yet-decoded
    lifetime blocks — the ISSUE's acceptance bound. `snapshot` is a
    duck-typed callable (observability never imports inference)
    returning::

        {"total": .., "free": .., "active": ..,   # BlockPool.stats()
         "trie_blocks": ..,                        # trie-held (refs)
         "shared_cells": ..}                       # sum over rows of
                                                   # trie-shared pre_len

    ``used_bytes`` counts each resident token once: row-committed cells
    minus the trie-shared cells they'd double-count, plus the trie's own
    blocks. A block the trie evicted while a row still holds it is
    undercounted by that row's shared cells — waste reads slightly high,
    never low. Inherits `note_admission` (fed fresh-block cells per
    admission, so the pad-waste histogram measures intra-block slack)
    and the dense lock discipline.
    """

    def __init__(self, batch_size: int, cells_per_row: int,
                 pool_bytes: int, num_blocks: int, block: int,
                 snapshot,
                 registry: Optional[metrics.Registry] = None,
                 census: Optional[dict] = None):
        super().__init__(batch_size, cells_per_row, pool_bytes,
                         registry=registry, census=census)
        if num_blocks < 2 or block < 1:
            raise ValueError(
                f"need num_blocks >= 2 and block >= 1, got "
                f"{num_blocks}/{block}"
            )
        self._block = int(block)
        self._blocks_total = int(num_blocks) - 1  # null block excluded
        # per-cell cost re-based on the POOL's geometry (the null block
        # included in the denominator: it is real allocated HBM)
        self._cell_bytes = pool_bytes / float(num_blocks * block)
        self._snapshot = snapshot

    @property
    def block(self) -> int:
        return self._block

    @property
    def block_bytes(self) -> float:
        return self._cell_bytes * self._block

    @property
    def row_bytes(self) -> float:
        """Worst-case per-row cost: a full block table — the headroom
        model's conservative admission unit."""
        blocks_per_row = -(-self._cells // self._block)
        return self.block_bytes * blocks_per_row

    def observe(self, committed, req) -> dict:
        used = 0
        active = 0
        for r in range(self._b):
            if req[r] is not None:
                active += 1
                used += int(committed[r])
        snap = self._snapshot()
        trie_blocks = int(snap.get("trie_blocks", 0))
        shared = int(snap.get("shared_cells", 0))
        held = int(snap["active"])  # rows + trie, refcount-deduped
        free = int(snap["free"])
        used_cells = max(used - shared, 0) + trie_blocks * self._block
        with self._lock:
            self._used_cells = used_cells
            self._rows_active = active
        allocated = held * self.block_bytes
        used_bytes = used_cells * self._cell_bytes
        waste = (1.0 - used_bytes / allocated) if allocated else 0.0
        g = self._reg.gauge
        g("kv/allocated_bytes").set(allocated)
        g("kv/used_bytes").set(used_bytes)
        g("kv/waste_frac").set(waste)
        g("kv/rows_active").set(active)
        g("kv/rows_free").set(self._b - active)
        g("kv/pool_blocks_total").set(self._blocks_total)
        g("kv/pool_blocks_free").set(free)
        g("kv/pool_blocks_active").set(held - trie_blocks)
        g("kv/pool_blocks_trie").set(trie_blocks)
        out = {
            "allocated_bytes": allocated,
            "used_bytes": used_bytes,
            "used_cells": used_cells,
            "waste_frac": waste,
            "rows_active": active,
            "rows_free": self._b - active,
            "pool_blocks_total": self._blocks_total,
            "pool_blocks_free": free,
            "pool_blocks_active": held - trie_blocks,
            "pool_blocks_trie": trie_blocks,
        }
        out.update(self._publish_census())
        return out


class CapacityModel:
    """Headroom: how many more rows/tokens fit before the memory budget.

    budget_bytes = 0 (the default, ``TFDE_CAPACITY_BUDGET_BYTES``)
    derives capacity from the dense slab itself: the slab is
    pre-allocated, so headroom is simply the free rows (and their
    cells). A positive budget models a tighter external constraint —
    the forced-low-budget drill, or a real HBM envelope shared with the
    params — and headroom_rows is what still fits under it at the
    ledger's measured per-row cost.
    """

    def __init__(self, ledger: CapacityLedger,
                 budget_bytes: Optional[int] = None,
                 registry: Optional[metrics.Registry] = None):
        if budget_bytes is None:
            budget_bytes = knobs.env_int("TFDE_CAPACITY_BUDGET_BYTES", 0)
        self._ledger = ledger
        self.budget_bytes = int(budget_bytes or 0)
        self._reg = registry or metrics.default_registry()

    def headroom(self, occ: dict) -> dict:
        """Headroom rows/tokens for an `observe()` stats dict; publishes
        the kv/headroom_* gauges and returns the two fields (merged into
        the /load kv block)."""
        rows_free = int(occ["rows_free"])
        if self.budget_bytes <= 0:
            rows = rows_free
            tokens = rows_free * self._ledger.cells_per_row
        else:
            spare = self.budget_bytes - float(occ["used_bytes"])
            rows = min(rows_free,
                       max(0, int(spare // self._ledger.row_bytes)))
            tokens = min(rows_free * self._ledger.cells_per_row,
                         max(0, int(spare // self._ledger.cell_bytes)))
        g = self._reg.gauge
        g("kv/headroom_rows").set(rows)
        g("kv/headroom_tokens").set(tokens)
        return {"headroom_rows": rows, "headroom_tokens": tokens}


class PagedCapacityModel(CapacityModel):
    """Headroom over a block pool: the admission currency is BLOCKS.

    With no byte budget, what fits is whatever the free list (plus
    nothing — trie slack is the admission gate's business) can grant:
    ``headroom_tokens`` is the free blocks' cells and ``headroom_rows``
    conservatively prices a row at a full block table (the worst case a
    request may claim; the actual per-request block gate lives in the
    batcher's `_admit_capacity`). A positive ``TFDE_CAPACITY_BUDGET_
    BYTES`` first caps the grantable blocks at what the budget buys —
    the same-envelope dense-vs-paged comparison the bench A/B runs.
    """

    def headroom(self, occ: dict) -> dict:
        ledger = self._ledger
        rows_free = int(occ["rows_free"])
        free_blocks = int(occ.get("pool_blocks_free", 0))
        if self.budget_bytes > 0:
            held = int(occ.get("pool_blocks_active", 0)
                       + occ.get("pool_blocks_trie", 0))
            affordable = int(self.budget_bytes // ledger.block_bytes)
            free_blocks = min(free_blocks, max(0, affordable - held))
        blocks_per_row = -(-ledger.cells_per_row // ledger.block)
        rows = min(rows_free, free_blocks // blocks_per_row)
        tokens = free_blocks * ledger.block
        g = self._reg.gauge
        g("kv/headroom_rows").set(rows)
        g("kv/headroom_tokens").set(tokens)
        return {"headroom_rows": rows, "headroom_tokens": tokens}


# -- usage metering -----------------------------------------------------------
class UsageLog:
    """Bounded append-only JSONL usage log.

    One line per finished request. The byte bound (``TFDE_CAPACITY_
    USAGE_LOG_BYTES``) is enforced by compaction: when an append would
    overflow, the oldest lines are dropped until the newest half of the
    bound remains — so the file never grows past the bound and always
    holds the most recent records. Local paths only (the replica's
    model_dir/metrics); listed in tools/tfdelint.py LOCKED_CLASSES.
    """

    def __init__(self, path: str, max_bytes: Optional[int] = None):
        if max_bytes is None:
            max_bytes = knobs.env_int("TFDE_CAPACITY_USAGE_LOG_BYTES",
                                      DEFAULT_USAGE_LOG_BYTES)
        self._lock = threading.Lock()
        self.path = str(path)
        self.max_bytes = int(max_bytes or DEFAULT_USAGE_LOG_BYTES)
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        with self._lock:
            self._f = open(self.path, "a")
            self._bytes = self._f.tell()

    def write(self, rec: dict) -> None:
        line = json.dumps(rec, sort_keys=True) + "\n"
        with self._lock:
            if self._f is None:
                return
            if self._bytes + len(line) > self.max_bytes:
                self._compact_locked(len(line))
            self._f.write(line)
            self._f.flush()
            self._bytes += len(line)

    def _compact_locked(self, incoming: int) -> None:
        """Drop oldest lines until newest `max_bytes // 2` (minus the
        incoming line) remain. Called with the lock held."""
        self._f.close()
        keep_budget = max(self.max_bytes // 2 - incoming, 0)
        with open(self.path) as f:
            lines = f.readlines()
        kept: list = []
        size = 0
        for line in reversed(lines):
            if size + len(line) > keep_budget:
                break
            kept.append(line)
            size += len(line)
        kept.reverse()
        with open(self.path, "w") as f:
            f.writelines(kept)
        self._f = open(self.path, "a")
        self._bytes = size

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


def resolve_usage_log(model_dir: Optional[str] = None
                      ) -> Optional[UsageLog]:
    """Normalize ``TFDE_USAGE_LOG``: unset/``off`` -> None; ``on`` ->
    ``<model_dir>/metrics/usage_<host>.jsonl`` (None when no model_dir
    to anchor it — the ReplicaServer re-arms with its model_dir);
    anything else is an explicit path."""
    spec = (knobs.env_str("TFDE_USAGE_LOG") or "").strip()
    if spec.lower() in ("", "off", "0", "false", "no"):
        return None
    if spec.lower() in ("on", "1", "true", "yes"):
        if model_dir is None:
            return None
        from tfde_tpu.observability.flightrec import _host_id

        return UsageLog(os.path.join(
            model_dir, "metrics", f"usage_{int(_host_id())}.jsonl"))
    return UsageLog(spec)


class UsageMeter:
    """Per-request usage accounting: prompt/generated tokens and
    KV-residency token·seconds, stamped with priority and outcome.

    Residency integrates slab occupancy over the request's resident
    window [admit, finish] with the trapezoid of its token count
    (prompt at admit, prompt+generated at finish) — the billing-grade
    capacity-cost unit. Requests finished before admission (queue-side
    shed/cancel) occupied no slab and meter zero residency. Listed in
    tools/tfdelint.py LOCKED_CLASSES: all shared state under `_lock`.
    """

    def __init__(self, registry: Optional[metrics.Registry] = None,
                 log: Optional[UsageLog] = None):
        self._lock = threading.Lock()
        self._reg = registry or metrics.default_registry()
        self._log = log if log is not None else resolve_usage_log(None)
        self._open: Dict[int, dict] = {}
        self._totals = {"requests": 0, "prompt_tokens": 0,
                        "generated_tokens": 0, "kv_token_seconds": 0.0}

    def arm(self, model_dir: Optional[str]) -> None:
        """Late-bind the JSONL log once a model_dir exists (the
        ReplicaServer construction path). First successful arm wins."""
        log = resolve_usage_log(model_dir)
        with self._lock:
            if self._log is None:
                self._log = log
            elif log is not None:
                log.close()

    @property
    def log_path(self) -> Optional[str]:
        with self._lock:
            return self._log.path if self._log is not None else None

    def begin(self, rid: int, prompt_tokens: int, priority: str) -> None:
        rec = {"rid": int(rid), "prompt_tokens": int(prompt_tokens),
               "priority": str(priority),
               "t_submit": time.perf_counter(), "t_admit": None}
        with self._lock:
            self._open[int(rid)] = rec

    def admitted(self, rid: int) -> None:
        now = time.perf_counter()
        with self._lock:
            rec = self._open.get(int(rid))
            if rec is not None and rec["t_admit"] is None:
                rec["t_admit"] = now

    def finish(self, rid: int, generated_tokens: int,
               outcome: str = "ok") -> Optional[dict]:
        """Close one request's meter; idempotent (an unknown/already-
        closed rid is a no-op). Returns the usage record."""
        now = time.perf_counter()
        with self._lock:
            rec = self._open.pop(int(rid), None)
        if rec is None:
            return None
        prompt = int(rec["prompt_tokens"])
        gen = int(generated_tokens)
        t_admit = rec["t_admit"]
        resident_s = (now - t_admit) if t_admit is not None else 0.0
        # trapezoid: prompt cells at admit, prompt+generated at finish
        residency = (prompt + (prompt + gen)) / 2.0 * resident_s
        out = {
            "ts": time.time(),
            "rid": int(rid),
            "priority": rec["priority"],
            "outcome": str(outcome),
            "prompt_tokens": prompt,
            "generated_tokens": gen,
            "kv_token_seconds": round(residency, 6),
            "queue_wait_s": round(
                (t_admit - rec["t_submit"])
                if t_admit is not None else now - rec["t_submit"], 6),
            "resident_s": round(resident_s, 6),
        }
        with self._lock:
            self._totals["requests"] += 1
            self._totals["prompt_tokens"] += prompt
            self._totals["generated_tokens"] += gen
            self._totals["kv_token_seconds"] += residency
            log = self._log
        c = self._reg.counter
        c("usage/requests").incr()
        c(f"usage/requests/{rec['priority']}").incr()
        c(f"usage/requests/{outcome}").incr()
        c("usage/prompt_tokens").incr(prompt)
        c("usage/generated_tokens").incr(gen)
        c("usage/kv_token_seconds").incr(residency)
        if log is not None:
            log.write(out)
        return out

    def totals(self) -> dict:
        """Cumulative sums across finished requests (the bit-exactness
        pin: prompt/generated totals equal the per-request emissions)."""
        with self._lock:
            return dict(self._totals)

    def close(self) -> None:
        with self._lock:
            log, self._log = self._log, None
        if log is not None:
            log.close()
