"""Profiling — first-class restoration of the reference's commented-out
ProfilerHook (mnist_keras_distributed.py:235-237,261; SURVEY.md §5).

`jax.profiler` traces (XProf format) are viewable in TensorBoard's profile
plugin or xprof; they capture XLA op timelines, HBM usage, and ICI collective
time — the TPU-native superset of ProfilerHook's show_memory=True.
"""

from __future__ import annotations

import contextlib
import logging
import os
from typing import Iterator, Optional

import jax

log = logging.getLogger(__name__)


@contextlib.contextmanager
def profile_trace(
    logdir: Optional[str],
    enabled: Optional[bool] = None,
) -> Iterator[None]:
    """Trace the enclosed block when enabled (or $TFDE_PROFILE is set).

    with profile_trace(run_config.model_dir):    # traces steps inside
        for batch in feed: state, m = step(...)
    """
    if enabled is None:
        enabled = os.environ.get("TFDE_PROFILE", "") not in ("", "0", "false", "False")
    if not enabled or logdir is None:
        yield
        return
    # start_trace itself appends plugins/profile/<timestamp> — pass the raw
    # logdir so TensorBoard's profile plugin finds the run.
    log.info("profiler trace -> %s/plugins/profile", logdir)
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region inside a trace (TraceAnnotation)."""
    return jax.profiler.TraceAnnotation(name)


def _parse_window(raw: str) -> Optional[tuple]:
    """'100:110' -> (100, 110); '100' -> (100, 110) (10-step default)."""
    raw = raw.strip()
    if not raw or raw in ("0", "false", "False"):
        return None
    if ":" in raw:
        a, b = raw.split(":", 1)
        return (int(a), int(b))
    start = int(raw)
    return (start, start + 10)


class StepWindowProfiler:
    """Trace a [start, stop) window of training steps into
    `<logdir>/plugins/profile/` — the ProfilerHook capability
    (mnist_keras_distributed.py:235-237: save_steps + output_dir), wired
    into Estimator.train via RunConfig.profile_steps or $TFDE_PROFILE
    ("start:stop" or "start").

    Steps are *global* steps, so on resume the window refers to the same
    steps it would in an uninterrupted run. The default window starts past
    step 1 to keep the first-compile out of the trace.
    """

    def __init__(self, logdir: Optional[str], window: Optional[tuple] = None):
        if window is None:
            window = _parse_window(os.environ.get("TFDE_PROFILE", ""))
        self._window = window
        self._logdir = logdir
        self._active = False
        if window is not None and logdir is None:
            log.warning("profiling requested but no model_dir — disabled")
            self._window = None
        from tfde_tpu.utils import fs

        if self._window is not None and fs.is_remote(logdir):
            # the profiler's C++ writer only handles local paths here;
            # remote trace upload would need TF's gfile machinery
            log.warning(
                "profiling to a remote model_dir (%s) is not supported — "
                "disabled; point model_dir at local disk to trace", logdir
            )
            self._window = None

    @property
    def enabled(self) -> bool:
        return self._window is not None

    def step(self, step: int) -> None:
        """Call once per train step with the *post-increment* global step."""
        if self._window is None:
            return
        start, stop = self._window
        if not self._active and start <= step < stop:
            log.info(
                "profiler: tracing steps [%d, %d) -> %s/plugins/profile",
                step, stop, self._logdir,
            )
            jax.profiler.start_trace(self._logdir)
            self._active = True
        elif self._active and step >= stop:
            jax.profiler.stop_trace()
            self._active = False
            log.info("profiler: trace complete at step %d", step)

    def close(self) -> None:
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
