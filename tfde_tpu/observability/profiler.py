"""Profiling — first-class restoration of the reference's commented-out
ProfilerHook (mnist_keras_distributed.py:235-237,261; SURVEY.md §5).

`jax.profiler` traces (XProf format) are viewable in TensorBoard's profile
plugin or xprof; they capture XLA op timelines, HBM usage, and ICI collective
time — the TPU-native superset of ProfilerHook's show_memory=True.
"""

from __future__ import annotations

import contextlib
import logging
import os
from typing import Iterator, Optional

import jax

log = logging.getLogger(__name__)


@contextlib.contextmanager
def profile_trace(
    logdir: Optional[str],
    enabled: Optional[bool] = None,
) -> Iterator[None]:
    """Trace the enclosed block when enabled (or $TFDE_PROFILE is set).

    with profile_trace(run_config.model_dir):    # traces steps inside
        for batch in feed: state, m = step(...)
    """
    if enabled is None:
        enabled = os.environ.get("TFDE_PROFILE", "") not in ("", "0", "false", "False")
    if not enabled or logdir is None:
        yield
        return
    # start_trace itself appends plugins/profile/<timestamp> — pass the raw
    # logdir so TensorBoard's profile plugin finds the run.
    log.info("profiler trace -> %s/plugins/profile", logdir)
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region inside a trace (TraceAnnotation)."""
    return jax.profiler.TraceAnnotation(name)
