"""Profiling — first-class restoration of the reference's commented-out
ProfilerHook (mnist_keras_distributed.py:235-237,261; SURVEY.md §5).

`jax.profiler` traces (XProf format) are viewable in TensorBoard's profile
plugin or xprof; they capture XLA op timelines, HBM usage, and ICI collective
time — the TPU-native superset of ProfilerHook's show_memory=True.
"""

from __future__ import annotations

import contextlib
import logging
import os
from typing import Iterator, Optional

import jax

log = logging.getLogger(__name__)


@contextlib.contextmanager
def profile_trace(
    logdir: Optional[str],
    enabled: Optional[bool] = None,
) -> Iterator[None]:
    """Trace the enclosed block when enabled (or $TFDE_PROFILE is set).

    with profile_trace(run_config.model_dir):    # traces steps inside
        for batch in feed: state, m = step(...)
    """
    if enabled is None:
        enabled = os.environ.get("TFDE_PROFILE", "") not in ("", "0", "false", "False")
    if not enabled or logdir is None:
        yield
        return
    # start_trace itself appends plugins/profile/<timestamp> — pass the raw
    # logdir so TensorBoard's profile plugin finds the run.
    log.info("profiler trace -> %s/plugins/profile", logdir)
    from tfde_tpu.observability import spans

    jax.profiler.start_trace(logdir)
    spans.set_trace_active(True)
    try:
        yield
    finally:
        spans.set_trace_active(False)
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region inside a trace (TraceAnnotation)."""
    return jax.profiler.TraceAnnotation(name)


def _parse_window(raw: str) -> Optional[tuple]:
    """'100:110' -> (100, 110); '100' -> (100, 110) (10-step default);
    'every:N' -> ('every', N, 10); 'every:N:S' -> ('every', N, S)."""
    raw = raw.strip()
    if not raw or raw in ("0", "false", "False"):
        return None
    if raw.startswith("every:"):
        parts = raw.split(":")
        n = int(parts[1])
        if n <= 0:  # 'every:0' means disabled, like the documented '0'
            return None
        span = int(parts[2]) if len(parts) > 2 else 10
        if not 0 < span < n:
            raise ValueError(
                f"profile window 'every:{n}:{span}': the traced span must "
                f"be shorter than the period (else the trace never closes)"
            )
        return ("every", n, span)
    if ":" in raw:
        a, b = raw.split(":", 1)
        return (int(a), int(b))
    start = int(raw)
    return (start, start + 10)


class StepWindowProfiler:
    """Trace training-step windows into `<logdir>/plugins/profile/` — the
    ProfilerHook capability (mnist_keras_distributed.py:235-237: save_steps +
    output_dir), wired into Estimator.train via RunConfig.profile_steps or
    $TFDE_PROFILE.

    Window forms:
    - (start, stop) or "start:stop": one trace of steps [start, stop).
    - "every:N" (or ("every", N, span)): a repeating window — trace `span`
      steps (default 10) every N steps, i.e. [N, N+span), [2N, 2N+span), ...
      The ProfilerHook re-traced every save_steps=100; this is that, each
      window landing in its own timestamped plugins/profile run.

    Steps are *global* steps, so on resume the window refers to the same
    steps it would in an uninterrupted run. Windows start past step 1 to
    keep the first-compile out of the trace.
    """

    def __init__(self, logdir: Optional[str], window=None):
        if window is None:
            window = _parse_window(os.environ.get("TFDE_PROFILE", ""))
        elif isinstance(window, str):
            window = _parse_window(window)
        if window is not None and window[0] == "every":
            _, n, span = window
            if n <= 0:
                window = None
            elif not 0 < span < n:
                raise ValueError(
                    f"profile window ('every', {n}, {span}): span must be "
                    f"in (0, {n}) or the trace never closes"
                )
        self._window = window
        self.windows_traced = 0
        self._logdir = logdir
        self._active = False
        if window is not None and logdir is None:
            log.warning("profiling requested but no model_dir — disabled")
            self._window = None
        from tfde_tpu.utils import fs

        if self._window is not None and fs.is_remote(logdir):
            # the profiler's C++ writer only handles local paths here;
            # remote trace upload would need TF's gfile machinery
            log.warning(
                "profiling to a remote model_dir (%s) is not supported — "
                "disabled; point model_dir at local disk to trace", logdir
            )
            self._window = None

    @property
    def enabled(self) -> bool:
        return self._window is not None

    def arm(self, start_step: int, span: int = 10) -> bool:
        """Arm a one-shot window [start_step, start_step+span) at runtime —
        the numerics sentry's auto-capture hook (observability/sentry.py):
        on a trip it arms the next `span` steps so the blow-up's immediate
        aftermath lands on an XProf timeline. Refuses (returns False) when
        a window is already configured/active or there is no usable logdir,
        so auto-capture never clobbers an operator-requested trace."""
        if self._window is not None or self._active or self._logdir is None:
            return False
        from tfde_tpu.utils import fs

        if fs.is_remote(self._logdir):
            return False  # same limit as __init__: local trace dirs only
        if span < 1:
            raise ValueError("span must be >= 1")
        self._window = (int(start_step), int(start_step) + int(span))
        log.info("profiler: auto-armed window [%d, %d) -> %s/plugins/profile",
                 self._window[0], self._window[1], self._logdir)
        return True

    def _in_window(self, step: int) -> bool:
        if self._window[0] == "every":
            _, n, span = self._window
            return step >= n and (step % n) < span
        start, stop = self._window
        return start <= step < stop

    def step(self, step: int) -> None:
        """Call once per train step with the *post-increment* global step."""
        if self._window is None:
            return
        in_window = self._in_window(step)
        if not self._active and in_window:
            log.info(
                "profiler: trace window opening at step %d -> %s/plugins/profile",
                step, self._logdir,
            )
            jax.profiler.start_trace(self._logdir)
            self._set_spans(True)
            self._active = True
        elif self._active and not in_window:
            self._set_spans(False)
            jax.profiler.stop_trace()
            self._active = False
            self.windows_traced += 1
            log.info("profiler: trace complete at step %d", step)

    @staticmethod
    def _set_spans(active: bool) -> None:
        # spans emit TraceAnnotations only inside a window, so the same
        # phase names land on the XProf timeline at zero steady-state cost
        from tfde_tpu.observability import spans

        spans.set_trace_active(active)

    def close(self) -> None:
        if self._active:
            self._set_spans(False)
            jax.profiler.stop_trace()
            self._active = False
            self.windows_traced += 1
