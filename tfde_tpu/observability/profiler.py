"""Profiling — first-class restoration of the reference's commented-out
ProfilerHook (mnist_keras_distributed.py:235-237,261; SURVEY.md §5).

`jax.profiler` traces (XProf format) are viewable in TensorBoard's profile
plugin or xprof; they capture XLA op timelines, HBM usage, and ICI collective
time — the TPU-native superset of ProfilerHook's show_memory=True.

Beyond the operator-requested window (``$TFDE_PROFILE``), this module hosts
the *trigger-driven* capture loop: live anomaly signals (SLO burn-rate
crossings, straggler flags, recompile storms, sentry trips) funnel into a
``ProfileTrigger`` hub that arms a bounded capture on whichever profiler is
registered — a training step window (``StepWindowProfiler``) or a serving
decode-round window (``RoundWindowProfiler``). Every closed capture is
recorded in a retention-bounded artifact index under
``<model_dir>/debug/profiles/`` stamped with the trigger reason, step/round
range, and active trace ids, so the evidence for a perf anomaly survives the
process (surfaced by ``tools/obs_dump.py --profiles``).
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import threading
import time
from typing import Callable, Dict, Iterator, List, Optional

import jax

from tfde_tpu import knobs

log = logging.getLogger(__name__)

# Capture-overhead histogram: the host-side dispatch cost of opening and
# closing a trace (start_trace/stop_trace). goodput.py drains this into its
# own ledger bucket so a traced window can't masquerade as a compute
# regression.
CAPTURE_HISTOGRAM = "profile/capture"


def _observe_capture(seconds: float) -> None:
    try:
        from tfde_tpu.observability import metrics

        metrics.default_registry().histogram(CAPTURE_HISTOGRAM).observe(seconds)
    except Exception:  # pragma: no cover - metrics must never break a trace
        pass


def _start_trace(logdir: str) -> None:
    t0 = time.perf_counter()
    jax.profiler.start_trace(logdir)
    _observe_capture(time.perf_counter() - t0)


def _stop_trace() -> None:
    t0 = time.perf_counter()
    jax.profiler.stop_trace()
    _observe_capture(time.perf_counter() - t0)


@contextlib.contextmanager
def profile_trace(
    logdir: Optional[str],
    enabled: Optional[bool] = None,
) -> Iterator[None]:
    """Trace the enclosed block when enabled (or $TFDE_PROFILE is set).

    with profile_trace(run_config.model_dir):    # traces steps inside
        for batch in feed: state, m = step(...)
    """
    if enabled is None:
        raw = knobs.env_str("TFDE_PROFILE", "") or ""
        enabled = raw not in ("", "0", "false", "False")
    if not enabled or logdir is None:
        yield
        return
    # start_trace itself appends plugins/profile/<timestamp> — pass the raw
    # logdir so TensorBoard's profile plugin finds the run.
    log.info("profiler trace -> %s/plugins/profile", logdir)
    from tfde_tpu.observability import spans

    _start_trace(logdir)
    spans.set_trace_active(True)
    try:
        yield
    finally:
        spans.set_trace_active(False)
        _stop_trace()


def annotate(name: str):
    """Named region inside a trace (TraceAnnotation)."""
    return jax.profiler.TraceAnnotation(name)


def _parse_window(raw: str) -> Optional[tuple]:
    """'100:110' -> (100, 110); '100' -> (100, 110) (10-step default);
    'every:N' -> ('every', N, 10); 'every:N:S' -> ('every', N, S)."""
    raw = raw.strip()
    if not raw or raw in ("0", "false", "False"):
        return None
    if raw.startswith("every:"):
        parts = raw.split(":")
        n = int(parts[1])
        if n <= 0:  # 'every:0' means disabled, like the documented '0'
            return None
        span = int(parts[2]) if len(parts) > 2 else 10
        if not 0 < span < n:
            raise ValueError(
                f"profile window 'every:{n}:{span}': the traced span must "
                f"be shorter than the period (else the trace never closes)"
            )
        return ("every", n, span)
    if ":" in raw:
        a, b = raw.split(":", 1)
        return (int(a), int(b))
    start = int(raw)
    return (start, start + 10)


def _window_from_env() -> Optional[tuple]:
    """Parse $TFDE_PROFILE with the knob contract: garbage in the
    environment warns once and disables, it never raises (explicit
    RunConfig/ctor windows still raise — operator typos in code should
    fail fast, typos in a shell export should not kill a run)."""
    raw = knobs.env_str("TFDE_PROFILE", "") or ""
    try:
        return _parse_window(raw)
    except ValueError:
        knobs._warn_once("TFDE_PROFILE", raw,
                         "is not a valid profile window", None)
        return None


# --------------------------------------------------------------------------
# Artifact index: <model_dir>/debug/profiles/
# --------------------------------------------------------------------------

PROFILES_SUBDIR = os.path.join("debug", "profiles")


class ProfileArtifacts:
    """Retention-bounded index of completed captures.

    One JSON file per capture under ``<model_dir>/debug/profiles/``, stamped
    with the trigger reason, capture kind, step/round range, and the request
    trace ids that were in flight — enough to line a capture up against the
    flight recorder and the distributed-trace store after the fact. Retention
    (``TFDE_PROFILE_RETAIN``) bounds disk: oldest index entries are pruned.
    The XProf payloads themselves live wherever jax.profiler put them
    (``<logdir>/plugins/profile/<ts>``) and are not deleted here — the index
    is the cheap part we keep tightly bounded and machine-readable.
    """

    def __init__(self, model_dir: Optional[str], retain: Optional[int] = None):
        self._dir = (
            os.path.join(model_dir, PROFILES_SUBDIR) if model_dir else None
        )
        if retain is None:
            retain = knobs.env_int("TFDE_PROFILE_RETAIN", 8)
        self._retain = max(1, int(retain))
        self._seq = 0
        self._lock = threading.Lock()

    @property
    def dir(self) -> Optional[str]:
        return self._dir

    def record(
        self,
        reason: str,
        kind: str,
        start: Optional[int],
        stop: Optional[int],
        traces: Optional[List[str]] = None,
        logdir: Optional[str] = None,
        **extra,
    ) -> Optional[str]:
        """Write one capture record; returns its path (None when no dir)."""
        if self._dir is None:
            return None
        try:
            os.makedirs(self._dir, exist_ok=True)
            with self._lock:
                self._seq += 1
                seq = self._seq
            ts = time.time()
            safe = "".join(c if c.isalnum() or c in "-_" else "-" for c in reason)[:48]
            name = f"profile_{ts:017.6f}_{seq:04d}_{safe or 'capture'}.json"
            rec = {
                "reason": reason,
                "kind": kind,
                "start": start,
                "stop": stop,
                "traces": sorted(traces) if traces else [],
                "logdir": logdir,
                "host": jax.process_index() if jax.process_count() > 1 else 0,
                "pid": os.getpid(),
                "unix_time": ts,
            }
            rec.update(extra)
            path = os.path.join(self._dir, name)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(rec, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
            self._prune()
            return path
        except OSError as e:  # index failure must never break the capture
            log.warning("profile artifact index write failed: %s", e)
            return None

    def _prune(self) -> None:
        entries = sorted(
            f for f in os.listdir(self._dir)
            if f.startswith("profile_") and f.endswith(".json")
        )
        for stale in entries[: max(0, len(entries) - self._retain)]:
            with contextlib.suppress(OSError):
                os.remove(os.path.join(self._dir, stale))


def list_artifacts(model_dir: str) -> List[dict]:
    """Load every capture record under <model_dir>/debug/profiles/,
    oldest first (the obs_dump --profiles backend)."""
    d = os.path.join(model_dir, PROFILES_SUBDIR)
    out: List[dict] = []
    if not os.path.isdir(d):
        return out
    for name in sorted(os.listdir(d)):
        if not (name.startswith("profile_") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(d, name)) as f:
                rec = json.load(f)
            rec["_file"] = name
            out.append(rec)
        except (OSError, ValueError):
            continue
    return out


# --------------------------------------------------------------------------
# Trigger hub
# --------------------------------------------------------------------------

# A sink arms a bounded capture: sink(reason, span, info) -> bool (armed).
TriggerSink = Callable[[str, int, dict], bool]


class ProfileTrigger:
    """Funnel for live anomaly signals -> bounded profile captures.

    SLO burn-rate crossings (slo.py), straggler flags (aggregate.py),
    recompile storms (recompile.py), and sentry trips (sentry.py) all call
    ``trigger(reason, ...)``; registered sinks (a StepWindowProfiler in
    training, a RoundWindowProfiler in serving, the aggregator's cross-host
    broadcast on the chief) arm the actual capture. Two rate limits keep
    auto-capture from thrashing the run:

    - global cooldown (``TFDE_PROFILE_COOLDOWN_S``): at most one armed
      capture per window, regardless of reason;
    - per-key dedupe (``TFDE_PROFILE_DEDUPE_S``): the *same* reason key
      can't re-arm until its dedupe window passes, so a storm of identical
      signals produces one capture, not eight.

    Timestamps are consumed only when a sink actually armed — a refused
    trigger (window already configured, no logdir) doesn't burn the budget,
    so the next anomaly still gets its evidence.
    """

    def __init__(
        self,
        cooldown_s: Optional[float] = None,
        dedupe_s: Optional[float] = None,
        enabled: Optional[bool] = None,
        clock=time.monotonic,
    ):
        if cooldown_s is None:
            cooldown_s = knobs.env_float("TFDE_PROFILE_COOLDOWN_S", 120.0)
        if dedupe_s is None:
            dedupe_s = knobs.env_float("TFDE_PROFILE_DEDUPE_S", 600.0)
        if enabled is None:
            enabled = knobs.env_flag("TFDE_PROFILE_TRIGGERS", True)
        self.cooldown_s = float(cooldown_s)
        self.dedupe_s = float(dedupe_s)
        self.enabled = bool(enabled)
        self._clock = clock
        self._lock = threading.Lock()
        self._sinks: Dict[str, TriggerSink] = {}
        self._last_fire: Optional[float] = None
        self._last_by_key: Dict[str, float] = {}

    def register(self, name: str, sink: TriggerSink) -> None:
        with self._lock:
            self._sinks[name] = sink

    def unregister(self, name: str) -> None:
        with self._lock:
            self._sinks.pop(name, None)

    def sinks(self) -> List[str]:
        with self._lock:
            return sorted(self._sinks)

    def trigger(
        self,
        reason: str,
        key: Optional[str] = None,
        span: Optional[int] = None,
        extra_sink: Optional[TriggerSink] = None,
        **info,
    ) -> bool:
        """Request a capture. Returns True when some sink armed one.

        ``key`` scopes dedupe (defaults to the reason); ``extra_sink`` lets
        a caller offer a capture mechanism without registering (the sentry's
        own profiler, say). ``info`` rides along to sinks and into the
        flightrec breadcrumb.
        """
        if not self.enabled:
            return False
        if span is None:
            span = knobs.env_int("TFDE_PROFILE_SPAN", 8)
        span = max(1, int(span))
        key = key or reason
        now = self._clock()
        with self._lock:
            if self._last_fire is not None and now - self._last_fire < self.cooldown_s:
                return False
            last_key = self._last_by_key.get(key)
            if last_key is not None and now - last_key < self.dedupe_s:
                return False
            sinks = list(self._sinks.items())
        if extra_sink is not None:
            sinks = sinks + [("extra", extra_sink)]
        armed_by = []
        for name, sink in sinks:
            try:
                if sink(reason, span, dict(info)):
                    armed_by.append(name)
            except Exception as e:  # a broken sink must not mask the others
                log.warning("profile trigger sink %r failed: %s", name, e)
        if not armed_by:
            return False
        # consume the budget only on success so refusals don't starve the
        # next real anomaly
        with self._lock:
            self._last_fire = now
            self._last_by_key[key] = now
        log.warning(
            "profile trigger %r armed capture (span=%d) via %s",
            reason, span, ",".join(armed_by),
        )
        try:
            from tfde_tpu.observability import flightrec, metrics

            metrics.default_registry().counter("profile/triggers").incr()
            flightrec.record(
                "profile_trigger", reason=reason, span=span,
                sinks=armed_by, **{k: v for k, v in info.items()
                                   if isinstance(v, (str, int, float, bool))},
            )
        except Exception:  # pragma: no cover
            pass
        return True


_HUB: Optional[ProfileTrigger] = None
_HUB_LOCK = threading.Lock()


def hub() -> ProfileTrigger:
    """Process-wide trigger hub (lazily built from the TFDE_PROFILE_* knobs)."""
    global _HUB
    with _HUB_LOCK:
        if _HUB is None:
            _HUB = ProfileTrigger()
        return _HUB


def trigger(reason: str, **kwargs) -> bool:
    """Module-level convenience: hub().trigger(...)."""
    return hub().trigger(reason, **kwargs)


def reset_hub() -> None:
    """Drop the process hub (test hook; next hub() re-reads the knobs)."""
    global _HUB
    with _HUB_LOCK:
        _HUB = None


# --------------------------------------------------------------------------
# Training-side: step windows
# --------------------------------------------------------------------------


class StepWindowProfiler:
    """Trace training-step windows into `<logdir>/plugins/profile/` — the
    ProfilerHook capability (mnist_keras_distributed.py:235-237: save_steps +
    output_dir), wired into Estimator.train via RunConfig.profile_steps or
    $TFDE_PROFILE.

    Window forms:
    - (start, stop) or "start:stop": one trace of steps [start, stop).
    - "every:N" (or ("every", N, span)): a repeating window — trace `span`
      steps (default 10) every N steps, i.e. [N, N+span), [2N, 2N+span), ...
      The ProfilerHook re-traced every save_steps=100; this is that, each
      window landing in its own timestamped plugins/profile run.

    Steps are *global* steps, so on resume the window refers to the same
    steps it would in an uninterrupted run. Windows start past step 1 to
    keep the first-compile out of the trace.
    """

    def __init__(self, logdir: Optional[str], window=None,
                 artifacts: Optional[ProfileArtifacts] = None):
        if window is None:
            window = _window_from_env()
        elif isinstance(window, str):
            window = _parse_window(window)
        if window is not None and window[0] == "every":
            _, n, span = window
            if n <= 0:
                window = None
            elif not 0 < span < n:
                raise ValueError(
                    f"profile window ('every', {n}, {span}): span must be "
                    f"in (0, {n}) or the trace never closes"
                )
        self._window = window
        self.windows_traced = 0
        self._logdir = logdir
        self._active = False
        self._artifacts = artifacts
        self._reason = "window" if window is not None else None
        self._open_step: Optional[int] = None
        self._last_step = 0
        if window is not None and logdir is None:
            log.warning("profiling requested but no model_dir — disabled")
            self._window = None
        from tfde_tpu.utils import fs

        if self._window is not None and fs.is_remote(logdir):
            # the profiler's C++ writer only handles local paths here;
            # remote trace upload would need TF's gfile machinery
            log.warning(
                "profiling to a remote model_dir (%s) is not supported — "
                "disabled; point model_dir at local disk to trace", logdir
            )
            self._window = None

    @property
    def enabled(self) -> bool:
        return self._window is not None

    def arm(self, start_step: int, span: int = 10, reason: str = "auto") -> bool:
        """Arm a one-shot window [start_step, start_step+span) at runtime —
        the trigger hub's capture hook (sentry trips, SLO burn, recompile
        storms). Refuses (returns False) when a window is already
        configured/active or there is no usable logdir, so auto-capture
        never clobbers an operator-requested trace."""
        if self._window is not None or self._active or self._logdir is None:
            return False
        from tfde_tpu.utils import fs

        if fs.is_remote(self._logdir):
            return False  # same limit as __init__: local trace dirs only
        if span < 1:
            raise ValueError("span must be >= 1")
        self._window = (int(start_step), int(start_step) + int(span))
        self._reason = str(reason)
        log.info("profiler: auto-armed window [%d, %d) (%s) -> %s/plugins/profile",
                 self._window[0], self._window[1], self._reason, self._logdir)
        return True

    def trigger_sink(self, reason: str, span: int, info: dict) -> bool:
        """ProfileTrigger sink: arm a window starting at the next step."""
        start = int(info.get("step", self._last_step)) + 1
        return self.arm(start, span, reason=reason)

    def _in_window(self, step: int) -> bool:
        if self._window[0] == "every":
            _, n, span = self._window
            return step >= n and (step % n) < span
        start, stop = self._window
        return start <= step < stop

    def step(self, step: int) -> None:
        """Call once per train step with the *post-increment* global step."""
        self._last_step = step
        if self._window is None:
            return
        in_window = self._in_window(step)
        if not self._active and in_window:
            log.info(
                "profiler: trace window opening at step %d -> %s/plugins/profile",
                step, self._logdir,
            )
            _start_trace(self._logdir)
            self._set_spans(True)
            self._active = True
            self._open_step = step
        elif self._active and not in_window:
            self._close_window(step)
            log.info("profiler: trace complete at step %d", step)

    def _close_window(self, step: int) -> None:
        self._set_spans(False)
        _stop_trace()
        self._active = False
        self.windows_traced += 1
        if self._artifacts is not None:
            self._artifacts.record(
                self._reason or "window", "step",
                self._open_step, step, logdir=self._logdir,
            )
        # a one-shot auto-armed window is consumed on close so the next
        # trigger can arm again; repeating/explicit windows stay configured
        if self._reason not in (None, "window"):
            self._window = None
            self._reason = None
        self._open_step = None

    @staticmethod
    def _set_spans(active: bool) -> None:
        # spans emit TraceAnnotations only inside a window, so the same
        # phase names land on the XProf timeline at zero steady-state cost
        from tfde_tpu.observability import spans

        spans.set_trace_active(active)

    def close(self) -> None:
        if self._active:
            self._close_window(self._last_step)


# --------------------------------------------------------------------------
# Serving-side: decode-round windows
# --------------------------------------------------------------------------


class RoundWindowProfiler:
    """Bounded capture over continuous-batcher decode rounds — the serving
    sibling of StepWindowProfiler. There is no global step in serving, so
    windows are measured in decode rounds: ``arm(span)`` opens a trace at
    the next round boundary and closes it ``span`` rounds later, recording
    an artifact stamped with the round range and every request trace id
    that was in flight during the window.

    Driven by the batcher: ``on_round(rounds, traces)`` once per step with
    the cumulative round count and the active trace ids.
    """

    def __init__(self, logdir: Optional[str],
                 artifacts: Optional[ProfileArtifacts] = None):
        from tfde_tpu.utils import fs

        if logdir is not None and fs.is_remote(logdir):
            log.warning("round profiling to a remote dir (%s) is not "
                        "supported — disabled", logdir)
            logdir = None
        self._logdir = logdir
        self._artifacts = artifacts
        self._lock = threading.Lock()
        self._armed_span = 0
        self._reason: Optional[str] = None
        self._active = False
        self._open_round: Optional[int] = None
        self._stop_round: Optional[int] = None
        self._traces: set = set()
        self.windows_traced = 0

    @property
    def enabled(self) -> bool:
        return self._logdir is not None

    def arm(self, span: Optional[int] = None, reason: str = "manual") -> bool:
        """Arm a capture of the next `span` decode rounds. Refuses when a
        capture is already armed/active or there is no usable logdir."""
        if span is None:
            span = knobs.env_int("TFDE_PROFILE_SPAN", 8)
        if span < 1:
            raise ValueError("span must be >= 1")
        with self._lock:
            if self._logdir is None or self._active or self._armed_span:
                return False
            self._armed_span = int(span)
            self._reason = str(reason)
        log.info("round profiler: armed %d-round capture (%s) -> %s",
                 span, reason, self._logdir)
        return True

    def trigger_sink(self, reason: str, span: int, info: dict) -> bool:
        """ProfileTrigger sink."""
        return self.arm(span=span, reason=reason)

    def on_round(self, rounds: int, traces=None) -> None:
        """Batcher hook: cumulative decode-round count after each step."""
        with self._lock:
            if self._active:
                if traces:
                    self._traces.update(traces)
                if rounds >= self._stop_round:
                    self._close_locked(rounds)
                return
            if self._armed_span and self._logdir is not None:
                _start_trace(self._logdir)
                from tfde_tpu.observability import spans

                spans.set_trace_active(True)
                self._active = True
                self._open_round = rounds
                self._stop_round = rounds + self._armed_span
                self._armed_span = 0
                if traces:
                    self._traces.update(traces)
                log.info("round profiler: trace open at round %d (until %d)",
                         rounds, self._stop_round)

    def _close_locked(self, rounds: int) -> None:
        from tfde_tpu.observability import spans

        spans.set_trace_active(False)
        _stop_trace()
        self._active = False
        self.windows_traced += 1
        if self._artifacts is not None:
            self._artifacts.record(
                self._reason or "manual", "round",
                self._open_round, rounds,
                traces=list(self._traces), logdir=self._logdir,
            )
        log.info("round profiler: trace complete at round %d (%s)",
                 rounds, self._reason)
        self._reason = None
        self._open_round = self._stop_round = None
        self._traces = set()

    def close(self) -> None:
        with self._lock:
            if self._active:
                self._close_locked(self._stop_round or 0)
