"""Recompile sentinel: jit-cache-miss detection for the hot entry points.

Recompiles are a silent perf hazard: bench.py once read 0.7 TFLOPs because
a recompile landed inside a timed window, and the serving pad-ladder can
churn buckets into fresh compilations with nothing counting them. The
pjit-on-TPUv4 experience is that compile time is a first-class budget at
scale — so it gets the same treatment as wall-clock: measured, attributed,
and gated.

Mechanism
---------
`jax.monitoring` fires a ``/jax/core/compile/backend_compile_duration``
event on every *actual* XLA compilation and nothing on a cache hit (the
C++ fast path never re-enters Python). One process-global listener
(installed lazily, idempotent) turns those events into:

- ``compile/seconds_total`` / ``compile/process_compiles`` — process-wide
  compile time and count, site or no site. ``process_compiles()`` is what
  bench.py's window guard diffs to assert a timed window was
  compile-free.
- per-**site** attribution via a thread-local: a `Site` wraps one hot jit
  entry point (train step, a serving pad-ladder bucket); every call runs
  under ``site.watch(*fingerprint)`` and any compile event fired during
  the call is charged to that site's ``compile/<site>/{cache_hits,misses,
  seconds_total}`` counters. The *fingerprint* (shape-bucket, dtype,
  static-arg tuple — whatever the call site says shapes the program)
  classifies each miss: a **novel** fingerprint is an expected first
  compile; a miss on an already-seen fingerprint (cache thrash, a
  donation/weak-type bug) or past a declared signature budget is
  **unexpected**.
- every miss leaves a flight-recorder breadcrumb and (when the PR-9 ring
  is on) a ``compile/miss`` trace event carrying the victim request ids —
  a mid-serve recompile shows up in the waterfall that paid for it.
- ``storm_threshold`` unexpected misses on one site escalate once through
  the sentry-style supervisor warn path: loud log + ``recompile_storm``
  flight breadcrumb + ``compile/storms`` counter. Never raises —
  observability must not take serving down.

The listener and the bookkeeping are a dict lookup and two counter adds
per call; sites are safe to wrap around per-token paths.
"""

from __future__ import annotations

import contextlib
import logging
import threading
from typing import Dict, Iterator, Optional

from tfde_tpu.observability import flightrec, metrics
from tfde_tpu.observability import trace as _trace

log = logging.getLogger(__name__)

#: unexpected misses on one site before the storm escalation fires
STORM_THRESHOLD = 8

_EVENT_PREFIX = "/jax/core/compile/"
_BACKEND_EVENT = "/jax/core/compile/backend_compile_duration"

_tls = threading.local()
_lock = threading.Lock()
_sites: Dict[str, "Site"] = {}
_installed = False
_install_failed = False


def install() -> bool:
    """Register the process-global compile-event listener (idempotent).
    Returns False when this JAX has no monitoring hook — sites then
    count fingerprint novelty only (misses inferred, seconds zero)."""
    global _installed, _install_failed
    with _lock:
        if _installed:
            return True
        if _install_failed:
            return False
        try:
            from jax import monitoring

            monitoring.register_event_duration_secs_listener(_on_event)
        except Exception as e:  # noqa: BLE001 — degrade, don't crash
            _install_failed = True
            log.warning("recompile sentinel: no jax.monitoring listener "
                        "(%s); falling back to fingerprint novelty", e)
            return False
        _installed = True
        return True


def _on_event(event: str, duration: float, **_kw) -> None:
    """The jax.monitoring listener: fires once per actual compile stage
    (jaxpr trace, MLIR lowering, backend compile), never on a cache
    hit. Attribution: whatever Site the calling thread is inside."""
    if not event.startswith(_EVENT_PREFIX):
        return
    if getattr(_tls, "suppress", 0):
        # memwatch's own ledger interrogation (eval_shape / AOT compile)
        # must not read as a recompile of the program it is measuring
        metrics.counter("compile/memwatch_seconds_total").incr(duration)
        return
    metrics.counter("compile/seconds_total").incr(duration)
    if event == _BACKEND_EVENT:
        metrics.counter("compile/process_compiles").incr()
    pending = getattr(_tls, "pending", None)
    if pending is not None:
        pending[1] += duration
        if event == _BACKEND_EVENT:
            pending[0] += 1


@contextlib.contextmanager
def suppress() -> Iterator[None]:
    """Compile events in this block are counted as ledger overhead
    (``compile/memwatch_seconds_total``), not as process compiles or
    site misses. memwatch.py wraps its interrogation in this."""
    prev = getattr(_tls, "suppress", 0)
    _tls.suppress = prev + 1
    try:
        yield
    finally:
        _tls.suppress = prev


class Site:
    """One watched jit entry point. Create through `site()` so every
    caller naming the same site shares one fingerprint set."""

    def __init__(self, name: str, stable: bool = False,
                 expect: Optional[int] = None,
                 storm_threshold: int = STORM_THRESHOLD,
                 registry: Optional[metrics.Registry] = None):
        self.name = name
        #: stable sites additionally treat every signature past `expect`
        #: as unexpected (the bucket-churn failure mode); non-stable
        #: sites only flag re-compiles of an already-seen fingerprint
        self.stable = bool(stable)
        self.expect = expect
        self.storm_threshold = int(storm_threshold)
        self._reg = registry or metrics.default_registry()
        self._fingerprints: set = set()
        self.hits = 0
        self.misses = 0
        self.seconds = 0.0
        self.unexpected = 0
        self._storm_reported = False
        self._c_hits = self._reg.counter(f"compile/{name}/cache_hits")
        self._c_miss = self._reg.counter(f"compile/{name}/misses")
        self._c_secs = self._reg.counter(f"compile/{name}/seconds_total")
        self._g_sigs = self._reg.gauge(f"compile/{name}/signatures")

    @contextlib.contextmanager
    def watch(self, *fingerprint, traces=None) -> Iterator[None]:
        """Run one call to the wrapped entry point under this site.
        `fingerprint` is the call's program signature (shape bucket,
        dtype, static args); `traces` optionally carries the request
        trace ids a miss would have stalled."""
        install()
        prev_site = getattr(_tls, "site", None)
        prev_pending = getattr(_tls, "pending", None)
        _tls.site = self
        _tls.pending = pending = [0, 0.0]
        try:
            yield
        finally:
            _tls.site = prev_site
            _tls.pending = prev_pending
            self._settle(tuple(fingerprint), pending[0], pending[1],
                         traces)

    def _settle(self, key, compiles: int, secs: float, traces) -> None:
        with _lock:
            novel = key not in self._fingerprints
            self._fingerprints.add(key)
            nsigs = len(self._fingerprints)
        self._g_sigs.set(nsigs)
        if compiles == 0 and (_installed or not novel):
            # no monitoring hook: fall back to novelty as the miss signal
            self.hits += 1
            self._c_hits.incr()
            return
        self.misses += 1
        self._c_miss.incr()
        self.seconds += secs
        if secs:
            self._c_secs.incr(secs)
        unexpected = (not novel) or (
            self.stable and self.expect is not None and nsigs > self.expect
        )
        flightrec.record(
            "recompile", site=self.name, fingerprint=repr(key),
            seconds=round(secs, 4), novel=bool(novel),
            unexpected=bool(unexpected),
        )
        if _trace.active():
            _trace.event("compile/miss", traces=traces, dur=secs or None,
                         site=self.name, fingerprint=repr(key))
        if unexpected:
            self.unexpected += 1
            self._reg.counter(f"compile/{self.name}/unexpected").incr()
            if (self.unexpected >= self.storm_threshold
                    and not self._storm_reported):
                self._storm_reported = True
                self._escalate()

    def _escalate(self) -> None:
        """The sentry->supervisor warn path (observability/sentry.py's
        action='warn' shape): loud log + flight breadcrumb + counter.
        Deliberately never raises."""
        self._reg.counter("compile/storms").incr()
        flightrec.record(
            "recompile_storm", site=self.name, misses=self.misses,
            unexpected=self.unexpected, signatures=len(self._fingerprints),
            seconds=round(self.seconds, 3),
        )
        log.error(
            "recompile storm on site %s: %d unexpected misses "
            "(%d total, %d signatures, %.2fs compiling) — a supposedly "
            "shape-stable program is churning the jit cache; see "
            "WORKFLOWS.md §15",
            self.name, self.unexpected, self.misses,
            len(self._fingerprints), self.seconds,
        )
        try:
            # a storm is exactly when an XProf timeline answers "what shape
            # keeps changing" — ask the trigger hub for a bounded capture
            from tfde_tpu.observability import profiler

            profiler.trigger(
                "recompile_storm", key=f"recompile_storm:{self.name}",
                site=self.name, unexpected=self.unexpected,
                signatures=len(self._fingerprints),
            )
        except Exception:  # escalation must never raise into the hot path
            pass

    def snapshot(self) -> dict:
        with _lock:
            nsigs = len(self._fingerprints)
        return {
            "hits": self.hits,
            "misses": self.misses,
            "seconds": self.seconds,
            "signatures": nsigs,
            "unexpected": self.unexpected,
        }


def site(name: str, stable: bool = False, expect: Optional[int] = None,
         storm_threshold: int = STORM_THRESHOLD,
         registry: Optional[metrics.Registry] = None) -> Site:
    """Get-or-create the process-wide site `name`. Keyword arguments
    apply on first creation only (a site's policy is set by its owner)."""
    with _lock:
        s = _sites.get(name)
        if s is None:
            s = Site(name, stable=stable, expect=expect,
                     storm_threshold=storm_threshold, registry=registry)
            _sites[name] = s
        return s


def sites() -> Dict[str, dict]:
    """{site name: snapshot} — the memgate/bench readout surface."""
    with _lock:
        items = list(_sites.items())
    return {name: s.snapshot() for name, s in items}


def process_compiles() -> int:
    """Actual XLA compiles observed process-wide (site or not) — the
    number bench.py diffs around a timed window."""
    return int(metrics.counter("compile/process_compiles").value)


def seconds_total() -> float:
    return float(metrics.counter("compile/seconds_total").value)


def reset(registry: Optional[metrics.Registry] = None) -> None:
    """Drop every site and the compile/* metrics — test isolation hook.
    The monitoring listener stays installed (it cannot be unregistered)
    but re-created counters restart from zero."""
    with _lock:
        _sites.clear()
    (registry or metrics.default_registry()).reset("compile/")
