"""Measured memory ledger: what each compiled program actually costs.

The framework's memory story used to be analytic — `opt/state_bytes` was
computed from leaf shapes, never from what XLA allocates. At production
scale peak-HBM is a budget tracked per program, not guessed, so this
module interrogates every registered executable and publishes, per named
program (train step, prefill wave, decode scan, ZeRO update)::

    mem/<name>/peak_bytes            arg + out + temp + code - aliased
    mem/<name>/argument_bytes        input buffers
    mem/<name>/output_bytes          result buffers
    mem/<name>/temp_bytes            XLA scratch (0 under estimate mode)
    mem/<name>/generated_code_bytes  executable size (0 under estimate)
    mem/<name>/measured              1 = XLA memory_analysis, 0 = estimate

plus live device-buffer totals (``jax.live_arrays()``) sampled on the
existing metrics cadence — a registry collector refreshes ``mem/live/*``
at the top of every `snapshot()`, so the numbers ride /metrics, the JSONL
log, the TB bridge, and the cross-host push without a new loop.

Modes (``TFDE_MEMWATCH``):

- ``off``   — no ledger, no sampler; registration is a no-op.
- ``on``    — the default: **estimate** mode. Argument/output bytes come
  from the avals (one `jax.eval_shape` trace, no XLA compile), aliasing
  from the donated args the call site names, temp/code are 0. Free of
  compile-time cost, exact for the dominant arg/output terms.
- ``full``  — AOT-lower and compile each registered program
  (`jax.stages.Compiled.memory_analysis()` / `cost_analysis()`) for
  XLA-measured temp/code/alias bytes. Costs one extra compile per
  program; the mode for a TPU capture, not the default. On backends
  whose memory_analysis is degenerate (CPU reports temp = code = 0) the
  estimate fills in aliasing, so tier-1 exercises the full path.

Ledger interrogation runs under `recompile.suppress()` — measuring a
program must never read as a recompile of it.

`device_bytes(tree)` is the measured counterpart of the ZeRO layer's
analytic accounting: per-device bytes actually resident for a pytree of
committed arrays, from each leaf's addressable shards (max over devices;
replicated leaves count fully on every device).
"""

from __future__ import annotations

import collections
import dataclasses
import json
import logging
import os
import threading
import time
from typing import Dict, Optional

from tfde_tpu.observability import metrics

log = logging.getLogger(__name__)

ENV_MEMWATCH = "TFDE_MEMWATCH"
MODES = ("off", "on", "full")
TOP_K = 8
#: min seconds between debug/memwatch.json rewrites when armed
DUMP_INTERVAL_S = 5.0

_FIELDS = ("peak_bytes", "argument_bytes", "output_bytes", "temp_bytes",
           "generated_code_bytes")


def resolve(value: Optional[str] = None) -> str:
    """Normalize the TFDE_MEMWATCH knob to one of MODES (default 'on')."""
    v = (value if value is not None
         else os.environ.get(ENV_MEMWATCH, "on")).strip().lower()
    if v in ("", "1", "true", "yes", "on"):
        return "on"
    if v in ("0", "false", "no", "off"):
        return "off"
    if v in ("full", "measured"):
        return "full"
    log.warning("%s=%r not understood; using 'on'", ENV_MEMWATCH, v)
    return "on"


def enabled() -> bool:
    return resolve() != "off"


@dataclasses.dataclass
class ProgramMemory:
    """One registered program's memory interrogation result."""

    name: str
    peak_bytes: int = 0
    argument_bytes: int = 0
    output_bytes: int = 0
    temp_bytes: int = 0
    generated_code_bytes: int = 0
    alias_bytes: int = 0
    flops: float = 0.0
    measured: bool = False  # True = XLA memory_analysis was authoritative

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def _leaf_bytes(leaf) -> int:
    """Bytes of one pytree leaf: works for committed arrays, numpy, and
    aval-ish objects (ShapeDtypeStruct); non-array leaves count zero."""
    try:
        nb = getattr(leaf, "nbytes", None)
        if nb is not None:
            return int(nb)
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            return 0
        import numpy as np

        n = 1
        for d in shape:
            n *= int(d)
        return int(n * np.dtype(dtype).itemsize)
    except Exception:  # noqa: BLE001 — a weird leaf must not sink the ledger
        return 0


def _tree_bytes(tree) -> int:
    if tree is None:
        return 0
    import jax

    return sum(_leaf_bytes(leaf) for leaf in jax.tree_util.tree_leaves(tree))


def device_bytes(tree) -> int:
    """MEASURED per-device bytes for a pytree of committed arrays: sum of
    each device's actually-allocated shard bytes, max over devices.
    Replicated leaves count fully on every device (each holds a copy);
    abstract / host leaves count as replicated. The cross-check against
    `parallel/zero.state_bytes`'s analytic number."""
    import jax

    dev_totals: Dict = collections.defaultdict(int)
    replicated = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if shards is not None:
            try:
                for sh in shards:
                    key = getattr(sh.device, "id", sh.device)
                    dev_totals[key] += int(sh.data.nbytes)
                continue
            except Exception:  # noqa: BLE001 — deleted/abstract mid-walk
                pass
        replicated += _leaf_bytes(leaf)
    if not dev_totals:
        return replicated
    return max(dev_totals.values()) + replicated


def _cost_flops(compiled) -> float:
    """`cost_analysis()` returns a dict on new JAX, a [dict] on 0.4.x."""
    try:
        cost = compiled.cost_analysis()
    except Exception:  # noqa: BLE001
        return 0.0
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    try:
        return float(cost.get("flops", 0.0))
    except Exception:  # noqa: BLE001
        return 0.0


class MemoryLedger:
    """The per-process program registry. Use the module-level helpers
    (`register`, `sample_live`, ...) unless a test needs isolation."""

    def __init__(self, registry: Optional[metrics.Registry] = None):
        self._reg = registry or metrics.default_registry()
        self._lock = threading.Lock()
        self._programs: Dict[str, ProgramMemory] = {}
        self._warned: set = set()
        self._dump_path: Optional[str] = None
        self._last_dump = 0.0
        self._collector_installed = False

    # -- registration --------------------------------------------------------
    def register(self, name: str, fn=None, args=(), kwargs=None,
                 donated=None, compiled=None,
                 mode: Optional[str] = None) -> Optional[ProgramMemory]:
        """Interrogate one program and publish its `mem/<name>/*` gauges.

        Give either a `compiled` (`jax.stages.Compiled`) or the jitted
        `fn` plus the call's `args`/`kwargs`; `donated` names the
        pytree(s) the program donates (aliased buffers — subtracted from
        the peak estimate). Returns None when the ledger is off or the
        interrogation failed (logged once per name, never raised: the
        ledger must not take the caller down)."""
        mode = resolve(mode)
        if mode == "off":
            return None
        try:
            pm = self._interrogate(name, fn, args, kwargs or {}, donated,
                                   compiled, mode)
        except Exception as e:  # noqa: BLE001 — observability-only path
            if name not in self._warned:
                self._warned.add(name)
                log.warning("memwatch: could not register %s: %s", name, e)
            return None
        with self._lock:
            self._programs[name] = pm
        self._publish(pm)
        return pm

    def _interrogate(self, name, fn, args, kwargs, donated, compiled,
                     mode) -> ProgramMemory:
        import jax

        from tfde_tpu.observability import recompile

        with recompile.suppress():
            if (compiled is None and mode == "full"
                    and hasattr(fn, "lower")):
                compiled = fn.lower(*args, **kwargs).compile()
            stats = None
            if compiled is not None:
                stats = compiled.memory_analysis()
            arg_bytes = _tree_bytes((args, kwargs))
            alias_bytes = _tree_bytes(donated)
            if stats is not None and stats.output_size_in_bytes:
                out_bytes = int(stats.output_size_in_bytes)
                arg_bytes = int(stats.argument_size_in_bytes) or arg_bytes
            else:
                out_bytes = _tree_bytes(jax.eval_shape(fn, *args, **kwargs))
        temp = int(getattr(stats, "temp_size_in_bytes", 0) or 0)
        code = int(getattr(stats, "generated_code_size_in_bytes", 0) or 0)
        xla_alias = int(getattr(stats, "alias_size_in_bytes", 0) or 0)
        # CPU's memory_analysis zeroes temp/code/alias — fall back to the
        # donated-aval estimate for aliasing so the peak stays honest
        measured = stats is not None and (temp or code or xla_alias)
        alias = xla_alias if measured else alias_bytes
        peak = max(arg_bytes, out_bytes,
                   arg_bytes + out_bytes + temp + code - alias)
        return ProgramMemory(
            name=name, peak_bytes=int(peak), argument_bytes=int(arg_bytes),
            output_bytes=int(out_bytes), temp_bytes=temp,
            generated_code_bytes=code, alias_bytes=int(alias),
            flops=_cost_flops(compiled) if compiled is not None else 0.0,
            measured=bool(measured),
        )

    def _publish(self, pm: ProgramMemory) -> None:
        for field in _FIELDS:
            self._reg.gauge(f"mem/{pm.name}/{field}").set(
                getattr(pm, field))
        self._reg.gauge(f"mem/{pm.name}/measured").set(
            1.0 if pm.measured else 0.0)

    def programs(self) -> Dict[str, ProgramMemory]:
        with self._lock:
            return dict(self._programs)

    def get(self, name: str) -> Optional[ProgramMemory]:
        with self._lock:
            return self._programs.get(name)

    # -- live device buffers -------------------------------------------------
    def sample_live(self, top_k: int = TOP_K) -> dict:
        """One `jax.live_arrays()` sweep: total bytes, buffer count, and
        the top-K largest live buffers (bytes/shape/dtype)."""
        import jax

        total = 0
        rows = []
        for arr in jax.live_arrays():
            try:
                nb = int(arr.nbytes)
                shape = tuple(arr.shape)
                dtype = str(arr.dtype)
            except Exception:  # noqa: BLE001 — deleted mid-sweep
                continue
            total += nb
            rows.append((nb, shape, dtype))
        rows.sort(key=lambda r: -r[0])
        return {
            "ts": time.time(),
            "bytes": total,
            "buffers": len(rows),
            "top": [{"bytes": nb, "shape": list(shape), "dtype": dtype}
                    for nb, shape, dtype in rows[:top_k]],
        }

    def publish_live(self, top_k: int = TOP_K) -> dict:
        """sample_live + publish `mem/live/*` gauges (+ the armed JSON
        side-file for obs_dump --mem's top-K table)."""
        sample = self.sample_live(top_k)
        self._reg.gauge("mem/live/bytes").set(sample["bytes"])
        self._reg.gauge("mem/live/buffers").set(sample["buffers"])
        self._reg.gauge("mem/live/largest_bytes").set(
            sample["top"][0]["bytes"] if sample["top"] else 0)
        self._maybe_dump(sample)
        return sample

    def _collect(self) -> None:
        """The Registry collector: refresh mem/live/* on every snapshot —
        'sampled on the existing metrics cadence'."""
        self.publish_live()

    def install_collector(self) -> None:
        """Hook the live sampler into the registry's snapshot cadence
        (idempotent)."""
        with self._lock:
            if self._collector_installed:
                return
            self._collector_installed = True
        self._reg.add_collector(self._collect)

    # -- armed side-file (obs_dump --mem) ------------------------------------
    def arm(self, model_dir: str) -> None:
        """Write ``<model_dir>/debug/memwatch.json`` (programs + latest
        live sample + top-K buffers) on the sampling cadence, throttled
        to one rewrite per DUMP_INTERVAL_S."""
        from tfde_tpu.utils import fs

        d = fs.join(model_dir, "debug")
        fs.makedirs(d)
        self._dump_path = fs.join(d, "memwatch.json")
        self._last_dump = 0.0

    def _maybe_dump(self, sample: dict) -> None:
        path = self._dump_path
        if path is None or time.time() - self._last_dump < DUMP_INTERVAL_S:
            return
        self._last_dump = time.time()
        try:
            from tfde_tpu.utils import fs

            body = {
                "live": sample,
                "programs": {n: p.as_dict()
                             for n, p in self.programs().items()},
            }
            fs.write_bytes(path, json.dumps(body, sort_keys=True).encode())
        except Exception as e:  # noqa: BLE001 — dump is best-effort
            log.debug("memwatch dump failed: %s", e)

    def reset(self) -> None:
        with self._lock:
            self._programs.clear()
            self._warned.clear()
            self._dump_path = None
        self._reg.reset("mem/")


_default = MemoryLedger()


def default_ledger() -> MemoryLedger:
    return _default


def register(name: str, fn=None, args=(), kwargs=None, donated=None,
             compiled=None, mode: Optional[str] = None):
    return _default.register(name, fn=fn, args=args, kwargs=kwargs,
                             donated=donated, compiled=compiled, mode=mode)


def sample_live(top_k: int = TOP_K) -> dict:
    return _default.sample_live(top_k)


def publish_live(top_k: int = TOP_K) -> dict:
    return _default.publish_live(top_k)


def install_collector() -> None:
    _default.install_collector()


def arm(model_dir: str) -> None:
    _default.arm(model_dir)


def programs() -> Dict[str, ProgramMemory]:
    return _default.programs()


def reset() -> None:
    _default.reset()
