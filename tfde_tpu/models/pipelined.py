"""Pipeline-parallel causal LM — a transformer stack executed through the
collective pipeline (parallel/pipeline.py) over the 'pipe' mesh axis.

Scale-up scope beyond the reference (SURVEY.md §2c: "Pipeline parallel:
absent"). Where GPU frameworks place different *programs* on different
devices and hand-schedule send/recv, the TPU-native formulation keeps one
SPMD program: stage weights live stacked along a leading [num_stages, ...]
axis sharded over 'pipe', and activations hop ranks via `lax.ppermute`
(neighbor ICI traffic). See parallel/pipeline.py for the schedule.

Architecture = GPT arrangement (models/gpt.py): tied embedding/LM head,
learned positions, pre-LN TransformerBlocks, causal attention. The model is
deliberately *mesh-agnostic*: `apply` runs the stage stack through
`pipeline_apply` when the active mesh (parallel/axes.use_axes, set by the
step factories) has a 'pipe' axis of size > 1, and as a plain sequential
scan otherwise — so the same params train on a DP mesh or a pipe mesh, which
is exactly what the pipe-vs-DP numerics test asserts
(tests/test_pipelined_lm.py).

Not an `nn.Module`: the stacked-stage param layout ([S, L, ...] leaves) is
the load-bearing design, and flax's module system fights external param
stacking. Instead the class duck-types `model.init(rng, sample, train=...)`
/ `model.apply(variables, batch, train=..., rngs=...)`, which is all
training/step.py's `init_state` + `make_custom_train_step` consume.

3D (round 3): on a mesh with a >1 'tensor' axis the pipe auto-selects
pipeline_apply's partial-manual mode — stage weights shard over 'pipe' AND
Megatron-split over 'tensor' (PipelineParallelStrategy(tensor=T)), with the
automatic partitioner inserting the TP collectives inside the ring
(dp x pp x tp; tests/test_pipelined_lm.py::test_3d_dp_pp_tp_matches_dp).

pp x sp (round 4): a >1 'seq' axis shards the SEQUENCE inside the
fully-manual pipe — stage attention runs the per-shard ring body
(ops/ring_attention.ring_attention_manual via parallel/axes.manual_seq),
activations shard their seq dim in the pipe specs, and the loss routes
through the full-logit path outside the pipe (a last-stage shifted loss
would misalign at shard boundaries). pp x sp x tp and 1F1B+seq are
refused loudly — see _pipe_mesh / parallel/pipeline.py.

Dropout (round-3, closing VERDICT r2 weak #8's capability cliff vs GPT):
`dropout_rate > 0` threads per-tick keys through the shard_map schedule —
each stage derives fold_in(base, microbatch, global_layer, data_shard) from
the tick's microbatch index (pipeline_apply's 3-arg stage_fn form), its pipe
rank, and its data-shard index, so masks are deterministic per seed and
uncorrelated across microbatches, layers, and shards. Masks are layout-
dependent (a different mesh samples different noise), so exact-numerics
parity tests run at dropout 0, like every framework's.

Loss (round-3, VERDICT r2 weak #8's perf note): `loss_and_metrics` computes
the shifted next-token CE through pipeline_apply's last-stage reduction —
the [M, micro, seq, hidden] full-output psum broadcast at the end of the
pipe is replaced by a 3-scalar psum; use `pipelined_next_token_loss` with
make_custom_train_step to train on that path. `apply` (full logits) keeps
the broadcast, which inference/decoding genuinely needs.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

from tfde_tpu.models.transformer import TransformerBlock
from tfde_tpu.parallel import axes as axes_lib
from tfde_tpu.parallel.pipeline import pipeline_apply


@dataclasses.dataclass(frozen=True)
class PipelinedLM:
    """Decoder-only LM over [B, S] int ids -> [B, S, vocab] fp32 logits."""

    vocab_size: int = 50257
    hidden_size: int = 768
    num_heads: int = 12
    mlp_dim: int = 3072
    max_position: int = 1024
    num_stages: int = 2
    layers_per_stage: int = 6
    microbatches: int = 4
    dropout_rate: float = 0.0
    dtype: Any = jnp.bfloat16
    attn_impl: str = "auto"
    fused_qkv: bool = False  # one-GEMM qkv projection (transformer.py)
    remat: Any = False  # False | True/'full' | 'dots' (transformer.remat_policy)
    # pipeline_apply execution mode: None auto-selects — 'auto' (partial-
    # manual shard_map; required for tensor-parallel stage weights, dp x pp
    # x tp) when the mesh has a >1 'tensor' axis, the proven fully-'manual'
    # ring otherwise. Set explicitly to force either.
    pipeline_mode: Optional[str] = None
    # backward schedule for the training loss path: 'gpipe' (AD through the
    # forward ring — activation memory O(M + S) per rank) or '1f1b'
    # (pipeline_train_1f1b: explicit fwd/bwd interleave, memory O(S) with
    # stage-input remat; manual mode only — see parallel/pipeline.py).
    schedule: str = "gpipe"

    @property
    def depth(self) -> int:
        return self.num_stages * self.layers_per_stage

    def _block(self) -> TransformerBlock:
        return TransformerBlock(
            num_heads=self.num_heads,
            head_dim=self.hidden_size // self.num_heads,
            mlp_dim=self.mlp_dim,
            dtype=self.dtype,
            dropout_rate=self.dropout_rate,
            attn_impl=self.attn_impl,
            fused_qkv=self.fused_qkv,
            causal=True,
            norm_style="pre",
        )

    def _dropout_base(self, train: bool, rngs: Optional[dict]):
        """The base dropout key, or None when dropout is inactive. Keys are
        derived as fold_in(base, microbatch, global_layer[, data_shard]) —
        the data-shard fold matters inside shard_map, where flax would
        otherwise draw the SAME mask on every data shard (same key, same
        local shape = correlated dropout across shards). Masks are therefore
        deterministic per seed but layout-dependent; numerical parity tests
        run at dropout 0, like every framework's."""
        if not train or self.dropout_rate <= 0.0 or not rngs:
            return None
        return rngs.get("dropout")

    # -- init ----------------------------------------------------------------
    def init(self, rng, sample_tokens: jax.Array, train: bool = False) -> dict:
        """Returns {'params': {wte, wpe, stages, ln_final}} where every leaf
        under 'stages' is stacked [num_stages, layers_per_stage, ...]."""
        del train
        if self.hidden_size % self.num_heads:
            raise ValueError("hidden_size must divide by num_heads")
        seq = sample_tokens.shape[1]
        if seq > self.max_position:
            raise ValueError(f"seq {seq} > max_position {self.max_position}")
        k_wte, k_wpe, k_blocks = jax.random.split(rng, 3)

        block = self._block()
        dummy = jnp.zeros((1, seq, self.hidden_size), self.dtype)
        n = self.num_stages * self.layers_per_stage
        block_keys = jax.random.split(k_blocks, n)
        per_layer = jax.vmap(
            lambda k: block.init(k, dummy, None, False)["params"]
        )(block_keys)
        stages = jax.tree_util.tree_map(
            lambda v: v.reshape(
                (self.num_stages, self.layers_per_stage) + v.shape[1:]
            ),
            per_layer,
        )
        params = {
            "wte": jax.random.normal(
                k_wte, (self.vocab_size, self.hidden_size), jnp.float32
            ) * 0.02,
            "wpe": jax.random.normal(
                k_wpe, (self.max_position, self.hidden_size), jnp.float32
            ) * 0.02,
            "stages": stages,
            "ln_final": {
                "scale": jnp.ones((self.hidden_size,), jnp.float32),
                "bias": jnp.zeros((self.hidden_size,), jnp.float32),
            },
        }
        return {"params": params}

    # -- shared pieces -------------------------------------------------------
    def _embed(self, p: dict, tokens: jax.Array) -> jax.Array:
        seq = tokens.shape[1]
        if seq > self.max_position:
            raise ValueError(f"seq {seq} > max_position {self.max_position}")
        x = jnp.take(p["wte"], tokens, axis=0)
        x = x + p["wpe"][None, :seq]
        return x.astype(self.dtype)

    @staticmethod
    def _head(extra: dict, x: jax.Array) -> jax.Array:
        """Final LN in fp32, then the tied LM head (GPT-2 convention).
        extra = {'wte', 'ln_final'}; usable inside the pipe's last-stage
        reduction as well as on the broadcast output."""
        x32 = x.astype(jnp.float32)
        mean = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.var(x32, axis=-1, keepdims=True)
        x32 = (x32 - mean) * jax.lax.rsqrt(var + 1e-6)
        x32 = x32 * extra["ln_final"]["scale"] + extra["ln_final"]["bias"]
        logits = x32.astype(x.dtype) @ extra["wte"].astype(x.dtype).T
        return logits.astype(jnp.float32)

    def _make_layer_fn(self, train: bool, base_key, in_pipe: bool,
                       shard_axes: tuple = (), auto_axes: bool = False,
                       seq_ring: int = 1, manual_axes: tuple = ()):
        """One block application, scanned over a stage's layers. Carries
        (h, mb_idx); per-layer dropout key = fold_in(base, mb, layer) plus,
        inside the fully-manual pipe, the data-shard index (see
        _dropout_base; in auto mode masks are global, no fold needed)."""
        block = self._block()

        def layer(carry, lp_li):
            h, mb = carry
            lp, li = lp_li
            kwargs = {}
            if base_key is not None:
                key = jax.random.fold_in(
                    jax.random.fold_in(base_key, mb), li
                )
                for a in shard_axes:
                    key = jax.random.fold_in(key, jax.lax.axis_index(a))
                kwargs["rngs"] = {"dropout": key}
            if in_pipe and not auto_axes:
                # fully-manual shard_map: every mesh axis is manual, so the
                # blocks' `constrain` annotations (which name full-mesh
                # axes) must degrade to identity here. With a >1 'seq'
                # ring, attention must run the per-shard ring body
                # (pp x sp) — manual_seq flips ops/attention's dispatch.
                with axes_lib.use_axes(None):
                    if seq_ring > 1:
                        with axes_lib.manual_seq(seq_ring, manual_axes):
                            h = block.apply({"params": lp}, h, None, train,
                                            **kwargs)
                    else:
                        h = block.apply({"params": lp}, h, None, train,
                                        **kwargs)
            elif in_pipe:
                # partial-manual (auto) mode: non-pipe axes stay under the
                # automatic partitioner — bind constraints to the abstract
                # mesh so 'tensor'/'data' annotations apply inside the ring
                from tfde_tpu.utils import compat as _compat

                with axes_lib.use_axes(_compat.get_abstract_mesh()):
                    h = block.apply({"params": lp}, h, None, train, **kwargs)
            else:
                h = block.apply({"params": lp}, h, None, train, **kwargs)
            return (h, mb), None

        from tfde_tpu.models.transformer import remat_policy

        policy = remat_policy(self.remat)
        if policy is not None:
            layer = jax.checkpoint(layer, policy=policy)
        return layer

    def _pipe_mode(self, mesh) -> str:
        if self.pipeline_mode is not None:
            return self.pipeline_mode
        tensor = "tensor" in mesh.axis_names and mesh.shape["tensor"] > 1
        return "auto" if tensor else "manual"

    def _make_stage_fn(self, train: bool, base_key, mesh=None):
        from tfde_tpu.parallel.sharding import data_axes as _data_axes

        auto = mesh is not None and self._pipe_mode(mesh) == "auto"
        seq_ring = self._seq_ring(mesh) if mesh is not None else 1
        shard_axes = _data_axes(mesh) if (mesh is not None and base_key
                                          is not None and not auto) else ()
        if shard_axes and seq_ring > 1:
            shard_axes = shard_axes + ("seq",)  # uncorrelated dropout/shard
        layer = self._make_layer_fn(
            train, base_key, in_pipe=True, shard_axes=shard_axes,
            auto_axes=auto, seq_ring=seq_ring,
            # >1 axes only, matching data_axes/vary conventions: promoting
            # accumulators over a SIZE-1 axis would retype the stage-scan
            # carry mid-loop (caught at dryrun data=1 x pipe=2 x seq=2)
            manual_axes=tuple(
                a for a in mesh.axis_names if mesh.shape[a] > 1
            ) if mesh is not None else (),
        )
        lps = self.layers_per_stage

        def stage_fn(stage_params, h, mb_idx):
            # stage_params: [layers_per_stage, ...] pytree; scan applies the
            # same traced block per layer — compiler-friendly, no unrolling.
            # Global layer index = rank * layers_per_stage + local index.
            rank = jax.lax.axis_index("pipe")
            lis = rank * lps + jnp.arange(lps)
            (h, _), _ = jax.lax.scan(layer, (h, mb_idx), (stage_params, lis))
            return h

        return stage_fn

    def _sequential_stack(
        self, p: dict, x: jax.Array, train: bool, base_key
    ) -> jax.Array:
        """No-pipe fallback. With dropout active, processes the batch in the
        SAME microbatch slices with the SAME (mb, layer) keys as the pipe
        path, so the numerics are identical either way."""
        flat = jax.tree_util.tree_map(
            lambda v: v.reshape((self.depth,) + v.shape[2:]), p["stages"]
        )
        layer = self._make_layer_fn(train, base_key, in_pipe=False)
        lis = jnp.arange(self.depth)
        if base_key is None:
            (x, _), _ = jax.lax.scan(layer, (x, jnp.int32(0)), (flat, lis))
            return x
        m = self.microbatches
        batch = x.shape[0]
        if batch % m:
            raise ValueError(
                f"global batch {batch} must divide by microbatches {m}"
            )
        xm = x.reshape((m, batch // m) + x.shape[1:])

        def per_mb(h, mb):
            (h, _), _ = jax.lax.scan(layer, (h, mb), (flat, lis))
            return h

        xm = jax.vmap(per_mb)(xm, jnp.arange(m))
        return xm.reshape((batch,) + x.shape[1:])

    def _microbatched(self, x: jax.Array) -> jax.Array:
        batch = x.shape[0]
        m = self.microbatches
        if batch % m:
            raise ValueError(
                f"global batch {batch} must divide by microbatches {m}"
            )
        return x.reshape((m, batch // m) + x.shape[1:])

    @staticmethod
    def _seq_ring(mesh) -> int:
        return (mesh.shape["seq"]
                if mesh is not None and "seq" in mesh.axis_names else 1)

    def _pipe_mesh(self):
        mesh = axes_lib.current_mesh()
        if (
            mesh is not None
            and "pipe" in mesh.axis_names
            and mesh.shape["pipe"] > 1
        ):
            if self._seq_ring(mesh) > 1 and self._pipe_mode(mesh) != "manual":
                # pp x sp runs only in the fully-manual ring (the ring
                # body inlines into the same flat manual region); the
                # partial-manual 'tensor' mode would nest manual regions,
                # which does not lower (Shardy, jax 0.9)
                raise ValueError(
                    "pp x sp x tp does not compose: a 'seq' axis needs the "
                    "fully-manual pipe (no 'tensor' axis / "
                    "pipeline_mode='manual') — drop either tensor or seq"
                )
            return mesh
        return None

    # -- apply ---------------------------------------------------------------
    def apply(
        self,
        variables: dict,
        tokens: jax.Array,
        train: bool = False,
        rngs: Optional[dict] = None,
    ) -> jax.Array:
        p = variables["params"]
        batch, seq = tokens.shape
        x = self._embed(p, tokens)
        base_key = self._dropout_base(train, rngs)

        mesh = self._pipe_mesh()
        if mesh is not None:
            xm = self._microbatched(x)
            xm = pipeline_apply(
                self._make_stage_fn(train, base_key, mesh), p["stages"],
                xm, mesh, mode=self._pipe_mode(mesh),
            )
            x = xm.reshape((batch, seq, self.hidden_size))
        else:
            x = self._sequential_stack(p, x, train, base_key)
        return self._head({"wte": p["wte"], "ln_final": p["ln_final"]}, x)

    # -- loss (last-stage reduction) ----------------------------------------
    def loss_and_metrics(
        self,
        variables: dict,
        tokens: jax.Array,
        train: bool = False,
        rngs: Optional[dict] = None,
    ):
        """Shifted next-token CE (gpt.next_token_loss convention) computed
        through the pipe's last-stage reduction: only {loss, correct, count}
        sums cross the ring instead of the full [M, micro, seq, hidden]
        output broadcast. Returns (loss, {'next_token_accuracy': acc})."""
        p = variables["params"]
        base_key = self._dropout_base(train, rngs)
        labels = tokens[:, 1:].astype(jnp.int32)

        mesh = self._pipe_mesh()
        if mesh is None or self._seq_ring(mesh) > 1:
            # no pipe mesh: the sequential fallback. pp x sp: loss on the
            # GLOBAL sequence outside the pipe — the last-stage reduction
            # would shift labels across seq-shard boundaries. Either way
            # the full-logit path computes the exact shifted CE.
            if mesh is not None and self.schedule == "1f1b":
                raise NotImplementedError(
                    "schedule='1f1b' does not compose with a 'seq' axis "
                    "(its loss runs inside the pipe, where the shifted "
                    "next-token loss would misalign at shard boundaries) "
                    "— use schedule='gpipe' for pp x sp"
                )
            logits = self.apply(variables, tokens, train=train, rngs=rngs)
            from tfde_tpu.ops.losses import masked_lm_loss

            loss, acc = masked_lm_loss(logits[:, :-1], labels)
            return loss, {"next_token_accuracy": acc}

        x = self._embed(p, tokens)
        xm = self._microbatched(x)
        labels_m = self._microbatched(labels)
        extra = {"wte": p["wte"], "ln_final": p["ln_final"]}
        head = self._head

        def reduce_fn(extra, outputs, labels_loc):
            # outputs [..., micro_local, seq, H]; labels_loc [...,
            # micro_local, seq-1] — the leading dims are [M] on the GPipe
            # full-buffer reduction and absent on the 1F1B per-microbatch
            # loss, so slicing is ellipsis-based. Per-shard SUMS
            # (the pipeline psums them globally).
            logits = head(extra, outputs)[..., :-1, :]
            import optax

            per_tok = optax.losses.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), labels_loc
            )
            correct = (jnp.argmax(logits, axis=-1) == labels_loc)
            return {
                "loss_sum": jnp.sum(per_tok),
                "correct_sum": jnp.sum(correct.astype(jnp.float32)),
                "count": jnp.asarray(per_tok.size, jnp.float32),
            }

        if self.schedule not in ("gpipe", "1f1b"):
            raise ValueError(
                f"schedule must be 'gpipe' or '1f1b', got {self.schedule!r}"
            )
        mode = self._pipe_mode(mesh)
        if self.schedule == "1f1b":
            if mode != "manual":
                raise NotImplementedError(
                    "schedule='1f1b' runs in the fully-manual ring only; "
                    "the partial-manual 'tensor' mode (dp x pp x tp) uses "
                    "AD for its backward — use schedule='gpipe' there"
                )
            red = _sums_1f1b(self, mesh, reduce_fn, train)(
                p["stages"], extra, xm, labels_m, base_key
            )
        else:
            red = pipeline_apply(
                self._make_stage_fn(train, base_key, mesh), p["stages"],
                xm, mesh, reduce_fn=reduce_fn, reduce_aux=labels_m,
                extra_params=extra, mode=mode,
            )
        denom = jnp.maximum(red["count"], 1.0)
        loss = red["loss_sum"] / denom
        acc = red["correct_sum"] / denom
        return loss, {"next_token_accuracy": acc}


def _sums_1f1b(model: "PipelinedLM", mesh, loss_fn, train: bool):
    """custom_vjp around the pipelined loss sums so jax.grad composes with
    the hand-scheduled 1F1B backward (parallel/pipeline.pipeline_train_1f1b):

    - primal (no differentiation, e.g. eval loss): the cheap forward-only
      GPipe pass — identical sums, no gradient work.
    - fwd rule (under jax.grad): ONE 1F1B pass computes the sums AND the
      gradients; the grads ride the residuals.
    - bwd rule: scales the stored grads by the loss_sum cotangent. The
      other sums (count, correct_sum) are shape-constants / argmax metrics
      with zero derivative a.e. — their cotangents are ignored.

    The dropout key is an explicit argument (not a closure): custom_vjp
    functions must not close over tracers, and the key is traced inside a
    jitted train step.
    """
    import numpy as np

    def stage_of(key):
        return model._make_stage_fn(train, key, mesh)

    @jax.custom_vjp
    def sums(stages, extra, xm, labels_m, key):
        return pipeline_apply(
            stage_of(key), stages, xm, mesh, reduce_fn=loss_fn,
            reduce_aux=labels_m, extra_params=extra, mode="manual",
        )

    def fwd(stages, extra, xm, labels_m, key):
        from tfde_tpu.parallel.pipeline import pipeline_train_1f1b

        s, grads = pipeline_train_1f1b(
            stage_of(key), stages, xm, mesh, loss_fn=loss_fn,
            loss_aux=labels_m, extra_params=extra,
        )
        return s, (grads, labels_m, key)

    def bwd(res, ct):
        grads, labels_m, key = res
        scale = ct["loss_sum"]
        sc = lambda t: jax.tree_util.tree_map(
            lambda g: (g * scale).astype(g.dtype), t
        )
        key_ct = (None if key is None
                  else np.zeros(np.shape(key), jax.dtypes.float0))
        return (sc(grads["stages"]), sc(grads["extra"]), sc(grads["x"]),
                np.zeros(labels_m.shape, jax.dtypes.float0), key_ct)

    sums.defvjp(fwd, bwd)
    return sums


def pipelined_next_token_loss(state, params, batch, rng):
    """(loss, metrics) for make_custom_train_step — gpt.next_token_loss's
    pipelined twin, routed through the last-stage reduction so the training
    step never pays the full-logit psum broadcast."""
    (tokens,) = batch if isinstance(batch, tuple) else (batch,)
    model = state.apply_fn.__self__  # PipelinedLM instance (bound method)
    loss, metrics = model.loss_and_metrics(
        {"params": params}, tokens, train=True, rngs={"dropout": rng}
    )
    return loss, metrics


def pipelined_tiny_test(**kw) -> PipelinedLM:
    """CI config for the 8-device CPU mesh (SURVEY.md §4)."""
    defaults = dict(
        vocab_size=97, hidden_size=32, num_heads=4, mlp_dim=64,
        max_position=64, num_stages=2, layers_per_stage=2, microbatches=4,
        dtype=jnp.float32,
    )
    defaults.update(kw)
    return PipelinedLM(**defaults)
