"""Pipeline-parallel causal LM — a transformer stack executed through the
collective pipeline (parallel/pipeline.py) over the 'pipe' mesh axis.

Scale-up scope beyond the reference (SURVEY.md §2c: "Pipeline parallel:
absent"). Where GPU frameworks place different *programs* on different
devices and hand-schedule send/recv, the TPU-native formulation keeps one
SPMD program: stage weights live stacked along a leading [num_stages, ...]
axis sharded over 'pipe', and activations hop ranks via `lax.ppermute`
(neighbor ICI traffic). See parallel/pipeline.py for the schedule.

Architecture = GPT arrangement (models/gpt.py): tied embedding/LM head,
learned positions, pre-LN TransformerBlocks, causal attention. The model is
deliberately *mesh-agnostic*: `apply` runs the stage stack through
`pipeline_apply` when the active mesh (parallel/axes.use_axes, set by the
step factories) has a 'pipe' axis of size > 1, and as a plain sequential
scan otherwise — so the same params train on a DP mesh or a pipe mesh, which
is exactly what the pipe-vs-DP numerics test asserts
(tests/test_pipelined_lm.py).

Not an `nn.Module`: the stacked-stage param layout ([S, L, ...] leaves) is
the load-bearing design, and flax's module system fights external param
stacking. Instead the class duck-types `model.init(rng, sample, train=...)`
/ `model.apply(variables, batch, train=..., rngs=...)`, which is all
training/step.py's `init_state` + `make_custom_train_step` consume.

Dropout is fixed at 0 in the pipelined stack (rngs accepted and unused):
threading per-tick dropout keys through the shard_map schedule buys nothing
for the LM pretraining configs this serves (GPT-2 uses dropout 0.0 at scale).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

from tfde_tpu.models.transformer import TransformerBlock
from tfde_tpu.parallel import axes as axes_lib
from tfde_tpu.parallel.pipeline import pipeline_apply


@dataclasses.dataclass(frozen=True)
class PipelinedLM:
    """Decoder-only LM over [B, S] int ids -> [B, S, vocab] fp32 logits."""

    vocab_size: int = 50257
    hidden_size: int = 768
    num_heads: int = 12
    mlp_dim: int = 3072
    max_position: int = 1024
    num_stages: int = 2
    layers_per_stage: int = 6
    microbatches: int = 4
    dtype: Any = jnp.bfloat16
    attn_impl: str = "auto"
    remat: bool = False  # jax.checkpoint each block: HBM for FLOPs

    @property
    def depth(self) -> int:
        return self.num_stages * self.layers_per_stage

    def _block(self) -> TransformerBlock:
        return TransformerBlock(
            num_heads=self.num_heads,
            head_dim=self.hidden_size // self.num_heads,
            mlp_dim=self.mlp_dim,
            dtype=self.dtype,
            dropout_rate=0.0,
            attn_impl=self.attn_impl,
            causal=True,
            norm_style="pre",
        )

    # -- init ----------------------------------------------------------------
    def init(self, rng, sample_tokens: jax.Array, train: bool = False) -> dict:
        """Returns {'params': {wte, wpe, stages, ln_final}} where every leaf
        under 'stages' is stacked [num_stages, layers_per_stage, ...]."""
        del train
        if self.hidden_size % self.num_heads:
            raise ValueError("hidden_size must divide by num_heads")
        seq = sample_tokens.shape[1]
        if seq > self.max_position:
            raise ValueError(f"seq {seq} > max_position {self.max_position}")
        k_wte, k_wpe, k_blocks = jax.random.split(rng, 3)

        block = self._block()
        dummy = jnp.zeros((1, seq, self.hidden_size), self.dtype)
        n = self.num_stages * self.layers_per_stage
        block_keys = jax.random.split(k_blocks, n)
        per_layer = jax.vmap(
            lambda k: block.init(k, dummy, None, False)["params"]
        )(block_keys)
        stages = jax.tree_util.tree_map(
            lambda v: v.reshape(
                (self.num_stages, self.layers_per_stage) + v.shape[1:]
            ),
            per_layer,
        )
        params = {
            "wte": jax.random.normal(
                k_wte, (self.vocab_size, self.hidden_size), jnp.float32
            ) * 0.02,
            "wpe": jax.random.normal(
                k_wpe, (self.max_position, self.hidden_size), jnp.float32
            ) * 0.02,
            "stages": stages,
            "ln_final": {
                "scale": jnp.ones((self.hidden_size,), jnp.float32),
                "bias": jnp.zeros((self.hidden_size,), jnp.float32),
            },
        }
        return {"params": params}

    # -- apply ---------------------------------------------------------------
    def apply(
        self,
        variables: dict,
        tokens: jax.Array,
        train: bool = False,
        rngs: Optional[dict] = None,
    ) -> jax.Array:
        del rngs  # dropout fixed at 0; see module docstring
        p = variables["params"]
        batch, seq = tokens.shape
        if seq > self.max_position:
            raise ValueError(f"seq {seq} > max_position {self.max_position}")

        x = jnp.take(p["wte"], tokens, axis=0)
        x = x + p["wpe"][None, :seq]
        x = x.astype(self.dtype)

        block = self._block()

        def layer_in_pipe(h, lp):
            # use_axes(None): inside shard_map every mesh axis is manual, so
            # the blocks' `constrain` annotations (which name full-mesh axes)
            # must degrade to identity here.
            with axes_lib.use_axes(None):
                return block.apply({"params": lp}, h, None, train), None

        def layer_seq(h, lp):
            return block.apply({"params": lp}, h, None, train), None

        if self.remat:
            layer_in_pipe = jax.checkpoint(
                layer_in_pipe, policy=jax.checkpoint_policies.nothing_saveable
            )
            layer_seq = jax.checkpoint(
                layer_seq, policy=jax.checkpoint_policies.nothing_saveable
            )

        def stage_fn(stage_params, h):
            # stage_params: [layers_per_stage, ...] pytree; scan applies the
            # same traced block per layer — compiler-friendly, no unrolling.
            h, _ = jax.lax.scan(layer_in_pipe, h, stage_params)
            return h

        mesh = axes_lib.current_mesh()
        pipelined = (
            mesh is not None
            and "pipe" in mesh.axis_names
            and mesh.shape["pipe"] > 1
        )
        if pipelined:
            m = self.microbatches
            if batch % m:
                raise ValueError(
                    f"global batch {batch} must divide by microbatches {m}"
                )
            xm = x.reshape((m, batch // m, seq, self.hidden_size))
            xm = pipeline_apply(stage_fn, p["stages"], xm, mesh)
            x = xm.reshape((batch, seq, self.hidden_size))
        else:
            # sequential fallback: one scan over all S*L layers
            flat = jax.tree_util.tree_map(
                lambda v: v.reshape((self.depth,) + v.shape[2:]), p["stages"]
            )
            x, _ = jax.lax.scan(layer_seq, x, flat)

        # final LN in fp32, then the tied LM head (GPT-2 convention)
        x32 = x.astype(jnp.float32)
        mean = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.var(x32, axis=-1, keepdims=True)
        x32 = (x32 - mean) * jax.lax.rsqrt(var + 1e-6)
        x32 = x32 * p["ln_final"]["scale"] + p["ln_final"]["bias"]
        logits = x32.astype(self.dtype) @ p["wte"].astype(self.dtype).T
        return logits.astype(jnp.float32)


def pipelined_tiny_test(**kw) -> PipelinedLM:
    """CI config for the 8-device CPU mesh (SURVEY.md §4)."""
    defaults = dict(
        vocab_size=97, hidden_size=32, num_heads=4, mlp_dim=64,
        max_position=64, num_stages=2, layers_per_stage=2, microbatches=4,
        dtype=jnp.float32,
    )
    defaults.update(kw)
    return PipelinedLM(**defaults)
