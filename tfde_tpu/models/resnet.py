"""ResNet family — the CIFAR-10/ImageNet scale-up config.

BASELINE.json configs[2]: "CIFAR-10 ResNet-50 (distributed_with_keras.py
scaled to v4-32)". The reference itself has no ResNet (its largest model is
the 3-conv MNIST BN-CNN, mnist_keras_distributed.py:67-120); this is the
driver-mandated scale config built on the same Flax/TrainState conventions as
models/cnn.py so every strategy in parallel/strategies.py applies unchanged.

TPU-first choices:
- bf16 activations/weights-compute, fp32 parameter master copies and BN
  statistics (`dtype` vs `param_dtype`): keeps the MXU fed at its native
  precision while preserving training numerics.
- ResNet v1.5 bottleneck (stride on the 3x3, not the 1x1): the layout XLA's
  conv emitter tiles best, and the variant modern TPU baselines quote.
- A `cifar_stem` flag (3x3/stride-1 stem, no max-pool) so 32x32 inputs keep
  spatial extent — standard CIFAR practice; ImageNet stem is the default.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    """1x1 reduce -> 3x3 (stride here: v1.5) -> 1x1 expand, residual add."""

    features: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        residual = x
        y = self.conv(self.features, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.features, (3, 3), strides=self.strides)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.features * 4, (1, 1))(y)
        # Zero-init the last BN scale so each block starts as identity —
        # standard ResNet trick; large-batch DP training depends on it.
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.features * 4, (1, 1), strides=self.strides,
                name="conv_proj",
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class BasicBlock(nn.Module):
    """Two 3x3 convs with residual add — ResNet-18/34 block."""

    features: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        residual = x
        y = self.conv(self.features, (3, 3), strides=self.strides)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.features, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.features, (1, 1), strides=self.strides, name="conv_proj"
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """`stage_sizes` picks the depth (50 = [3,4,6,3]); `block_cls` the block."""

    stage_sizes: Sequence[int]
    block_cls: ModuleDef = BottleneckBlock
    num_classes: int = 10
    num_filters: int = 64
    dtype: jnp.dtype = jnp.bfloat16
    cifar_stem: bool = False

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        conv = functools.partial(
            nn.Conv, use_bias=False, dtype=self.dtype, param_dtype=jnp.float32
        )
        norm = functools.partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            param_dtype=jnp.float32,
        )
        x = x.astype(self.dtype)
        if self.cifar_stem:
            x = conv(self.num_filters, (3, 3), name="conv_init")(x)
        else:
            x = conv(self.num_filters, (7, 7), strides=(2, 2), name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        if not self.cifar_stem:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    features=self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                )(x)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        # Head in fp32: the logits/softmax path is precision-sensitive.
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


ResNet18 = functools.partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock)
ResNet50 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3])
ResNet101 = functools.partial(ResNet, stage_sizes=[3, 4, 23, 3])


def resnet50_cifar(num_classes: int = 10, dtype: jnp.dtype = jnp.bfloat16) -> ResNet:
    """The BASELINE.json configs[2] model: ResNet-50, CIFAR stem, 10 classes."""
    return ResNet50(num_classes=num_classes, dtype=dtype, cifar_stem=True)
