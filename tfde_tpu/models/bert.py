"""BERT encoder + masked-LM head — the sequence scale-up config.

BASELINE.json configs[4]: "BERT-base MLM (sequence batch data-parallel on
v4-32)". The reference has no sequence models (SURVEY.md §5 "long-context:
entirely absent"); this is the driver-mandated config, sharing the encoder
core (models/transformer.py, norm_style='post' — original BERT arrangement)
so TP/SP/ring-attention apply to it unchanged.

TPU-first choices:
- bf16 activations / fp32 params + LayerNorms (models/transformer.py).
- Tied MLM decoder: logits = h @ E^T via `nn.Embed.attend` — one [hidden,
  vocab] matmul on the MXU, no separate 23M-param decoder matrix.
- Vocab size 30522 rounds to 30720 (multiple of 128) when `pad_vocab=True`
  so the embedding/decoder matmuls tile the MXU cleanly; padded ids are
  never produced by the masking pipeline.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from tfde_tpu.models.transformer import Encoder
from tfde_tpu.ops.attention import padding_mask
from tfde_tpu.parallel.axes import batch_axes, constrain


class BertEmbeddings(nn.Module):
    vocab_size: int
    hidden_size: int
    max_position: int = 512
    type_vocab_size: int = 2
    dropout_rate: float = 0.1
    dtype: jnp.dtype = jnp.bfloat16
    ln_eps: float = 1e-6

    def setup(self):
        self.word = nn.Embed(
            self.vocab_size, self.hidden_size, dtype=self.dtype,
            param_dtype=jnp.float32, name="word",
        )
        self.position = nn.Embed(
            self.max_position, self.hidden_size, dtype=self.dtype,
            param_dtype=jnp.float32, name="position",
        )
        self.token_type = nn.Embed(
            self.type_vocab_size, self.hidden_size, dtype=self.dtype,
            param_dtype=jnp.float32, name="token_type",
        )
        self.ln = nn.LayerNorm(epsilon=self.ln_eps, dtype=jnp.float32,
                               param_dtype=jnp.float32)
        self.dropout = nn.Dropout(self.dropout_rate)

    def __call__(
        self,
        input_ids: jax.Array,
        token_type_ids: Optional[jax.Array] = None,
        train: bool = False,
    ) -> jax.Array:
        seq = input_ids.shape[1]
        x = self.word(input_ids)
        x = x + self.position(jnp.arange(seq, dtype=jnp.int32)[None, :])
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = x + self.token_type(token_type_ids)
        x = self.ln(x).astype(self.dtype)
        return self.dropout(x, deterministic=not train)


def _trunk(m, input_ids, attention_mask, token_type_ids, train):
    """Shared embeddings+encoder trunk of `Bert` and `BertClassifier` —
    ONE construction site, so the param-tree names/shapes the weight-graft
    and HF-conversion paths depend on cannot diverge between the two
    heads. `m` is either module (identical trunk fields by construction).
    Returns (hidden states [B, S, H], the embeddings module for head
    weight-tying)."""
    b = batch_axes()
    emb = BertEmbeddings(
        vocab_size=m.padded_vocab,
        hidden_size=m.hidden_size,
        max_position=m.max_position,
        type_vocab_size=m.type_vocab_size,
        dropout_rate=m.dropout_rate,
        dtype=m.dtype,
        ln_eps=m.ln_eps,
        name="embeddings",
    )
    x = emb(input_ids, token_type_ids, train=train)
    x = constrain(x, b, "seq")
    mask = None
    if attention_mask is not None:
        mask = padding_mask(attention_mask)
    x = Encoder(
        depth=m.depth,
        num_heads=m.num_heads,
        head_dim=m.hidden_size // m.num_heads,
        mlp_dim=m.mlp_dim,
        dtype=m.dtype,
        dropout_rate=m.dropout_rate,
        attn_impl=m.attn_impl,
        fused_qkv=m.fused_qkv,
        norm_style="post",
        ln_eps=m.ln_eps,
        remat=m.remat,
        name="encoder",
    )(x, mask=mask, train=train)
    return x, emb


class Bert(nn.Module):
    """BERT encoder with tied masked-LM head over [B, S] int token ids."""

    vocab_size: int = 30522
    hidden_size: int = 768
    depth: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    max_position: int = 512
    type_vocab_size: int = 2
    dropout_rate: float = 0.1
    dtype: jnp.dtype = jnp.bfloat16
    attn_impl: str = "auto"
    remat: Any = False  # False | True/'full' | 'dots' (transformer.remat_policy)
    fused_qkv: bool = False  # one-GEMM qkv projection (transformer.py)
    pad_vocab: bool = False
    ln_eps: float = 1e-6  # BERT checkpoints use 1e-12 (models/convert.py)

    @property
    def padded_vocab(self) -> int:
        if not self.pad_vocab:
            return self.vocab_size
        return -(-self.vocab_size // 128) * 128  # round up to MXU lane width

    @nn.compact
    def __call__(
        self,
        input_ids: jax.Array,
        attention_mask: Optional[jax.Array] = None,
        token_type_ids: Optional[jax.Array] = None,
        train: bool = False,
    ) -> jax.Array:
        """Returns MLM logits [B, S, vocab] (fp32)."""
        b = batch_axes()
        x, emb = _trunk(self, input_ids, attention_mask, token_type_ids,
                        train)

        # MLM transform head (dense + gelu + LN), then tied decoder.
        h = nn.Dense(
            self.hidden_size, dtype=self.dtype, param_dtype=jnp.float32,
            name="mlm_dense",
        )(x)
        h = nn.gelu(h)
        h = nn.LayerNorm(
            epsilon=self.ln_eps, dtype=jnp.float32, param_dtype=jnp.float32,
            name="mlm_ln",
        )(h)
        logits = emb.word.attend(h.astype(self.dtype))
        bias = self.param(
            "mlm_bias", nn.initializers.zeros, (self.padded_vocab,), jnp.float32
        )
        logits = logits.astype(jnp.float32) + bias
        return constrain(logits, b, "seq", "tensor")


class BertClassifier(nn.Module):
    """BERT encoder + pooler + sequence-classification head — the
    fine-tuning workflow every BERT deployment actually runs (GLUE-style:
    pretrain MLM, classify on [CLS]).

    The embeddings/encoder submodules carry the SAME names and shapes as
    `Bert`'s, so MLM-pretrained params (or an HF conversion,
    models/convert.py bert_from_hf) transfer directly —
    `classifier_params_from_mlm` grafts them under freshly initialized
    pooler/classifier heads. Pooler = tanh(Dense(hidden)) on the [CLS]
    position, the original BERT arrangement; logits are fp32.
    """

    num_labels: int
    vocab_size: int = 30522
    hidden_size: int = 768
    depth: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    max_position: int = 512
    type_vocab_size: int = 2
    dropout_rate: float = 0.1
    dtype: jnp.dtype = jnp.bfloat16
    attn_impl: str = "auto"
    remat: Any = False
    fused_qkv: bool = False
    pad_vocab: bool = False
    ln_eps: float = 1e-6

    @property
    def padded_vocab(self) -> int:
        if not self.pad_vocab:
            return self.vocab_size
        return -(-self.vocab_size // 128) * 128

    @nn.compact
    def __call__(
        self,
        input_ids: jax.Array,
        attention_mask: Optional[jax.Array] = None,
        token_type_ids: Optional[jax.Array] = None,
        train: bool = False,
    ) -> jax.Array:
        """Returns classification logits [B, num_labels] (fp32)."""
        b = batch_axes()
        x, _ = _trunk(self, input_ids, attention_mask, token_type_ids,
                      train)

        pooled = jnp.tanh(
            nn.Dense(
                self.hidden_size, dtype=self.dtype, param_dtype=jnp.float32,
                name="pooler",
            )(x[:, 0])
        )
        if self.dropout_rate > 0.0:
            pooled = nn.Dropout(
                self.dropout_rate, deterministic=not train
            )(pooled)
        logits = nn.Dense(
            self.num_labels, dtype=jnp.float32, param_dtype=jnp.float32,
            name="classifier",
        )(pooled.astype(jnp.float32))
        return constrain(logits, b)


def classifier_params_from_mlm(classifier: BertClassifier, mlm_params,
                               rng, sample_ids) -> dict:
    """Classifier params with the embeddings/encoder grafted from an
    MLM-pretrained `Bert` tree (models/convert.py bert_from_hf output or a
    Bert training run); the pooler/classifier heads keep their fresh
    initialization — the standard fine-tuning starting point."""
    params = dict(classifier.init(rng, sample_ids, train=False)["params"])
    for k in ("embeddings", "encoder"):
        if k not in mlm_params:
            raise ValueError(
                f"MLM params carry no {k!r} subtree — pass a Bert (or "
                f"bert_from_hf) param tree"
            )
        params[k] = mlm_params[k]
    return params


BertBase = functools.partial(
    Bert, hidden_size=768, depth=12, num_heads=12, mlp_dim=3072
)
BertLarge = functools.partial(
    Bert, hidden_size=1024, depth=24, num_heads=16, mlp_dim=4096
)


def bert_tiny_test(**kw) -> Bert:
    """CI config for the 8-device CPU mesh (SURVEY.md §4)."""
    return Bert(
        vocab_size=97, hidden_size=32, depth=2, num_heads=4, mlp_dim=64,
        max_position=64, dtype=jnp.float32, dropout_rate=0.0, **kw,
    )
