"""T5 encoder-decoder family: relative-position-bias attention, unscaled
scores, RMSNorm, relu / gated-gelu MLPs, tied or untied LM head — the
sequence-to-sequence capability beside the causal-LM families.

Beyond-reference scope (the reference trains MNIST classifiers —
/root/reference/mnist_keras_distributed.py:67-120 — with no text model at
all); built because a framework users switch to from the transformers
ecosystem needs the seq2seq family its encoder-only (BERT) and decoder-only
(GPT/LLaMA/...) families bracket. TPU-first choices:

- One attention einsum path: the shared `ops.attention.grouped_attention`
  takes the additive relative-position bias (`bias=`) and T5's unscaled
  convention (`scale=1.0`) as arguments — no forked kernel, and the GQA
  non-materializing einsum / fp32 softmax discipline carries over.
- The relative bias is ONE [num_buckets, heads] table per stack (T5 shares
  block 0's table across layers; storing it at the stack level makes that
  sharing structural instead of a parameter-threading convention) and the
  bucket math is pure jnp — traced once, fused by XLA, no gathers beyond
  one embedding lookup.
- Decode is the same static-shape KV-cache discipline as GPT
  (models/transformer.py): prefill + single-token steps through
  `dynamic_update_slice`, cross-attention K/V computed once from the
  encoder output and cached, self-attention bias computed at traced cache
  positions — the whole generate call is one compiled program
  (`t5_generate`).

HF parity: `t5_from_hf` / `t5_to_hf` (models/convert.py) map
T5ForConditionalGeneration checkpoints both ways, logit-match tested.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from tfde_tpu.ops.attention import grouped_attention
from tfde_tpu.parallel.axes import batch_axes, constrain


def relative_position_bucket(
    relative_position: jax.Array,
    bidirectional: bool = True,
    num_buckets: int = 32,
    max_distance: int = 128,
) -> jax.Array:
    """T5's log-bucketed relative positions (the transformers
    `_relative_position_bucket` math, re-derived in jnp): exact buckets up
    to num_buckets//2 (//4 per sign when bidirectional), log-spaced out to
    max_distance, clamped beyond. relative_position = key_pos - query_pos.
    """
    rel = relative_position.astype(jnp.int32)
    out = jnp.zeros_like(rel)
    if bidirectional:
        num_buckets //= 2
        out = out + (rel > 0).astype(jnp.int32) * num_buckets
        rel = jnp.abs(rel)
    else:
        # causal: only the past (rel <= 0) is reachable; future distances
        # clamp to bucket 0 like HF
        rel = -jnp.minimum(rel, 0)
    max_exact = num_buckets // 2
    is_small = rel < max_exact
    # log-spaced buckets for distances in [max_exact, max_distance)
    rel_f = jnp.maximum(rel.astype(jnp.float32), 1.0)
    large = max_exact + (
        jnp.log(rel_f / max_exact)
        / jnp.log(max_distance / max_exact)
        * (num_buckets - max_exact)
    ).astype(jnp.int32)
    large = jnp.minimum(large, num_buckets - 1)
    return out + jnp.where(is_small, rel, large)


class T5Attention(nn.Module):
    """T5 self- or cross-attention: bias-free q/k/v/o projections onto an
    inner dim decoupled from the model dim (d_kv * heads != d_model on
    several releases), UNSCALED scores, additive position bias.

    `bias_fn(q_pos [Sq], k_pos [Sk]) -> [1, H, Sq, Sk]` computes the
    relative bias at absolute positions — passed by the owning stack
    (which holds the one shared table) so the decode path can evaluate it
    at traced cache positions. Cross-attention passes None (T5 gives
    enc-dec attention no position bias).

    decode=True: GPT-style cache (models/transformer.py discipline) —
    self-attention grows `cached_key/value` at `cache_index`;
    cross-attention computes K/V from the encoder output once and caches
    them (they never change during generation).
    """

    num_heads: int
    head_dim: int
    dtype: jnp.dtype = jnp.bfloat16
    causal: bool = False
    decode: bool = False
    dropout_rate: float = 0.0

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        kv: Optional[jax.Array] = None,
        bias_fn: Optional[Callable] = None,
        mask: Optional[jax.Array] = None,
        train: bool = False,
    ) -> jax.Array:
        b_axes = batch_axes()
        proj = functools.partial(
            nn.DenseGeneral, dtype=self.dtype, param_dtype=jnp.float32,
            use_bias=False,
        )
        cross = kv is not None
        source = kv if cross else x
        if cross and mask is not None and mask.ndim == 2:
            # [B, S_enc] source-padding mask -> [B, 1, 1, S_enc]; ONE
            # normalization site for the teacher-forced and decode paths
            # (grouped_attention reads a raw 2-D mask as [Sq, Sk])
            mask = mask[:, None, None, :]
        kproj = functools.partial(proj,
                                  features=(self.num_heads, self.head_dim))
        q = kproj(name="query")(x)
        q = constrain(q, b_axes, "seq", "tensor")

        if self.decode and cross:
            # the whole point of the cross cache: skip the K/V GEMMs over
            # the (constant) encoder output on every filled step
            y = self._decode_cross(q, source, kproj, mask)
        elif self.decode:
            k = constrain(kproj(name="key")(source), b_axes, "seq", "tensor")
            v = constrain(kproj(name="value")(source), b_axes, "seq",
                          "tensor")
            y = self._decode_self(q, k, v, bias_fn)
        else:
            k = constrain(kproj(name="key")(source), b_axes, "seq", "tensor")
            v = constrain(kproj(name="value")(source), b_axes, "seq",
                          "tensor")
            sq, sk = q.shape[1], k.shape[1]
            bias = None
            if bias_fn is not None:
                bias = bias_fn(jnp.arange(sq, dtype=jnp.int32),
                               jnp.arange(sk, dtype=jnp.int32))
            y = grouped_attention(q, k, v, mask=mask, causal=self.causal,
                                  bias=bias, scale=1.0)
        y = constrain(y, b_axes, "seq", "tensor")
        y = proj(features=x.shape[-1], axis=(-2, -1), name="out")(y)
        y = constrain(y, b_axes, "seq")
        if self.dropout_rate > 0.0:
            y = nn.Dropout(self.dropout_rate, deterministic=not train)(y)
        return y

    def _decode_cross(self, q, source, kproj, mask):
        """Encoder K/V are generation-constant. On the cache-creating call
        (t5_generate's real budget-shaped init apply — NOT an eval_shape
        zeros fill, which could not distinguish "filled" from "empty") the
        projections run once and their REAL values become the cache's
        initial values; every later step skips both K/V GEMMs over the
        encoder sequence entirely. mask [B, S_enc] masks padded source
        positions."""
        is_filled = self.has_variable("cache", "cross_key")
        if not is_filled:
            k = kproj(name="key")(source)
            v = kproj(name="value")(source)
            self.variable("cache", "cross_key", lambda: k)
            self.variable("cache", "cross_value", lambda: v)
        else:
            k = self.variable("cache", "cross_key", None).value
            v = self.variable("cache", "cross_value", None).value
        return grouped_attention(q, k, v, mask=mask, scale=1.0)

    def _decode_self(self, q, k, v, bias_fn):
        """Causal cache decode with the relative bias evaluated at the
        query's absolute cache position (models/transformer.py
        `_decode_attention` discipline; shared scalar index — T5 serving
        has no per-row speculative rewind)."""
        is_filled = self.has_variable("cache", "cached_key")
        cached_key = self.variable("cache", "cached_key", jnp.zeros,
                                   k.shape, k.dtype)
        cached_value = self.variable("cache", "cached_value", jnp.zeros,
                                     v.shape, v.dtype)
        cache_index = self.variable("cache", "cache_index",
                                    lambda: jnp.zeros((), jnp.int32))
        if not is_filled:
            sq = q.shape[1]
            pos = jnp.arange(sq, dtype=jnp.int32)
            bias = bias_fn(pos, pos) if bias_fn is not None else None
            return grouped_attention(q, k, v, causal=True, bias=bias,
                                     scale=1.0)
        sq = q.shape[1]
        max_len = cached_key.value.shape[1]
        idx = cache_index.value
        k_all = jax.lax.dynamic_update_slice(
            cached_key.value, k.astype(cached_key.value.dtype),
            (0, idx, 0, 0)
        )
        v_all = jax.lax.dynamic_update_slice(
            cached_value.value, v.astype(cached_value.value.dtype),
            (0, idx, 0, 0)
        )
        pos_q = idx + jnp.arange(sq, dtype=jnp.int32)
        cols = jnp.arange(max_len, dtype=jnp.int32)
        valid = (cols[None, :] <= pos_q[:, None])[None, None]
        bias = bias_fn(pos_q, cols) if bias_fn is not None else None
        cached_key.value = k_all
        cached_value.value = v_all
        cache_index.value = idx + sq
        return grouped_attention(q, k_all, v_all, mask=valid, bias=bias,
                                 scale=1.0)


class T5LayerNorm(nn.Module):
    """T5's RMSNorm: no mean subtraction, no bias, PLAIN weight (unlike
    Gemma's 1+w), computed in fp32."""

    eps: float = 1e-6

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        dtype = x.dtype
        x32 = x.astype(jnp.float32)
        var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],),
                           jnp.float32)
        return (x32 * jax.lax.rsqrt(var + self.eps) * scale).astype(dtype)


class T5Block(nn.Module):
    """Pre-norm residual block: self-attn [+ cross-attn] + MLP, each
    sublayer as x + Sub(LN(x)) with its own RMSNorm."""

    num_heads: int
    head_dim: int
    mlp_dim: int
    mlp_act: str
    dtype: jnp.dtype
    causal: bool
    cross: bool
    decode: bool = False
    dropout_rate: float = 0.0
    ln_eps: float = 1e-6

    @nn.compact
    def __call__(self, x, enc_out=None, bias_fn=None, self_mask=None,
                 enc_mask=None, train=False):
        attn = functools.partial(
            T5Attention, num_heads=self.num_heads, head_dim=self.head_dim,
            dtype=self.dtype, decode=self.decode,
            dropout_rate=self.dropout_rate,
        )
        h = T5LayerNorm(eps=self.ln_eps, name="ln_attn")(x)
        x = x + attn(causal=self.causal, name="attn")(
            h, bias_fn=bias_fn, mask=self_mask, train=train
        )
        if self.cross:
            h = T5LayerNorm(eps=self.ln_eps, name="ln_cross")(x)
            x = x + attn(causal=False, name="cross_attn")(
                h, kv=enc_out, mask=enc_mask, train=train
            )
        from tfde_tpu.models.transformer import Mlp

        h = T5LayerNorm(eps=self.ln_eps, name="ln_mlp")(x)
        x = x + Mlp(
            mlp_dim=self.mlp_dim, dtype=self.dtype, act=self.mlp_act,
            use_bias=False, dropout_rate=self.dropout_rate, name="mlp",
        )(h, train=train)
        return x


class T5Stack(nn.Module):
    """Encoder (bidirectional) or decoder (causal + cross-attention) stack
    with the ONE shared relative-bias table (T5 computes the bias in block
    0 and shares it; owning the table here makes that structural)."""

    depth: int
    num_heads: int
    head_dim: int
    mlp_dim: int
    mlp_act: str
    dtype: jnp.dtype
    causal: bool
    num_buckets: int = 32
    max_distance: int = 128
    decode: bool = False
    dropout_rate: float = 0.0
    ln_eps: float = 1e-6

    @nn.compact
    def __call__(self, x, enc_out=None, self_mask=None, enc_mask=None,
                 train=False):
        table = self.param(
            "rel_bias", nn.initializers.normal(stddev=1.0),
            (self.num_buckets, self.num_heads), jnp.float32,
        )

        def bias_fn(q_pos, k_pos):
            rel = k_pos[None, :] - q_pos[:, None]
            buckets = relative_position_bucket(
                rel, bidirectional=not self.causal,
                num_buckets=self.num_buckets,
                max_distance=self.max_distance,
            )
            # [Sq, Sk, H] -> [1, H, Sq, Sk]. jnp.take (not table[buckets]):
            # converted params arrive as host numpy arrays, which cannot be
            # indexed by a traced bucket array
            return jnp.transpose(
                jnp.take(jnp.asarray(table), buckets, axis=0), (2, 0, 1)
            )[None]

        for i in range(self.depth):
            x = T5Block(
                num_heads=self.num_heads, head_dim=self.head_dim,
                mlp_dim=self.mlp_dim, mlp_act=self.mlp_act,
                dtype=self.dtype, causal=self.causal,
                cross=self.causal, decode=self.decode,
                dropout_rate=self.dropout_rate, ln_eps=self.ln_eps,
                name=f"block_{i}",
            )(x, enc_out=enc_out, bias_fn=bias_fn, self_mask=self_mask,
              enc_mask=enc_mask, train=train)
        x = T5LayerNorm(eps=self.ln_eps, name="ln_final")(x)
        if self.dropout_rate > 0.0:
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return x


class T5(nn.Module):
    """T5ForConditionalGeneration twin: shared embedding, encoder stack,
    decoder stack with cross-attention, tied (v1.0: logits scaled by
    d_model^-0.5) or untied (v1.1) LM head.

    `__call__(input_ids, decoder_input_ids)` is the teacher-forced
    training/eval forward. `encode` / `decode_step` split the model for
    generation (`t5_generate`): encoder runs once, the decoder runs under
    the KV-cache discipline.

    mlp_act: 'relu' (v1.0) or 'geglu' (v1.1's gated tanh-gelu — the
    models/transformer.py Mlp gate convention matches HF's gated-gelu
    wi_0/wi_1 split; conversion maps gate<->wi_0, fc1<->wi_1).
    """

    vocab_size: int = 32128
    hidden_size: int = 512
    depth: int = 6
    decoder_depth: Optional[int] = None  # None = depth
    num_heads: int = 8
    head_dim: int = 64  # T5's d_kv — decoupled from hidden_size/num_heads
    mlp_dim: int = 2048
    mlp_act: str = "relu"
    num_buckets: int = 32
    max_distance: int = 128
    tie_embeddings: bool = True
    dropout_rate: float = 0.1
    dtype: jnp.dtype = jnp.bfloat16
    ln_eps: float = 1e-6
    decode: bool = False
    pad_id: int = 0  # doubles as decoder_start_token_id (the T5 default)

    def setup(self):
        self.shared = nn.Embed(
            self.vocab_size, self.hidden_size,
            embedding_init=nn.initializers.normal(stddev=1.0),
            param_dtype=jnp.float32, name="shared",
        )
        common = dict(
            num_heads=self.num_heads, head_dim=self.head_dim,
            mlp_dim=self.mlp_dim, mlp_act=self.mlp_act, dtype=self.dtype,
            num_buckets=self.num_buckets, max_distance=self.max_distance,
            dropout_rate=self.dropout_rate, ln_eps=self.ln_eps,
        )
        self.encoder = T5Stack(depth=self.depth, causal=False,
                               name="encoder", **common)
        self.decoder = T5Stack(depth=self.decoder_depth or self.depth,
                               causal=True, decode=self.decode,
                               name="decoder", **common)
        if not self.tie_embeddings:
            self.lm_head = nn.Dense(
                self.vocab_size, use_bias=False, dtype=self.dtype,
                param_dtype=jnp.float32, name="lm_head",
            )

    def _logits(self, dec: jax.Array) -> jax.Array:
        if self.tie_embeddings:
            # v1.0 tied-head convention: rescale before the shared table
            dec = dec * (self.hidden_size ** -0.5)
            return self.shared.attend(dec.astype(jnp.float32))
        return self.lm_head(dec).astype(jnp.float32)

    def encode(self, input_ids: jax.Array,
               enc_mask: Optional[jax.Array] = None,
               train: bool = False) -> jax.Array:
        x = self.shared(input_ids).astype(self.dtype)
        if self.dropout_rate > 0.0:
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        self_mask = None if enc_mask is None else enc_mask[:, None, None, :]
        return self.encoder(x, self_mask=self_mask, train=train)

    def decode_step(self, decoder_input_ids: jax.Array, enc_out: jax.Array,
                    enc_mask: Optional[jax.Array] = None,
                    train: bool = False) -> jax.Array:
        x = self.shared(decoder_input_ids).astype(self.dtype)
        if self.dropout_rate > 0.0:
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        dec = self.decoder(x, enc_out=enc_out, enc_mask=enc_mask,
                           train=train)
        return self._logits(dec)

    def __call__(self, input_ids: jax.Array,
                 decoder_input_ids: jax.Array,
                 enc_mask: Optional[jax.Array] = None,
                 train: bool = False) -> jax.Array:
        enc_out = self.encode(input_ids, enc_mask=enc_mask, train=train)
        return self.decode_step(decoder_input_ids, enc_out,
                                enc_mask=enc_mask, train=train)


T5Small = functools.partial(T5)  # t5-small IS the default config
T5Base = functools.partial(
    T5, hidden_size=768, depth=12, num_heads=12, mlp_dim=3072,
)


def t5_tiny_test(**kw) -> T5:
    """CI config for the 8-device CPU mesh (SURVEY.md §4)."""
    defaults = dict(
        vocab_size=97, hidden_size=32, depth=2, num_heads=4, head_dim=8,
        mlp_dim=64, num_buckets=8, max_distance=16, dropout_rate=0.0,
        dtype=jnp.float32,
    )
    defaults.update(kw)
    return T5(**defaults)


def shift_right(labels: jax.Array, start_id: int = 0,
                pad_id: Optional[int] = None,
                ignore_id: int = -100) -> jax.Array:
    """Teacher-forcing decoder inputs from labels (the HF `_shift_right`):
    position 0 is the decoder start token, position i+1 is label i, and
    ignored (-100) label positions feed `pad_id` (defaults to start_id —
    every T5 release sets decoder_start_token_id == pad_token_id == 0,
    but the two roles stay distinct parameters)."""
    pad = start_id if pad_id is None else pad_id
    labels = jnp.where(labels == ignore_id, pad, labels)
    return jnp.concatenate(
        [jnp.full_like(labels[:, :1], start_id), labels[:, :-1]], axis=1
    )


def t5_seq2seq_loss(state, params, batch, rng):
    """(loss, metrics) for make_custom_train_step: teacher-forced seq2seq
    CE. batch = (input_ids, labels) with -100 marking ignored label
    positions (padding); decoder inputs are the shifted labels, starting
    from the MODEL's pad_id (read off state.apply_fn's bound model) so
    training and t5_generate agree on the start token when pad_id != 0."""
    from tfde_tpu.ops.losses import masked_lm_loss

    input_ids, labels = batch
    mdl = getattr(state.apply_fn, "__self__", None)
    start = getattr(mdl, "pad_id", 0)
    dec_in = shift_right(labels, start_id=start)
    logits = state.apply_fn(
        {"params": params}, input_ids, dec_in, train=True,
        rngs={"dropout": rng},
    )
    loss, acc = masked_lm_loss(logits, labels.astype(jnp.int32))
    # the CE normalizes by the target-position count — grad_weight lets
    # grad_accum weight each microbatch by its own count, reproducing the
    # exact full-batch update on padded batches (training/step.py)
    n_targets = jnp.sum((labels != -100).astype(jnp.float32))
    return loss, {"seq2seq_accuracy": acc, "grad_weight": n_targets}


def t5_generate(
    model: T5,
    params,
    input_ids: jax.Array,
    max_new_tokens: int,
    rng: Optional[jax.Array] = None,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    eos_id: Optional[int] = 1,  # </s> in every T5 release
    enc_mask: Optional[jax.Array] = None,
):
    """Encoder-decoder generation: encode once, then KV-cache decode from
    the start token. Returns (tokens [B, 1 + max_new_tokens] — the start
    token then the generated continuation, post-EOS positions hold pad —
    lengths [B] counting generated-through-EOS).

    The same one-compiled-program shape as inference/decode.generate:
    prefill is the single start token, each scan step is one decoder
    forward over the cached prefix + the constant encoder output.
    """
    from tfde_tpu.inference.decode import sample_logits

    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if rng is None:
        rng = jax.random.key(0)
    b = input_ids.shape[0]
    total = 1 + max_new_tokens
    decode_model = model.clone(decode=True, dropout_rate=0.0)

    enc_out = decode_model.apply(
        {"params": params}, input_ids.astype(jnp.int32),
        enc_mask=enc_mask, method=T5.encode,
    )

    # cache init, two cheap halves. Self caches need only SHAPES at the
    # [B, total] budget — eval_shape, zero compute (the GPT
    # inference/decode.init_cache discipline). Cross caches need real
    # VALUES (the encoder K/V projections — what every later step skips),
    # which a 1-token real apply computes through the actual modules
    # (bit-identical to the training forward — no re-derived einsum to
    # drift). Merging by leaf name swaps the real cross_* values into the
    # budget-shaped zero tree; a full budget-shaped real forward here
    # would roughly double the cost of short generations.
    shapes = jax.eval_shape(
        lambda t, e: decode_model.init(jax.random.key(0), t, e,
                                       method=T5.decode_step),
        jax.ShapeDtypeStruct((b, total), jnp.int32),
        jax.ShapeDtypeStruct(enc_out.shape, enc_out.dtype),
    )
    _, seeded = decode_model.apply(
        {"params": params}, jnp.zeros((b, 1), jnp.int32), enc_out,
        enc_mask=enc_mask, mutable=["cache"], method=T5.decode_step,
    )

    def merge(zero_tree, seed_tree):
        out = {}
        for name, sub in zero_tree.items():
            if hasattr(sub, "items"):  # dict or FrozenDict subtree
                out[name] = merge(sub, seed_tree[name])
            elif name.startswith("cross_"):
                out[name] = seed_tree[name]
            else:
                out[name] = jnp.zeros(sub.shape, sub.dtype)
        return out

    cache = merge(shapes["cache"], seeded["cache"])

    def model_step(cache, tokens):
        logits, mutated = decode_model.apply(
            {"params": params, "cache": cache}, tokens, enc_out,
            enc_mask=enc_mask, mutable=["cache"], method=T5.decode_step,
        )
        return mutated["cache"], logits[:, -1].astype(jnp.float32)

    sample = functools.partial(sample_logits, temperature=temperature,
                               top_k=top_k)
    start = jnp.full((b, 1), model.pad_id, jnp.int32)
    cache, last_logits = model_step(cache, start)
    rng, sub = jax.random.split(rng)
    tok = sample(last_logits, sub)
    done = jnp.zeros((b,), jnp.bool_)
    if eos_id is not None:
        done = tok == eos_id

    def step(carry, _):
        cache, tok, rng, done = carry
        cache, logits = model_step(cache, tok[:, None])
        rng, sub = jax.random.split(rng)
        nxt = sample(logits, sub)
        if eos_id is not None:
            nxt = jnp.where(done, model.pad_id, nxt)
            done = done | (nxt == eos_id)
        return (cache, nxt, rng, done), nxt

    (_, _, _, done), rest = jax.lax.scan(
        step, (cache, tok, rng, done), length=max_new_tokens - 1
    )
    new_tokens = jnp.concatenate(
        [tok[:, None], jnp.moveaxis(rest, 0, 1)], axis=1
    )
    tokens = jnp.concatenate([start, new_tokens], axis=1)
    if eos_id is None:
        lengths = jnp.full((b,), max_new_tokens, jnp.int32)
    else:
        is_eos = (new_tokens == eos_id).astype(jnp.int32)
        seen_before = jnp.cumsum(is_eos, axis=1) - is_eos
        lengths = jnp.sum((seen_before == 0).astype(jnp.int32), axis=1)
    return tokens, lengths
