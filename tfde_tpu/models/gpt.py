"""GPT-style causal language model — decoder-only transformer.

Rounds out the model families (reference: CNNs only, SURVEY.md §2a; driver
configs add ViT + BERT): the causal decoder exercises the attention paths
the other configs don't — causal masking in the reference kernel, causal
block-skipping in the Pallas flash kernel (ops/flash_attention.py), and
causal ring attention for long-context (ops/ring_attention.py) — all through
the same Encoder (models/transformer.py, pre-LN, the GPT-2 arrangement).

Weight tying (GPT-2 convention): LM head = embedding transpose via
`nn.Embed.attend`, same as models/bert.py's MLM decoder.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from tfde_tpu.models.transformer import Encoder
from tfde_tpu.parallel.axes import batch_axes, constrain


class GPT(nn.Module):
    """Decoder-only LM over [B, S] int token ids -> [B, S, vocab] logits."""

    vocab_size: int = 50257
    hidden_size: int = 768
    depth: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    max_position: int = 1024
    dropout_rate: float = 0.0
    dtype: jnp.dtype = jnp.bfloat16
    attn_impl: str = "auto"
    remat: Any = False  # False | True/'full' | 'dots' (transformer.remat_policy)
    fused_qkv: bool = False  # one-GEMM qkv projection (transformer.py)
    # > 0 swaps every `moe_every`-th block's MLP for a routed expert MLP
    # (models/moe.py) — train under ExpertParallelStrategy to shard experts
    num_experts: int = 0
    moe_every: int = 2
    experts_per_token: int = 2
    moe_capacity_factor: float = 1.25  # models/moe.py MoEMlp
    moe_normalize_topk: bool = True        # models/moe.py MoEMlp
    moe_shared_expert_dim: Optional[int] = None  # Qwen2-MoE shared expert
    router_z_loss_weight: float = 0.0  # ST-MoE stabilizer (models/moe.py)
    # autoregressive serving mode (inference/decode.py): KV caches in the
    # "cache" collection; positions continue from the cached prefix
    decode: bool = False
    # window-bounded rolling decode cache (transformer.MultiHeadAttention
    # rolling_cache) — set by _decode_clone(rolling=True) on paths that
    # never rewind the cache
    rolling_cache: bool = False
    # paged KV pool (transformer.MultiHeadAttention paged_blocks/kv_block)
    # — set by inference/paged._paged_clone under TFDE_PAGED_KV; None keeps
    # the dense per-row slabs
    paged_blocks: Optional[int] = None
    kv_block: int = 16
    # None (fp) | 'int8': quantized KV cache (transformer.MultiHeadAttention
    # kv_quant, TFDE_KV_QUANT) — int8 payload + per-(position, kv-head)
    # fp32 scale sidecars in every cache layout (dense slab / paged pool),
    # dequantized inside the attention program. Orthogonal to `quant`
    # (weights): either, both, or neither. Serving-only like the cache
    # itself; set by _decode_clone(kv_quant=...).
    kv_quant: Optional[str] = None
    ln_eps: float = 1e-6  # GPT-2 checkpoints use 1e-5 (models/convert.py)
    # 'learned' = GPT-2 absolute wpe table; 'rope' = rotary q/k rotation
    # (ops/rotary.py) — no position table, relative-position attention,
    # better length extrapolation
    position: str = "learned"
    rope_theta: float = 10_000.0
    # RoPE frequency rescaling (ops/rotary.scale_frequencies tuple):
    # ('linear', factor) | ('llama3', factor, low, high, orig_max) — the
    # Llama-3.1+ long-context checkpoints carry this
    rope_scaling: Optional[Any] = None
    # partial rotary (the Phi family): only the first rope_dim features of
    # each head rotate; None = full head_dim
    rope_dim: Optional[int] = None
    # grouped-query attention: KV heads per layer (None = num_heads); the
    # KV cache shrinks by num_heads/num_kv_heads — the serving memory knob
    num_kv_heads: Optional[int] = None
    norm: str = "layer"      # 'layer' | 'rms' (LLaMA)
    mlp_act: str = "gelu"    # 'gelu' | 'relu' (OPT) | 'swiglu' (LLaMA) |
    #                          'geglu' (Gemma)
    use_bias: bool = True    # False: LLaMA bias-free projections
    # Qwen2: biased q/k/v projections beside bias-free out/MLP
    qkv_bias: bool = False
    # Qwen3: per-head RMSNorm on q and k before rotary (transformer.py)
    qk_norm: bool = False
    # 'pre' (GPT-2/LLaMA) | 'parallel' (Phi: one LN per block, attention
    # and MLP side by side on it) | 'parallel2' (GPT-NeoX/Pythia: parallel
    # residual with separate attention/MLP LayerNorms)
    norm_style: str = "pre"
    # Phi: the untied lm_head carries a bias
    head_bias: bool = False
    # token embeddings are multiplied by this after lookup (Gemma:
    # sqrt(hidden_size)); None = no scaling (every other family)
    embed_scale: Optional[float] = None
    # per-head width; None = hidden_size // num_heads. Gemma-7b-style
    # checkpoints decouple it (attention width heads*head_dim != hidden;
    # the out projection maps back to hidden either way)
    head_dim: Optional[int] = None
    # True (GPT-2): LM head = wte^T via Embed.attend; False (LLaMA):
    # separate bias-free lm_head Dense
    tie_embeddings: bool = True
    # None (fp) | 'int8': W8A8 serving twin (ops/quant.py) — block
    # projections, the embedding/tied head, and the untied lm_head all go
    # int8; wpe and norms stay fp32. Build params with quantize_model.
    quant: Optional[str] = None
    # sliding-window attention (the Mistral family): each position attends
    # the last `sliding_window` positions. The flash forward AND backward
    # skip out-of-band tiles (compute and DMA drop to O(S * window) for
    # the full fwd+bwd step — the backward scans only the statically
    # in-band tile pairs). The decode cache mask carries the band.
    # None = full causal.
    sliding_window: Optional[int] = None
    # 'all' | 'alternate' (Gemma-2: even blocks windowed, odd blocks full)
    sliding_window_pattern: str = "all"
    # Gemma-2 attention deltas (transformer.MultiHeadAttention)
    attn_scale: Optional[float] = None
    attn_logit_cap: Optional[float] = None
    # Gemma-2 final logit softcapping: logits = cap * tanh(logits / cap)
    final_logit_cap: Optional[float] = None

    @nn.compact
    def __call__(self, input_ids: jax.Array, train: bool = False,
                 segment_ids: Optional[jax.Array] = None) -> jax.Array:
        """segment_ids [B, S]: sequence-packing support (data/packing.py)
        — tokens attend only within their own segment (block-diagonal
        causal mask; padding is segment 0 and attends only other padding,
        keeping its softmax rows finite). Positions stay GLOBAL within
        the packed row: exact for rope (attention depends only on
        relative position, and cross-segment pairs are masked), offset
        but consistent for learned positions. Training-side only —
        decode mode refuses it."""
        if self.quant is not None and train:
            raise ValueError(
                "quant='int8' is a serving-only mode (round() has zero "
                "gradient) — train the fp model, then quantize_model it"
            )
        seg_mask = None
        if segment_ids is not None:
            if self.decode:
                raise NotImplementedError(
                    "segment_ids (sequence packing) is a training-side "
                    "capability; the decode cache has no segment plane"
                )
            if self.sliding_window is not None:
                raise NotImplementedError(
                    "segment_ids does not compose with sliding_window "
                    "yet (the band would need per-segment offsets)"
                )
            from tfde_tpu.ops.attention import _seq_parallel_active

            if _seq_parallel_active():
                # auto-dispatch would pick the seq ring, which takes
                # key-padding masks only — fail HERE with the cause named
                # instead of a mask-shape error deep inside the ring
                raise NotImplementedError(
                    "segment_ids (sequence packing) does not compose "
                    "with sequence parallelism — the ring would need a "
                    "sharded segment plane; train packed batches under "
                    "dp/fsdp/tp"
                )
            seg = segment_ids.astype(jnp.int32)
            # [B, 1, S, S]; the causal triangle composes inside attention
            seg_mask = (seg[:, None, :, None] == seg[:, None, None, :])
        b = batch_axes()
        seq = input_ids.shape[1]
        if self.quant is not None:
            from tfde_tpu.ops.quant import QuantEmbed

            wte = QuantEmbed(self.vocab_size, self.hidden_size,
                             dtype=self.dtype, name="wte")
        else:
            wte = nn.Embed(
                self.vocab_size, self.hidden_size, dtype=self.dtype,
                param_dtype=jnp.float32, name="wte",
            )
        if self.position not in ("learned", "rope"):
            raise ValueError(
                f"position must be 'learned' or 'rope', got {self.position!r}"
            )
        x = wte(input_ids)
        if self.embed_scale is not None:
            x = x * jnp.asarray(self.embed_scale, self.dtype)
        if self.position == "learned":
            wpe = nn.Embed(
                self.max_position, self.hidden_size, dtype=self.dtype,
                param_dtype=jnp.float32, name="wpe",
            )
            positions = jnp.arange(seq, dtype=jnp.int32)
            if self.decode:
                # position offset rides the cache like the K/V do: a decode
                # step at cache position t embeds wpe[t], matching the full-
                # sequence forward exactly. Check BEFORE self.variable
                # creates it: a call with no pre-existing cache is position 0
                # and must not advance (the attention layers' fresh
                # cache_index stays 0 the same way).
                is_filled = self.has_variable("cache", "position_index")
                pos_index = self.variable("cache", "position_index",
                                          lambda: jnp.zeros((), jnp.int32))
                if is_filled and not self.is_initializing():
                    # scalar index -> positions [S]; per-row [B] index (the
                    # batched-speculation rewind, inference/speculative.py)
                    # broadcasts to [B, S]
                    positions = pos_index.value[..., None] + positions
                    pos_index.value = pos_index.value + seq
            x = x + wpe(positions if positions.ndim == 2
                        else positions[None, :])
        x = constrain(x, b, "seq")
        if self.dropout_rate > 0.0:
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = Encoder(
            depth=self.depth,
            num_heads=self.num_heads,
            head_dim=self.head_dim or self.hidden_size // self.num_heads,
            mlp_dim=self.mlp_dim,
            dtype=self.dtype,
            dropout_rate=self.dropout_rate,
            attn_impl=self.attn_impl,
            causal=True,
            decode=self.decode,
            rope=self.position == "rope",
            rope_theta=self.rope_theta,
            rope_scaling=(tuple(self.rope_scaling)
                          if self.rope_scaling is not None else None),
            rope_dim=self.rope_dim,
            num_kv_heads=self.num_kv_heads,
            fused_qkv=self.fused_qkv,
            quant=self.quant,
            window=self.sliding_window,
            window_pattern=self.sliding_window_pattern,
            rolling_cache=self.rolling_cache,
            paged_blocks=self.paged_blocks,
            kv_block=self.kv_block,
            kv_quant=self.kv_quant,
            attn_scale=self.attn_scale,
            attn_logit_cap=self.attn_logit_cap,
            norm=self.norm,
            norm_style=self.norm_style,
            mlp_act=self.mlp_act,
            use_bias=self.use_bias,
            qkv_bias=self.qkv_bias,
            qk_norm=self.qk_norm,
            ln_eps=self.ln_eps,
            remat=self.remat,
            num_experts=self.num_experts,
            moe_every=self.moe_every,
            experts_per_token=self.experts_per_token,
            moe_capacity_factor=self.moe_capacity_factor,
            moe_normalize_topk=self.moe_normalize_topk,
            moe_shared_expert_dim=self.moe_shared_expert_dim,
            router_z_loss_weight=self.router_z_loss_weight,
            name="decoder",
        )(x, mask=seg_mask, train=train)
        if self.tie_embeddings:
            if self.head_bias:
                raise ValueError(
                    "head_bias=True requires tie_embeddings=False (the "
                    "tied head is wte^T via Embed.attend, which carries "
                    "no bias) — a silently dropped bias would change the "
                    "architecture"
                )
            logits = wte.attend(x.astype(self.dtype)).astype(jnp.float32)
        elif self.quant is not None:
            from tfde_tpu.ops.quant import QuantDenseGeneral

            logits = QuantDenseGeneral(
                self.vocab_size, use_bias=self.head_bias, dtype=self.dtype,
                name="lm_head",
            )(x.astype(self.dtype)).astype(jnp.float32)
        else:
            logits = nn.Dense(
                self.vocab_size, use_bias=self.head_bias, dtype=self.dtype,
                param_dtype=jnp.float32, name="lm_head",
            )(x.astype(self.dtype)).astype(jnp.float32)
        if self.final_logit_cap is not None:
            logits = self.final_logit_cap * jnp.tanh(
                logits / self.final_logit_cap
            )
        return constrain(logits, b, "seq", "tensor")


GPT2Small = functools.partial(
    GPT, hidden_size=768, depth=12, num_heads=12, mlp_dim=3072
)
GPT2Medium = functools.partial(
    GPT, hidden_size=1024, depth=24, num_heads=16, mlp_dim=4096,
)


def gpt_tiny_test(**kw) -> GPT:
    """CI config for the 8-device CPU mesh (SURVEY.md §4)."""
    return GPT(
        vocab_size=97, hidden_size=32, depth=2, num_heads=4, mlp_dim=64,
        max_position=64, dtype=jnp.float32, **kw,
    )


def next_token_loss(state, params, batch, rng):
    """(loss, metrics) for make_custom_train_step: shifted CE over all
    positions (predict token t+1 from prefix <= t).

    Applies with mutable=["losses"] so values the model sows there — the
    MoE load-balance aux and router z-loss (models/moe.py) — join the
    objective, matching the default classification path (training/step.py
    `_forward`). Without this an MoE GPT would train with unbalanced
    routing: sow() into an immutable collection is a silent no-op. Each
    sown loss is also surfaced as a metric (summed over layers) so
    telemetry and the bench can watch router balance.
    """
    from tfde_tpu.ops.losses import masked_lm_loss

    (tokens,) = batch if isinstance(batch, tuple) else (batch,)
    try:
        logits, mutated = state.apply_fn(
            {"params": params}, tokens, train=True, rngs={"dropout": rng},
            mutable=["losses"],
        )
    except TypeError as e:
        # custom apply_fns without flax's kwarg (PipelinedLM.apply) — no
        # sown-loss collections to collect there. Match the exact
        # unsupported-kwarg signature error: a looser match would silently
        # rerun (and drop sown losses for) models whose own TypeError
        # merely mentions mutable
        if "unexpected keyword argument 'mutable'" not in str(e):
            raise
        logits = state.apply_fn(
            {"params": params}, tokens, train=True, rngs={"dropout": rng}
        )
        mutated = {}
    # align: logits[:, :-1] predict tokens[:, 1:]
    labels = tokens[:, 1:].astype(jnp.int32)
    loss, acc = masked_lm_loss(logits[:, :-1], labels)
    metrics = {"next_token_accuracy": acc}
    from tfde_tpu.training.step import sown_losses_by_name

    for name, total in sown_losses_by_name(mutated.get("losses", {})).items():
        loss = loss + total
        metrics[name] = total
    return loss, metrics
