"""Vision Transformer — the FSDP scale-up config.

BASELINE.json configs[3]: "ImageNet ViT-B/16 (pjit FSDP over ICI mesh)". The
reference has no transformer (SURVEY.md §5); this is the driver-mandated
scale config, built on the shared encoder (models/transformer.py) so the
tensor/sequence-parallel machinery applies to it unchanged.

TPU-first choices:
- Patch embedding as a strided Conv — XLA lowers it to one big MXU matmul
  over [patches, 3*16*16].
- bf16 compute / fp32 params, fp32 pooling+head (see models/transformer.py).
- CLS-token head by default (parity with the canonical ViT-B/16 recipe and
  its 86.6M param count); `pool='gap'` gives the token-free mean-pool
  variant.
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from tfde_tpu.models.transformer import Encoder
from tfde_tpu.parallel.axes import batch_axes, constrain


class ViT(nn.Module):
    """Vision Transformer classifier over [B, H, W, C] images."""

    num_classes: int = 1000
    patch_size: int = 16
    embed_dim: int = 768
    depth: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    dropout_rate: float = 0.0
    dtype: jnp.dtype = jnp.bfloat16
    pool: str = "cls"  # 'cls' | 'gap'
    attn_impl: str = "auto"
    remat: Any = False  # False | True/'full' | 'dots' (transformer.remat_policy)
    fused_qkv: bool = False  # one-GEMM qkv projection (transformer.py)

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        b = batch_axes()
        p = self.patch_size
        x = nn.Conv(
            self.embed_dim,
            kernel_size=(p, p),
            strides=(p, p),
            padding="VALID",
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name="patch_embed",
        )(x.astype(self.dtype))
        bsz, gh, gw, c = x.shape
        x = x.reshape(bsz, gh * gw, c)
        if self.pool == "cls":
            cls = self.param(
                "cls_token", nn.initializers.zeros, (1, 1, self.embed_dim),
                jnp.float32,
            )
            x = jnp.concatenate(
                [jnp.broadcast_to(cls, (bsz, 1, c)).astype(self.dtype), x], axis=1
            )
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(stddev=0.02),
            (1, x.shape[1], self.embed_dim),
            jnp.float32,
        )
        x = x + pos.astype(self.dtype)
        x = constrain(x, b, "seq")
        if self.dropout_rate > 0.0:
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = Encoder(
            depth=self.depth,
            num_heads=self.num_heads,
            head_dim=self.embed_dim // self.num_heads,
            mlp_dim=self.mlp_dim,
            dtype=self.dtype,
            dropout_rate=self.dropout_rate,
            attn_impl=self.attn_impl,
            fused_qkv=self.fused_qkv,
            remat=self.remat,
            name="encoder",
        )(x, train=train)
        if self.pool == "cls":
            x = x[:, 0]
        else:
            x = jnp.mean(x, axis=1)
        # Head in fp32: the logits path is precision-sensitive.
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)


ViT_B16 = functools.partial(
    ViT, patch_size=16, embed_dim=768, depth=12, num_heads=12, mlp_dim=3072
)
ViT_L16 = functools.partial(
    ViT, patch_size=16, embed_dim=1024, depth=24, num_heads=16, mlp_dim=4096
)
ViT_S16 = functools.partial(
    ViT, patch_size=16, embed_dim=384, depth=12, num_heads=6, mlp_dim=1536
)


def vit_tiny_test(num_classes: int = 10, **kw) -> ViT:
    """Small config for CI on the 8-device CPU mesh (SURVEY.md §4)."""
    return ViT(
        num_classes=num_classes, patch_size=4, embed_dim=32, depth=2,
        num_heads=4, mlp_dim=64, dtype=jnp.float32, **kw,
    )
