"""The reference's two MNIST models as Flax modules.

- `PlainCNN` — distributed_with_keras.py:32-44 (and the dead estimator
  model_fn, tf2_mnist_distributed.py:66-72): Conv2D(32,3,valid,relu) ->
  MaxPool(2) -> Flatten -> Dense(64,relu) -> Dense(10 logits).
- `BatchNormCNN` — mnist_keras_distributed.py:67-120 (duplicate
  tf2_mnist_distributed.py:93-146): Reshape 784->28x28x1; three
  Conv(no-bias)->BN(center,no-scale)->ReLU blocks with filters 6/12/24,
  kernels 3/6/6, strides 1/2/2, padding 'same'; Flatten; Dense(200,
  no-bias)->BN->ReLU->Dropout(0.5); Dense(10).

Deviation from the reference, on purpose: the Keras BN-CNN ends in
`softmax` and feeds probabilities to the loss (mnist_keras:108,114). We return
*logits* and take softmax only at the serving boundary (the export layer) —
numerically safer and one fused op cheaper; the observable serving signature
([N,784] -> 10 probabilities, SURVEY.md §3.4) is unchanged.

BatchNorm semantics under data parallelism: under `jit` over a sharded batch
axis XLA computes *global-batch* statistics (sync-BN). TF MirroredStrategy
instead normalizes with *per-replica local* statistics (SURVEY.md §7). The
idiomatic sync-BN is the default here.
"""

from __future__ import annotations

from typing import Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


class PlainCNN(nn.Module):
    """distributed_with_keras.py:32-44. Input [N,28,28,1] float; returns logits."""

    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        if x.ndim == 2:  # accept flat [N, 784] too
            x = x.reshape(-1, 28, 28, 1)
        x = x.astype(self.dtype)
        # Keras Conv2D default padding is VALID (dwk:34).
        x = nn.Conv(32, (3, 3), padding="VALID", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape(x.shape[0], -1)
        x = nn.Dense(64, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


class BatchNormCNN(nn.Module):
    """mnist_keras_distributed.py:67-120. Input [N,784] or [N,28,28,1]; logits.

    BN matches Keras `BatchNormalization(scale=False, center=True)`
    (mnist_keras:86): bias (beta) yes, gamma no, momentum 0.99, eps 1e-3.
    """

    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32
    dropout_rate: float = 0.5
    features: Sequence[int] = (6, 12, 24)
    kernels: Sequence[int] = (3, 6, 6)
    strides: Sequence[int] = (1, 2, 2)

    def _bn(self, train: bool) -> Callable[[jax.Array], jax.Array]:
        return nn.BatchNorm(
            use_running_average=not train,
            use_scale=False,
            use_bias=True,
            momentum=0.99,
            epsilon=1e-3,
            dtype=self.dtype,
        )

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        x = x.reshape(-1, 28, 28, 1).astype(self.dtype)  # Reshape (mnist_keras:81)
        for f, k, s in zip(self.features, self.kernels, self.strides):
            x = nn.Conv(
                f, (k, k), strides=(s, s), padding="SAME", use_bias=False,
                dtype=self.dtype,
            )(x)
            x = self._bn(train)(x)
            x = nn.relu(x)
        x = x.reshape(x.shape[0], -1)  # Flatten (mnist_keras:102)
        x = nn.Dense(200, use_bias=False, dtype=self.dtype)(x)
        x = self._bn(train)(x)
        x = nn.relu(x)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x
