"""Model zoo: the reference's two MNIST CNNs; the BASELINE.json scale configs
(ResNet-50, ViT-B/16, BERT-base) are added per SURVEY.md §7 layer 7."""

from tfde_tpu.models.cnn import PlainCNN, BatchNormCNN  # noqa: F401
from tfde_tpu.models.resnet import (  # noqa: F401
    ResNet,
    ResNet18,
    ResNet50,
    ResNet101,
    resnet50_cifar,
)
from tfde_tpu.models.transformer import (  # noqa: F401
    Encoder,
    MultiHeadAttention,
    TransformerBlock,
)
from tfde_tpu.models.vit import ViT, ViT_B16, ViT_L16, ViT_S16, vit_tiny_test  # noqa: F401
from tfde_tpu.models.bert import Bert, BertBase, BertLarge, bert_tiny_test  # noqa: F401
from tfde_tpu.models.gpt import GPT, GPT2Small, GPT2Medium, gpt_tiny_test  # noqa: F401
from tfde_tpu.models.moe import MoEMlp  # noqa: F401
from tfde_tpu.models.pipelined import PipelinedLM, pipelined_tiny_test  # noqa: F401
from tfde_tpu.models.t5 import (  # noqa: F401
    T5,
    T5Base,
    T5Small,
    t5_generate,
    t5_seq2seq_loss,
    t5_tiny_test,
)
