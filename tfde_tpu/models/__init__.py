"""Model zoo: the reference's two MNIST CNNs; the BASELINE.json scale configs
(ResNet-50, ViT-B/16, BERT-base) are added per SURVEY.md §7 layer 7."""

from tfde_tpu.models.cnn import PlainCNN, BatchNormCNN  # noqa: F401
from tfde_tpu.models.resnet import (  # noqa: F401
    ResNet,
    ResNet18,
    ResNet50,
    ResNet101,
    resnet50_cifar,
)
