"""Mixture-of-Experts MLP with expert parallelism over the 'expert' mesh axis.

Scale-up scope beyond the reference (SURVEY.md §2c: "Expert parallel: absent")
— the framework-level capability that rounds out the parallelism families the
mesh already names (runtime/mesh.AXIS_ORDER).

TPU-first design — the GShard/Switch einsum formulation, not a gather/scatter
one: dispatch and combine are one-hot einsums, so the whole layer is four MXU
matmuls over static shapes (no dynamic gathers, nothing data-dependent in the
traced graph). Expert weights are [E, ...] arrays sharded over 'expert'
(ExpertParallelStrategy, parallel/strategies.py); the dispatch einsum crosses
the token (data-sharded) and expert (expert-sharded) dims, and the XLA SPMD
partitioner lowers that boundary to the all-to-all-style collectives over ICI.

Capacity: each expert processes at most C = ceil(k * tokens / E * cf) tokens;
overflow tokens are dropped by the dispatch mask (their gate mass is simply
missing from the combine) — the residual connection around the MLP carries
them through, the standard Switch behavior.

Load-balance auxiliary loss (Switch eq. 4): E * sum_e f_e * P_e, sown into
the 'losses' collection; training/step.py adds every sown loss to the
objective automatically when the model mutates that collection.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from tfde_tpu.parallel.axes import batch_axes, constrain


class MoEMlp(nn.Module):
    """Top-k routed expert MLP: fc1 -> gelu -> fc2 per expert."""

    num_experts: int
    mlp_dim: int
    experts_per_token: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    dropout_rate: float = 0.0
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        import math

        b_axes = batch_axes()
        bsz, seq, d = x.shape
        e, k = self.num_experts, self.experts_per_token
        n = bsz * seq
        capacity = max(1, math.ceil(k * n / e * self.capacity_factor))

        tokens = x.reshape(n, d)
        # router in fp32 — routing decisions are precision-sensitive
        logits = nn.Dense(
            e, use_bias=False, dtype=jnp.float32, param_dtype=jnp.float32,
            name="router",
        )(tokens.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)  # [n, e]

        gate_vals, gate_idx = jax.lax.top_k(probs, k)  # [n, k]
        gate_vals = gate_vals / jnp.maximum(
            jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9
        )

        # position of each (token, choice) within its expert's capacity:
        # cumsum over the flattened (choice-major) token stream
        choice_mask = jax.nn.one_hot(gate_idx, e, dtype=jnp.float32)  # [n,k,e]
        flat_mask = choice_mask.transpose(1, 0, 2).reshape(k * n, e)
        pos = jnp.cumsum(flat_mask, axis=0) * flat_mask - flat_mask  # 0-based
        within = pos < capacity
        flat_mask = flat_mask * within
        pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), capacity) * flat_mask[..., None]
        # dispatch/combine [n, e, c]
        pos_oh = pos_oh.reshape(k, n, e, capacity)
        gates = gate_vals.transpose(1, 0)[..., None, None]  # [k, n, 1, 1]
        dispatch = jnp.sum(pos_oh, axis=0)
        combine = jnp.sum(pos_oh * gates, axis=0)

        # Switch load-balance aux loss: fraction routed x mean prob, top-1
        top1 = jax.nn.one_hot(gate_idx[:, 0], e, dtype=jnp.float32)
        f = jnp.mean(top1, axis=0)
        p = jnp.mean(probs, axis=0)
        aux = self.aux_loss_weight * e * jnp.sum(f * p)
        self.sow("losses", "moe_aux", aux)  # default tuple-append reduce

        w1 = self.param(
            "experts_fc1",
            nn.initializers.lecun_normal(batch_axis=0),
            (e, d, self.mlp_dim), jnp.float32,
        )
        b1 = self.param("experts_b1", nn.initializers.zeros,
                        (e, 1, self.mlp_dim), jnp.float32)
        w2 = self.param(
            "experts_fc2",
            nn.initializers.lecun_normal(batch_axis=0),
            (e, self.mlp_dim, d), jnp.float32,
        )
        b2 = self.param("experts_b2", nn.initializers.zeros,
                        (e, 1, d), jnp.float32)

        xin = jnp.einsum(
            "nec,nd->ecd", dispatch.astype(self.dtype), tokens.astype(self.dtype),
            preferred_element_type=jnp.float32,
        ).astype(self.dtype)
        xin = constrain(xin, "expert")
        h = jnp.einsum(
            "ecd,edf->ecf", xin, w1.astype(self.dtype),
            preferred_element_type=jnp.float32,
        ) + b1.astype(jnp.float32)
        h = nn.gelu(h.astype(self.dtype))
        h = constrain(h, "expert")
        out_e = jnp.einsum(
            "ecf,efd->ecd", h, w2.astype(self.dtype),
            preferred_element_type=jnp.float32,
        ) + b2.astype(jnp.float32)
        out_e = constrain(out_e.astype(self.dtype), "expert")
        y = jnp.einsum(
            "nec,ecd->nd", combine.astype(self.dtype), out_e,
            preferred_element_type=jnp.float32,
        )
        y = y.astype(x.dtype).reshape(bsz, seq, d)
        if self.dropout_rate > 0.0:
            y = nn.Dropout(self.dropout_rate, deterministic=not train)(y)
        return constrain(y, b_axes, "seq")
