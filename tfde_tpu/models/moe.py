"""Mixture-of-Experts MLP with expert parallelism over the 'expert' mesh axis.

Scale-up scope beyond the reference (SURVEY.md §2c: "Expert parallel: absent")
— the framework-level capability that rounds out the parallelism families the
mesh already names (runtime/mesh.AXIS_ORDER).

TPU-first design — the GShard/Switch einsum formulation, not a gather/scatter
one: dispatch and combine are one-hot einsums, so the whole layer is four MXU
matmuls over static shapes (no dynamic gathers, nothing data-dependent in the
traced graph). Expert weights are [E, ...] arrays sharded over 'expert'
(ExpertParallelStrategy, parallel/strategies.py); the dispatch einsum crosses
the token (data-sharded) and expert (expert-sharded) dims, and the XLA SPMD
partitioner lowers that boundary to the all-to-all-style collectives over ICI.

Capacity is **per group** (the GShard formulation): tokens reshape to
[G, n/G, d] groups aligned with the data sharding (default: one group per
sequence, so the group dim is the batch dim), and each expert processes at
most C = ceil(k * (n/G) / E * cf) tokens *per group*. The dispatch one-hot is
[G, n/G, E, C] — its size is linear in the token count at fixed group size,
where the round-1/2 global formulation ([n, E, C] with C ∝ n) was quadratic
(tens of GB at BERT-base scale; VERDICT r2 "weak" #4). Overflow tokens are
dropped by the dispatch mask (their gate mass is simply missing from the
combine) — the residual connection around the MLP carries them through, the
standard Switch behavior.

Load-balance auxiliary loss (Switch eq. 4): E * sum_e f_e * P_e, sown into
the 'losses' collection; training/step.py adds every sown loss to the
objective automatically when the model mutates that collection.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from tfde_tpu.parallel.axes import batch_axes, constrain


def group_capacity(tokens_per_group: int, num_experts: int,
                   experts_per_token: int, capacity_factor: float) -> int:
    """Per-group expert capacity C = ceil(k * m / E * cf) — linear in the
    group's token count m, never in the global token count."""
    import math

    return max(1, math.ceil(
        experts_per_token * tokens_per_group / num_experts * capacity_factor
    ))


def dispatch_shape(batch: int, seq: int, num_experts: int,
                   experts_per_token: int = 2, capacity_factor: float = 1.25,
                   num_groups: Optional[int] = None) -> tuple:
    """The [G, m, E, C] dispatch-tensor shape MoEMlp will build — exposed so
    capacity scaling is testable without tracing the layer."""
    n = batch * seq
    g = num_groups or batch
    if n % g:
        raise ValueError(f"{n} tokens not divisible into {g} groups")
    m = n // g
    c = group_capacity(m, num_experts, experts_per_token, capacity_factor)
    return (g, m, num_experts, c)


class MoEMlp(nn.Module):
    """Top-k routed expert MLP: fc1 -> gelu -> fc2 per expert.

    num_groups: dispatch groups (default: the batch dim, one group per
    sequence) — groups route independently with per-group capacity, and the
    group dim carries the data sharding.
    """

    num_experts: int
    mlp_dim: int
    experts_per_token: int = 2
    capacity_factor: float = 1.25
    # 'gelu' (Switch/GShard) | 'swiglu' (Mixtral: per-expert gated-silu,
    # bias-free — a parallel experts_gate projection beside the up
    # projection, the expert-wise analog of transformer.Mlp's swiglu)
    act: str = "gelu"
    use_bias: bool = True
    # False (Qwen2-MoE): combine with the RAW softmax probabilities of the
    # top-k experts instead of renormalizing them to sum to 1 (the
    # Switch/Mixtral convention)
    normalize_topk: bool = True
    # Qwen2-MoE shared expert: a DENSE bias-free swiglu MLP of this width
    # runs on every token beside the routed experts, its output scaled by
    # a learned sigmoid gate — replicated weights (no expert axis)
    shared_expert_dim: Optional[int] = None
    aux_loss_weight: float = 0.01
    # router z-loss (ST-MoE): penalizes mean(logsumexp(router logits)^2),
    # keeping logit magnitudes bounded so fp32 routing stays stable over
    # long runs. 0 = off (the Switch default); 1e-3 is the ST-MoE setting.
    router_z_loss_weight: float = 0.0
    dropout_rate: float = 0.0
    dtype: jnp.dtype = jnp.bfloat16
    num_groups: Optional[int] = None

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        b_axes = batch_axes()
        bsz, seq, d = x.shape
        e, k = self.num_experts, self.experts_per_token
        n = bsz * seq
        g = self.num_groups or bsz
        if n % g:
            raise ValueError(f"{n} tokens not divisible into {g} groups")
        m = n // g
        capacity = group_capacity(m, e, k, self.capacity_factor)

        # [G, m, d] token groups; with the default g=bsz the group dim IS the
        # batch dim, so groups inherit the data sharding unchanged.
        tokens = x.reshape(g, m, d)
        # router in fp32 — routing decisions are precision-sensitive
        logits = nn.Dense(
            e, use_bias=False, dtype=jnp.float32, param_dtype=jnp.float32,
            name="router",
        )(tokens.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)  # [g, m, e]

        gate_vals, gate_idx = jax.lax.top_k(probs, k)  # [g, m, k]
        if self.normalize_topk:
            gate_vals = gate_vals / jnp.maximum(
                jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9
            )

        # position of each (token, choice) within its expert's per-group
        # capacity: cumsum over the group's choice-major token stream
        choice_mask = jax.nn.one_hot(gate_idx, e, dtype=jnp.float32)  # [g,m,k,e]
        flat_mask = choice_mask.transpose(0, 2, 1, 3).reshape(g, k * m, e)
        pos = jnp.cumsum(flat_mask, axis=1) * flat_mask - flat_mask  # 0-based
        within = pos < capacity
        flat_mask = flat_mask * within
        pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), capacity) * flat_mask[..., None]
        # dispatch/combine [g, m, e, c] — size linear in tokens at fixed m
        pos_oh = pos_oh.reshape(g, k, m, e, capacity)
        gates = gate_vals.transpose(0, 2, 1)[..., None, None]  # [g, k, m, 1, 1]
        dispatch = jnp.sum(pos_oh, axis=1)
        combine = jnp.sum(pos_oh * gates, axis=1)

        # Switch load-balance aux loss: fraction routed x mean prob, top-1,
        # averaged over ALL tokens (global, not per-group)
        top1 = jax.nn.one_hot(gate_idx[..., 0], e, dtype=jnp.float32)
        f = jnp.mean(top1, axis=(0, 1))
        p = jnp.mean(probs, axis=(0, 1))
        aux = self.aux_loss_weight * e * jnp.sum(f * p)
        self.sow("losses", "moe_aux", aux)  # default tuple-append reduce
        if self.router_z_loss_weight > 0.0:
            z = jax.nn.logsumexp(logits, axis=-1)  # [g, m]
            self.sow("losses", "moe_z",
                     self.router_z_loss_weight * jnp.mean(z * z))

        if self.act not in ("gelu", "swiglu"):
            raise ValueError(
                f"act must be 'gelu' or 'swiglu', got {self.act!r}"
            )
        w1 = self.param(
            "experts_fc1",
            nn.initializers.lecun_normal(batch_axis=0),
            (e, d, self.mlp_dim), jnp.float32,
        )
        w2 = self.param(
            "experts_fc2",
            nn.initializers.lecun_normal(batch_axis=0),
            (e, self.mlp_dim, d), jnp.float32,
        )
        if self.use_bias:
            b1 = self.param("experts_b1", nn.initializers.zeros,
                            (e, 1, self.mlp_dim), jnp.float32)
            b2 = self.param("experts_b2", nn.initializers.zeros,
                            (e, 1, d), jnp.float32)
        if self.act == "swiglu":
            wg = self.param(
                "experts_gate",
                nn.initializers.lecun_normal(batch_axis=0),
                (e, d, self.mlp_dim), jnp.float32,
            )

        # [e, g, c, d]: expert-major so the expert shard is dim 0, the
        # (data-sharded) group dim rides along — the token<->expert layout
        # crossing below is what XLA lowers to the all-to-all over ICI.
        xin = jnp.einsum(
            "gmec,gmd->egcd", dispatch.astype(self.dtype), tokens.astype(self.dtype),
            preferred_element_type=jnp.float32,
        ).astype(self.dtype)
        xin = constrain(xin, "expert", b_axes)

        def expert_dense(w, rhs):
            return jnp.einsum(
                "egcd,edf->egcf", rhs, w.astype(self.dtype),
                preferred_element_type=jnp.float32,
            )

        h = expert_dense(w1, xin)
        if self.use_bias:
            h = h + b1.astype(jnp.float32)[:, None]
        if self.act == "swiglu":
            # gated-silu (Mixtral): gate and up are both expert-sharded on
            # dim 0, so the product crosses no shard boundary
            gate = expert_dense(wg, xin)
            h = nn.silu(gate.astype(self.dtype)) * h.astype(self.dtype)
        else:
            h = nn.gelu(h.astype(self.dtype))
        h = constrain(h, "expert", b_axes)
        out_e = jnp.einsum(
            "egcf,efd->egcd", h, w2.astype(self.dtype),
            preferred_element_type=jnp.float32,
        )
        if self.use_bias:
            out_e = out_e + b2.astype(jnp.float32)[:, None]
        out_e = constrain(out_e.astype(self.dtype), "expert", b_axes)
        y = jnp.einsum(
            "gmec,egcd->gmd", combine.astype(self.dtype), out_e,
            preferred_element_type=jnp.float32,
        )
        y = y.astype(x.dtype).reshape(bsz, seq, d)
        if self.shared_expert_dim is not None:
            if self.act != "swiglu" or self.use_bias:
                raise NotImplementedError(
                    "shared_expert_dim is the Qwen2-MoE arrangement: "
                    "bias-free swiglu experts only"
                )
            dense = lambda feats, name: nn.Dense(
                feats, use_bias=False, dtype=self.dtype,
                param_dtype=jnp.float32, name=name,
            )
            sh = nn.silu(dense(self.shared_expert_dim, "shared_gate")(x)) \
                * dense(self.shared_expert_dim, "shared_fc1")(x)
            sh = dense(d, "shared_fc2")(sh)
            # scalar sigmoid gate per token (fp32: a saturating gate is
            # precision-sensitive)
            gate = jax.nn.sigmoid(
                nn.Dense(1, use_bias=False, dtype=jnp.float32,
                         param_dtype=jnp.float32,
                         name="shared_expert_gate")(
                    x.astype(jnp.float32)
                )
            )
            y = y + (gate * sh.astype(jnp.float32)).astype(x.dtype)
        if self.dropout_rate > 0.0:
            y = nn.Dropout(self.dropout_rate, deterministic=not train)(y)
        return constrain(y, b_axes, "seq")
