"""Pretrained-checkpoint conversion: HuggingFace transformers -> this
framework's param trees.

The migration story for users arriving with trained models: GPT-2 and BERT
checkpoints in the `transformers` torch format load directly into
models/gpt.GPT and models/bert.Bert, verified by logit matching
(tests/test_convert.py builds tiny HF models and asserts our forward
reproduces theirs). Conversion is pure reshaping on host numpy:

- GPT-2 stores fused-projection Conv1D weights as [in, out] — no
  transpose; the [H, 3H] c_attn splits into q/k/v and reshapes to the
  Megatron-friendly [in, heads, head_dim] kernels our DenseGeneral uses.
- BERT uses torch.nn.Linear ([out, in]) — transposed, then reshaped the
  same way.
- LM heads are weight-tied in both (our `Embed.attend` convention), so no
  separate head tensor exists or is needed; BERT's prediction bias maps to
  `mlm_bias`.

Known approximation: our Mlp uses the tanh-approximate gelu (flax
default), which IS GPT-2's `gelu_new` exactly, but differs from BERT's
exact `gelu` by ~1e-3 in activations — far below bf16 noise on TPU, and
the logit-match test bounds it.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def _np(t) -> np.ndarray:
    return t.detach().cpu().numpy().astype(np.float32)


def gpt2_from_hf(hf_model, dtype=None) -> Tuple[object, dict]:
    """(GPT, params) from a transformers GPT2LMHeadModel (or GPT2Model).

    `dtype` overrides the activation dtype (default: the model family's
    bf16; pass jnp.float32 for exact-match verification on CPU)."""
    import jax.numpy as jnp

    from tfde_tpu.models.gpt import GPT

    cfg = hf_model.config
    heads = cfg.n_head
    hidden = cfg.n_embd
    hd = hidden // heads
    mlp_dim = cfg.n_inner if cfg.n_inner is not None else 4 * hidden
    model = GPT(
        vocab_size=cfg.vocab_size,
        hidden_size=hidden,
        depth=cfg.n_layer,
        num_heads=heads,
        mlp_dim=mlp_dim,
        max_position=cfg.n_positions,
        dropout_rate=0.0,
        dtype=dtype if dtype is not None else jnp.bfloat16,
        ln_eps=cfg.layer_norm_epsilon,
    )
    sd = {k: _np(v) for k, v in hf_model.state_dict().items()}
    pre = "transformer." if any(k.startswith("transformer.") for k in sd) else ""

    params = {
        "wte": {"embedding": sd[f"{pre}wte.weight"]},
        "wpe": {"embedding": sd[f"{pre}wpe.weight"]},
        "decoder": {
            "ln_final": {
                "scale": sd[f"{pre}ln_f.weight"],
                "bias": sd[f"{pre}ln_f.bias"],
            },
        },
    }
    for i in range(cfg.n_layer):
        h = f"{pre}h.{i}."
        # Conv1D weight layout is [in, out] already
        c_attn_w = sd[h + "attn.c_attn.weight"]  # [H, 3H]
        c_attn_b = sd[h + "attn.c_attn.bias"]    # [3H]
        qw, kw, vw = np.split(c_attn_w, 3, axis=1)
        qb, kb, vb = np.split(c_attn_b, 3)
        params["decoder"][f"block_{i}"] = {
            "ln_attn": {"scale": sd[h + "ln_1.weight"],
                        "bias": sd[h + "ln_1.bias"]},
            "ln_mlp": {"scale": sd[h + "ln_2.weight"],
                       "bias": sd[h + "ln_2.bias"]},
            "attn": {
                "query": {"kernel": qw.reshape(hidden, heads, hd),
                          "bias": qb.reshape(heads, hd)},
                "key": {"kernel": kw.reshape(hidden, heads, hd),
                        "bias": kb.reshape(heads, hd)},
                "value": {"kernel": vw.reshape(hidden, heads, hd),
                          "bias": vb.reshape(heads, hd)},
                "out": {
                    "kernel": sd[h + "attn.c_proj.weight"].reshape(
                        heads, hd, hidden
                    ),
                    "bias": sd[h + "attn.c_proj.bias"],
                },
            },
            "mlp": {
                "fc1": {"kernel": sd[h + "mlp.c_fc.weight"],
                        "bias": sd[h + "mlp.c_fc.bias"]},
                "fc2": {"kernel": sd[h + "mlp.c_proj.weight"],
                        "bias": sd[h + "mlp.c_proj.bias"]},
            },
        }
    return model, params


def _rope_scaling_tuple(rs, max_position=None) -> "Optional[tuple]":
    """HF rope_scaling dict -> the hashable tuple ops/rotary understands:
    ('linear', factor), ('llama3', factor, low, high, orig_max), or
    ('yarn', factor, beta_fast, beta_slow, orig_max, attention_factor,
    truncate). None passes through; dynamic-NTK / longrope are refused
    (their frequency rules are not implemented — converting would produce
    silently wrong logits). `max_position` is the config's
    max_position_embeddings — yarn's original_max falls back to it, the
    HF convention."""
    import math

    if not rs:
        return None
    kind = rs.get("rope_type") or rs.get("type")
    if kind == "linear":
        return ("linear", float(rs["factor"]))
    if kind == "llama3":
        return (
            "llama3", float(rs["factor"]),
            float(rs["low_freq_factor"]), float(rs["high_freq_factor"]),
            float(rs["original_max_position_embeddings"]),
        )
    if kind == "yarn":
        factor = float(rs["factor"])
        att = rs.get("attention_factor")
        if att is None:
            # the paper's mscale rule (HF _compute_yarn_parameters)
            def get_mscale(scale, m=1.0):
                return 1.0 if scale <= 1 else 0.1 * m * math.log(scale) + 1.0

            mscale = rs.get("mscale")
            mscale_all = rs.get("mscale_all_dim")
            if mscale and mscale_all:
                att = get_mscale(factor, mscale) / get_mscale(factor,
                                                              mscale_all)
            else:
                att = get_mscale(factor)
        orig_max = (rs.get("original_max_position_embeddings")
                    or max_position)
        if orig_max is None:
            raise NotImplementedError(
                "yarn rope_scaling without original_max_position_"
                "embeddings needs the config's max_position_embeddings"
            )
        return (
            "yarn", factor,
            float(rs.get("beta_fast") or 32.0),
            float(rs.get("beta_slow") or 1.0),
            float(orig_max), float(att),
            bool(rs.get("truncate", True)),
        )
    if kind == "default":
        return None
    raise NotImplementedError(
        f"rope_scaling type {kind!r} is not supported (only 'linear', "
        f"'llama3' and 'yarn'); converting would produce silently wrong "
        f"logits"
    )


def _rope_scaling_dict(scaling) -> "Optional[dict]":
    """The inverse of _rope_scaling_tuple, for to_hf exports."""
    if scaling is None:
        return None
    scaling = tuple(scaling)
    if scaling[0] == "linear":
        return {"rope_type": "linear", "factor": float(scaling[1])}
    if scaling[0] == "llama3":
        return {
            "rope_type": "llama3", "factor": float(scaling[1]),
            "low_freq_factor": float(scaling[2]),
            "high_freq_factor": float(scaling[3]),
            "original_max_position_embeddings": int(scaling[4]),
        }
    if scaling[0] == "yarn":
        return {
            "rope_type": "yarn", "factor": float(scaling[1]),
            "beta_fast": float(scaling[2]),
            "beta_slow": float(scaling[3]),
            "original_max_position_embeddings": int(scaling[4]),
            # explicit attention_factor: guarantees the exported model
            # computes the identical temperature even if the import
            # derived it from mscale
            "attention_factor": float(scaling[5]),
            "truncate": bool(scaling[6]),
        }
    raise NotImplementedError(f"unknown rope scaling {scaling!r}")


def llama_from_hf(hf_model, dtype=None) -> Tuple[object, dict]:
    """(GPT, params) from a transformers LlamaForCausalLM — the LLaMA
    family maps onto GPT(position='rope', num_kv_heads=..., norm='rms',
    mlp_act='swiglu', use_bias=False): rotary rotate-half, grouped-query
    K/V, RMSNorm (scale only), gated-silu MLP, bias-free projections, and
    an untied lm_head unless the checkpoint ties it."""
    import jax.numpy as jnp

    from tfde_tpu.models.gpt import GPT

    cfg = hf_model.config
    rope_scaling = _rope_scaling_tuple(
        getattr(cfg, "rope_scaling", None),
        max_position=cfg.max_position_embeddings,
    )
    if getattr(cfg, "attention_bias", False) or getattr(cfg, "mlp_bias", False):
        raise NotImplementedError(
            "checkpoints with attention_bias/mlp_bias are not supported by "
            "this converter (the bias tensors would be silently dropped)"
        )
    heads = cfg.num_attention_heads
    hidden = cfg.hidden_size
    # Gemma-7b-class checkpoints decouple the per-head width from
    # hidden/heads; honor the config's head_dim when present
    hd = getattr(cfg, "head_dim", None) or hidden // heads
    kv = cfg.num_key_value_heads
    tied = bool(getattr(cfg, "tie_word_embeddings", False))
    model = GPT(
        vocab_size=cfg.vocab_size,
        hidden_size=hidden,
        depth=cfg.num_hidden_layers,
        num_heads=heads,
        mlp_dim=cfg.intermediate_size,
        max_position=cfg.max_position_embeddings,
        dropout_rate=0.0,
        dtype=dtype if dtype is not None else jnp.bfloat16,
        position="rope",
        rope_theta=float(cfg.rope_theta),
        rope_scaling=rope_scaling,
        num_kv_heads=kv,
        norm="rms",
        mlp_act="swiglu",
        use_bias=False,
        tie_embeddings=tied,
        ln_eps=cfg.rms_norm_eps,
        head_dim=None if hd == hidden // heads else hd,
    )
    sd = {k: _np(v) for k, v in hf_model.state_dict().items()}
    pre = "model." if any(k.startswith("model.") for k in sd) else ""

    params = {
        "wte": {"embedding": sd[f"{pre}embed_tokens.weight"]},
        "decoder": {
            "ln_final": {"scale": sd[f"{pre}norm.weight"]},
        },
    }
    if not tied:
        params["lm_head"] = {"kernel": sd["lm_head.weight"].T}
    for i in range(cfg.num_hidden_layers):
        h = f"{pre}layers.{i}."
        params["decoder"][f"block_{i}"] = {
            "ln_attn": {"scale": sd[h + "input_layernorm.weight"]},
            "ln_mlp": {"scale": sd[h + "post_attention_layernorm.weight"]},
            "attn": {
                # torch Linear [out, in] -> in-major kernels
                "query": {"kernel": sd[h + "self_attn.q_proj.weight"].T
                          .reshape(hidden, heads, hd)},
                "key": {"kernel": sd[h + "self_attn.k_proj.weight"].T
                        .reshape(hidden, kv, hd)},
                "value": {"kernel": sd[h + "self_attn.v_proj.weight"].T
                          .reshape(hidden, kv, hd)},
                "out": {"kernel": sd[h + "self_attn.o_proj.weight"].T
                        .reshape(heads, hd, hidden)},
            },
            "mlp": {
                "gate": {"kernel": sd[h + "mlp.gate_proj.weight"].T},
                "fc1": {"kernel": sd[h + "mlp.up_proj.weight"].T},
                "fc2": {"kernel": sd[h + "mlp.down_proj.weight"].T},
            },
        }
    return model, params


def mistral_from_hf(hf_model, dtype=None) -> Tuple[object, dict]:
    """(GPT, params) from a transformers MistralForCausalLM.

    Mistral is the LLaMA architecture (rope + GQA + swiglu + RMSNorm +
    bias-free) plus sliding-window attention; the HF state-dict layout is
    identical, so this delegates the weight mapping to `llama_from_hf` and
    sets `sliding_window` from the config (None in the config means full
    attention — some later Mistral checkpoints disable the window)."""
    model, params = llama_from_hf(hf_model, dtype=dtype)
    window = getattr(hf_model.config, "sliding_window", None)
    if window is not None:
        model = model.clone(sliding_window=int(window))
    return model, params


def gemma_from_hf(hf_model, dtype=None) -> Tuple[object, dict]:
    """(GPT, params) from a transformers GemmaForCausalLM.

    Gemma is LLaMA-shaped (rope + GQA + RMSNorm + bias-free + gated MLP
    + decoupled head_dim on 7b), so the weight mapping delegates to
    `llama_from_hf` — like `mistral_from_hf` — and this function handles
    the three Gemma deltas:

    - gelu-gated MLP (`mlp_act='geglu'`, HF gelu_pytorch_tanh);
    - token embeddings scaled by sqrt(hidden) (`GPT(embed_scale=...)`);
    - zero-centered RMSNorm weights (the HF module computes `x * (1 + w)`)
      — folded into the stored scales as `1 + w` at conversion, so the
      model's plain RMSNorm reproduces the math with no runtime branch.
    """
    cfg = hf_model.config
    # hidden_act is what the installed GemmaMLP actually runs
    # (ACT2FN[config.hidden_act]); hidden_activation is a config-era alias
    # that GemmaConfig folds into it — validating the alias could pass a
    # checkpoint whose live field says something else
    act = getattr(cfg, "hidden_act", None)
    if act not in ("gelu_pytorch_tanh", "gelu_tanh", None):
        raise NotImplementedError(
            f"hidden_act {act!r} is not supported (expected the Gemma "
            f"tanh-gelu); converting would silently change the math"
        )
    if not bool(getattr(cfg, "tie_word_embeddings", True)):
        # every Gemma release ties; an untied fine-tune would carry a
        # distinct lm_head.weight this path would silently drop
        raise NotImplementedError(
            "untied Gemma-architecture checkpoints are not supported "
            "(lm_head.weight would be silently dropped)"
        )
    model, params = llama_from_hf(hf_model, dtype=dtype)
    model = model.clone(
        mlp_act="geglu",
        tie_embeddings=True,
        embed_scale=float(cfg.hidden_size) ** 0.5,
    )
    dec = params["decoder"]
    dec["ln_final"]["scale"] = 1.0 + dec["ln_final"]["scale"]
    for i in range(cfg.num_hidden_layers):
        blk = dec[f"block_{i}"]
        blk["ln_attn"]["scale"] = 1.0 + blk["ln_attn"]["scale"]
        blk["ln_mlp"]["scale"] = 1.0 + blk["ln_mlp"]["scale"]
    return model, params


def gemma2_from_hf(hf_model, dtype=None) -> Tuple[object, dict]:
    """(GPT, params) from a transformers Gemma2ForCausalLM.

    Gemma-2 extends the Gemma arrangement with: SANDWICH norms (each
    sublayer normed both sides — `norm_style='sandwich'`, four RMSNorms
    per block), attention logit softcapping and a custom query scale
    (`attn_logit_cap`, `attn_scale = query_pre_attn_scalar^-0.5`), final
    logit softcapping, and ALTERNATING local/global attention (even
    blocks sliding-window, odd full — `sliding_window_pattern=
    'alternate'`). All five norm kinds carry the zero-centered 1+w fold
    like Gemma-1."""
    import jax.numpy as jnp

    from tfde_tpu.models.gpt import GPT

    cfg = hf_model.config
    act = getattr(cfg, "hidden_activation", "gelu_pytorch_tanh")
    if act not in ("gelu_pytorch_tanh", "gelu_tanh"):
        raise NotImplementedError(
            f"hidden_activation {act!r} is not supported (expected the "
            f"Gemma tanh-gelu)"
        )
    if not bool(getattr(cfg, "tie_word_embeddings", True)):
        raise NotImplementedError(
            "untied Gemma-2 checkpoints are not supported"
        )
    if bool(getattr(cfg, "attention_bias", False)):
        raise NotImplementedError(
            "attention_bias=True checkpoints are not supported (the bias "
            "tensors would be silently dropped)"
        )
    lt = getattr(cfg, "layer_types", None)
    if lt is not None:
        expect = ["sliding_attention", "full_attention"]
        if any(t != expect[i % 2] for i, t in enumerate(lt)):
            raise NotImplementedError(
                f"layer_types {lt!r} does not match the Gemma-2 "
                f"even-sliding/odd-full interleave this model expresses"
            )
    heads = cfg.num_attention_heads
    hidden = cfg.hidden_size
    hd = cfg.head_dim
    kv = cfg.num_key_value_heads
    model = GPT(
        vocab_size=cfg.vocab_size,
        hidden_size=hidden,
        depth=cfg.num_hidden_layers,
        num_heads=heads,
        head_dim=None if hd == hidden // heads else hd,
        mlp_dim=cfg.intermediate_size,
        max_position=cfg.max_position_embeddings,
        dropout_rate=0.0,
        dtype=dtype if dtype is not None else jnp.bfloat16,
        position="rope",
        rope_theta=float(cfg.rope_theta),
        num_kv_heads=kv,
        use_bias=False,
        norm="rms",
        norm_style="sandwich",
        mlp_act="geglu",
        tie_embeddings=True,
        embed_scale=float(hidden) ** 0.5,
        ln_eps=cfg.rms_norm_eps,
        sliding_window=cfg.sliding_window,
        sliding_window_pattern="alternate",
        attn_scale=float(cfg.query_pre_attn_scalar) ** -0.5,
        attn_logit_cap=(float(cfg.attn_logit_softcapping)
                        if cfg.attn_logit_softcapping else None),
        final_logit_cap=(float(cfg.final_logit_softcapping)
                         if cfg.final_logit_softcapping else None),
    )
    sd = {k: _np(v) for k, v in hf_model.state_dict().items()}
    pre = "model." if any(k.startswith("model.") for k in sd) else ""

    def fold(w):  # zero-centered RMSNorm weights: stored scale = 1 + w
        return 1.0 + w

    params = {
        "wte": {"embedding": sd[f"{pre}embed_tokens.weight"]},
        "decoder": {
            "ln_final": {"scale": fold(sd[f"{pre}norm.weight"])},
        },
    }
    for i in range(cfg.num_hidden_layers):
        h = f"{pre}layers.{i}."
        params["decoder"][f"block_{i}"] = {
            "ln_attn": {"scale": fold(sd[h + "input_layernorm.weight"])},
            "ln_attn_post": {
                "scale": fold(sd[h + "post_attention_layernorm.weight"])
            },
            "ln_mlp": {
                "scale": fold(sd[h + "pre_feedforward_layernorm.weight"])
            },
            "ln_mlp_post": {
                "scale": fold(sd[h + "post_feedforward_layernorm.weight"])
            },
            "attn": {
                "query": {"kernel": sd[h + "self_attn.q_proj.weight"].T
                          .reshape(hidden, heads, hd)},
                "key": {"kernel": sd[h + "self_attn.k_proj.weight"].T
                        .reshape(hidden, kv, hd)},
                "value": {"kernel": sd[h + "self_attn.v_proj.weight"].T
                          .reshape(hidden, kv, hd)},
                "out": {"kernel": sd[h + "self_attn.o_proj.weight"].T
                        .reshape(heads, hd, hidden)},
            },
            "mlp": {
                "gate": {"kernel": sd[h + "mlp.gate_proj.weight"].T},
                "fc1": {"kernel": sd[h + "mlp.up_proj.weight"].T},
                "fc2": {"kernel": sd[h + "mlp.down_proj.weight"].T},
            },
        }
    return model, params


def gemma2_to_hf(model, params):
    """A transformers Gemma2ForCausalLM carrying `params` — the inverse
    of `gemma2_from_hf` (all five norm kinds un-fold 1+w)."""
    import transformers

    heads = model.num_heads
    hidden = model.hidden_size
    hd = model.head_dim or hidden // heads
    if (model.position != "rope" or model.norm != "rms"
            or model.mlp_act != "geglu" or model.use_bias
            or not model.tie_embeddings or model.qkv_bias
            or getattr(model, "qk_norm", False) or model.head_bias
            or model.norm_style != "sandwich"
            or model.rope_dim is not None
            or model.rope_scaling is not None
            or model.sliding_window is None
            or model.sliding_window_pattern != "alternate"
            or model.attn_scale is None
            or model.embed_scale is None
            or abs(model.embed_scale - hidden ** 0.5) > 1e-6):
        raise NotImplementedError(
            "gemma2_to_hf requires the Gemma-2 arrangement (sandwich "
            "norms, geglu, tied scaled embeddings, alternating sliding "
            "window, custom query scale) — Gemma-1 models export via "
            "gemma_to_hf"
        )
    cfg = transformers.Gemma2Config(
        vocab_size=model.vocab_size, hidden_size=hidden,
        num_hidden_layers=model.depth, num_attention_heads=heads,
        num_key_value_heads=model.num_kv_heads or heads,
        intermediate_size=model.mlp_dim, head_dim=hd,
        max_position_embeddings=model.max_position,
        rope_theta=model.rope_theta, rms_norm_eps=model.ln_eps,
        sliding_window=int(model.sliding_window),
        query_pre_attn_scalar=float(model.attn_scale) ** -2.0,
        attn_logit_softcapping=(float(model.attn_logit_cap)
                                if model.attn_logit_cap else None),
        final_logit_softcapping=(float(model.final_logit_cap)
                                 if model.final_logit_cap else None),
        tie_word_embeddings=True, attention_dropout=0.0,
        hidden_activation="gelu_pytorch_tanh",
    )
    hf = transformers.Gemma2ForCausalLM(cfg)
    sd = {}
    sd["model.embed_tokens.weight"] = _t(params["wte"]["embedding"])
    dec = params["decoder"]

    def unfold(s):  # stored 1 + w -> the HF zero-centered weight
        return _t(np.asarray(s) - 1.0)

    sd["model.norm.weight"] = unfold(dec["ln_final"]["scale"])
    sd["lm_head.weight"] = sd["model.embed_tokens.weight"]
    kv = model.num_kv_heads or heads
    for i in range(model.depth):
        blk = dec[f"block_{i}"]
        h = f"model.layers.{i}."
        sd[h + "input_layernorm.weight"] = unfold(blk["ln_attn"]["scale"])
        sd[h + "post_attention_layernorm.weight"] = unfold(
            blk["ln_attn_post"]["scale"]
        )
        sd[h + "pre_feedforward_layernorm.weight"] = unfold(
            blk["ln_mlp"]["scale"]
        )
        sd[h + "post_feedforward_layernorm.weight"] = unfold(
            blk["ln_mlp_post"]["scale"]
        )
        a = blk["attn"]
        sd[h + "self_attn.q_proj.weight"] = _t(
            np.asarray(a["query"]["kernel"]).reshape(hidden, heads * hd).T
        )
        sd[h + "self_attn.k_proj.weight"] = _t(
            np.asarray(a["key"]["kernel"]).reshape(hidden, kv * hd).T
        )
        sd[h + "self_attn.v_proj.weight"] = _t(
            np.asarray(a["value"]["kernel"]).reshape(hidden, kv * hd).T
        )
        sd[h + "self_attn.o_proj.weight"] = _t(
            np.asarray(a["out"]["kernel"]).reshape(heads * hd, hidden).T
        )
        sd[h + "mlp.gate_proj.weight"] = _t(
            np.asarray(blk["mlp"]["gate"]["kernel"]).T
        )
        sd[h + "mlp.up_proj.weight"] = _t(
            np.asarray(blk["mlp"]["fc1"]["kernel"]).T
        )
        sd[h + "mlp.down_proj.weight"] = _t(
            np.asarray(blk["mlp"]["fc2"]["kernel"]).T
        )
    missing, unexpected = hf.load_state_dict(sd, strict=False)
    missing = [k for k in missing if "rotary_emb" not in k]
    if missing or unexpected:
        raise RuntimeError(f"to_hf mapping drift: missing={missing} "
                           f"unexpected={list(unexpected)}")
    hf.eval()
    return hf


def qwen2_from_hf(hf_model, dtype=None) -> Tuple[object, dict]:
    """(GPT, params) from a transformers Qwen2ForCausalLM.

    Qwen2 is the LLaMA architecture with biased q/k/v projections beside
    a bias-free out projection and MLP (`GPT(qkv_bias=True)`); the HF
    modeling code hardcodes those biases, so the weight mapping delegates
    to `llama_from_hf` and this function adds the three bias tensors per
    layer. Sliding-window Qwen2 configs interleave windowed and full
    layers (`layer_types`), which the single-window GPT cannot express —
    refused loudly; every mainline release ships use_sliding_window=False.
    """
    cfg = hf_model.config
    if bool(getattr(cfg, "use_sliding_window", False)):
        raise NotImplementedError(
            "use_sliding_window=True interleaves windowed and full "
            "attention per layer (max_window_layers), which the "
            "single-window model cannot express; mainline Qwen2 releases "
            "ship with it disabled"
        )
    model, params = llama_from_hf(hf_model, dtype=dtype)
    model = model.clone(qkv_bias=True)
    heads = cfg.num_attention_heads
    hd = getattr(cfg, "head_dim", None) or cfg.hidden_size // heads
    kv = cfg.num_key_value_heads
    # pull ONLY the bias tensors — llama_from_hf already materialized the
    # full state dict once; a second full-checkpoint fp32 copy to read
    # O(layers * 3 * width) floats would double peak host memory at 7B
    sd = hf_model.state_dict()
    pre = "model." if any(k.startswith("model.") for k in sd) else ""
    for i in range(cfg.num_hidden_layers):
        h = f"{pre}layers.{i}.self_attn."
        attn = params["decoder"][f"block_{i}"]["attn"]
        attn["query"]["bias"] = _np(sd[h + "q_proj.bias"]).reshape(heads, hd)
        attn["key"]["bias"] = _np(sd[h + "k_proj.bias"]).reshape(kv, hd)
        attn["value"]["bias"] = _np(sd[h + "v_proj.bias"]).reshape(kv, hd)
    return model, params


def qwen2moe_from_hf(hf_model, dtype=None) -> Tuple[object, dict]:
    """(GPT, params) from a transformers Qwen2MoeForCausalLM.

    Qwen2-MoE = the Qwen2 attention arrangement (biased q/k/v beside
    bias-free o/MLP) with EVERY layer's MLP routed, plus two deltas the
    MoE layer grew for it: RAW top-k combine weights
    (`moe_normalize_topk=False` when norm_topk_prob is off — the released
    A2.7B config) and a dense SHARED expert beside the routed ones, its
    output scaled by a learned sigmoid gate
    (`moe_shared_expert_dim`). Conversion pins the no-drop capacity
    (E/k) like Mixtral, making the converted forward exact."""
    import jax.numpy as jnp

    from tfde_tpu.models.gpt import GPT

    cfg = hf_model.config
    if list(getattr(cfg, "mlp_only_layers", []) or []):
        raise NotImplementedError(
            f"mlp_only_layers {cfg.mlp_only_layers!r} (dense layers "
            f"interleaved among MoE) is not supported — the released "
            f"Qwen2-MoE configs route every layer"
        )
    if int(getattr(cfg, "decoder_sparse_step", 1)) != 1:
        raise NotImplementedError(
            f"decoder_sparse_step {cfg.decoder_sparse_step} != 1 is not "
            f"supported"
        )
    if bool(getattr(cfg, "use_sliding_window", False)):
        raise NotImplementedError(
            "use_sliding_window=True is not supported (per-layer windows)"
        )
    heads = cfg.num_attention_heads
    hidden = cfg.hidden_size
    hd = hidden // heads
    kv = cfg.num_key_value_heads
    e = cfg.num_experts
    k = cfg.num_experts_per_tok
    model = GPT(
        vocab_size=cfg.vocab_size,
        hidden_size=hidden,
        depth=cfg.num_hidden_layers,
        num_heads=heads,
        mlp_dim=cfg.moe_intermediate_size,
        max_position=cfg.max_position_embeddings,
        dropout_rate=0.0,
        dtype=dtype if dtype is not None else jnp.bfloat16,
        position="rope",
        rope_theta=float(cfg.rope_theta),
        rope_scaling=_rope_scaling_tuple(
            getattr(cfg, "rope_scaling", None),
            max_position=cfg.max_position_embeddings,
        ),
        num_kv_heads=kv,
        use_bias=False,
        qkv_bias=True,
        norm="rms",
        mlp_act="swiglu",
        num_experts=e,
        moe_every=1,
        experts_per_token=k,
        moe_capacity_factor=float(e) / k,
        moe_normalize_topk=bool(getattr(cfg, "norm_topk_prob", False)),
        moe_shared_expert_dim=cfg.shared_expert_intermediate_size,
        tie_embeddings=bool(getattr(cfg, "tie_word_embeddings", False)),
        ln_eps=cfg.rms_norm_eps,
    )
    sd = {k_: _np(v) for k_, v in hf_model.state_dict().items()}
    pre = "model." if any(k_.startswith("model.") for k_ in sd) else ""
    params = {
        "wte": {"embedding": sd[f"{pre}embed_tokens.weight"]},
        "decoder": {
            "ln_final": {"scale": sd[f"{pre}norm.weight"]},
        },
    }
    if not model.tie_embeddings:
        params["lm_head"] = {"kernel": sd["lm_head.weight"].T}
    for i in range(cfg.num_hidden_layers):
        h = f"{pre}layers.{i}."
        moe_pre = h + "mlp."
        params["decoder"][f"block_{i}"] = {
            "ln_attn": {"scale": sd[h + "input_layernorm.weight"]},
            "ln_mlp": {"scale": sd[h + "post_attention_layernorm.weight"]},
            "attn": {
                "query": {"kernel": sd[h + "self_attn.q_proj.weight"].T
                          .reshape(hidden, heads, hd),
                          "bias": sd[h + "self_attn.q_proj.bias"]
                          .reshape(heads, hd)},
                "key": {"kernel": sd[h + "self_attn.k_proj.weight"].T
                        .reshape(hidden, kv, hd),
                        "bias": sd[h + "self_attn.k_proj.bias"]
                        .reshape(kv, hd)},
                "value": {"kernel": sd[h + "self_attn.v_proj.weight"].T
                          .reshape(hidden, kv, hd),
                          "bias": sd[h + "self_attn.v_proj.bias"]
                          .reshape(kv, hd)},
                "out": {"kernel": sd[h + "self_attn.o_proj.weight"].T
                        .reshape(heads, hd, hidden)},
            },
            "moe": {
                "router": {"kernel": sd[moe_pre + "gate.weight"].T},
                "experts_gate": np.stack(
                    [sd[moe_pre + f"experts.{j}.gate_proj.weight"].T
                     for j in range(e)]
                ),
                "experts_fc1": np.stack(
                    [sd[moe_pre + f"experts.{j}.up_proj.weight"].T
                     for j in range(e)]
                ),
                "experts_fc2": np.stack(
                    [sd[moe_pre + f"experts.{j}.down_proj.weight"].T
                     for j in range(e)]
                ),
                "shared_gate": {
                    "kernel": sd[moe_pre + "shared_expert.gate_proj.weight"].T
                },
                "shared_fc1": {
                    "kernel": sd[moe_pre + "shared_expert.up_proj.weight"].T
                },
                "shared_fc2": {
                    "kernel": sd[moe_pre + "shared_expert.down_proj.weight"].T
                },
                "shared_expert_gate": {
                    "kernel": sd[moe_pre + "shared_expert_gate.weight"].T
                },
            },
        }
    return model, params


def qwen2moe_to_hf(model, params):
    """A transformers Qwen2MoeForCausalLM carrying `params` — the inverse
    of `qwen2moe_from_hf`."""
    import transformers

    e = model.num_experts
    k = model.experts_per_token
    if (model.position != "rope" or model.norm != "rms"
            or model.mlp_act != "swiglu" or model.use_bias
            or not model.qkv_bias or e <= 0 or model.moe_every != 1
            or model.moe_shared_expert_dim is None
            or getattr(model, "qk_norm", False) or model.head_bias
            or model.embed_scale is not None or model.head_dim is not None
            or model.norm_style != "pre" or model.rope_dim is not None
            or model.sliding_window is not None):
        raise NotImplementedError(
            "qwen2moe_to_hf requires the Qwen2-MoE arrangement (biased "
            "q/k/v, every layer routed, shared expert) — other families "
            "export via their own inverses"
        )
    if model.moe_capacity_factor < float(e) / k:
        raise NotImplementedError(
            f"moe_capacity_factor {model.moe_capacity_factor} < E/k = "
            f"{float(e) / k}: this model can drop overflow tokens, which "
            f"capacity-free HF Qwen2-MoE cannot express"
        )
    heads = model.num_heads
    hidden = model.hidden_size
    hd = hidden // heads
    kv = model.num_kv_heads or heads
    cfg = transformers.Qwen2MoeConfig(
        vocab_size=model.vocab_size, hidden_size=hidden,
        num_hidden_layers=model.depth, num_attention_heads=heads,
        num_key_value_heads=kv,
        # intermediate_size (the DENSE MLP width) is inert here: both
        # directions pin mlp_only_layers=[] and decoder_sparse_step=1, so
        # no dense layer is ever instantiated and the original value is
        # not recorded by the import — set to the expert width, not a
        # claim about the source config
        intermediate_size=model.mlp_dim,
        moe_intermediate_size=model.mlp_dim,
        shared_expert_intermediate_size=model.moe_shared_expert_dim,
        num_experts=e, num_experts_per_tok=k,
        norm_topk_prob=model.moe_normalize_topk,
        decoder_sparse_step=1, mlp_only_layers=[],
        max_position_embeddings=model.max_position,
        rope_theta=model.rope_theta,
        rope_scaling=_rope_scaling_dict(model.rope_scaling),
        rms_norm_eps=model.ln_eps,
        tie_word_embeddings=model.tie_embeddings,
        use_sliding_window=False, attention_dropout=0.0,
        router_aux_loss_coef=0.0, output_router_logits=False,
    )
    hf = transformers.Qwen2MoeForCausalLM(cfg)
    sd = {}
    sd["model.embed_tokens.weight"] = _t(params["wte"]["embedding"])
    dec = params["decoder"]
    sd["model.norm.weight"] = _t(dec["ln_final"]["scale"])
    sd["lm_head.weight"] = (
        sd["model.embed_tokens.weight"] if model.tie_embeddings
        else _t(np.asarray(params["lm_head"]["kernel"]).T)
    )
    for i in range(model.depth):
        blk = dec[f"block_{i}"]
        h = f"model.layers.{i}."
        sd[h + "input_layernorm.weight"] = _t(blk["ln_attn"]["scale"])
        sd[h + "post_attention_layernorm.weight"] = _t(
            blk["ln_mlp"]["scale"]
        )
        a = blk["attn"]
        for ours, theirs, nh in (("query", "q_proj", heads),
                                 ("key", "k_proj", kv),
                                 ("value", "v_proj", kv)):
            sd[h + f"self_attn.{theirs}.weight"] = _t(
                np.asarray(a[ours]["kernel"]).reshape(hidden, nh * hd).T
            )
            sd[h + f"self_attn.{theirs}.bias"] = _t(
                np.asarray(a[ours]["bias"]).reshape(nh * hd)
            )
        sd[h + "self_attn.o_proj.weight"] = _t(
            np.asarray(a["out"]["kernel"]).reshape(heads * hd, hidden).T
        )
        moe = blk["moe"]
        sd[h + "mlp.gate.weight"] = _t(
            np.asarray(moe["router"]["kernel"]).T
        )
        gate_s = np.asarray(moe["experts_gate"])
        up_s = np.asarray(moe["experts_fc1"])
        down_s = np.asarray(moe["experts_fc2"])
        for j in range(e):
            sd[h + f"mlp.experts.{j}.gate_proj.weight"] = _t(gate_s[j].T)
            sd[h + f"mlp.experts.{j}.up_proj.weight"] = _t(up_s[j].T)
            sd[h + f"mlp.experts.{j}.down_proj.weight"] = _t(down_s[j].T)
        sd[h + "mlp.shared_expert.gate_proj.weight"] = _t(
            np.asarray(moe["shared_gate"]["kernel"]).T
        )
        sd[h + "mlp.shared_expert.up_proj.weight"] = _t(
            np.asarray(moe["shared_fc1"]["kernel"]).T
        )
        sd[h + "mlp.shared_expert.down_proj.weight"] = _t(
            np.asarray(moe["shared_fc2"]["kernel"]).T
        )
        sd[h + "mlp.shared_expert_gate.weight"] = _t(
            np.asarray(moe["shared_expert_gate"]["kernel"]).T
        )
    missing, unexpected = hf.load_state_dict(sd, strict=False)
    missing = [k_ for k_ in missing if "rotary_emb" not in k_]
    if missing or unexpected:
        raise RuntimeError(f"to_hf mapping drift: missing={missing} "
                           f"unexpected={list(unexpected)}")
    hf.eval()
    return hf


def phi3_from_hf(hf_model, dtype=None) -> Tuple[object, dict]:
    """(GPT, params) from a transformers Phi3ForCausalLM (Phi-3/3.5-mini).

    The Phi-3 arrangement is LLaMA-shaped (rope + GQA + RMSNorm +
    gated-silu + bias-free + untied head) with FUSED checkpoint layouts:
    qkv_proj packs [q | k | v] rows flat, gate_up_proj packs
    [gate | up] — split here into the standard kernels. Long-context
    variants carry rope_scaling='longrope', which _rope_scaling_tuple
    refuses loudly (the 4k-context releases ship rope_scaling null).
    partial_rotary_factor < 1 maps to GPT(rope_dim=...)."""
    import jax.numpy as jnp

    from tfde_tpu.models.gpt import GPT

    cfg = hf_model.config
    if getattr(cfg, "hidden_act", "silu") != "silu":
        raise NotImplementedError(
            f"hidden_act {cfg.hidden_act!r} is not supported (Phi-3 "
            f"releases use silu)"
        )
    heads = cfg.num_attention_heads
    hidden = cfg.hidden_size
    hd = hidden // heads
    kv = cfg.num_key_value_heads
    prf = float(getattr(cfg, "partial_rotary_factor", 1.0))
    rope_dim = None if prf == 1.0 else int(hd * prf)
    model = GPT(
        vocab_size=cfg.vocab_size,
        hidden_size=hidden,
        depth=cfg.num_hidden_layers,
        num_heads=heads,
        mlp_dim=cfg.intermediate_size,
        max_position=cfg.max_position_embeddings,
        dropout_rate=0.0,
        dtype=dtype if dtype is not None else jnp.bfloat16,
        position="rope",
        rope_theta=float(cfg.rope_theta),
        rope_scaling=_rope_scaling_tuple(
            getattr(cfg, "rope_scaling", None),
            max_position=cfg.max_position_embeddings,
        ),
        rope_dim=rope_dim,
        num_kv_heads=kv,
        use_bias=False,
        norm="rms",
        mlp_act="swiglu",
        sliding_window=getattr(cfg, "sliding_window", None),
        tie_embeddings=bool(getattr(cfg, "tie_word_embeddings", False)),
        ln_eps=cfg.rms_norm_eps,
    )
    sd = {k: _np(v) for k, v in hf_model.state_dict().items()}
    pre = "model." if any(k.startswith("model.") for k in sd) else ""
    params = {
        "wte": {"embedding": sd[f"{pre}embed_tokens.weight"]},
        "decoder": {
            "ln_final": {"scale": sd[f"{pre}norm.weight"]},
        },
    }
    if not model.tie_embeddings:
        params["lm_head"] = {"kernel": sd["lm_head.weight"].T}
    f = cfg.intermediate_size
    for i in range(cfg.num_hidden_layers):
        h = f"{pre}layers.{i}."
        qkv = sd[h + "self_attn.qkv_proj.weight"].T  # [d, H + 2*kv*hd]
        qw, kw, vw = np.split(
            qkv, [heads * hd, heads * hd + kv * hd], axis=1
        )
        gate_up = sd[h + "mlp.gate_up_proj.weight"].T  # [d, 2F]
        gw, uw = np.split(gate_up, [f], axis=1)
        params["decoder"][f"block_{i}"] = {
            "ln_attn": {"scale": sd[h + "input_layernorm.weight"]},
            "ln_mlp": {"scale": sd[h + "post_attention_layernorm.weight"]},
            "attn": {
                "query": {"kernel": qw.reshape(hidden, heads, hd)},
                "key": {"kernel": kw.reshape(hidden, kv, hd)},
                "value": {"kernel": vw.reshape(hidden, kv, hd)},
                "out": {"kernel": sd[h + "self_attn.o_proj.weight"].T
                        .reshape(heads, hd, hidden)},
            },
            "mlp": {
                "gate": {"kernel": gw},
                "fc1": {"kernel": uw},
                "fc2": {"kernel": sd[h + "mlp.down_proj.weight"].T},
            },
        }
    return model, params


def phi3_to_hf(model, params):
    """A transformers Phi3ForCausalLM carrying `params` — the inverse of
    `phi3_from_hf`: the shared LLaMA-style state dict with q/k/v fused
    back into qkv_proj and gate/up into gate_up_proj."""
    import torch
    import transformers

    heads = model.num_heads
    hidden = model.hidden_size
    hd = hidden // heads
    kv = model.num_kv_heads or heads
    if (model.position != "rope" or model.norm != "rms"
            or model.mlp_act != "swiglu" or model.use_bias
            or model.qkv_bias or model.head_bias
            or getattr(model, "qk_norm", False)
            or model.embed_scale is not None or model.head_dim is not None
            or model.norm_style != "pre"):
        raise NotImplementedError(
            "phi3_to_hf requires the Phi-3 arrangement (LLaMA-style "
            "bias-free gated-silu blocks with fused-checkpoint layouts) "
            "— other families export via their own inverses"
        )
    if model.rope_scaling is not None:
        # Phi3Config validates rope_scaling as longrope-format only
        # ({type, short_factor, long_factor}); the linear/llama3/yarn
        # tuples this framework carries have no Phi-3 representation
        raise NotImplementedError(
            f"rope_scaling {tuple(model.rope_scaling)!r} has no Phi-3 "
            f"config representation (Phi-3 long-context is 'longrope', "
            f"which is not implemented) — export via llama_to_hf instead"
        )
    prf = 1.0 if model.rope_dim is None else model.rope_dim / hd
    cfg = transformers.Phi3Config(
        vocab_size=model.vocab_size, hidden_size=hidden,
        num_hidden_layers=model.depth, num_attention_heads=heads,
        num_key_value_heads=kv, intermediate_size=model.mlp_dim,
        max_position_embeddings=model.max_position,
        rope_theta=model.rope_theta,
        partial_rotary_factor=prf,
        rms_norm_eps=model.ln_eps,
        sliding_window=model.sliding_window,
        tie_word_embeddings=model.tie_embeddings,
        attention_dropout=0.0, resid_pdrop=0.0, embd_pdrop=0.0,
        pad_token_id=0,
    )
    hf = transformers.Phi3ForCausalLM(cfg)
    # the ONE llama-style builder, then fuse its per-layer keys into the
    # Phi-3 checkpoint layout
    sd = _llama_style_sd(model, params)
    for i in range(model.depth):
        h = f"model.layers.{i}."
        sd[h + "self_attn.qkv_proj.weight"] = torch.cat(
            [sd.pop(h + "self_attn.q_proj.weight"),
             sd.pop(h + "self_attn.k_proj.weight"),
             sd.pop(h + "self_attn.v_proj.weight")], dim=0,
        )
        sd[h + "mlp.gate_up_proj.weight"] = torch.cat(
            [sd.pop(h + "mlp.gate_proj.weight"),
             sd.pop(h + "mlp.up_proj.weight")], dim=0,
        )
    missing, unexpected = hf.load_state_dict(sd, strict=False)
    missing = [k for k in missing if "rotary_emb" not in k]
    if missing or unexpected:
        raise RuntimeError(f"to_hf mapping drift: missing={missing} "
                           f"unexpected={list(unexpected)}")
    hf.eval()
    return hf


def qwen3_from_hf(hf_model, dtype=None) -> Tuple[object, dict]:
    """(GPT, params) from a transformers Qwen3ForCausalLM.

    Qwen3 is the LLaMA arrangement (bias-free this generation — Qwen2's
    qkv biases are gone) plus per-head RMSNorm on q and k before rotary
    (`GPT(qk_norm=True)`, one [head_dim] scale each shared across heads)
    and a decoupled head_dim. Delegates the weight mapping to
    `llama_from_hf` and adds the two norm scales per layer."""
    cfg = hf_model.config
    model, params = llama_from_hf(hf_model, dtype=dtype)
    model = model.clone(qk_norm=True)
    sd = hf_model.state_dict()
    pre = "model." if any(k.startswith("model.") for k in sd) else ""
    for i in range(cfg.num_hidden_layers):
        h = f"{pre}layers.{i}.self_attn."
        attn = params["decoder"][f"block_{i}"]["attn"]
        attn["q_norm"] = {"scale": _np(sd[h + "q_norm.weight"])}
        attn["k_norm"] = {"scale": _np(sd[h + "k_norm.weight"])}
    return model, params


def qwen3_to_hf(model, params):
    """A transformers Qwen3ForCausalLM carrying `params` — the inverse of
    `qwen3_from_hf`: the LLaMA-style state dict plus the per-layer
    q_norm/k_norm scales."""
    import transformers

    if (model.position != "rope" or model.norm != "rms"
            or model.mlp_act != "swiglu" or model.use_bias
            or model.qkv_bias or not model.qk_norm
            or model.embed_scale is not None or model.head_bias
            or model.norm_style != "pre" or model.rope_dim is not None
            or model.sliding_window is not None):
        raise NotImplementedError(
            "qwen3_to_hf requires the Qwen3 arrangement (LLaMA-style "
            "bias-free blocks with per-head q/k RMSNorm) — models without "
            "qk_norm export via llama_to_hf"
        )
    heads = model.num_heads
    hidden = model.hidden_size
    hd = model.head_dim or hidden // heads
    cfg = transformers.Qwen3Config(
        vocab_size=model.vocab_size, hidden_size=hidden,
        num_hidden_layers=model.depth, num_attention_heads=heads,
        num_key_value_heads=model.num_kv_heads or heads,
        intermediate_size=model.mlp_dim, head_dim=hd,
        max_position_embeddings=model.max_position,
        rope_theta=model.rope_theta,
        rope_scaling=_rope_scaling_dict(model.rope_scaling),
        rms_norm_eps=model.ln_eps,
        tie_word_embeddings=model.tie_embeddings,
        attention_bias=False, attention_dropout=0.0,
        use_sliding_window=False,
    )
    hf = transformers.Qwen3ForCausalLM(cfg)
    sd = _llama_style_sd(model, params)
    dec = params["decoder"]
    for i in range(model.depth):
        a = dec[f"block_{i}"]["attn"]
        h = f"model.layers.{i}.self_attn."
        sd[h + "q_norm.weight"] = _t(a["q_norm"]["scale"])
        sd[h + "k_norm.weight"] = _t(a["k_norm"]["scale"])
    missing, unexpected = hf.load_state_dict(sd, strict=False)
    missing = [k for k in missing if "rotary_emb" not in k]
    if missing or unexpected:
        raise RuntimeError(f"to_hf mapping drift: missing={missing} "
                           f"unexpected={list(unexpected)}")
    hf.eval()
    return hf


def phi_from_hf(hf_model, dtype=None) -> Tuple[object, dict]:
    """(GPT, params) from a transformers PhiForCausalLM.

    The Phi arrangement: PARALLEL blocks (one LayerNorm feeds attention
    and MLP side by side — `GPT(norm_style='parallel')`), partial rotary
    (`rope_dim = partial_rotary_factor * head_dim`), tanh-gelu MLP,
    biases everywhere including the untied lm_head (`head_bias=True`)."""
    import jax.numpy as jnp

    from tfde_tpu.models.gpt import GPT

    cfg = hf_model.config
    if getattr(cfg, "rope_scaling", None):
        raise NotImplementedError(
            f"rope_scaling={cfg.rope_scaling!r} is not supported; "
            f"converting would produce silently wrong logits — only plain "
            f"rope_theta Phi checkpoints convert today"
        )
    if bool(getattr(cfg, "qk_layernorm", False)):
        raise NotImplementedError(
            "qk_layernorm=True Phi checkpoints are not supported (the "
            "per-head q/k norms would be silently dropped)"
        )
    if getattr(cfg, "hidden_act", None) not in ("gelu_new", None):
        raise NotImplementedError(
            f"hidden_act {cfg.hidden_act!r} is not supported (expected "
            f"Phi's gelu_new, which our tanh-gelu Mlp matches exactly)"
        )
    heads = cfg.num_attention_heads
    hidden = cfg.hidden_size
    hd = hidden // heads
    kv = cfg.num_key_value_heads
    rope_dim = int(getattr(cfg, "partial_rotary_factor", 1.0) * hd)
    model = GPT(
        vocab_size=cfg.vocab_size,
        hidden_size=hidden,
        depth=cfg.num_hidden_layers,
        num_heads=heads,
        mlp_dim=cfg.intermediate_size,
        max_position=cfg.max_position_embeddings,
        dropout_rate=0.0,
        dtype=dtype if dtype is not None else jnp.bfloat16,
        position="rope",
        rope_theta=float(cfg.rope_theta),
        rope_dim=None if rope_dim == hd else rope_dim,
        num_kv_heads=kv,
        norm="layer",
        norm_style="parallel",
        mlp_act="gelu",
        use_bias=True,
        tie_embeddings=False,
        head_bias=True,
        ln_eps=cfg.layer_norm_eps,
    )
    sd = {k: _np(v) for k, v in hf_model.state_dict().items()}
    pre = "model." if any(k.startswith("model.") for k in sd) else ""
    params = {
        "wte": {"embedding": sd[f"{pre}embed_tokens.weight"]},
        "decoder": {
            "ln_final": {"scale": sd[f"{pre}final_layernorm.weight"],
                         "bias": sd[f"{pre}final_layernorm.bias"]},
        },
        "lm_head": {"kernel": sd["lm_head.weight"].T,
                    "bias": sd["lm_head.bias"]},
    }
    for i in range(cfg.num_hidden_layers):
        h = f"{pre}layers.{i}."
        params["decoder"][f"block_{i}"] = {
            # parallel blocks have ONE norm: input_layernorm -> ln_attn
            "ln_attn": {"scale": sd[h + "input_layernorm.weight"],
                        "bias": sd[h + "input_layernorm.bias"]},
            "attn": {
                "query": {"kernel": sd[h + "self_attn.q_proj.weight"].T
                          .reshape(hidden, heads, hd),
                          "bias": sd[h + "self_attn.q_proj.bias"]
                          .reshape(heads, hd)},
                "key": {"kernel": sd[h + "self_attn.k_proj.weight"].T
                        .reshape(hidden, kv, hd),
                        "bias": sd[h + "self_attn.k_proj.bias"]
                        .reshape(kv, hd)},
                "value": {"kernel": sd[h + "self_attn.v_proj.weight"].T
                          .reshape(hidden, kv, hd),
                          "bias": sd[h + "self_attn.v_proj.bias"]
                          .reshape(kv, hd)},
                "out": {"kernel": sd[h + "self_attn.dense.weight"].T
                        .reshape(heads, hd, hidden),
                        "bias": sd[h + "self_attn.dense.bias"]},
            },
            "mlp": {
                "fc1": {"kernel": sd[h + "mlp.fc1.weight"].T,
                        "bias": sd[h + "mlp.fc1.bias"]},
                "fc2": {"kernel": sd[h + "mlp.fc2.weight"].T,
                        "bias": sd[h + "mlp.fc2.bias"]},
            },
        }
    return model, params


def neox_from_hf(hf_model, dtype=None) -> Tuple[object, dict]:
    """(GPT, params) from a transformers GPTNeoXForCausalLM (the Pythia
    family).

    The NeoX arrangement: parallel residual with SEPARATE attention/MLP
    LayerNorms (`norm_style='parallel2'`; use_parallel_residual=False
    checkpoints map to plain 'pre'), 25%-partial rotary
    (`rope_dim = rotary_pct * head_dim`), biased projections, untied
    bias-free embed_out head. The fused query_key_value weight is
    PER-HEAD interleaved ([heads, 3, head_dim, hidden]) — de-interleaved
    here into the three projection kernels.

    Known approximation: NeoX runs exact erf-gelu; our Mlp uses the
    tanh approximation (~1e-3 activation delta, same as the BERT
    converter — the logit-match test bounds it)."""
    import jax.numpy as jnp

    from tfde_tpu.models.gpt import GPT

    cfg = hf_model.config
    if getattr(cfg, "rope_scaling", None):
        raise NotImplementedError(
            f"rope_scaling={cfg.rope_scaling!r} is not supported; only "
            f"plain rotary_emb_base checkpoints convert today"
        )
    if getattr(cfg, "hidden_act", "gelu") not in ("gelu", "gelu_new",
                                                  "gelu_pytorch_tanh"):
        raise NotImplementedError(
            f"hidden_act {cfg.hidden_act!r} is not supported (expected a "
            f"gelu variant)"
        )
    if not bool(getattr(cfg, "attention_bias", True)):
        raise NotImplementedError(
            "attention_bias=False NeoX checkpoints are not supported (the "
            "converter maps the biased arrangement every Pythia release "
            "ships)"
        )
    heads = cfg.num_attention_heads
    hidden = cfg.hidden_size
    hd = hidden // heads
    rope_dim = int(hd * cfg.rotary_pct)
    tied = bool(getattr(cfg, "tie_word_embeddings", False))
    if tied:
        raise NotImplementedError(
            "tied-embedding NeoX checkpoints are not supported (every "
            "Pythia release unties embed_out); the tied head would drop "
            "embed_out.weight silently"
        )
    model = GPT(
        vocab_size=cfg.vocab_size,
        hidden_size=hidden,
        depth=cfg.num_hidden_layers,
        num_heads=heads,
        mlp_dim=cfg.intermediate_size,
        max_position=cfg.max_position_embeddings,
        dropout_rate=0.0,
        dtype=dtype if dtype is not None else jnp.bfloat16,
        position="rope",
        rope_theta=float(getattr(cfg, "rotary_emb_base", 10_000.0)),
        rope_dim=None if rope_dim == hd else rope_dim,
        norm="layer",
        norm_style=("parallel2" if cfg.use_parallel_residual else "pre"),
        mlp_act="gelu",
        use_bias=True,
        tie_embeddings=False,
        ln_eps=cfg.layer_norm_eps,
    )
    sd = {k: _np(v) for k, v in hf_model.state_dict().items()}
    pre = "gpt_neox." if any(k.startswith("gpt_neox.") for k in sd) else ""
    if "embed_out.weight" not in sd:
        raise NotImplementedError(
            "pass a GPTNeoXForCausalLM (with its embed_out head); a bare "
            "GPTNeoXModel has no LM head to map"
        )
    params = {
        "wte": {"embedding": sd[f"{pre}embed_in.weight"]},
        "decoder": {
            "ln_final": {"scale": sd[f"{pre}final_layer_norm.weight"],
                         "bias": sd[f"{pre}final_layer_norm.bias"]},
        },
        "lm_head": {"kernel": sd["embed_out.weight"].T},
    }
    for i in range(cfg.num_hidden_layers):
        h = f"{pre}layers.{i}."
        # [3H, H] rows are per-head interleaved: head h's q, then k, then v
        qkv_w = sd[h + "attention.query_key_value.weight"].reshape(
            heads, 3, hd, hidden
        )
        qkv_b = sd[h + "attention.query_key_value.bias"].reshape(
            heads, 3, hd
        )

        def proj(j):
            # [heads, hd, hidden] -> in-major [hidden, heads, hd]
            return {"kernel": qkv_w[:, j].transpose(2, 0, 1),
                    "bias": qkv_b[:, j]}

        params["decoder"][f"block_{i}"] = {
            "ln_attn": {"scale": sd[h + "input_layernorm.weight"],
                        "bias": sd[h + "input_layernorm.bias"]},
            "ln_mlp": {"scale": sd[h + "post_attention_layernorm.weight"],
                       "bias": sd[h + "post_attention_layernorm.bias"]},
            "attn": {
                "query": proj(0),
                "key": proj(1),
                "value": proj(2),
                "out": {"kernel": sd[h + "attention.dense.weight"].T
                        .reshape(heads, hd, hidden),
                        "bias": sd[h + "attention.dense.bias"]},
            },
            "mlp": {
                "fc1": {"kernel": sd[h + "mlp.dense_h_to_4h.weight"].T,
                        "bias": sd[h + "mlp.dense_h_to_4h.bias"]},
                "fc2": {"kernel": sd[h + "mlp.dense_4h_to_h.weight"].T,
                        "bias": sd[h + "mlp.dense_4h_to_h.bias"]},
            },
        }
    return model, params


def bigcode_from_hf(hf_model, dtype=None) -> Tuple[object, dict]:
    """(GPT, params) from a transformers GPTBigCodeForCausalLM (the
    StarCoder family): the GPT-2 arrangement (learned positions,
    LayerNorm, tanh-gelu — exact for gelu_pytorch_tanh — tied head,
    biased projections) with MULTI-QUERY attention; the fused c_attn
    packs [q (H) | k (kv*hd) | v (kv*hd)] rows, split here into the
    three projection kernels."""
    import jax.numpy as jnp

    from tfde_tpu.models.gpt import GPT

    cfg = hf_model.config
    if not bool(getattr(cfg, "scale_attn_weights", True)):
        raise NotImplementedError(
            "scale_attn_weights=False checkpoints are not supported (our "
            "attention always scales by 1/sqrt(head_dim))"
        )
    if getattr(cfg, "activation_function",
               "gelu_pytorch_tanh") not in ("gelu_pytorch_tanh",
                                            "gelu_new"):
        # exact-erf 'gelu' would convert with a silent ~1e-3 drift; the
        # tanh variants match our Mlp exactly
        raise NotImplementedError(
            f"activation_function {cfg.activation_function!r} is not "
            f"supported (expected the tanh-gelu variants "
            f"gelu_pytorch_tanh/gelu_new, which our Mlp matches exactly)"
        )
    heads = cfg.n_head
    hidden = cfg.n_embd
    hd = hidden // heads
    kv = 1 if cfg.multi_query else heads
    mlp_dim = cfg.n_inner if cfg.n_inner is not None else 4 * hidden
    model = GPT(
        vocab_size=cfg.vocab_size,
        hidden_size=hidden,
        depth=cfg.n_layer,
        num_heads=heads,
        mlp_dim=mlp_dim,
        max_position=cfg.n_positions,
        dropout_rate=0.0,
        dtype=dtype if dtype is not None else jnp.bfloat16,
        num_kv_heads=kv,
        ln_eps=cfg.layer_norm_epsilon,
    )
    sd = {k: _np(v) for k, v in hf_model.state_dict().items()}
    pre = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
    params = {
        "wte": {"embedding": sd[f"{pre}wte.weight"]},
        "wpe": {"embedding": sd[f"{pre}wpe.weight"]},
        "decoder": {
            "ln_final": {"scale": sd[f"{pre}ln_f.weight"],
                         "bias": sd[f"{pre}ln_f.bias"]},
        },
    }
    for i in range(cfg.n_layer):
        h = f"{pre}h.{i}."
        # torch Linear [out, in] -> in-major, then split. The two fused
        # layouts differ: multi_query packs flat [Q (H) | K (hd) | V (hd)]
        # blocks; classic MHA interleaves PER HEAD ([q_h | k_h | v_h] for
        # each head — the .view(heads, 3*hd) split in the HF forward)
        w = sd[h + "attn.c_attn.weight"].T
        b = sd[h + "attn.c_attn.bias"]
        if cfg.multi_query:
            qw, kw, vw = np.split(w, [hidden, hidden + kv * hd], axis=1)
            qb, kb, vb = np.split(b, [hidden, hidden + kv * hd])
        else:
            w4 = w.reshape(hidden, heads, 3, hd)
            b3 = b.reshape(heads, 3, hd)
            qw, kw, vw = w4[:, :, 0], w4[:, :, 1], w4[:, :, 2]
            qb, kb, vb = b3[:, 0], b3[:, 1], b3[:, 2]
        params["decoder"][f"block_{i}"] = {
            "ln_attn": {"scale": sd[h + "ln_1.weight"],
                        "bias": sd[h + "ln_1.bias"]},
            "ln_mlp": {"scale": sd[h + "ln_2.weight"],
                       "bias": sd[h + "ln_2.bias"]},
            "attn": {
                "query": {"kernel": qw.reshape(hidden, heads, hd),
                          "bias": qb.reshape(heads, hd)},
                "key": {"kernel": kw.reshape(hidden, kv, hd),
                        "bias": kb.reshape(kv, hd)},
                "value": {"kernel": vw.reshape(hidden, kv, hd),
                          "bias": vb.reshape(kv, hd)},
                "out": {"kernel": sd[h + "attn.c_proj.weight"].T
                        .reshape(heads, hd, hidden),
                        "bias": sd[h + "attn.c_proj.bias"]},
            },
            "mlp": {
                "fc1": {"kernel": sd[h + "mlp.c_fc.weight"].T,
                        "bias": sd[h + "mlp.c_fc.bias"]},
                "fc2": {"kernel": sd[h + "mlp.c_proj.weight"].T,
                        "bias": sd[h + "mlp.c_proj.bias"]},
            },
        }
    return model, params


def opt_from_hf(hf_model, dtype=None) -> Tuple[object, dict]:
    """(GPT, params) from a transformers OPTForCausalLM.

    The OPT arrangement: pre-LN blocks, relu MLP, learned positions with
    the legacy offset-2 table — handled at conversion by SLICING the
    first two embedding rows off (position i uses HF row i+2; our
    0-based lookup then hits the identical vector, no model knob) —
    biased projections, tied head, final LayerNorm. Projected-embedding
    checkpoints (word_embed_proj_dim != hidden, e.g. opt-350m, which is
    also the only post-LN release) are refused."""
    import jax.numpy as jnp

    from tfde_tpu.models.gpt import GPT

    cfg = hf_model.config
    if cfg.word_embed_proj_dim != cfg.hidden_size:
        raise NotImplementedError(
            f"word_embed_proj_dim {cfg.word_embed_proj_dim} != hidden "
            f"{cfg.hidden_size}: projected-embedding OPT checkpoints "
            f"(opt-350m) are not supported"
        )
    if not bool(getattr(cfg, "do_layer_norm_before", True)):
        raise NotImplementedError(
            "do_layer_norm_before=False (post-LN OPT) is not supported"
        )
    if bool(getattr(cfg, "_remove_final_layer_norm", False)):
        raise NotImplementedError(
            "_remove_final_layer_norm=True (pre-release metaseq "
            "conversions) is not supported — the checkpoint has no "
            "final LayerNorm to map"
        )
    if not bool(getattr(cfg, "enable_bias", True)) or not bool(
            getattr(cfg, "layer_norm_elementwise_affine", True)):
        raise NotImplementedError(
            "bias-free / non-affine-LN OPT variants are not supported"
        )
    if getattr(cfg, "activation_function", "relu") != "relu":
        raise NotImplementedError(
            f"activation_function {cfg.activation_function!r} is not "
            f"supported (OPT releases use relu)"
        )
    if not bool(getattr(cfg, "tie_word_embeddings", True)):
        raise NotImplementedError(
            "untied OPT checkpoints are not supported (lm_head.weight "
            "would be silently dropped)"
        )
    heads = cfg.num_attention_heads
    hidden = cfg.hidden_size
    hd = hidden // heads
    model = GPT(
        vocab_size=cfg.vocab_size,
        hidden_size=hidden,
        depth=cfg.num_hidden_layers,
        num_heads=heads,
        mlp_dim=cfg.ffn_dim,
        max_position=cfg.max_position_embeddings,
        dropout_rate=0.0,
        dtype=dtype if dtype is not None else jnp.bfloat16,
        mlp_act="relu",
        tie_embeddings=True,
        ln_eps=1e-5,  # torch nn.LayerNorm default, what OPT runs
    )
    sd = {k: _np(v) for k, v in hf_model.state_dict().items()}
    pre = ("model.decoder."
           if any(k.startswith("model.decoder.") for k in sd)
           else "decoder." if any(k.startswith("decoder.") for k in sd)
           else "")
    params = {
        "wte": {"embedding": sd[f"{pre}embed_tokens.weight"]},
        # drop the legacy offset rows: HF looks up row i+2 for position i
        "wpe": {"embedding": sd[f"{pre}embed_positions.weight"][2:]},
        "decoder": {
            "ln_final": {"scale": sd[f"{pre}final_layer_norm.weight"],
                         "bias": sd[f"{pre}final_layer_norm.bias"]},
        },
    }
    for i in range(cfg.num_hidden_layers):
        h = f"{pre}layers.{i}."
        params["decoder"][f"block_{i}"] = {
            "ln_attn": {"scale": sd[h + "self_attn_layer_norm.weight"],
                        "bias": sd[h + "self_attn_layer_norm.bias"]},
            "ln_mlp": {"scale": sd[h + "final_layer_norm.weight"],
                       "bias": sd[h + "final_layer_norm.bias"]},
            "attn": {
                "query": {"kernel": sd[h + "self_attn.q_proj.weight"].T
                          .reshape(hidden, heads, hd),
                          "bias": sd[h + "self_attn.q_proj.bias"]
                          .reshape(heads, hd)},
                "key": {"kernel": sd[h + "self_attn.k_proj.weight"].T
                        .reshape(hidden, heads, hd),
                        "bias": sd[h + "self_attn.k_proj.bias"]
                        .reshape(heads, hd)},
                "value": {"kernel": sd[h + "self_attn.v_proj.weight"].T
                          .reshape(hidden, heads, hd),
                          "bias": sd[h + "self_attn.v_proj.bias"]
                          .reshape(heads, hd)},
                "out": {"kernel": sd[h + "self_attn.out_proj.weight"].T
                        .reshape(heads, hd, hidden),
                        "bias": sd[h + "self_attn.out_proj.bias"]},
            },
            "mlp": {
                "fc1": {"kernel": sd[h + "fc1.weight"].T,
                        "bias": sd[h + "fc1.bias"]},
                "fc2": {"kernel": sd[h + "fc2.weight"].T,
                        "bias": sd[h + "fc2.bias"]},
            },
        }
    return model, params


def bert_from_hf(hf_model, dtype=None) -> Tuple[object, dict]:
    """(Bert, params) from a transformers BertForMaskedLM (or BertModel —
    then the MLM head params initialize to the identity transform)."""
    import jax.numpy as jnp

    from tfde_tpu.models.bert import Bert

    cfg = hf_model.config
    heads = cfg.num_attention_heads
    hidden = cfg.hidden_size
    hd = hidden // heads
    model = Bert(
        vocab_size=cfg.vocab_size,
        hidden_size=hidden,
        depth=cfg.num_hidden_layers,
        num_heads=heads,
        mlp_dim=cfg.intermediate_size,
        max_position=cfg.max_position_embeddings,
        type_vocab_size=cfg.type_vocab_size,
        dropout_rate=0.0,
        pad_vocab=False,
        dtype=dtype if dtype is not None else jnp.bfloat16,
        ln_eps=cfg.layer_norm_eps,
    )
    sd = {k: _np(v) for k, v in hf_model.state_dict().items()}
    pre = "bert." if any(k.startswith("bert.") for k in sd) else ""

    def lin_kernel(name, shape):
        # torch.nn.Linear stores [out, in]; our kernels are in-major
        return sd[name].T.reshape(shape)

    params = {
        "embeddings": {
            "word": {"embedding": sd[f"{pre}embeddings.word_embeddings.weight"]},
            "position": {
                "embedding": sd[f"{pre}embeddings.position_embeddings.weight"]
            },
            "token_type": {
                "embedding": sd[f"{pre}embeddings.token_type_embeddings.weight"]
            },
            "ln": {"scale": sd[f"{pre}embeddings.LayerNorm.weight"],
                   "bias": sd[f"{pre}embeddings.LayerNorm.bias"]},
        },
        "encoder": {},
    }
    for i in range(cfg.num_hidden_layers):
        h = f"{pre}encoder.layer.{i}."
        params["encoder"][f"block_{i}"] = {
            "attn": {
                "query": {
                    "kernel": lin_kernel(h + "attention.self.query.weight",
                                         (hidden, heads, hd)),
                    "bias": sd[h + "attention.self.query.bias"].reshape(
                        heads, hd),
                },
                "key": {
                    "kernel": lin_kernel(h + "attention.self.key.weight",
                                         (hidden, heads, hd)),
                    "bias": sd[h + "attention.self.key.bias"].reshape(
                        heads, hd),
                },
                "value": {
                    "kernel": lin_kernel(h + "attention.self.value.weight",
                                         (hidden, heads, hd)),
                    "bias": sd[h + "attention.self.value.bias"].reshape(
                        heads, hd),
                },
                "out": {
                    "kernel": lin_kernel(h + "attention.output.dense.weight",
                                         (heads, hd, hidden)),
                    "bias": sd[h + "attention.output.dense.bias"],
                },
            },
            "ln_attn": {
                "scale": sd[h + "attention.output.LayerNorm.weight"],
                "bias": sd[h + "attention.output.LayerNorm.bias"],
            },
            "mlp": {
                "fc1": {"kernel": lin_kernel(h + "intermediate.dense.weight",
                                             (hidden, cfg.intermediate_size)),
                        "bias": sd[h + "intermediate.dense.bias"]},
                "fc2": {"kernel": lin_kernel(h + "output.dense.weight",
                                             (cfg.intermediate_size, hidden)),
                        "bias": sd[h + "output.dense.bias"]},
            },
            "ln_mlp": {"scale": sd[h + "output.LayerNorm.weight"],
                       "bias": sd[h + "output.LayerNorm.bias"]},
        }
    if "cls.predictions.transform.dense.weight" in sd:
        params["mlm_dense"] = {
            "kernel": sd["cls.predictions.transform.dense.weight"].T,
            "bias": sd["cls.predictions.transform.dense.bias"],
        }
        params["mlm_ln"] = {
            "scale": sd["cls.predictions.transform.LayerNorm.weight"],
            "bias": sd["cls.predictions.transform.LayerNorm.bias"],
        }
        params["mlm_bias"] = sd["cls.predictions.bias"]
    else:
        # bare BertModel: identity transform + zero bias keeps the MLM head
        # well-defined (logits = embeddings . hidden)
        params["mlm_dense"] = {"kernel": np.eye(hidden, dtype=np.float32),
                               "bias": np.zeros(hidden, np.float32)}
        params["mlm_ln"] = {"scale": np.ones(hidden, np.float32),
                            "bias": np.zeros(hidden, np.float32)}
        params["mlm_bias"] = np.zeros(cfg.vocab_size, np.float32)
    return model, params


def mixtral_from_hf(hf_model, dtype=None) -> Tuple[object, dict]:
    """(GPT, params) from a transformers MixtralForCausalLM — the routed
    sparse-MoE LLaMA: every layer's MLP is a top-k gated expert mixture
    (w1=gate, w3=up, w2=down per expert, silu-gated), attention/norms are
    the LLaMA arrangement.

    Maps to GPT(num_experts=E, moe_every=1, mlp_act='swiglu',
    use_bias=False) over models/moe.MoEMlp with experts_gate beside
    experts_fc1/fc2. Routing parity: both sides softmax the full router
    logits, take top-k, and renormalize the kept gates; Mixtral drops NO
    tokens, so conversion pins `moe_capacity_factor = E / k` — per-group
    capacity C = m (every token could route to one expert), making the
    converted forward exact at the cost of an O(m^2 E) dispatch one-hot.
    Fine-tuning configs can lower the factor; serving parity keeps it."""
    import jax.numpy as jnp

    from tfde_tpu.models.gpt import GPT

    cfg = hf_model.config
    heads = cfg.num_attention_heads
    hidden = cfg.hidden_size
    hd = getattr(cfg, "head_dim", None) or hidden // heads
    kv = cfg.num_key_value_heads
    e = cfg.num_local_experts
    k = cfg.num_experts_per_tok
    model = GPT(
        vocab_size=cfg.vocab_size,
        hidden_size=hidden,
        depth=cfg.num_hidden_layers,
        num_heads=heads,
        head_dim=None if hd == hidden // heads else hd,
        mlp_dim=cfg.intermediate_size,
        max_position=cfg.max_position_embeddings,
        dropout_rate=0.0,
        dtype=dtype if dtype is not None else jnp.bfloat16,
        position="rope",
        rope_theta=float(cfg.rope_theta),
        rope_scaling=_rope_scaling_tuple(
            getattr(cfg, "rope_scaling", None),
            max_position=cfg.max_position_embeddings,
        ),
        num_kv_heads=kv,
        use_bias=False,
        norm="rms",
        mlp_act="swiglu",
        num_experts=e,
        moe_every=1,
        experts_per_token=k,
        moe_capacity_factor=float(e) / k,
        sliding_window=getattr(cfg, "sliding_window", None),
        tie_embeddings=bool(getattr(cfg, "tie_word_embeddings", False)),
        ln_eps=cfg.rms_norm_eps,
    )
    sd = {k_: _np(v) for k_, v in hf_model.state_dict().items()}
    pre = "model." if any(k_.startswith("model.") for k_ in sd) else ""
    params = {
        "wte": {"embedding": sd[f"{pre}embed_tokens.weight"]},
        "decoder": {
            "ln_final": {"scale": sd[f"{pre}norm.weight"]},
        },
    }
    if not model.tie_embeddings:
        params["lm_head"] = {"kernel": sd["lm_head.weight"].T}
    for i in range(cfg.num_hidden_layers):
        h = f"{pre}layers.{i}."
        moe_pre = h + "block_sparse_moe."
        params["decoder"][f"block_{i}"] = {
            "ln_attn": {"scale": sd[h + "input_layernorm.weight"]},
            "ln_mlp": {"scale": sd[h + "post_attention_layernorm.weight"]},
            "attn": {
                "query": {"kernel": sd[h + "self_attn.q_proj.weight"].T
                          .reshape(hidden, heads, hd)},
                "key": {"kernel": sd[h + "self_attn.k_proj.weight"].T
                        .reshape(hidden, kv, hd)},
                "value": {"kernel": sd[h + "self_attn.v_proj.weight"].T
                          .reshape(hidden, kv, hd)},
                "out": {"kernel": sd[h + "self_attn.o_proj.weight"].T
                        .reshape(heads, hd, hidden)},
            },
            "moe": {
                "router": {"kernel": sd[moe_pre + "gate.weight"].T},
                # per-expert [f, d] torch Linears stack to [E, d, f]/[E, f, d]
                "experts_gate": np.stack(
                    [sd[moe_pre + f"experts.{j}.w1.weight"].T
                     for j in range(e)]
                ),
                "experts_fc1": np.stack(
                    [sd[moe_pre + f"experts.{j}.w3.weight"].T
                     for j in range(e)]
                ),
                "experts_fc2": np.stack(
                    [sd[moe_pre + f"experts.{j}.w2.weight"].T
                     for j in range(e)]
                ),
            },
        }
    return model, params


def mixtral_to_hf(model, params):
    """A transformers MixtralForCausalLM carrying `params` — the inverse
    of `mixtral_from_hf`: expert stacks unstack into per-expert w1/w2/w3
    Linears, the router transposes back to gate.weight."""
    import transformers

    e = model.num_experts
    k = model.experts_per_token
    if (model.position != "rope" or model.norm != "rms"
            or model.mlp_act != "swiglu" or model.use_bias
            or e <= 0 or model.moe_every != 1
            or getattr(model, "qk_norm", False)
            or model.qkv_bias or model.head_bias
            or model.embed_scale is not None
            or model.norm_style != "pre" or model.rope_dim is not None):
        raise NotImplementedError(
            "mixtral_to_hf requires the Mixtral arrangement (LLaMA-style "
            "attention/norms with every layer's MLP routed, bias-free "
            "swiglu experts) — dense models export via llama_to_hf"
        )
    if model.moe_capacity_factor < float(e) / k:
        # HF Mixtral has no capacity concept: it computes EVERY token. A
        # model fine-tuned with drops learned around them — exporting it
        # as drop-free would silently change its logits.
        raise NotImplementedError(
            f"moe_capacity_factor {model.moe_capacity_factor} < E/k = "
            f"{float(e) / k}: this model can drop overflow tokens, which "
            f"HF Mixtral (capacity-free) cannot express — raise the "
            f"factor to E/k (exact) before exporting"
        )
    heads = model.num_heads
    hidden = model.hidden_size
    hd = model.head_dim or hidden // heads
    kv = model.num_kv_heads or heads
    cfg = transformers.MixtralConfig(
        vocab_size=model.vocab_size, hidden_size=hidden,
        num_hidden_layers=model.depth, num_attention_heads=heads,
        num_key_value_heads=kv, intermediate_size=model.mlp_dim,
        num_local_experts=e, num_experts_per_tok=k, head_dim=hd,
        max_position_embeddings=model.max_position,
        rope_theta=model.rope_theta,
        rope_scaling=_rope_scaling_dict(model.rope_scaling),
        rms_norm_eps=model.ln_eps,
        sliding_window=model.sliding_window,
        tie_word_embeddings=model.tie_embeddings,
        attention_dropout=0.0, router_aux_loss_coef=0.0,
    )
    hf = transformers.MixtralForCausalLM(cfg)

    def moe_mlp_fn(sd, h, blk):
        moe = blk["moe"]
        moe_pre = h + "block_sparse_moe."
        sd[moe_pre + "gate.weight"] = _t(
            np.asarray(moe["router"]["kernel"]).T
        )
        gate_s = np.asarray(moe["experts_gate"])
        up_s = np.asarray(moe["experts_fc1"])
        down_s = np.asarray(moe["experts_fc2"])
        for j in range(e):
            sd[moe_pre + f"experts.{j}.w1.weight"] = _t(gate_s[j].T)
            sd[moe_pre + f"experts.{j}.w3.weight"] = _t(up_s[j].T)
            sd[moe_pre + f"experts.{j}.w2.weight"] = _t(down_s[j].T)

    sd = _llama_style_sd(model, params, mlp_fn=moe_mlp_fn)
    missing, unexpected = hf.load_state_dict(sd, strict=False)
    missing = [k for k in missing if "rotary_emb" not in k]
    if missing or unexpected:
        raise RuntimeError(f"to_hf mapping drift: missing={missing} "
                           f"unexpected={list(unexpected)}")
    hf.eval()
    return hf


def falcon_from_hf(hf_model, dtype=None) -> Tuple[object, dict]:
    """(GPT, params) from a transformers FalconForCausalLM.

    Three Falcon arrangements, all expressible with existing GPT knobs:
    the 7B shape (multi_query + parallel_attn: ONE LayerNorm feeds
    attention and MLP — `norm_style='parallel'`, kv=1), the 40B/180B
    shape (new_decoder_architecture: separate ln_attn/ln_mlp parallel
    residual — `norm_style='parallel2'`, grouped kv), and the sequential
    pre-LN shape (parallel_attn=False). All are rope + bias-free Linears
    beside biased LayerNorms (GPT(use_bias=False) keeps LN affine+bias —
    the Phi/NeoX convention this model zoo already relies on).

    The fused query_key_value weight unpacks per arrangement: the 40B
    form groups [g q-heads | k | v] per KV head; multi-query packs flat
    [Q (H) | k | v]; classic MHA interleaves per head. alibi checkpoints
    (falcon-rw) and bias=True Linears are refused — no GPT knob expresses
    them. Falcon's MLP runs erf-gelu; this framework's gelu is the tanh
    approximation — a documented ~1e-3 bounded logit delta, the same as
    bert_from_hf."""
    import jax.numpy as jnp

    from tfde_tpu.models.gpt import GPT

    cfg = hf_model.config
    if bool(getattr(cfg, "alibi", False)):
        raise NotImplementedError(
            "alibi Falcon checkpoints (falcon-rw) are not supported — "
            "the position machinery here is rope/learned, not alibi"
        )
    if bool(getattr(cfg, "bias", False)):
        raise NotImplementedError(
            "bias=True Falcon variants are not supported (the mainline "
            "7B/40B/180B releases are bias-free)"
        )
    if getattr(cfg, "rope_scaling", None):
        raise NotImplementedError(
            f"rope_scaling {cfg.rope_scaling!r} is not supported — "
            f"converting would silently apply unscaled rotary embeddings"
        )
    act = getattr(cfg, "activation", "gelu")
    if act not in ("gelu", "gelu_new", "gelu_pytorch_tanh"):
        raise NotImplementedError(
            f"activation {act!r} is not supported (Falcon releases use "
            f"gelu; converting would silently change the math)"
        )
    heads = cfg.num_attention_heads
    hidden = cfg.hidden_size
    hd = hidden // heads
    new_arch = bool(getattr(cfg, "new_decoder_architecture", False))
    # LN arrangement: the 40B/180B new-arch form carries TWO parallel LNs
    # (parallel2) UNLESS num_ln_in_parallel_attn == 1 (the Falcon2-11B
    # form: grouped kv but ONE shared LN — 'parallel'); pre-new-arch
    # models have one LN when parallel_attn, two sequential otherwise
    if new_arch:
        kv = cfg.num_kv_heads
        two_ln = getattr(cfg, "num_ln_in_parallel_attn", None) != 1
        norm_style = "parallel2" if two_ln else "parallel"
    else:
        kv = 1 if bool(getattr(cfg, "multi_query", True)) else heads
        norm_style = ("parallel" if getattr(cfg, "parallel_attn", True)
                      else "pre")
    model = GPT(
        vocab_size=cfg.vocab_size,
        hidden_size=hidden,
        depth=cfg.num_hidden_layers,
        num_heads=heads,
        mlp_dim=getattr(cfg, "ffn_hidden_size", None) or 4 * hidden,
        max_position=getattr(cfg, "max_position_embeddings", 2048),
        dropout_rate=0.0,
        dtype=dtype if dtype is not None else jnp.bfloat16,
        position="rope",
        rope_theta=float(getattr(cfg, "rope_theta", 10_000.0)),
        num_kv_heads=kv,
        use_bias=False,
        norm="layer",
        norm_style=norm_style,
        tie_embeddings=bool(getattr(cfg, "tie_word_embeddings", True)),
        ln_eps=cfg.layer_norm_epsilon,
    )
    sd = {k: _np(v) for k, v in hf_model.state_dict().items()}
    pre = ("transformer."
           if any(k.startswith("transformer.") for k in sd) else "")
    params = {
        "wte": {"embedding": sd[f"{pre}word_embeddings.weight"]},
        "decoder": {
            "ln_final": {"scale": sd[f"{pre}ln_f.weight"],
                         "bias": sd[f"{pre}ln_f.bias"]},
        },
    }
    if not model.tie_embeddings:
        params["lm_head"] = {"kernel": sd["lm_head.weight"].T}
    g = heads // kv
    for i in range(cfg.num_hidden_layers):
        h = f"{pre}h.{i}."
        w = sd[h + "self_attention.query_key_value.weight"].T  # [in, out]
        if new_arch:
            # [hidden, kv, g+2, hd]: per-KV-group [g q | k | v]
            w4 = w.reshape(hidden, kv, g + 2, hd)
            qw = w4[:, :, :g].reshape(hidden, heads, hd)
            kw = w4[:, :, g]
            vw = w4[:, :, g + 1]
        elif kv == 1:
            # flat [Q (H) | k (hd) | v (hd)]
            qw, kw, vw = np.split(w, [hidden, hidden + hd], axis=1)
            qw = qw.reshape(hidden, heads, hd)
            kw = kw.reshape(hidden, 1, hd)
            vw = vw.reshape(hidden, 1, hd)
        else:
            # classic MHA: per-head [q_h | k_h | v_h] interleave
            w4 = w.reshape(hidden, heads, 3, hd)
            qw, kw, vw = w4[:, :, 0], w4[:, :, 1], w4[:, :, 2]
        blk = {
            "attn": {
                "query": {"kernel": qw},
                "key": {"kernel": kw},
                "value": {"kernel": vw},
                "out": {"kernel": sd[h + "self_attention.dense.weight"].T
                        .reshape(heads, hd, hidden)},
            },
            "mlp": {
                "fc1": {"kernel": sd[h + "mlp.dense_h_to_4h.weight"].T},
                "fc2": {"kernel": sd[h + "mlp.dense_4h_to_h.weight"].T},
            },
        }
        if norm_style == "parallel2":
            blk["ln_attn"] = {"scale": sd[h + "ln_attn.weight"],
                              "bias": sd[h + "ln_attn.bias"]}
            blk["ln_mlp"] = {"scale": sd[h + "ln_mlp.weight"],
                             "bias": sd[h + "ln_mlp.bias"]}
        else:
            # 'parallel' (one LN — 7B and the new-arch Falcon2-11B form
            # alike) and 'pre' both read input_layernorm
            blk["ln_attn"] = {"scale": sd[h + "input_layernorm.weight"],
                              "bias": sd[h + "input_layernorm.bias"]}
            if norm_style == "pre":
                blk["ln_mlp"] = {
                    "scale": sd[h + "post_attention_layernorm.weight"],
                    "bias": sd[h + "post_attention_layernorm.bias"],
                }
        params["decoder"][f"block_{i}"] = blk
    return model, params


def t5_from_hf(hf_model, dtype=None) -> Tuple[object, dict]:
    """(T5, params) from a transformers T5ForConditionalGeneration.

    The T5 arrangement (models/t5.py): shared embedding, relative-position
    -bias attention (UNSCALED scores), T5-RMSNorm (plain w, no 1+ fold),
    bias-free projections with an inner attention dim decoupled from
    d_model, relu (v1.0) or gated tanh-gelu (v1.1) MLPs, tied head with
    the d_model^-0.5 logit rescale (v1.0) or an untied lm_head (v1.1).
    The per-stack shared bias table (HF stores it in block 0's attention;
    this model stores it at the stack level — the same single table) maps
    across directly."""
    import jax.numpy as jnp

    from tfde_tpu.models.t5 import T5

    cfg = hf_model.config
    gated = bool(getattr(cfg, "is_gated_act", False))
    act = getattr(cfg, "dense_act_fn", "relu")
    if gated:
        if act not in ("gelu_new", "gelu_pytorch_tanh"):
            raise NotImplementedError(
                f"gated dense_act_fn {act!r} is not supported (expected "
                f"the v1.1 tanh-gelu, which models/t5.py 'geglu' matches "
                f"exactly)"
            )
        mlp_act = "geglu"
    else:
        if act != "relu":
            raise NotImplementedError(
                f"dense_act_fn {act!r} is not supported (T5 v1.0 uses "
                f"relu)"
            )
        mlp_act = "relu"
    heads = cfg.num_heads
    model = T5(
        vocab_size=cfg.vocab_size,
        hidden_size=cfg.d_model,
        depth=cfg.num_layers,
        decoder_depth=cfg.num_decoder_layers,
        num_heads=heads,
        head_dim=cfg.d_kv,
        mlp_dim=cfg.d_ff,
        mlp_act=mlp_act,
        num_buckets=cfg.relative_attention_num_buckets,
        max_distance=getattr(cfg, "relative_attention_max_distance", 128),
        tie_embeddings=bool(cfg.tie_word_embeddings),
        dropout_rate=0.0,
        dtype=dtype if dtype is not None else jnp.bfloat16,
        ln_eps=cfg.layer_norm_epsilon,
        pad_id=cfg.pad_token_id,
    )
    hidden, hd = cfg.d_model, cfg.d_kv
    sd = {k: _np(v) for k, v in hf_model.state_dict().items()}
    params: dict = {"shared": {"embedding": sd["shared.weight"]}}
    if not model.tie_embeddings:
        params["lm_head"] = {"kernel": sd["lm_head.weight"].T}

    def attn_tree(pre: str) -> dict:
        return {
            "query": {"kernel": sd[pre + "q.weight"].T
                      .reshape(hidden, heads, hd)},
            "key": {"kernel": sd[pre + "k.weight"].T
                    .reshape(hidden, heads, hd)},
            "value": {"kernel": sd[pre + "v.weight"].T
                      .reshape(hidden, heads, hd)},
            "out": {"kernel": sd[pre + "o.weight"].T
                    .reshape(heads, hd, hidden)},
        }

    def mlp_tree(pre: str) -> dict:
        if gated:
            t = {"gate": {"kernel": sd[pre + "wi_0.weight"].T},
                 "fc1": {"kernel": sd[pre + "wi_1.weight"].T}}
        else:
            t = {"fc1": {"kernel": sd[pre + "wi.weight"].T}}
        t["fc2"] = {"kernel": sd[pre + "wo.weight"].T}
        return t

    for stack, n_layers, cross in (("encoder", cfg.num_layers, False),
                                   ("decoder", cfg.num_decoder_layers,
                                    True)):
        tree: dict = {
            "rel_bias": sd[
                f"{stack}.block.0.layer.0.SelfAttention"
                f".relative_attention_bias.weight"
            ],
            "ln_final": {
                "scale": sd[f"{stack}.final_layer_norm.weight"]
            },
        }
        mlp_layer = 2 if cross else 1
        for i in range(n_layers):
            h = f"{stack}.block.{i}."
            blk = {
                "ln_attn": {"scale": sd[h + "layer.0.layer_norm.weight"]},
                "attn": attn_tree(h + "layer.0.SelfAttention."),
                f"ln_mlp": {
                    "scale": sd[h + f"layer.{mlp_layer}.layer_norm.weight"]
                },
                "mlp": mlp_tree(h + f"layer.{mlp_layer}.DenseReluDense."),
            }
            if cross:
                blk["ln_cross"] = {
                    "scale": sd[h + "layer.1.layer_norm.weight"]
                }
                blk["cross_attn"] = attn_tree(h + "layer.1.EncDecAttention.")
            tree[f"block_{i}"] = blk
        params[stack] = tree
    return model, params


def bert_classifier_from_hf(hf_model, dtype=None) -> Tuple[object, dict]:
    """(BertClassifier, params) from a transformers
    BertForSequenceClassification — the fine-tuned-classifier import path.
    Delegates the encoder mapping to `bert_from_hf` (identical layout under
    the 'bert.' prefix) and adds the pooler + classification head."""
    import dataclasses

    from tfde_tpu.models.bert import BertClassifier

    cfg = hf_model.config
    bert, mlm_params = bert_from_hf(hf_model, dtype=dtype)
    # one cfg->constructor mapping site: rebuild from the Bert that
    # bert_from_hf returned, so the classifier config can never drift
    # from the encoder params grafted below
    shared = {
        f.name: getattr(bert, f.name)
        for f in dataclasses.fields(BertClassifier)
        if f.name not in ("parent", "name", "num_labels")
        and hasattr(bert, f.name)
    }
    model = BertClassifier(num_labels=cfg.num_labels, **shared)
    sd = hf_model.state_dict()
    params = {
        "embeddings": mlm_params["embeddings"],
        "encoder": mlm_params["encoder"],
        "pooler": {"kernel": _np(sd["bert.pooler.dense.weight"]).T,
                   "bias": _np(sd["bert.pooler.dense.bias"])},
        "classifier": {"kernel": _np(sd["classifier.weight"]).T,
                       "bias": _np(sd["classifier.bias"])},
    }
    return model, params


# --------------------------------------------------------------------------
# Reverse conversion: this framework's params -> transformers checkpoints.
# The OTHER half of the migration story: fine-tune here (full, LoRA-merged,
# distilled), deploy anywhere transformers runs. Exact inverses of the
# *_from_hf mappings above, verified by round-trip state-dict equality and
# logit matching (tests/test_convert.py).
# --------------------------------------------------------------------------


def _t(a) -> "object":
    import torch

    return torch.from_numpy(np.ascontiguousarray(np.asarray(a, np.float32)))


def gpt2_to_hf(model, params):
    """A transformers GPT2LMHeadModel carrying `params` — the inverse of
    `gpt2_from_hf`. Requires the GPT-2 arrangement (learned positions,
    gelu MLP, LayerNorm, tied head, biased projections)."""
    import transformers

    if (model.position != "learned" or model.norm != "layer"
            or model.mlp_act != "gelu" or not model.tie_embeddings
            or not model.use_bias or model.sliding_window is not None
            or model.head_dim is not None or model.embed_scale is not None
            or model.qkv_bias or model.head_bias
            or model.norm_style != "pre" or model.rope_dim is not None
            or (model.num_kv_heads not in (None, model.num_heads))):
        raise NotImplementedError(
            "gpt2_to_hf requires the GPT-2 arrangement (learned positions, "
            "LayerNorm, gelu, tied head, uniformly biased projections, "
            "classic MHA, unscaled embeddings, full causal attention) — "
            "other families export via llama_to_hf or stay native"
        )
    cfg = transformers.GPT2Config(
        vocab_size=model.vocab_size, n_embd=model.hidden_size,
        n_layer=model.depth, n_head=model.num_heads,
        n_inner=model.mlp_dim, n_positions=model.max_position,
        layer_norm_epsilon=model.ln_eps,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
    )
    hf = transformers.GPT2LMHeadModel(cfg)
    hidden = model.hidden_size
    sd = {}
    sd["transformer.wte.weight"] = _t(params["wte"]["embedding"])
    sd["transformer.wpe.weight"] = _t(params["wpe"]["embedding"])
    dec = params["decoder"]
    sd["transformer.ln_f.weight"] = _t(dec["ln_final"]["scale"])
    sd["transformer.ln_f.bias"] = _t(dec["ln_final"]["bias"])
    for i in range(model.depth):
        blk = dec[f"block_{i}"]
        h = f"transformer.h.{i}."
        sd[h + "ln_1.weight"] = _t(blk["ln_attn"]["scale"])
        sd[h + "ln_1.bias"] = _t(blk["ln_attn"]["bias"])
        sd[h + "ln_2.weight"] = _t(blk["ln_mlp"]["scale"])
        sd[h + "ln_2.bias"] = _t(blk["ln_mlp"]["bias"])
        a = blk["attn"]
        # Conv1D layout is [in, out]: stack q/k/v back into [H, 3H]
        c_attn_w = np.concatenate(
            [np.asarray(a[n]["kernel"]).reshape(hidden, hidden)
             for n in ("query", "key", "value")], axis=1,
        )
        c_attn_b = np.concatenate(
            [np.asarray(a[n]["bias"]).reshape(hidden)
             for n in ("query", "key", "value")]
        )
        sd[h + "attn.c_attn.weight"] = _t(c_attn_w)
        sd[h + "attn.c_attn.bias"] = _t(c_attn_b)
        sd[h + "attn.c_proj.weight"] = _t(
            np.asarray(a["out"]["kernel"]).reshape(hidden, hidden)
        )
        sd[h + "attn.c_proj.bias"] = _t(a["out"]["bias"])
        sd[h + "mlp.c_fc.weight"] = _t(blk["mlp"]["fc1"]["kernel"])
        sd[h + "mlp.c_fc.bias"] = _t(blk["mlp"]["fc1"]["bias"])
        sd[h + "mlp.c_proj.weight"] = _t(blk["mlp"]["fc2"]["kernel"])
        sd[h + "mlp.c_proj.bias"] = _t(blk["mlp"]["fc2"]["bias"])
    sd["lm_head.weight"] = sd["transformer.wte.weight"]
    missing, unexpected = hf.load_state_dict(sd, strict=False)
    # attn.bias buffers (causal masks) are regenerated by HF; everything
    # else must load
    missing = [k for k in missing if not k.endswith("attn.bias")
               and not k.endswith("attn.masked_bias")]
    unexpected = list(unexpected)
    if missing or unexpected:
        raise RuntimeError(f"to_hf mapping drift: missing={missing} "
                           f"unexpected={unexpected}")
    hf.eval()
    return hf


def _llama_style_sd(model, params, mlp_fn=None) -> dict:
    """The transformers state dict for a LLaMA-arranged decoder
    (model.layers.* keys) — shared by `llama_to_hf` (LLaMA/Mistral/Qwen2),
    `gemma_to_hf` (which un-folds the zero-centered norms on top), and
    `mixtral_to_hf` (which swaps the dense-MLP writer for the routed
    expert stacks via `mlp_fn(sd, layer_prefix, block_params)`)."""
    heads = model.num_heads
    hidden = model.hidden_size
    hd = model.head_dim or hidden // heads
    kv = model.num_kv_heads or heads
    sd = {}
    sd["model.embed_tokens.weight"] = _t(params["wte"]["embedding"])
    dec = params["decoder"]
    sd["model.norm.weight"] = _t(dec["ln_final"]["scale"])
    if not model.tie_embeddings:
        sd["lm_head.weight"] = _t(np.asarray(params["lm_head"]["kernel"]).T)
    else:
        sd["lm_head.weight"] = sd["model.embed_tokens.weight"]
    for i in range(model.depth):
        blk = dec[f"block_{i}"]
        h = f"model.layers.{i}."
        sd[h + "input_layernorm.weight"] = _t(blk["ln_attn"]["scale"])
        sd[h + "post_attention_layernorm.weight"] = _t(
            blk["ln_mlp"]["scale"]
        )
        a = blk["attn"]
        sd[h + "self_attn.q_proj.weight"] = _t(
            np.asarray(a["query"]["kernel"]).reshape(hidden, heads * hd).T
        )
        sd[h + "self_attn.k_proj.weight"] = _t(
            np.asarray(a["key"]["kernel"]).reshape(hidden, kv * hd).T
        )
        sd[h + "self_attn.v_proj.weight"] = _t(
            np.asarray(a["value"]["kernel"]).reshape(hidden, kv * hd).T
        )
        sd[h + "self_attn.o_proj.weight"] = _t(
            np.asarray(a["out"]["kernel"]).reshape(heads * hd, hidden).T
        )
        if model.qkv_bias:
            sd[h + "self_attn.q_proj.bias"] = _t(
                np.asarray(a["query"]["bias"]).reshape(heads * hd)
            )
            sd[h + "self_attn.k_proj.bias"] = _t(
                np.asarray(a["key"]["bias"]).reshape(kv * hd)
            )
            sd[h + "self_attn.v_proj.bias"] = _t(
                np.asarray(a["value"]["bias"]).reshape(kv * hd)
            )
        if mlp_fn is not None:
            mlp_fn(sd, h, blk)
        else:
            sd[h + "mlp.gate_proj.weight"] = _t(
                np.asarray(blk["mlp"]["gate"]["kernel"]).T
            )
            sd[h + "mlp.up_proj.weight"] = _t(
                np.asarray(blk["mlp"]["fc1"]["kernel"]).T
            )
            sd[h + "mlp.down_proj.weight"] = _t(
                np.asarray(blk["mlp"]["fc2"]["kernel"]).T
            )
    return sd


def llama_to_hf(model, params):
    """A transformers LlamaForCausalLM (or Qwen2 twin when
    model.qkv_bias) carrying `params` — the inverse of `llama_from_hf` /
    `qwen2_from_hf`. Mistral-style `sliding_window` models export as
    MistralForCausalLM with the window in the config."""
    import transformers

    if (model.position != "rope" or model.norm != "rms"
            or model.mlp_act != "swiglu" or model.use_bias
            or model.embed_scale is not None or model.head_bias
            or getattr(model, "qk_norm", False)
            or model.norm_style != "pre" or model.rope_dim is not None):
        raise NotImplementedError(
            "llama_to_hf requires the LLaMA arrangement (rope — full, not "
            "partial — RMSNorm, swiglu, bias-free pre-norm blocks, "
            "unscaled embeddings, bias-free head); Gemma/Phi-style models "
            "stay native"
        )
    heads = model.num_heads
    hidden = model.hidden_size
    hd = model.head_dim or hidden // heads
    kv = model.num_kv_heads or heads
    common = dict(
        vocab_size=model.vocab_size, hidden_size=hidden,
        num_hidden_layers=model.depth, num_attention_heads=heads,
        num_key_value_heads=kv, intermediate_size=model.mlp_dim,
        max_position_embeddings=model.max_position,
        rope_theta=model.rope_theta,
        rope_scaling=_rope_scaling_dict(model.rope_scaling),
        rms_norm_eps=model.ln_eps,
        tie_word_embeddings=model.tie_embeddings, attention_dropout=0.0,
    )
    if model.qkv_bias:
        if model.sliding_window is not None:
            raise NotImplementedError(
                "qkv_bias + sliding_window has no faithful transformers "
                "twin here (Qwen2 windows are per-layer) — exporting "
                "without the window would silently widen attention"
            )
        cfg = transformers.Qwen2Config(use_sliding_window=False,
                                       head_dim=hd, **common)
        hf = transformers.Qwen2ForCausalLM(cfg)
    elif model.sliding_window is not None:
        cfg = transformers.MistralConfig(
            sliding_window=int(model.sliding_window), head_dim=hd, **common
        )
        hf = transformers.MistralForCausalLM(cfg)
    else:
        cfg = transformers.LlamaConfig(head_dim=hd, **common)
        hf = transformers.LlamaForCausalLM(cfg)
    sd = _llama_style_sd(model, params)
    missing, unexpected = hf.load_state_dict(sd, strict=False)
    missing = [k for k in missing if "rotary_emb" not in k]
    if missing or unexpected:
        raise RuntimeError(f"to_hf mapping drift: missing={missing} "
                           f"unexpected={list(unexpected)}")
    hf.eval()
    return hf


def gemma_to_hf(model, params):
    """A transformers GemmaForCausalLM carrying `params` — the inverse of
    `gemma_from_hf`: the LLaMA-style state dict with the two Gemma folds
    undone — the stored RMSNorm scales carry the zero-centered `1 + w`
    fold, so the exported weights are `scale - 1` (the HF module computes
    `x * (1 + w)`); the sqrt(hidden) embedding scale and tanh-gelu gate
    are config-level and checked, not transformed."""
    import transformers

    if (model.position != "rope" or model.norm != "rms"
            or model.mlp_act != "geglu" or model.use_bias
            or not model.tie_embeddings or model.qkv_bias
            or getattr(model, "qk_norm", False)
            or model.head_bias or model.sliding_window is not None
            or model.norm_style != "pre" or model.rope_dim is not None
            or model.embed_scale is None
            or abs(model.embed_scale - model.hidden_size ** 0.5) > 1e-6):
        raise NotImplementedError(
            "gemma_to_hf requires the Gemma arrangement (full rope, "
            "RMSNorm, geglu, bias-free pre-norm blocks, tied head, "
            "sqrt(hidden)-scaled embeddings) — LLaMA-style models export "
            "via llama_to_hf"
        )
    heads = model.num_heads
    hidden = model.hidden_size
    hd = model.head_dim or hidden // heads
    cfg = transformers.GemmaConfig(
        vocab_size=model.vocab_size, hidden_size=hidden,
        num_hidden_layers=model.depth, num_attention_heads=heads,
        num_key_value_heads=model.num_kv_heads or heads,
        intermediate_size=model.mlp_dim, head_dim=hd,
        max_position_embeddings=model.max_position,
        rope_theta=model.rope_theta,
        # re-emit frequency scaling: dropping it would export unscaled
        # rope — silently wrong logits at long context
        rope_scaling=_rope_scaling_dict(model.rope_scaling),
        rms_norm_eps=model.ln_eps,
        tie_word_embeddings=True, attention_dropout=0.0,
        # our geglu gate IS the tanh approximation — the exact match
        hidden_activation="gelu_pytorch_tanh",
    )
    hf = transformers.GemmaForCausalLM(cfg)
    sd = _llama_style_sd(model, params)
    for k in list(sd):
        # un-fold 1+w on every RMSNorm scale (2 per layer + final)
        if k.endswith("layernorm.weight") or k == "model.norm.weight":
            sd[k] = sd[k] - 1.0
    missing, unexpected = hf.load_state_dict(sd, strict=False)
    missing = [k for k in missing if "rotary_emb" not in k]
    if missing or unexpected:
        raise RuntimeError(f"to_hf mapping drift: missing={missing} "
                           f"unexpected={list(unexpected)}")
    hf.eval()
    return hf


def phi_to_hf(model, params):
    """A transformers PhiForCausalLM carrying `params` — the inverse of
    `phi_from_hf` (parallel blocks, partial rotary, biased everything)."""
    import transformers

    if (model.position != "rope" or model.norm != "layer"
            or model.mlp_act != "gelu" or model.tie_embeddings
            or not model.use_bias or not model.head_bias
            or model.norm_style != "parallel"
            or model.sliding_window is not None
            or model.embed_scale is not None
            or model.head_dim is not None):
        raise NotImplementedError(
            "phi_to_hf requires the Phi arrangement (parallel blocks, "
            "LayerNorm, gelu, biased projections and head, untied) — "
            "other families export via gpt2_to_hf/llama_to_hf or stay "
            "native"
        )
    heads = model.num_heads
    hidden = model.hidden_size
    hd = hidden // heads  # head_dim is None past the guard
    kv = model.num_kv_heads or heads
    cfg = transformers.PhiConfig(
        vocab_size=model.vocab_size, hidden_size=hidden,
        num_hidden_layers=model.depth, num_attention_heads=heads,
        num_key_value_heads=kv, intermediate_size=model.mlp_dim,
        max_position_embeddings=model.max_position,
        rope_theta=model.rope_theta,
        partial_rotary_factor=(model.rope_dim or hd) / hd,
        layer_norm_eps=model.ln_eps, tie_word_embeddings=False,
        attention_dropout=0.0, embd_pdrop=0.0, resid_pdrop=0.0,
    )
    hf = transformers.PhiForCausalLM(cfg)
    sd = {}
    sd["model.embed_tokens.weight"] = _t(params["wte"]["embedding"])
    dec = params["decoder"]
    sd["model.final_layernorm.weight"] = _t(dec["ln_final"]["scale"])
    sd["model.final_layernorm.bias"] = _t(dec["ln_final"]["bias"])
    sd["lm_head.weight"] = _t(np.asarray(params["lm_head"]["kernel"]).T)
    sd["lm_head.bias"] = _t(params["lm_head"]["bias"])
    for i in range(model.depth):
        blk = dec[f"block_{i}"]
        h = f"model.layers.{i}."
        sd[h + "input_layernorm.weight"] = _t(blk["ln_attn"]["scale"])
        sd[h + "input_layernorm.bias"] = _t(blk["ln_attn"]["bias"])
        a = blk["attn"]
        for ours, theirs, n in (("query", "q_proj", heads),
                                ("key", "k_proj", kv),
                                ("value", "v_proj", kv)):
            sd[h + f"self_attn.{theirs}.weight"] = _t(
                np.asarray(a[ours]["kernel"]).reshape(hidden, n * hd).T
            )
            sd[h + f"self_attn.{theirs}.bias"] = _t(
                np.asarray(a[ours]["bias"]).reshape(n * hd)
            )
        sd[h + "self_attn.dense.weight"] = _t(
            np.asarray(a["out"]["kernel"]).reshape(heads * hd, hidden).T
        )
        sd[h + "self_attn.dense.bias"] = _t(a["out"]["bias"])
        sd[h + "mlp.fc1.weight"] = _t(np.asarray(blk["mlp"]["fc1"]["kernel"]).T)
        sd[h + "mlp.fc1.bias"] = _t(blk["mlp"]["fc1"]["bias"])
        sd[h + "mlp.fc2.weight"] = _t(np.asarray(blk["mlp"]["fc2"]["kernel"]).T)
        sd[h + "mlp.fc2.bias"] = _t(blk["mlp"]["fc2"]["bias"])
    missing, unexpected = hf.load_state_dict(sd, strict=False)
    missing = [k for k in missing if "rotary_emb" not in k]
    if missing or unexpected:
        raise RuntimeError(f"to_hf mapping drift: missing={missing} "
                           f"unexpected={list(unexpected)}")
    hf.eval()
    return hf


def neox_to_hf(model, params):
    """A transformers GPTNeoXForCausalLM carrying `params` — the inverse
    of `neox_from_hf`: the three projection kernels re-interleave into
    the per-head fused query_key_value weight."""
    import transformers

    if (model.position != "rope" or model.norm != "layer"
            or model.mlp_act != "gelu" or model.tie_embeddings
            or not model.use_bias or model.head_bias
            or model.norm_style not in ("parallel2", "pre")
            or model.sliding_window is not None
            or model.embed_scale is not None
            or model.head_dim is not None
            or (model.num_kv_heads not in (None, model.num_heads))):
        raise NotImplementedError(
            "neox_to_hf requires the NeoX arrangement (parallel2/pre "
            "blocks, LayerNorm, gelu, biased projections, untied "
            "bias-free head, MHA) — other families export via their own "
            "inverses or stay native"
        )
    heads = model.num_heads
    hidden = model.hidden_size
    hd = hidden // heads  # head_dim is None past the guard
    cfg = transformers.GPTNeoXConfig(
        vocab_size=model.vocab_size, hidden_size=hidden,
        num_hidden_layers=model.depth, num_attention_heads=heads,
        intermediate_size=model.mlp_dim,
        max_position_embeddings=model.max_position,
        rotary_emb_base=model.rope_theta,
        rotary_pct=(model.rope_dim or hd) / hd,
        use_parallel_residual=model.norm_style == "parallel2",
        layer_norm_eps=model.ln_eps, tie_word_embeddings=False,
        attention_dropout=0.0, hidden_dropout=0.0,
        # our Mlp 'gelu' IS the tanh approximation — export the matching
        # activation so round-trip logits stay exact (plain 'gelu' in HF
        # is the erf form, a silent ~1e-3 drift)
        hidden_act="gelu_pytorch_tanh",
    )
    hf = transformers.GPTNeoXForCausalLM(cfg)
    sd = {}
    sd["gpt_neox.embed_in.weight"] = _t(params["wte"]["embedding"])
    dec = params["decoder"]
    sd["gpt_neox.final_layer_norm.weight"] = _t(dec["ln_final"]["scale"])
    sd["gpt_neox.final_layer_norm.bias"] = _t(dec["ln_final"]["bias"])
    sd["embed_out.weight"] = _t(np.asarray(params["lm_head"]["kernel"]).T)
    for i in range(model.depth):
        blk = dec[f"block_{i}"]
        h = f"gpt_neox.layers.{i}."
        sd[h + "input_layernorm.weight"] = _t(blk["ln_attn"]["scale"])
        sd[h + "input_layernorm.bias"] = _t(blk["ln_attn"]["bias"])
        sd[h + "post_attention_layernorm.weight"] = _t(
            blk["ln_mlp"]["scale"]
        )
        sd[h + "post_attention_layernorm.bias"] = _t(blk["ln_mlp"]["bias"])
        a = blk["attn"]
        # [hidden, heads, hd] kernels -> per-head interleaved [3H, hidden]
        qkv_w = np.stack(
            [np.asarray(a[n]["kernel"]).transpose(1, 2, 0)
             for n in ("query", "key", "value")], axis=1,
        )  # [heads, 3, hd, hidden]
        qkv_b = np.stack(
            [np.asarray(a[n]["bias"]) for n in ("query", "key", "value")],
            axis=1,
        )  # [heads, 3, hd]
        sd[h + "attention.query_key_value.weight"] = _t(
            qkv_w.reshape(3 * hidden, hidden)
        )
        sd[h + "attention.query_key_value.bias"] = _t(
            qkv_b.reshape(3 * hidden)
        )
        sd[h + "attention.dense.weight"] = _t(
            np.asarray(a["out"]["kernel"]).reshape(heads * hd, hidden).T
        )
        sd[h + "attention.dense.bias"] = _t(a["out"]["bias"])
        sd[h + "mlp.dense_h_to_4h.weight"] = _t(
            np.asarray(blk["mlp"]["fc1"]["kernel"]).T
        )
        sd[h + "mlp.dense_h_to_4h.bias"] = _t(blk["mlp"]["fc1"]["bias"])
        sd[h + "mlp.dense_4h_to_h.weight"] = _t(
            np.asarray(blk["mlp"]["fc2"]["kernel"]).T
        )
        sd[h + "mlp.dense_4h_to_h.bias"] = _t(blk["mlp"]["fc2"]["bias"])
    missing, unexpected = hf.load_state_dict(sd, strict=False)
    missing = [k for k in missing if "rotary_emb" not in k
               and "attention.bias" not in k
               and "masked_bias" not in k]
    if missing or unexpected:
        raise RuntimeError(f"to_hf mapping drift: missing={missing} "
                           f"unexpected={list(unexpected)}")
    hf.eval()
    return hf


def bigcode_to_hf(model, params):
    """A transformers GPTBigCodeForCausalLM carrying `params` — the
    inverse of `bigcode_from_hf`: q/k/v kernels re-fuse into c_attn with
    the layout the HF forward expects (flat [Q|K|V] blocks under
    multi-query; per-head interleave under classic MHA)."""
    import transformers

    heads = model.num_heads
    kv = model.num_kv_heads or heads
    if (model.position != "learned" or model.norm != "layer"
            or model.mlp_act != "gelu" or not model.tie_embeddings
            or not model.use_bias or model.sliding_window is not None
            or model.head_dim is not None or model.embed_scale is not None
            or model.qkv_bias or model.head_bias
            or model.norm_style != "pre" or model.rope_dim is not None
            or kv not in (1, heads)):
        raise NotImplementedError(
            "bigcode_to_hf requires the StarCoder arrangement (learned "
            "positions, LayerNorm, gelu, tied head, biased projections, "
            "multi-query or classic MHA) — other families export via "
            "their own inverses or stay native"
        )
    hidden = model.hidden_size
    hd = hidden // heads
    multi_query = kv == 1 and heads > 1
    cfg = transformers.GPTBigCodeConfig(
        vocab_size=model.vocab_size, n_embd=hidden, n_layer=model.depth,
        n_head=heads, n_inner=model.mlp_dim,
        n_positions=model.max_position, multi_query=multi_query,
        layer_norm_epsilon=model.ln_eps, scale_attn_weights=True,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
        # our Mlp gelu IS the tanh approximation — exact for this export
        activation_function="gelu_pytorch_tanh",
    )
    hf = transformers.GPTBigCodeForCausalLM(cfg)
    sd = {}
    sd["transformer.wte.weight"] = _t(params["wte"]["embedding"])
    sd["transformer.wpe.weight"] = _t(params["wpe"]["embedding"])
    dec = params["decoder"]
    sd["transformer.ln_f.weight"] = _t(dec["ln_final"]["scale"])
    sd["transformer.ln_f.bias"] = _t(dec["ln_final"]["bias"])
    for i in range(model.depth):
        blk = dec[f"block_{i}"]
        h = f"transformer.h.{i}."
        sd[h + "ln_1.weight"] = _t(blk["ln_attn"]["scale"])
        sd[h + "ln_1.bias"] = _t(blk["ln_attn"]["bias"])
        sd[h + "ln_2.weight"] = _t(blk["ln_mlp"]["scale"])
        sd[h + "ln_2.bias"] = _t(blk["ln_mlp"]["bias"])
        a = blk["attn"]
        qw = np.asarray(a["query"]["kernel"])   # [hidden, heads, hd]
        kw = np.asarray(a["key"]["kernel"])     # [hidden, kv, hd]
        vw = np.asarray(a["value"]["kernel"])
        qb = np.asarray(a["query"]["bias"])     # [heads, hd]
        kb = np.asarray(a["key"]["bias"])       # [kv, hd]
        vb = np.asarray(a["value"]["bias"])
        if multi_query:
            # flat [Q (H) | K (hd) | V (hd)] rows, exactly the split
            # bigcode_from_hf undoes
            w = np.concatenate(
                [qw.reshape(hidden, hidden), kw.reshape(hidden, kv * hd),
                 vw.reshape(hidden, kv * hd)], axis=1,
            )
            b = np.concatenate(
                [qb.reshape(hidden), kb.reshape(kv * hd),
                 vb.reshape(kv * hd)]
            )
        else:
            # classic MHA interleaves per head: [q_h | k_h | v_h] each head
            w = np.stack([qw, kw, vw], axis=2).reshape(hidden, 3 * hidden)
            b = np.stack([qb, kb, vb], axis=1).reshape(3 * hidden)
        sd[h + "attn.c_attn.weight"] = _t(w.T)
        sd[h + "attn.c_attn.bias"] = _t(b)
        sd[h + "attn.c_proj.weight"] = _t(
            np.asarray(a["out"]["kernel"]).reshape(heads * hd, hidden).T
        )
        sd[h + "attn.c_proj.bias"] = _t(a["out"]["bias"])
        sd[h + "mlp.c_fc.weight"] = _t(
            np.asarray(blk["mlp"]["fc1"]["kernel"]).T
        )
        sd[h + "mlp.c_fc.bias"] = _t(blk["mlp"]["fc1"]["bias"])
        sd[h + "mlp.c_proj.weight"] = _t(
            np.asarray(blk["mlp"]["fc2"]["kernel"]).T
        )
        sd[h + "mlp.c_proj.bias"] = _t(blk["mlp"]["fc2"]["bias"])
    sd["lm_head.weight"] = sd["transformer.wte.weight"]
    missing, unexpected = hf.load_state_dict(sd, strict=False)
    # '.attn.bias' (with the dot) is the causal-mask buffer ONLY — a bare
    # 'attn.bias' suffix would also swallow the real c_attn.bias weight
    missing = [k for k in missing if not k.endswith(".attn.bias")
               and not k.endswith("masked_bias")]
    if missing or unexpected:
        raise RuntimeError(f"to_hf mapping drift: missing={missing} "
                           f"unexpected={list(unexpected)}")
    hf.eval()
    return hf


def opt_to_hf(model, params):
    """A transformers OPTForCausalLM carrying `params` — the inverse of
    `opt_from_hf`. The legacy offset-2 position table is rebuilt by
    PREPENDING two zero rows (opt_from_hf sliced the originals off; HF
    only reaches rows 0-1 for left-padded positions, which attention
    masks exclude — unpadded logits are exact)."""
    import transformers

    heads = model.num_heads
    hidden = model.hidden_size
    hd = hidden // heads
    if (model.position != "learned" or model.norm != "layer"
            or model.mlp_act != "relu" or not model.tie_embeddings
            or not model.use_bias or model.sliding_window is not None
            or model.head_dim is not None or model.embed_scale is not None
            or model.qkv_bias or model.head_bias
            or model.norm_style != "pre" or model.rope_dim is not None
            or (model.num_kv_heads not in (None, heads))
            or abs(model.ln_eps - 1e-5) > 1e-12):
        raise NotImplementedError(
            "opt_to_hf requires the OPT arrangement (learned positions, "
            "pre-LN with eps 1e-5, relu MLP, tied head, biased "
            "projections, classic MHA) — other families export via their "
            "own inverses or stay native"
        )
    cfg = transformers.OPTConfig(
        vocab_size=model.vocab_size, hidden_size=hidden,
        num_hidden_layers=model.depth, num_attention_heads=heads,
        ffn_dim=model.mlp_dim, max_position_embeddings=model.max_position,
        word_embed_proj_dim=hidden, do_layer_norm_before=True,
        activation_function="relu", tie_word_embeddings=True,
        dropout=0.0, attention_dropout=0.0, enable_bias=True,
        layer_norm_elementwise_affine=True,
    )
    hf = transformers.OPTForCausalLM(cfg)
    sd = {}
    pre = "model.decoder."
    sd[pre + "embed_tokens.weight"] = _t(params["wte"]["embedding"])
    wpe = np.asarray(params["wpe"]["embedding"], np.float32)
    sd[pre + "embed_positions.weight"] = _t(
        np.concatenate([np.zeros((2, hidden), np.float32), wpe], axis=0)
    )
    dec = params["decoder"]
    sd[pre + "final_layer_norm.weight"] = _t(dec["ln_final"]["scale"])
    sd[pre + "final_layer_norm.bias"] = _t(dec["ln_final"]["bias"])
    for i in range(model.depth):
        blk = dec[f"block_{i}"]
        h = f"{pre}layers.{i}."
        sd[h + "self_attn_layer_norm.weight"] = _t(blk["ln_attn"]["scale"])
        sd[h + "self_attn_layer_norm.bias"] = _t(blk["ln_attn"]["bias"])
        sd[h + "final_layer_norm.weight"] = _t(blk["ln_mlp"]["scale"])
        sd[h + "final_layer_norm.bias"] = _t(blk["ln_mlp"]["bias"])
        a = blk["attn"]
        for ours, theirs in (("query", "q_proj"), ("key", "k_proj"),
                             ("value", "v_proj")):
            sd[h + f"self_attn.{theirs}.weight"] = _t(
                np.asarray(a[ours]["kernel"]).reshape(hidden, hidden).T
            )
            sd[h + f"self_attn.{theirs}.bias"] = _t(
                np.asarray(a[ours]["bias"]).reshape(hidden)
            )
        sd[h + "self_attn.out_proj.weight"] = _t(
            np.asarray(a["out"]["kernel"]).reshape(heads * hd, hidden).T
        )
        sd[h + "self_attn.out_proj.bias"] = _t(a["out"]["bias"])
        sd[h + "fc1.weight"] = _t(np.asarray(blk["mlp"]["fc1"]["kernel"]).T)
        sd[h + "fc1.bias"] = _t(blk["mlp"]["fc1"]["bias"])
        sd[h + "fc2.weight"] = _t(np.asarray(blk["mlp"]["fc2"]["kernel"]).T)
        sd[h + "fc2.bias"] = _t(blk["mlp"]["fc2"]["bias"])
    sd["lm_head.weight"] = sd[pre + "embed_tokens.weight"]
    missing, unexpected = hf.load_state_dict(sd, strict=False)
    if missing or unexpected:
        raise RuntimeError(f"to_hf mapping drift: missing={missing} "
                           f"unexpected={list(unexpected)}")
    hf.eval()
    return hf


def _bert_encoder_sd(model, params, pre: str) -> dict:
    """The transformers embeddings+encoder state dict (under prefix `pre`)
    for a converted Bert/BertClassifier — the shared inverse of the
    encoder mapping in `bert_from_hf`."""
    heads = model.num_heads
    hidden = model.hidden_size
    hd = hidden // heads
    emb = params["embeddings"]
    sd = {
        pre + "embeddings.word_embeddings.weight":
            _t(emb["word"]["embedding"]),
        pre + "embeddings.position_embeddings.weight":
            _t(emb["position"]["embedding"]),
        pre + "embeddings.token_type_embeddings.weight":
            _t(emb["token_type"]["embedding"]),
        pre + "embeddings.LayerNorm.weight": _t(emb["ln"]["scale"]),
        pre + "embeddings.LayerNorm.bias": _t(emb["ln"]["bias"]),
    }
    for i in range(model.depth):
        blk = params["encoder"][f"block_{i}"]
        h = f"{pre}encoder.layer.{i}."
        a = blk["attn"]
        for ours, theirs in (("query", "attention.self.query"),
                             ("key", "attention.self.key"),
                             ("value", "attention.self.value")):
            sd[h + theirs + ".weight"] = _t(
                np.asarray(a[ours]["kernel"]).reshape(hidden, hidden).T
            )
            sd[h + theirs + ".bias"] = _t(
                np.asarray(a[ours]["bias"]).reshape(hidden)
            )
        sd[h + "attention.output.dense.weight"] = _t(
            np.asarray(a["out"]["kernel"]).reshape(heads * hd, hidden).T
        )
        sd[h + "attention.output.dense.bias"] = _t(a["out"]["bias"])
        sd[h + "attention.output.LayerNorm.weight"] = _t(
            blk["ln_attn"]["scale"]
        )
        sd[h + "attention.output.LayerNorm.bias"] = _t(
            blk["ln_attn"]["bias"]
        )
        sd[h + "intermediate.dense.weight"] = _t(
            np.asarray(blk["mlp"]["fc1"]["kernel"]).T
        )
        sd[h + "intermediate.dense.bias"] = _t(blk["mlp"]["fc1"]["bias"])
        sd[h + "output.dense.weight"] = _t(
            np.asarray(blk["mlp"]["fc2"]["kernel"]).T
        )
        sd[h + "output.dense.bias"] = _t(blk["mlp"]["fc2"]["bias"])
        sd[h + "output.LayerNorm.weight"] = _t(blk["ln_mlp"]["scale"])
        sd[h + "output.LayerNorm.bias"] = _t(blk["ln_mlp"]["bias"])
    return sd


def _bert_config(model, **extra):
    import transformers

    return transformers.BertConfig(
        vocab_size=model.vocab_size, hidden_size=model.hidden_size,
        num_hidden_layers=model.depth,
        num_attention_heads=model.num_heads,
        intermediate_size=model.mlp_dim,
        max_position_embeddings=model.max_position,
        type_vocab_size=model.type_vocab_size,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        layer_norm_eps=model.ln_eps,
        # our encoder's gelu is the tanh approximation; exporting the
        # matching activation keeps native-vs-exported logits exact (a
        # checkpoint imported from erf-gelu BERT re-exports with ~1e-3
        # drift vs its origin — the same delta bert_from_hf documents)
        hidden_act="gelu_pytorch_tanh",
        **extra,
    )


def _check_bert_exportable(model, fn: str) -> None:
    if getattr(model, "pad_vocab", False) or getattr(model, "fused_qkv",
                                                     False):
        raise NotImplementedError(
            f"{fn} requires the transformers-compatible arrangement "
            f"(pad_vocab=False — a padded vocab widens the logit table — "
            f"and unfused per-projection qkv kernels)"
        )


def bert_to_hf(model, params):
    """A transformers BertForMaskedLM carrying `params` — the inverse of
    `bert_from_hf` (encoder + MLM transform head, tied decoder)."""
    import transformers

    _check_bert_exportable(model, "bert_to_hf")
    sd = _bert_encoder_sd(model, params, "bert.")
    sd["cls.predictions.transform.dense.weight"] = _t(
        np.asarray(params["mlm_dense"]["kernel"]).T
    )
    sd["cls.predictions.transform.dense.bias"] = _t(
        params["mlm_dense"]["bias"]
    )
    sd["cls.predictions.transform.LayerNorm.weight"] = _t(
        params["mlm_ln"]["scale"]
    )
    sd["cls.predictions.transform.LayerNorm.bias"] = _t(
        params["mlm_ln"]["bias"]
    )
    sd["cls.predictions.bias"] = _t(params["mlm_bias"])
    sd["cls.predictions.decoder.weight"] = sd[
        "bert.embeddings.word_embeddings.weight"
    ]
    sd["cls.predictions.decoder.bias"] = sd["cls.predictions.bias"]
    hf = transformers.BertForMaskedLM(_bert_config(model))
    missing, unexpected = hf.load_state_dict(sd, strict=False)
    missing = [k for k in missing if "position_ids" not in k
               and "token_type_ids" not in k]
    if missing or unexpected:
        raise RuntimeError(f"to_hf mapping drift: missing={missing} "
                           f"unexpected={list(unexpected)}")
    hf.eval()
    return hf


def bert_classifier_to_hf(model, params):
    """A transformers BertForSequenceClassification carrying `params` —
    the inverse of `bert_classifier_from_hf` (encoder + pooler +
    classification head)."""
    import transformers

    _check_bert_exportable(model, "bert_classifier_to_hf")
    sd = _bert_encoder_sd(model, params, "bert.")
    sd["bert.pooler.dense.weight"] = _t(
        np.asarray(params["pooler"]["kernel"]).T
    )
    sd["bert.pooler.dense.bias"] = _t(params["pooler"]["bias"])
    sd["classifier.weight"] = _t(
        np.asarray(params["classifier"]["kernel"]).T
    )
    sd["classifier.bias"] = _t(params["classifier"]["bias"])
    hf = transformers.BertForSequenceClassification(
        _bert_config(model, num_labels=model.num_labels)
    )
    missing, unexpected = hf.load_state_dict(sd, strict=False)
    missing = [k for k in missing if "position_ids" not in k
               and "token_type_ids" not in k]
    if missing or unexpected:
        raise RuntimeError(f"to_hf mapping drift: missing={missing} "
                           f"unexpected={list(unexpected)}")
    hf.eval()
    return hf


def falcon_to_hf(model, params):
    """A transformers FalconForCausalLM carrying `params` — the inverse of
    `falcon_from_hf`: q/k/v kernels re-fuse into query_key_value per
    arrangement (grouped 40B form, flat multi-query, per-head MHA)."""
    import transformers

    heads = model.num_heads
    kv = model.num_kv_heads or heads
    if (model.position != "rope" or model.norm != "layer"
            or model.mlp_act != "gelu" or model.use_bias
            or model.qkv_bias or model.head_bias
            or model.sliding_window is not None
            or model.head_dim is not None or model.embed_scale is not None
            or model.rope_dim is not None
            or model.norm_style not in ("parallel", "parallel2", "pre")):
        raise NotImplementedError(
            "falcon_to_hf requires the Falcon arrangement (full rope, "
            "biased LayerNorms beside bias-free projections, gelu MLP, "
            "parallel/parallel2/pre blocks) — other families export via "
            "their own inverses or stay native"
        )
    hidden = model.hidden_size
    hd = hidden // heads
    # arrangement: parallel2 -> the 40B two-LN new arch; parallel with
    # grouped kv -> the Falcon2-11B new arch with ONE LN
    # (num_ln_in_parallel_attn=1); parallel/pre with kv in (1, heads) ->
    # the pre-new-arch forms
    new_arch = (model.norm_style == "parallel2"
                or (model.norm_style == "parallel"
                    and kv not in (1, heads)))
    if model.norm_style == "pre" and kv not in (1, heads):
        raise NotImplementedError(
            "grouped kv with sequential pre-LN blocks has no Falcon twin"
        )
    cfg = transformers.FalconConfig(
        vocab_size=model.vocab_size, hidden_size=hidden,
        num_hidden_layers=model.depth, num_attention_heads=heads,
        num_kv_heads=kv, new_decoder_architecture=new_arch,
        multi_query=(not new_arch and kv == 1),
        parallel_attn=model.norm_style != "pre",
        num_ln_in_parallel_attn=(
            1 if new_arch and model.norm_style == "parallel" else None
        ),
        alibi=False, bias=False,
        layer_norm_epsilon=model.ln_eps,
        rope_theta=model.rope_theta,
        max_position_embeddings=model.max_position,
        tie_word_embeddings=model.tie_embeddings,
        ffn_hidden_size=model.mlp_dim,
        hidden_dropout=0.0, attention_dropout=0.0,
    )
    hf = transformers.FalconForCausalLM(cfg)
    sd = {}
    pre = "transformer."
    sd[pre + "word_embeddings.weight"] = _t(params["wte"]["embedding"])
    dec = params["decoder"]
    sd[pre + "ln_f.weight"] = _t(dec["ln_final"]["scale"])
    sd[pre + "ln_f.bias"] = _t(dec["ln_final"]["bias"])
    sd["lm_head.weight"] = (
        _t(np.asarray(params["lm_head"]["kernel"]).T)
        if not model.tie_embeddings
        else sd[pre + "word_embeddings.weight"]
    )
    g = heads // kv
    for i in range(model.depth):
        blk = dec[f"block_{i}"]
        h = f"{pre}h.{i}."
        a = blk["attn"]
        qw = np.asarray(a["query"]["kernel"])   # [hidden, heads, hd]
        kw = np.asarray(a["key"]["kernel"])     # [hidden, kv, hd]
        vw = np.asarray(a["value"]["kernel"])
        if new_arch:
            w4 = np.concatenate(
                [qw.reshape(hidden, kv, g, hd), kw[:, :, None],
                 vw[:, :, None]], axis=2,
            )  # [hidden, kv, g+2, hd]
            w = w4.reshape(hidden, (kv * (g + 2)) * hd)
        elif kv == 1:
            w = np.concatenate(
                [qw.reshape(hidden, hidden), kw.reshape(hidden, hd),
                 vw.reshape(hidden, hd)], axis=1,
            )
        else:
            w = np.stack([qw, kw, vw], axis=2).reshape(hidden, 3 * hidden)
        sd[h + "self_attention.query_key_value.weight"] = _t(w.T)
        sd[h + "self_attention.dense.weight"] = _t(
            np.asarray(a["out"]["kernel"]).reshape(heads * hd, hidden).T
        )
        sd[h + "mlp.dense_h_to_4h.weight"] = _t(
            np.asarray(blk["mlp"]["fc1"]["kernel"]).T
        )
        sd[h + "mlp.dense_4h_to_h.weight"] = _t(
            np.asarray(blk["mlp"]["fc2"]["kernel"]).T
        )
        if model.norm_style == "parallel2":
            sd[h + "ln_attn.weight"] = _t(blk["ln_attn"]["scale"])
            sd[h + "ln_attn.bias"] = _t(blk["ln_attn"]["bias"])
            sd[h + "ln_mlp.weight"] = _t(blk["ln_mlp"]["scale"])
            sd[h + "ln_mlp.bias"] = _t(blk["ln_mlp"]["bias"])
        else:
            # one LN: 'parallel' (incl. the new-arch 11B form) and 'pre'
            sd[h + "input_layernorm.weight"] = _t(blk["ln_attn"]["scale"])
            sd[h + "input_layernorm.bias"] = _t(blk["ln_attn"]["bias"])
            if model.norm_style == "pre":
                sd[h + "post_attention_layernorm.weight"] = _t(
                    blk["ln_mlp"]["scale"]
                )
                sd[h + "post_attention_layernorm.bias"] = _t(
                    blk["ln_mlp"]["bias"]
                )
    missing, unexpected = hf.load_state_dict(sd, strict=False)
    missing = [k for k in missing if "rotary_emb" not in k]
    if missing or unexpected:
        raise RuntimeError(f"to_hf mapping drift: missing={missing} "
                           f"unexpected={list(unexpected)}")
    hf.eval()
    return hf


def t5_to_hf(model, params):
    """A transformers T5ForConditionalGeneration carrying `params` — the
    inverse of `t5_from_hf` (per-stack bias table back into block 0's
    attention, kernels back to [out, in])."""
    import transformers

    if model.mlp_act not in ("relu", "geglu"):
        raise NotImplementedError(
            "t5_to_hf requires the T5 arrangement (relu v1.0 or gated "
            "tanh-gelu v1.1 MLPs) — other activations stay native"
        )
    gated = model.mlp_act == "geglu"
    cfg = transformers.T5Config(
        vocab_size=model.vocab_size, d_model=model.hidden_size,
        d_kv=model.head_dim, d_ff=model.mlp_dim,
        num_layers=model.depth,
        num_decoder_layers=model.decoder_depth or model.depth,
        num_heads=model.num_heads,
        relative_attention_num_buckets=model.num_buckets,
        relative_attention_max_distance=model.max_distance,
        dropout_rate=0.0, layer_norm_epsilon=model.ln_eps,
        feed_forward_proj="gated-gelu" if gated else "relu",
        tie_word_embeddings=model.tie_embeddings,
        pad_token_id=model.pad_id, decoder_start_token_id=model.pad_id,
    )
    hf = transformers.T5ForConditionalGeneration(cfg)
    heads, hd = model.num_heads, model.head_dim
    hidden = model.hidden_size
    sd = {}
    sd["shared.weight"] = _t(params["shared"]["embedding"])
    sd["encoder.embed_tokens.weight"] = sd["shared.weight"]
    sd["decoder.embed_tokens.weight"] = sd["shared.weight"]
    sd["lm_head.weight"] = (
        _t(np.asarray(params["lm_head"]["kernel"]).T)
        if not model.tie_embeddings else sd["shared.weight"]
    )

    def put_attn(pre: str, a: dict) -> None:
        sd[pre + "q.weight"] = _t(
            np.asarray(a["query"]["kernel"]).reshape(hidden, heads * hd).T
        )
        sd[pre + "k.weight"] = _t(
            np.asarray(a["key"]["kernel"]).reshape(hidden, heads * hd).T
        )
        sd[pre + "v.weight"] = _t(
            np.asarray(a["value"]["kernel"]).reshape(hidden, heads * hd).T
        )
        sd[pre + "o.weight"] = _t(
            np.asarray(a["out"]["kernel"]).reshape(heads * hd, hidden).T
        )

    def put_mlp(pre: str, m: dict) -> None:
        if gated:
            sd[pre + "wi_0.weight"] = _t(np.asarray(m["gate"]["kernel"]).T)
            sd[pre + "wi_1.weight"] = _t(np.asarray(m["fc1"]["kernel"]).T)
        else:
            sd[pre + "wi.weight"] = _t(np.asarray(m["fc1"]["kernel"]).T)
        sd[pre + "wo.weight"] = _t(np.asarray(m["fc2"]["kernel"]).T)

    for stack, n_layers, cross in (
        ("encoder", model.depth, False),
        ("decoder", model.decoder_depth or model.depth, True),
    ):
        tree = params[stack]
        sd[f"{stack}.final_layer_norm.weight"] = _t(
            tree["ln_final"]["scale"]
        )
        sd[f"{stack}.block.0.layer.0.SelfAttention"
           f".relative_attention_bias.weight"] = _t(tree["rel_bias"])
        mlp_layer = 2 if cross else 1
        for i in range(n_layers):
            blk = tree[f"block_{i}"]
            h = f"{stack}.block.{i}."
            sd[h + "layer.0.layer_norm.weight"] = _t(
                blk["ln_attn"]["scale"]
            )
            put_attn(h + "layer.0.SelfAttention.", blk["attn"])
            if cross:
                sd[h + "layer.1.layer_norm.weight"] = _t(
                    blk["ln_cross"]["scale"]
                )
                put_attn(h + "layer.1.EncDecAttention.", blk["cross_attn"])
            sd[h + f"layer.{mlp_layer}.layer_norm.weight"] = _t(
                blk["ln_mlp"]["scale"]
            )
            put_mlp(h + f"layer.{mlp_layer}.DenseReluDense.", blk["mlp"])
    missing, unexpected = hf.load_state_dict(sd, strict=False)
    if missing or unexpected:
        raise RuntimeError(f"to_hf mapping drift: missing={missing} "
                           f"unexpected={list(unexpected)}")
    hf.eval()
    return hf


# --------------------------------------------------------------------------
# CLI: python -m tfde_tpu.models.convert <family> <hf_path> <out_dir>
# --------------------------------------------------------------------------

_FAMILIES = {
    "gpt2": ("GPT2LMHeadModel", "gpt2_from_hf"),
    "bert": ("BertForMaskedLM", "bert_from_hf"),
    "llama": ("LlamaForCausalLM", "llama_from_hf"),
    "mistral": ("MistralForCausalLM", "mistral_from_hf"),
    "gemma": ("GemmaForCausalLM", "gemma_from_hf"),
    "qwen2": ("Qwen2ForCausalLM", "qwen2_from_hf"),
    "bert-classifier": ("BertForSequenceClassification",
                        "bert_classifier_from_hf"),
    "phi": ("PhiForCausalLM", "phi_from_hf"),
    "neox": ("GPTNeoXForCausalLM", "neox_from_hf"),
    "bigcode": ("GPTBigCodeForCausalLM", "bigcode_from_hf"),
    "opt": ("OPTForCausalLM", "opt_from_hf"),
    "t5": ("T5ForConditionalGeneration", "t5_from_hf"),
    "falcon": ("FalconForCausalLM", "falcon_from_hf"),
    "mixtral": ("MixtralForCausalLM", "mixtral_from_hf"),
    "qwen3": ("Qwen3ForCausalLM", "qwen3_from_hf"),
    "phi3": ("Phi3ForCausalLM", "phi3_from_hf"),
    "gemma2": ("Gemma2ForCausalLM", "gemma2_from_hf"),
    "qwen2-moe": ("Qwen2MoeForCausalLM", "qwen2moe_from_hf"),
}


def _read_config(artifact_dir: str) -> dict:
    """The artifact's model_config.json as a dict — the one read site."""
    import json

    from tfde_tpu.utils import fs

    with fs.fs_open(fs.join(artifact_dir, "model_config.json"), "r") as f:
        return json.load(f)


def save_converted(model, params, out_dir: str, family: str) -> str:
    """Write (model, params) as a conversion artifact (params.npz +
    model_config.json) — what the forward CLI produces, and what
    `load_converted` / `--reverse` consume. The save half of the artifact
    contract: persist a fine-tuned model (e.g. Estimator.merged_params()
    output on a converted base) so it can be reloaded or exported back to
    transformers later."""
    import dataclasses
    import json

    from tfde_tpu.export.serving import write_params_npz
    from tfde_tpu.utils import fs

    if family not in _FAMILIES:
        raise ValueError(f"unknown family {family!r}; one of "
                         f"{sorted(_FAMILIES)}")
    fs.makedirs(out_dir, exist_ok=True)
    write_params_npz(fs.join(out_dir, "params.npz"), params)
    def _persistable(v) -> bool:
        scalar = (int, float, str, bool, type(None))
        if isinstance(v, scalar):
            return True
        # scalar tuples persist too (rope_scaling); json stores them as
        # lists, which load_converted re-tuples for hashability
        return (isinstance(v, (tuple, list))
                and all(isinstance(x, scalar) for x in v))

    config = {
        f.name: getattr(model, f.name)
        for f in dataclasses.fields(model)
        if f.name not in ("parent", "name")
        and _persistable(getattr(model, f.name))
    }
    config["family"] = family
    config["dtype"] = str(np.dtype(model.dtype))
    with fs.fs_open(fs.join(out_dir, "model_config.json"), "w") as f:
        json.dump(config, f, indent=2)
    return out_dir


def load_converted(artifact_dir: str, dtype=None):
    """(model, params) from a conversion-CLI artifact directory
    (params.npz + model_config.json, written by
    `python -m tfde_tpu.models.convert`). The public loader every
    consumer of converted checkpoints uses — the serving example,
    notebooks, and the CLI round-trip test share this one rebuild path.

    dtype overrides the recorded compute dtype (e.g. jnp.float32 on CPU).
    """
    import io

    import jax.numpy as jnp

    from tfde_tpu.export.serving import _unflatten_params
    from tfde_tpu.utils import fs

    conf = _read_config(artifact_dir)
    family = conf.pop("family")
    recorded = conf.pop("dtype")
    kwargs = {
        # json stores tuples as lists; re-tuple so the rebuilt module's
        # config stays hashable (rope_scaling)
        k: tuple(v) if isinstance(v, list) else v
        for k, v in conf.items()
    }
    kwargs["dtype"] = jnp.dtype(dtype if dtype is not None else recorded)

    from tfde_tpu.models.bert import Bert, BertClassifier
    from tfde_tpu.models.gpt import GPT
    from tfde_tpu.models.t5 import T5

    cls = {"gpt2": GPT, "llama": GPT, "mistral": GPT, "gemma": GPT,
           "qwen2": GPT, "phi": GPT, "neox": GPT, "bigcode": GPT,
           "opt": GPT, "falcon": GPT, "mixtral": GPT, "qwen3": GPT,
           "phi3": GPT, "gemma2": GPT, "qwen2-moe": GPT, "bert": Bert,
           "bert-classifier": BertClassifier, "t5": T5}[family]
    model = cls(**kwargs)
    with fs.fs_open(fs.join(artifact_dir, "params.npz"), "rb") as f:
        z = np.load(io.BytesIO(f.read()))
        params = _unflatten_params({k: z[k] for k in z.files})
    return model, params


def _cli(argv=None) -> str:
    """Convert a local HF checkpoint directory into this framework's
    artifact: <out>/params.npz (flat, the export/serving layout) +
    <out>/model_config.json (the constructor kwargs to rebuild the model).
    Returns the output dir. Offline by construction — `hf_path` is a local
    directory saved with save_pretrained(); nothing is downloaded."""
    import argparse

    parser = argparse.ArgumentParser(
        description="HF checkpoint -> tfde_tpu params (or back, --reverse)",
    )
    parser.add_argument("family", choices=sorted(_FAMILIES))
    parser.add_argument("hf_path", help="local save_pretrained() directory "
                        "(with --reverse: a conversion-artifact dir)")
    parser.add_argument("out_dir")
    parser.add_argument("--reverse", action="store_true",
                        help="artifact dir -> HF save_pretrained() "
                             "checkpoint: deploy a model fine-tuned here "
                             "(full, LoRA-merged, distilled) anywhere "
                             "transformers runs")
    args = parser.parse_args(argv)

    if args.reverse:
        recorded = _read_config(args.hf_path).get("family")
        if recorded != args.family:
            raise SystemExit(
                f"artifact {args.hf_path!r} records family {recorded!r}, "
                f"not {args.family!r} — pass the family the artifact was "
                f"converted as"
            )
        model, params = load_converted(args.hf_path)
        to_hf = {
            "gpt2": gpt2_to_hf, "llama": llama_to_hf,
            "mistral": llama_to_hf, "qwen2": llama_to_hf,
            "gemma": gemma_to_hf, "phi": phi_to_hf, "neox": neox_to_hf,
            "bigcode": bigcode_to_hf, "opt": opt_to_hf,
            "bert": bert_to_hf, "bert-classifier": bert_classifier_to_hf,
            "t5": t5_to_hf, "falcon": falcon_to_hf,
            "mixtral": mixtral_to_hf, "qwen3": qwen3_to_hf,
            "phi3": phi3_to_hf, "gemma2": gemma2_to_hf,
            "qwen2-moe": qwen2moe_to_hf,
        }[args.family]
        hf = to_hf(model, params)
        hf.save_pretrained(args.out_dir)
        print(f"exported {args.family} HF checkpoint -> {args.out_dir}")
        return args.out_dir

    import os

    import transformers

    if not os.path.isdir(args.hf_path):
        raise SystemExit(
            f"{args.hf_path!r} is not a directory — pass a local "
            f"save_pretrained() checkpoint; this CLI never downloads"
        )
    cls_name, fn_name = _FAMILIES[args.family]
    hf = getattr(transformers, cls_name).from_pretrained(
        args.hf_path, local_files_only=True
    )
    hf.eval()
    model, params = globals()[fn_name](hf)
    save_converted(model, params, args.out_dir, args.family)
    print(f"converted {args.family} checkpoint -> {args.out_dir}")
    return args.out_dir


if __name__ == "__main__":
    _cli()
