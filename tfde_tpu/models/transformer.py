"""Transformer encoder core shared by ViT (models/vit.py) and BERT
(models/bert.py) — the driver's scale-up configs (BASELINE.json configs[3-4]).

TPU-first choices:
- bf16 activations / fp32 params + LayerNorm (`dtype` vs `param_dtype`): MXU
  native precision on the matmuls, fp32 where numerics are touchy.
- Megatron-compatible weight shapes: qkv projections produce [embed, heads,
  head_dim] kernels (heads contiguous in one trailing block) and the output /
  fc2 projections consume their sharded dim first — so a tensor-parallel
  strategy can column/row-shard them over the 'tensor' axis with exactly two
  psums per block, both of which XLA overlaps with the following matmul.
- Activation constraints via parallel/axes.constrain: batch over data-like
  axes, sequence over 'seq', heads/hidden over 'tensor'. No-ops when the
  active mesh lacks those axes, so one definition serves every strategy.
- `remat` wraps each block in jax.checkpoint — HBM for FLOPs, the standard
  long-sequence trade.
"""

from __future__ import annotations

import functools
from typing import Callable, Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from tfde_tpu.ops import attention as attn_lib
from tfde_tpu.ops.quant import QuantDenseGeneral, kv_dequantize, kv_quantize
from tfde_tpu.ops.rotary import apply_rotary
from tfde_tpu.parallel.axes import batch_axes, constrain


def _check_quant(quant, train: bool = False) -> bool:
    """Shared `quant` field validation: None (fp) or 'int8' (serving-only
    W8A8 twins, ops/quant.py). train=True with quant on is refused here —
    round() has zero gradient, so a quantized projection would silently
    block all gradient flow (GPT raises the same error at the model level;
    this guard covers direct Encoder/Block/Mlp/MHA users)."""
    if quant not in (None, "int8"):
        raise ValueError(f"quant must be None or 'int8', got {quant!r}")
    if quant is not None and train:
        raise ValueError(
            "quant='int8' is a serving-only mode (round() has zero "
            "gradient) — train the fp model, then quantize_model it"
        )
    return quant == "int8"


class MultiHeadAttention(nn.Module):
    """Self-attention with dispatchable kernel (ops/attention.attention).

    `decode=True` turns on autoregressive KV caching (the serving path,
    inference/decode.py): `cached_key`/`cached_value`/`cache_index`
    variables live in the "cache" collection (flax convention — created at
    `init` with the full `[B, max_len]` input, so the cache length is the
    generation budget). A call with S>1 is a *prefill* (writes the whole
    prompt's K/V at [index, index+S)); S=1 is one decode step. Both use
    `dynamic_update_slice` with a traced start, so the compiled step serves
    every position — no per-position recompiles, static shapes throughout
    (XLA/TPU requirement)."""

    num_heads: int
    head_dim: int
    dtype: jnp.dtype = jnp.bfloat16
    dropout_rate: float = 0.0
    attn_impl: str = "auto"
    causal: bool = False
    decode: bool = False
    rope: bool = False  # rotary q/k rotation (ops/rotary.py) inside the layer
    rope_theta: float = 10_000.0
    # RoPE frequency rescaling tuple (ops/rotary.scale_frequencies):
    # ('linear', factor) or ('llama3', factor, low, high, orig_max) — the
    # Llama-3.1 long-context convention. Tuple (not dict) so the module
    # config stays hashable.
    rope_scaling: Optional[tuple] = None
    # partial rotary (Phi convention): only the first rope_dim features of
    # each head rotate; None = full head_dim
    rope_dim: Optional[int] = None
    # grouped-query attention: K/V carry this many heads (must divide
    # num_heads); each KV head serves num_heads/num_kv_heads query heads.
    # None = classic MHA. The KV cache and its decode bandwidth shrink by
    # the same factor — the reason every modern serving stack uses GQA.
    num_kv_heads: Optional[int] = None
    use_bias: bool = True  # False: the LLaMA bias-free projections
    # True: bias on q/k/v (and fused qkv) even when use_bias=False — the
    # Qwen2 arrangement (qkv biased, out projection and MLP bias-free)
    qkv_bias: bool = False
    # per-head RMSNorm on q and k after projection, BEFORE rotary — the
    # Qwen3 arrangement (one [head_dim] scale each, shared across heads)
    qk_norm: bool = False
    ln_eps: float = 1e-6  # qk_norm epsilon (the block's rms_norm_eps)
    # one [embed, 3, heads, head_dim] projection instead of three
    # [embed, heads, head_dim] GEMMs: a 3x-wider matmul keeps the MXU
    # busier at small per-chip batch (the training MFU knob). Parameter
    # layout changes ('qkv' vs 'query'/'key'/'value'), so checkpoint
    # conversion (models/convert.py) and HF interop stay on the unfused
    # default; MHA only (GQA's k/v are shaped differently).
    fused_qkv: bool = False
    # None (fp) | 'int8': W8A8 dynamic-quantized projections (ops/quant.py)
    # — the serving-only decode-bandwidth lever; params via quantize_model
    quant: Optional[str] = None
    # sliding-window attention (Mistral convention): position i attends the
    # last `window` positions inclusive. Requires causal; composes with the
    # decode cache (the validity mask carries the band), the flash kernel
    # (windowed tile skip), and the 'seq' ring (band on global positions)
    window: Optional[int] = None
    # Gemma-2 attention deltas: attn_scale overrides the 1/sqrt(head_dim)
    # score scale (query_pre_attn_scalar^-0.5); attn_logit_cap softcaps
    # scores (cap * tanh(s/cap)). Both route through the attention()
    # dispatcher like every other knob — the flash kernel applies them
    # inside the fused forward AND backward and the seq ring inside its
    # chunk step, so capped models train fused and sequence-parallel.
    attn_scale: Optional[float] = None
    attn_logit_cap: Optional[float] = None
    # rolling KV cache (decode + window only): the cache holds min(budget,
    # window) slots, each token writing slot (position mod len) — decode
    # memory bounded by the window, not the generation budget (the Mistral
    # rolling-buffer serving lever). OPT-IN because cache REWIND
    # (speculative decoding) breaks it: a rejected draft's write can alias
    # the slot of a committed token one window back; paths that never
    # rewind (inference/decode.generate/generate_ragged/beam_search) turn
    # it on via _decode_clone(rolling=True).
    rolling_cache: bool = False
    # paged KV cache (decode only, TFDE_PAGED_KV): K/V live in ONE shared
    # physical pool of `paged_blocks` blocks x `kv_block` tokens
    # ("pool_key"/"pool_value" cache vars) and each row carries a
    # "block_table" [B, nmax] mapping its logical block to a pool block.
    # Writes scatter by (table[pos // kv_block], pos % kv_block); attention
    # gathers the row's table back into position order, so the SAME static
    # program serves every (prompt length, rows) shape — the pad-ladder
    # compile collapse (inference/paged.py owns allocation/refcounts).
    # Block 0 is the null block: unallocated table slots point there and
    # out-of-range writes are routed there, so junk never lands in a live
    # block. Mutually exclusive with rolling_cache.
    paged_blocks: Optional[int] = None
    kv_block: int = 16
    # None (fp) | 'int8': quantized KV cache (TFDE_KV_QUANT). K/V are
    # stored int8 with one fp32 scale per (position, kv-head) — sidecar
    # cache vars "cached_key_scale"/"cached_value_scale" (dense) or
    # "pool_key_scale"/"pool_value_scale" (paged, organized per kv_block
    # like the payload so trie sharing/refcounts carry quantized blocks
    # for free). Quantize-on-write, dequantize fused into the attention
    # read (ops/quant.kv_quantize/kv_dequantize) — the wire format never
    # leaves the device program, and the cache footprint drops ~4x at
    # fp32 / ~2x at bf16 (minus the 4/head_dim scale overhead). Same
    # static program count as fp. Mutually exclusive with rolling_cache
    # (a rolling slot rewrites scales out of order with its payload).
    kv_quant: Optional[str] = None

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        mask: Optional[jax.Array] = None,
        train: bool = False,
    ) -> jax.Array:
        if self.kv_heads <= 0 or self.num_heads % self.kv_heads:
            # (12 % -4 == 0 in Python — the sign check is load-bearing)
            raise ValueError(
                f"num_kv_heads={self.kv_heads} must be positive and divide "
                f"num_heads={self.num_heads}"
            )
        if self.window is not None and not self.causal:
            raise ValueError(
                f"window={self.window} requires causal attention (the "
                f"sliding window is a band below the causal diagonal)"
            )
        b = batch_axes()
        if _check_quant(self.quant, train):
            proj = functools.partial(
                QuantDenseGeneral, dtype=self.dtype, use_bias=self.use_bias,
            )
        else:
            proj = functools.partial(
                nn.DenseGeneral,
                dtype=self.dtype,
                param_dtype=jnp.float32,
                use_bias=self.use_bias,
            )
        in_bias = self.use_bias or self.qkv_bias
        if self.fused_qkv:
            if self.kv_heads != self.num_heads:
                raise NotImplementedError(
                    "fused_qkv requires classic MHA (num_kv_heads=None): "
                    "GQA's k/v projections have different shapes and "
                    "cannot stack into one kernel"
                )
            qkv = proj(
                features=(3, self.num_heads, self.head_dim), name="qkv",
                use_bias=in_bias,
            )(x)  # [B, S, 3, H, D] from ONE GEMM
            q, k, v = (qkv[..., i, :, :] for i in range(3))
        else:
            q = proj(features=(self.num_heads, self.head_dim),
                     name="query", use_bias=in_bias)(x)
            k = proj(features=(self.kv_heads, self.head_dim), name="key",
                     use_bias=in_bias)(x)
            v = proj(features=(self.kv_heads, self.head_dim),
                     name="value", use_bias=in_bias)(x)
        if self.qk_norm:
            qk_rms = functools.partial(
                nn.RMSNorm, epsilon=self.ln_eps, dtype=jnp.float32,
                param_dtype=jnp.float32,
            )
            q = qk_rms(name="q_norm")(q).astype(self.dtype)
            k = qk_rms(name="k_norm")(k).astype(self.dtype)
        if self.rope and not self.decode:
            q, k = self._rotate(q, k, jnp.zeros((), jnp.int32))
        # [B, S, H, D]: heads carry the tensor-parallel shard.
        q, k, v = (constrain(t, b, "seq", "tensor") for t in (q, k, v))
        if self.decode:
            if mask is not None:
                raise NotImplementedError(
                    "decode mode builds its own cache-position mask; "
                    "explicit masks are not supported"
                )
            if not self.causal:
                raise ValueError(
                    "decode=True requires causal attention (autoregressive "
                    "generation is a causal-LM capability)"
                )
            y = self._decode_attention(q, k, v, b)
        else:
            # GQA included: K/V stay kv_heads-shaped end to end — the
            # dispatcher routes to the flash kernel (GQA head-folding
            # index maps), the seq ring (kv_heads-sized shards rotate),
            # or the grouped einsum; never a repeat-then-attend
            # expansion. attn_scale/attn_logit_cap (the Gemma-2
            # attention deltas) go through the dispatcher too — every
            # impl applies them natively (flash inside the fused
            # forward+backward, ring inside its chunk step), so capped/
            # windowed models train fused and sequence-parallel; an impl
            # without cap support warn-falls-back to the grouped einsum
            # in the dispatcher rather than refusing here
            y = attn_lib.attention(
                q, k, v, mask=mask, causal=self.causal,
                impl=self.attn_impl, window=self.window,
                scale=self.attn_scale, logit_cap=self.attn_logit_cap,
            )
        y = constrain(y, b, "seq", "tensor")
        y = proj(features=x.shape[-1], axis=(-2, -1), name="out")(y)
        y = constrain(y, b, "seq")
        if self.dropout_rate > 0.0:
            y = nn.Dropout(self.dropout_rate, deterministic=not train)(y)
        return y

    def _rotate(self, q, k, start):
        """Rotary q/k rotation at absolute positions start + [0, S) — the
        ONE rotation site for the train forward and both decode paths. A
        cached key's rotation is fixed at write time, so each call rotates
        only its own tokens. `start` is a scalar (shared cache index) or
        [B] (per-row indices, the batched-speculation path): both broadcast
        to per-token positions [S] / [B, S], which apply_rotary accepts."""
        if not self.rope:
            return q, k
        pos = jnp.asarray(start, jnp.int32)[..., None] + jnp.arange(
            q.shape[1], dtype=jnp.int32
        )  # scalar -> [S] (shape-(1,) start broadcasts away), [B] -> [B, S]
        return (apply_rotary(q, pos, self.rope_theta,
                             rotary_dim=self.rope_dim,
                             scaling=self.rope_scaling),
                apply_rotary(k, pos, self.rope_theta,
                             rotary_dim=self.rope_dim,
                             scaling=self.rope_scaling))

    def _decode_attention(self, q, k, v, batch) -> jax.Array:
        """Write this call's K/V into the cache, attend q over the filled
        prefix. The validity mask `j <= index + i` covers prefill (full
        causal triangle over the prompt) and single-step decode (attend
        everything written so far) in one expression.

        Contract: the caller must not advance `cache_index` past the cache
        budget — `index` is traced, so an overflow cannot raise here, and a
        predicated write would put a full-cache copy on the bandwidth-bound
        decode hot path (dynamic_update_slice would clamp the start and
        overwrite the last entries instead). inference/decode.generate sizes
        the cache to prompt + max_new_tokens exactly and can never overflow;
        direct drivers of this layer own the same invariant."""
        if self.kv_quant not in (None, "int8"):
            raise ValueError(
                f"kv_quant must be None or 'int8', got {self.kv_quant!r}"
            )
        if self.paged_blocks is not None:
            if self.rolling_cache and self.window is not None:
                raise NotImplementedError(
                    "paged_blocks and rolling_cache are mutually exclusive "
                    "cache layouts (a rolling slot can alias any pool "
                    "block); pick one"
                )
            return self._paged_attention(q, k, v, batch)
        is_filled = self.has_variable("cache", "cached_key")
        rolling = self.rolling_cache and self.window is not None
        quant = self.kv_quant == "int8"
        if quant and rolling:
            raise NotImplementedError(
                "kv_quant='int8' and rolling_cache are mutually exclusive: "
                "the rolling slot rewrite (slot = position mod window) "
                "would need a second modular scatter for the scale sidecar "
                "on the decode hot path; pick one"
            )
        cache_shape = list(k.shape)
        if rolling:
            cache_shape[1] = min(cache_shape[1], self.window)
        cached_key = self.variable("cache", "cached_key", jnp.zeros,
                                   tuple(cache_shape),
                                   jnp.int8 if quant else k.dtype)
        cached_value = self.variable("cache", "cached_value", jnp.zeros,
                                     tuple(cache_shape),
                                     jnp.int8 if quant else v.dtype)
        if quant:
            # fp32 scale per (row, position, kv-head) — zeros dequantize
            # to exact 0.0, matching the fp cache's zero fill
            scale_shape = tuple(cache_shape[:2]) + (k.shape[2],)
            key_scale = self.variable("cache", "cached_key_scale",
                                      jnp.zeros, scale_shape, jnp.float32)
            value_scale = self.variable("cache", "cached_value_scale",
                                        jnp.zeros, scale_shape, jnp.float32)
        cache_index = self.variable("cache", "cache_index",
                                    lambda: jnp.zeros((), jnp.int32))

        if not is_filled:
            # init pass: variables were just created from this call's shapes
            # (the [B, max_len] budget input) — plain causal attention.
            q, k = self._rotate(q, k, jnp.zeros((), jnp.int32))
            return attn_lib.grouped_attention(
                q, k, v, causal=True, window=self.window,
                scale=self.attn_scale, logit_cap=self.attn_logit_cap,
            )
        sq = q.shape[1]
        max_len = cached_key.value.shape[1]
        if sq > max_len and not rolling:
            raise ValueError(
                f"input length {sq} exceeds the cache budget {max_len}; "
                f"re-init the cache with a larger max_len"
            )
        idx = cache_index.value
        q, k = self._rotate(q, k, idx)
        if rolling:
            return self._rolling_attention(
                q, k, v, batch, cached_key, cached_value, cache_index
            )
        if quant:
            # quantize-on-write: the int8 payload + fp32 per-(position,
            # head) scale are what the scatter below stores; attention
            # reads dequantize after the scatter so this call's own
            # tokens round-trip through the wire format too (parity with
            # what a later step would read back)
            k_w, k_sc = kv_quantize(k)
            v_w, v_sc = kv_quantize(v)
        else:
            k_w, v_w = (k.astype(cached_key.value.dtype),
                        v.astype(cached_value.value.dtype))
        if idx.ndim == 0:
            # shared index (generate / batch-1 speculation): one cheap
            # dynamic_update_slice covers every row
            k_all = jax.lax.dynamic_update_slice(
                cached_key.value, k_w, (0, idx, 0, 0)
            )
            v_all = jax.lax.dynamic_update_slice(
                cached_value.value, v_w, (0, idx, 0, 0)
            )
            if quant:
                ks_all = jax.lax.dynamic_update_slice(
                    key_scale.value, k_sc, (0, idx, 0))
                vs_all = jax.lax.dynamic_update_slice(
                    value_scale.value, v_sc, (0, idx, 0))
            # [1, 1, Sq, max_len]: query (position idx+i) sees kv j<=idx+i
            pos_q = idx + jnp.arange(sq, dtype=jnp.int32)
            cols = jnp.arange(max_len, dtype=jnp.int32)[None, :]
            valid = cols <= pos_q[:, None]
            if self.window is not None:
                # sliding band over the cache: j in (pos - window, pos]
                valid = jnp.logical_and(
                    valid, pos_q[:, None] - cols < self.window
                )
            valid = valid[None, None]
        else:
            # per-row indices [B] (batched speculation, inference/
            # speculative.py: acceptance lengths diverge across rows, so
            # each row writes at its own offset). vmapping the update
            # slice over rows gives per-row starts and lowers to an
            # in-place scatter of just the sq new tokens — no full-cache
            # rewrite on the bandwidth-bound decode path.
            write = jax.vmap(
                lambda cache, new, i: jax.lax.dynamic_update_slice(
                    cache, new, (i, 0, 0)
                )
            )
            k_all = write(cached_key.value, k_w, idx)
            v_all = write(cached_value.value, v_w, idx)
            if quant:
                swrite = jax.vmap(
                    lambda cache, new, i: jax.lax.dynamic_update_slice(
                        cache, new, (i, 0)
                    )
                )
                ks_all = swrite(key_scale.value, k_sc, idx)
                vs_all = swrite(value_scale.value, v_sc, idx)
            # [B, 1, Sq, max_len]: row b's query i sits at idx[b]+i
            pos_w = idx[:, None] + jnp.arange(sq, dtype=jnp.int32)  # [B,sq]
            colsb = jnp.arange(max_len, dtype=jnp.int32)[None, None, :]
            valid = colsb <= pos_w[:, :, None]
            if self.window is not None:
                valid = jnp.logical_and(
                    valid, pos_w[:, :, None] - colsb < self.window
                )
            valid = valid[:, None]
        cached_key.value = constrain(k_all, batch, None, "tensor")
        cached_value.value = constrain(v_all, batch, None, "tensor")
        if quant:
            key_scale.value = constrain(ks_all, batch, None, "tensor")
            value_scale.value = constrain(vs_all, batch, None, "tensor")
            # dequant fused into the attention read: elementwise
            # int8 * fp32-scale feeding the einsum, so the fp copy lives
            # only inside this program — HBM holds int8 + scales
            k_all = kv_dequantize(k_all, ks_all, k.dtype)
            v_all = kv_dequantize(v_all, vs_all, v.dtype)
        cache_index.value = idx + sq
        # grouped_attention == reference_attention at kv_heads == num_heads;
        # with GQA the kv_heads-shaped cache feeds the einsum directly (no
        # expanded copy on the bandwidth-bound decode path)
        return attn_lib.grouped_attention(
            q, k_all, v_all, mask=valid, scale=self.attn_scale,
            logit_cap=self.attn_logit_cap,
        )

    def _paged_attention(self, q, k, v, batch) -> jax.Array:
        """Paged decode attention: write this call's K/V into pool blocks
        through the row's block table, gather the table back into position
        order, attend under the same `j <= index + i` validity mask as the
        dense path.

        Bit-exactness with the dense slab: the gathered [B, nmax*block]
        keys are in position order (table slot s holds positions
        [s*block, (s+1)*block)), so column j of the gather IS position j —
        identical to the dense cache column-for-column up to max_len, plus
        trailing columns the mask zeroes exactly (grouped_attention masks
        with finfo.min, so masked weights are exactly 0.0 and garbage
        columns contribute exact-zero terms to both the softmax numerator
        and denominator).

        Junk-write invariant (same as dense, plus the null-block routing):
        any write at a position beyond a row's committed count lands either
        in the row's own allocated-but-uncommitted cells (overwritten
        position-exactly before any mask reaches them), in an unallocated
        table slot (block 0), or past the table entirely (`slot >= nmax`,
        routed to block 0). Shared (refcounted) trie blocks are never
        written: the trie only holds COMPLETE prompt blocks, and a warm
        row's first write position >= pre_len is block-aligned into its
        own private block."""
        is_filled = self.has_variable("cache", "pool_key")
        block = self.kv_block
        bsz = k.shape[0]
        quant = self.kv_quant == "int8"
        pool_shape = (self.paged_blocks, block, k.shape[2], k.shape[3])
        pool_key = self.variable("cache", "pool_key", jnp.zeros,
                                 pool_shape,
                                 jnp.int8 if quant else k.dtype)
        pool_value = self.variable("cache", "pool_value", jnp.zeros,
                                   pool_shape,
                                   jnp.int8 if quant else v.dtype)
        if quant:
            # fp32 scale sidecar per pool block: [nblocks, block, Kv] rides
            # the same block ids as the payload, so trie sharing, refcounts
            # and defrag permutation carry the scales for free
            key_scale = self.variable("cache", "pool_key_scale", jnp.zeros,
                                      pool_shape[:3], jnp.float32)
            value_scale = self.variable("cache", "pool_value_scale",
                                        jnp.zeros, pool_shape[:3],
                                        jnp.float32)
        # nmax from the init call's [B, max_len] budget input; +1 because
        # the decode scan writes one-past-committed for finished rows
        block_table = self.variable(
            "cache", "block_table", jnp.zeros,
            (bsz, -(-(k.shape[1] + 1) // block)), jnp.int32)
        cache_index = self.variable("cache", "cache_index",
                                    lambda: jnp.zeros((), jnp.int32))
        if not is_filled:
            # init pass: pool/table variables just created — plain causal
            # attention over the budget input, exactly like the dense init
            q, k = self._rotate(q, k, jnp.zeros((), jnp.int32))
            return attn_lib.grouped_attention(
                q, k, v, causal=True, window=self.window,
                scale=self.attn_scale, logit_cap=self.attn_logit_cap,
            )
        sq = q.shape[1]
        nmax = block_table.value.shape[1]
        idx = cache_index.value
        q, k = self._rotate(q, k, idx)
        # scalar (shared) or [B] per-row indices both become [B] — the
        # paged program is per-row by construction
        idxv = jnp.broadcast_to(jnp.asarray(idx, jnp.int32), (bsz,))
        pos = idxv[:, None] + jnp.arange(sq, dtype=jnp.int32)  # [B, sq]
        slot = pos // block
        off = pos % block
        table = block_table.value  # [B, nmax]
        rows = jnp.arange(bsz, dtype=jnp.int32)[:, None]
        # out-of-table writes go to the null block, never a live one
        blk = jnp.where(slot < nmax,
                        table[rows, jnp.clip(slot, 0, nmax - 1)], 0)
        # sanitize the write: junk positions (a rider row pad-fed past its
        # committed count during a chunked prefill) can carry non-finite
        # activations — e.g. a learned position embedding looked up past
        # max_position fills NaN — and a masked column's exact-zero weight
        # still poisons the output through 0 * NaN. nan_to_num is identity
        # on every finite (legit) value, so bit-exactness is untouched;
        # it only guarantees the POOL itself never holds a non-finite cell
        if quant:
            # kv_quantize nan_to_nums internally — same sanitize guarantee
            # as the fp write below, plus the scale itself stays finite
            k_w, k_sc = kv_quantize(k)
            v_w, v_sc = kv_quantize(v)
            k_pool = pool_key.value.at[blk, off].set(k_w)
            v_pool = pool_value.value.at[blk, off].set(v_w)
            ks_pool = key_scale.value.at[blk, off].set(k_sc)
            vs_pool = value_scale.value.at[blk, off].set(v_sc)
            # gather payload + scales through the same table, dequant
            # fused into the attention read: [B, nmax*block, Kv, D]
            k_all = kv_dequantize(k_pool[table], ks_pool[table], k.dtype
                                  ).reshape(bsz, nmax * block, *k.shape[2:])
            v_all = kv_dequantize(v_pool[table], vs_pool[table], v.dtype
                                  ).reshape(bsz, nmax * block, *v.shape[2:])
        else:
            k_pool = pool_key.value.at[blk, off].set(
                jnp.nan_to_num(k.astype(pool_key.value.dtype)))
            v_pool = pool_value.value.at[blk, off].set(
                jnp.nan_to_num(v.astype(pool_value.value.dtype)))
            # gather the row's table into position order:
            # [B, nmax*block, Kv, D]
            k_all = k_pool[table].reshape(bsz, nmax * block, *k.shape[2:])
            v_all = v_pool[table].reshape(bsz, nmax * block, *v.shape[2:])
        cols = jnp.arange(nmax * block, dtype=jnp.int32)[None, None, :]
        valid = cols <= pos[:, :, None]  # [B, sq, nmax*block]
        if self.window is not None:
            valid = jnp.logical_and(valid, pos[:, :, None] - cols
                                    < self.window)
        valid = valid[:, None]
        pool_key.value = constrain(k_pool, None, None, "tensor")
        pool_value.value = constrain(v_pool, None, None, "tensor")
        if quant:
            key_scale.value = constrain(ks_pool, None, None, "tensor")
            value_scale.value = constrain(vs_pool, None, None, "tensor")
        cache_index.value = idx + sq
        return attn_lib.grouped_attention(
            q, k_all, v_all, mask=valid, scale=self.attn_scale,
            logit_cap=self.attn_logit_cap,
        )

    def _rolling_attention(self, q, k, v, batch, cached_key, cached_value,
                           cache_index) -> jax.Array:
        """Window-bounded rolling KV cache: the token at absolute position
        p lives in slot p mod Wc (Wc = min(budget, window)), so decode
        memory is O(window) regardless of how long the generation runs.

        The mask is reconstructed from slot arithmetic instead of stored
        positions: after this call's writes the newest absolute position
        is P, so slot j's content is the token at b_j = P - ((P - j) mod
        Wc) — the latest position congruent to j. A query at position p
        attends slot j iff 0 <= b_j <= p and p - b_j < window.

        Caller invariant (STRICTER than "no rewind"): ONE prefill from
        position 0, then single-token (sq == 1) steps. A multi-token
        write onto a filled cache would clobber in-window keys its own
        earlier queries still need (e.g. a 4-token chunk at positions
        8-11 with window 4 destroys keys 5-7 before the query at 8 reads
        them), and cache_index is traced so no runtime check can fire.
        generate / generate_ragged / beam_search all satisfy this (their
        scans are strictly one token per step after the prefill);
        speculative decoding violates it twice over (multi-token verify
        steps AND rewind) and therefore never enables rolling.

        A prompt longer than the cache (sq > Wc) attends in-batch (valid
        only at cache position 0 — the generate prefill; every key a
        band-limited query needs is in the batch) and keeps the last Wc
        tokens.
        """
        sq = q.shape[1]
        wc = cached_key.value.shape[1]
        idx = cache_index.value
        kd = cached_key.value.dtype

        if sq > wc:
            if idx.ndim != 0:
                raise ValueError(
                    "per-row prefill longer than the rolling window cache "
                    "is unsupported (rows would need in-batch keys beyond "
                    "their own cache)"
                )
            # long prefill from position 0: band-limited queries only need
            # in-batch keys; keep the newest Wc tokens
            y = attn_lib.grouped_attention(
                q, k, v, causal=True, window=self.window,
                scale=self.attn_scale, logit_cap=self.attn_logit_cap,
            )
            pos_last = idx + jnp.arange(sq - wc, sq, dtype=jnp.int32)
            slots = pos_last % wc
            k_all = cached_key.value.at[:, slots].set(
                k[:, -wc:].astype(kd)
            )
            v_all = cached_value.value.at[:, slots].set(
                v[:, -wc:].astype(cached_value.value.dtype)
            )
        else:
            cols = jnp.arange(wc, dtype=jnp.int32)
            if idx.ndim == 0:
                pos_q = idx + jnp.arange(sq, dtype=jnp.int32)
                slots = pos_q % wc
                k_all = cached_key.value.at[:, slots].set(k.astype(kd))
                v_all = cached_value.value.at[:, slots].set(
                    v.astype(cached_value.value.dtype)
                )
                last = idx + sq - 1
                b = last - ((last - cols) % wc)  # [Wc] slot -> abs position
                valid = ((b[None, :] >= 0)
                         & (b[None, :] <= pos_q[:, None])
                         & (pos_q[:, None] - b[None, :] < self.window))
                valid = valid[None, None]  # [1, 1, Sq, Wc]
            else:
                # no rolling-enabled driver produces per-row indices:
                # generate/ragged/beam share a scalar cache_index, and the
                # [B]-index producer (speculative rewind) never rolls.
                # Refuse rather than ship a never-executed branch.
                raise NotImplementedError(
                    "rolling_cache with per-row cache indices is "
                    "unsupported — the per-row paths (speculative "
                    "decoding, row-recycling servers) use the full-budget "
                    "cache"
                )
            y = attn_lib.grouped_attention(
                q, k_all, v_all, mask=valid, scale=self.attn_scale,
                logit_cap=self.attn_logit_cap,
            )
        cached_key.value = constrain(k_all, batch, None, "tensor")
        cached_value.value = constrain(v_all, batch, None, "tensor")
        cache_index.value = idx + sq
        return y


class Mlp(nn.Module):
    """fc1 -> act -> fc2; hidden dim carries the tensor-parallel shard.

    act='swiglu' (the LLaMA family): a parallel `gate` projection gates the
    up-projection with silu — gate and fc1 are both column-sharded under
    TP, so the elementwise product needs no extra collective."""

    mlp_dim: int
    dtype: jnp.dtype = jnp.bfloat16
    dropout_rate: float = 0.0
    act: str = "gelu"  # 'gelu' (tanh approx, == GPT-2 gelu_new) | 'swiglu'
    use_bias: bool = True
    quant: Optional[str] = None  # see MultiHeadAttention.quant

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        b = batch_axes()
        if _check_quant(self.quant, train):
            dense = functools.partial(
                QuantDenseGeneral, dtype=self.dtype, use_bias=self.use_bias,
            )
        else:
            dense = functools.partial(
                nn.Dense, dtype=self.dtype, param_dtype=jnp.float32,
                use_bias=self.use_bias,
            )
        h = dense(self.mlp_dim, name="fc1")(x)
        if self.act == "gelu":
            h = nn.gelu(h)
        elif self.act == "relu":
            h = nn.relu(h)
        elif self.act == "swiglu":
            gate = dense(self.mlp_dim, name="gate")(x)
            h = nn.silu(gate) * h
        elif self.act == "geglu":
            # gelu-gated (the Gemma family): tanh-approximate gelu on the
            # gate, matching HF's gelu_pytorch_tanh
            gate = dense(self.mlp_dim, name="gate")(x)
            h = nn.gelu(gate, approximate=True) * h
        else:
            raise ValueError(
                f"act must be 'gelu', 'relu', 'swiglu' or 'geglu', got "
                f"{self.act!r}"
            )
        h = constrain(h, b, "seq", "tensor")
        h = dense(x.shape[-1], name="fc2")(h)
        h = constrain(h, b, "seq")
        if self.dropout_rate > 0.0:
            h = nn.Dropout(self.dropout_rate, deterministic=not train)(h)
        return h


class TransformerBlock(nn.Module):
    """Pre-LN (default): x + MHA(LN(x)); x + MLP(LN(x)) — the stable-training
    variant ViT/GPT use. `norm_style='post'`: LN(x + MHA(x)); LN(x + MLP(x))
    — the original BERT arrangement (models/bert.py needs it for exact
    architecture parity)."""

    num_heads: int
    head_dim: int
    mlp_dim: int
    dtype: jnp.dtype = jnp.bfloat16
    dropout_rate: float = 0.0
    attn_impl: str = "auto"
    causal: bool = False
    decode: bool = False
    rope: bool = False
    rope_theta: float = 10_000.0
    rope_scaling: Optional[tuple] = None  # RoPE rescale (MultiHeadAttention)
    rope_dim: Optional[int] = None  # partial rotary (MultiHeadAttention)
    num_kv_heads: Optional[int] = None  # GQA (MultiHeadAttention)
    fused_qkv: bool = False  # one-GEMM qkv projection (MultiHeadAttention)
    quant: Optional[str] = None  # int8 serving twins (MultiHeadAttention)
    window: Optional[int] = None  # sliding window (MultiHeadAttention)
    rolling_cache: bool = False  # window-bounded decode cache (MHA)
    paged_blocks: Optional[int] = None  # paged KV pool (MultiHeadAttention)
    kv_block: int = 16  # paged pool block size in tokens (TFDE_KV_BLOCK)
    kv_quant: Optional[str] = None  # int8 KV cache (MHA, TFDE_KV_QUANT)
    attn_scale: Optional[float] = None    # Gemma-2 (MultiHeadAttention)
    attn_logit_cap: Optional[float] = None
    norm_style: str = "pre"
    # 'pre' | 'post' | 'parallel' (Phi: one LN, x + attn(ln(x)) + mlp(ln(x)))
    # | 'parallel2' (NeoX/Pythia: parallel residual, separate attn/MLP LNs)
    norm: str = "layer"  # 'layer' | 'rms' (LLaMA: scale-only, no bias)
    mlp_act: str = "gelu"  # Mlp.act
    use_bias: bool = True
    qkv_bias: bool = False  # Qwen2: biased q/k/v beside bias-free out/MLP
    qk_norm: bool = False  # Qwen3: per-head q/k RMSNorm (MultiHeadAttention)
    ln_eps: float = 1e-6  # checkpoint fidelity: GPT-2 1e-5, BERT 1e-12
    num_experts: int = 0  # > 0 swaps the dense MLP for a routed MoE MLP
    experts_per_token: int = 2
    moe_capacity_factor: float = 1.25  # MoEMlp.capacity_factor
    moe_normalize_topk: bool = True        # MoEMlp.normalize_topk
    moe_shared_expert_dim: Optional[int] = None  # MoEMlp.shared_expert_dim
    router_z_loss_weight: float = 0.0  # ST-MoE stabilizer (models/moe.py)

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        mask: Optional[jax.Array] = None,
        train: bool = False,
    ) -> jax.Array:
        if self.norm not in ("layer", "rms"):
            raise ValueError(f"norm must be 'layer' or 'rms', got {self.norm!r}")
        ln = functools.partial(
            nn.RMSNorm if self.norm == "rms" else nn.LayerNorm,
            epsilon=self.ln_eps, dtype=jnp.float32, param_dtype=jnp.float32,
        )
        attn = MultiHeadAttention(
            num_heads=self.num_heads,
            head_dim=self.head_dim,
            dtype=self.dtype,
            dropout_rate=self.dropout_rate,
            attn_impl=self.attn_impl,
            causal=self.causal,
            decode=self.decode,
            rope=self.rope,
            rope_theta=self.rope_theta,
            rope_scaling=self.rope_scaling,
            rope_dim=self.rope_dim,
            num_kv_heads=self.num_kv_heads,
            fused_qkv=self.fused_qkv,
            quant=self.quant,
            window=self.window,
            rolling_cache=self.rolling_cache,
            paged_blocks=self.paged_blocks,
            kv_block=self.kv_block,
            kv_quant=self.kv_quant,
            attn_scale=self.attn_scale,
            attn_logit_cap=self.attn_logit_cap,
            use_bias=self.use_bias,
            qkv_bias=self.qkv_bias,
            qk_norm=self.qk_norm,
            ln_eps=self.ln_eps,
            name="attn",
        )
        if self.num_experts > 0:
            if (self.mlp_act, self.use_bias) not in (
                ("gelu", True), ("swiglu", False),
            ):
                raise NotImplementedError(
                    "MoE expert MLPs are gelu+bias (Switch/GShard) or "
                    "bias-free swiglu (Mixtral); other mlp_act/use_bias "
                    "combinations would silently build a different "
                    "architecture than requested"
                )
            if self.quant is not None:
                raise NotImplementedError(
                    "quant='int8' does not cover MoE expert MLPs yet — "
                    "quantize a dense model, or set num_experts=0"
                )
            from tfde_tpu.models.moe import MoEMlp

            mlp = MoEMlp(
                num_experts=self.num_experts,
                mlp_dim=self.mlp_dim,
                experts_per_token=self.experts_per_token,
                capacity_factor=self.moe_capacity_factor,
                normalize_topk=self.moe_normalize_topk,
                shared_expert_dim=self.moe_shared_expert_dim,
                act=self.mlp_act,
                use_bias=self.use_bias,
                router_z_loss_weight=self.router_z_loss_weight,
                dropout_rate=self.dropout_rate,
                dtype=self.dtype,
                name="moe",
            )
        else:
            mlp = Mlp(
                mlp_dim=self.mlp_dim,
                dtype=self.dtype,
                dropout_rate=self.dropout_rate,
                act=self.mlp_act,
                use_bias=self.use_bias,
                quant=self.quant,
                name="mlp",
            )
        if self.norm_style == "pre":
            y = ln(name="ln_attn")(x).astype(self.dtype)
            x = x + attn(y, mask=mask, train=train)
            y = ln(name="ln_mlp")(x).astype(self.dtype)
            return x + mlp(y, train=train)
        if self.norm_style == "post":
            x = ln(name="ln_attn")(x + attn(x, mask=mask, train=train))
            x = x.astype(self.dtype)
            x = ln(name="ln_mlp")(x + mlp(x, train=train))
            return x.astype(self.dtype)
        if self.norm_style == "parallel":
            # the Phi arrangement: ONE LayerNorm feeds attention and MLP
            # side by side, residual added once — attn and MLP GEMMs have
            # no serial dependency, so XLA overlaps them freely
            y = ln(name="ln_attn")(x).astype(self.dtype)
            return x + attn(y, mask=mask, train=train) + mlp(y, train=train)
        if self.norm_style == "parallel2":
            # the GPT-NeoX/Pythia arrangement: parallel residual like Phi,
            # but attention and MLP each get their OWN LayerNorm
            ya = ln(name="ln_attn")(x).astype(self.dtype)
            ym = ln(name="ln_mlp")(x).astype(self.dtype)
            return (x + attn(ya, mask=mask, train=train)
                    + mlp(ym, train=train))
        if self.norm_style == "sandwich":
            # the Gemma-2 arrangement: each sublayer normed BOTH sides —
            # x + post_ln(sub(pre_ln(x))) — taming residual-stream growth
            y = ln(name="ln_attn")(x).astype(self.dtype)
            a = attn(y, mask=mask, train=train)
            x = x + ln(name="ln_attn_post")(a).astype(self.dtype)
            y = ln(name="ln_mlp")(x).astype(self.dtype)
            h = mlp(y, train=train)
            return x + ln(name="ln_mlp_post")(h).astype(self.dtype)
        raise ValueError(
            f"norm_style must be 'pre', 'post', 'parallel', 'parallel2' "
            f"or 'sandwich', got {self.norm_style!r}"
        )


def remat_policy(remat):
    """Checkpoint-policy selector shared by every model family:
    False — no remat; True / 'full' — nothing_saveable (recompute the whole
    block in backward: max HBM savings, ~1.33x FLOPs); 'dots' — save MXU
    matmul outputs and recompute only the elementwise/fusible ops (the
    usual best HBM/FLOPs tradeoff on TPU: backward recompute is nearly
    free because it never re-runs the matmuls)."""
    if not remat:
        return None
    if remat is True or remat == "full":
        return jax.checkpoint_policies.nothing_saveable
    if remat == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    raise ValueError(
        f"remat must be False, True, 'full', or 'dots'; got {remat!r}"
    )


class Encoder(nn.Module):
    """Stack of TransformerBlocks with optional per-block rematerialization
    (`remat`: False | True/'full' | 'dots', see remat_policy)."""

    depth: int
    num_heads: int
    head_dim: int
    mlp_dim: int
    dtype: jnp.dtype = jnp.bfloat16
    dropout_rate: float = 0.0
    attn_impl: str = "auto"
    causal: bool = False
    decode: bool = False
    rope: bool = False
    rope_theta: float = 10_000.0
    rope_scaling: Optional[tuple] = None
    rope_dim: Optional[int] = None
    num_kv_heads: Optional[int] = None
    fused_qkv: bool = False
    quant: Optional[str] = None
    window: Optional[int] = None
    # 'all': every block windowed; 'alternate': blocks 0, 2, ... windowed,
    # odd blocks full attention (the Gemma-2 local/global interleave)
    window_pattern: str = "all"
    rolling_cache: bool = False
    paged_blocks: Optional[int] = None
    kv_block: int = 16
    kv_quant: Optional[str] = None
    attn_scale: Optional[float] = None
    attn_logit_cap: Optional[float] = None
    norm_style: str = "pre"
    norm: str = "layer"
    mlp_act: str = "gelu"
    use_bias: bool = True
    qkv_bias: bool = False
    qk_norm: bool = False
    ln_eps: float = 1e-6
    remat: Any = False
    num_experts: int = 0   # > 0: MoE MLP in every `moe_every`-th block
    experts_per_token: int = 2
    moe_capacity_factor: float = 1.25
    moe_normalize_topk: bool = True
    moe_shared_expert_dim: Optional[int] = None
    router_z_loss_weight: float = 0.0
    moe_every: int = 2     # GShard convention: alternate dense / MoE

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        mask: Optional[jax.Array] = None,
        train: bool = False,
    ) -> jax.Array:
        if self.window_pattern not in ("all", "alternate"):
            raise ValueError(
                f"window_pattern must be 'all' or 'alternate', got "
                f"{self.window_pattern!r}"
            )

        def body(mdl: TransformerBlock, h: jax.Array) -> jax.Array:
            # mask/train close over: constants to jax.checkpoint (no grads
            # flow to them — mask is boolean, train is a Python bool).
            return mdl(h, mask, train)

        policy = remat_policy(self.remat)
        if policy is not None:
            if self.decode:
                raise ValueError(
                    "decode=True does not compose with remat: the KV-cache "
                    "mutation inside jax.checkpoint is unsupported (and "
                    "pointless — decode is inference, there is no backward)"
                )
            body = nn.remat(body, policy=policy)
        for i in range(self.depth):
            is_moe = (
                self.num_experts > 0 and i % self.moe_every == self.moe_every - 1
            )
            block = TransformerBlock(
                num_heads=self.num_heads,
                head_dim=self.head_dim,
                mlp_dim=self.mlp_dim,
                dtype=self.dtype,
                dropout_rate=self.dropout_rate,
                attn_impl=self.attn_impl,
                causal=self.causal,
                decode=self.decode,
                rope=self.rope,
                rope_theta=self.rope_theta,
                rope_scaling=self.rope_scaling,
                rope_dim=self.rope_dim,
                num_kv_heads=self.num_kv_heads,
                fused_qkv=self.fused_qkv,
                quant=self.quant,
                window=(self.window
                        if self.window_pattern == "all" or i % 2 == 0
                        else None),
                rolling_cache=self.rolling_cache,
                paged_blocks=self.paged_blocks,
                kv_block=self.kv_block,
                kv_quant=self.kv_quant,
                attn_scale=self.attn_scale,
                attn_logit_cap=self.attn_logit_cap,
                norm_style=self.norm_style,
                norm=self.norm,
                mlp_act=self.mlp_act,
                use_bias=self.use_bias,
                qkv_bias=self.qkv_bias,
                qk_norm=self.qk_norm,
                ln_eps=self.ln_eps,
                num_experts=self.num_experts if is_moe else 0,
                experts_per_token=self.experts_per_token,
                moe_capacity_factor=self.moe_capacity_factor,
                moe_normalize_topk=self.moe_normalize_topk,
                moe_shared_expert_dim=self.moe_shared_expert_dim,
                router_z_loss_weight=self.router_z_loss_weight,
                name=f"block_{i}",
            )
            x = body(block, x)
        if self.norm_style == "post":
            return x  # post-LN blocks already end normalized
        norm_cls = nn.RMSNorm if self.norm == "rms" else nn.LayerNorm
        return norm_cls(
            epsilon=self.ln_eps, dtype=jnp.float32, param_dtype=jnp.float32,
            name="ln_final",
        )(x)
