"""The shared preemption signal handler, hoisted out of training/lifecycle.

SIGTERM/SIGINT-safe training (the restart-tolerance contract,
mnist_keras:245-248, extended to preemption: TPU pools SIGTERM their
workers, and losing up to save_checkpoints_steps-1 steps on every
preemption is real lost work — VERDICT r4 weak #6).

The handler only sets a flag (async-signal-safe); the train loop polls it
each step, breaks, and its normal tail force-saves and waits for the async
commit. The first signal also RESTORES the previous handler, so a second
signal kills immediately — the operator's escape hatch if the save itself
wedges. After the commit, the loop re-raises the signal under the restored
handler so the process exits with the signal's semantics (SIGTERM ->
killed-by-15, SIGINT -> KeyboardInterrupt) instead of pretending the run
finished.

Signal handlers can only be installed from the main thread; anywhere else
(the concurrent evaluator, tests driving train() from a worker thread) the
guard is inert and behavior is unchanged.

Known limit, on purpose: a signal landing while the loop is blocked in
next(feed) is acted on when the next batch arrives — a flag-setting handler
is the only one that cannot corrupt the step in flight (a raising handler
would surface at an arbitrary bytecode, e.g. after the step donated the
previous state's buffers but before the new state bound, leaving nothing
valid to save). A feed stalled past the pool's SIGKILL grace therefore
still loses the window since the last periodic save; the second signal
(default handler) is the immediate kill.

New here (vs the lifecycle-era private class): the supervisor composes with
the restore-previous-handler design. When `Supervisor` runs in
resume-on-preemption mode it installs its OWN outer handler (one that
raises `Preempted`) *before* entering the train loop; the guard saves that
handler as "previous", so the post-commit re-raise lands in the
supervisor's handler instead of the process default — the checkpoint is
committed first, then the supervisor restarts the loop from it. Production
runs without a supervisor keep the exact old exit-by-signal behavior.
"""

from __future__ import annotations

import logging
import signal
import threading
from typing import Optional

log = logging.getLogger(__name__)


class Preempted(BaseException):
    """Raised (by a supervisor's outer handler) after a preemption signal's
    checkpoint has committed. BaseException on purpose — an `except
    Exception` inside user data code must not swallow a preemption."""

    def __init__(self, signum: int):
        super().__init__(f"preempted by signal {signum}")
        self.signum = signum


class PreemptionGuard:
    """See module docstring. Context manager; `fired` is the signum of the
    first caught signal, None otherwise."""

    _SIGNUMS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self):
        self.fired: Optional[int] = None
        self._prev: dict = {}

    def __enter__(self) -> "PreemptionGuard":
        if threading.current_thread() is threading.main_thread():
            for s in self._SIGNUMS:
                try:
                    self._prev[s] = signal.signal(s, self._handle)
                except (ValueError, OSError):  # exotic embedding; stay inert
                    pass
        return self

    def _handle(self, signum, frame):
        self.fired = signum
        signal.signal(signum, self._prev.get(signum, signal.SIG_DFL))
        self._prev.pop(signum, None)

    def __exit__(self, *exc) -> bool:
        # list(): a signal landing mid-restore pops from _prev via the
        # still-installed handler; iterating the live dict would raise and
        # swallow the re-raise below
        for s, h in list(self._prev.items()):
            try:
                signal.signal(s, h)
            except (ValueError, OSError):
                pass
        self._prev.clear()
        return False

    def reraise_if_fired(self, saved_step: Optional[int]) -> None:
        if self.fired is None:
            return
        if saved_step is not None:
            log.warning(
                "preemption signal %d: checkpoint at step %d committed; "
                "re-raising", self.fired, saved_step,
            )
        else:
            log.warning(
                "preemption signal %d: NO checkpoint manager configured "
                "(model_dir/save_checkpoints_steps unset) — progress since "
                "start is lost; re-raising", self.fired,
            )
        signal.raise_signal(self.fired)
