"""Deterministic fault injection — the test substrate for the resilience
subsystem.

A fault story that is only exercised by real outages is untested code. This
module lets tests (and chaos drills) wrap any callable with a *seeded,
deterministic* failure schedule: raise IOError on the Nth call, deliver
SIGTERM when the training step counter reaches k, inject latency to trip the
stall watchdog. Schedules are plain data, so a test reads as "calls 2 and 3
fail, everything else passes" — no monkeypatching races, no flaky
probability.

Three layers:
- actions: `RaiseFault`, `DelayFault`, `SignalFault` — what happens when a
  schedule entry fires;
- `FaultSchedule`: call-index -> action map, plus `seeded(...)` for
  pseudo-random-but-reproducible schedules;
- `FaultInjector`: wraps a callable (or patches an attribute, as a context
  manager) and consults the schedule on every call.

`StepFaults` is the training-loop face: an input-iterator wrapper that
fires actions keyed by *step number* — e.g. SIGTERM at step k, simulating a
TPU-pool preemption exactly where the scheduler would deliver it.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import random
import signal as _signal
import time
from typing import Callable, Dict, Iterable, Iterator, Optional, Union

from tfde_tpu.observability import counters

log = logging.getLogger(__name__)


# -- actions -----------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class RaiseFault:
    """Raise `exc_type(message)` instead of running the callable."""

    exc_type: type = IOError
    message: str = "injected fault"

    def fire(self, where: str) -> None:
        counters.incr("resilience/faults_injected")
        log.info("fault injection: raising %s at %s", self.exc_type.__name__, where)
        raise self.exc_type(f"{self.message} [{where}]")


@dataclasses.dataclass(frozen=True)
class DelayFault:
    """Sleep `seconds` before running the callable — models a stalled
    storage endpoint or a wedged collective; the substrate for watchdog
    tests."""

    seconds: float = 1.0
    sleep: Callable[[float], None] = time.sleep

    def fire(self, where: str) -> None:
        counters.incr("resilience/faults_injected")
        log.info("fault injection: %.2fs delay at %s", self.seconds, where)
        self.sleep(self.seconds)


@dataclasses.dataclass(frozen=True)
class SignalFault:
    """Deliver `signum` to this process — the preemption simulator (TPU
    pools SIGTERM their workers)."""

    signum: int = _signal.SIGTERM

    def fire(self, where: str) -> None:
        counters.incr("resilience/faults_injected")
        log.info("fault injection: signal %d at %s", self.signum, where)
        os.kill(os.getpid(), self.signum)


@dataclasses.dataclass(frozen=True)
class PeerLossFault:
    """Simulate losing a peer rank — the elastic-path drill. Registers the
    suspicion with the elastic layer (as the heartbeat staleness detector
    would) and then fails the way the survivor's next collective does:
    with a `PeerLostError` the supervisor classifies as TOPOLOGY. Makes
    shrink-and-resume drillable in a single process, not only in the
    2-process kill test."""

    rank: int = 1
    reason: str = "injected peer loss"

    def fire(self, where: str) -> None:
        counters.incr("resilience/faults_injected")
        log.info("fault injection: peer rank %d lost at %s", self.rank, where)
        from tfde_tpu.resilience import elastic

        elastic.note_peer_lost(self.rank, self.reason)
        raise elastic.PeerLostError(self.rank, f"{self.reason} [{where}]")


@dataclasses.dataclass(frozen=True)
class OverloadFault:
    """Force the serving admission layer into saturation for `seconds` —
    the overload drill's lever. Unlike the other actions it does not
    raise or stall the wrapped call: it arms a process-wide switch
    (`inference/admission.force_overload`) that makes every
    AdmissionController reject with QueueFull, so the full 429 /
    Retry-After / brownout path is exercised without generating
    2x-capacity load."""

    seconds: float = 5.0

    def fire(self, where: str) -> None:
        counters.incr("resilience/faults_injected")
        log.info("fault injection: forced overload for %.1fs at %s",
                 self.seconds, where)
        from tfde_tpu.inference import admission

        admission.force_overload(self.seconds)


Action = Union[RaiseFault, DelayFault, SignalFault, PeerLossFault,
               OverloadFault]


# -- schedules ---------------------------------------------------------------
class FaultSchedule:
    """1-based call-index -> action. Immutable once built; the injector
    keeps the mutable call counter so one schedule can arm many injectors."""

    def __init__(self, plan: Optional[Dict[int, Action]] = None):
        bad = [k for k in (plan or {}) if k < 1]
        if bad:
            raise ValueError(f"call indices are 1-based; got {sorted(bad)}")
        self._plan: Dict[int, Action] = dict(plan or {})

    @classmethod
    def fail_on(cls, *call_indices: int, exc_type: type = IOError,
                message: str = "injected fault") -> "FaultSchedule":
        """Raise-on-Nth-call, the workhorse: `fail_on(1, 2)` makes the
        first two calls fail and the rest succeed."""
        a = RaiseFault(exc_type=exc_type, message=message)
        return cls({i: a for i in call_indices})

    @classmethod
    def slow_on(cls, *call_indices: int, seconds: float = 1.0,
                sleep: Callable[[float], None] = time.sleep) -> "FaultSchedule":
        return cls({i: DelayFault(seconds=seconds, sleep=sleep) for i in call_indices})

    @classmethod
    def seeded(cls, seed: int, n_calls: int, p_fail: float,
               action: Optional[Action] = None) -> "FaultSchedule":
        """Reproducible pseudo-random schedule: each of the first `n_calls`
        calls independently fails with probability `p_fail` under `seed`.
        Same seed -> same schedule, across processes and runs."""
        rng = random.Random(seed)
        action = action or RaiseFault()
        return cls({i: action for i in range(1, n_calls + 1) if rng.random() < p_fail})

    def action_for(self, call_index: int) -> Optional[Action]:
        return self._plan.get(call_index)

    @property
    def plan(self) -> Dict[int, Action]:
        return dict(self._plan)

    def __repr__(self) -> str:
        return f"FaultSchedule({self._plan!r})"


# -- injectors ---------------------------------------------------------------
class FaultInjector:
    """Wrap a callable so each call first consults the schedule.

    Also a context manager that patches `obj.attr` in place (and restores on
    exit) so production call sites need zero test hooks:

        with FaultInjector(schedule).patch(manager, "save"):
            ...  # the 2nd manager.save(...) raises IOError
    """

    def __init__(self, schedule: FaultSchedule, name: str = ""):
        self.schedule = schedule
        self.name = name
        self.calls = 0
        self._patches = []

    def wrap(self, fn: Callable) -> Callable:
        def inner(*args, **kwargs):
            self.calls += 1
            action = self.schedule.action_for(self.calls)
            if action is not None:
                action.fire(f"{self.name or getattr(fn, '__qualname__', 'call')}#{self.calls}")
            return fn(*args, **kwargs)

        return inner

    def patch(self, obj, attr: str) -> "FaultInjector":
        self._patches.append((obj, attr, getattr(obj, attr)))
        setattr(obj, attr, self.wrap(getattr(obj, attr)))
        return self

    def __enter__(self) -> "FaultInjector":
        return self

    def __exit__(self, *exc) -> None:
        while self._patches:
            obj, attr, orig = self._patches.pop()
            setattr(obj, attr, orig)


class StepFaults:
    """Training-loop fault injection: wrap an input iterable so that the
    batch draw for step k (1-based, counted from this process's first draw)
    first fires the scheduled action — `{k: SignalFault()}` is "preempt at
    step k", `{k: DelayFault(s)}` is "stall step k".

    Counted per *process attempt* on purpose: a restarted run re-arms from
    1, so `fires_once=True` (default) disarms an action after it fires —
    otherwise a SIGTERM at step 5 would re-preempt every restart that
    passes step 5 and the run could never finish.
    """

    def __init__(self, plan: Dict[int, Action], fires_once: bool = True):
        self._plan = dict(plan)
        self._fires_once = fires_once

    def wrap(self, batches: Iterable) -> Iterator:
        def gen():
            step = 0
            for b in batches:
                step += 1
                action = self._plan.get(step)
                if action is not None:
                    if self._fires_once:
                        del self._plan[step]
                    action.fire(f"step#{step}")
                yield b

        return gen()

    def wrap_input_fn(self, input_fn: Callable[[], Iterable]) -> Callable[[], Iterator]:
        return lambda: self.wrap(input_fn())
