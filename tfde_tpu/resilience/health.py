"""Per-process heartbeat and stall detection.

A wedged collective (one slice dropped out of a DCN rendezvous), a hung
storage read, or a poisoned input pipeline all present the same way: the
step counter stops moving while the process stays alive — invisible to a
scheduler that only watches liveness. The reference stack leaned on TF's
session timeouts; here the watchdog is explicit: the train loop (or the
supervisor's input wrapper) calls `Heartbeat.beat(step)` as progress
happens, and a daemon thread checks the age of the last beat. When it
exceeds `stall_timeout_secs` the watchdog *escalates*: by default it
delivers SIGTERM to its own process, which lands in the preemption guard —
so escalation IS checkpoint-and-exit, riding the exact force-save/commit
path a pool preemption takes. Under a supervisor that same path becomes
checkpoint-and-restart.

Metrics exported through the observability registry:
- ``resilience/stalls_detected``       counter — watchdog firings
- ``resilience/heartbeats``            counter — total beats (rate ~ steps/sec)
- ``resilience/last_step``             gauge — step of the latest beat
- ``resilience/heartbeat_age_seconds`` gauge — staleness at last watchdog poll

Cluster-level health (fed by observability/aggregate.py's straggler and
staleness detectors on the chief):
- ``resilience/stragglers_detected``   counter — straggler flaggings
- ``resilience/straggler_host``        gauge — slowest flagged host (-1 ok)
- ``resilience/straggler_ratio``       gauge — its median / cluster median
- ``resilience/stale_hosts_detected``  counter — hosts gone silent
"""

from __future__ import annotations

import dataclasses
import logging
import os
import signal as _signal
import threading
import time
from typing import Callable, Optional

from tfde_tpu.observability import counters, metrics

log = logging.getLogger(__name__)


class StallError(Exception):
    """Raised by `Heartbeat.check()` (the poll-style API) when the last
    beat is older than the stall timeout. Classified as restartable by the
    supervisor: a stall is environmental until proven otherwise."""

    def __init__(self, age: float, last_step: Optional[int]):
        super().__init__(
            f"no step progress for {age:.1f}s (last step: {last_step})"
        )
        self.age = age
        self.last_step = last_step


def _default_escalation() -> None:
    """Checkpoint-and-exit: SIGTERM self, landing in the preemption guard's
    force-save path (resilience/preemption.py)."""
    os.kill(os.getpid(), _signal.SIGTERM)


def note_straggler(host: int, ratio: float) -> None:
    """Chief-side sink for the cluster straggler detector
    (observability/aggregate.py): a host's rolling step-time median exceeds
    the cluster median by the configured factor. Exported as resilience
    gauges so dashboards and the supervisor's TB export see it, and
    recorded in the flight ring for post-mortems."""
    counters.incr("resilience/stragglers_detected")
    metrics.gauge("resilience/straggler_host").set(host)
    metrics.gauge("resilience/straggler_ratio").set(ratio)
    from tfde_tpu.observability import flightrec

    flightrec.record("straggler", host=int(host), ratio=float(ratio))


def note_replica_down(replica: int, reason: str) -> None:
    """Router-side sink for a lost SERVING replica (inference/router.py):
    a connection failure or stale metric pushes took it out of rotation.
    Mirrors the training-side host sinks so the same dashboards and
    flight-ring reads cover serving incidents."""
    counters.incr("resilience/replicas_lost")
    metrics.gauge("resilience/last_replica_lost").set(replica)
    from tfde_tpu.observability import flightrec

    flightrec.record("replica_lost", replica=int(replica),
                     reason=str(reason))


def note_stale_host(host: int, age_seconds: float) -> None:
    """Chief-side sink for the dead-host detector: a host stopped pushing
    snapshots. Liveness itself is per-host (the scheduler's job); this is
    the fleet-view breadcrumb."""
    counters.incr("resilience/stale_hosts_detected")
    from tfde_tpu.observability import flightrec

    flightrec.record("stale_host", host=int(host),
                     age_seconds=round(float(age_seconds), 3))
    # elastic suspicion: a host silent past the detect threshold becomes a
    # topology suspect, so the next TOPOLOGY-classified failure shrinks
    # around *evidence* instead of presumption (resilience/elastic.py)
    from tfde_tpu.resilience import elastic

    ecfg = elastic.resolve(None)
    if ecfg is not None and float(age_seconds) >= ecfg.detect_timeout_secs:
        elastic.note_peer_lost(
            int(host), f"no metric pushes for {float(age_seconds):.1f}s")


@dataclasses.dataclass
class Heartbeat:
    """Progress tracker + optional background watchdog.

    Use poll-style (`beat` + `check`) from a loop that owns its cadence, or
    `start_watchdog()` for a daemon thread that escalates on its own. The
    clock is injectable so tests run in virtual time.
    """

    stall_timeout_secs: float = 300.0
    clock: Callable[[], float] = time.monotonic
    on_stall: Callable[[], None] = _default_escalation

    def __post_init__(self):
        if self.stall_timeout_secs <= 0:
            raise ValueError("stall_timeout_secs must be positive")
        self._lock = threading.Lock()
        self._last_beat: Optional[float] = None
        self._last_step: Optional[int] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._stalled = False

    # -- progress ------------------------------------------------------------
    def beat(self, step: Optional[int] = None) -> None:
        counters.incr("resilience/heartbeats")
        if step is not None:
            metrics.gauge("resilience/last_step").set(step)
        with self._lock:
            self._last_beat = self.clock()
            if step is not None:
                self._last_step = int(step)

    @property
    def last_step(self) -> Optional[int]:
        with self._lock:
            return self._last_step

    def age(self) -> float:
        """Seconds since the last beat (or since construction-time arm via
        the first check/watchdog tick when no beat has happened yet)."""
        with self._lock:
            if self._last_beat is None:
                self._last_beat = self.clock()  # arm on first observation
            return self.clock() - self._last_beat

    # -- poll-style ----------------------------------------------------------
    def check(self) -> None:
        """Raise StallError when the last beat is too old. For loops that
        interleave their own watchdog polling (e.g. the supervisor between
        restart attempts)."""
        a = self.age()
        if a > self.stall_timeout_secs:
            counters.incr("resilience/stalls_detected")
            raise StallError(a, self.last_step)

    # -- watchdog thread -----------------------------------------------------
    def start_watchdog(self, poll_secs: Optional[float] = None) -> "Heartbeat":
        """Start the daemon watchdog; fires `on_stall` ONCE per stall (the
        flag re-arms on the next beat, so a recovered-then-wedged-again
        process escalates again)."""
        if self._thread is not None:
            return self
        poll = poll_secs if poll_secs is not None else max(0.1, self.stall_timeout_secs / 10.0)

        from tfde_tpu.observability import flightrec

        def run():
            while not self._stop.wait(poll):
                a = self.age()
                metrics.gauge("resilience/heartbeat_age_seconds").set(a)
                # watchdog-cadence health beats in the flight ring: cheap
                # (one event per poll, not per step) and exactly the "was it
                # alive, was it progressing" trail a post-mortem wants
                flightrec.record("health_beat", age_seconds=round(a, 3),
                                 last_step=self.last_step)
                if a > self.stall_timeout_secs:
                    if not self._stalled:
                        self._stalled = True
                        counters.incr("resilience/stalls_detected")
                        log.error(
                            "stall detected: no progress for %.1fs (last "
                            "step %s); escalating", a, self.last_step,
                        )
                        flightrec.record("stall", age_seconds=round(a, 3),
                                         last_step=self.last_step)
                        try:
                            self.on_stall()
                        except Exception:
                            log.exception("stall escalation callback failed")
                else:
                    self._stalled = False

        self._thread = threading.Thread(target=run, daemon=True, name="stall-watchdog")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "Heartbeat":
        return self.start_watchdog()

    def __exit__(self, *exc) -> None:
        self.stop()
