"""Elastic training: survive a topology change, not just a restart.

The supervisor (resilience/supervisor.py) made single-topology restarts
boringly reliable — but a preempted pod slice *changes N*. Restarting at
the old world size then deadlocks in `jax.distributed.initialize` waiting
for hosts that will never come back. This module is the missing leg: when
a failure is classified TOPOLOGY (a peer died under us), the supervisor
tears the old runtime down, re-resolves the cluster from the surviving
hosts, and resumes from the latest checkpoint at the new world size. The
checkpoint layer already restores M-way state onto an N-way mesh
(checkpoint/manager.py::_restore_cross_format + parallel/zero.py::
relayout_opt_state), so elasticity here is cluster plumbing, not math.

The three mechanisms:

- **suspicion registry** — `note_peer_lost(rank, reason)` is the sink for
  every peer-death signal: the chief's staleness detector
  (resilience/health.py::note_stale_host), the `PeerLossFault` drill
  (resilience/faults.py), or application code that caught a dead socket.
  Suspects accumulate until the next `rebootstrap()` consumes them.
- **env shrink** — `shrink_env()` rewrites the cluster contract
  (TF_CONFIG / CLUSTER_SPEC / TFDE_*) to the dense re-ranking of the
  survivors, with coordinator re-election = lowest surviving rank's host.
  It only runs when a fresh `resolve_cluster()` still matches the dead
  topology — a scheduler that already rewrote the env wins outright.
- **re-bootstrap** — `rebootstrap()` sequences teardown
  (cluster.shutdown), env shrink, backend clearing (only when a
  distributed runtime was actually up — never in single-process drills
  sharing a backend with live arrays), and `cluster.bootstrap()` at the
  new N. The transition is observable: `cluster/world_size` gauge,
  `resilience/topology_changes` counter, `resilience/rebootstrap_seconds`
  (charged to the goodput ledger's ``restart_loss``), and a
  `topology_change` flight-recorder breadcrumb.

Semantic continuity is the caller's half of the contract: the input_fn
must re-derive its per-process batch from the *current* world so the
global batch — and with it the loss trajectory and the LR schedule
position — is preserved across the shrink. `per_process_batch()` does the
division; `note_batch()` (called by the lifecycle at every train start)
logs the re-tune line and drops the `batch_retune` breadcrumb when the
world changed between segments.

Enabled by `SupervisorConfig.elastic` or the ``TFDE_ELASTIC`` knob
(off by default — see ``TFDE_ELASTIC_*`` in knobs.py).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
from typing import Dict, Iterable, Optional, Tuple, Union

from tfde_tpu import knobs
from tfde_tpu.observability import counters, metrics
from tfde_tpu.runtime import cluster

log = logging.getLogger(__name__)


class PeerLostError(RuntimeError):
    """A peer process is gone (heartbeat silence, dead socket, injected
    drill). Classified as TOPOLOGY by the supervisor: restartable, but only
    after an elastic re-bootstrap at the surviving world size."""

    def __init__(self, rank: int, reason: str = "peer lost"):
        super().__init__(f"peer rank {rank} lost: {reason}")
        self.rank = int(rank)
        self.reason = str(reason)


@dataclasses.dataclass(frozen=True)
class ElasticConfig:
    """Elastic re-bootstrap policy (env defaults: ``TFDE_ELASTIC_*``)."""

    #: topology changes allowed across one supervised run — a cluster that
    #: keeps losing hosts converges to min_world and then to an abort
    max_topology_changes: int = 4
    #: heartbeat-staleness age at which a silent host becomes a suspect
    #: (consumed by health.note_stale_host's forwarding gate)
    detect_timeout_secs: float = 5.0
    #: when a collective dies with NO identified peer, presume every other
    #: rank lost and shrink to self. The only rank a survivor can vouch for
    #: without evidence is itself; real deployments pair this with the
    #: scheduler's env rewrite (which wins) or heartbeat evidence.
    presume_lost_without_evidence: bool = True
    #: abort instead of resuming when the surviving world is smaller
    min_world: int = 1


def resolve(value: Union[None, bool, ElasticConfig] = None
            ) -> Optional[ElasticConfig]:
    """Normalize a config knob: an ElasticConfig passes through, False
    disables, True forces the env-tuned config, and None defers to the
    ``TFDE_ELASTIC`` flag (off by default)."""
    if isinstance(value, ElasticConfig):
        return value
    if value is False:
        return None
    if value is None and not knobs.env_flag("TFDE_ELASTIC", False):
        return None
    return ElasticConfig(
        max_topology_changes=knobs.env_int("TFDE_ELASTIC_MAX_CHANGES", 4),
        detect_timeout_secs=knobs.env_float(
            "TFDE_ELASTIC_DETECT_TIMEOUT_S", 5.0),
        presume_lost_without_evidence=knobs.env_flag(
            "TFDE_ELASTIC_PRESUME_LOST", True),
        min_world=knobs.env_int("TFDE_ELASTIC_MIN_WORLD", 1),
    )


# -- suspicion registry --------------------------------------------------------
_lock = threading.Lock()
_suspects: Dict[int, str] = {}


def note_peer_lost(rank: int, reason: str) -> None:
    """Register a suspected-dead peer. Every detection channel funnels here
    (staleness detector, fault drill, application socket errors); the next
    `rebootstrap()` consumes the set. Re-noting a known suspect is free —
    detectors poll, and one flight breadcrumb per peer is enough."""
    rank = int(rank)
    with _lock:
        known = rank in _suspects
        _suspects[rank] = str(reason)
    if known:
        return
    counters.incr("resilience/peers_lost")
    log.warning("peer rank %d suspected lost: %s", rank, reason)
    from tfde_tpu.observability import flightrec

    flightrec.record("peer_lost", rank=rank, reason=str(reason))


def suspects() -> Dict[int, str]:
    """Snapshot of currently suspected-dead ranks -> reason."""
    with _lock:
        return dict(_suspects)


def clear_suspects() -> None:
    with _lock:
        _suspects.clear()


# -- failure-shape heuristics --------------------------------------------------
#: lowercase substrings of the errors a survivor's collective raises when
#: its peer's half of the connection died (gloo/grpc spellings observed on
#: the CPU rehearsal backend and DCN)
_PEER_LOSS_PATTERNS = (
    "connection reset",
    "connection closed",
    "connection refused",
    "connection aborted",
    "broken pipe",
    "socket",
    "gloo",
    "recv",
    "peer",
    "unavailable",
    "deadline exceeded",
)


def looks_like_peer_loss(exc: BaseException) -> bool:
    """Heuristic upgrade for errors that reach the supervisor untyped: a
    RuntimeError/OSError whose message smells like a dead peer's half-open
    connection. Only consulted when elastic is enabled AND the run is
    distributed — a local file-descriptor error must not trigger a
    topology change."""
    if isinstance(exc, PeerLostError):
        return True
    if not isinstance(exc, (RuntimeError, ConnectionError, OSError)):
        return False
    msg = str(exc).lower()
    return any(p in msg for p in _PEER_LOSS_PATTERNS)


def in_distributed_run() -> bool:
    """True when this process is (or was configured to be) part of a
    multi-process cluster — the gate on the peer-loss heuristic."""
    info = cluster.last_info()
    if info is None:
        info = cluster.resolve_cluster()
    return info.is_distributed


# -- env shrink ----------------------------------------------------------------
def shrink_env(old: cluster.ClusterInfo,
               lost_ranks: Iterable[int]) -> Tuple[int, int]:
    """Rewrite the cluster env contract to the dense re-ranking of the
    survivors of `old` minus `lost_ranks`; returns (new_world, new_rank)
    for this process.

    Coordinator re-election = lowest surviving rank's host, which the
    TF_CONFIG path expresses naturally (survivor list order IS rank
    order). The bare ``TFDE_*`` contract carries no per-rank host list, so
    losing rank 0 under it is only recoverable when the surviving world is
    1 (no coordinator needed) — otherwise the scheduler must rewrite the
    env, which `refresh_if_changed()` picks up.
    """
    lost = sorted({int(r) for r in lost_ranks})
    if old.process_id in lost:
        raise ValueError(
            f"cannot shrink around self: rank {old.process_id} is in the "
            f"lost set {lost}")
    survivors = [r for r in range(old.num_processes) if r not in lost]
    new_world = len(survivors)
    new_rank = survivors.index(old.process_id)

    # when the old coordinator survives into a multi-survivor world, its
    # abandoned coordination service still holds the old port (teardown of
    # a dead topology's runtime is fatal — see cluster.shutdown); every
    # survivor deterministically derives the SAME successor port
    def _bump_port(addr: str) -> str:
        host, _, port = addr.rpartition(":")
        return f"{host}:{int(port) + 1}" if port.isdigit() and host else addr

    rebind = 0 not in lost and new_world > 1

    raw = os.environ.get("TF_CONFIG")
    if raw:
        try:
            cfg = json.loads(raw)
        except json.JSONDecodeError:
            cfg = None
        if cfg and "cluster" in cfg:
            cl = cfg["cluster"]
            ranked = (list(cl.get("chief", []) or cl.get("master", []))
                      + list(cl.get("worker", [])))
            if len(ranked) == old.num_processes:
                hosts = [ranked[r] for r in survivors]
                if rebind:
                    hosts[0] = _bump_port(hosts[0])
                # all survivors are plain workers in the new spec: rank 0
                # of the dense re-ranking is the chief by position
                # (cluster._rank_from_tf_config normalizes worker 0 with
                # no chief entry to the chief role)
                os.environ["TF_CONFIG"] = json.dumps({
                    "cluster": {"worker": hosts},
                    "task": {"type": "worker", "index": new_rank},
                })
                if os.environ.get("CLUSTER_SPEC"):
                    os.environ["CLUSTER_SPEC"] = json.dumps({"worker": hosts})
                    os.environ["TASK_INDEX"] = str(new_rank)
                    os.environ["JOB_NAME"] = "worker"

    if os.environ.get("TFDE_NUM_PROCESSES"):
        os.environ["TFDE_NUM_PROCESSES"] = str(new_world)
        os.environ["TFDE_PROCESS_ID"] = str(new_rank)
        if rebind and os.environ.get("TFDE_COORDINATOR"):
            os.environ["TFDE_COORDINATOR"] = _bump_port(
                os.environ["TFDE_COORDINATOR"])
        if 0 in lost and os.environ.get("TFDE_COORDINATOR"):
            if new_world == 1:
                os.environ.pop("TFDE_COORDINATOR", None)
            else:
                log.warning(
                    "lost rank 0 under the bare TFDE_* contract with %d "
                    "survivors: no host list to re-elect a coordinator "
                    "from — keeping the stale TFDE_COORDINATOR and hoping "
                    "the scheduler rewrites it", new_world)

    from tfde_tpu.observability import flightrec

    flightrec.record("env_shrunk", old_world=old.num_processes,
                     new_world=new_world, new_rank=new_rank,
                     lost_ranks=lost)
    log.warning("cluster env shrunk: world %d -> %d (lost ranks %s; this "
                "process re-ranked %d -> %d)",
                old.num_processes, new_world, lost, old.process_id, new_rank)
    return new_world, new_rank


# -- re-bootstrap --------------------------------------------------------------
def rebootstrap(cfg: ElasticConfig, cause: str = "") -> cluster.ClusterInfo:
    """Tear down the dead topology and come back up at the surviving world
    size. Called by the supervisor at the TOP of the next attempt (after
    the failed Estimator closed), never inside the failure handler.

    Sequence: consume suspects -> cluster.shutdown() -> fresh env resolve
    (a scheduler rewrite wins; otherwise shrink around the suspects, or —
    with no evidence and `presume_lost_without_evidence` — around
    everyone but self) -> clear backends iff a distributed runtime was
    actually up -> cluster.bootstrap() at the new N.
    """
    t0 = time.monotonic()
    was_up = cluster.initialized()
    # the topology the failed run was ACTUALLY using: the live runtime's
    # when one is up, else the env contract (a stale last_info() from an
    # earlier unrelated bootstrap must not shadow the current spec)
    old = (cluster.last_info() if was_up else None) or cluster.resolve_cluster()
    lost = suspects()
    # abandon, don't bid farewell: the graceful protocol's cluster-wide
    # shutdown barrier can never complete once a peer died
    cluster.shutdown(abandon=True)
    fresh = cluster.resolve_cluster()
    if fresh == old and old.is_distributed:
        if not lost and cfg.presume_lost_without_evidence:
            lost = {r: "presumed lost (no evidence)"
                    for r in range(old.num_processes) if r != old.process_id}
        if lost:
            shrink_env(old, lost.keys())
    elif fresh != old:
        log.warning("cluster env changed under the failure (%s -> %s): "
                    "the scheduler's rewrite wins over local suspicion",
                    old, fresh)
    clear_suspects()

    if was_up:
        # executables and arrays are bound to the dead process group's
        # runtime; clearing forces re-creation against the new one. Never
        # done when no distributed runtime was up: a single-process drill
        # shares its backend with every live array in the process.
        import jax

        jax.extend.backend.clear_backends()

    info = cluster.bootstrap()
    if info.num_processes < cfg.min_world:
        raise RuntimeError(
            f"elastic re-bootstrap resolved world {info.num_processes} < "
            f"min_world {cfg.min_world}; refusing to resume")
    dt = time.monotonic() - t0
    counters.incr("resilience/topology_changes")
    # pure restart tax: the goodput ledger folds this into restart_loss
    counters.incr("resilience/rebootstrap_seconds", dt)
    metrics.gauge("cluster/world_size").set(info.num_processes)
    from tfde_tpu.observability import flightrec

    flightrec.record("topology_change", old_world=old.num_processes,
                     new_world=info.num_processes,
                     process_id=info.process_id,
                     lost_ranks=sorted(lost), cause=str(cause),
                     seconds=round(dt, 3))
    log.warning("elastic re-bootstrap: world %d -> %d (rank %d, %.2fs%s)",
                old.num_processes, info.num_processes, info.process_id, dt,
                f", cause: {cause}" if cause else "")
    return info


def refresh_if_changed() -> Optional[cluster.ClusterInfo]:
    """Re-read the cluster env and force a re-bootstrap when it no longer
    matches the running topology. The supervisor calls this once per
    restart attempt, so a scheduler that rewrites TF_CONFIG / TFDE_*
    between attempts (replacement hosts, a grown slice) is picked up
    instead of silently ignored. Returns the new ClusterInfo on change,
    None when unchanged or never bootstrapped."""
    old = cluster.last_info()
    if old is None:
        return None
    fresh = cluster.resolve_cluster()
    # compare only the fields that place processes — a job-type label
    # drift ("chief" vs "local" for the same 1-process world) is not a
    # topology change and must not force a re-bootstrap
    if (fresh.num_processes == old.num_processes
            and fresh.process_id == old.process_id
            and fresh.coordinator_address == old.coordinator_address):
        return None
    log.warning("cluster spec changed between attempts (%s -> %s); "
                "re-bootstrapping", old, fresh)
    was_up = cluster.initialized()
    cluster.shutdown()
    if was_up:
        import jax

        jax.extend.backend.clear_backends()
    info = cluster.bootstrap()
    counters.incr("resilience/topology_changes")
    from tfde_tpu.observability import flightrec

    flightrec.record("topology_change", old_world=old.num_processes,
                     new_world=info.num_processes,
                     process_id=info.process_id, lost_ranks=[],
                     cause="env_rewrite", seconds=0.0)
    return info


# -- semantic continuity -------------------------------------------------------
_LAST_SEGMENT: Optional[Tuple[int, int]] = None  # (world, per-process batch)


def per_process_batch(global_batch: int, world: Optional[int] = None) -> int:
    """The re-tuned per-process batch that preserves `global_batch` at the
    current (or given) world size — the caller-side half of semantic
    continuity: same global batch => same loss trajectory and the same LR
    schedule position per optimizer step."""
    if world is None:
        info = cluster.last_info() or cluster.resolve_cluster()
        world = info.num_processes
    if world < 1:
        raise ValueError(f"world must be >= 1, got {world}")
    if global_batch % world:
        raise ValueError(
            f"global batch {global_batch} does not divide over world "
            f"{world}; pick a global batch divisible by every world size "
            f"the run may shrink to")
    return global_batch // world


def note_batch(per_process: int, world: int) -> None:
    """Record the (world, per-process batch) of a starting train segment.
    Sets the `cluster/world_size` gauge; when the world changed since the
    previous segment, logs the re-tune line and drops a `batch_retune`
    flight breadcrumb stating whether the global batch was preserved.
    The caller (training/lifecycle.py) computes the per-process size —
    only it knows whether the host batch is per-host (DATA policy) or the
    full global batch each host slices from (OFF policy)."""
    global _LAST_SEGMENT
    per_proc = int(per_process)
    world = int(world)
    metrics.gauge("cluster/world_size").set(world)
    prev, _LAST_SEGMENT = _LAST_SEGMENT, (world, per_proc)
    if prev is None or prev[0] == world:
        return
    old_world, old_per = prev
    preserved = per_proc > 0 and old_per * old_world == per_proc * world
    log.warning(
        "elastic batch re-tune: world %d -> %d, per-process batch %d -> %d "
        "(global batch %d %s)", old_world, world, old_per, per_proc,
        per_proc * world,
        "preserved" if preserved
        else "CHANGED — loss trajectory and LR schedule position may shift")
    from tfde_tpu.observability import flightrec

    flightrec.record("batch_retune", old_world=old_world, new_world=world,
                     old_per_process=old_per, new_per_process=per_proc,
                     global_batch=per_proc * world, preserved=preserved)
