"""Resilience: retry policies, fault injection, preemption handling, and
the training-run supervisor.

At TPU-pod scale, preemptions and transient ICI/DCN/storage failures are
routine operating conditions, not exceptions. This package makes the fault
story a first-class, independently testable layer:

- policy.py     — composable retry policies (backoff+jitter, deadlines)
- faults.py     — deterministic fault injection (the test substrate)
- preemption.py — the shared SIGTERM/SIGINT guard (hoisted from lifecycle)
- health.py     — heartbeat/stall watchdog, escalates to checkpoint-and-exit
- supervisor.py — bounded restart-from-checkpoint around Estimator.train
- elastic.py    — topology-change survival: shrink the cluster to the
                  survivors and resume from the latest checkpoint
"""

from tfde_tpu.resilience.policy import (  # noqa: F401
    DEFAULT_POLICY,
    NO_RETRY,
    RetryBudgetExceeded,
    RetryPolicy,
    TransientError,
    policy_from_env,
    retry,
    retry_call,
)
from tfde_tpu.resilience.faults import (  # noqa: F401
    DelayFault,
    FaultInjector,
    FaultSchedule,
    PeerLossFault,
    RaiseFault,
    SignalFault,
    StepFaults,
)
from tfde_tpu.resilience.elastic import (  # noqa: F401
    ElasticConfig,
    PeerLostError,
    note_peer_lost,
    per_process_batch,
)
from tfde_tpu.resilience.preemption import Preempted, PreemptionGuard  # noqa: F401
from tfde_tpu.resilience.health import Heartbeat, StallError  # noqa: F401
from tfde_tpu.resilience.supervisor import (  # noqa: F401
    FailureKind,
    Supervisor,
    SupervisorAborted,
    SupervisorConfig,
    classify_failure,
    train_supervised,
)
