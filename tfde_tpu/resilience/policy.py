"""Composable retry policies: exponential backoff + jitter, deadline
budgets, max-attempt caps.

The reference runtime's fault tolerance was monolithic — TF's gRPC layer
retried internally and the Estimator restarted from checkpoints — with no
operator control in between. Here the retry behavior is a first-class value:
a `RetryPolicy` describes *how* to retry, `retry_call`/`retry` apply it to
any fallible callable, and the I/O layers (checkpoint/manager.py,
utils/fs.py, data source opens, runtime bootstrap) take a policy instead of
hand-rolling loops. Everything is injectable (sleep, clock, rng) so tests
run in virtual time, and jitter is seeded so schedules are reproducible.

Classification: only exceptions in `policy.retryable` are retried —
everything else (a structure-mismatch ValueError, a poison-step assertion)
propagates on the first throw. `TransientError` is the marker callers can
raise/wrap to force classification as retryable.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import os
import random
import time
from typing import Callable, Optional, Tuple, Type

from tfde_tpu.observability import counters

log = logging.getLogger(__name__)

# The transient I/O surface: blips on DCN/storage (gs:// timeouts, reset
# connections) present as OSError subclasses or timeouts. IOError is an
# alias of OSError; ConnectionError is an OSError subclass — listed for
# readers, harmless as duplicates.
DEFAULT_RETRYABLE: Tuple[Type[BaseException], ...] = (
    OSError,
    TimeoutError,
    ConnectionError,
)


class TransientError(Exception):
    """Marker for failures the raiser *knows* are transient (worth a retry
    under any policy) even when the underlying type isn't in the policy's
    retryable set."""


class RetryBudgetExceeded(OSError):
    """All attempts (or the deadline budget) were consumed. `__cause__` is
    the last underlying failure; `attempts` is how many were made.

    Subclasses OSError so call sites that guard I/O with `except OSError`
    keep working when the budget (not a single call) is what failed — and
    the supervisor classifies it transient the same way.
    """

    def __init__(self, msg: str, attempts: int):
        super().__init__(msg)
        self.attempts = attempts


#: Deterministic outcomes that happen to be OSErrors — retrying them burns
#: the backoff budget to reach the same answer. Checked before `retryable`.
DEFAULT_NON_RETRYABLE: Tuple[Type[BaseException], ...] = (
    FileNotFoundError,
    FileExistsError,
    IsADirectoryError,
    NotADirectoryError,
    PermissionError,
)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """How to retry: attempts, backoff shape, and a wall-clock budget.

    backoff for attempt k (1-based failures) is
    `min(max_backoff, initial_backoff * multiplier**(k-1))`, scaled by a
    uniform jitter in [1-jitter, 1+jitter] so a fleet of workers retrying
    the same dead storage endpoint doesn't thundering-herd it.

    deadline is the total seconds budget across ALL attempts including
    sleeps; None means attempts alone bound the loop. max_attempts counts
    the first call: max_attempts=1 means no retries.
    """

    max_attempts: int = 4
    initial_backoff: float = 0.2
    max_backoff: float = 30.0
    multiplier: float = 2.0
    jitter: float = 0.25
    deadline: Optional[float] = None
    retryable: Tuple[Type[BaseException], ...] = DEFAULT_RETRYABLE
    non_retryable: Tuple[Type[BaseException], ...] = DEFAULT_NON_RETRYABLE

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {self.max_attempts}")
        if not 0.0 <= self.jitter < 1.0:
            raise ValueError(f"jitter must be in [0, 1), got {self.jitter}")

    def is_retryable(self, exc: BaseException) -> bool:
        if isinstance(exc, TransientError):  # explicit marker wins
            return True
        if isinstance(exc, self.non_retryable):
            return False
        return isinstance(exc, self.retryable)

    def backoff(self, failure_index: int, rng: Optional[random.Random] = None) -> float:
        """Sleep before the (failure_index+1)-th retry; failure_index is
        1-based (first failure -> initial_backoff)."""
        base = self.initial_backoff * (self.multiplier ** (failure_index - 1))
        base = min(self.max_backoff, base)
        if self.jitter and rng is not None:
            base *= rng.uniform(1.0 - self.jitter, 1.0 + self.jitter)
        return max(0.0, base)


#: Conservative default for library I/O paths. NO_RETRY opts a path out
#: without branching at every call site.
DEFAULT_POLICY = RetryPolicy()
NO_RETRY = RetryPolicy(max_attempts=1)


def policy_from_env(prefix: str = "TFDE_RETRY_", base: Optional[RetryPolicy] = None) -> RetryPolicy:
    """Operator knobs (documented in README "Fault tolerance"):

    - ``TFDE_RETRY_MAX_ATTEMPTS`` (int, default 4; 1 disables retries)
    - ``TFDE_RETRY_INITIAL_BACKOFF`` / ``TFDE_RETRY_MAX_BACKOFF`` (seconds)
    - ``TFDE_RETRY_DEADLINE`` (seconds total budget; unset = attempts only)
    """
    base = base or DEFAULT_POLICY
    kw = {}
    for env, field, cast in (
        ("MAX_ATTEMPTS", "max_attempts", int),
        ("INITIAL_BACKOFF", "initial_backoff", float),
        ("MAX_BACKOFF", "max_backoff", float),
        ("DEADLINE", "deadline", float),
    ):
        raw = os.environ.get(prefix + env)
        if raw is None:
            continue
        try:
            kw[field] = cast(raw)
        except ValueError as e:
            raise ValueError(f"{prefix}{env}={raw!r} is not a valid {cast.__name__}") from e
    return dataclasses.replace(base, **kw) if kw else base


def retry_call(
    fn: Callable,
    *args,
    policy: RetryPolicy = DEFAULT_POLICY,
    what: str = "",
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
    rng: Optional[random.Random] = None,
    counter: str = "resilience/retries",
    **kwargs,
):
    """Call `fn(*args, **kwargs)` under `policy`.

    Non-retryable exceptions propagate immediately and untouched. When the
    budget runs out, raises RetryBudgetExceeded from the last failure so
    callers/operators see both the exhaustion and the root cause. Every
    retry increments the `counter` observability counter.
    """
    what = what or getattr(fn, "__qualname__", repr(fn))
    rng = rng if rng is not None else random.Random(0xC0FFEE)
    t0 = clock()
    last: Optional[BaseException] = None
    for attempt in range(1, policy.max_attempts + 1):
        try:
            return fn(*args, **kwargs)
        except BaseException as e:
            if not policy.is_retryable(e):
                raise
            last = e
            if attempt >= policy.max_attempts:
                break
            delay = policy.backoff(attempt, rng)
            if policy.deadline is not None and (clock() - t0) + delay > policy.deadline:
                break
            counters.incr(counter)
            log.warning(
                "%s failed (attempt %d/%d, %s: %s); retrying in %.2fs",
                what, attempt, policy.max_attempts, type(e).__name__, e, delay,
            )
            sleep(delay)
    assert last is not None
    raise RetryBudgetExceeded(
        f"{what}: retry budget exhausted after {policy.max_attempts} "
        f"attempt(s) ({type(last).__name__}: {last})",
        attempts=policy.max_attempts,
    ) from last


def retry(policy: RetryPolicy = DEFAULT_POLICY, **retry_kwargs) -> Callable:
    """Decorator form of `retry_call` for defs owned by this codebase:

        @retry(RetryPolicy(max_attempts=3))
        def open_shard(path): ...
    """

    def wrap(fn: Callable) -> Callable:
        @functools.wraps(fn)
        def inner(*args, **kwargs):
            return retry_call(fn, *args, policy=policy, **retry_kwargs, **kwargs)

        return inner

    return wrap
