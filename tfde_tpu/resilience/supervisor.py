"""Preemption-aware training supervisor: the run loop around the run loop.

The reference's Estimator runtime gave workers transparent fault tolerance —
a restarted process resumes from the latest checkpoint with no operator
action (SURVEY §5). In the TPU-native stack that contract was implicit: the
lifecycle resumes-by-default, but nothing *owned* the restart. This module
is that owner. `Supervisor.run()` drives `Estimator.train()` in a bounded
restart loop:

- **classify**: a failure is a PREEMPTION (signal, checkpoint already
  committed by the guard), TRANSIENT (I/O blip that outlived the retry
  policy's budget), a STALL (watchdog escalation), POISON (deterministic
  error — an assertion, a shape mismatch — that would recur on every
  restart and must abort), or NUMERICS (a sentry-reported NaN/blow-up —
  poison with a better error message: the replayed steps are
  deterministic, so restarting from the pre-NaN checkpoint re-trips), or
  a TOPOLOGY change (a peer died — restartable only after an elastic
  re-bootstrap at the surviving world size, see resilience/elastic.py);
- **restart**: restartable kinds rebuild a fresh Estimator from the
  factory; resume-by-default restores the latest *committed* step, so the
  restart replays at most save_checkpoints_steps-1 steps;
- **bound**: `max_restarts` caps the loop, restart backoff rides a
  RetryPolicy, and a restart that makes no checkpoint progress twice in a
  row is escalated to abort (a restart loop that never advances is poison
  with extra steps);
- **observe**: restarts/lost-step estimates/stalls are exported through
  observability counters, and written as TensorBoard scalars under
  `<model_dir>/resilience` on the chief.

Preemption handling composes with the hoisted `PreemptionGuard`
(resilience/preemption.py): in `resume_on_preemption` mode the supervisor
installs an outer SIGTERM handler that raises `Preempted`; the guard saves
it as "previous", so the guard's post-commit re-raise lands there and the
supervisor restarts from the just-committed checkpoint instead of dying.
A second SIGTERM still kills (the outer handler restores the default before
raising). Without a supervisor — or with `resume_on_preemption=False`, the
production default where the pool scheduler owns restarts — the process
exits by signal exactly as before.
"""

from __future__ import annotations

import dataclasses
import enum
import logging
import random
import signal as _signal
import threading
import time
from typing import Callable, Iterable, Optional

from tfde_tpu.observability import counters
from tfde_tpu.resilience.health import Heartbeat, StallError
from tfde_tpu.resilience.policy import (
    RetryBudgetExceeded,
    RetryPolicy,
    TransientError,
)
from tfde_tpu.resilience.preemption import Preempted

log = logging.getLogger(__name__)


class FailureKind(enum.Enum):
    PREEMPTION = "preemption"
    TRANSIENT = "transient"
    STALL = "stall"
    POISON = "poison"
    #: numerics-sentry trip (observability/sentry.py NumericsError).
    #: Non-restartable like POISON: resume-by-default restores the pre-NaN
    #: checkpoint and the blow-up deterministically replays.
    NUMERICS = "numerics"
    #: a peer process died (resilience/elastic.py PeerLostError, or a
    #: connection-shaped error in a distributed run with elastic enabled).
    #: Restartable, but only after an elastic re-bootstrap at the
    #: surviving world size — a same-world restart would deadlock in
    #: jax.distributed.initialize waiting for the dead host.
    TOPOLOGY = "topology"


def classify_failure(exc: BaseException) -> FailureKind:
    """Map a failure to its restart semantics. KeyboardInterrupt is NOT
    classified here — operator intent aborts before classification."""
    from tfde_tpu.observability.sentry import NumericsError
    from tfde_tpu.resilience.elastic import PeerLostError

    if isinstance(exc, Preempted):
        return FailureKind.PREEMPTION
    if isinstance(exc, PeerLostError):
        return FailureKind.TOPOLOGY
    if isinstance(exc, NumericsError):
        return FailureKind.NUMERICS
    if isinstance(exc, StallError):
        return FailureKind.STALL
    if isinstance(exc, RetryBudgetExceeded):
        # the I/O layer already retried in place; a restart gets fresh
        # connections/processes, which is the next rung on the ladder
        return FailureKind.TRANSIENT
    if isinstance(exc, (OSError, TimeoutError, ConnectionError, TransientError)):
        return FailureKind.TRANSIENT
    return FailureKind.POISON


#: kinds the supervisor refuses to restart: the failure replays from the
#: restored checkpoint, so a restart is a slower way to fail again
_NON_RESTARTABLE = (FailureKind.POISON, FailureKind.NUMERICS)


class SupervisorAborted(RuntimeError):
    """The supervisor gave up: restart budget exhausted, no forward
    progress, or a poison failure. `__cause__` is the last failure;
    `restarts` is how many restarts were attempted."""

    def __init__(self, msg: str, restarts: int):
        super().__init__(msg)
        self.restarts = restarts


@dataclasses.dataclass
class SupervisorConfig:
    #: total restarts allowed across the run (attempts = max_restarts + 1)
    max_restarts: int = 5
    #: backoff shape between restarts (max_attempts is ignored here —
    #: max_restarts bounds the loop)
    restart_policy: RetryPolicy = dataclasses.field(
        default_factory=lambda: RetryPolicy(initial_backoff=1.0, max_backoff=60.0)
    )
    #: in-process restart on SIGTERM (single-process pools, tests, chaos
    #: drills). False = production default: the guard checkpoint-commits and
    #: the process exits by signal; the cluster scheduler owns the restart.
    resume_on_preemption: bool = False
    #: arm the stall watchdog (None = off). Escalation is SIGTERM-to-self,
    #: i.e. checkpoint-and-exit (or checkpoint-and-restart under
    #: resume_on_preemption).
    stall_timeout_secs: Optional[float] = None
    #: abort after this many consecutive restarts with no checkpoint
    #: progress — an advancing run may be preempted forever and keep
    #: making progress; one that cannot advance is effectively poison
    no_progress_limit: int = 2
    #: elastic topology-change handling (resilience/elastic.py): an
    #: ElasticConfig enables with that policy, True enables with the
    #: env-tuned config, False disables, None (default) defers to the
    #: TFDE_ELASTIC knob (off by default)
    elastic: object = None
    #: deterministic restart-backoff jitter
    seed: int = 0


class Supervisor:
    """Owns a training run: builds Estimators from `estimator_factory`,
    drives `train()`, classifies failures, restarts from the latest
    committed checkpoint.

    The factory is called once per attempt — a fresh Estimator per restart
    is the whole point (fresh Orbax manager, fresh compiled steps, fresh
    state restored from disk), mirroring what a real process restart gets.
    """

    def __init__(
        self,
        estimator_factory: Callable[[], "Estimator"],
        config: Optional[SupervisorConfig] = None,
    ):
        self.factory = estimator_factory
        self.config = config or SupervisorConfig()
        self.restarts = 0
        self.last_failure: Optional[BaseException] = None
        self._rng = random.Random(self.config.seed)

    # -- internals -----------------------------------------------------------
    @staticmethod
    def _committed_step(est) -> Optional[int]:
        """Latest step on disk for `est`'s model_dir; None when
        checkpointing is off or the directory is empty/unreadable."""
        try:
            mngr = est._ckpt_mngr()
            if mngr is None:
                return None
            mngr.reload()
            return mngr.latest_step
        except Exception:
            return None

    def _outer_sigterm(self):
        """Install the resume-on-preemption outer handler (main thread
        only); returns a restore callable. The handler restores the
        *default* handler first, so a second SIGTERM during restart/save is
        the operator's hard kill, then raises Preempted."""
        if (not self.config.resume_on_preemption
                or threading.current_thread() is not threading.main_thread()):
            return lambda: None

        def handler(signum, frame):
            _signal.signal(signum, _signal.SIG_DFL)
            raise Preempted(signum)

        prev = _signal.signal(_signal.SIGTERM, handler)
        return lambda: _signal.signal(_signal.SIGTERM, prev)

    def _beat_input_fn(self, input_fn, heartbeat: Heartbeat, start_step: int):
        """Wrap the input so every batch draw beats the heartbeat with the
        (approximate) step about to run — batch draws are the loop's pulse,
        and a wedged compile/collective/storage read stops them too."""

        def wrapped() -> Iterable:
            def gen():
                step = start_step
                for b in input_fn():
                    step += 1
                    heartbeat.beat(step)
                    yield b

            return gen()

        return wrapped

    @staticmethod
    def _abort_dump(flightrec, kind: FailureKind) -> None:
        """Flush the flight ring before SupervisorAborted unwinds — the
        abort is the post-mortem moment; without this the ring's last
        window (the trip, the failed restarts) dies with the process if
        nothing above catches the abort."""
        try:
            flightrec.record("supervisor_abort", failure_kind=kind.value)
            flightrec.dump("supervisor_abort")
        except Exception:
            log.debug("flight dump on abort failed", exc_info=True)

    def _export(self, est, step: int) -> None:
        """Chief-side metric export as TensorBoard scalars next to the
        run's curves — the resilience counters plus the run-level goodput
        gauges the supervisor's ledger published."""
        try:
            model_dir = est.config.model_dir
            if model_dir is None or not est._is_chief:
                return
            from tfde_tpu.observability import exposition
            from tfde_tpu.observability.tensorboard import SummaryWriter
            from tfde_tpu.utils import fs

            w = SummaryWriter(fs.join(model_dir, "resilience"))
            try:
                exposition.export_to_tensorboard(w, step, prefix="resilience/")
                exposition.export_to_tensorboard(w, step, prefix="goodput/")
            finally:
                w.close()
        except Exception:
            log.exception("resilience counter export failed (non-fatal)")

    # -- the run loop --------------------------------------------------------
    def run(self, input_fn, max_steps: int, **train_kwargs):
        """Supervised `Estimator.train(input_fn, max_steps)`. Returns the
        final TrainState; raises SupervisorAborted when the run cannot be
        completed."""
        cfg = self.config
        no_progress = 0
        committed_before: Optional[int] = None
        # run-level ledger: spans EVERY attempt, so restart backoff and
        # replayed steps show up as restart_loss in one goodput fraction
        from tfde_tpu.observability.goodput import GoodputLedger
        from tfde_tpu.resilience import elastic as elastic_lib

        ledger = GoodputLedger()
        ecfg = elastic_lib.resolve(cfg.elastic)
        topology_changes = 0
        pending_topology: Optional[str] = None

        while True:
            if pending_topology is not None:
                # deferred to the TOP of the next attempt on purpose: the
                # failed attempt's finally (heartbeat stop, est.close) ran
                # against the old runtime before it is torn down here
                cause, pending_topology = pending_topology, None
                # the rejoin is a fresh boot epoch on the cold-start
                # ledger (observability/boot.py): the re-bootstrap lands
                # in its `bootstrap` phase and the attempt's checkpoint
                # restore in `restore`, so training rejoin cost is
                # measured by the same instrument as a serving replica's
                # cold start — cross-checkable against the goodput
                # ledger's restart_loss/init buckets
                from tfde_tpu.observability import boot as boot_lib

                boot_led = boot_lib.current()
                boot_led.new_epoch(cause=cause)
                try:
                    with boot_led.phase("bootstrap"):
                        elastic_lib.rebootstrap(ecfg, cause=cause)
                except BaseException as te:
                    raise SupervisorAborted(
                        f"elastic re-bootstrap failed after {self.restarts} "
                        f"restart(s): {type(te).__name__}: {te}",
                        restarts=self.restarts,
                    ) from te
            elif self.restarts:
                # re-read the cluster env per attempt: a scheduler that
                # rewrote the spec between attempts must win over the
                # topology the first bootstrap resolved
                try:
                    elastic_lib.refresh_if_changed()
                except Exception:
                    log.warning("cluster env refresh failed (continuing at "
                                "the old topology)", exc_info=True)
            est = self.factory()
            restore_handler = self._outer_sigterm()
            heartbeat = None
            if cfg.stall_timeout_secs is not None:
                heartbeat = Heartbeat(stall_timeout_secs=cfg.stall_timeout_secs)
            start_committed = self._committed_step(est) or 0
            try:
                fn = input_fn
                if heartbeat is not None:
                    fn = self._beat_input_fn(input_fn, heartbeat, start_committed)
                    heartbeat.start_watchdog()
                state = est.train(fn, max_steps, **train_kwargs)
                rep = ledger.export()
                log.info(
                    "supervised run complete: goodput %.3f over %.1fs "
                    "(%d restarts, %.0f lost steps)",
                    rep["goodput"], rep["wall_seconds"],
                    self.restarts, rep["lost_steps"],
                )
                self._export(est, max_steps)
                return state
            except KeyboardInterrupt:
                # operator intent (or a guard-committed SIGINT): stop, never
                # restart — the checkpoint, if any, is already on disk
                raise
            except BaseException as e:
                kind = classify_failure(e)
                if (ecfg is not None and kind is not FailureKind.TOPOLOGY
                        and elastic_lib.looks_like_peer_loss(e)
                        and elastic_lib.in_distributed_run()):
                    # untyped connection-shaped error in a distributed run:
                    # a same-world restart would hang on the dead host, so
                    # treat it as a topology change
                    kind = FailureKind.TOPOLOGY
                committed = self._committed_step(est)
                reached = heartbeat.last_step if heartbeat is not None else None
                lost = max(0, (reached or 0) - (committed or 0))
                if lost:
                    counters.incr("resilience/lost_steps", lost)
                counters.incr(f"resilience/failures_{kind.value}")
                self.last_failure = e
                from tfde_tpu.observability import flightrec

                flightrec.record(
                    "supervisor_failure", failure_kind=kind.value,
                    error=f"{type(e).__name__}: {e}",
                    committed_step=committed, restarts=self.restarts,
                )

                if kind in _NON_RESTARTABLE:
                    log.error("%s failure (%s: %s); aborting run",
                              kind.value, type(e).__name__, e)
                    self._abort_dump(flightrec, kind)
                    raise SupervisorAborted(
                        f"non-restartable failure after {self.restarts} "
                        f"restart(s): {type(e).__name__}: {e}",
                        restarts=self.restarts,
                    ) from e
                if self.restarts >= cfg.max_restarts:
                    self._abort_dump(flightrec, kind)
                    raise SupervisorAborted(
                        f"restart budget ({cfg.max_restarts}) exhausted; "
                        f"last failure: {type(e).__name__}: {e}",
                        restarts=self.restarts,
                    ) from e

                # forward-progress bound: a restart loop whose committed
                # step never moves is poison wearing a transient's clothes
                # (no checkpoint at all counts as step 0)
                if (committed or 0) <= (committed_before or 0):
                    no_progress += 1
                else:
                    no_progress = 0
                committed_before = committed
                if no_progress >= cfg.no_progress_limit:
                    self._abort_dump(flightrec, kind)
                    raise SupervisorAborted(
                        f"no checkpoint progress across {no_progress} "
                        f"consecutive restarts (stuck at step {committed}); "
                        f"last failure: {type(e).__name__}: {e}",
                        restarts=self.restarts,
                    ) from e

                if kind is FailureKind.TOPOLOGY and ecfg is not None:
                    if topology_changes >= ecfg.max_topology_changes:
                        self._abort_dump(flightrec, kind)
                        raise SupervisorAborted(
                            f"topology-change budget "
                            f"({ecfg.max_topology_changes}) exhausted; "
                            f"last failure: {type(e).__name__}: {e}",
                            restarts=self.restarts,
                        ) from e
                    topology_changes += 1
                    pending_topology = f"{type(e).__name__}: {e}"

                self.restarts += 1
                counters.incr("resilience/restarts")
                flightrec.record("supervisor_restart", attempt=self.restarts,
                                 from_step=committed,
                                 failure_kind=kind.value)
                delay = cfg.restart_policy.backoff(self.restarts, self._rng)
                # backoff sleep is pure restart tax — the goodput ledger
                # reads this back as part of restart_loss
                counters.incr("resilience/restart_backoff_seconds", delay)
                log.warning(
                    "%s failure (%s: %s); restart %d/%d from committed step "
                    "%s in %.2fs",
                    kind.value, type(e).__name__, e, self.restarts,
                    cfg.max_restarts, committed, delay,
                )
                time.sleep(delay)
            finally:
                if heartbeat is not None:
                    heartbeat.stop()
                restore_handler()
                try:
                    est.close()
                except Exception:
                    log.debug("estimator close after failure raised", exc_info=True)


def train_supervised(
    estimator_factory: Callable[[], "Estimator"],
    input_fn,
    max_steps: int,
    config: Optional[SupervisorConfig] = None,
    **train_kwargs,
):
    """One-call form: `train_supervised(lambda: Estimator(...), input_fn,
    max_steps)` — the supervised analog of `estimator.train(...)`."""
    return Supervisor(estimator_factory, config).run(
        input_fn, max_steps, **train_kwargs
    )
