"""Runtime core: process bootstrap and device-mesh construction."""

from tfde_tpu.runtime.cluster import ClusterInfo, bootstrap  # noqa: F401
from tfde_tpu.runtime.mesh import MeshSpec, make_mesh, data_parallel_mesh  # noqa: F401
